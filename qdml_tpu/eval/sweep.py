"""SNR-sweep evaluation harness (reference ``model_val``, ``Test.py:64-275``).

For each SNR in the grid (default ``{5,7,9,11,13,15}`` dB, ``Test.py:66``) over
``test_len`` fresh samples (``Test.py:20,127``):

- classical baselines: the full-pilot LS observation (``HLS``) and its LMMSE
  refinement (``Test.py:141-147``),
- scenario classification with the classical CNN and (optionally) the quantum
  classifier (``Test.py:158-164``),
- HDCE estimation with each sample routed through the trunk matching its
  PREDICTED scenario (``Test.py:167-214``) — expressed as run-all-trunks +
  ``take_along_axis`` gather (:mod:`qdml_tpu.ops.routing`), no host sync,
- NMSE vs perfect CSI for LS / MMSE / HDCE-classical / HDCE-quantum and both
  classifier accuracies (``Test.py:217-256``),
- optionally the monolithic DCE baseline (reference ``DCE_P128``,
  ``Estimators_QuantumNAT_onchipQNN.py:40-75`` — defined there but never
  trained by the shipped runner): one un-routed trunk+head on the same
  pilots, the architectural control for the hierarchical design's gain.

Everything inside the per-batch step is one jitted function, data generation
included.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.baselines import (
    beam_delay_profile,
    mmse_estimate,
    mmse_generic_estimate,
)
from qdml_tpu.data.channels import ChannelGeometry, label_noise_var
from qdml_tpu.data.datasets import make_network_batch
from qdml_tpu.models.cnn import DCEP128, SCP128
from qdml_tpu.models.qsc import QSCP128
from qdml_tpu.ops.routing import select_expert, sparse_dispatch
from qdml_tpu.telemetry import span
from qdml_tpu.train.hdce import HDCE
from qdml_tpu.utils.metrics import nmse_db


def _sum_sq(x) -> jnp.ndarray:
    return jnp.sum(x.abs2()) if hasattr(x, "abs2") else jnp.sum(x**2)


def make_sweep_step(
    cfg: ExperimentConfig,
    geom: ChannelGeometry,
    hdce_vars: dict,
    sc_vars: dict,
    qsc_vars: dict | None,
    profile: jnp.ndarray,
    dce_vars: dict | None = None,
    mesh=None,
    dispatch: str = "dense",
    capacity_factor: float = 1.25,
):
    """Build the jitted per-batch sweep step: ``step(start, count_base,
    snr_db)`` returns a dict of error/power sums and correct-counts for one
    ``eval.batch_size`` batch.

    ``dispatch`` selects the expert-routing formulation for the HDCE curves:
    ``"dense"`` (run all trunks + gather — the default and the S=3 winner) or
    ``"sparse"`` (capacity-bucketed top-1, ``routing.sparse_dispatch`` — the
    S≫3 path the serve engine's dispatcher bakes in; value-equivalent, so
    the NMSE curves are dispatch-invariant to float tolerance).

    With a ``mesh`` carrying a ``fed`` axis of size ``n_scenarios`` (and
    ``hdce_vars`` placed by
    :func:`qdml_tpu.parallel.federated.shard_hdce_vars`), the all-hypotheses
    trunk pass runs expert-parallel: scenario ``s``'s trunk weights and its
    hypothesis batch live only on fed-slice ``s``; the predicted-scenario
    routing gather is the one cross-slice collective. A ``data`` axis
    additionally shards the batch (and its on-device generation) within
    each slice."""
    if dispatch not in ("dense", "sparse"):
        raise ValueError(f"dispatch must be dense|sparse, got {dispatch!r}")
    hdce = HDCE(
        n_scenarios=cfg.data.n_scenarios,
        features=cfg.model.features,
        out_dim=cfg.h_out_dim,
    )
    sc = SCP128(n_classes=cfg.quantum.n_classes)
    dce = (
        DCEP128(features=cfg.model.features, out_dim=cfg.h_out_dim)
        if dce_vars is not None
        else None
    )
    qsc = (
        QSCP128(
            n_qubits=cfg.quantum.n_qubits,
            n_layers=cfg.quantum.n_layers,
            n_classes=cfg.quantum.n_classes,
            backend=cfg.quantum.backend,
            impl=cfg.quantum.impl,
            mps_chi=cfg.quantum.mps_chi,
            input_norm=cfg.quantum.input_norm,
        )
        if qsc_vars is not None
        else None
    )
    n_scen = cfg.data.n_scenarios

    def _batch_metrics(
        start: jnp.ndarray, count_base: jnp.ndarray, snr_db: jnp.ndarray
    ) -> dict:
        bs = cfg.eval.batch_size
        i = count_base + jnp.arange(bs)
        scen = i % n_scen
        user = (i // n_scen) % cfg.data.n_users
        batch = make_network_batch(
            jnp.uint32(cfg.data.seed), scen, user, start + i, snr_db, geom
        )
        h = batch["h_perf_c"]
        x = batch["yp_img"]

        # classical baselines: the full-pilot LS observation IS the LS
        # estimator (Test.py's HLS); MMSE is its Wiener refinement
        # (Test.py:145) — generic site-agnostic covariance for the headline
        # curve, plus the empirical beam-delay oracle prior as a strictly
        # stronger genie variant.
        h_ls = batch["h_ls"]
        sigma2 = label_noise_var(geom, snr_db)
        h_mmse = mmse_generic_estimate(h_ls, sigma2, geom)
        h_mmse_oracle = mmse_estimate(h_ls, sigma2, profile, geom)

        # stacked-trunk HDCE outputs for every scenario hypothesis — the
        # dense formulation's all-hypotheses pass; the sparse formulation
        # defers trunk work until each classifier's predictions exist
        est_all = None
        if dispatch == "dense":
            xs = jnp.broadcast_to(x[None], (n_scen,) + x.shape)
            if mesh is not None:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                fed = "fed" if mesh.shape.get("fed", 1) == n_scen else None
                data = "data" if mesh.shape.get("data", 1) > 1 else None
                xs = jax.lax.with_sharding_constraint(
                    xs, NamedSharding(mesh, P(fed, data, *(None,) * (xs.ndim - 2)))
                )
            est_all = hdce.apply(hdce_vars, xs, train=False)  # (S, B, 2048)

        def _pin_fed(xs: jnp.ndarray) -> jnp.ndarray:
            """Pin a (S, ...) leading axis to ``fed`` — the serve engine's
            ``_apply_trunks`` twin, so bucket/hypothesis s co-locates with
            trunk s's weights under expert-sharded params on the sparse path
            exactly as the dense branch's constraint guarantees."""
            if mesh is None:
                return xs
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            fed = "fed" if mesh.shape.get("fed", 1) == n_scen else None
            return jax.lax.with_sharding_constraint(
                xs, NamedSharding(mesh, P(fed, *(None,) * (xs.ndim - 1)))
            )

        def _route(pred: jnp.ndarray) -> jnp.ndarray:
            if dispatch == "dense":
                return select_expert(est_all, pred)

            def dense_fb(xb, pb):
                xsb = _pin_fed(jnp.broadcast_to(xb[None], (n_scen,) + xb.shape))
                return select_expert(hdce.apply(hdce_vars, xsb, train=False), pb)

            routed, _ = sparse_dispatch(
                lambda buckets: hdce.apply(hdce_vars, _pin_fed(buckets), train=False),
                dense_fb,
                x,
                pred,
                n_scen,
                capacity_factor,
            )
            return routed

        out: dict[str, jnp.ndarray] = {
            "pow": _sum_sq(h),
            "err_ls": _sum_sq(h_ls - h),
            "err_mmse": _sum_sq(h_mmse - h),
            "err_mmse_oracle": _sum_sq(h_mmse_oracle - h),
            "count": jnp.asarray(bs, jnp.float32),
        }

        label2 = jnp.concatenate([h.re, h.im], -1)
        if dce is not None:
            out["err_dce"] = _sum_sq(dce.apply(dce_vars, x, train=False) - label2)
        for name, vars_, model in (("classical", sc_vars, sc), ("quantum", qsc_vars, qsc)):
            if model is None:
                continue
            logp = model.apply(vars_, x, train=False)
            pred = jnp.argmax(logp, -1)
            routed = _route(pred)  # (B, 2048)
            out[f"err_hdce_{name}"] = _sum_sq(routed - label2)
            out[f"correct_{name}"] = jnp.sum(pred == batch["indicator"]).astype(jnp.float32)
        return out

    return jax.jit(_batch_metrics)


def make_snr_scan(cfg: ExperimentConfig, batch_metrics, n_batches: int):
    """One device dispatch per SNR point: ``lax.scan`` over the batch index,
    stacking each batch's metric dict; the (n_batches,)-shaped outputs are
    summed host-side in float64, matching the per-batch dispatch loop's
    accumulation (sequential float64 adds of float32 batch values). Replaces
    ``n_batches`` separate dispatches plus ~10 blocking scalar transfers per
    batch with ONE dispatch and one transfer set — the eval twin of the
    training scan path (docs/ROOFLINE.md)."""
    import numpy as np

    bs = cfg.eval.batch_size

    @jax.jit
    def _stacked(start: jnp.ndarray, snr_db: jnp.ndarray) -> dict:
        def body(_, b):
            return None, batch_metrics(start, b * bs, snr_db)

        _, outs = jax.lax.scan(body, None, jnp.arange(n_batches))
        return outs

    def sweep_one_snr(start: jnp.ndarray, snr_db: jnp.ndarray) -> dict:
        outs = jax.device_get(_stacked(start, snr_db))
        return {k: float(np.asarray(v, np.float64).sum()) for k, v in outs.items()}

    return sweep_one_snr


def run_snr_sweep(
    cfg: ExperimentConfig,
    hdce_vars: dict,
    sc_vars: dict,
    qsc_vars: dict | None = None,
    logger=None,
    dce_vars: dict | None = None,
    mesh=None,
    dispatch: str = "dense",
) -> dict[str, Any]:
    """Full sweep; returns ``{"snr": [...], "nmse_db": {curve: [...]}, "acc": {...}}``.

    When a :class:`qdml_tpu.utils.metrics.MetricsLogger` is passed, every
    SNR row is appended to its JSONL stream as it completes (curve NMSEs in
    dB, classifier accuracies, sample count) — line-level provenance for the
    aggregate ``results/*.json`` the reporters write.
    """
    geom = ChannelGeometry.from_config(cfg.data)
    profile = beam_delay_profile(geom)
    step = make_sweep_step(
        cfg, geom, hdce_vars, sc_vars, qsc_vars, profile, dce_vars=dce_vars,
        mesh=mesh, dispatch=dispatch,
        capacity_factor=cfg.serve.capacity_factor,
    )
    n_batches = max(cfg.eval.test_len // cfg.eval.batch_size, 1)
    sweep_one_snr = make_snr_scan(cfg, step, n_batches)

    start = cfg.data.data_len * 3  # offset past training data (Test.py:127)
    curves: dict[str, list] = {}
    accs: dict[str, list] = {}
    for snr in cfg.eval.snr_grid:
        # span to the global telemetry sink (set by the CLI); the first SNR
        # point carries the sweep's jit compile
        with span("snr_point", snr_db=float(snr)):
            sums = sweep_one_snr(jnp.asarray(start), jnp.float32(snr))
        pow_ = max(sums["pow"], 1e-30)
        row: dict[str, float] = {}
        for key in sums:
            if key.startswith("err_"):
                db = nmse_db(sums[key] / pow_)
                curves.setdefault(key[4:], []).append(db)
                row[f"nmse_db_{key[4:]}"] = db
            elif key.startswith("correct_"):
                acc = sums[key] / sums["count"]
                accs.setdefault(key[8:], []).append(acc)
                row[f"acc_{key[8:]}"] = acc
        if logger is not None:
            logger.log(snr_db=float(snr), n_samples=sums["count"], **row)
    return {"snr": list(cfg.eval.snr_grid), "nmse_db": curves, "acc": accs}


# ---------------------------------------------------------------------------
# Scenario-scaling axis (the S = 3 ... 64 sweep, docs/SERVING.md)
# ---------------------------------------------------------------------------

# The scaling grid: the reference's 3-scenario grid (the dense anchor every
# committed curve lives at), the sparse-eligibility edge's near side (4), the
# first raced point (8), and the scale-out regime (16/32/64) where the dense
# all-trunks pass burns O(S) compute for O(1) useful work.
SCENARIO_SCALING_GRID = (3, 4, 8, 16, 32, 64)


def scenario_batch(n_scenarios: int) -> int:
    """Per-point request-batch for the scenario sweep: the serve engine's
    largest default bucket, held constant across S — the scenario axis scales
    EXPERT count, not batch, so every point routes the same 64 rows and the
    per-S gates stay comparable run-to-run (each S only gates against
    itself, mirroring ``scaling_batch``'s contract on the qubit axis)."""
    return 64


def dispatch_agreement(
    n_scenarios: int,
    batch: int = 32,
    features: int = 8,
    capacity_factor: float = 1.25,
    seed: int = 0,
) -> dict:
    """Numerics cross-check for one scenario-scaling point: how far the
    sparse routing stage sits from the dense formulation at the same
    (params, inputs, predictions) — checked under BOTH a balanced load
    (buckets fill evenly, pure sparse path) and a fully skewed one (every
    row one expert, the overflow fallback IS the dense path). The two
    formulations share no routing code, so a packing/unsort bug cannot
    cancel out. Returns ``{"max_abs_delta", "overflow_balanced",
    "overflow_skewed"}``."""
    import numpy as np

    from qdml_tpu.train.hdce import HDCE

    s = int(n_scenarios)
    rng = np.random.default_rng(seed)
    model = HDCE(n_scenarios=s, features=features, out_dim=64)
    x = jnp.asarray(rng.standard_normal((batch, 16, 8, 2)).astype(np.float32))
    vars_ = model.init(
        jax.random.PRNGKey(seed), jnp.broadcast_to(x[None], (s,) + x.shape), train=False
    )

    def dense_fb(xb, pb):
        xs = jnp.broadcast_to(xb[None], (s,) + xb.shape)
        return select_expert(model.apply(vars_, xs, train=False), pb)

    def run_experts(buckets):
        return model.apply(vars_, buckets, train=False)

    out: dict[str, Any] = {"max_abs_delta": 0.0}
    for name, pred in (
        ("balanced", jnp.arange(batch, dtype=jnp.int32) % s),
        ("skewed", jnp.zeros(batch, jnp.int32)),
    ):
        routed, ovf = sparse_dispatch(
            run_experts, dense_fb, x, pred, s, capacity_factor
        )
        delta = float(jnp.max(jnp.abs(routed - dense_fb(x, pred))))
        out["max_abs_delta"] = round(max(out["max_abs_delta"], delta), 8)
        out[f"overflow_{name}"] = int(ovf)
    return out


# ---------------------------------------------------------------------------
# Qubit-scaling axis (the n = 4 ... 24 sweep, docs/QUANTUM.md)
# ---------------------------------------------------------------------------

# The scaling grid: the paper's published 4/6/8-qubit regime, the dense and
# pallas windows' edges (10/12), the tensor crossover (14), and the
# compressed/partitioned-only regime (16/20/24) nothing dense-shaped reaches.
QUBIT_SCALING_GRID = (4, 6, 8, 10, 12, 14, 16, 20, 24)


def scaling_batch(n_qubits: int) -> int:
    """Per-point circuit batch for the scaling sweep: the full-statevector
    footprint is ``batch * 2^n`` amplitudes, so the batch shrinks as n grows
    to keep every point runnable on the CPU virtual-device harness (and
    comparable run-to-run — the per-n batch is deterministic, and each n only
    ever gates against itself)."""
    if n_qubits <= 16:
        return 64
    if n_qubits <= 20:
        return 8
    return 2


def scaling_chi(n_qubits: int, chi: int) -> int:
    """The mps bond dimension a scaling point actually runs: ``chi`` capped
    at the exactness bound 2^(n/2) — a larger chi buys nothing (the chain's
    Schmidt rank can't exceed the bound) and would just pad the SVDs."""
    return max(2, min(int(chi), 1 << (n_qubits // 2)))


def impl_agreement(
    n_qubits: int,
    impl: str,
    n_layers: int = 3,
    batch: int = 4,
    mps_chi: int | None = None,
    seed: int = 0,
) -> dict:
    """Numerics cross-check for one scaling point: how far ``impl``'s
    per-wire <Z> sits from an INDEPENDENT formulation at the same
    (angles, weights).

    The reference is dense (n <= 12) or the gate-wise tensor path (n <= 14)
    — past every full-statevector window the compressed (mps) and
    partitioned (sharded_statevector) states check each OTHER when the
    topology offers both (two formulations sharing no code path), and a
    point with no second formulation reports ``reference: null`` rather
    than a vacuous self-check. Returns ``{reference, max_abs_delta}``."""
    import numpy as np

    from qdml_tpu.quantum import autotune
    from qdml_tpu.quantum.circuits import run_circuit

    reference: str | None = None
    if impl != "dense" and n_qubits <= 12:
        reference = "dense"
    elif impl == "tensor":
        # tensor winning the 13-14 crossover window: mps is the independent
        # formulation there (dense is past its wall, and a full-chi mps is
        # exact for this circuit class)
        reference = "mps"
    elif impl != "tensor" and n_qubits <= 14:
        reference = "tensor"
    elif impl == "mps" and autotune.model_axis_devices() >= 2:
        reference = "sharded_statevector"
    elif impl == "sharded_statevector":
        reference = "mps"
    if reference is None:
        return {"reference": None, "max_abs_delta": None}
    rng = np.random.default_rng(seed)
    angles = jnp.asarray(rng.uniform(-1, 1, (batch, n_qubits)).astype(np.float32))
    weights = jnp.asarray(
        rng.uniform(0, 2 * np.pi, (n_layers, n_qubits, 2)).astype(np.float32)
    )
    chi = scaling_chi(n_qubits, mps_chi or 16)
    out = run_circuit(
        angles, weights, n_qubits, n_layers, backend=impl, mps_chi=chi
    )
    ref = run_circuit(
        angles, weights, n_qubits, n_layers, backend=reference, mps_chi=chi
    )
    return {
        "reference": reference,
        "max_abs_delta": round(float(jnp.max(jnp.abs(out - ref))), 8),
    }
