"""Command-line entry points.

The reference has no CLI at all — hardcoded ``__main__`` blocks
(``Runner_P128_QuantumNAT_onchipQNN.py:432-444``, ``Test.py:339-346``). Here:

    python -m qdml_tpu.cli train-hdce [--preset=NAME] [--train.lr=3e-4 ...]
    python -m qdml_tpu.cli train-dce  [...]      # monolithic (non-HDCE) baseline
    python -m qdml_tpu.cli train-sc   [...]      # classical scenario classifier
    python -m qdml_tpu.cli train-qsc  [...]      # quantum scenario classifier
    python -m qdml_tpu.cli nat-sweep  [...]      # vmapped QuantumNAT noise-level ensemble
    python -m qdml_tpu.cli eval       [...]      # SNR sweep + plots + JSON
    python -m qdml_tpu.cli loss-curves --curves=LABEL:metrics.jsonl[,...]
                                                 # reference Loss Curve figure
    python -m qdml_tpu.cli profile [--out=DIR]   # jax.profiler trace + samples/sec
    python -m qdml_tpu.cli gen-data --out=DIR    # materialise .npy cache
    python -m qdml_tpu.cli import-torch --out=SRCDIR  # reference .pth -> orbax
    python -m qdml_tpu.cli export-torch --out=DSTDIR  # orbax -> reference .pth
    python -m qdml_tpu.cli report --current=PATH[,..] --baseline=PATH
                                  [--threshold=PCT] [--out=FILE.md] [--json=FILE.json]
                                  [--lint=LINT.json]
                                  # telemetry delta table (+ cost section,
                                  # machine-readable gate); exit 3 on regression
                                  # (--lint folds a lint-gate row in too)
    python -m qdml_tpu.cli lint   [--baseline] [--write-baseline] [--json=F]
                                  [--durations=F] [--paths=...] [--list-rules]
                                  # graftlint static analysis gate
                                  # (docs/ANALYSIS.md); exit 1 on new findings
    python -m qdml_tpu.cli serve  [--serve.port=8377 --serve.replicas=N ...]
                                  # online inference: restore ckpt, AOT-warm
                                  # buckets (mesh-sharded when >1 device),
                                  # SUPERVISED replica pool (crash restart/
                                  # quarantine, docs/RESILIENCE.md), hardened
                                  # JSON/TCP loop ({"op": "metrics"} live
                                  # counters; {"op": "health"} cheap liveness;
                                  # {"op": "swap"} zero-downtime checkpoint
                                  # hot-swap; conn timeouts + idempotent-id
                                  # dedup; --serve.breaker=true brownout);
                                  # --serve.batching=auto|bucket|ragged picks
                                  # pad-to-bucket coalescing vs traced
                                  # valid-count continuous batching (auto =
                                  # per-capacity race table, docs/SERVING.md);
                                  # --serve.trace_sample=F samples phase-
                                  # decomposed request traces (batch_wait/
                                  # queue_wait/compute/fetch [+router wire],
                                  # docs/TELEMETRY.md; 0 = off, overhead-free)
    python -m qdml_tpu.cli loadgen [--rate=RPS] [--n=N] [--drift-at=K]
                                  # open-loop traffic
                                  # (--serve.arrival=poisson|bursty|diurnal)
                                  # vs an in-process warmed engine/pool;
                                  # --drift-at injects channel-family drift
                                  # (--serve.drift_step / drift_scenario)
                                  # into the offered stream from index K
    python -m qdml_tpu.cli control [--ticks=N] [--control.dry_run=true ...]
                                  # fleet control plane (docs/CONTROL.md):
                                  # attach to the running serve endpoint,
                                  # detect per-scenario drift, fine-tune the
                                  # drifted trunk, canary-gate + hot-swap,
                                  # watch/rollback, autoscale replicas
    python -m qdml_tpu.cli route  [--fleet.backends=h:p,h:p ...]
                                  # fleet router tier (docs/FLEET.md): front
                                  # door speaking the serve protocol, fanning
                                  # requests over backend serve processes
                                  # (--fleet.balance=hash|least_queue),
                                  # breaker-style ejection/re-admission,
                                  # swap fan-out + metrics/health aggregation
                                  # (point `control` at fleet.host:fleet.port
                                  # to supervise the whole fleet)
    python -m qdml_tpu.cli monitor --addr=HOST:PORT [--duration=S]
                                  [--interval=S] [--out=FILE.jsonl]
                                  [--slo-target=0.99] [--threshold=8]
                                  # flight deck (docs/TELEMETRY.md): scrape
                                  # health/metrics/events (NEVER inference),
                                  # window cumulative counters into rates,
                                  # multi-window SLO error-budget burn
                                  # alerting; monitor --render --current=F
                                  # [--events=stack.jsonl] renders the
                                  # correlated event timeline; --attach
                                  # closes the hands-off loop: each window
                                  # ticks a fleet autoscaler acting through
                                  # {"op": "fleet"}, with reconnect-backoff
                                  # and typed give-ups (docs/CONTROL.md)
    python -m qdml_tpu.cli events --addr=HOST:PORT [--follow]
                                  [--interval=S] [--limit=N]
                                  [--min-severity=debug] [--kinds=a,b]
                                  # event-spine tail (docs/TELEMETRY.md
                                  # "event spine"): the unified envelope
                                  # stream of a RUNNING serve/route process
                                  # — cursor-resumable, restart-surviving,
                                  # explicit loss ledger; --follow streams
    python -m qdml_tpu.cli plan   --trace=W.jsonl[,..] (--validate |
                                  --target-rps=X --p99-ms=Y
                                  [--emit-target=T.json])
                                  # trace-replay capacity planner: DES of
                                  # the batcher->engine->fetch pipeline from
                                  # committed phase spans; --validate gates
                                  # predicted-vs-measured p99/throughput;
                                  # --emit-target writes the sealed fleet
                                  # target the fleet autoscaler consumes
    python -m qdml_tpu.cli fleet-scale --addr=HOST:PORT [--backends=N]
                                  # elastic-fleet lever (docs/FLEET.md):
                                  # {"op": "fleet"} against a RUNNING router
                                  # — status form without --backends, else
                                  # spawn-and-warm/drain-then-retire to N
                                  # via the router's lifecycle manager
                                  # (fleet.elastic=true); exit 3 when the
                                  # fleet did not converge

Every command's metrics JSONL starts with a run-manifest header (config hash,
git SHA, device topology, perf knobs, seeds) and carries span/counter records
from the telemetry layer (docs/TELEMETRY.md).

Dotted-path overrides map onto :mod:`qdml_tpu.config` dataclasses; presets are
the five BASELINE.json benchmark configs plus robust_qsc.
"""

from __future__ import annotations

import os
import sys
import time

from qdml_tpu import config as cfg_mod
from qdml_tpu.utils.metrics import MetricsLogger
from qdml_tpu.utils.platform import honor_platform_env


_COMMANDS = (
    "train-hdce",
    "train-dce",
    "train-sc",
    "train-qsc",
    "nat-sweep",
    "eval",
    "loss-curves",
    "profile",
    "gen-data",
    "import-torch",
    "export-torch",
    "serve",
    "loadgen",
    "control",
    "route",
)  # "report"/"lint"/"monitor"/"events"/"plan" dispatch before config
# parsing (host-side: no jax, no workdir)

_PASSTHROUGH = (  # command args, not config overrides
    "--out=",
    "--curves=",
    "--current=",
    "--baseline=",
    "--threshold=",
    "--rate=",
    "--n=",
    "--drift-at=",
    "--ticks=",
)


def _cfg(argv):
    extra = [a for a in argv if a.startswith(_PASSTHROUGH)]
    rest = [a for a in argv if not a.startswith(_PASSTHROUGH)]
    return cfg_mod.from_args(rest), extra


def _workdir(cfg) -> str:
    # reference scheme: ./workspace/Pn_128/HDCE (Runner...py:237-266)
    return os.path.join(cfg.train.workdir, f"Pn_{cfg.data.pilot_num}", cfg.name)


def fleet_scale_main(argv: list[str]) -> int:
    """``qdml-tpu fleet-scale --addr=HOST:PORT [--backends=N]
    [--timeout-s=S]``: the ``{"op": "fleet"}`` verb from the shell. Without
    ``--backends`` prints the membership/lifecycle status (always answers);
    with it, asks the router's lifecycle manager to converge the serving
    backend count — spawn-and-warm admissions and drain-then-retire
    removals, which can take minutes (``--timeout-s`` defaults to 900).
    Exit 0 on success/status, 3 when the fleet did not converge (typed
    reason printed), 2 on usage errors."""
    import json

    from qdml_tpu.serve.client import ServeClient, ServeClientError

    def arg(name, default):
        return next(
            (a.split("=", 1)[1] for a in argv if a.startswith(f"--{name}=")),
            default,
        )

    addr = arg("addr", None)
    if not addr or ":" not in addr:
        print("fleet-scale needs --addr=HOST:PORT (a running qdml-tpu route)")
        return 2
    host, port = addr.rsplit(":", 1)
    backends = arg("backends", None)
    timeout_s = float(arg("timeout-s", "900"))
    client = ServeClient(host, int(port), timeout_s=timeout_s, retries=0)
    try:
        rep = client.fleet(
            backends=None if backends is None else int(backends)
        )
    except (ServeClientError, ConnectionError, OSError) as e:
        print(json.dumps({"ok": False, "reason": f"{type(e).__name__}: {e}"}))
        return 3
    finally:
        client.close_connection()
    # rep is the full wire reply: ok carries the convergence verdict for
    # the scaling form (and the typed fleet_scale_unavailable refusal)
    print(json.dumps(rep, indent=2))
    return 0 if rep.get("ok") else 3


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    if argv[0] == "report":
        # Host-side tool over committed/produced artifacts: no jax, no
        # distributed init, no workdir — exit code is the regression gate.
        from qdml_tpu.telemetry.report import report_main

        return report_main(argv[1:])
    if argv[0] == "lint":
        # Host-side tool over source files: no jax, no config parsing —
        # exit code is the static-analysis gate (docs/ANALYSIS.md).
        from qdml_tpu.analysis.cli import lint_main

        return lint_main(argv[1:])
    if argv[0] == "monitor":
        # Host-side scraper: attaches to a RUNNING serve/route address over
        # the cheap health/metrics/events verbs only — no jax, no config
        # parsing, never an inference request (docs/TELEMETRY.md "flight
        # deck"; --attach drives the hands-off fleet loop, docs/CONTROL.md).
        from qdml_tpu.telemetry.timeseries import monitor_main

        return monitor_main(argv[1:])
    if argv[0] == "events":
        # Host-side event-spine tail: cursor-polls a RUNNING serve/route
        # address's {"op": "events"} verb — no jax, no config parsing
        # (docs/TELEMETRY.md "event spine").
        from qdml_tpu.telemetry.events import events_main

        return events_main(argv[1:])
    if argv[0] == "plan":
        # Host-side capacity planner over COMMITTED trace windows: exit
        # code is the planner-validation gate (docs/TELEMETRY.md).
        from qdml_tpu.telemetry.capacity import plan_main

        return plan_main(argv[1:])
    if argv[0] == "fleet-scale":
        # Host-side elastic-fleet lever: one {"op": "fleet"} exchange with
        # a RUNNING router — no jax, no config parsing, the router's
        # lifecycle manager does the spawning (docs/FLEET.md).
        return fleet_scale_main(argv[1:])
    # Make JAX_PLATFORMS=cpu actually select the CPU backend (the plugin
    # rewrites jax_platforms at interpreter start; qdml_tpu.utils.platform
    # is the single home for the workaround).
    honor_platform_env()
    # Multi-host: jax.distributed must initialize BEFORE any JAX computation
    # touches the backend (loaders/model init do). Explicit env triple first
    # (JAX_COORDINATOR_ADDRESS et al. — the strict path: failures propagate);
    # pod autodetection only when the environment carries a pod-worker hint,
    # so plain single-host startup never pays for (or depends on the failure
    # mode of) a cluster probe.
    from qdml_tpu.parallel.mesh import init_distributed
    from qdml_tpu.parallel.multihost import init_distributed_from_env, pod_env_hint

    if not init_distributed_from_env() and pod_env_hint():
        init_distributed()
    cmd, rest = argv[0], argv[1:]
    if cmd not in _COMMANDS:
        print(f"unknown command {cmd!r}")
        return 2
    cfg, extra = _cfg(rest)
    workdir = _workdir(cfg)
    # Run manifest + telemetry sink: the metrics stream opens with the
    # provenance header, and library-level spans/counters (train loops, eval
    # sweep) land in the same file.
    from qdml_tpu.telemetry import DivergenceError, run_manifest, set_sink

    logger = MetricsLogger(
        os.path.join(workdir, f"{cmd}.metrics.jsonl"),
        manifest=run_manifest(cfg, argv=argv),
    )
    set_sink(logger.telemetry)
    t0 = time.time()

    try:
        if cmd == "train-hdce":
            from qdml_tpu.train.hdce import train_hdce

            train_hdce(cfg, logger=logger, workdir=workdir)
        elif cmd == "train-dce":
            from qdml_tpu.train.dce import train_dce

            train_dce(cfg, logger=logger, workdir=workdir)
        elif cmd in ("train-sc", "train-qsc"):
            from qdml_tpu.train.qsc import train_classifier

            train_classifier(cfg, quantum=(cmd == "train-qsc"), logger=logger, workdir=workdir)
        elif cmd == "nat-sweep":
            from qdml_tpu.train.nat_sweep import train_nat_sweep

            train_nat_sweep(
                cfg, noise_levels=cfg.quantum.noise_sweep, logger=logger, workdir=workdir
            )
        elif cmd == "eval":
            from qdml_tpu.eval.report import create_comparison_plots, save_results_json
            from qdml_tpu.eval.sweep import run_snr_sweep
            from qdml_tpu.train.checkpoint import latest_tag, restore_params

            # Tag discovery (best > last > resume) is latest_tag's job — one
            # policy shared with the serving engine, no duplicated fallbacks.
            hdce_vars, _ = restore_params(workdir, latest_tag(workdir, "hdce") or "hdce_best")
            sc_vars, _ = restore_params(workdir, latest_tag(workdir, "sc") or "sc_best")
            qsc_vars = None
            qsc_tag = latest_tag(workdir, "qsc")
            if qsc_tag is not None:  # graceful fallback (Test.py:81-86)
                from qdml_tpu.train.checkpoint import reconcile_quantum_cfg

                qsc_vars, qsc_meta = restore_params(workdir, qsc_tag)
                cfg = reconcile_quantum_cfg(cfg, qsc_meta)
            # Optional monolithic-DCE baseline curve (beyond the reference's
            # shipped eval): included whenever `cli train-dce` has produced a
            # checkpoint in this workdir.
            dce_vars = None
            dce_tag = latest_tag(workdir, "dce")
            if dce_tag is not None:
                dce_vars, _ = restore_params(workdir, dce_tag)
            # Multi-device eval: same mesh contract as the trainers. A fed axis
            # == n_scenarios runs the all-hypotheses trunk pass expert-parallel
            # (each scenario's trunk on its own slice); the data axis shards the
            # test batch and its on-device generation.
            from qdml_tpu.parallel.mesh import training_mesh

            mesh = training_mesh(cfg)
            if mesh is not None:
                from qdml_tpu.parallel.federated import shard_hdce_vars

                hdce_vars = shard_hdce_vars(
                    hdce_vars, mesh, n_scenarios=cfg.data.n_scenarios
                )
            results = run_snr_sweep(
                cfg, hdce_vars, sc_vars, qsc_vars, logger=logger, dce_vars=dce_vars, mesh=mesh
            )
            out_json = save_results_json(results, cfg.eval.results_dir)
            out_png = create_comparison_plots(results, cfg.eval.results_dir)
            from qdml_tpu.eval.report import results_markdown_table

            table = results_markdown_table(results)
            with open(os.path.join(cfg.eval.results_dir, "results_table.md"), "w") as fh:
                fh.write(table + "\n")
            print(table)
            print(f"results: {out_json} plot: {out_png}")
        elif cmd == "loss-curves":
            from qdml_tpu.eval.loss_curves import (
                create_loss_curve_plot,
                parse_curve_spec,
                read_loss_history,
            )

            spec = next(
                (a.split("=", 1)[1] for a in extra if a.startswith("--curves=")), None
            )
            if spec is None:
                raise SystemExit("loss-curves requires --curves=LABEL:PATH[,LABEL:PATH...]")
            curves = [
                (label, read_loss_history(path)) for label, path in parse_curve_spec(spec)
            ]
            out = create_loss_curve_plot(curves, cfg.eval.results_dir)
            print(f"loss curves: {out}")
        elif cmd == "profile":
            # Captured-trace evidence for SURVEY.md §5.1: a TensorBoard-loadable
            # jax.profiler trace of real train steps plus steady-state
            # samples/sec from StepTimer.
            import json

            from qdml_tpu.data.datasets import DMLGridLoader
            from qdml_tpu.train.hdce import init_hdce_state, make_hdce_train_step
            from qdml_tpu.utils.profiling import StepTimer, trace

            from qdml_tpu.telemetry import device_memory_snapshot, span
            from qdml_tpu.utils.compile_cache import compile_cache_stats
            from qdml_tpu.utils.profiling import force

            out = next((e.split("=", 1)[1] for e in extra if e.startswith("--out=")), "results/tpu_trace")
            loader = DMLGridLoader(cfg.data, cfg.train.batch_size)
            batch = next(iter(loader.epoch(0)))
            model, state = init_hdce_state(cfg, loader.steps_per_epoch)
            # probes follow the same knob as the train loops, so the profiled
            # program is the one a real run with this config executes (and
            # --train.probe_every=0 compiles them out, matching its contract)
            step = make_hdce_train_step(
                model, state.tx, probes=cfg.train.probe_every > 0
            )
            with span("compile"):  # compile + first execute, outside the trace
                state, m = step(state, batch)
                force(m["loss"])
            timer = StepTimer(warmup=2)
            n_steps = 12
            with trace(out):
                with span("steady_state", steps=n_steps):
                    for _ in range(n_steps):
                        state, m = step(state, batch)
                        timer.tick(m["loss"])
            import jax as _jax

            grid = cfg.data.n_scenarios * cfg.data.n_users
            summary = {
                "backend": _jax.default_backend(),
                "steps_traced": n_steps,
                "samples_per_sec": round(
                    timer.samples_per_sec(cfg.train.batch_size * grid), 1
                ),
                # percentiles, not just the mean rate (dispatch intervals on an
                # async backend — see StepTimer.histogram)
                "step_ms": timer.histogram(),
                "memory": device_memory_snapshot(),
                "compile_cache": compile_cache_stats(),
                "trace_dir": out,
            }
            with open(os.path.join(out, "summary.json"), "w") as fh:
                json.dump(summary, fh, indent=2)
            print(json.dumps(summary))
        elif cmd == "gen-data":
            from qdml_tpu.data.datasets import save_npy_cache

            out = next((e.split("=", 1)[1] for e in extra if e.startswith("--out=")), "available_data")
            save_npy_cache(out, cfg.data)
            print(f"wrote npy cache to {out}")
        elif cmd == "import-torch":
            from qdml_tpu.train.checkpoint import save_checkpoint
            from qdml_tpu.train.torch_interop import import_reference_dir

            src = next((e.split("=", 1)[1] for e in extra if e.startswith("--out=")), ".")
            trees = import_reference_dir(
                src, batch_size=cfg.train.batch_size, snr_db=int(cfg.data.snr_db)
            )
            for name, tree in trees.items():
                meta: dict = {"source": src}
                if name == "qsc":
                    # Architecture facts from the imported params themselves so
                    # eval rebuilds the right model (reference QSCs are raw-pilot:
                    # no input normalization).
                    qw = tree["params"]["qweights"]
                    from qdml_tpu.quantum.circuits import resolve_backend

                    meta["quantum"] = {
                        "n_qubits": int(qw.shape[1]),
                        "n_layers": int(qw.shape[0]),
                        "n_classes": int(tree["params"]["Dense_0"]["bias"].shape[0]),
                        # resolved path, not the "auto" alias (provenance)
                        "backend": resolve_backend(cfg.quantum.backend, int(qw.shape[1])),
                        "input_norm": False,
                    }
                save_checkpoint(workdir, f"{name}_best", tree, meta)
            print(f"imported {sorted(trees)} from {src} -> {workdir}")
        elif cmd == "export-torch":
            from qdml_tpu.train.checkpoint import has_checkpoint, restore_checkpoint
            from qdml_tpu.train.torch_interop import export_reference_dir

            out = next((e.split("=", 1)[1] for e in extra if e.startswith("--out=")), "torch_ckpts")
            kwargs = {}
            if has_checkpoint(workdir, "hdce_best"):
                kwargs["hdce_vars"], _ = restore_checkpoint(workdir, "hdce_best")
            if has_checkpoint(workdir, "sc_best"):
                kwargs["sc_params"] = restore_checkpoint(workdir, "sc_best")[0]["params"]
            if has_checkpoint(workdir, "qsc_best"):
                kwargs["qsc_params"] = restore_checkpoint(workdir, "qsc_best")[0]["params"]
            written = export_reference_dir(
                out, batch_size=cfg.train.batch_size, snr_db=int(cfg.data.snr_db), **kwargs
            )
            print("wrote:\n  " + "\n  ".join(written))
        elif cmd == "serve":
            from qdml_tpu.parallel.mesh import serve_mesh
            from qdml_tpu.serve import ServeEngine
            from qdml_tpu.serve.server import run_server
            from qdml_tpu.telemetry import span as _span

            # mesh before the engine: every bucket executable bakes in its
            # sharding at warmup (docs/SERVING.md, "sharded serving")
            engine = ServeEngine.from_workdir(cfg, workdir, mesh=serve_mesh(cfg))
            with _span("serve_warmup", buckets=list(engine.buckets)):
                engine.warmup()
            # workdir arms the {"op": "swap"} hot-swap verb: a training run
            # promoting a new *_best deploys without restarting the server
            run_server(cfg, engine, logger=logger, workdir=workdir)
        elif cmd == "loadgen":
            import json

            from qdml_tpu.parallel.mesh import serve_mesh
            from qdml_tpu.serve import ServeEngine
            from qdml_tpu.serve.loadgen import run_loadgen

            rate = float(next(
                (e.split("=", 1)[1] for e in extra if e.startswith("--rate=")), 200.0
            ))
            n = int(next(
                (e.split("=", 1)[1] for e in extra if e.startswith("--n=")), 512
            ))
            engine = ServeEngine.from_workdir(cfg, workdir, mesh=serve_mesh(cfg))
            deadline = cfg.serve.deadline_ms if cfg.serve.deadline_ms > 0 else None
            drift_at = next(
                (int(e.split("=", 1)[1]) for e in extra if e.startswith("--drift-at=")),
                None,
            )
            summary = run_loadgen(
                cfg, engine, rate=rate, n=n, deadline_ms=deadline, logger=logger,
                drift_at=drift_at,
            )
            print(json.dumps(summary))
        elif cmd == "control":
            from qdml_tpu.control.loop import control_main

            ticks = next(
                (int(e.split("=", 1)[1]) for e in extra if e.startswith("--ticks=")),
                None,
            )
            # attaches to the RUNNING `qdml-tpu serve` at serve.host:port
            # over the metrics/swap/scale verbs; fine-tune + canary run in
            # this process against the shared workdir (docs/CONTROL.md)
            return control_main(cfg, logger=logger, workdir=workdir, ticks=ticks)
        elif cmd == "route":
            from qdml_tpu.fleet import run_router

            # pure protocol tier: no checkpoints, no device compute — the
            # backends named by fleet.backends own the models (docs/FLEET.md)
            run_router(cfg, logger=logger)
        # reference prints total minutes (Runner...py:437-440)
        print(f"total time: {(time.time() - t0) / 60.0:.2f} min")
        return 0
    except DivergenceError as e:
        # divergence watchdog trips arrive as typed errors carrying the
        # flight-recorder dump path — surface the pointer, not a traceback;
        # everything else propagates untouched (narrowed from a broad
        # isinstance-and-reraise, graftlint broad-except)
        print(f"DIVERGED: {e}")
        return 4
    finally:
        # always detach the global sink and close the stream — an exception
        # mid-command (or an in-process caller) must not leave later spans
        # appending to a dead run's file
        set_sink(None)
        logger.close()


if __name__ == "__main__":
    raise SystemExit(main())
