from qdml_tpu.data.baselines import (  # noqa: F401
    beam_delay_profile,
    ls_estimate,
    mmse_estimate,
    sigma2_for_snr,
)
from qdml_tpu.data.channels import (  # noqa: F401
    ChannelGeometry,
    generate_samples,
    label_noise_var,
    make_sample_key,
    noise_var,
    sample_channel,
    sound_pilots,
)
from qdml_tpu.data.datasets import (  # noqa: F401
    DMLGridLoader,
    generate_datapair,
    load_npy_cache,
    make_network_batch,
    save_npy_cache,
)
