"""Synthetic DeepMIMO-style geometric channel generator (TPU-native, real-pair).

The reference trains on pre-generated ``.npy`` arrays from DeepMIMO ray tracing
loaded by a ``generate_data`` module that is MISSING from its snapshot (imported
at ``Runner_P128_QuantumNAT_onchipQNN.py:16`` and ``Test.py:7``; contracts
reconstructed in SURVEY.md §2.8). This module is the TPU-native replacement: a
fully jittable, deterministic (seeded per sample index) geometric multipath
generator — three frozen reference scenarios x three users by default, and a
parameterized family synthesizer (:func:`family_table`) deriving S >> 3
UMa/UMi/InH-style propagation families (delay spread / angular spread /
K-factor / Doppler-mobility ladders) entirely on device for the scenario
scale-out axis — matching the reference's array contracts:

- ``Yp``: complex ``(N, 128)`` pilots (beam-major flattening of an
  ``(n_beam=8, n_sub=16)`` beam-sounding grid),
- ``Hperf``: complex ``(N, 1024)`` perfect CSI (flat ``(n_ant=64, n_sub=16)``),
- ``Hlabel``: complex ``(N, 1024)`` LS estimate used as the training label
  (``Test.py:140`` names it ``HLS``),
- ``indicator``: int scenario id in {0,1,2} (``Runner...py:58-61``).

All complex values are :class:`~qdml_tpu.utils.complexops.CArr` real pairs —
TPUs have no complex dtype; contractions lower to real MXU matmuls.

Physics: a base station ULA with ``n_ant`` antennas sounds the channel through
the first ``n_beam`` rows of the unitary ``n_ant``-point DFT (a beam codebook),
observing ``Yp = F_beam @ H + noise`` per subcarrier. Scenarios differ in path
count, angular spread, delay spread and LOS K-factor; users differ in their
angular sector. Channel energy concentrates in the sounded beam sector, so LS
back-projection is a meaningful baseline while a learned estimator can exploit
the scenario-conditional structure (Dirichlet side-lobe leakage into unsounded
beams is a deterministic function of path geometry).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from qdml_tpu.config import DataConfig
from qdml_tpu.utils.complexops import CArr, ceinsum, cexp_i, cexp_i_ramp
from qdml_tpu.utils.platform import ensure_jax_compat

# The generator's anti-fusion barrier (sample_channel) must vmap/grad on jax
# versions that ship optimization_barrier without those rules.
ensure_jax_compat()

# Maximum paths across scenarios; per-scenario counts are masked (static shapes
# for jit — no data-dependent Python control flow).
MAX_PATHS = 20

# Per-scenario propagation parameters: [LOS-dominant, moderate NLOS, rich
# scattering] — the 3GPP-flavored base presets (InH-LOS-like, UMi-like,
# UMa-NLOS-like) every committed stream was generated from. These three rows
# are FROZEN: family_table(3) returns exactly them, so the reference-parity
# S=3 datasets stay bit-identical forever.
FAMILY_PRESET_NAMES = ("inh_los", "umi_street", "uma_nlos")
SCENARIO_N_PATHS = np.array([3, 8, 20], dtype=np.int32)
SCENARIO_ANGLE_SPREAD = np.array([0.3 / 64, 0.8 / 64, 1.6 / 64], dtype=np.float32)
SCENARIO_DELAY_SPREAD = np.array([0.6, 1.8, 3.5], dtype=np.float32)  # in samples
SCENARIO_K_FACTOR = np.array([8.0, 2.0, 0.5], dtype=np.float32)  # LOS power boost
# Per-preset mobility (Doppler phase spread, radians RMS per path). The base
# presets carry 0.0 — mobility multiplies every path gain by exp(i*phi) with
# phi ~ N(0, mobility^2), and exp(i*0) = 1 + 0i is an EXACT float identity,
# so the committed S=3 streams are untouched down to the bit. Derived
# families (s >= 3) get nonzero mobility: the pedestrian/vehicular axis that
# makes S >> 3 families genuinely distinct, not re-seeded copies.
SCENARIO_MOBILITY = np.array([0.0, 0.0, 0.0], dtype=np.float32)


def family_table(
    n_scenarios: int, drift_step: int = 0, drift_scenario: int = -1
) -> dict[str, np.ndarray]:
    """Per-scenario propagation parameters for an S-family grid — the
    on-device channel-family synthesizer's parameter bank (host numpy; the
    geometry is a jit-static argument, so these become trace-time constants
    inside the scan-fused step — S >> 3 costs no host transfer and no DeepMIMO
    files, preserving the zero-host-transfer training pin).

    Rows 0..2 are the frozen base presets (bit-identical S=3 streams); row
    ``s >= 3`` derives family ``s`` from base preset ``s % 3`` at tier
    ``s // 3``: each tier adds paths, widens the angular spread, stretches
    the delay spread (capped at the CP-like n_sub/2 the sampler clips to),
    bleeds K-factor toward Rayleigh, and turns on mobility — a deterministic
    UMa/UMi/InH-style family ladder, so family s is the same physics on every
    host and every run. Prefix property: ``family_table(S)[k] ==
    family_table(S')[k]`` for every ``k < min(S, S')`` — growing the grid
    never re-parameterizes existing scenarios (pinned in tests/test_data.py).

    Drift trajectory (``drift_step > 0``): a deterministic parameterized
    perturbation of the table as a function of the drift step ``d`` — the
    environment evolving under a model's feet (the fleet-control subsystem's
    testable stand-in for a real scenario drifting, docs/CONTROL.md). Per
    step, the affected row(s) stretch delay spread (+12%/step, same CP-style
    cap as the tier ladder), bleed K-factor toward Rayleigh (/(1+0.25 d)),
    widen the angular spread (+8%/step) and pick up mobility (+0.08 rad/step
    Doppler phase spread). ``drift_scenario`` selects ONE drifting family
    (-1 drifts them all). ``drift_step=0`` returns the frozen table with NO
    float ops applied — bit-identical to the undrifted call, pinned in
    tests/test_control.py.
    """
    if n_scenarios < 1:
        raise ValueError(f"n_scenarios must be >= 1, got {n_scenarios}")
    if drift_step < 0:
        raise ValueError(f"drift_step must be >= 0, got {drift_step}")
    idx = np.arange(n_scenarios)
    base = idx % 3
    tier = (idx // 3).astype(np.float32)
    table = {
        "n_paths": np.clip(
            SCENARIO_N_PATHS[base] + 2 * (idx // 3), 1, MAX_PATHS
        ).astype(np.int32),
        "angle_spread": (
            SCENARIO_ANGLE_SPREAD[base] * (1.0 + 0.25 * tier)
        ).astype(np.float32),
        "delay_spread": np.clip(
            SCENARIO_DELAY_SPREAD[base] * (1.0 + 0.3 * tier), 0.1, None
        ).astype(np.float32),
        "k_factor": (SCENARIO_K_FACTOR[base] / (1.0 + 0.5 * tier)).astype(
            np.float32
        ),
        "mobility": (
            SCENARIO_MOBILITY[base]
            + np.where(tier > 0, 0.15 * np.sqrt(tier), 0.0)
        ).astype(np.float32),
        # plain python list (host metadata, never gathered on device)
        "preset": [
            FAMILY_PRESET_NAMES[b] + (f"+t{t:.0f}" if t else "")
            for b, t in zip(base, tier)
        ],
    }
    if drift_step == 0:
        # the frozen table, untouched: no float op may run here — this exact
        # early return is what makes "drift 0 == the committed streams" a
        # bitwise fact rather than a rounding accident
        return table
    d = np.float32(drift_step)
    hit = np.ones(n_scenarios, bool) if drift_scenario < 0 else (idx == drift_scenario)
    table["delay_spread"] = np.where(
        hit, np.clip(table["delay_spread"] * (1.0 + 0.12 * d), 0.1, None),
        table["delay_spread"],
    ).astype(np.float32)
    table["k_factor"] = np.where(
        hit, table["k_factor"] / (1.0 + 0.25 * d), table["k_factor"]
    ).astype(np.float32)
    table["angle_spread"] = np.where(
        hit, table["angle_spread"] * (1.0 + 0.08 * d), table["angle_spread"]
    ).astype(np.float32)
    table["mobility"] = np.where(
        hit, table["mobility"] + 0.08 * d, table["mobility"]
    ).astype(np.float32)
    table["preset"] = [
        p + (f"~d{drift_step}" if h else "") for p, h in zip(table["preset"], hit)
    ]
    return table
# Per-user angular sector centres, in spatial-frequency units f = d/lambda*sin(theta).
# Sector centres + 2-sigma truncated spreads stay strictly inside the sounded
# beam span (max f = 4.2/64 + 2*1.6/64 = 7.4/64 < n_beam/64): the compressed
# pilots observe essentially ALL channel energy, so a learned estimator's
# ceiling is pilot noise + path-prior averaging — the regime in which the
# reference's published HDCE-vs-MMSE gaps (-9 vs -3.5 dB @ 5 dB SNR) are
# achievable (VERDICT r1 missing #4: generator must make the published
# science reproducible, not just plausible).
USER_CENTER_F = np.array([0.8 / 64, 2.5 / 64, 4.2 / 64], dtype=np.float32)


@dataclasses.dataclass(frozen=True)
class ChannelGeometry:
    """Precomputed constants for a dataset geometry (hashable -> static under jit)."""

    n_ant: int = 64
    n_sub: int = 16
    n_beam: int = 8
    # Scenario-family count S: rows of family_table(S) the sampler can gather
    # (the scenario id is a traced int; the TABLE is a trace-time constant of
    # this static field). 3 = the frozen reference presets; S > 3 appends
    # derived UMa/UMi/InH-style families without touching rows 0..2.
    n_scenarios: int = 3
    # Channel-family drift trajectory (family_table's drift args): drift_step
    # 0 (default) is the frozen table down to the bit; > 0 perturbs
    # delay-spread / K-factor / angular-spread / mobility of drift_scenario
    # (-1 = every family) as a deterministic function of the step — the
    # fleet-control subsystem's injected-drift axis (docs/CONTROL.md). Static
    # fields: a drifted geometry selects a different compiled program, never
    # a runtime branch.
    drift_step: int = 0
    drift_scenario: int = -1
    # Full-pilot LS label noise scale: per-entry variance of the Hlabel/HLS
    # observation is ``label_noise_factor * 10**(-SNR/10)`` (unit channel-entry
    # power). 1.9 (= 10**0.28, i.e. a 2.8 dB pilot-overhead loss) calibrates
    # the LS baseline to the reference's published curve: NMSE_LS ~= -SNR+2.8
    # dB (-2.2 dB @ 5, -12.2 dB @ 15; `channel estimation performace
    # comparison.png`, BASELINE.md).
    label_noise_factor: float = 1.9
    # PRNG implementation for sample synthesis ("threefry" | "rbg"); see
    # DataConfig.rng_impl. Static (geometry is a jit static argument), so
    # the choice selects the compiled program, not a runtime branch.
    rng_impl: str = "threefry"
    # Phase-ramp evaluation for steering/delay responses: "direct" (one
    # sin/cos per ramp element — bit-compatible with every committed stream)
    # or "split" (angle-addition factorization, ~4x fewer transcendentals,
    # same values to f32 rounding; see complexops.cexp_i_ramp). Static.
    trig_impl: str = "direct"

    def __post_init__(self):
        # Same rejection contract as make_sample_key's rng_impl check (ADVICE
        # r5 low): an unknown trig_impl must not silently select "direct".
        if self.rng_impl not in ("threefry", "rbg"):
            raise ValueError(
                f"rng_impl must be 'threefry' or 'rbg', got {self.rng_impl!r}"
            )
        if self.trig_impl not in ("direct", "split"):
            raise ValueError(
                f"trig_impl must be 'direct' or 'split', got {self.trig_impl!r}"
            )
        if self.drift_step < 0:
            raise ValueError(f"drift_step must be >= 0, got {self.drift_step}")
        if not (-1 <= self.drift_scenario < self.n_scenarios):
            raise ValueError(
                f"drift_scenario must be -1 (all) or a scenario id < "
                f"{self.n_scenarios}, got {self.drift_scenario}"
            )

    @classmethod
    def from_config(cls, cfg: DataConfig) -> "ChannelGeometry":
        return cls(
            n_ant=cfg.n_ant,
            n_sub=cfg.n_sub,
            n_beam=cfg.n_beam,
            n_scenarios=cfg.n_scenarios,
            drift_step=cfg.drift_step,
            drift_scenario=cfg.drift_scenario,
            label_noise_factor=cfg.label_noise_factor,
            rng_impl=cfg.rng_impl,
            trig_impl=cfg.trig_impl,
        )

    @property
    def pilot_num(self) -> int:
        return self.n_beam * self.n_sub

    @property
    def h_dim(self) -> int:
        return self.n_ant * self.n_sub

    def _dft(self, rows: int, n: int) -> CArr:
        m = np.arange(rows)[:, None]
        a = np.arange(n)[None, :]
        ang = -2.0 * np.pi * m * a / n
        scale = 1.0 / np.sqrt(n)
        return CArr(
            jnp.asarray((np.cos(ang) * scale).astype(np.float32)),
            jnp.asarray((np.sin(ang) * scale).astype(np.float32)),
        )

    @property
    def beam_matrix(self) -> CArr:
        """First ``n_beam`` rows of the unitary ``n_ant``-point DFT: (n_beam, n_ant)."""
        return self._dft(self.n_beam, self.n_ant)

    @property
    def ant_dft(self) -> CArr:
        """Full unitary antenna DFT (n_ant, n_ant) — beam-domain transform."""
        return self._dft(self.n_ant, self.n_ant)

    @property
    def sub_dft(self) -> CArr:
        """Full unitary subcarrier DFT (n_sub, n_sub) — delay-domain transform."""
        return self._dft(self.n_sub, self.n_sub)

    @property
    def noise_ref_power(self) -> float:
        """Nominal per-pilot signal power used to set the noise floor.

        With unit average channel-entry power, the sounded-beam sector holds
        ~all the energy, so per-pilot power ~= h_dim / pilot_num.
        """
        return self.h_dim / self.pilot_num


def noise_var(geom: ChannelGeometry, snr_db: jnp.ndarray | float) -> jnp.ndarray:
    """Per-pilot-entry complex noise variance for a given SNR (dB)."""
    return geom.noise_ref_power * 10.0 ** (-jnp.asarray(snr_db, jnp.float32) / 10.0)


def label_noise_var(geom: ChannelGeometry, snr_db: jnp.ndarray | float) -> jnp.ndarray:
    """Per-entry complex noise variance of the full-pilot LS label ``Hlabel``.

    The reference's ``Hlabel``/``HLS`` is a 1024-entry LS estimate — it cannot
    be a function of the 128-entry ``Yp`` (SURVEY.md §2.8 shape contract), so
    it models an independent full-dimension pilot observation
    ``H + CN(0, sigma2_label)``. This is what makes training against it
    non-degenerate: its conditional mean given ``Yp`` is the true channel, so
    a learned estimator denoises instead of reproducing back-projection.
    """
    return geom.label_noise_factor * 10.0 ** (-jnp.asarray(snr_db, jnp.float32) / 10.0)


# ---------------------------------------------------------------------------
# Single-sample generation (vmapped for batches)
# ---------------------------------------------------------------------------


def _steering(f: jnp.ndarray, n_ant: int, trig_impl: str = "direct") -> CArr:
    """ULA steering vectors for spatial frequencies f: (L,) -> (L, n_ant)."""
    if trig_impl == "split":
        return cexp_i_ramp(2.0 * jnp.pi * f, n_ant)
    n = jnp.arange(n_ant, dtype=jnp.float32)
    return cexp_i(2.0 * jnp.pi * f[:, None] * n)


def _delay_response(tau: jnp.ndarray, n_sub: int, trig_impl: str = "direct") -> CArr:
    """Subcarrier responses for delays tau (samples): (L,) -> (L, n_sub)."""
    if trig_impl == "split":
        return cexp_i_ramp(-2.0 * jnp.pi * tau / n_sub, n_sub)
    k = jnp.arange(n_sub, dtype=jnp.float32)
    return cexp_i(-2.0 * jnp.pi * tau[:, None] * k / n_sub)


@partial(jax.jit, static_argnames=("geom",))
def sample_channel(
    key: jax.Array, scenario: jnp.ndarray, user: jnp.ndarray, geom: ChannelGeometry
) -> CArr:
    """Draw one channel realisation H (n_ant, n_sub) as a CArr.

    ``scenario``/``user`` are traced int32 scalars — all branching is via
    gather/mask so the function stays shape-static under jit and vmap.
    """
    k_f, k_tau, k_gain = jax.random.split(key, 3)
    s = scenario.astype(jnp.int32)
    u = user.astype(jnp.int32)

    fam = family_table(geom.n_scenarios, geom.drift_step, geom.drift_scenario)
    n_paths = jnp.asarray(fam["n_paths"])[s]
    spread = jnp.asarray(fam["angle_spread"])[s]
    dly = jnp.asarray(fam["delay_spread"])[s]
    kfac = jnp.asarray(fam["k_factor"])[s]
    center = jnp.asarray(USER_CENTER_F)[u]

    mask = (jnp.arange(MAX_PATHS) < n_paths).astype(jnp.float32)

    # Path spatial frequencies around the user's sector centre.
    f = center + spread * jax.random.truncated_normal(k_f, -2.0, 2.0, (MAX_PATHS,))
    f = jnp.clip(f, 0.05 / geom.n_ant, None)

    # Path delays: LOS path at tau=0, NLOS exponential with scenario spread.
    tau_raw = dly * jax.random.exponential(k_tau, (MAX_PATHS,))
    tau = jnp.where(jnp.arange(MAX_PATHS) == 0, 0.0, jnp.clip(tau_raw, 0.0, geom.n_sub / 2.0))

    # Path powers: exponential decay in delay; LOS K-factor boost on path 0.
    p = jnp.exp(-tau / jnp.maximum(dly, 0.3))
    p = p * jnp.where(jnp.arange(MAX_PATHS) == 0, kfac, 1.0) * mask
    p = p / jnp.maximum(jnp.sum(p), 1e-12)  # E||H||^2 = n_ant * n_sub

    g = jax.random.normal(k_gain, (MAX_PATHS, 2))
    amp = jnp.sqrt(p / 2.0)
    alpha = CArr(amp * g[:, 0], amp * g[:, 1])  # (L,)

    # Mobility (Doppler) phase spread: per-path gain rotated by exp(i*phi),
    # phi ~ N(0, mobility^2). The key derives by fold_in — NOT another split
    # of `key` — so k_f/k_tau/k_gain (and with them every committed stream)
    # are byte-for-byte unchanged. The whole block is compiled OUT when no
    # family in this (static) geometry is mobile — fam is a trace-time host
    # constant, so the frozen S=3 reference grid pays zero extra ops, not
    # just a bitwise-identity rotation (the sin/cos tail is the generator's
    # stated VPU bottleneck). Mobile families at mobility = 0 would still be
    # exact: cos 0 = 1, sin 0 = 0 make the multiply a float identity.
    if np.any(fam["mobility"] > 0.0):
        mobility = jnp.asarray(fam["mobility"])[s]
        phi = mobility * jax.random.normal(
            jax.random.fold_in(key, 7), (MAX_PATHS,)
        )
        alpha = alpha * cexp_i(phi)

    a = _steering(f, geom.n_ant, geom.trig_impl)  # (L, n_ant)
    b = _delay_response(tau, geom.n_sub, geom.trig_impl)  # (L, n_sub)
    w = CArr(alpha.re[:, None], alpha.im[:, None]) * a  # (L, n_ant)
    # Materialize the steering/delay factors before the path contraction.
    # Without this barrier XLA (TPU) fuses the sin/cos chains INTO the
    # reduction loop — a "convolution fusion" that recomputes the trig for
    # every (antenna, subcarrier) output element, ~n_sub*n_ant-fold redundant
    # work that made this contraction 5x the cost of the whole rest of the
    # generator (measured on v5e: 3.0 -> 0.57 ms per 2304-sample batch).
    wre, wim, bre, bim = jax.lax.optimization_barrier((w.re, w.im, b.re, b.im))
    return ceinsum("la,lk->ak", CArr(wre, wim), CArr(bre, bim))  # (n_ant, n_sub)


@partial(jax.jit, static_argnames=("geom",))
def sound_pilots(
    key: jax.Array, h: CArr, snr_db: jnp.ndarray, geom: ChannelGeometry
) -> CArr:
    """Observe Yp = F_beam @ H + noise, flattened beam-major to (pilot_num,)."""
    x = ceinsum("ba,ak->bk", geom.beam_matrix, h)  # (n_beam, n_sub)
    sigma2 = noise_var(geom, snr_db)
    nre, nim = jax.random.normal(key, (2,) + x.shape)
    scale = jnp.sqrt(sigma2 / 2.0)
    return (x + CArr(scale * nre, scale * nim)).reshape(geom.pilot_num)


def make_sample_key(
    seed: int | jnp.ndarray, scenario, user, index, impl: str = "threefry"
) -> jax.Array:
    """Deterministic per-sample key: (seed, scenario, user, index) -> PRNGKey.

    Replaces the reference's pre-generated-file determinism (``Runner...py:49-55``
    filename scheme + ``start`` offsets in ``Test.py:127-129``): sample ``index``
    of cell (scenario, user) is always the same realisation.

    ``impl`` selects the jax PRNG implementation: "threefry" (default,
    bit-reproducible everywhere) or "rbg" (key derivation still threefry;
    bit *generation* uses XLA's RngBitGenerator — the fast path on TPU for
    in-dispatch synthesis, see DataConfig.rng_impl).
    """
    if impl == "threefry":
        # Raw (legacy) key, exactly as always — keeps every committed stream
        # bit-identical.
        key = jax.random.PRNGKey(seed)
    elif impl == "rbg":
        # Typed key: a raw uint32[4] rbg key would be re-interpreted as
        # threefry by downstream jax.random calls; the typed dtype carries
        # the impl through fold_in/split/vmap.
        key = jax.random.key(seed, impl="rbg")
    else:
        raise ValueError(f"rng_impl must be 'threefry' or 'rbg', got {impl!r}")
    key = jax.random.fold_in(key, scenario)
    key = jax.random.fold_in(key, user)
    return jax.random.fold_in(key, index)


@partial(jax.jit, static_argnames=("geom",))
def generate_samples(
    seed: jnp.ndarray,
    scenarios: jnp.ndarray,
    users: jnp.ndarray,
    indices: jnp.ndarray,
    snr_db: jnp.ndarray,
    geom: ChannelGeometry,
) -> dict:
    """Vectorised sample synthesis.

    Returns dict with ``yp (B, pilot_num) CArr``, ``h_perf (B, h_dim) CArr``,
    ``h_ls (B, h_dim) CArr`` — the full-pilot LS observation
    ``H + CN(0, label_noise_var)``, independent of ``yp``'s noise (the
    reference's ``Hlabel``/``HLS`` training label and LS eval baseline) — and
    ``indicator (B,) i32``.
    """

    def one(scenario, user, index):
        key = make_sample_key(seed, scenario, user, index, impl=geom.rng_impl)
        k_h, k_n, k_l = jax.random.split(key, 3)
        h = sample_channel(k_h, scenario, user, geom)
        yp = sound_pilots(k_n, h, snr_db, geom)
        hf = h.reshape(geom.h_dim)
        scale = jnp.sqrt(label_noise_var(geom, snr_db) / 2.0)
        lre, lim = jax.random.normal(k_l, (2,) + hf.shape)
        h_ls = hf + CArr(scale * lre, scale * lim)
        return yp, hf, h_ls

    yp, h, h_ls = jax.vmap(one)(scenarios, users, indices)
    return {
        "yp": yp,
        "h_perf": h,
        "h_ls": h_ls,
        "indicator": scenarios.astype(jnp.int32),
    }
