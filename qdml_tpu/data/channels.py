"""Synthetic DeepMIMO-style geometric channel generator (TPU-native, real-pair).

The reference trains on pre-generated ``.npy`` arrays from DeepMIMO ray tracing
loaded by a ``generate_data`` module that is MISSING from its snapshot (imported
at ``Runner_P128_QuantumNAT_onchipQNN.py:16`` and ``Test.py:7``; contracts
reconstructed in SURVEY.md §2.8). This module is the TPU-native replacement: a
fully jittable, deterministic (seeded per sample index) geometric multipath
generator with three propagation scenarios x three users, matching the
reference's array contracts:

- ``Yp``: complex ``(N, 128)`` pilots (beam-major flattening of an
  ``(n_beam=8, n_sub=16)`` beam-sounding grid),
- ``Hperf``: complex ``(N, 1024)`` perfect CSI (flat ``(n_ant=64, n_sub=16)``),
- ``Hlabel``: complex ``(N, 1024)`` LS estimate used as the training label
  (``Test.py:140`` names it ``HLS``),
- ``indicator``: int scenario id in {0,1,2} (``Runner...py:58-61``).

All complex values are :class:`~qdml_tpu.utils.complexops.CArr` real pairs —
TPUs have no complex dtype; contractions lower to real MXU matmuls.

Physics: a base station ULA with ``n_ant`` antennas sounds the channel through
the first ``n_beam`` rows of the unitary ``n_ant``-point DFT (a beam codebook),
observing ``Yp = F_beam @ H + noise`` per subcarrier. Scenarios differ in path
count, angular spread, delay spread and LOS K-factor; users differ in their
angular sector. Channel energy concentrates in the sounded beam sector, so LS
back-projection is a meaningful baseline while a learned estimator can exploit
the scenario-conditional structure (Dirichlet side-lobe leakage into unsounded
beams is a deterministic function of path geometry).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from qdml_tpu.config import DataConfig
from qdml_tpu.utils.complexops import CArr, ceinsum, cexp_i, cexp_i_ramp
from qdml_tpu.utils.platform import ensure_jax_compat

# The generator's anti-fusion barrier (sample_channel) must vmap/grad on jax
# versions that ship optimization_barrier without those rules.
ensure_jax_compat()

# Maximum paths across scenarios; per-scenario counts are masked (static shapes
# for jit — no data-dependent Python control flow).
MAX_PATHS = 20

# Per-scenario propagation parameters: [LOS-dominant, moderate NLOS, rich scattering]
SCENARIO_N_PATHS = np.array([3, 8, 20], dtype=np.int32)
SCENARIO_ANGLE_SPREAD = np.array([0.3 / 64, 0.8 / 64, 1.6 / 64], dtype=np.float32)
SCENARIO_DELAY_SPREAD = np.array([0.6, 1.8, 3.5], dtype=np.float32)  # in samples
SCENARIO_K_FACTOR = np.array([8.0, 2.0, 0.5], dtype=np.float32)  # LOS power boost
# Per-user angular sector centres, in spatial-frequency units f = d/lambda*sin(theta).
# Sector centres + 2-sigma truncated spreads stay strictly inside the sounded
# beam span (max f = 4.2/64 + 2*1.6/64 = 7.4/64 < n_beam/64): the compressed
# pilots observe essentially ALL channel energy, so a learned estimator's
# ceiling is pilot noise + path-prior averaging — the regime in which the
# reference's published HDCE-vs-MMSE gaps (-9 vs -3.5 dB @ 5 dB SNR) are
# achievable (VERDICT r1 missing #4: generator must make the published
# science reproducible, not just plausible).
USER_CENTER_F = np.array([0.8 / 64, 2.5 / 64, 4.2 / 64], dtype=np.float32)


@dataclasses.dataclass(frozen=True)
class ChannelGeometry:
    """Precomputed constants for a dataset geometry (hashable -> static under jit)."""

    n_ant: int = 64
    n_sub: int = 16
    n_beam: int = 8
    # Full-pilot LS label noise scale: per-entry variance of the Hlabel/HLS
    # observation is ``label_noise_factor * 10**(-SNR/10)`` (unit channel-entry
    # power). 1.9 (= 10**0.28, i.e. a 2.8 dB pilot-overhead loss) calibrates
    # the LS baseline to the reference's published curve: NMSE_LS ~= -SNR+2.8
    # dB (-2.2 dB @ 5, -12.2 dB @ 15; `channel estimation performace
    # comparison.png`, BASELINE.md).
    label_noise_factor: float = 1.9
    # PRNG implementation for sample synthesis ("threefry" | "rbg"); see
    # DataConfig.rng_impl. Static (geometry is a jit static argument), so
    # the choice selects the compiled program, not a runtime branch.
    rng_impl: str = "threefry"
    # Phase-ramp evaluation for steering/delay responses: "direct" (one
    # sin/cos per ramp element — bit-compatible with every committed stream)
    # or "split" (angle-addition factorization, ~4x fewer transcendentals,
    # same values to f32 rounding; see complexops.cexp_i_ramp). Static.
    trig_impl: str = "direct"

    def __post_init__(self):
        # Same rejection contract as make_sample_key's rng_impl check (ADVICE
        # r5 low): an unknown trig_impl must not silently select "direct".
        if self.rng_impl not in ("threefry", "rbg"):
            raise ValueError(
                f"rng_impl must be 'threefry' or 'rbg', got {self.rng_impl!r}"
            )
        if self.trig_impl not in ("direct", "split"):
            raise ValueError(
                f"trig_impl must be 'direct' or 'split', got {self.trig_impl!r}"
            )

    @classmethod
    def from_config(cls, cfg: DataConfig) -> "ChannelGeometry":
        return cls(
            n_ant=cfg.n_ant,
            n_sub=cfg.n_sub,
            n_beam=cfg.n_beam,
            label_noise_factor=cfg.label_noise_factor,
            rng_impl=cfg.rng_impl,
            trig_impl=cfg.trig_impl,
        )

    @property
    def pilot_num(self) -> int:
        return self.n_beam * self.n_sub

    @property
    def h_dim(self) -> int:
        return self.n_ant * self.n_sub

    def _dft(self, rows: int, n: int) -> CArr:
        m = np.arange(rows)[:, None]
        a = np.arange(n)[None, :]
        ang = -2.0 * np.pi * m * a / n
        scale = 1.0 / np.sqrt(n)
        return CArr(
            jnp.asarray((np.cos(ang) * scale).astype(np.float32)),
            jnp.asarray((np.sin(ang) * scale).astype(np.float32)),
        )

    @property
    def beam_matrix(self) -> CArr:
        """First ``n_beam`` rows of the unitary ``n_ant``-point DFT: (n_beam, n_ant)."""
        return self._dft(self.n_beam, self.n_ant)

    @property
    def ant_dft(self) -> CArr:
        """Full unitary antenna DFT (n_ant, n_ant) — beam-domain transform."""
        return self._dft(self.n_ant, self.n_ant)

    @property
    def sub_dft(self) -> CArr:
        """Full unitary subcarrier DFT (n_sub, n_sub) — delay-domain transform."""
        return self._dft(self.n_sub, self.n_sub)

    @property
    def noise_ref_power(self) -> float:
        """Nominal per-pilot signal power used to set the noise floor.

        With unit average channel-entry power, the sounded-beam sector holds
        ~all the energy, so per-pilot power ~= h_dim / pilot_num.
        """
        return self.h_dim / self.pilot_num


def noise_var(geom: ChannelGeometry, snr_db: jnp.ndarray | float) -> jnp.ndarray:
    """Per-pilot-entry complex noise variance for a given SNR (dB)."""
    return geom.noise_ref_power * 10.0 ** (-jnp.asarray(snr_db, jnp.float32) / 10.0)


def label_noise_var(geom: ChannelGeometry, snr_db: jnp.ndarray | float) -> jnp.ndarray:
    """Per-entry complex noise variance of the full-pilot LS label ``Hlabel``.

    The reference's ``Hlabel``/``HLS`` is a 1024-entry LS estimate — it cannot
    be a function of the 128-entry ``Yp`` (SURVEY.md §2.8 shape contract), so
    it models an independent full-dimension pilot observation
    ``H + CN(0, sigma2_label)``. This is what makes training against it
    non-degenerate: its conditional mean given ``Yp`` is the true channel, so
    a learned estimator denoises instead of reproducing back-projection.
    """
    return geom.label_noise_factor * 10.0 ** (-jnp.asarray(snr_db, jnp.float32) / 10.0)


# ---------------------------------------------------------------------------
# Single-sample generation (vmapped for batches)
# ---------------------------------------------------------------------------


def _steering(f: jnp.ndarray, n_ant: int, trig_impl: str = "direct") -> CArr:
    """ULA steering vectors for spatial frequencies f: (L,) -> (L, n_ant)."""
    if trig_impl == "split":
        return cexp_i_ramp(2.0 * jnp.pi * f, n_ant)
    n = jnp.arange(n_ant, dtype=jnp.float32)
    return cexp_i(2.0 * jnp.pi * f[:, None] * n)


def _delay_response(tau: jnp.ndarray, n_sub: int, trig_impl: str = "direct") -> CArr:
    """Subcarrier responses for delays tau (samples): (L,) -> (L, n_sub)."""
    if trig_impl == "split":
        return cexp_i_ramp(-2.0 * jnp.pi * tau / n_sub, n_sub)
    k = jnp.arange(n_sub, dtype=jnp.float32)
    return cexp_i(-2.0 * jnp.pi * tau[:, None] * k / n_sub)


@partial(jax.jit, static_argnames=("geom",))
def sample_channel(
    key: jax.Array, scenario: jnp.ndarray, user: jnp.ndarray, geom: ChannelGeometry
) -> CArr:
    """Draw one channel realisation H (n_ant, n_sub) as a CArr.

    ``scenario``/``user`` are traced int32 scalars — all branching is via
    gather/mask so the function stays shape-static under jit and vmap.
    """
    k_f, k_tau, k_gain = jax.random.split(key, 3)
    s = scenario.astype(jnp.int32)
    u = user.astype(jnp.int32)

    n_paths = jnp.asarray(SCENARIO_N_PATHS)[s]
    spread = jnp.asarray(SCENARIO_ANGLE_SPREAD)[s]
    dly = jnp.asarray(SCENARIO_DELAY_SPREAD)[s]
    kfac = jnp.asarray(SCENARIO_K_FACTOR)[s]
    center = jnp.asarray(USER_CENTER_F)[u]

    mask = (jnp.arange(MAX_PATHS) < n_paths).astype(jnp.float32)

    # Path spatial frequencies around the user's sector centre.
    f = center + spread * jax.random.truncated_normal(k_f, -2.0, 2.0, (MAX_PATHS,))
    f = jnp.clip(f, 0.05 / geom.n_ant, None)

    # Path delays: LOS path at tau=0, NLOS exponential with scenario spread.
    tau_raw = dly * jax.random.exponential(k_tau, (MAX_PATHS,))
    tau = jnp.where(jnp.arange(MAX_PATHS) == 0, 0.0, jnp.clip(tau_raw, 0.0, geom.n_sub / 2.0))

    # Path powers: exponential decay in delay; LOS K-factor boost on path 0.
    p = jnp.exp(-tau / jnp.maximum(dly, 0.3))
    p = p * jnp.where(jnp.arange(MAX_PATHS) == 0, kfac, 1.0) * mask
    p = p / jnp.maximum(jnp.sum(p), 1e-12)  # E||H||^2 = n_ant * n_sub

    g = jax.random.normal(k_gain, (MAX_PATHS, 2))
    amp = jnp.sqrt(p / 2.0)
    alpha = CArr(amp * g[:, 0], amp * g[:, 1])  # (L,)

    a = _steering(f, geom.n_ant, geom.trig_impl)  # (L, n_ant)
    b = _delay_response(tau, geom.n_sub, geom.trig_impl)  # (L, n_sub)
    w = CArr(alpha.re[:, None], alpha.im[:, None]) * a  # (L, n_ant)
    # Materialize the steering/delay factors before the path contraction.
    # Without this barrier XLA (TPU) fuses the sin/cos chains INTO the
    # reduction loop — a "convolution fusion" that recomputes the trig for
    # every (antenna, subcarrier) output element, ~n_sub*n_ant-fold redundant
    # work that made this contraction 5x the cost of the whole rest of the
    # generator (measured on v5e: 3.0 -> 0.57 ms per 2304-sample batch).
    wre, wim, bre, bim = jax.lax.optimization_barrier((w.re, w.im, b.re, b.im))
    return ceinsum("la,lk->ak", CArr(wre, wim), CArr(bre, bim))  # (n_ant, n_sub)


@partial(jax.jit, static_argnames=("geom",))
def sound_pilots(
    key: jax.Array, h: CArr, snr_db: jnp.ndarray, geom: ChannelGeometry
) -> CArr:
    """Observe Yp = F_beam @ H + noise, flattened beam-major to (pilot_num,)."""
    x = ceinsum("ba,ak->bk", geom.beam_matrix, h)  # (n_beam, n_sub)
    sigma2 = noise_var(geom, snr_db)
    nre, nim = jax.random.normal(key, (2,) + x.shape)
    scale = jnp.sqrt(sigma2 / 2.0)
    return (x + CArr(scale * nre, scale * nim)).reshape(geom.pilot_num)


def make_sample_key(
    seed: int | jnp.ndarray, scenario, user, index, impl: str = "threefry"
) -> jax.Array:
    """Deterministic per-sample key: (seed, scenario, user, index) -> PRNGKey.

    Replaces the reference's pre-generated-file determinism (``Runner...py:49-55``
    filename scheme + ``start`` offsets in ``Test.py:127-129``): sample ``index``
    of cell (scenario, user) is always the same realisation.

    ``impl`` selects the jax PRNG implementation: "threefry" (default,
    bit-reproducible everywhere) or "rbg" (key derivation still threefry;
    bit *generation* uses XLA's RngBitGenerator — the fast path on TPU for
    in-dispatch synthesis, see DataConfig.rng_impl).
    """
    if impl == "threefry":
        # Raw (legacy) key, exactly as always — keeps every committed stream
        # bit-identical.
        key = jax.random.PRNGKey(seed)
    elif impl == "rbg":
        # Typed key: a raw uint32[4] rbg key would be re-interpreted as
        # threefry by downstream jax.random calls; the typed dtype carries
        # the impl through fold_in/split/vmap.
        key = jax.random.key(seed, impl="rbg")
    else:
        raise ValueError(f"rng_impl must be 'threefry' or 'rbg', got {impl!r}")
    key = jax.random.fold_in(key, scenario)
    key = jax.random.fold_in(key, user)
    return jax.random.fold_in(key, index)


@partial(jax.jit, static_argnames=("geom",))
def generate_samples(
    seed: jnp.ndarray,
    scenarios: jnp.ndarray,
    users: jnp.ndarray,
    indices: jnp.ndarray,
    snr_db: jnp.ndarray,
    geom: ChannelGeometry,
) -> dict:
    """Vectorised sample synthesis.

    Returns dict with ``yp (B, pilot_num) CArr``, ``h_perf (B, h_dim) CArr``,
    ``h_ls (B, h_dim) CArr`` — the full-pilot LS observation
    ``H + CN(0, label_noise_var)``, independent of ``yp``'s noise (the
    reference's ``Hlabel``/``HLS`` training label and LS eval baseline) — and
    ``indicator (B,) i32``.
    """

    def one(scenario, user, index):
        key = make_sample_key(seed, scenario, user, index, impl=geom.rng_impl)
        k_h, k_n, k_l = jax.random.split(key, 3)
        h = sample_channel(k_h, scenario, user, geom)
        yp = sound_pilots(k_n, h, snr_db, geom)
        hf = h.reshape(geom.h_dim)
        scale = jnp.sqrt(label_noise_var(geom, snr_db) / 2.0)
        lre, lim = jax.random.normal(k_l, (2,) + hf.shape)
        h_ls = hf + CArr(scale * lre, scale * lim)
        return yp, hf, h_ls

    yp, h, h_ls = jax.vmap(one)(scenarios, users, indices)
    return {
        "yp": yp,
        "h_perf": h,
        "h_ls": h_ls,
        "indicator": scenarios.astype(jnp.int32),
    }
