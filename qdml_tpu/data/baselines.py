"""Classical channel-estimation baselines: LS and LMMSE.

Replaces the reference's missing ``generate_data.generate_MMSE_estimate``
(called at ``Test.py:145`` with ``(HLS_numpy, sigma2)``). The LS baseline IS
the ``Hlabel``/``HLS`` full-pilot observation produced by the generator
(``Test.py:140``, :func:`qdml_tpu.data.channels.label_noise_var`);
:func:`mmse_estimate` is its LMMSE refinement, a pure jittable function over
:class:`~qdml_tpu.utils.complexops.CArr` real pairs using an empirical
beam-delay prior profile computed once from the generator (diagonal Wiener
filter in the beam-delay domain, where the geometric channel is approximately
uncorrelated). :func:`ls_estimate` (minimum-norm back-projection of the
compressed ``Yp`` pilots) is kept as a utility for the sounded-sector
analysis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from qdml_tpu.data.channels import ChannelGeometry, generate_samples, noise_var
from qdml_tpu.utils.complexops import CArr, ceinsum


@partial(jax.jit, static_argnames=("geom",))
def ls_estimate(yp: CArr, geom: ChannelGeometry) -> CArr:
    """LS (matched-filter / back-projection) estimate: (..., pilot_num) -> (..., h_dim).

    With a unitary-row beam codebook ``B``, the minimum-norm LS solution of
    ``Yp = B H`` is ``B^H Yp`` — observed beams are restored, unsounded beams
    are zero. This is the array the reference calls ``Hlabel``/``HLS``.
    """
    x = yp.reshape(yp.shape[:-1] + (geom.n_beam, geom.n_sub))
    h = ceinsum("ba,...bk->...ak", geom.beam_matrix.conj(), x)
    return h.reshape(yp.shape[:-1] + (geom.h_dim,))


def _to_beam_delay(h: CArr, geom: ChannelGeometry) -> CArr:
    """(..., n_ant, n_sub) antenna-frequency -> beam-delay domain."""
    g = ceinsum("ma,...ak->...mk", geom.ant_dft, h)
    return ceinsum("...mk,kd->...md", g, geom.sub_dft.conj().transpose())


def _from_beam_delay(g: CArr, geom: ChannelGeometry) -> CArr:
    h = ceinsum("am,...md->...ad", geom.ant_dft.conj().transpose(), g)
    return ceinsum("...ad,dk->...ak", h, geom.sub_dft)


def beam_delay_profile(
    geom: ChannelGeometry, seed: int = 7, n_samples: int = 768
) -> jnp.ndarray:
    """Empirical prior variance profile E|G[m, d]|^2 in the beam-delay domain,
    averaged over all scenarios/users: (n_ant, n_sub) float32.

    Plays the role of the channel covariance a real LMMSE would use; computed
    once per geometry from noiseless generator draws.
    """
    per_cell = max(n_samples // 9, 1)
    scen = jnp.repeat(jnp.arange(3), 3 * per_cell)
    user = jnp.tile(jnp.repeat(jnp.arange(3), per_cell), 3)
    idx = jnp.tile(jnp.arange(per_cell), 9)
    out = generate_samples(jnp.uint32(seed), scen, user, idx, jnp.float32(200.0), geom)
    h = out["h_perf"].reshape(-1, geom.n_ant, geom.n_sub)
    g = _to_beam_delay(h, geom)
    return jnp.mean(g.abs2(), axis=0)


@partial(jax.jit, static_argnames=("geom",))
def mmse_estimate(
    h_ls: CArr, sigma2: jnp.ndarray, profile: jnp.ndarray, geom: ChannelGeometry
) -> CArr:
    """LMMSE refinement of the full-pilot LS estimate (reference
    ``generate_MMSE_estimate``, ``Test.py:145``, called with ``(HLS, sigma2)``).

    Transforms the LS observation to the beam-delay domain and applies the
    diagonal Wiener gain ``P / (P + sigma2)``. ``sigma2`` is the label noise
    variance (:func:`qdml_tpu.data.channels.label_noise_var`) — white noise
    stays white with the same per-entry variance under the unitary transforms.
    """
    hh = h_ls.reshape(h_ls.shape[:-1] + (geom.n_ant, geom.n_sub))
    g = _to_beam_delay(hh, geom)
    g = g * (profile / (profile + sigma2))
    h = _from_beam_delay(g, geom)
    return h.reshape(h_ls.shape)


def sigma2_for_snr(geom: ChannelGeometry, snr_db) -> jnp.ndarray:
    """Noise variance matching the generator's pilot noise (for MMSE eval)."""
    return noise_var(geom, snr_db)


@partial(jax.jit, static_argnames=("geom", "rho"))
def mmse_generic_estimate(
    h_ls: CArr, sigma2: jnp.ndarray, geom: ChannelGeometry, rho: float = 0.85
) -> CArr:
    """Reference-faithful generic LMMSE (``generate_MMSE_estimate``,
    ``Test.py:145``): per-antenna frequency-domain Wiener filter under an
    ASSUMED exponential subcarrier correlation ``R[k,k'] = rho**|k-k'|`` —
    the site-agnostic covariance model a deployed LMMSE would use, with no
    knowledge of the generator's true beam-delay prior.

    ``rho = 0.85`` calibrates the curve to the reference's published MMSE
    (-13.5 dB @ 15 dB SNR; BASELINE.md). :func:`mmse_estimate` (empirical
    beam-delay oracle prior) is the strictly stronger genie variant reported
    alongside it.
    """
    k = jnp.arange(geom.n_sub)
    corr = rho ** jnp.abs(k[:, None] - k[None, :]).astype(jnp.float32)
    w = corr @ jnp.linalg.inv(corr + sigma2 * jnp.eye(geom.n_sub))
    hh = h_ls.reshape(h_ls.shape[:-1] + (geom.n_ant, geom.n_sub))
    out = CArr(
        jnp.einsum("...ak,jk->...aj", hh.re, w),
        jnp.einsum("...ak,jk->...aj", hh.im, w),
    )
    return out.reshape(h_ls.shape)
