"""Dataset plumbing: DML grid batching, eval datapair generation, npy cache.

Replaces the reference's missing ``generate_data`` module (SURVEY.md §2.8):

- ``DatasetFolder_DML`` (9-way zip dataset over the 3x3 scenario/user grid,
  ``Runner_P128_QuantumNAT_onchipQNN.py:87-93``) becomes :class:`DMLGridLoader`,
  which yields the whole grid as ONE stacked array batch
  ``(n_scenarios, n_users, bs, ...)`` instead of nine Python objects — the
  TPU-friendly shape for a single fused train step.
- ``generate_datapair(Ns, Pilot_num, index, SNRdb, start, training_data_len)``
  (``Test.py:127-129``) becomes :func:`generate_datapair` with the same
  offset-past-training-data semantics via deterministic per-index seeding.
- The ``.npy`` cache with the reference's filename scheme
  (``Runner...py:49-55``) is reproduced by :func:`save_npy_cache` /
  :func:`load_npy_cache` for interop.

Data synthesis runs jitted on-device; there is no host dataloader bottleneck
(the reference pins ``num_workers=0``, ``Runner...py:24``).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from qdml_tpu.config import DataConfig
from qdml_tpu.data.channels import ChannelGeometry, generate_samples
from qdml_tpu.utils.complexops import pack_h, yp_to_image


@partial(jax.jit, static_argnames=("geom",))
def make_network_batch(
    seed: jnp.ndarray,
    scenarios: jnp.ndarray,
    users: jnp.ndarray,
    indices: jnp.ndarray,
    snr_db: jnp.ndarray,
    geom: ChannelGeometry,
) -> dict[str, jnp.ndarray]:
    """Synthesize samples and produce network-ready arrays (leading dims of the
    scenario/user/index arrays are preserved — pass ``(S, U, B)`` grids or flat
    ``(N,)`` vectors).

    Fields: ``yp_img (..., n_sub, n_beam, 2) f32``, ``h_label (..., 2*h_dim) f32``
    (the packed full-pilot LS observation the reference trains against,
    ``Runner...py:112`` — an independent noisy view of H, see
    :func:`qdml_tpu.data.channels.label_noise_var`), ``h_perf (..., 2*h_dim)
    f32``, ``indicator (...) i32``, plus complex ``yp``/``h_ls``/``h_perf_c``
    for the classical baselines.
    """
    lead = scenarios.shape
    flat = generate_samples(
        seed, scenarios.reshape(-1), users.reshape(-1), indices.reshape(-1), snr_db, geom
    )
    yp = flat["yp"].reshape(lead + (geom.pilot_num,))
    h_perf = flat["h_perf"].reshape(lead + (geom.h_dim,))
    h_ls = flat["h_ls"].reshape(lead + (geom.h_dim,))
    return {
        "yp": yp,
        "h_ls": h_ls,
        "h_perf_c": h_perf,
        "yp_img": yp_to_image(yp, geom.n_sub, geom.n_beam).astype(jnp.float32),
        "h_label": pack_h(h_ls).astype(jnp.float32),
        "h_perf": pack_h(h_perf).astype(jnp.float32),
        "indicator": flat["indicator"].reshape(lead),
    }


def _resolve_split(cfg: DataConfig, split: str) -> tuple[int, int]:
    """(index_base, n) for a split — the reference's 90/10 train/val cut of
    each (scenario, user) cell (``Runner...py:67-71``)."""
    n_train = int(cfg.data_len * cfg.train_split)
    if split == "train":
        return 0, n_train
    if split == "val":
        return n_train, cfg.data_len - n_train
    raise ValueError(f"unknown split {split!r}")


def _epoch_perms(
    cfg: DataConfig, n: int, index_base: int, epoch: int, shuffle: bool
) -> np.ndarray:
    """(S, U, n) per-cell sample indices for one epoch, deterministic in
    ``(cfg.seed, epoch)`` — shared by both grid loaders so the on-device and
    npy-cache data paths shuffle identically."""
    s, u = cfg.n_scenarios, cfg.n_users
    if shuffle:
        rng = np.random.default_rng((cfg.seed, epoch))
        perms = rng.permuted(
            np.broadcast_to(np.arange(n), (s, u, n)).copy(), axis=-1
        )
    else:
        perms = np.broadcast_to(np.arange(n), (s, u, n))
    return perms + index_base


class DMLGridLoader:
    """Iterates (shuffled) minibatches of the full 3x3 scenario/user grid.

    Each step yields arrays with leading shape ``(n_scenarios, n_users, bs)``,
    the stacked equivalent of the reference's 9-tuple batches
    (``Runner...py:181``). Per-epoch shuffling is deterministic in
    ``(data_seed, epoch)``.
    """

    def __init__(
        self,
        cfg: DataConfig,
        batch_size: int,
        split: str = "train",
        geom: ChannelGeometry | None = None,
    ):
        self.cfg = cfg
        self.geom = geom or ChannelGeometry.from_config(cfg)
        self.index_base, self.n = _resolve_split(cfg, split)
        self.batch_size = batch_size = min(batch_size, self.n)
        self.steps_per_epoch = self.n // batch_size
        self._pslice: tuple[int, int] | None = None
        self._sslice: tuple[int, int] = (0, cfg.n_scenarios)
        s, u = cfg.n_scenarios, cfg.n_users
        self._scen = jnp.broadcast_to(jnp.arange(s)[:, None, None], (s, u, batch_size))
        self._user = jnp.broadcast_to(jnp.arange(u)[None, :, None], (s, u, batch_size))

    def set_process_slice(
        self,
        start: int,
        length: int,
        scen_start: int = 0,
        scen_count: int | None = None,
    ) -> None:
        """Multi-host data path: generate only ``[start, start+length)`` of
        each global batch window — and, under a federated cross-host layout,
        only scenario rows ``[scen_start, scen_start+scen_count)`` — every
        host synthesizes its own rectangle and the global array is assembled
        by :func:`qdml_tpu.parallel.multihost.local_grid_batch_to_global`,
        so no host ever materializes the full batch (or, federated, any
        other base station's scenario data)."""
        if not (0 <= start and start + length <= self.batch_size):
            raise ValueError(
                f"process slice [{start}, {start + length}) outside batch "
                f"window of {self.batch_size}"
            )
        s, u = self.cfg.n_scenarios, self.cfg.n_users
        scen_count = s if scen_count is None else scen_count
        if not (0 <= scen_start and scen_start + scen_count <= s):
            raise ValueError(
                f"scenario slice [{scen_start}, {scen_start + scen_count}) "
                f"outside the {s}-scenario grid"
            )
        self._pslice = (start, length)
        self._sslice = (scen_start, scen_count)
        scen = jnp.arange(scen_start, scen_start + scen_count)
        self._scen = jnp.broadcast_to(scen[:, None, None], (scen_count, u, length))
        self._user = jnp.broadcast_to(
            jnp.arange(u)[None, :, None], (scen_count, u, length)
        )

    @property
    def grid_coords(self) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Broadcast ``(scenario, user)`` coordinate grids matching the index
        windows this loader yields (accounts for any process slice)."""
        return self._scen, self._user

    def _step_snr(self, epoch: int, step: int) -> float:
        """Per-step training SNR: fixed ``cfg.snr_db`` (reference protocol,
        SNRdb=10) or, with ``cfg.snr_jitter=(lo, hi)``, drawn uniformly per
        batch — deterministic in ``(seed, epoch, step)``. Jitter trains one
        estimator that generalizes across the eval SNR grid, the robustness
        the reference's published curves exhibit."""
        lo_hi = self.cfg.snr_jitter
        if lo_hi is None:
            return float(self.cfg.snr_db)
        rng = np.random.default_rng((self.cfg.seed, 7, epoch, step))
        return float(rng.uniform(lo_hi[0], lo_hi[1]))

    def _step_window(self, perms: np.ndarray, step: int) -> np.ndarray:
        """This step's (S, U, bs) index window, process-sliced if configured.
        Single source for both iterators below: the scan path's bitwise
        equivalence to the per-step path rests on them slicing identically."""
        bs = self.batch_size
        window = perms[:, :, step * bs : (step + 1) * bs]
        if self._pslice is not None:
            p0, plen = self._pslice
            s0, scount = self._sslice
            window = window[s0 : s0 + scount, :, p0 : p0 + plen]
        return window

    def _snr_for(self, epoch: int, step: int, shuffle: bool) -> float:
        # jitter applies to shuffled (training) epochs only: validation
        # iterates with shuffle=False and stays at the fixed cfg.snr_db
        return self._step_snr(epoch, step) if shuffle else float(self.cfg.snr_db)

    def epoch(self, epoch: int, shuffle: bool = True) -> Iterator[dict[str, jnp.ndarray]]:
        perms = _epoch_perms(self.cfg, self.n, self.index_base, epoch, shuffle)
        for step in range(self.steps_per_epoch):
            idx = jnp.asarray(self._step_window(perms, step))
            yield make_network_batch(
                jnp.uint32(self.cfg.seed),
                self._scen,
                self._user,
                idx,
                jnp.float32(self._snr_for(epoch, step, shuffle)),
                self.geom,
            )

    def epoch_chunks(
        self, epoch: int, k: int, shuffle: bool = True
    ) -> Iterator[tuple[jnp.ndarray, jnp.ndarray]]:
        """Scan-fused view of :meth:`epoch`: ``(idx (k', S, U, B), snr (k',))``
        chunks covering the SAME per-step index windows and per-step SNRs the
        step-at-a-time iterator would produce, grouped ``k`` steps at a time
        (the final chunk may be shorter). Feed to
        :func:`qdml_tpu.train.hdce.make_hdce_scan_steps` — the device
        synthesizes each step's batch inside the scan, so the host dispatches
        once per chunk. At most two chunk lengths occur per epoch (``k`` and
        the tail), bounding jit recompilation."""
        perms = _epoch_perms(self.cfg, self.n, self.index_base, epoch, shuffle)
        for c0 in range(0, self.steps_per_epoch, k):
            steps = range(c0, min(c0 + k, self.steps_per_epoch))
            windows = np.stack([self._step_window(perms, step) for step in steps])
            snrs = [self._snr_for(epoch, step, shuffle) for step in steps]
            yield jnp.asarray(windows), jnp.asarray(snrs, jnp.float32)


def generate_datapair(
    ns: int,
    pilot_num: int,
    index: int,
    snr_db: float,
    start: int,
    cfg: DataConfig | None = None,
    geom: ChannelGeometry | None = None,
) -> dict[str, jnp.ndarray]:
    """Test-set synthesis matching the reference call
    ``generate_datapair(Ns, Pilot_num, index, SNRdb, start, training_data_len)``
    (``Test.py:127-129``): ``index=-1`` mixes all scenarios (round-robin over
    the 3x3 grid); ``start`` offsets sample indices past the training range so
    test realisations never overlap training ones.
    """
    cfg = cfg or DataConfig()
    geom = geom or ChannelGeometry.from_config(cfg)
    if pilot_num != geom.pilot_num:
        raise ValueError(f"pilot_num {pilot_num} != geometry pilot_num {geom.pilot_num}")
    i = jnp.arange(ns)
    if index == -1:
        scen = i % cfg.n_scenarios
        user = (i // cfg.n_scenarios) % cfg.n_users
    else:
        scen = jnp.full((ns,), index % cfg.n_scenarios)
        user = (i % cfg.n_users)
    return make_network_batch(
        jnp.uint32(cfg.seed), scen, user, start + i, jnp.float32(snr_db), geom
    )


# ---------------------------------------------------------------------------
# Reference-compatible .npy cache (``available_data/`` naming, Runner...py:49-55)
# ---------------------------------------------------------------------------


def _npy_names(dirpath: str, cfg: DataConfig, scenario: int, user: int) -> dict[str, str]:
    tpl = "{name}{ind}_{pn}_{hd}_{snr}dB_{uid}_datalen_{dl}.npy"
    return {
        name: os.path.join(
            dirpath,
            tpl.format(
                name=name,
                ind=scenario,
                pn=cfg.pilot_num,
                hd=cfg.h_dim,
                snr=int(cfg.snr_db),
                uid=user,
                dl=cfg.data_len,
            ),
        )
        for name in ("Yp", "Hlabel", "Hperf")
    }


def save_npy_cache(dirpath: str, cfg: DataConfig, chunk: int = 2048) -> None:
    """Materialise the dataset to ``.npy`` files with the reference's
    ``available_data/`` filename scheme (``Runner...py:49-55``)."""
    os.makedirs(dirpath, exist_ok=True)
    geom = ChannelGeometry.from_config(cfg)
    for s in range(cfg.n_scenarios):
        for u in range(cfg.n_users):
            parts: dict[str, list[np.ndarray]] = {"Yp": [], "Hlabel": [], "Hperf": []}
            for lo in range(0, cfg.data_len, chunk):
                n = min(chunk, cfg.data_len - lo)
                out = make_network_batch(
                    jnp.uint32(cfg.seed),
                    jnp.full((n,), s),
                    jnp.full((n,), u),
                    jnp.arange(lo, lo + n),
                    jnp.float32(cfg.snr_db),
                    geom,
                )
                parts["Yp"].append(out["yp"].to_numpy())
                parts["Hlabel"].append(out["h_ls"].to_numpy())
                parts["Hperf"].append(out["h_perf_c"].to_numpy())
            for name, path in _npy_names(dirpath, cfg, s, u).items():
                np.save(path, np.concatenate(parts[name], axis=0))


def load_npy_cache(dirpath: str, cfg: DataConfig, scenario: int, user: int) -> dict[str, np.ndarray]:
    """Load one (scenario, user) cell from a reference-style ``.npy`` cache."""
    return {n: np.load(p) for n, p in _npy_names(dirpath, cfg, scenario, user).items()}


class NpyGridLoader:
    """DML grid loader over a materialised ``.npy`` cache, via the native IO
    runtime: files are mmap'd zero-copy (:class:`~qdml_tpu.runtime.NativeNpyFile`),
    shuffled batches are assembled by the C++ multithreaded row gather, and a
    depth-2 pipeline overlaps the next batch's host assembly with the current
    device step — the file-based twin of :class:`DMLGridLoader` (which
    synthesizes on-device) and the replacement for the reference's
    ``DataLoader(num_workers=0)`` host path (``Runner...py:24, 87-93``).

    Yields the same stacked ``(S, U, bs, ...)`` batches as
    :class:`DMLGridLoader` (``yp_img``, ``h_label``, ``h_perf``, ``indicator``).
    """

    def __init__(
        self,
        dirpath: str,
        cfg: DataConfig,
        batch_size: int,
        split: str = "train",
        n_threads: int = 4,
        prefetch_depth: int = 2,
    ):
        from qdml_tpu.runtime import NativeNpyFile

        if cfg.snr_jitter is not None:
            raise ValueError(
                "snr_jitter is impossible on a materialised npy cache (files "
                "were generated at the fixed cfg.snr_db); use DMLGridLoader "
                "for the jittered protocol"
            )
        self.cfg = cfg
        self.geom = ChannelGeometry.from_config(cfg)
        self.n_threads = n_threads
        self.prefetch_depth = max(prefetch_depth, 1)
        self._files: dict[tuple[int, int, str], NativeNpyFile] = {}
        for s in range(cfg.n_scenarios):
            for u in range(cfg.n_users):
                for name, path in _npy_names(dirpath, cfg, s, u).items():
                    self._files[(s, u, name)] = NativeNpyFile(path)
        self.index_base, self.n = _resolve_split(cfg, split)
        self.batch_size = min(batch_size, self.n)
        self.steps_per_epoch = self.n // self.batch_size

    @property
    def is_native(self) -> bool:
        return all(f.is_native for f in self._files.values())

    def _assemble(self, idx_grid: np.ndarray) -> dict[str, jnp.ndarray]:
        """Gather one (S, U, bs) step's rows from all 27 mmaps (C++ threads)."""
        from qdml_tpu.runtime import gather_rows
        from qdml_tpu.utils.complexops import CArr

        cfg, geom = self.cfg, self.geom
        s_n, u_n, bs = idx_grid.shape
        grids: dict[str, np.ndarray] = {}
        for name, dim in (("Yp", geom.pilot_num), ("Hlabel", geom.h_dim), ("Hperf", geom.h_dim)):
            rows = np.empty((s_n, u_n, bs, dim), np.complex64)
            for s in range(s_n):
                for u in range(u_n):
                    rows[s, u] = gather_rows(
                        self._files[(s, u, name)].array, idx_grid[s, u], self.n_threads
                    )
            grids[name] = rows
        yp = CArr.from_numpy(grids["Yp"])
        h_ls = CArr.from_numpy(grids["Hlabel"])
        h_perf = CArr.from_numpy(grids["Hperf"])
        indicator = np.broadcast_to(
            np.arange(s_n, dtype=np.int32)[:, None, None], (s_n, u_n, bs)
        )
        return {
            "yp_img": yp_to_image(yp, geom.n_sub, geom.n_beam).astype(jnp.float32),
            "h_label": pack_h(h_ls).astype(jnp.float32),
            "h_perf": pack_h(h_perf).astype(jnp.float32),
            "indicator": jnp.asarray(indicator),
        }

    def epoch(self, epoch: int, shuffle: bool = True) -> Iterator[dict[str, jnp.ndarray]]:
        import queue
        import threading

        bs = self.batch_size
        perms = _epoch_perms(self.cfg, self.n, self.index_base, epoch, shuffle)

        # Depth-limited producer thread: the C++ gather releases the GIL, so
        # host assembly of step k+1 overlaps the device's step k. The producer
        # ALWAYS terminates with a sentinel — an assembly error is forwarded
        # to the consumer (no silent hang), and consumer abandonment (early
        # `break`) sets `stop` so the producer is never left blocked on put().
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_depth)
        stop = threading.Event()
        _DONE, _ERR = object(), object()

        def producer():
            try:
                for step in range(self.steps_per_epoch):
                    item = self._assemble(perms[:, :, step * bs : (step + 1) * bs])
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
                # Same stop-aware put loop as data items: an unconditional
                # blocking put could outlive the consumer's 5s join if the
                # queue is full when the epoch is abandoned.
                while not stop.is_set():
                    try:
                        q.put((_DONE, None), timeout=0.1)
                        break
                    except queue.Full:
                        continue
            except BaseException as e:  # lint: disable=broad-except(producer-thread failures (incl. KeyboardInterrupt) are forwarded through the queue and re-raised on the consumer)
                while not stop.is_set():
                    try:
                        q.put((_ERR, e), timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if isinstance(item, tuple) and len(item) == 2 and item[0] in (_DONE, _ERR):
                    if item[0] is _ERR:
                        raise item[1]
                    break
                yield item
        finally:
            stop.set()
            t.join(timeout=5.0)

    def close(self) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()
