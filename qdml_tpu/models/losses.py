"""Loss functions: NMSE (reference ``NMSELoss``) and NLL for the classifiers.

Reference semantics preserved exactly:
- NMSE is a whole-batch ratio ``sum((x_hat - x)^2) / sum(x^2)`` — NOT a
  per-sample mean (``Estimators_QuantumNAT_onchipQNN.py:282-295``).
- Classifier loss is ``F.nll_loss`` over ``log_softmax`` outputs
  (``Runner_P128_QuantumNAT_onchipQNN.py:292``), i.e. mean negative
  log-likelihood.
"""

from __future__ import annotations

import jax.numpy as jnp


def nmse_loss(x_hat: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Whole-batch NMSE over real (packed re/im) arrays."""
    return jnp.sum((x_hat - x) ** 2) / jnp.sum(x**2)


def nll_loss(log_probs: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean negative log-likelihood given log-probabilities (torch ``F.nll_loss``)."""
    picked = jnp.take_along_axis(log_probs, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(picked)


def accuracy(log_probs: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((jnp.argmax(log_probs, axis=-1) == labels).astype(jnp.float32))
