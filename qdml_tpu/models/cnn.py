"""Classical model zoo: Flax re-designs of the reference estimators.

Reference architectures (``Estimators_QuantumNAT_onchipQNN.py``):

- ``Conv_P128`` (:237-268): 3 x [Conv3x3(no bias) + BatchNorm + ReLU],
  channels 2->32->32->32, flatten to 32*16*8 = 4096.
- ``FC_P128`` (:272-279): Linear(4096 -> 64*16*2 = 2048) — the shared head.
- ``DCE_P128`` (:40-75): Conv_P128 trunk + the linear head in one module.
- ``SC_P128`` (:79-101): Conv3x3 2->32 + ReLU + maxpool2, Conv3x3 32->32 +
  ReLU + maxpool2, flatten 32*4*2 = 256, Linear(256, 3), log_softmax.

TPU-first deviations from the torch originals: NHWC layout (inputs are
``(batch, n_sub=16, n_beam=8, 2)``), optional bfloat16 activation dtype for the
MXU (params stay float32), and a scenario-stacked trunk
(:class:`StackedConvP128`) that evaluates all three per-scenario trunks as one
batched conv — replacing the reference's three separate ``Conv_P128`` instances
(``Runner_P128_QuantumNAT_onchipQNN.py:139-141``) with a single vmapped module
so the 3x3 DML grid trains in one fused step.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


def activation_dtype(name: str):
    """ModelConfig.dtype string -> jnp dtype for module activations (params
    always stay float32; bfloat16 activations feed the MXU fast path)."""
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[name]


class ConvBlock(nn.Module):
    """Conv3x3(no bias) + BatchNorm + ReLU (reference trunk block).

    ``bn_momentum``: running-stat decay per update. The reference's torch
    ``BatchNorm2d`` uses momentum=0.1, i.e. per-update decay 0.9
    (``Estimators...py:52``) — that is this module's default. The fused HDCE
    step sees ONE BN update per train step where the reference's per-cell
    loop applies ``n_users`` sequential updates (``Runner...py:181-199``);
    passing ``0.9 ** n_users`` matches the reference's per-step warm-up
    timescale (measured in ``tests/test_bn_semantics.py``).
    """

    features: int = 32
    dtype: Any = jnp.float32
    bn_momentum: float = 0.9

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=self.bn_momentum, dtype=jnp.float32
        )(x)
        return nn.relu(x)


class ConvP128(nn.Module):
    """Per-scenario feature extractor (reference ``Conv_P128``, :237-268).

    ``(B, 16, 8, 2) -> (B, 4096)``.
    """

    features: int = 32
    n_layers: int = 3
    dtype: Any = jnp.float32
    bn_momentum: float = 0.9

    @nn.compact
    def __call__(self, x, train: bool = False):
        for _ in range(self.n_layers):
            x = ConvBlock(self.features, self.dtype, self.bn_momentum)(x, train=train)
        return x.reshape(x.shape[0], -1).astype(jnp.float32)


class FCP128(nn.Module):
    """Shared estimation head (reference ``FC_P128``, :272-279): 4096 -> 2048."""

    out_dim: int = 2048
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.out_dim, dtype=self.dtype)(x).astype(jnp.float32)


class DCEP128(nn.Module):
    """Monolithic direct channel estimator (reference ``DCE_P128``, :40-75)."""

    features: int = 32
    out_dim: int = 2048
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = ConvP128(self.features, dtype=self.dtype)(x, train=train)
        return FCP128(self.out_dim, dtype=self.dtype)(x)


class SCP128(nn.Module):
    """Classical scenario classifier (reference ``SC_P128``, :79-101).

    ``(B, 16, 8, 2) -> (B, 3)`` log-probabilities.
    """

    n_classes: int = 3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):  # train unused: no BatchNorm
        x = nn.Conv(32, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(32, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1).astype(jnp.float32)  # (B, 32*4*2)
        x = nn.Dense(self.n_classes)(x)
        return nn.log_softmax(x, axis=-1)


class StackedConvP128(nn.Module):
    """All ``n_scenarios`` Conv_P128 trunks as one vmapped module.

    Parameters carry a leading scenario axis; input ``(S, B, 16, 8, 2)`` maps to
    ``(S, B, 4096)``. Replaces the reference's list of three independent
    modules + three optimizers (``Runner...py:139-141, 160-163``) — gradients
    for scenario ``s`` flow only to slice ``s`` of the stacked params, which is
    mathematically identical (elementwise Adam over disjoint slices) but runs
    as one XLA computation and shards naturally over a mesh axis.
    """

    n_scenarios: int = 3
    features: int = 32
    dtype: Any = jnp.float32
    bn_momentum: float = 0.9

    @nn.compact
    def __call__(self, x, train: bool = False):
        vconv = nn.vmap(
            ConvP128,
            in_axes=(0, None),  # x stacked over scenarios; train broadcast
            out_axes=0,
            variable_axes={"params": 0, "batch_stats": 0},
            split_rngs={"params": True},
            methods=["__call__"],
        )
        # NOTE: train must be positional — flax nn.vmap drops kwargs.
        return vconv(self.features, dtype=self.dtype, bn_momentum=self.bn_momentum)(x, train)


class QSCPreprocess(nn.Module):
    """CNN front-end of the quantum classifier (reference ``QSC_P128.preprocess``,
    ``Estimators...py:152-162``): Conv 2->16 + ReLU + maxpool2, Conv 16->32 +
    ReLU + maxpool2, flatten 256, Dense -> n_qubits, tanh (angle range [-1, 1])."""

    n_qubits: int = 6
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(16, (3, 3), padding=1, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(32, (3, 3), padding=1, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1).astype(jnp.float32)
        x = nn.Dense(self.n_qubits)(x)
        return nn.tanh(x)
