"""Classical model zoo: Flax re-designs of the reference estimators.

Reference architectures (``Estimators_QuantumNAT_onchipQNN.py``):

- ``Conv_P128`` (:237-268): 3 x [Conv3x3(no bias) + BatchNorm + ReLU],
  channels 2->32->32->32, flatten to 32*16*8 = 4096.
- ``FC_P128`` (:272-279): Linear(4096 -> 64*16*2 = 2048) — the shared head.
- ``DCE_P128`` (:40-75): Conv_P128 trunk + the linear head in one module.
- ``SC_P128`` (:79-101): Conv3x3 2->32 + ReLU + maxpool2, Conv3x3 32->32 +
  ReLU + maxpool2, flatten 32*4*2 = 256, Linear(256, 3), log_softmax.

TPU-first deviations from the torch originals: NHWC layout (inputs are
``(batch, n_sub=16, n_beam=8, 2)``), optional bfloat16 activation dtype for the
MXU (params stay float32), and a scenario-stacked trunk
(:class:`StackedConvP128`) that evaluates all three per-scenario trunks as one
batched conv — replacing the reference's three separate ``Conv_P128`` instances
(``Runner_P128_QuantumNAT_onchipQNN.py:139-141``) with a single vmapped module
so the 3x3 DML grid trains in one fused step.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


def activation_dtype(name: str):
    """ModelConfig.dtype string -> jnp dtype for module activations (params
    always stay float32; bfloat16 activations feed the MXU fast path)."""
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[name]


def resolve_conv_impl(impl: str) -> str:
    """Resolve ``auto`` to a concrete conv lowering for this backend.

    ``conv``: ``lax.conv_general_dilated`` — the right call on TPU, where
    XLA's conv emitter tiles onto the MXU. ``shift_matmul``: the same SAME
    convolution as kh*kw shifted-input matmuls. The choice exists because
    XLA:CPU's gradient kernels for BATCHED convs — what the vmapped
    per-scenario trunks (:class:`StackedConvP128`) lower to — are
    pathologically slow: 23x a plain conv's fwd+bwd at identical total work
    (58.5 ms -> 1357.4 ms when the same conv is vmapped over 3 kernels;
    the 3-layer trunk: 2.78 s conv vs 0.57 s shift_matmul per
    quarter-batch; ``results/perf_r4/cpu_fallback_profile.json``). Batched
    matmuls have no such cliff, so ``auto`` picks ``shift_matmul`` off-TPU.
    """
    if impl not in ("auto", "conv", "shift_matmul"):
        raise ValueError(
            f"conv_impl must be auto|conv|shift_matmul, got {impl!r}"
        )
    if impl != "auto":
        return impl
    return "conv" if jax.default_backend() == "tpu" else "shift_matmul"


class SpatialConv(nn.Module):
    """'SAME' no-bias convolution with a selectable lowering.

    Param-compatible with the ``nn.Conv`` it replaces inside
    :class:`ConvBlock` — same param name ("kernel"), shape
    ``(kh, kw, cin, cout)``, and lecun-normal init, so checkpoints trained
    under either lowering (or by earlier rounds' ``nn.Conv`` modules, via
    ``name="Conv_0"``) load interchangeably; the two impls agree to float
    tolerance (``tests/test_models.py::test_conv_impls_agree``).
    """

    features: int
    kernel_size: tuple = (3, 3)
    dtype: Any = jnp.float32
    impl: str = "auto"

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        if kh % 2 == 0 or kw % 2 == 0:
            # the shift lowering pads k//2 both sides, which only equals
            # 'SAME' for odd kernels — an even size would make the two
            # impls (and so the two platforms under "auto") disagree
            raise ValueError(f"SpatialConv requires odd kernel sizes, got {(kh, kw)}")
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (kh, kw, x.shape[-1], self.features),
        )
        xd = x.astype(self.dtype)
        kd = kernel.astype(self.dtype)
        if resolve_conv_impl(self.impl) == "conv":
            return jax.lax.conv_general_dilated(
                xd, kd, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
            )
        xp = jnp.pad(xd, ((0, 0), (kh // 2, kh // 2), (kw // 2, kw // 2), (0, 0)))
        out = None
        for dy in range(kh):
            for dx in range(kw):
                xs = jax.lax.dynamic_slice(xp, (0, dy, dx, 0), xd.shape)
                # accumulate the kh*kw window in f32 like lax.conv does, so
                # the two lowerings agree in bfloat16 too (ADVICE r4): the
                # cast back to the activation dtype happens once, at the end
                y = jnp.einsum(
                    "bhwc,cd->bhwd", xs, kd[dy, dx],
                    preferred_element_type=jnp.float32,
                )
                out = y if out is None else out + y
        return out.astype(self.dtype)


class ConvBlock(nn.Module):
    """Conv3x3(no bias) + BatchNorm + ReLU (reference trunk block).

    ``bn_momentum``: running-stat decay per update. The reference's torch
    ``BatchNorm2d`` uses momentum=0.1, i.e. per-update decay 0.9
    (``Estimators...py:52``) — that is this module's default. The fused HDCE
    step sees ONE BN update per train step where the reference's per-cell
    loop applies ``n_users`` sequential updates (``Runner...py:181-199``);
    passing ``0.9 ** n_users`` matches the reference's per-step warm-up
    timescale (measured in ``tests/test_bn_semantics.py``).
    """

    features: int = 32
    dtype: Any = jnp.float32
    bn_momentum: float = 0.9
    conv_impl: str = "auto"

    @nn.compact
    def __call__(self, x, train: bool = False):
        # name="Conv_0": keep the nn.Conv-era param path (same tree, same
        # init RNG derivation) so existing checkpoints load unchanged
        x = SpatialConv(
            self.features, (3, 3), dtype=self.dtype, impl=self.conv_impl, name="Conv_0"
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=self.bn_momentum, dtype=jnp.float32
        )(x)
        return nn.relu(x)


class ConvP128(nn.Module):
    """Per-scenario feature extractor (reference ``Conv_P128``, :237-268).

    ``(B, 16, 8, 2) -> (B, 4096)``.
    """

    features: int = 32
    n_layers: int = 3
    dtype: Any = jnp.float32
    bn_momentum: float = 0.9
    conv_impl: str = "auto"

    @nn.compact
    def __call__(self, x, train: bool = False):
        for _ in range(self.n_layers):
            x = ConvBlock(self.features, self.dtype, self.bn_momentum, self.conv_impl)(
                x, train=train
            )
        return x.reshape(x.shape[0], -1).astype(jnp.float32)


class FCP128(nn.Module):
    """Shared estimation head (reference ``FC_P128``, :272-279): 4096 -> 2048."""

    out_dim: int = 2048
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.out_dim, dtype=self.dtype)(x).astype(jnp.float32)


class DCEP128(nn.Module):
    """Monolithic direct channel estimator (reference ``DCE_P128``, :40-75)."""

    features: int = 32
    out_dim: int = 2048
    dtype: Any = jnp.float32
    conv_impl: str = "auto"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = ConvP128(self.features, dtype=self.dtype, conv_impl=self.conv_impl)(
            x, train=train
        )
        return FCP128(self.out_dim, dtype=self.dtype)(x)


class SCP128(nn.Module):
    """Classical scenario classifier (reference ``SC_P128``, :79-101).

    ``(B, 16, 8, 2) -> (B, 3)`` log-probabilities.
    """

    n_classes: int = 3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):  # train unused: no BatchNorm
        x = nn.Conv(32, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(32, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1).astype(jnp.float32)  # (B, 32*4*2)
        x = nn.Dense(self.n_classes)(x)
        return nn.log_softmax(x, axis=-1)


class StackedConvP128(nn.Module):
    """All ``n_scenarios`` Conv_P128 trunks as one vmapped module.

    Parameters carry a leading scenario axis; input ``(S, B, 16, 8, 2)`` maps to
    ``(S, B, 4096)``. Replaces the reference's list of three independent
    modules + three optimizers (``Runner...py:139-141, 160-163``) — gradients
    for scenario ``s`` flow only to slice ``s`` of the stacked params, which is
    mathematically identical (elementwise Adam over disjoint slices) but runs
    as one XLA computation and shards naturally over a mesh axis.
    """

    n_scenarios: int = 3
    features: int = 32
    dtype: Any = jnp.float32
    bn_momentum: float = 0.9
    conv_impl: str = "auto"

    @nn.compact
    def __call__(self, x, train: bool = False):
        vconv = nn.vmap(
            ConvP128,
            in_axes=(0, None),  # x stacked over scenarios; train broadcast
            out_axes=0,
            variable_axes={"params": 0, "batch_stats": 0},
            split_rngs={"params": True},
            methods=["__call__"],
        )
        # NOTE: train must be positional — flax nn.vmap drops kwargs.
        return vconv(
            self.features,
            dtype=self.dtype,
            bn_momentum=self.bn_momentum,
            conv_impl=self.conv_impl,
        )(x, train)


class QSCPreprocess(nn.Module):
    """CNN front-end of the quantum classifier (reference ``QSC_P128.preprocess``,
    ``Estimators...py:152-162``): Conv 2->16 + ReLU + maxpool2, Conv 16->32 +
    ReLU + maxpool2, flatten 256, Dense -> n_qubits, tanh (angle range [-1, 1])."""

    n_qubits: int = 6
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(16, (3, 3), padding=1, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(32, (3, 3), padding=1, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1).astype(jnp.float32)
        x = nn.Dense(self.n_qubits)(x)
        return nn.tanh(x)
