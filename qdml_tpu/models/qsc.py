"""Quantum scenario classifier: CNN front-end + variational circuit + head.

TPU-native re-design of ``QSC_P128`` (reference
``Estimators_QuantumNAT_onchipQNN.py:107-228``). The PennyLane
``QNode``/``TorchLayer`` bridge (reference ``:148-149``) disappears: circuit
weights are a plain Flax param and the circuit is just a differentiable
function in the forward pass, executed by the in-tree statevector simulator on
the same device as the CNN — no host round-trip per forward.

QuantumNAT noise injection (reference ``:176-196``) becomes pure-functional:
instead of mutating ``param.data`` in place and restoring it, the forward
evaluates the circuit at ``weights + noise`` with noise drawn from a threaded
PRNG stream. The gradient is therefore taken at the *noisy* point while the
optimizer state tracks the *clean* params — exactly the reference semantics
(SURVEY.md §3.4) with no mutate/restore dance.

Gradient pruning (reference ``apply_gradient_pruning``, ``:205-228``) is NOT a
model method here; it is an optax transform in the optimizer chain
(:func:`qdml_tpu.ops.grad_prune.gradient_prune`).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from qdml_tpu.models.cnn import QSCPreprocess
from qdml_tpu.quantum.circuits import run_circuit
from qdml_tpu.quantum.trajectories import run_circuit_trajectories


class QSCP128(nn.Module):
    """``(B, 16, 8, 2) -> (B, n_classes)`` log-probabilities."""

    n_qubits: int = 6
    n_layers: int = 3
    n_classes: int = 3
    use_quantumnat: bool = False   # reference ships with this OFF (Runner...py:313-316)
    noise_level: float = 0.01      # QuantumNAT sigma (Estimators...py:118)
    backend: str = "auto"  # legacy forced path (circuits.resolve_impl precedence)
    # autotuned dispatcher override (quantum.impl): "auto" consults the
    # measured selection table per shape/platform, falling back to dense;
    # an explicit impl wins over the table AND the legacy backend knob
    impl: str = "auto"
    # Bond dimension when the "mps" impl runs (quantum.mps_chi): exact at
    # chi >= 2^(n/2), a controlled approximation below (docs/QUANTUM.md)
    mps_chi: int = 8
    # Per-sample RMS normalization of the pilot image before the CNN. OFF by
    # default (reference parity: QSC_P128 consumes raw pilots). The raw-pilot
    # angle encoding is scale-sensitive — a classifier trained at SNR 10
    # collapses at SNR 5 (0.45 vs the classical CNN's 0.88 accuracy in
    # results/quantum_classical_comparison.json) because the input power
    # shift pushes the tanh angles off their trained range; normalizing makes
    # the encoding scale-invariant.
    input_norm: bool = False
    # State-level hardware-noise evaluation (beyond reference): with
    # depolarizing_p > 0 the clean circuit is replaced by Pauli-twirl
    # trajectory averaging (:mod:`qdml_tpu.quantum.trajectories`) — every
    # wire suffers a random Pauli with this probability after the embedding
    # and after each layer. Requires an rng stream at apply time:
    # ``model.apply(vars, x, rngs={"trajectories": key})``.
    depolarizing_p: float = 0.0
    n_trajectories: int = 32
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.input_norm:
            rms = jnp.sqrt(jnp.mean(x**2, axis=(1, 2, 3), keepdims=True) + 1e-12)
            x = x / rms
        angles = QSCPreprocess(self.n_qubits, dtype=self.dtype)(x)

        # PennyLane TorchLayer initialises circuit weights uniform in [0, 2pi).
        weights = self.param(
            "qweights",
            lambda key, shape: jax.random.uniform(key, shape, jnp.float32, 0.0, 2.0 * np.pi),
            (self.n_layers, self.n_qubits, 2),
        )
        if train and self.use_quantumnat and self.noise_level > 0:
            noise = self.noise_level * jax.random.normal(
                self.make_rng("quantumnat"), weights.shape, jnp.float32
            )
            weights = weights + noise  # gradient at the noisy point (C7 semantics)

        if self.depolarizing_p > 0.0:
            # honor resolve_impl precedence: an explicit impl wins outright,
            # so impl='tensor' is fine whatever the legacy backend says; with
            # impl auto/unset the legacy backend must be tensor-compatible
            forced_ok = self.impl == "tensor" or (
                self.impl in ("", "auto") and self.backend in ("auto", "tensor")
            )
            if not forced_ok:
                # the trajectory simulator only has the gate-wise tensor
                # formulation; silently ignoring an explicit dense/pallas/
                # sharded choice would e.g. drop a sharded high-qubit model
                # to a full per-device statevector without warning
                raise ValueError(
                    f"depolarizing_p={self.depolarizing_p} uses the trajectory "
                    f"simulator (tensor formulation only); backend="
                    f"{self.backend!r}/impl={self.impl!r} cannot be honored — "
                    "configure 'tensor' (or leave 'auto') for noisy evaluation"
                )
            expz = run_circuit_trajectories(
                angles,
                weights,
                self.n_qubits,
                self.n_layers,
                self.depolarizing_p,
                self.make_rng("trajectories"),
                self.n_trajectories,
            )
        else:
            # mode picks the autotune winner: the train step cares about
            # forward+backward, eval/serving about the forward alone
            expz = run_circuit(
                angles,
                weights,
                self.n_qubits,
                self.n_layers,
                self.backend,
                impl=self.impl,
                mode="train" if train else "infer",
                mps_chi=self.mps_chi,
            )
        logits = nn.Dense(self.n_classes)(expz)
        return nn.log_softmax(logits, axis=-1)
