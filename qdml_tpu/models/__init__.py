from qdml_tpu.models.cnn import (  # noqa: F401
    ConvBlock,
    ConvP128,
    DCEP128,
    FCP128,
    QSCPreprocess,
    SCP128,
    StackedConvP128,
)
from qdml_tpu.models.losses import accuracy, nll_loss, nmse_loss  # noqa: F401
from qdml_tpu.models.qsc import QSCP128  # noqa: F401
