"""qdml_tpu — TPU-native quantum-distributed ML for RIS channel estimation.

A brand-new JAX/XLA/Pallas/pjit framework with the capabilities of the reference
repo `Fazilaton-Nisha/Quantum-Distributed-Machine-Learning-RIS-Channel-Estimation`
(hierarchical deep channel estimation for RIS-assisted 6G with a hybrid
quantum-classical scenario classifier), re-designed TPU-first:

- the quantum layer is an in-tree, jit'd, differentiable state-vector simulator
  (``qdml_tpu.quantum``) instead of PennyLane's CPU ``default.qubit``
  (reference: ``Estimators_QuantumNAT_onchipQNN.py:122-149``),
- the CNN/MLP estimators are Flax modules (``qdml_tpu.models``) trained with
  optax (reference: torch.nn modules, ``Estimators_QuantumNAT_onchipQNN.py:40-295``),
- QuantumNAT noise injection and on-chip-QNN gradient pruning are
  pure-functional transforms (``qdml_tpu.ops``; reference:
  ``Estimators_QuantumNAT_onchipQNN.py:176-228``),
- distributed "DML" training (3 scenarios x 3 users with a shared head, plus
  data parallelism) runs as SPMD over a ``jax.sharding.Mesh``
  (``qdml_tpu.parallel``; reference: ``torch.nn.DataParallel``,
  ``Runner_P128_QuantumNAT_onchipQNN.py:144-148``),
- the missing-from-reference data module (``generate_data``) is implemented as
  a synthetic DeepMIMO-style geometric channel generator with LS/LMMSE
  classical baselines (``qdml_tpu.data``).
"""

__version__ = "0.1.0"

from qdml_tpu import config  # noqa: F401
