from qdml_tpu.utils.complexops import (  # noqa: F401
    CArr,
    ceinsum,
    cexp_i,
    cexp_i_ramp,
    cmatmul,
    complex_to_real_pair,
    cconcat,
    cstack,
    cwhere,
    pack_h,
    unpack_h,
    yp_to_image,
)
from qdml_tpu.utils.metrics import MetricsLogger, nmse, nmse_complex, nmse_db  # noqa: F401
