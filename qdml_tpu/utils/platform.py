"""Backend-platform pinning helpers.

The TPU accelerator plugin's registration hook rewrites jax's
``jax_platforms`` config to "axon,cpu" at interpreter start, so setting the
``JAX_PLATFORMS`` env var alone does not pin a backend — the config value
must be re-applied after ``import jax`` and before the first backend init.
This is the single home for that workaround (used by the CLI, the test
conftest, and the driver entry's multi-chip dryrun).
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    """Re-apply ``JAX_PLATFORMS`` from the environment to jax's config so an
    explicit env choice (e.g. ``JAX_PLATFORMS=cpu``) actually selects that
    backend. No-op when the env var is unset."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)


def backend_initialized() -> bool:
    """True once jax has committed to a backend (after which neither the
    platform nor the virtual device count can be changed)."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:  # lint: disable=broad-except(private-API probe — moved API reads as not-initialized; callers fail loudly later)
        # Private API moved: report "not initialized" so callers still
        # attempt the pin. The site hook pre-imports jax in every process, so
        # any sys.modules-based fallback would be always-True and turn
        # force_cpu into a silent permanent no-op; a pin attempted too late
        # instead fails loudly at the caller's device-count check.
        return False


def pallas_interpret() -> bool:
    """One knob for Pallas interpret-mode selection across every kernel.

    Each kernel module used to sniff the backend for itself; this is the
    single config-driven home for that decision so eager/jit/interpret
    selection cannot drift between kernels. ``QDML_PALLAS_INTERPRET``:

    - ``auto`` (default/unset): interpret off-TPU (the CPU test suite runs
      the kernels through the Pallas interpreter), compiled Mosaic on TPU;
    - ``1``/``true``/``on``: force interpret everywhere (kernel debugging on
      a real TPU without losing the device);
    - ``0``/``false``/``off``: never interpret (fail loudly off-TPU instead
      of silently benchmarking the interpreter).
    """
    mode = os.environ.get("QDML_PALLAS_INTERPRET", "auto").strip().lower()
    if mode in ("1", "true", "on", "yes"):
        return True
    if mode in ("0", "false", "off", "no"):
        return False
    import jax

    return jax.default_backend() != "tpu"


def donation_argnums(*argnums: int) -> tuple[int, ...]:
    """``donate_argnums`` for a train step, or ``()`` where donation is a
    no-op. Donating the train state lets XLA update params/optimizer
    buffers in place (halves the step's HBM traffic on those trees); the
    CPU backend only warns about unimplemented donation, so tests stay
    quiet by not requesting it. Accelerator detection is by exclusion — the
    tunnelled TPU registers under the plugin's own platform name, not
    "tpu"."""
    import jax

    return () if jax.default_backend() == "cpu" else argnums


_COMPAT_DONE = False


def ensure_jax_compat() -> None:
    """Backfill jax transformation rules this container's jax version lacks.

    jax 0.4.37 ships ``lax.optimization_barrier`` without batching/JVP/
    transpose rules (added upstream later), so any ``vmap``/``grad`` over
    code using the barrier — the channel generator's anti-fusion barrier,
    ``data/channels.py`` — raises NotImplementedError. The rules below are
    the upstream ones (barrier each operand; identity-shaped through vmap,
    barrier primals and tangents through jvp, barrier cotangents through
    transpose); registration is a no-op on jax versions that already have
    them. Idempotent and exception-safe: a moved private API degrades to
    leaving jax exactly as it was.
    """
    global _COMPAT_DONE
    if _COMPAT_DONE:
        return
    _COMPAT_DONE = True
    try:
        from jax._src.interpreters import ad, batching
        from jax._src.lax.lax import optimization_barrier_p as p
    except Exception:  # lint: disable=broad-except(compat shim for absent private APIs — nothing to patch means nothing to do)
        return
    try:
        if p not in batching.primitive_batchers:

            def _batch_rule(args, dims):
                return p.bind(*args), dims

            batching.primitive_batchers[p] = _batch_rule
        if p not in ad.primitive_jvps:

            def _jvp_rule(primals, tangents):
                tangents = [ad.instantiate_zeros(t) for t in tangents]
                return p.bind(*primals), p.bind(*tangents)

            ad.primitive_jvps[p] = _jvp_rule
        if p not in ad.primitive_transposes:

            def _transpose_rule(cts, *primals):
                return p.bind(*[ad.instantiate_zeros(ct) for ct in cts])

            ad.primitive_transposes[p] = _transpose_rule
    except Exception:  # lint: disable=broad-except(best-effort compat registration; newer jax works unpatched)
        pass


def force_cpu(n_virtual_devices: int | None = None) -> bool:
    """Pin the CPU platform (optionally with N virtual devices) if the
    backend choice is still open. Returns True when the pin was applied.

    Must be called before any jax computation; safe to call when jax is
    already imported, since the plugin pre-imports jax at interpreter start
    without initializing a backend.
    """
    if backend_initialized():
        return False
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_virtual_devices is not None:
        # Replace (not merely append to) any ambient device-count flag: a
        # stale count would surface later as an opaque mesh reshape error.
        flags = [
            f
            for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_virtual_devices}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
    honor_platform_env()
    return True
