"""Tracing / profiling hooks (reference has none — SURVEY.md §5.1).

The reference's only instrumentation is coarse wall-clock prints
(``Runner_P128_QuantumNAT_onchipQNN.py:171-173, 437-440``). Here — both now
thin facades over :mod:`qdml_tpu.telemetry`:

- :func:`trace` — context manager around ``jax.profiler`` producing a
  TensorBoard-loadable trace of device execution (XLA ops, fusion, HBM);
  telemetry spans opened inside it annotate the trace timeline,
- :class:`StepTimer` — steady-state step timing with correct semantics for
  tunnelled backends (forces a host transfer; ``block_until_ready`` alone
  does not flush execution through the axon tunnel), reporting
  samples/sec/chip — the BASELINE.json north-star metric — plus per-tick
  interval percentiles (:meth:`StepTimer.histogram`).
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator

import jax

from qdml_tpu.telemetry.counters import Histogram
from qdml_tpu.telemetry.spans import profiler_trace


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """``with trace('/tmp/trace'):`` — profile the enclosed device work."""
    with profiler_trace(logdir):
        yield


def force(x) -> float:
    """Force execution and return a host scalar from an array pytree leaf."""
    leaf = jax.tree.leaves(x)[0]
    return float(leaf.reshape(-1)[0])


class StepTimer:
    """Throughput measurement: ``warmup`` untimed steps (compile + ramp),
    then timed steps with a final host sync.

    >>> timer = StepTimer(warmup=3)
    >>> for _ in range(50):
    ...     out = step(...)
    ...     timer.tick(out)
    >>> timer.samples_per_sec(batch_size)

    ``histogram()`` summarizes the timed tick-to-tick intervals as
    p50/p95/max. With async dispatch these are dispatch intervals (enqueue
    gaps backpressured by the device), not synced per-step device times —
    the mean-rate denominator stays the single final sync, unchanged.
    """

    def __init__(self, warmup: int = 3):
        self.warmup = warmup
        self._seen = 0
        self._t0: float | None = time.perf_counter() if warmup == 0 else None
        self._steps = 0
        self._last = None
        self._frozen: float | None = None
        self._t_prev: float | None = self._t0
        self._hist = Histogram()

    def tick(self, out=None) -> None:
        self._seen += 1
        self._last = out
        self._frozen = None
        if self._seen == self.warmup:
            if out is not None:
                force(out)  # drain the pipeline before starting the clock
            self._t0 = time.perf_counter()
            self._t_prev = self._t0
        elif self._seen > self.warmup:
            self._steps += 1
            now = time.perf_counter()
            if self._t_prev is not None:
                self._hist.add(now - self._t_prev)
            self._t_prev = now

    def elapsed(self) -> float:
        """Seconds over the timed steps; frozen at the first call after the
        last tick (so repeated reads agree)."""
        if self._t0 is None:
            return 0.0
        if self._frozen is None:
            if self._last is not None:
                force(self._last)  # final sync
                self._last = None
            self._frozen = time.perf_counter() - self._t0
        return self._frozen

    def steps_per_sec(self) -> float:
        dt = self.elapsed()
        return self._steps / dt if dt > 0 else 0.0

    def samples_per_sec(self, batch_size: int) -> float:
        return self.steps_per_sec() * batch_size

    def histogram(self) -> dict | None:
        """p50/p95/max (ms) of the timed tick intervals; None before any."""
        return self._hist.summary()
