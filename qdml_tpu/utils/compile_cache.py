"""Persistent XLA compilation cache, shared by every entry point.

One definition of the cache location/thresholds so bench.py,
``__graft_entry__`` and the test suite can never desynchronize (compile time
dominates every cold run on both the 1-CPU driver host and the tunnelled TPU).

Telemetry: enabling the cache also installs a ``jax.monitoring`` listener
counting cache hits/misses/requests; :func:`compile_cache_stats` is the
process-wide counter snapshot the telemetry layer folds into its per-epoch
``counters`` records (a cold-cache run is a different measurement than a
warm one — now the artifact says which).
"""

from __future__ import annotations

CACHE_DIR = "/tmp/qdml_jax_cache"

_COUNTS = {"hits": 0, "misses": 0, "requests": 0}
_LISTENING = False


def _on_event(event: str, **kwargs) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        _COUNTS["hits"] += 1
    elif event == "/jax/compilation_cache/cache_misses":
        _COUNTS["misses"] += 1
    elif event == "/jax/compilation_cache/compile_requests_use_cache":
        _COUNTS["requests"] += 1


def compile_cache_stats() -> dict:
    """Snapshot of this process's compile-cache hit/miss/request counters
    (all zero until :func:`enable_compile_cache` has installed the listener
    and a jit compile has gone through the cache). ``requests`` ticks on
    EVERY compile that consulted the cache; ``misses`` only on compiles long
    enough to be worth persisting — so "did anything compile?" checks (the
    serve warmup gate) must watch ``requests``, not just ``misses``."""
    return dict(_COUNTS)


def reset_stats() -> None:
    """Zero the counters in place — for test harnesses and standalone
    warmup-verification scripts that want a clean window. The counters are
    PROCESS-WIDE: long-lived consumers that share the process with others
    (the serving engine, StepClock) must snapshot-and-diff instead of
    resetting, or they clobber every other reader's run totals."""
    for k in _COUNTS:
        _COUNTS[k] = 0


def _install_listener() -> None:
    """Register the jax.monitoring listener exactly once per process.

    Idempotent under repeated :func:`enable_compile_cache` calls — and under
    direct repeated calls — via the module-level flag, which is only set
    AFTER successful registration (a failed attempt may retry later without
    ever double-registering, which would double-count every event).
    """
    global _LISTENING
    if _LISTENING:
        return
    try:
        from jax import monitoring

        monitoring.register_event_listener(_on_event)
        _LISTENING = True
    except Exception:  # lint: disable=broad-except(jax.monitoring moved or absent — the cache still works; counters stay 0)
        # jax.monitoring moved/absent: the cache still works, counters stay 0.
        pass


def enable_compile_cache() -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _install_listener()
