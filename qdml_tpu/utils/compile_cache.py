"""Persistent XLA compilation cache, shared by every entry point.

One definition of the cache location/thresholds so bench.py,
``__graft_entry__`` and the test suite can never desynchronize (compile time
dominates every cold run on both the 1-CPU driver host and the tunnelled TPU).
"""

from __future__ import annotations

CACHE_DIR = "/tmp/qdml_jax_cache"


def enable_compile_cache() -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
