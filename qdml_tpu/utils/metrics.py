"""NMSE metrics and structured JSONL metric logging.

The reference logs with bare ``print()`` (``Runner...py:206-208, 268-270``) and
keeps histories in in-memory lists (``Runner...py:36-38``); its NMSE is a
whole-batch ratio ``sum((x_hat-x)**2)/sum(x**2)``
(``Estimators_QuantumNAT_onchipQNN.py:282-286``), reported in dB as
``10*log10(nmse)`` (``Test.py:259-265``).
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, IO

import jax.numpy as jnp


def _is_primary() -> bool:
    """True on the single process that should write shared files."""
    import jax

    try:
        return jax.process_index() == 0
    except Exception:
        return True


def nmse(x_hat: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Whole-batch NMSE over real arrays (reference ``NMSE_cuda``)."""
    return jnp.sum((x_hat - x) ** 2) / jnp.sum(x**2)


def nmse_complex(h_hat, h) -> jnp.ndarray:
    """Whole-batch NMSE over complex (CArr real-pair) arrays."""
    return jnp.sum((h_hat - h).abs2()) / jnp.sum(h.abs2())


def nmse_db(value: float) -> float:
    return 10.0 * math.log10(max(float(value), 1e-30))


class MetricsLogger:
    """Append-only JSONL metrics stream + optional console echo."""

    def __init__(self, path: str | None = None, echo: bool = True):
        self._fh: IO[str] | None = None
        self.echo = echo
        if path is not None and _is_primary():
            # Multi-host: only process 0 writes (every host runs the same
            # loop; concurrent appends to a shared file would interleave).
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", buffering=1)

    def log(self, step: int | None = None, **values: Any) -> None:
        rec = {"ts": round(time.time(), 3)}
        if step is not None:
            rec["step"] = step
        for k, v in values.items():
            rec[k] = float(v) if hasattr(v, "item") else v
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
        if self.echo:
            shown = {k: (round(v, 6) if isinstance(v, float) else v) for k, v in rec.items() if k != "ts"}
            print(" ".join(f"{k}={v}" for k, v in shown.items()), flush=True)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
