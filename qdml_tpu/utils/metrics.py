"""NMSE metrics and structured JSONL metric logging.

The reference logs with bare ``print()`` (``Runner...py:206-208, 268-270``) and
keeps histories in in-memory lists (``Runner...py:36-38``); its NMSE is a
whole-batch ratio ``sum((x_hat-x)**2)/sum(x**2)``
(``Estimators_QuantumNAT_onchipQNN.py:282-286``), reported in dB as
``10*log10(nmse)`` (``Test.py:259-265``).
"""

from __future__ import annotations

import math
import time
from typing import Any

import jax.numpy as jnp

from qdml_tpu.telemetry.core import is_primary as _is_primary  # noqa: F401 (compat)


def nmse(x_hat: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Whole-batch NMSE over real arrays (reference ``NMSE_cuda``)."""
    return jnp.sum((x_hat - x) ** 2) / jnp.sum(x**2)


def nmse_complex(h_hat, h) -> jnp.ndarray:
    """Whole-batch NMSE over complex (CArr real-pair) arrays."""
    return jnp.sum((h_hat - h).abs2()) / jnp.sum(h.abs2())


def nmse_db(value: float) -> float:
    return 10.0 * math.log10(max(float(value), 1e-30))


class MetricsLogger:
    """Append-only JSONL metrics stream + optional console echo.

    Thin facade over :class:`qdml_tpu.telemetry.core.Telemetry` (multi-host:
    only process 0 writes; every host runs the same loop, and concurrent
    appends to a shared file would interleave). Metric records keep the
    legacy bare shape (no ``kind`` field) so existing readers are untouched;
    passing ``manifest`` (a :func:`qdml_tpu.telemetry.run_manifest` dict)
    writes it as the stream's provenance header line.
    """

    def __init__(
        self,
        path: str | None = None,
        echo: bool = True,
        manifest: dict | None = None,
    ):
        from qdml_tpu.telemetry.core import Telemetry

        self._tele = Telemetry(path, manifest=manifest)
        self.echo = echo

    @property
    def telemetry(self):
        """The underlying sink — spans/counters route through it too."""
        return self._tele

    def span(self, name: str, **tags):
        """A :func:`qdml_tpu.telemetry.span` bound to this logger's stream."""
        from qdml_tpu.telemetry.spans import span

        return span(name, sink=self._tele, **tags)

    def log(self, step: int | None = None, **values: Any) -> None:
        rec: dict[str, Any] = {"ts": round(time.time(), 3)}
        if step is not None:
            rec["step"] = step
        for k, v in values.items():
            rec[k] = float(v) if hasattr(v, "item") else v
        self._tele.write_raw(rec)
        if self.echo:
            shown = {k: (round(v, 6) if isinstance(v, float) else v) for k, v in rec.items() if k != "ts"}
            print(" ".join(f"{k}={v}" for k, v in shown.items()), flush=True)

    def close(self) -> None:
        self._tele.close()
