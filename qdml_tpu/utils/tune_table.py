"""Shared persistence for measured-dispatch autotune tables.

The repo now carries three dispatcher races (circuit impls, dense-vs-sparse
routing, bucket-vs-ragged batching); the two newer ones
(:mod:`qdml_tpu.ops.dispatch_autotune`,
:mod:`qdml_tpu.serve.batching_autotune`) share this one table store instead
of each re-implementing the load/atomic-save/status/cache machinery — a fix
to the shared contract (status taxonomy, manifest header, atomic replace)
lands once. :mod:`qdml_tpu.quantum.autotune` predates the store and still
carries its original copy (its tests reach into the module-level cache);
migrate it onto the store the next time that subsystem is touched — the
routing dispatcher's delegation is the template.

Contract (inherited from the quantum dispatcher and unchanged):

- loads NEVER raise: any pathology degrades to ``{}`` entries with a status
  in ``ok|missing|corrupt|alien|unreadable`` — tuning can speed a hot path
  up, never crash it;
- saves are atomic (tmp + ``os.replace``) and best-effort: serving must
  survive a read-only results directory;
- an in-process cache keyed on the absolute path makes repeat lookups free;
  ``invalidate()`` clears it (tests point the store at tmp tables).
"""

from __future__ import annotations

import json
import os


class TableStore:
    """One autotune table's path resolution, cache, load and atomic save."""

    def __init__(self, default_path: str, env_var: str, kind: str, argv_tag: str):
        self.default_path = default_path
        self.env_var = env_var
        self.kind = kind          # payload "kind" stamped into saved tables
        self.argv_tag = argv_tag  # manifest argv label for provenance
        self._cache: dict[str, dict] = {}
        self._status: dict[str, str] = {}
        self._active: str | None = None

    def set_path(self, path: str | None) -> None:
        """Install (or clear) the process-wide table location."""
        self._active = os.path.abspath(path) if path else None

    def path(self, path: str | None = None) -> str:
        return os.path.abspath(
            path or self._active or os.environ.get(self.env_var) or self.default_path
        )

    def load(self, path: str | None = None) -> dict:
        """entries dict; {} on missing/corrupt/alien — never raises."""
        p = self.path(path)
        if p in self._cache:
            return self._cache[p]
        entries: dict = {}
        status = "ok"
        try:
            with open(p) as fh:
                data = json.load(fh)
            if isinstance(data, dict) and isinstance(data.get("entries"), dict):
                entries = data["entries"]
            else:
                status = "alien"
        except FileNotFoundError:
            status = "missing"
        except json.JSONDecodeError:
            status = "corrupt"
        except OSError:
            status = "unreadable"
        except (ValueError, TypeError):
            status = "corrupt"
        self._cache[p] = entries
        self._status[p] = status
        return entries

    def status(self, path: str | None = None) -> str:
        self.load(path)
        return self._status.get(self.path(path), "ok")

    def save(self, entries: dict, path: str | None = None, schema: int = 1) -> str:
        """Atomically persist the manifest-headed table; best-effort."""
        p = self.path(path)
        from qdml_tpu.telemetry import run_manifest

        payload = {
            "schema": schema,
            "kind": self.kind,
            "manifest": run_manifest(argv=[self.argv_tag], include_jax=True),
            "entries": entries,
        }
        try:
            os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
            tmp = f"{p}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(payload, fh, indent=2)
                fh.write("\n")
            os.replace(tmp, p)
        except OSError:
            pass
        self._cache[p] = entries
        self._status[p] = "ok"
        return p

    def invalidate(self) -> None:
        self._cache.clear()
        self._status.clear()
        self.set_path(None)
