"""TPU-native complex arithmetic: complex numbers as real pairs.

TPUs have no native complex dtype support (on this backend even materialising a
``complex64`` constant is UNIMPLEMENTED), and the MXU only multiplies real
matrices. The idiomatic TPU representation of the complex-valued signal
processing in the reference (complex pilots/channels throughout
``Runner_P128_QuantumNAT_onchipQNN.py:97-132``, ``Test.py:140-214``) is a
real/imag pair of float32 arrays — :class:`CArr` — with complex ops expanded
into real ops:

- elementwise ``(a+ib)(c+id) = (ac - bd) + i(ad + bc)``,
- contractions (``cmatmul``/``ceinsum``) as four real contractions, each of
  which XLA tiles onto the MXU,
- ``exp(i theta) = (cos theta, sin theta)``.

``CArr`` is a registered pytree, so it passes transparently through ``jit``,
``vmap``, ``grad``, and sharding. Host-side conversion to numpy ``complex64``
(for plots/serialisation) is the only place a true complex dtype appears.

The reference's real-packing conventions (``cat([real, imag], dim=1)``,
``view(bs, 2, 16, 8)`` at ``Runner...py:104-108``) map to :func:`pack_h` and
:func:`yp_to_image` below, in TPU-friendly NHWC layout.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
class CArr:
    """A complex array stored as a (real, imag) pair of real arrays."""

    __slots__ = ("re", "im")

    def __init__(self, re: jnp.ndarray, im: jnp.ndarray):
        self.re = re
        self.im = im

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.re, self.im), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    # -- basic info --------------------------------------------------------
    @property
    def shape(self):
        return jnp.shape(self.re)

    @property
    def dtype(self):
        return jnp.result_type(self.re)

    @property
    def ndim(self):
        return jnp.ndim(self.re)

    def __repr__(self):
        return f"CArr(shape={self.shape}, dtype={self.dtype})"

    # -- constructors ------------------------------------------------------
    @classmethod
    def zeros(cls, shape, dtype=jnp.float32) -> "CArr":
        return cls(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    @classmethod
    def from_real(cls, re: jnp.ndarray) -> "CArr":
        return cls(re, jnp.zeros_like(re))

    @classmethod
    def from_numpy(cls, x: np.ndarray | Any) -> "CArr":
        """Host-side: numpy complex (or real) array -> CArr of float32."""
        x = np.asarray(x)
        return cls(
            jnp.asarray(np.real(x), jnp.float32), jnp.asarray(np.imag(x), jnp.float32)
        )

    def to_numpy(self) -> np.ndarray:
        """Host-side: CArr -> numpy complex64."""
        return np.asarray(self.re) + 1j * np.asarray(self.im)

    # -- elementwise algebra ----------------------------------------------
    def __add__(self, o):
        o = _as_carr(o)
        return CArr(self.re + o.re, self.im + o.im)

    def __sub__(self, o):
        o = _as_carr(o)
        return CArr(self.re - o.re, self.im - o.im)

    def __mul__(self, o):
        if isinstance(o, (int, float)) or (hasattr(o, "dtype") and not isinstance(o, CArr)):
            return CArr(self.re * o, self.im * o)  # real scalar/array scaling
        return CArr(
            self.re * o.re - self.im * o.im, self.re * o.im + self.im * o.re
        )

    __rmul__ = __mul__
    __radd__ = __add__

    def conj(self) -> "CArr":
        return CArr(self.re, -self.im)

    def abs2(self) -> jnp.ndarray:
        return self.re * self.re + self.im * self.im

    def abs(self) -> jnp.ndarray:
        return jnp.sqrt(self.abs2())

    # -- shape ops ---------------------------------------------------------
    def reshape(self, *shape) -> "CArr":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return CArr(self.re.reshape(shape), self.im.reshape(shape))

    def transpose(self, *axes) -> "CArr":
        return CArr(jnp.transpose(self.re, axes or None), jnp.transpose(self.im, axes or None))

    def __getitem__(self, idx) -> "CArr":
        return CArr(self.re[idx], self.im[idx])

    def astype(self, dtype) -> "CArr":
        return CArr(self.re.astype(dtype), self.im.astype(dtype))


def _as_carr(x) -> CArr:
    if isinstance(x, CArr):
        return x
    return CArr.from_real(jnp.asarray(x))


# ---------------------------------------------------------------------------
# Complex contractions as real contractions (MXU path)
# ---------------------------------------------------------------------------


def ceinsum(spec: str, a: CArr | jnp.ndarray, b: CArr | jnp.ndarray) -> CArr:
    """Complex einsum over CArr operands via four real einsums."""
    a, b = _as_carr(a), _as_carr(b)
    rr = jnp.einsum(spec, a.re, b.re)
    ii = jnp.einsum(spec, a.im, b.im)
    ri = jnp.einsum(spec, a.re, b.im)
    ir = jnp.einsum(spec, a.im, b.re)
    return CArr(rr - ii, ri + ir)


def cmatmul(a: CArr, b: CArr) -> CArr:
    """Complex matmul via the 3-multiplication Gauss/Karatsuba trick.

    ``(a+ib)(c+id)``: with ``k1=c(a+b)``, ``k2=a(d-c)``, ``k3=b(c+d)`` the
    product is ``(k1-k3) + i(k1+k2)`` — three MXU matmuls instead of four.
    """
    a, b = _as_carr(a), _as_carr(b)
    k1 = (a.re + a.im) @ b.re
    k2 = a.re @ (b.im - b.re)
    k3 = a.im @ (b.re + b.im)
    return CArr(k1 - k3, k1 + k2)


def ckron(a: CArr, b: CArr) -> CArr:
    """Complex Kronecker product of 2-D CArrs: (p,q) x (r,s) -> (pr, qs)."""
    out = ceinsum("ij,kl->ikjl", a, b)
    p, q = a.shape
    r, s = b.shape
    return out.reshape(p * r, q * s)


def cexp_i(theta: jnp.ndarray) -> CArr:
    """``exp(i * theta)`` for real theta."""
    return CArr(jnp.cos(theta), jnp.sin(theta))


def cexp_i_ramp(theta: jnp.ndarray, n: int, split: int | None = None) -> CArr:
    """``exp(i * theta[..., None] * arange(n))`` with ~2*sqrt(n) instead of n
    transcendental pairs per theta element.

    Factoring the ramp index ``k = a + split*b`` and applying the angle-
    addition identity ``e^{i theta (a + split b)} = e^{i theta a} e^{i theta
    split b}`` needs ``split + ceil(n/split)`` sin/cos pairs plus one complex
    outer product. sin/cos throughput is the VPU bottleneck of the channel
    generator's steering/delay phase ramps (the two trig fusions are 325
    us/step of the scan-fused HDCE step on v5e, ~40% of the generator tail —
    results/perf_r5/scan_rbg.trace.json.gz), so quartering the transcendental
    count is the lever; the outer product it adds is cheap elementwise work.
    Exact to f32 rounding — no recurrence error accumulation.
    """
    if split is None:
        split = max(1, int(round(n**0.5)))
        while n % split:  # prefer a divisor of n: no tail slice needed
            split -= 1
    n_hi = -(-n // split)
    a = jnp.arange(split, dtype=theta.dtype)
    b = jnp.arange(n_hi, dtype=theta.dtype) * split
    lo = cexp_i(theta[..., None] * a)  # (..., split)
    hi = cexp_i(theta[..., None] * b)  # (..., n_hi)
    out = CArr(
        hi.re[..., :, None] * lo.re[..., None, :]
        - hi.im[..., :, None] * lo.im[..., None, :],
        hi.re[..., :, None] * lo.im[..., None, :]
        + hi.im[..., :, None] * lo.re[..., None, :],
    ).reshape(tuple(theta.shape) + (n_hi * split,))
    return out[..., :n] if n_hi * split != n else out


def cstack(arrs: list[CArr], axis: int = 0) -> CArr:
    return CArr(
        jnp.stack([a.re for a in arrs], axis), jnp.stack([a.im for a in arrs], axis)
    )


def cconcat(arrs: list[CArr], axis: int = 0) -> CArr:
    return CArr(
        jnp.concatenate([a.re for a in arrs], axis),
        jnp.concatenate([a.im for a in arrs], axis),
    )


def cwhere(pred: jnp.ndarray, a: CArr, b: CArr) -> CArr:
    a, b = _as_carr(a), _as_carr(b)
    return CArr(jnp.where(pred, a.re, b.re), jnp.where(pred, a.im, b.im))


# ---------------------------------------------------------------------------
# Packing conventions (reference Runner...py:104-108, TPU NHWC)
# ---------------------------------------------------------------------------


def complex_to_real_pair(x: CArr) -> jnp.ndarray:
    """``(..., d) -> (..., 2d)`` real, real half first (reference
    ``cat([real, imag], dim=1)``, ``Runner...py:104-105``)."""
    return jnp.concatenate([x.re, x.im], axis=-1)


def pack_h(h: CArr) -> jnp.ndarray:
    """Flat complex channel ``(..., h_dim)`` -> real training target ``(..., 2*h_dim)``."""
    return complex_to_real_pair(h)


def unpack_h(h2: jnp.ndarray) -> CArr:
    """Inverse of :func:`pack_h`."""
    d = h2.shape[-1] // 2
    return CArr(h2[..., :d], h2[..., d:])


def yp_to_image(yp: CArr, n_sub: int = 16, n_beam: int = 8) -> jnp.ndarray:
    """Flat complex pilots ``(..., n_beam*n_sub)`` -> NHWC image
    ``(..., n_sub, n_beam, 2)``.

    The flat pilot vector is beam-major (``X[beam, sub].reshape(-1)``); the CNN
    sees a (subcarrier, beam) spatial grid with re/im as trailing channels (the
    reference uses a (2, 16, 8) NCHW view, ``Runner...py:108``; NHWC is the
    native TPU conv layout).
    """
    x = yp.reshape(yp.shape[:-1] + (n_beam, n_sub))
    img = jnp.stack([x.re, x.im], axis=-1)  # (..., n_beam, n_sub, 2)
    return jnp.swapaxes(img, -2, -3)  # (..., n_sub, n_beam, 2)
