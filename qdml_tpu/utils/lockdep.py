"""Runtime lock-order witness (lockdep) behind ``QDML_LOCKDEP=1``.

The static lock graph (:mod:`qdml_tpu.analysis.concurrency`) proves the
acquisition-order model over code the analyzer can see; this module proves
it over code that actually RAN. :func:`Lock`/:func:`RLock` are drop-in
factories for ``threading.Lock()``/``threading.RLock()`` taking a lock
*name* (the same ``Class._attr`` / ``module:NAME`` identities the static
graph uses):

- **disabled (default)**: the factory returns the stdlib primitive itself —
  not a wrapper, not a subclass, the exact object ``threading.Lock()``
  hands out. Zero per-acquire overhead, import-time inert; the same
  discipline as checkify-off being HLO-identical and trace-off being
  overhead-free. The env var is read at *construction* time, so a test can
  flip it with ``monkeypatch.setenv`` + a fresh lock; long-lived module
  locks are whatever the import-time setting said.
- **enabled (``QDML_LOCKDEP=1``)**: each lock becomes a :class:`_DepLock`
  recording, per thread, the stack of currently-held locks and, process-
  globally, every first-seen acquisition-order edge (A held while B
  acquired) with the stack that first exhibited it. Acquiring B while
  holding A when the REVERSE edge (B→A) is already on record raises
  :class:`LockOrderError` naming both edges and both first-seen stacks —
  the deadlock is reported from the second path even when the schedule
  never actually interleaves, which is the whole point: one chaos run
  witnesses orderings that production would need a pathological schedule
  to hit.

RLock re-entry (acquiring a lock this thread already holds) is legal by
construction and records no edge. Edge bookkeeping is guarded by one plain
stdlib lock which itself never participates in witnessing (no recursion).

``witness_summary()`` reports ``{"enabled", "locks", "edges", "max_held",
"inversions"}`` for the chaos/dryrun headline blocks: the headline gates on
``inversions == 0`` (recorded before the raise, so the certificate holds
even when a supervised worker thread's fault handling swallows the
exception).
"""

from __future__ import annotations

import os
import threading
import traceback

__all__ = [
    "Lock",
    "RLock",
    "LockOrderError",
    "enabled",
    "reset",
    "witness_summary",
]


def enabled() -> bool:
    """Whether locks constructed NOW would be witnessed."""
    return os.environ.get("QDML_LOCKDEP") == "1"


class LockOrderError(RuntimeError):
    """Two lock identities were acquired in both orders.

    Carries both edges and the first-seen stack of each, so the report
    names the two call paths that would deadlock against each other."""

    def __init__(
        self,
        first: tuple[str, str],
        second: tuple[str, str],
        first_stack: str,
        second_stack: str,
    ):
        self.first = first
        self.second = second
        self.first_stack = first_stack
        self.second_stack = second_stack
        super().__init__(
            f"lock-order inversion: edge {second[0]} -> {second[1]} "
            f"contradicts previously-seen edge {first[0]} -> {first[1]}\n"
            f"--- first-seen stack for {first[0]} -> {first[1]} ---\n"
            f"{first_stack}"
            f"--- acquiring stack for {second[0]} -> {second[1]} ---\n"
            f"{second_stack}"
        )


# process-global witness state; _guard is a raw stdlib lock and is never
# itself witnessed (leaf by construction — nothing is acquired under it)
_guard = threading.Lock()
_edges: dict[tuple[str, str], str] = {}  # (held, acquired) -> first stack
_names: set[str] = set()
_max_held = 0
# inversions seen, recorded BEFORE the raise: a LockOrderError thrown inside
# a supervised worker thread may be swallowed by that thread's fault
# handling (the supervisor treats it as a crash and restarts), so the
# dryrun headline gates on this counter, not on the exception escaping
_inversions: list[str] = []

_tls = threading.local()


def _held() -> list["_DepLock"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _short_stack(skip: int = 3) -> str:
    return "".join(traceback.format_stack()[:-skip][-8:])


class _DepLock:
    """Witnessing wrapper over a stdlib lock. Same acquire/release/context
    protocol; ``reentrant`` relaxes the re-entry rule (RLock)."""

    __slots__ = ("name", "reentrant", "_inner")

    def __init__(self, name: str, reentrant: bool):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        with _guard:
            _names.add(name)

    # -- witness core --------------------------------------------------------

    def _note_acquire(self) -> None:
        global _max_held
        stack = _held()
        if self.reentrant and any(h is self for h in stack):
            stack.append(self)  # re-entry: legal, no edge
            return
        if stack:
            held_names = [h.name for h in stack]
            my_stack = _short_stack()
            with _guard:
                for held in held_names:
                    if held == self.name:
                        continue
                    edge = (held, self.name)
                    rev = (self.name, held)
                    if rev in _edges:
                        _inversions.append(
                            f"{edge[0]} -> {edge[1]} vs {rev[0]} -> {rev[1]}"
                        )
                        raise LockOrderError(
                            rev, edge, _edges[rev], my_stack
                        )
                    _edges.setdefault(edge, my_stack)
        stack.append(self)
        if len(stack) > _max_held:
            with _guard:
                _max_held = max(_max_held, len(stack))

    def _note_release(self) -> None:
        stack = _held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # witness BEFORE blocking: the inversion report must fire even when
        # (especially when) the acquire would deadlock for real
        self._note_acquire()
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            self._note_release()
        return ok

    def release(self) -> None:
        self._inner.release()
        self._note_release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        if self.reentrant:
            return any(h is self for h in _held())
        return self._inner.locked()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        kind = "RLock" if self.reentrant else "Lock"
        return f"<lockdep.{kind} {self.name!r}>"


def Lock(name: str):
    """``threading.Lock()`` (disabled) or a witnessing lock (enabled)."""
    if not enabled():
        return threading.Lock()
    return _DepLock(name, reentrant=False)


def RLock(name: str):
    """``threading.RLock()`` (disabled) or a witnessing re-entrant lock."""
    if not enabled():
        return threading.RLock()
    return _DepLock(name, reentrant=True)


def reset() -> None:
    """Drop all witnessed state (tests; also safe between dryrun phases —
    per-thread held stacks are live and not touched)."""
    global _max_held
    with _guard:
        _edges.clear()
        _names.clear()
        _inversions.clear()
        _max_held = 0


def witness_summary() -> dict:
    """The dryrun-headline block. ``enabled`` reflects the env var NOW;
    counts cover every witnessed lock since the last :func:`reset`.
    ``inversions`` is the gate: each one also raised a LockOrderError at
    the acquisition site, but the counter survives a worker thread's fault
    handling swallowing the exception."""
    with _guard:
        return {
            "enabled": enabled(),
            "locks": len(_names),
            "edges": len(_edges),
            "max_held": _max_held,
            "inversions": len(_inversions),
            "inversion_edges": list(_inversions),
        }
