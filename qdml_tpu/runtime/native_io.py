"""ctypes bindings for the native IO runtime (``native/qdml_io.cpp``).

Provides three host-side primitives the reference's single-threaded torch
DataLoader path (``Runner_P128_QuantumNAT_onchipQNN.py:24, 48-95``) lacks:

- :class:`NativeNpyFile` — zero-copy mmap'd ``.npy`` access (header parsed in
  C++, data exposed as a numpy view of the mapping; the OS page cache is the
  buffer pool),
- :func:`gather_rows` — multithreaded batch assembly from shuffled row
  indices into one contiguous buffer,
- :class:`PrefetchPipeline` — an async slot-ring: C++ worker threads fill the
  next batches while the accelerator consumes the current one.

The shared library is compiled on first use with ``g++`` (no pybind11 in this
image — plain C ABI + ctypes). Every entry point degrades gracefully to a
numpy implementation when the toolchain or the library is unavailable, so the
framework never hard-depends on native code being buildable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from qdml_tpu.utils import lockdep
from typing import Sequence

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "native", "qdml_io.cpp")
_LOCK = lockdep.Lock("native_io:_LOCK")
_LIB: ctypes.CDLL | None = None
_TRIED = False

_DTYPES = {
    ("f", 4): np.float32,
    ("f", 8): np.float64,
    ("c", 8): np.complex64,
    ("c", 16): np.complex128,
    ("i", 4): np.int32,
    ("i", 8): np.int64,
    ("u", 4): np.uint32,
    ("u", 8): np.uint64,
}


def _build_lib() -> str | None:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return None
    out_dir = os.environ.get("QDML_NATIVE_DIR") or os.path.join(
        os.path.dirname(src)
    )
    out = os.path.join(out_dir, "libqdml_io.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        src, "-o", out,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    return out


def _load() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        path = _build_lib()  # lint: disable=blocking-under-lock(one-time lazy build: _LOCK makes the native compile exactly-once; every later caller needs the library and must wait for it regardless)
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.qdml_npy_open.restype = ctypes.c_void_p
        lib.qdml_npy_open.argtypes = [ctypes.c_char_p]
        lib.qdml_npy_info.restype = ctypes.c_int
        lib.qdml_npy_info.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_char),
        ]
        lib.qdml_npy_data.restype = ctypes.c_void_p
        lib.qdml_npy_data.argtypes = [ctypes.c_void_p]
        lib.qdml_npy_close.argtypes = [ctypes.c_void_p]
        lib.qdml_gather_rows.argtypes = [
            ctypes.c_void_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
            ctypes.c_long,
            ctypes.c_void_p,
            ctypes.c_int,
        ]
        lib.qdml_prefetch_create.restype = ctypes.c_void_p
        lib.qdml_prefetch_create.argtypes = [
            ctypes.c_void_p,
            ctypes.c_long,
            ctypes.c_int,
            ctypes.c_long,
            ctypes.c_int,
        ]
        lib.qdml_prefetch_submit.restype = ctypes.c_int
        lib.qdml_prefetch_submit.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_long),
            ctypes.c_long,
        ]
        lib.qdml_prefetch_wait.restype = ctypes.c_int
        lib.qdml_prefetch_wait.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.qdml_prefetch_buffer.restype = ctypes.c_void_p
        lib.qdml_prefetch_buffer.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.qdml_prefetch_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.qdml_prefetch_destroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def native_available() -> bool:
    """True when the C++ library could be built and loaded."""
    return _load() is not None


class NativeNpyFile:
    """mmap'd ``.npy`` file; ``.array`` is a zero-copy numpy view.

    Falls back to ``np.load(mmap_mode='r')`` when the native library is
    unavailable — same semantics, the C++ path just skips Python-level header
    parsing and keeps the mapping under runtime control.
    """

    def __init__(self, path: str):
        self.path = path
        self._handle = None
        self._lib = _load()
        if self._lib is not None:
            h = self._lib.qdml_npy_open(path.encode())
            if h:
                self._handle = h
                shape = (ctypes.c_long * 8)()
                ndim = ctypes.c_int()
                itemsize = ctypes.c_int()
                tch = ctypes.c_char()
                self._lib.qdml_npy_info(
                    h, shape, ctypes.byref(ndim), ctypes.byref(itemsize), ctypes.byref(tch)
                )
                dtype = _DTYPES.get((tch.value.decode(), itemsize.value))
                if dtype is None:
                    self._lib.qdml_npy_close(h)
                    self._handle = None
                else:
                    shp = tuple(shape[i] for i in range(ndim.value))
                    n = int(np.prod(shp)) if shp else 1
                    buf_t = ctypes.c_char * (n * itemsize.value)
                    buf = buf_t.from_address(self._lib.qdml_npy_data(h))
                    # The view's .base chain must keep THIS object (and so the
                    # mapping) alive: a bare from_address buffer references the
                    # raw pointer only, and letting the file be GC'd while the
                    # array is reachable would be a use-after-munmap.
                    buf._qdml_owner = self
                    view = np.frombuffer(buf, dtype=dtype).reshape(shp)
                    view.flags.writeable = False  # PROT_READ mapping
                    self.array = view
        if self._handle is None:
            self.array = np.load(path, mmap_mode="r")

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    def close(self) -> None:
        if self._handle is not None:
            # Drop the numpy view before unmapping.
            self.array = None
            self._lib.qdml_npy_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # lint: disable=broad-except(__del__ at interpreter shutdown — module globals may already be torn down)
            pass


def gather_rows(
    src: np.ndarray, indices: Sequence[int] | np.ndarray, n_threads: int = 4
) -> np.ndarray:
    """Gather ``src[indices]`` into a fresh contiguous array, multithreaded in
    C++ when available (releases the GIL for the whole copy)."""
    src = np.ascontiguousarray(src) if not src.flags["C_CONTIGUOUS"] else src
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    lib = _load()
    if lib is None:
        return np.ascontiguousarray(src[idx])
    row_shape = src.shape[1:]
    row_bytes = int(np.prod(row_shape, dtype=np.int64)) * src.itemsize
    out = np.empty((len(idx),) + row_shape, dtype=src.dtype)
    lib.qdml_gather_rows(
        src.ctypes.data_as(ctypes.c_void_p),
        row_bytes,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        len(idx),
        out.ctypes.data_as(ctypes.c_void_p),
        int(n_threads),
    )
    return out


class PrefetchPipeline:
    """Async batch assembly over a row-major source array.

    ``submit(indices)`` queues a batch fill on the C++ worker pool and returns
    a ticket; ``get(ticket)`` blocks until that batch is ready and returns a
    numpy view of the slot buffer (valid until ``release(ticket)``). With
    ``n_slots >= 2`` the next batch fills while the current one is consumed.

    Python-threads fallback keeps the same API when native code is absent.
    """

    def __init__(
        self,
        src: np.ndarray,
        batch: int,
        n_slots: int = 3,
        n_threads: int = 4,
    ):
        assert src.flags["C_CONTIGUOUS"], "prefetch source must be C-contiguous"
        self.src = src
        self.batch = batch
        self.row_shape = src.shape[1:]
        self.row_bytes = int(np.prod(self.row_shape, dtype=np.int64)) * src.itemsize
        self._lib = _load()
        self._fallback: dict[int, np.ndarray] = {}
        self._counts: dict[int, int] = {}
        self._next_ticket = 0
        if self._lib is not None:
            self._handle = self._lib.qdml_prefetch_create(
                src.ctypes.data_as(ctypes.c_void_p),
                self.row_bytes,
                int(n_slots),
                int(batch),
                int(n_threads),
            )
        else:
            self._handle = None

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    def submit(self, indices: np.ndarray) -> int:
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        assert len(idx) <= self.batch
        if self._handle is None:
            t = self._next_ticket
            self._next_ticket += 1
            self._fallback[t] = np.ascontiguousarray(self.src[idx])
            return t
        slot = self._lib.qdml_prefetch_submit(
            self._handle,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
            len(idx),
        )
        if slot < 0:
            raise RuntimeError(
                "no free prefetch slot — release() consumed batches first"
            )
        self._counts[slot] = len(idx)
        return slot

    def get(self, ticket: int) -> np.ndarray:
        if self._handle is None:
            return self._fallback[ticket]
        self._lib.qdml_prefetch_wait(self._handle, ticket)
        addr = self._lib.qdml_prefetch_buffer(self._handle, ticket)
        n = self._counts[ticket]
        buf_t = ctypes.c_char * (n * self.row_bytes)
        buf = buf_t.from_address(addr)
        return np.frombuffer(buf, dtype=self.src.dtype).reshape((n,) + self.row_shape)

    def release(self, ticket: int) -> None:
        if self._handle is None:
            self._fallback.pop(ticket, None)
        else:
            self._lib.qdml_prefetch_release(self._handle, ticket)
            # Drop the count so a stale ticket can't silently read a reused
            # slot's buffer with the wrong length.
            self._counts.pop(ticket, None)

    def close(self) -> None:
        if self._handle is not None:
            self._lib.qdml_prefetch_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # lint: disable=broad-except(__del__ at interpreter shutdown — module globals may already be torn down)
            pass
