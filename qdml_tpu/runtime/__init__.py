"""Native runtime components (C++ host-side IO, profiling hooks).

The compute path of qdml_tpu is JAX/XLA/Pallas; this package holds the
native-code runtime around it — the role the task's reference inventory
assigns to "executors, schedulers, IO, memory management" (the reference
itself is pure Python with a single-threaded host data path, SURVEY.md §0).
"""

from qdml_tpu.runtime.native_io import (  # noqa: F401
    NativeNpyFile,
    PrefetchPipeline,
    gather_rows,
    native_available,
)
