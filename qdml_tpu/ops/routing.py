"""Predicted-scenario expert routing without host round-trips.

The reference's evaluation partitions each batch by the classifier's PREDICTED
scenario and feeds each partition through the matching ``Conv_P128`` trunk with
Python-level boolean indexing (``Test.py:167-214``) — data-dependent control
flow that would force host sync under XLA. The TPU-native expression (SURVEY.md
§3.3, §7.3): run ALL trunks on the full batch (they are tiny and the stacked
trunk is one batched conv) and gather each sample's row by its predicted id —
a pure ``take_along_axis``, i.e. MoE-style hard routing with S=3 experts and
top-1 dispatch.
"""

from __future__ import annotations

import jax.numpy as jnp


def select_expert(stacked: jnp.ndarray, pred: jnp.ndarray) -> jnp.ndarray:
    """Gather per-sample expert outputs.

    ``stacked``: (S, B, D) outputs of every expert on every sample;
    ``pred``: (B,) int expert ids. Returns (B, D).

    Out-of-range ids are clipped into ``[0, S-1]`` rather than silently
    gathering garbage: under jit XLA clamps gather indices anyway, but eager
    numpy-semantics callers (and negative ids, which numpy would WRAP to the
    last expert) would otherwise diverge from the compiled path. A corrupted
    classifier id thus degrades to the nearest valid expert on every path
    identically, and ``one_hot_dispatch`` (which zeros out-of-range rows)
    stays the only intentionally-masking variant.
    """
    idx = jnp.clip(pred, 0, stacked.shape[0] - 1)[None, :, None]  # (1, B, 1)
    return jnp.take_along_axis(stacked, idx, axis=0)[0]


def one_hot_dispatch(stacked: jnp.ndarray, log_probs: jnp.ndarray) -> jnp.ndarray:
    """Differentiable variant: weight expert outputs by hard one-hot of argmax.

    Equivalent to :func:`select_expert` in value; expressed as a masked sum
    (einsum against a one-hot) which shards cleanly when ``stacked`` is
    scenario-sharded over a mesh axis.
    """
    pred = jnp.argmax(log_probs, axis=-1)
    onehot = jnp.equal(
        jnp.arange(stacked.shape[0])[:, None], pred[None, :]
    ).astype(stacked.dtype)  # (S, B)
    return jnp.einsum("sb,sbd->bd", onehot, stacked)
