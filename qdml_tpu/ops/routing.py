"""Predicted-scenario expert routing without host round-trips.

The reference's evaluation partitions each batch by the classifier's PREDICTED
scenario and feeds each partition through the matching ``Conv_P128`` trunk with
Python-level boolean indexing (``Test.py:167-214``) — data-dependent control
flow that would force host sync under XLA. Two TPU-native expressions live
here, and WHICH one runs is the autotune dispatcher's measured decision
(:mod:`qdml_tpu.ops.dispatch_autotune`), never a heuristic:

- **dense**: run ALL trunks on the full batch (the stacked trunk is one
  batched conv) and gather each sample's row by its predicted id — a pure
  ``take_along_axis``, MoE-style hard routing with top-1 dispatch
  (:func:`select_expert`). At the reference's S=3 the all-trunks pass is
  nearly free and the zero-bookkeeping gather wins the race.
- **sparse** (:func:`sparse_dispatch`): at S≫3 the dense pass stops being
  viable — estimation FLOPs grow O(S) while useful work stays O(1), so at
  S=64 it burns ~64x the compute it returns. The sparse path packs the batch
  into fixed-capacity per-expert buckets (static shapes — a ``capacity_factor``
  knob sizes them), runs ONLY the chosen trunk per bucket through the same
  stacked-conv vmap, and unsorts. Work drops from ``S*B`` trunk-rows to
  ``~capacity_factor*B`` regardless of S. Overflow rows (an expert offered
  more rows than its bucket holds) are NEVER dropped: a ``lax.cond`` falls
  back to the dense gather for exactly those rows, so the result is
  value-equivalent to :func:`select_expert` on every path (pinned in
  ``tests/test_routing_sparse.py``).

All bookkeeping is shape-static (one-hot cumsum ranks + scatter/gather into a
``(S, C)`` bucket tensor with a trash slot) — no ``jnp.nonzero`` / boolean
masking / data-dependent shapes, the hazard class graftlint's
``data-dependent-shape-in-jit`` rule exists to keep out of jitted hot paths.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp


def select_expert(stacked: jnp.ndarray, pred: jnp.ndarray) -> jnp.ndarray:
    """Gather per-sample expert outputs.

    ``stacked``: (S, B, D) outputs of every expert on every sample;
    ``pred``: (B,) int expert ids. Returns (B, D).

    Out-of-range ids are clipped into ``[0, S-1]`` rather than silently
    gathering garbage: under jit XLA clamps gather indices anyway, but eager
    numpy-semantics callers (and negative ids, which numpy would WRAP to the
    last expert) would otherwise diverge from the compiled path. A corrupted
    classifier id thus degrades to the nearest valid expert on every path
    identically, and ``one_hot_dispatch`` (which zeros out-of-range rows)
    stays the only intentionally-masking variant.
    """
    idx = jnp.clip(pred, 0, stacked.shape[0] - 1)[None, :, None]  # (1, B, 1)
    return jnp.take_along_axis(stacked, idx, axis=0)[0]


def one_hot_dispatch(stacked: jnp.ndarray, log_probs: jnp.ndarray) -> jnp.ndarray:
    """Differentiable variant: weight expert outputs by hard one-hot of argmax.

    Equivalent to :func:`select_expert` in value; expressed as a masked sum
    (einsum against a one-hot) which shards cleanly when ``stacked`` is
    scenario-sharded over a mesh axis.
    """
    pred = jnp.argmax(log_probs, axis=-1)
    onehot = jnp.equal(
        jnp.arange(stacked.shape[0])[:, None], pred[None, :]
    ).astype(stacked.dtype)  # (S, B)
    return jnp.einsum("sb,sbd->bd", onehot, stacked)


# ---------------------------------------------------------------------------
# Capacity-bucketed sparse top-1 dispatch
# ---------------------------------------------------------------------------


def expert_capacity(batch: int, n_experts: int, capacity_factor: float) -> int:
    """Static per-expert bucket size: ``ceil(B * f / S)`` clamped to
    ``[1, B]``. Total sparse trunk work is ``S * C ~= f * B`` rows — the
    O(S)-to-O(1) reduction the sparse path exists for. ``f`` trades compute
    headroom for overflow-fallback frequency under skewed routing."""
    c = math.ceil(batch * float(capacity_factor) / max(1, int(n_experts)))
    return max(1, min(int(c), int(batch)))


def bucket_ranks(
    pred: jnp.ndarray, n_experts: int, valid: jnp.ndarray | None = None
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(clipped ids, within-expert arrival rank) for each row — the static
    routing plan. Rank is the row's 0-based position among SAME-expert rows
    in batch order (one-hot cumsum: O(B*S) int work, no sort, no
    data-dependent shape). Rows with ``valid=False`` (padding) consume no
    rank: their one-hot column is zeroed, so a padded batch packs its real
    rows exactly like the unpadded batch would (padded-batch invariance)."""
    pred_c = jnp.clip(pred.astype(jnp.int32), 0, n_experts - 1)
    onehot = (pred_c[:, None] == jnp.arange(n_experts)[None, :]).astype(jnp.int32)
    if valid is not None:
        onehot = onehot * valid.astype(jnp.int32)[:, None]
    rank = jnp.take_along_axis(
        jnp.cumsum(onehot, axis=0) - 1, pred_c[:, None], axis=1
    )[:, 0]
    return pred_c, rank


def sparse_dispatch(
    run_experts: Callable[[jnp.ndarray], jnp.ndarray],
    dense_fallback: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    x: jnp.ndarray,
    pred: jnp.ndarray,
    n_experts: int,
    capacity_factor: float = 1.25,
    valid: jnp.ndarray | None = None,
    capacity: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Capacity-bucketed sparse top-1 dispatch, value-equivalent to
    ``select_expert(all-trunks, pred)`` with ~``capacity_factor/S`` of its
    trunk work.

    ``run_experts``: ``(S, C, *feat) -> (S, C, D)`` — expert s applied to its
    bucket rows only (the stacked-conv vmap on gathered buckets instead of a
    broadcast batch). ``dense_fallback``: ``(x, pred) -> (B, D)`` — the
    run-all-trunks + gather path, entered through ONE ``lax.cond`` only when
    overflow actually occurred, so balanced traffic never pays it.
    ``valid``: optional (B,) bool — padding rows consume no bucket capacity
    and their (garbage) outputs are the caller's to slice off. ``capacity``
    overrides the :func:`expert_capacity` default (which sizes off
    ``x.shape[0]`` — i.e. off the PADDED bucket in the serve engine, where
    the bucket size is the compiled static shape).

    Returns ``(out (B, D), overflow (i32 scalar))`` where ``overflow`` counts
    the valid rows served by the fallback. Mechanics, all shape-static:

    1. rank rows within their predicted expert (:func:`bucket_ranks`);
    2. scatter row i to flat slot ``pred[i]*C + rank[i]`` when ``rank < C``,
       else to a trash slot — the bucket tensor is ``(S*C + 1, ...)`` so
       overflow/padding rows can never corrupt a real bucket entry;
    3. ``run_experts`` on the ``(S, C, ...)`` buckets; gather each row's
       output back from its slot;
    4. overflow rows take the ``dense_fallback`` value via ``jnp.where`` —
       never dropped, bit-identical to the dense path (it IS the dense path).
    """
    b = x.shape[0]
    s = int(n_experts)
    c = capacity if capacity is not None else expert_capacity(b, s, capacity_factor)
    pred_c, rank = bucket_ranks(pred, s, valid=valid)
    fits = rank < c
    if valid is not None:
        fits = fits & valid
    # flat slot per row; the trash slot s*c absorbs overflow AND padding
    slot = jnp.where(fits, pred_c * c + rank, s * c)
    buckets = jnp.zeros((s * c + 1,) + x.shape[1:], x.dtype).at[slot].set(x)
    out_sc = run_experts(buckets[: s * c].reshape(s, c, *x.shape[1:]))
    out_flat = out_sc.reshape(s * c, out_sc.shape[-1])
    routed = jnp.take(out_flat, jnp.minimum(slot, s * c - 1), axis=0)
    overflow = jnp.sum(
        (~fits) if valid is None else ((~fits) & valid), dtype=jnp.int32
    )

    def _fallback(operand):
        xx, pp = operand
        return dense_fallback(xx, pp)

    def _skip(operand):
        return jnp.zeros_like(routed)

    # traced both ways, EXECUTED only on overflow: the rare skewed batch pays
    # the dense pass; the steady state pays one predicate
    dense_out = jax.lax.cond(overflow > 0, _fallback, _skip, (x, pred_c))
    return jnp.where(fits[:, None], routed, dense_out), overflow
