from qdml_tpu.ops.grad_prune import GradientPruneState, gradient_prune  # noqa: F401
from qdml_tpu.ops.quantumnat import perturb  # noqa: F401
from qdml_tpu.ops.routing import one_hot_dispatch, select_expert  # noqa: F401
