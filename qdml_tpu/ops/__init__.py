from qdml_tpu.ops.grad_prune import GradientPruneState, gradient_prune  # noqa: F401
from qdml_tpu.ops.quantumnat import perturb  # noqa: F401
from qdml_tpu.ops.routing import (  # noqa: F401
    bucket_ranks,
    expert_capacity,
    one_hot_dispatch,
    select_expert,
    sparse_dispatch,
)
