"""Autotuned dense-vs-sparse routing dispatch for the expert hot path.

The quantum half learned this lesson first (``quantum/autotune.py``,
BENCH_r05): nothing structural guarantees the "obviously faster" formulation
actually wins at a given shape, so the winner must be MEASURED, cached, and
dispatched from a table — never assumed. This module applies the identical
pattern to the classical half's mirror of the qubit wall: at the reference's
S=3 the run-all-trunks + gather path (``routing.select_expert``) is nearly
free, but estimation FLOPs grow O(S), so somewhere past the paper's grid the
capacity-bucketed sparse path (``routing.sparse_dispatch``) must take over.
WHERE is an empirical property of the platform, the scenario count and the
batch bucket — exactly what a ``(platform, S, bucket, dtype)``-keyed race
answers.

Contracts (mirroring the quantum dispatcher):

- ``ensure_route()`` (the tuner) is HOST-side and eager: serve warmup calls
  it per AOT bucket, the scenario-scaling bench per S point — never a traced
  function, never the serve request path.
- ``lookup()`` is read-only and cheap; any table pathology degrades to the
  ``dense`` fallback (the S=3-correct default), never raises.
- Eligibility windows bound what is worth timing: ``sparse`` only enters the
  race at ``S >= SPARSE_MIN_SCENARIOS`` — below it the bucketing bookkeeping
  cannot beat a 3-trunk fused pass, so the reference grid keeps its dense
  path with ZERO tuning compiles (the exclusion is recorded in the entry, a
  silent cap would read as "raced everything"). At eligible S the race is
  real: dense must EARN the S=3 slot and sparse must PROVE the S>=16 one
  (``results/scenario_scaling/`` is the committed proof).
- The race times the ROUTING STAGE under balanced top-1 load (pred supplied,
  ``i % S``): the classifier forward is identical in both candidates, and a
  random-init classifier's degenerate argmax would force every sparse row
  through the overflow fallback — measuring pathology, not dispatch.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable

from qdml_tpu.utils.tune_table import TableStore

SCHEMA = 1
DEFAULT_TABLE = os.path.join("results", "autotune", "routing_dispatch.json")
ENV_TABLE = "QDML_ROUTING_AUTOTUNE_TABLE"

# Below this scenario count the sparse path is not worth timing: S*C rows of
# sparse trunk work ~= capacity_factor*B barely undercuts S*B while paying
# rank/scatter/gather bookkeeping and a compiled-in fallback branch, and the
# committed S=3 artifacts pin dense as the reference-grid winner. The window
# keeps warmup at S=3 zero-extra-compile (only one eligible mode -> no race).
SPARSE_MIN_SCENARIOS = 6

_MODES = ("dense", "sparse")

# Table persistence/caching lives in the shared store (utils/tune_table.py);
# the module-level functions stay as this dispatcher's public API.
_STORE = TableStore(DEFAULT_TABLE, ENV_TABLE, "routing_dispatch_table",
                    "ops.dispatch_autotune")


def set_table_path(path: str | None) -> None:
    """Install (or clear) the process-wide routing-table location."""
    _STORE.set_path(path)


def table_path(path: str | None = None) -> str:
    return _STORE.path(path)


def table_key(
    platform: str,
    n_scenarios: int,
    bucket: int,
    dtype: str = "float32",
    capacity_factor: float = 1.25,
) -> str:
    """Entry key. ``capacity_factor`` is part of the raced SHAPE, not
    metadata: the sparse candidate does ~f·B rows of trunk work, so a winner
    raced at f=1.25 says nothing about f=4.0 — a re-knobbed deployment must
    re-race, never inherit a stale verdict."""
    return f"{platform}/S{n_scenarios}/b{bucket}/f{capacity_factor:g}/{dtype}"


def eligible_modes(n_scenarios: int) -> list[str]:
    """Dispatch modes worth racing at this scenario count. ``dense`` always
    (it is also the overflow fallback, so it must stay compiled-in anyway);
    ``sparse`` from :data:`SPARSE_MIN_SCENARIOS` up."""
    modes = ["dense"]
    if n_scenarios >= SPARSE_MIN_SCENARIOS:
        modes.append("sparse")
    return modes


def load_table(path: str | None = None) -> dict:
    """entries dict; {} on missing/corrupt/alien — a broken table degrades to
    dense, never raises (same contract as the quantum dispatcher)."""
    return _STORE.load(path)


def table_status(path: str | None = None) -> str:
    return _STORE.status(path)


def save_table(entries: dict, path: str | None = None) -> str:
    """Atomically persist the manifest-headed table; best-effort (serving
    must survive a read-only results dir)."""
    return _STORE.save(entries, path, schema=SCHEMA)


def invalidate_cache() -> None:
    _STORE.invalidate()


def lookup(
    n_scenarios: int,
    batch: int,
    dtype: str = "float32",
    path: str | None = None,
    capacity_factor: float = 1.25,
) -> str | None:
    """The tuned dispatch mode for this shape, or ``None`` (caller falls back
    to dense). Never raises, never benchmarks — safe anywhere."""
    try:
        import jax

        from qdml_tpu.quantum.autotune import batch_bucket

        entries = load_table(path)
        entry = entries.get(
            table_key(
                jax.default_backend(),
                n_scenarios,
                batch_bucket(batch),
                dtype,
                capacity_factor,
            )
        )
        if not isinstance(entry, dict):
            return None
        sel = entry.get("best_infer")
        if sel not in _MODES:
            return None
        if sel == "sparse" and n_scenarios < SPARSE_MIN_SCENARIOS:
            # an alien/hand-edited entry cannot force sparse below its window
            return None
        return sel
    except Exception:  # lint: disable=broad-except(dispatch lookup must degrade to the dense fallback on ANY table pathology — tuning can speed routing up, never crash it)
        return None


def route_candidates(
    apply_trunks: Callable,
    x,
    n_scenarios: int,
    capacity_factor: float,
) -> dict[str, tuple[Callable, tuple]]:
    """Build the two routing-stage candidates at this exact shape.

    ``apply_trunks``: ``(S, B', *feat) -> (S, B', D)`` — the stacked
    trunk+head apply with params closed over (the serve engine passes its
    live checkpoint; the bench a random init — routing cost is architecture-
    dependent, not weight-dependent). Both candidates consume the SAME
    balanced top-1 ``pred = i % S``: the load under which capacity buckets
    fill evenly, i.e. the steady state the capacity factor is sized for.
    """
    import jax
    import jax.numpy as jnp

    from qdml_tpu.ops.routing import select_expert, sparse_dispatch

    s = int(n_scenarios)
    pred = jnp.arange(x.shape[0], dtype=jnp.int32) % s

    def _dense(xx, pp):
        xs = jnp.broadcast_to(xx[None], (s,) + xx.shape)
        return select_expert(apply_trunks(xs), pp)

    def _sparse(xx, pp):
        out, _ = sparse_dispatch(
            apply_trunks, _dense, xx, pp, s, capacity_factor
        )
        return out

    return {
        "dense": (jax.jit(_dense), (x, pred)),
        "sparse": (jax.jit(_sparse), (x, pred)),
    }


def measure(
    candidates: dict[str, tuple[Callable, tuple]],
    budget_s: float = 0.2,
    max_reps: int = 30,
) -> dict[str, dict[str, Any]]:
    """Median-of-reps wall ms per candidate (the quantum tuner's timer — the
    two races must be comparable measurements). A candidate that fails to
    compile/run is recorded with its error and excluded from selection."""
    from qdml_tpu.quantum.autotune import _time_callable

    out: dict[str, dict[str, Any]] = {}
    for mode, (fn, args) in candidates.items():
        rec: dict[str, Any] = {}
        try:
            rec["infer_ms"] = round(_time_callable(fn, args, budget_s, max_reps), 4)
        except Exception as e:  # lint: disable=broad-except(candidate isolation: one mode failing to compile/run must not kill tuning for the other; the error is recorded in the table)
            rec["error"] = f"{type(e).__name__}: {e}"
        out[mode] = rec
    return out


def ensure_route(
    apply_trunks: Callable,
    x,
    n_scenarios: int,
    capacity_factor: float = 1.25,
    dtype: str = "float32",
    path: str | None = None,
    force: bool = False,
    budget_s: float = 0.2,
) -> dict:
    """Return this shape's table entry, racing and persisting it first if
    absent (or ``force``). With only one eligible mode NOTHING is timed —
    the entry records the winner-by-window with the exclusion reason, and
    the S=3 path stays zero-extra-compile."""
    import jax

    from qdml_tpu.quantum.autotune import batch_bucket

    platform = jax.default_backend()
    bucket = batch_bucket(x.shape[0])
    key = table_key(platform, n_scenarios, bucket, dtype, capacity_factor)
    entries = dict(load_table(path))
    entry = entries.get(key)
    if not force and isinstance(entry, dict) and entry.get("best_infer"):
        return entry
    modes = eligible_modes(n_scenarios)
    excluded = []
    if "sparse" not in modes:
        excluded.append(
            {
                "mode": "sparse",
                "reason": (
                    f"S={n_scenarios} < {SPARSE_MIN_SCENARIOS}: bucketing "
                    "bookkeeping cannot beat a fused all-trunks pass this "
                    "small (eligibility window, docs/SERVING.md)"
                ),
            }
        )
    raced = len(modes) > 1
    if not raced:
        cands: dict[str, dict[str, Any]] = {modes[0]: {"only_candidate": True}}
        best = modes[0]
    else:
        all_c = route_candidates(apply_trunks, x, n_scenarios, capacity_factor)
        cands = measure({m: all_c[m] for m in modes}, budget_s=budget_s)
        timed = {
            m: v["infer_ms"]
            for m, v in cands.items()
            if isinstance(v.get("infer_ms"), (int, float))
        }
        best = min(timed, key=timed.get) if timed else "dense"
    entry = {
        "key": key,
        "platform": platform,
        "n_scenarios": int(n_scenarios),
        "batch_bucket": bucket,
        "dtype": dtype,
        "capacity_factor": float(capacity_factor),
        "candidates": cands,
        "best_infer": best,
        "ts": round(time.time(), 3),
    }
    if excluded:
        entry["excluded"] = excluded
    if raced:
        # window-only decisions carry no timings worth caching — persisting
        # them would turn every reference-grid warmup (tests included) into
        # a table write; the entry is still returned for the warmup record
        entries[key] = entry
        save_table(entries, path)
    return entry
