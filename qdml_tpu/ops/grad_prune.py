"""On-chip-QNN gradient pruning as an optax GradientTransformation.

Reference behaviour (``Estimators_QuantumNAT_onchipQNN.py:205-228``): after
``loss.backward()`` and before ``optimizer.step()``, every gradient element
with ``|g| <= threshold`` (default 0.1, ``:119``) across ALL named parameters
is zeroed; the pruning ratio is logged when it exceeds 10%.

Here the same operation is a pure transform placed at the FRONT of the
optimizer chain (prune, then Adam/AdamW sees the pruned gradients — matching
the reference's backward -> prune -> step order,
``Runner_P128_QuantumNAT_onchipQNN.py:364-369``). The observed pruning ratio is
kept in the transform state for metric logging instead of printing.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class GradientPruneState(NamedTuple):
    prune_ratio: jnp.ndarray  # fraction of gradient elements zeroed last step


def gradient_prune(
    threshold: float = 0.1, mode: str = "absolute"
) -> optax.GradientTransformation:
    """Zero small-magnitude gradient elements.

    ``mode="absolute"`` (reference parity): zero ``|g| <= threshold``. At the
    reference's shipped 0.1 this zeroes every Adam-scale NLL gradient and
    freezes training (measured: ``results/noise_robustness/grad_prune/``) —
    the feature only looks benign there because it ships disabled.

    ``mode="quantile"``: ``threshold`` in [0, 1) is the FRACTION of gradient
    elements to prune — the per-step cutoff is the global
    ``threshold``-quantile of ``|g|`` across the whole tree, so the pruning
    ratio is scale-free and survives Adam-scale gradients. This is the
    usable form of the on-chip-QNN idea (measure fewer/cheaper gradients on
    hardware): ``threshold=0.5`` keeps the largest half each step.
    """
    if mode not in ("absolute", "quantile"):
        raise ValueError(f"gradient_prune mode must be absolute|quantile, got {mode!r}")
    if mode == "quantile" and not 0.0 <= threshold < 1.0:
        raise ValueError(f"quantile threshold must be in [0, 1), got {threshold}")

    def init_fn(params):
        del params
        return GradientPruneState(prune_ratio=jnp.zeros((), jnp.float32))

    def update_fn(updates, state, params=None):
        del params
        if mode == "quantile":
            flat = jnp.concatenate(
                [jnp.abs(g).reshape(-1) for g in jax.tree.leaves(updates)]
            )
            cutoff = jnp.quantile(flat, threshold)
            # Inclusive keep: elements AT the cutoff survive, so
            # threshold=0.0 is a no-op (cutoff = min|g|) and tied
            # magnitudes under-prune instead of over-pruning — a tie at
            # the cutoff with a strict mask could zero 100% of an
            # all-equal gradient, the exact freeze this mode prevents.
            def keep(g):
                return jnp.abs(g) >= cutoff

        else:
            # reference parity: |g| <= threshold is zeroed (strict >)
            def keep(g):
                return jnp.abs(g) > threshold

        masks = jax.tree.map(lambda g: keep(g).astype(g.dtype), updates)
        pruned = jax.tree.map(lambda g, m: g * m, updates, masks)
        total = sum(jnp.size(m) for m in jax.tree.leaves(masks))
        kept = sum(jnp.sum(m) for m in jax.tree.leaves(masks))
        ratio = 1.0 - kept / jnp.asarray(total, jnp.float32)
        return pruned, GradientPruneState(prune_ratio=ratio)

    return optax.GradientTransformation(init_fn, update_fn)
