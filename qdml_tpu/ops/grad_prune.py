"""On-chip-QNN gradient pruning as an optax GradientTransformation.

Reference behaviour (``Estimators_QuantumNAT_onchipQNN.py:205-228``): after
``loss.backward()`` and before ``optimizer.step()``, every gradient element
with ``|g| <= threshold`` (default 0.1, ``:119``) across ALL named parameters
is zeroed; the pruning ratio is logged when it exceeds 10%.

Here the same operation is a pure transform placed at the FRONT of the
optimizer chain (prune, then Adam/AdamW sees the pruned gradients — matching
the reference's backward -> prune -> step order,
``Runner_P128_QuantumNAT_onchipQNN.py:364-369``). The observed pruning ratio is
kept in the transform state for metric logging instead of printing.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class GradientPruneState(NamedTuple):
    prune_ratio: jnp.ndarray  # fraction of gradient elements zeroed last step


def gradient_prune(threshold: float = 0.1) -> optax.GradientTransformation:
    """Zero gradient elements with ``|g| <= threshold``."""

    def init_fn(params):
        del params
        return GradientPruneState(prune_ratio=jnp.zeros((), jnp.float32))

    def update_fn(updates, state, params=None):
        del params
        masks = jax.tree.map(lambda g: (jnp.abs(g) > threshold).astype(g.dtype), updates)
        pruned = jax.tree.map(lambda g, m: g * m, updates, masks)
        total = sum(jnp.size(m) for m in jax.tree.leaves(masks))
        kept = sum(jnp.sum(m) for m in jax.tree.leaves(masks))
        ratio = 1.0 - kept / jnp.asarray(total, jnp.float32)
        return pruned, GradientPruneState(prune_ratio=ratio)

    return optax.GradientTransformation(init_fn, update_fn)
