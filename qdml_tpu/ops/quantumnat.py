"""QuantumNAT noise injection as a pure-functional parameter perturbation.

Reference behaviour (``Estimators_QuantumNAT_onchipQNN.py:176-196``, after
QuantumNAT, arXiv:2110.11331): during training, clone the quantum parameters,
add ``noise_level * randn_like(param)``, forward through the circuit, restore
the originals. The gradient IS taken at the noisy point; the optimizer state
stays at the clean point (SURVEY.md §3.4).

In JAX this is simply evaluating the loss at ``params + sigma * normal(key)``
— the in-place mutate/restore dance does not exist. :class:`QSCP128` does this
inline for its circuit weights; :func:`perturb` is the generic tree-level
version for perturbing arbitrary parameter subtrees (e.g. noise-level sweeps,
BASELINE.json config 5).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp


def perturb(
    params: Any,
    key: jax.Array,
    noise_level: float | jnp.ndarray,
    where: Callable[[tuple, jnp.ndarray], bool] | None = None,
) -> Any:
    """Return ``params + noise_level * N(0, 1)`` on selected leaves.

    ``where(path, leaf) -> bool`` selects which leaves to perturb (default:
    all floating-point leaves).
    """
    leaves = jax.tree_util.tree_leaves_with_path(params)
    keys = jax.random.split(key, len(leaves))

    flat = {}
    for (path, leaf), k in zip(leaves, keys):
        sel = jnp.issubdtype(jnp.result_type(leaf), jnp.floating) and (
            where is None or where(path, leaf)
        )
        flat[path] = leaf + noise_level * jax.random.normal(k, jnp.shape(leaf), leaf.dtype) if sel else leaf

    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params), [flat[p] for p, _ in leaves]
    )
