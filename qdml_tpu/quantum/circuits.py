"""The reference's variational circuit, TPU-native, with two execution paths.

Circuit (reference ``Estimators_QuantumNAT_onchipQNN.py:125-142``):

1. ``AngleEmbedding(inputs, rotation="Y")`` — per-sample RY(angle_i) on wire i,
2. per layer l in [0, n_layers): RY(w[l,i,0]) then RZ(w[l,i,1]) on each wire,
   then the entangling ring CNOT(i, i+1) for i < n-1 plus CNOT(n-1, 0),
3. measure <PauliZ_i> on every wire.

Weight shape ``(n_layers, n_qubits, 2)`` (reference ``:145``); defaults
n_qubits=6, n_layers=3 (reference ``:108``); published variants use 4/6/8
qubits (Loss Curve.png legend).

Execution paths:

- ``tensor``: gates applied on the ``(batch, 2**n)`` statevector via axis
  reshapes — O(n) cheap ops per layer, scales to n ~ 14 single-chip.
- ``dense``: the whole weight-dependent ansatz is precompiled into ONE
  ``(2**n, 2**n)`` unitary per step (Kronecker composition + ring
  permutation); the RY embedding collapses to a closed-form REAL product
  state (:func:`~qdml_tpu.quantum.statevector.ry_product_state`), so each
  batch costs two real MXU matmuls against U^T plus the sign contraction.
  Best for the reference's 4-8 qubit regime where ``2**n`` is tiny compared
  to the batch.
- ``dense_fused``: the dense math with Qandle-style gate-matrix caching /
  layer fusion (arXiv 2404.09213): no per-gate 2x2 matrix is ever built —
  each layer's fused rotation unitary comes from one vectorized trig shot,
  a layer-batched real Kronecker chain and a phase einsum over the CACHED
  ``z_signs`` structure, with the cached ring permutation applied per layer
  (:func:`fused_layer_unitaries`). Registered as a first-class autotune
  impl so the dispatcher proves where it wins.
- ``pallas``: the dense math as ONE fused TPU kernel per batch tile —
  in-kernel embedding, blockdiag unitary matmul, <Z> contraction
  (:mod:`qdml_tpu.quantum.pallas_kernels`).
- ``pallas_circuit``: the gate-chain math as ONE VMEM-resident kernel per
  batch tile — in-kernel embedding, all L layers walked by an in-kernel loop
  with the statevector pinned in VMEM, adjoint backward
  (:func:`qdml_tpu.quantum.pallas_kernels.fused_circuit_expvals`). Scales
  past the dense unitary build (n ~ 7-12). ``pallas_tensor`` is the
  deprecated pre-v2 alias.
- ``sharded_statevector``: the 2^n amplitudes partitioned over the mesh's
  ``model`` axis inside one ``shard_map`` region; gates on sharded qubits
  are ``ppermute`` partner exchanges, ``<Z>`` one ``psum``
  (:mod:`qdml_tpu.quantum.sharded`; ``sharded`` is the deprecated alias).
- ``mps``: bond-dimension-chi matrix-product-state simulation — O(n * chi^2)
  state per sample instead of 2^n, exact at chi >= 2^(n/2), the capacity
  impl past every statevector window (:mod:`qdml_tpu.quantum.mps`).

All paths are pure jittable functions of ``(angles, weights)`` and
differentiable by JAX AD; they agree to float32 precision (tested against an
independent numpy simulator in ``tests/test_quantum.py``). Which one runs is
the dispatcher's job: :func:`resolve_impl` consults the measured autotune
table (:mod:`qdml_tpu.quantum.autotune`) before the static heuristic.
"""

from __future__ import annotations

import jax.numpy as jnp

from qdml_tpu.quantum import statevector as sv
from qdml_tpu.utils.complexops import CArr, ceinsum, ckron

VALID_BACKENDS = (
    "auto",
    "tensor",
    "dense",
    "dense_fused",
    "sharded",  # deprecated alias for sharded_statevector (pre-scaling name)
    "sharded_statevector",
    "mps",
    "pallas",
    "pallas_circuit",
    "pallas_tensor",  # deprecated alias for pallas_circuit (pre-v2 name)
)

# Deprecated impl names -> their canonical spelling. Aliases stay accepted
# everywhere (configs, checkpoints, autotune tables) but every resolution
# funnels through here so the rest of the engine sees ONE name per impl.
_IMPL_ALIASES = {"pallas_tensor": "pallas_circuit", "sharded": "sharded_statevector"}


def canonical_impl(name: str) -> str:
    """Normalize an impl/backend name to its canonical spelling.

    Raises ``ValueError`` on names outside :data:`VALID_BACKENDS` — the one
    choke point where a config/checkpoint/table naming an impl this build
    does not know produces a diagnosable error instead of a downstream
    ``KeyError`` deep in dispatch."""
    if name not in VALID_BACKENDS:
        raise ValueError(f"unknown circuit impl {name!r}; want one of {VALID_BACKENDS}")
    return _IMPL_ALIASES.get(name, name)


def rot_gate(w_ry: jnp.ndarray, w_rz: jnp.ndarray) -> CArr:
    """Single-qubit RZ(w_rz) @ RY(w_ry) — RY applied first, as in the reference
    per-wire order (``Estimators...py:132-134``). Scalar weights -> (2, 2) CArr."""
    c0, s0 = jnp.cos(w_ry / 2), jnp.sin(w_ry / 2)
    c1, s1 = jnp.cos(w_rz / 2), jnp.sin(w_rz / 2)
    re = jnp.stack(
        [jnp.stack([c1 * c0, -c1 * s0]), jnp.stack([c1 * s0, c1 * c0])]
    )
    im = jnp.stack(
        [jnp.stack([-s1 * c0, s1 * s0]), jnp.stack([s1 * s0, s1 * c0])]
    )
    return CArr(re, im)


def angle_embed(psi: CArr, angles: jnp.ndarray, n: int) -> CArr:
    """AngleEmbedding with Y rotations: angles (..., n) per sample."""
    for q in range(n):
        psi = sv.apply_ry(psi, n, q, angles[..., q])
    return psi


def apply_ansatz_tensor(psi: CArr, weights: jnp.ndarray, n: int, n_layers: int) -> CArr:
    """Gate-by-gate ansatz application on the statevector tensor.

    Gate-matrix caching (Qandle, arXiv 2404.09213): the per-gate trig is
    derived ONCE for the whole circuit — one vectorized cos/sin pair over the
    ``(L, n, 2)`` weight tensor — and each gate application reads its cached
    ``(cos, sin)`` scalar instead of re-deriving trig per gate (2Ln tiny
    transcendental ops collapse into 2 fused ones)."""
    ring = jnp.asarray(sv.ring_cnot_perm(n))
    half = 0.5 * weights
    cos_t, sin_t = jnp.cos(half), jnp.sin(half)  # (L, n, 2) each, one shot
    for l in range(n_layers):
        for q in range(n):
            psi = sv.apply_ry_cs(psi, n, q, cos_t[l, q, 0], sin_t[l, q, 0])
            psi = sv.apply_rz_cs(psi, n, q, cos_t[l, q, 1], sin_t[l, q, 1])
        psi = sv.apply_perm(psi, ring)
    return psi


def ansatz_unitary(weights: jnp.ndarray, n: int, n_layers: int) -> CArr:
    """Compile the full weight-dependent ansatz into one (2**n, 2**n) unitary.

    Layer unitary = RingPerm . (u_0 x u_1 x ... x u_{n-1}) with qubit 0 as the
    most significant factor; total = U_{L-1} ... U_0.

    This is the UNFUSED reference formulation — one 2x2 gate matrix built per
    (layer, qubit) and kron'd in sequence. The hot paths dispatch the fused
    twin (:func:`fused_ansatz_unitary`, impl ``dense_fused``); this one stays
    as the independently-derived construction the equivalence pins compare
    against (``tests/test_quantum.py``).
    """
    ring = sv.ring_cnot_perm(n)
    total: CArr | None = None
    for l in range(n_layers):
        u = rot_gate(weights[l, 0, 0], weights[l, 0, 1])  # lint: disable=gate-matrix-in-loop(the unfused reference construction the dense_fused equivalence pins compare against; hot paths dispatch fused_ansatz_unitary)
        for q in range(1, n):
            u = ckron(u, rot_gate(weights[l, q, 0], weights[l, q, 1]))  # lint: disable=gate-matrix-in-loop(unfused reference twin of fused_layer_unitaries — see above)
        # ring perm acts on rows: (P M)[y, :] = M[src[y], :]
        u = CArr(u.re[ring, :], u.im[ring, :])
        total = u if total is None else ceinsum("ij,jk->ik", u, total)
    assert total is not None
    return total


def fused_layer_unitaries(weights: jnp.ndarray, n: int, n_layers: int) -> CArr:
    """All L layer unitaries at once from the parameter vector — gate-matrix
    caching / layer fusion (Qandle, arXiv 2404.09213) applied to this ansatz.

    Structure exploited (vs :func:`ansatz_unitary`'s per-gate kron chain):

    - the whole circuit's trig comes from ONE vectorized cos/sin pair over the
      ``(L, n, 2)`` weight tensor (2 fused ops, not 2Ln scalar gate builds);
    - the RY half of every layer is REAL, so the rotation kron is a real
      doubling chain batched over all L layers simultaneously;
    - the RZ half is DIAGONAL: its phase per basis state is an einsum of the
      RZ half-angles against the CACHED ``z_signs`` bit-sign table
      (``phase[l, i] = -0.5 * sum_q signs[i, q] * w_rz[l, q]``) — the cached
      structure, rebuilt never, contracted once per step;
    - the ring-CNOT entangler is the cached composed permutation
      (:func:`~qdml_tpu.quantum.statevector.ring_cnot_perm`) applied to rows.

    Returns a ``(L, 2**n, 2**n)`` CArr; layer l equals
    ``RingPerm . (RZ(w[l,:,1]) RY(w[l,:,0]))^{(x) n}`` exactly (same qubit-0
    most-significant convention), to f32 rounding.
    """
    dim = 1 << n
    half = 0.5 * weights  # (L, n, 2)
    c, s = jnp.cos(half), jnp.sin(half)
    # Real RY kron chain, batched over layers: (L, 1, 1) -> (L, dim, dim) by
    # doubling, qubit 0 outermost (most significant) like the ckron chain.
    kron = jnp.ones((n_layers, 1, 1), weights.dtype)
    d = 1
    for q in range(n):
        # (L, 2, 2) RY matrix elements for THIS qubit, from the cached trig
        m = jnp.stack(
            [
                jnp.stack([c[:, q, 0], -s[:, q, 0]], axis=-1),
                jnp.stack([s[:, q, 0], c[:, q, 0]], axis=-1),
            ],
            axis=-2,
        )
        kron = kron[:, :, None, :, None] * m[:, None, :, None, :]
        d *= 2
        kron = kron.reshape(n_layers, d, d)
    # RZ diagonal phase per basis-state row: einsum over the cached sign
    # table (z_signs[i, q] = +1 when bit q of i is 0). RZ contributes
    # e^{-i w/2} on the 0-row and e^{+i w/2} on the 1-row of each qubit.
    signs = jnp.asarray(sv.z_signs(n))  # (dim, n), cached structure
    phase = -0.5 * jnp.einsum("iq,lq->li", signs, weights[:, :, 1])  # (L, dim)
    re = jnp.cos(phase)[:, :, None] * kron
    im = jnp.sin(phase)[:, :, None] * kron
    # ring perm acts on rows: (P M)[y, :] = M[src[y], :]
    ring = sv.ring_cnot_perm(n)
    return CArr(re[:, ring, :], im[:, ring, :])


def fused_ansatz_unitary(weights: jnp.ndarray, n: int, n_layers: int) -> CArr:
    """The full ansatz unitary from :func:`fused_layer_unitaries`: total =
    U_{L-1} ... U_0, composed by L-1 complex MXU matmuls. Numerically
    equivalent to :func:`ansatz_unitary` (pinned in ``tests/test_quantum.py``);
    built without any per-gate matrix construction."""
    layers = fused_layer_unitaries(weights, n, n_layers)
    total = CArr(layers.re[0], layers.im[0])
    for l in range(1, n_layers):
        total = ceinsum("ij,jk->ik", CArr(layers.re[l], layers.im[l]), total)
    return total


def resolve_backend(backend: str, n_qubits: int) -> str:
    """Resolve ``auto`` to a concrete execution path WITHOUT measurements.

    This is the static fallback: the dense per-ansatz unitary (MXU matmuls)
    up to ~10 qubits, the gate-wise tensor path to ~14 (its 2^n x 2^n
    unitary build dominates dense there), and the bond-chi MPS simulator
    past that — the full statevector itself is the wall at n > ~14, and the
    MPS impl is the one candidate that runs anywhere (the mesh-sharded
    statevector needs a multi-device mesh this helper cannot assume; the
    autotuner offers it where the topology allows, docs/QUANTUM.md).

    The kernel-vs-XLA choice is deliberately NOT made here anymore. The old
    static TPU promotion of the whole-circuit Pallas kernel rested on one
    round's A/B while the committed bench showed the same kernel LOSING the
    train step (BENCH_r05: qsc_pallas 9.76k vs qsc_dense 10.4k sps) — a
    fixed claim cannot arbitrate a shape/platform-dependent race. Measured
    dispatch lives in :mod:`qdml_tpu.quantum.autotune`; ``auto`` here means
    "the safe XLA formulation for this qubit count", and
    :func:`resolve_impl` consults the autotune table before falling back to
    this heuristic.
    """
    if backend != "auto":
        return backend
    if n_qubits <= 10:
        return "dense"
    return "tensor" if n_qubits <= 14 else "mps"


def resolve_impl(
    impl: str,
    backend: str,
    n_qubits: int,
    n_layers: int,
    batch: int,
    mode: str = "train",
) -> str:
    """Full dispatch resolution for one concrete circuit shape.

    Precedence: an explicit ``impl`` (the ``quantum.impl`` config override)
    wins outright; then an explicit legacy ``backend``; then the autotuned
    selection table for this exact ``(platform, n_qubits, n_layers,
    batch-bucket, mode)``; then :func:`resolve_backend`'s static heuristic.
    A missing/corrupt/unpopulated table degrades to the heuristic (which
    bottoms out at XLA dense in the small-n regime) — never an exception and
    never an unmeasured kernel promotion. A fallback caused by a table
    PATHOLOGY (corrupt/alien file, entry naming an impl this build cannot
    dispatch) is no longer invisible: it emits one structured
    ``autotune_fallback`` telemetry record per (table, shape, reason) into
    the active sink, so run JSONLs show WHY the heuristic ran.
    """
    if impl not in ("", "auto"):
        return canonical_impl(impl)
    if backend != "auto":
        return canonical_impl(backend)
    from qdml_tpu.quantum import autotune

    sel, reason = autotune.lookup_reason(n_qubits, n_layers, batch, mode=mode)
    if sel is not None:
        return sel
    fallback = resolve_backend("auto", n_qubits)
    if reason is not None:
        autotune.emit_fallback(reason, n_qubits, n_layers, batch, mode, fallback)
    return fallback


def run_circuit(
    angles: jnp.ndarray,
    weights: jnp.ndarray,
    n_qubits: int,
    n_layers: int,
    backend: str = "dense",
    impl: str = "auto",
    mode: str = "train",
    mps_chi: int | None = None,
) -> jnp.ndarray:
    """Full reference circuit: angles (..., n) -> per-wire <Z> (..., n).

    ``impl`` is the autotune-aware dispatcher override (``quantum.impl``);
    with both ``impl`` and ``backend`` at ``"auto"`` the measured selection
    table picks the implementation for this exact shape (``mode`` selects
    the forward-only vs forward+backward winner). Shapes are static under
    jit, so the lookup is a trace-time decision baked into the compiled
    program — exactly once per (shape, impl) compilation. ``mps_chi``
    (``quantum.mps_chi``) only matters when the ``mps`` impl runs: the bond
    dimension of its truncated tensor-network state.
    """
    import numpy as _np

    batch = int(_np.prod(angles.shape[:-1])) if angles.ndim > 1 else 1
    backend = resolve_impl(impl, backend, n_qubits, n_layers, batch, mode=mode)
    if backend in ("dense", "dense_fused"):
        # Closed-form embedding: the RY-embedded state is a REAL product
        # state (sv.ry_product_state), so the whole circuit is two real
        # matmuls against U^T plus the sign contraction — no gate chain on
        # the 2^n tensor, half the matmul work of a complex-LHS product.
        # "dense_fused" builds the unitary with gate-matrix caching / layer
        # fusion (fused_ansatz_unitary: one vectorized trig shot + batched
        # real kron + cached-sign-table phase einsum) instead of the per-gate
        # kron chain; same math, registered as its own impl so the autotuner
        # PROVES it wins instead of this module assuming it.
        build = fused_ansatz_unitary if backend == "dense_fused" else ansatz_unitary
        u = build(weights, n_qubits, n_layers)
        amp = sv.ry_product_state(angles, n_qubits)
        psi = CArr(
            jnp.einsum("...i,ji->...j", amp, u.re),
            jnp.einsum("...i,ji->...j", amp, u.im),
        )
        return sv.expvals_z(psi, n_qubits)
    if backend == "pallas":
        # Whole-circuit fused kernel: in-kernel product-state embedding +
        # blockdiag unitary matmul + |.|^2 <Z> contraction, one pallas_call
        # (qdml_tpu.quantum.pallas_kernels.fused_qsc_expvals).
        from qdml_tpu.quantum.pallas_kernels import fused_qsc_expvals

        u = ansatz_unitary(weights, n_qubits, n_layers)
        return fused_qsc_expvals(angles, u, n_qubits)
    if backend == "sharded_statevector":
        from qdml_tpu.quantum.sharded import run_circuit_sharded

        return run_circuit_sharded(angles, weights, n_qubits, n_layers)
    if backend == "mps":
        # Bond-chi MPS simulation (quantum/mps.py): the capacity impl past
        # the dense/pallas windows — O(n * chi^2) state per sample instead
        # of 2^n amplitudes, exact when chi >= 2^(n/2).
        from qdml_tpu.quantum.mps import DEFAULT_CHI, mps_circuit

        return mps_circuit(
            angles, weights, n_qubits, n_layers, chi=mps_chi or DEFAULT_CHI
        )
    if backend in ("pallas_circuit", "pallas_tensor"):
        # Whole-circuit VMEM-resident kernel: in-kernel embedding + L-layer
        # rotation/entangler chain in ONE pallas_call per batch tile, adjoint
        # backward (pallas_kernels.fused_circuit_expvals). Replaces the v1
        # per-layer kernel loop, which launched 2L pallas_calls per circuit
        # and bounced the statevector through HBM between every layer.
        from qdml_tpu.quantum.pallas_kernels import fused_circuit_expvals

        return fused_circuit_expvals(angles, weights, n_qubits, n_layers)
    psi = sv.zero_state(n_qubits, angles.shape[:-1])
    psi = angle_embed(psi, angles, n_qubits)
    if backend == "tensor":
        psi = apply_ansatz_tensor(psi, weights, n_qubits, n_layers)
    else:
        raise ValueError(f"unknown backend {backend!r}; want one of {VALID_BACKENDS}")
    return sv.expvals_z(psi, n_qubits)
