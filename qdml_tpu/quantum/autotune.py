"""Autotuned implementation dispatch for the quantum circuit hot path.

The repo carries SEVEN interchangeable circuit implementations (XLA dense and
its gate-matrix-fused twin, whole-circuit fused Pallas, VMEM-resident
multi-layer Pallas, gate-wise tensor, the bond-chi MPS tensor network, and
the mesh-sharded statevector) and its own bench history proves
the winner is shape- and platform-dependent: BENCH_r05 shows ``qsc_pallas``
LOSING the train step to ``qsc_dense`` (9.76k vs 10.4k sps) at the very shape
the old static heuristic promoted the kernel for. Nothing structural
guaranteed the winning implementation was the one dispatched in training,
serving or the NAT sweep — this module makes that guarantee measured.

Qandle's (arXiv 2404.09213) statevector lesson — cache what is reusable,
never re-derive per call — applied to dispatch: a micro-benchmark times every
eligible implementation ONCE per ``(platform, n_qubits, n_layers,
batch-bucket, dtype)`` key, the selection persists in a manifest-headed JSON
table, and every later trace of that shape reads the table (in-process cache,
one disk load) instead of guessing.

Contracts:

- ``ensure()`` (the tuner) is HOST-side and eager: train loops call it before
  building their jitted step, serve warmup calls it per AOT bucket — it never
  runs inside a trace and never on the serve request path.
- ``lookup()`` is read-only and cheap: table miss / missing file / corrupt
  file / unreadable entry all return ``None`` (the caller falls back to XLA
  dense via ``circuits.resolve_impl``) — autotuning can make dispatch faster,
  never make it raise.
- The table records the per-candidate timings next to the winner, so every
  artifact that says "impl X ran" can also say what X beat and by how much.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Sequence

SCHEMA = 1
DEFAULT_TABLE = os.path.join("results", "autotune", "qsc_impl.json")
ENV_TABLE = "QDML_QSC_AUTOTUNE_TABLE"

# In-process table cache: {abspath -> entries dict}. Written only by the
# host-side ensure()/lookup() helpers below — jit-reachable code never touches
# this module directly (circuits.resolve_impl calls in at TRACE time, where
# the selection is a static, deliberately-baked-in decision).
_CACHE: dict[str, dict] = {}
# How the cached entries were obtained: "ok" | "missing" (no file — the
# normal cold state, not a pathology) | "corrupt" (unparseable JSON) |
# "alien" (parsed, but not a selection table) | "unreadable" (I/O error).
# Everything except ok/missing is a SILENT-FALLBACK hazard the dispatcher
# surfaces as an `autotune_fallback` telemetry record (emit_fallback).
_STATUS: dict[str, str] = {}
# (table, key, reason) triples already reported — the lookup fires once per
# circuit trace, and one structured record per distinct pathology is signal
# where one per trace would be noise.
_FALLBACK_EMITTED: set[tuple] = set()
# Process-wide active table location, installed by prewarm() from
# quantum.autotune_table. The trace-time lookup has no config in scope (it
# fires deep inside model.apply), so a configured custom path must become
# THE path for the process — otherwise the tuner would write the winner to
# the custom file while dispatch reads the default one and silently stays
# on the dense fallback.
_ACTIVE_PATH: str | None = None

# Winners a table entry may name: concrete impls only — "auto" would recurse
# through the resolver. "sharded_statevector" is dispatchable but carries a
# topology precondition (>= 2 devices on the model axis); lookup() re-checks
# it at READ time so a table written on an 8-device mesh degrades to the
# heuristic on a 1-device process instead of dispatching a collective program
# with nobody to exchange with.
_DISPATCHABLE = frozenset(
    {
        "dense",
        "dense_fused",
        "pallas",
        "pallas_circuit",
        "pallas_tensor",
        "tensor",
        "mps",
        "sharded",
        "sharded_statevector",
    }
)

# Windows past which a full-statevector formulation stops being a sane
# candidate: the dense 2^n x 2^n unitary build caps at DENSE_MAX_QUBITS, the
# gate-wise tensor path at TENSOR_MAX_QUBITS (2^n amplitudes per sample
# still), and past that only the compressed (mps) / partitioned
# (sharded_statevector) states remain (docs/QUANTUM.md eligibility matrix).
DENSE_MAX_QUBITS = 12
TENSOR_MAX_QUBITS = 14
SHARDED_MIN_QUBITS = 10
MPS_MIN_QUBITS = 13


class ImplIneligibleError(ValueError):
    """A pinned circuit impl cannot run at this qubit count / topology.

    Raised where a configuration or checkpoint FORCES an impl (rather than
    letting the dispatcher choose) that :func:`impl_eligible` rejects — e.g.
    ``sharded_statevector`` restored on a single-device process, or ``dense``
    pinned at n > 12. Typed so restore/startup paths can fail with the
    eligibility reason instead of a KeyError (or a collective program with
    nobody to exchange with) deep in dispatch."""


def model_axis_devices() -> int:
    """Devices the default model mesh would span: the largest power of two
    <= the local device count (mirrors ``sharded._default_model_mesh``).
    1 on a single-device process — i.e. "no sharded candidate"."""
    import jax

    n = jax.device_count()
    k = 1
    while k * 2 <= n:
        k *= 2
    return k


def impl_eligible(
    impl: str, n_qubits: int, devices_on_model: int | None = None
) -> tuple[bool, str | None]:
    """Hard runnability of ``impl`` at this qubit count/topology: ``(ok,
    reason_when_not)``. This is the CAPACITY check (can this impl execute at
    all without an absurd footprint or a missing mesh), not the latency race
    — ``eligible_impls`` layers the worth-timing windows on top. Used by the
    checkpoint reconcile to turn "restored a sharded_statevector pin on one
    device" into a typed error instead of a downstream crash."""
    from qdml_tpu.quantum.circuits import canonical_impl

    impl = canonical_impl(impl)  # unknown names raise ValueError here
    if impl in ("dense", "dense_fused", "pallas") and n_qubits > DENSE_MAX_QUBITS:
        return False, (
            f"the dense (2^n x 2^n) unitary build is capped at n <= "
            f"{DENSE_MAX_QUBITS}; n={n_qubits}"
        )
    if impl == "pallas_circuit" and n_qubits > DENSE_MAX_QUBITS:
        return False, (
            f"the VMEM-resident kernel window ends at n <= {DENSE_MAX_QUBITS}; "
            f"n={n_qubits}"
        )
    if impl == "tensor" and n_qubits > TENSOR_MAX_QUBITS:
        return False, (
            f"the full 2^n statevector per sample is capped at n <= "
            f"{TENSOR_MAX_QUBITS}; n={n_qubits} needs mps or sharded_statevector"
        )
    if impl == "sharded_statevector":
        devs = model_axis_devices() if devices_on_model is None else devices_on_model
        if devs < 2:
            return False, (
                "sharded_statevector partitions the amplitudes over the mesh's "
                f"model axis and needs >= 2 devices; this topology has {devs}"
            )
    return True, None


def set_table_path(path: str | None) -> None:
    """Install (or clear, with None/"") the process-wide table location."""
    global _ACTIVE_PATH
    _ACTIVE_PATH = os.path.abspath(path) if path else None


def table_path(path: str | None = None) -> str:
    """Resolve the selection-table location: explicit arg > configured
    process-wide path (set_table_path, via quantum.autotune_table) > env >
    default."""
    return os.path.abspath(
        path or _ACTIVE_PATH or os.environ.get(ENV_TABLE) or DEFAULT_TABLE
    )


def batch_bucket(batch: int) -> int:
    """Power-of-two batch bucket (the serve engine's bucketing rule): one
    table entry covers every batch padded up to the same bucket."""
    b = 1
    while b < max(1, int(batch)):
        b *= 2
    return b


def table_key(
    platform: str, n_qubits: int, n_layers: int, bucket: int, dtype: str = "float32"
) -> str:
    return f"{platform}/n{n_qubits}/L{n_layers}/b{bucket}/{dtype}"


def eligible_impls(
    n_qubits: int, platform: str, devices_on_model: int | None = None
) -> list[str]:
    """Implementations worth timing at this qubit count/platform/topology.

    ``platform`` keys the caller's table entries but deliberately does NOT
    filter the pallas kernels here: off-TPU they run in interpret mode, and
    the equivalence/dispatch tests race them there on purpose. Callers with
    a timing budget to protect (the qubit-scaling sweep) exclude them at
    their own layer with a recorded per-point ``excluded`` reason —
    exclusion is an artifact policy, not an eligibility fact.

    - ``dense``: n <= 12 — the 2^n x 2^n unitary build is the wall past
      that (it used to be "always"; the scaling subsystem made the cap
      explicit so every n > 12 candidate set is non-dense by construction);
    - ``dense_fused`` (gate-matrix-cached / layer-fused unitary build,
      ``circuits.fused_ansatz_unitary``): wherever dense is — it races the
      unfused twin so the table PROVES where the fused build wins instead of
      the heuristic assuming it;
    - ``pallas`` (whole-circuit blockdiag-unitary kernel): dim <= 256 — its
      (2D, 2D) VMEM operand grows quadratically past n=8;
    - ``pallas_circuit`` (VMEM-resident multi-layer kernel): 128 <= dim <=
      4096 — below one lane tile it falls back to the XLA twin anyway, so
      timing it would just re-measure dense math;
    - ``tensor``: 9 <= n <= 14 — where the dense unitary build dominates but
      a full per-sample statevector still fits;
    - ``mps`` (bond-chi tensor network): n >= 13 — it races ``tensor`` over
      the 13-14 crossover window and is the ONLY single-device candidate
      past n = 14, where every full-statevector formulation is out;
    - ``sharded_statevector``: n >= 10 AND ``devices_on_model`` >= 2 — the
      amplitude-partitioned statevector only exists on a multi-device mesh,
      so the tuner includes it exactly when the caller proves the topology
      (pass :func:`model_axis_devices`; ``None`` keeps the topology-blind
      behavior and excludes it).
    """
    dim = 1 << n_qubits
    impls = []
    if n_qubits <= DENSE_MAX_QUBITS:
        impls += ["dense", "dense_fused"]
    if dim <= 256:
        impls.append("pallas")
    if 128 <= dim <= 4096:
        impls.append("pallas_circuit")
    if 9 <= n_qubits <= TENSOR_MAX_QUBITS:
        impls.append("tensor")
    if n_qubits >= MPS_MIN_QUBITS:
        impls.append("mps")
    if devices_on_model is not None and devices_on_model >= 2 and n_qubits >= SHARDED_MIN_QUBITS:
        impls.append("sharded_statevector")
    return impls


def autotune_enabled(setting: str, platform: str | None = None) -> bool:
    """``quantum.autotune`` resolution: "on" / "off" / "auto" (tune only on a
    real accelerator — the CPU test/fallback backend keeps the dense
    fallback and pays zero tuning compiles)."""
    s = (setting or "auto").lower()
    if s in ("on", "1", "true", "yes"):
        return True
    if s in ("off", "0", "false", "no"):
        return False
    if platform is None:
        import jax

        platform = jax.default_backend()
    return platform != "cpu"


# ---------------------------------------------------------------------------
# Persistence (manifest-headed, corruption-tolerant)
# ---------------------------------------------------------------------------


def load_table(path: str | None = None) -> dict:
    """entries dict for the table at ``path``; {} on missing/corrupt/alien
    files — a broken table must degrade to the dense fallback, not raise.
    WHY it degraded is remembered per path (:func:`table_status`) so the
    dispatcher can tell a normal cold start from a pathology worth a
    structured ``autotune_fallback`` record."""
    p = table_path(path)
    if p in _CACHE:
        return _CACHE[p]
    entries: dict = {}
    status = "ok"
    try:
        with open(p) as fh:
            data = json.load(fh)
        if isinstance(data, dict) and isinstance(data.get("entries"), dict):
            entries = data["entries"]
        else:
            status = "alien"
    except FileNotFoundError:
        status = "missing"
    except json.JSONDecodeError:
        status = "corrupt"
    except OSError:
        status = "unreadable"
    except (ValueError, TypeError):
        status = "corrupt"
    _CACHE[p] = entries
    _STATUS[p] = status
    return entries


def table_status(path: str | None = None) -> str:
    """How the table at ``path`` loaded: "ok" / "missing" / "corrupt" /
    "alien" / "unreadable" (loads + caches on first ask)."""
    load_table(path)
    return _STATUS.get(table_path(path), "ok")


def save_table(entries: dict, path: str | None = None) -> str:
    """Atomically persist the manifest-headed table; returns the path.
    Best-effort: serving/training must survive a read-only results dir."""
    p = table_path(path)
    from qdml_tpu.telemetry import run_manifest

    payload = {
        "schema": SCHEMA,
        "kind": "qsc_autotune_table",
        "manifest": run_manifest(argv=["quantum.autotune"], include_jax=True),
        "entries": entries,
    }
    try:
        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
        tmp = f"{p}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
        os.replace(tmp, p)
    except OSError:
        pass
    _CACHE[p] = entries
    _STATUS[p] = "ok"
    return p


def invalidate_cache() -> None:
    """Drop the in-process table cache AND the installed table-path override
    (tests, or after an external edit)."""
    _CACHE.clear()
    _STATUS.clear()
    _FALLBACK_EMITTED.clear()
    set_table_path(None)


# ---------------------------------------------------------------------------
# Micro-benchmark
# ---------------------------------------------------------------------------


def _time_callable(fn, args, budget_s: float, max_reps: int) -> float:
    """Median-of-reps wall ms for an async-dispatched jitted callable."""
    import jax

    out = fn(*args)  # compile + warmup
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    est = max(time.perf_counter() - t0, 1e-5)
    reps = max(3, min(max_reps, int(budget_s / est)))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    times.sort()
    return 1e3 * times[len(times) // 2]


def measure(
    n_qubits: int,
    n_layers: int,
    bucket: int,
    impls: Sequence[str] | None = None,
    budget_s: float = 0.25,
    max_reps: int = 30,
    mps_chi: int | None = None,
) -> dict[str, dict[str, Any]]:
    """Time forward and forward+backward for each candidate at this exact
    shape. A candidate that fails to compile/run is recorded with its error
    and excluded from selection — one broken kernel must not kill tuning.
    ``mps_chi`` parameterizes the ``mps`` candidate (the timing — and the
    numerics it buys — is chi-dependent; the entry records which chi was
    raced)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from qdml_tpu.quantum.circuits import run_circuit

    impls = list(impls) if impls is not None else eligible_impls(n_qubits, jax.default_backend())
    rng = np.random.default_rng(0)
    angles = jnp.asarray(rng.uniform(-1, 1, (bucket, n_qubits)).astype(np.float32))
    weights = jnp.asarray(
        rng.uniform(0, 2 * np.pi, (n_layers, n_qubits, 2)).astype(np.float32)
    )
    out: dict[str, dict[str, Any]] = {}
    for impl in impls:
        rec: dict[str, Any] = {}
        try:
            fwd = jax.jit(
                lambda a, w, b=impl: run_circuit(
                    a, w, n_qubits, n_layers, backend=b, mps_chi=mps_chi
                )
            )
            rec["fwd_ms"] = round(_time_callable(fwd, (angles, weights), budget_s, max_reps), 4)
            # train metric = ONE value_and_grad (what a train step actually
            # dispatches). fwd_ms + grad time would double-count the forward
            # and bias selection against forward-heavy impls — the exact
            # fwd-slower-but-step-faster profile the r3 kernel showed.
            step = jax.jit(
                jax.value_and_grad(
                    lambda w, a, b=impl: jnp.sum(
                        run_circuit(
                            a, w, n_qubits, n_layers, backend=b, mps_chi=mps_chi
                        )
                        ** 2
                    )
                )
            )
            rec["train_ms"] = round(
                _time_callable(step, (weights, angles), budget_s, max_reps), 4
            )
        except Exception as e:  # lint: disable=broad-except(candidate isolation: one impl failing to compile/run must not kill tuning for the others; the error is recorded in the table)
            rec["error"] = f"{type(e).__name__}: {e}"
        out[impl] = rec
    return out


def _pick(cands: dict[str, dict], field: str) -> str | None:
    timed = {k: v[field] for k, v in cands.items() if isinstance(v.get(field), (int, float))}
    return min(timed, key=timed.get) if timed else None


def ensure(
    n_qubits: int,
    n_layers: int,
    batch: int,
    dtype: str = "float32",
    path: str | None = None,
    force: bool = False,
    budget_s: float = 0.25,
    impls: Sequence[str] | None = None,
    mps_chi: int | None = None,
) -> dict:
    """Return this shape's table entry, micro-benchmarking and persisting it
    first if absent (or ``force``). Host-side and eager — call it where
    compiles are already expected (train-loop startup, serve warmup, bench),
    NEVER from a traced function or the serve request path. ``impls``
    overrides the candidate set (the qubit-scaling sweep uses it to bound
    per-point compile budgets); the default is this topology's
    :func:`eligible_impls`."""
    import jax

    platform = jax.default_backend()
    bucket = batch_bucket(batch)
    key = table_key(platform, n_qubits, n_layers, bucket, dtype)
    entries = dict(load_table(path))
    entry = entries.get(key)
    if not force and isinstance(entry, dict) and entry.get("best_train"):
        return entry
    if impls is None:
        impls = eligible_impls(n_qubits, platform, model_axis_devices())
    cands = measure(
        n_qubits, n_layers, bucket, impls=impls, budget_s=budget_s, mps_chi=mps_chi
    )
    entry = {
        "key": key,
        "platform": platform,
        "n_qubits": n_qubits,
        "n_layers": n_layers,
        "batch_bucket": bucket,
        "dtype": dtype,
        "candidates": cands,
        "best_fwd": _pick(cands, "fwd_ms"),
        "best_train": _pick(cands, "train_ms"),
        "ts": round(time.time(), 3),
    }
    if "mps" in cands:
        from qdml_tpu.quantum.mps import DEFAULT_CHI

        entry["mps_chi"] = int(mps_chi or DEFAULT_CHI)
    entries[key] = entry
    save_table(entries, path)
    return entry


def lookup_reason(
    n_qubits: int,
    n_layers: int,
    batch: int,
    dtype: str = "float32",
    mode: str = "train",
    path: str | None = None,
) -> tuple[str | None, str | None]:
    """``(selection, fallback_reason)`` for this shape.

    ``selection`` is the tuned impl or ``None`` (caller falls back to the
    static heuristic). ``fallback_reason`` is ``None`` for the NORMAL misses
    (no table yet, shape not tuned) and a short slug for the pathologies a
    run artifact should show: ``table-corrupt`` / ``table-alien`` /
    ``table-unreadable`` (the file exists but is not a usable table),
    ``entry-alien`` (the entry's winner names an impl this build cannot
    dispatch), ``entry-ineligible`` (the winner cannot run on this topology,
    e.g. a sharded_statevector selection read on one device). Never raises,
    never benchmarks, never touches the table file beyond one cached read —
    safe at trace time."""
    try:
        import jax

        platform = jax.default_backend()
        entries = load_table(path)
        status = table_status(path)
        reason = f"table-{status}" if status in ("corrupt", "alien", "unreadable") else None
        entry = entries.get(
            table_key(platform, n_qubits, n_layers, batch_bucket(batch), dtype)
        )
        if not isinstance(entry, dict):
            return None, reason
        sel = entry.get("best_fwd" if mode == "infer" else "best_train")
        if not isinstance(sel, str) or sel not in _DISPATCHABLE:
            return None, "entry-alien" if sel is not None else reason
        from qdml_tpu.quantum.circuits import canonical_impl

        sel = canonical_impl(sel)
        ok, _why = impl_eligible(sel, n_qubits)
        if not ok:
            return None, "entry-ineligible"
        return sel, None
    except Exception:  # lint: disable=broad-except(dispatch lookup must degrade to the dense fallback on ANY table pathology — a tuner can speed dispatch up, never crash it)
        return None, None


def lookup(
    n_qubits: int,
    n_layers: int,
    batch: int,
    dtype: str = "float32",
    mode: str = "train",
    path: str | None = None,
) -> str | None:
    """The tuned implementation for this shape, or ``None`` when the table
    has nothing trustworthy (back-compat view of :func:`lookup_reason`)."""
    return lookup_reason(n_qubits, n_layers, batch, dtype, mode, path)[0]


def emit_fallback(
    reason: str,
    n_qubits: int,
    n_layers: int,
    batch: int,
    mode: str,
    fallback: str,
) -> dict | None:
    """One structured ``autotune_fallback`` record into the active telemetry
    sink (``qdml_tpu.telemetry.get_sink``) for a PATHOLOGICAL dispatch
    fallback — corrupt/alien table, undispatchable entry. De-duplicated per
    (table, shape-key, reason): the lookup fires on every circuit trace and
    a record per trace would bury the signal. Returns the record (even with
    no sink attached — callers/tests can assert on it), ``None`` when this
    pathology was already reported."""
    try:
        import jax

        platform = jax.default_backend()
    except Exception:  # lint: disable=broad-except(fallback reporting must never take down dispatch — a record with an unknown platform beats an exception on the hot path)
        platform = "unknown"
    p = table_path()
    key = table_key(platform, n_qubits, n_layers, batch_bucket(batch))
    tok = (p, key, reason)
    if tok in _FALLBACK_EMITTED:
        return None
    _FALLBACK_EMITTED.add(tok)
    rec = {"reason": reason, "table": p, "key": key, "mode": mode, "fallback": fallback}
    from qdml_tpu.telemetry import get_sink

    sink = get_sink()
    if sink is not None and getattr(sink, "active", False):
        sink.emit("autotune_fallback", **rec)
    else:
        # no sink (bare script / library use): still one visible line —
        # "silent" was the bug this record exists to kill
        print(f"autotune_fallback: {reason} table={p} key={key} -> {fallback}")
    return rec


def prewarm(cfg, batch: int, force: bool = False) -> dict | None:
    """Config-driven tuning hook for the train loops / serve warmup / bench.

    Tunes (and persists) the selection for ``cfg.quantum``'s circuit at the
    given effective batch when the dispatcher is in play: ``quantum.impl``
    and the legacy ``quantum.backend`` both at ``auto``, and
    ``quantum.autotune`` enabled for this platform. A configured
    ``quantum.autotune_table`` is installed process-wide
    (:func:`set_table_path`) so the trace-time lookup reads the SAME table
    the tuner wrote. ``force`` re-measures even over an existing entry (the
    bench uses it: its artifact must carry timings from THIS window, not a
    previous session's). Returns the table entry (with candidate timings) or
    ``None`` when tuning was skipped — callers fold the entry into their
    telemetry so the chosen impl and what it beat are part of the run
    artifact.
    """
    q = cfg.quantum
    if q.autotune_table:
        set_table_path(q.autotune_table)
    if q.impl not in ("", "auto") or q.backend != "auto":
        return None
    if not autotune_enabled(q.autotune):
        return None
    return ensure(
        q.n_qubits,
        q.n_layers,
        batch,
        path=q.autotune_table or None,
        force=force,
        mps_chi=q.mps_chi,
    )
