"""Pauli-twirl trajectory simulation: state-level hardware-noise model.

The reference's QuantumNAT (``Estimators_QuantumNAT_onchipQNN.py:176-199``,
arXiv:2110.11331) emulates hardware noise at the PARAMETER level — Gaussian
perturbation of circuit weights during training. This module adds the
state-level counterpart the framework's in-tree simulator makes cheap: a
depolarizing channel realised as stochastic Pauli insertion ("quantum
trajectories"), averaged over vmapped trajectories.

After the embedding and after every ansatz layer, each wire independently
suffers a uniform random Pauli with probability ``p`` (X/Y/Z each ``p/3``).
Averaging trajectories converges to the depolarizing-channel density-matrix
evolution without ever materialising the 4^n density matrix — the same
memory footprint as one statevector times the trajectory batch, fully
jit/vmap-compatible with threaded PRNG keys (the framework's RNG discipline,
same as QuantumNAT's noise stream).

Single-qubit analytic anchor (pinned by ``tests/test_quantum.py``): one
twirl maps ⟨Z⟩ → (1 − 4p/3)⟨Z⟩, since X Z X = −Z, Y Z Y = −Z, Z Z Z = Z.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from qdml_tpu.quantum import statevector as sv
from qdml_tpu.quantum.circuits import angle_embed, apply_ansatz_tensor
from qdml_tpu.utils.complexops import CArr

# Stacked single-qubit Paulis (I, X, Y, Z) as one (4, 2, 2) real-pair tensor
# so a traced outcome index selects the gate with a gather — no lax.cond.
_PAULI_RE = np.array(
    [
        [[1.0, 0.0], [0.0, 1.0]],  # I
        [[0.0, 1.0], [1.0, 0.0]],  # X
        [[0.0, 0.0], [0.0, 0.0]],  # Y (real part)
        [[1.0, 0.0], [0.0, -1.0]],  # Z
    ],
    dtype=np.float32,
)
_PAULI_IM = np.array(
    [
        [[0.0, 0.0], [0.0, 0.0]],
        [[0.0, 0.0], [0.0, 0.0]],
        [[0.0, -1.0], [1.0, 0.0]],  # Y = [[0, -i], [i, 0]]
        [[0.0, 0.0], [0.0, 0.0]],
    ],
    dtype=np.float32,
)


def _check_p(p) -> None:
    """Eager guard on the Pauli probability: outside [0, 1] the choice
    distribution [1-p, p/3, p/3, p/3] is invalid and ``jax.random.choice``
    samples garbage SILENTLY under jit rather than erroring (the explicit-
    validation discipline of :mod:`qdml_tpu.ops.grad_prune`). Every entry
    point takes ``p`` as a config-derived Python float, so the concrete
    check is the real gate; a value already traced by an enclosing jit is
    unverifiable here and passes through."""
    try:
        pv = float(p)  # lint: disable=host-sync-hot-path(eager concrete-value guard — traced values deliberately pass through (see docstring))
    except (jax.errors.ConcretizationTypeError, TypeError):
        return
    if not 0.0 <= pv <= 1.0:  # also rejects nan
        raise ValueError(f"depolarizing probability p must be in [0, 1], got {pv}")


def apply_random_paulis(
    psi: CArr, key: jax.Array, p: float, n: int
) -> CArr:
    """One twirl: independently on each wire AND each batched sample, apply
    I with prob 1-p or a uniform random Pauli (X/Y/Z each p/3).

    Per-sample draws matter statistically: sharing one realization across a
    batch would make every sample's Monte-Carlo error perfectly correlated,
    so a batch-aggregated estimate (e.g. test accuracy) would not tighten
    with batch size. ``apply_1q`` broadcasts a ``lead + (2, 2)`` gate, so
    per-sample gates cost one gather per wire."""
    _check_p(p)
    lead = psi.re.shape[:-1]
    probs = jnp.array([1.0 - p, p / 3.0, p / 3.0, p / 3.0], jnp.float32)
    r = jax.random.choice(key, 4, lead + (n,), p=probs)
    pre = jnp.asarray(_PAULI_RE)
    pim = jnp.asarray(_PAULI_IM)
    for q in range(n):
        psi = sv.apply_1q(psi, n, q, CArr(pre[r[..., q]], pim[r[..., q]]))
    return psi


def run_circuit_trajectories(
    angles: jnp.ndarray,
    weights: jnp.ndarray,
    n_qubits: int,
    n_layers: int,
    p: jnp.ndarray | float,
    key: jax.Array,
    n_traj: int = 32,
) -> jnp.ndarray:
    """Reference circuit under per-layer depolarizing noise, trajectory-
    averaged: angles ``(..., n)`` -> per-wire ⟨Z⟩ ``(..., n)``.

    Noise sites: after the RY embedding and after each ansatz layer — one
    twirl per site per trajectory. ``p = 0`` reproduces the clean ``tensor``
    backend exactly (every outcome draws the identity).
    """
    # validate OUTSIDE the jit boundary: inside, p is already a tracer and
    # the concrete check in apply_random_paulis can no longer fire
    _check_p(p)
    return _run_circuit_trajectories(angles, weights, n_qubits, n_layers, p, key, n_traj)


@partial(jax.jit, static_argnames=("n_qubits", "n_layers", "n_traj"))
def _run_circuit_trajectories(
    angles: jnp.ndarray,
    weights: jnp.ndarray,
    n_qubits: int,
    n_layers: int,
    p: jnp.ndarray | float,
    key: jax.Array,
    n_traj: int = 32,
) -> jnp.ndarray:
    n, nl = n_qubits, n_layers

    def one(k: jax.Array) -> jnp.ndarray:
        keys = jax.random.split(k, nl + 1)
        psi = angle_embed(sv.zero_state(n, angles.shape[:-1]), angles, n)
        psi = apply_random_paulis(psi, keys[0], p, n)
        for l in range(nl):
            # one ansatz layer at a time — the clean circuit's own body
            # (circuits.apply_ansatz_tensor), so the two cannot drift
            psi = apply_ansatz_tensor(psi, weights[l : l + 1], n, 1)
            psi = apply_random_paulis(psi, keys[l + 1], p, n)
        return sv.expvals_z(psi, n)

    outs = jax.vmap(one)(jax.random.split(key, n_traj))
    return jnp.mean(outs, axis=0)
