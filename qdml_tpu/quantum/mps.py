"""Bond-dimension-χ matrix-product-state simulation of the reference circuit.

Past the dense/pallas windows (n > ~12) the full statevector is the scaling
wall: 2^n amplitudes per sample. The tensor-network QSVM work (arXiv
2405.02630) shows MPS simulation reaching hundreds of qubits for exactly this
repo's circuit class — a ring-CNOT + single-qubit-rotation ansatz is a
LOW-ENTANGLEMENT circuit, and an MPS with bond dimension χ stores it in
``O(n · χ²)`` numbers instead of ``2^n``. This module is the capacity impl
the autotune dispatcher selects when nothing dense-shaped fits
(``quantum/autotune.eligible_impls``): exact when ``χ ≥ 2^(n/2)`` (nothing to
truncate), an explicit controlled approximation below that, with the error
non-increasing in χ (pinned in ``tests/test_scaling_impls.py``).

Simulation scheme (single sample; the public entry vmaps over the batch):

- **Sites.** One tensor per qubit, shape ``(χ_l, 2, χ_r)`` with qubit 0 the
  leftmost site (MSB-first, the package convention). Bond dimensions GROW
  with the actual Schmidt-rank bound ``min(2^i, 2^(n-i), χ, ...)`` instead of
  being padded to χ up front — every shape is a static Python int, so the
  whole chain jits, and structurally-zero singular values (the NaN mine under
  SVD differentiation) never enter the decompositions.
- **Rotations** are local single-site contractions — no bond change, the
  whole circuit's trig from one vectorized shot (the gate-matrix-cache rule).
- **Adjacent ring CNOTs** are two-site gates: contract the bond, apply the
  4×4 gate, split back by SVD truncated to χ (the standard TEBD move).
- **The wraparound CNOT(n-1, 0)** spans the open chain; it applies as a SWAP
  chain — walk the control qubit down to position 1 with adjacent SWAPs,
  apply the reversed-control CNOT on sites (0, 1), walk it back. Every move
  is the same generic two-site split. (An exact bond-2 MPO + compression
  sweep is the textbook alternative and was tried first: the MPO's grown
  ``T ⊗ I₂`` tensors have EXACTLY degenerate singular spectra, the one input
  class where any broadened SVD backward is wrong — AD error ~1 at L ≥ 2 —
  while SWAP splits of generic circuit states keep clean gaps.)
- **⟨Z_i⟩** comes from one left-environment and one right-environment sweep
  (``O(n · χ³)``), normalized by ⟨ψ|ψ⟩ — truncation loses a little norm, and
  the normalized expectation is the number comparable to the dense paths.

Differentiability: plain JAX AD flows through every contraction; the SVD
gets a ``custom_vjp`` (:func:`svd_safe`) that re-implements jax's own SVD
JVP with Lorentzian-broadened denominators (the differentiable-tensor-network
standard, arXiv 1903.09650) and transposes it — degenerate or truncated-to-
zero singular values produce finite gradients instead of the stock rule's
0·inf NaNs. Grads match the dense path at full χ (pinned).

Dtype note — the ONE sanctioned complex-dtype user in the package: SVD is a
LAPACK-shaped factorization with no MXU formulation, so the real-pair CArr
discipline (``utils/complexops``) buys nothing here, and the impl targets
the CPU/GPU hosts where n > 12 simulation actually runs (the autotuner never
offers ``mps`` to the TPU's pallas window). Inputs/outputs are real float32;
complex64 lives only inside.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

DEFAULT_CHI = 8

# Lorentzian broadening for the split backward's kept-vs-discarded spectral
# gaps (x -> x / (x² + eps)): finite gradients when the truncation cut lands
# exactly on a degenerate multiplet (where the map is genuinely
# non-differentiable), relative error O(eps / gap²) otherwise — invisible at
# f32 for the gaps real circuits produce.
_SVD_EPS = 1e-10


# ---------------------------------------------------------------------------
# Gradient-safe truncated split
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def trunc_split(theta: jnp.ndarray, k: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Rank-``k`` split ``theta ≈ left @ right``: ``left = U_k`` (isometry),
    ``right = S_k V_k† = U_k† theta``.

    The backward is NOT the textbook SVD adjoint. jax's stock rule (and its
    Lorentzian-broadened variant) differentiates the singular VECTORS, whose
    ``1/(s_j² − s_i²)`` terms are wrong or NaN at degenerate spectra — and
    this package's circuits hit EXACT degeneracies structurally (isometric
    sites walked through SWAP/MPO moves), measured as O(1) gradient error at
    L ≥ 2. This rule instead exploits the bond-gauge invariance every MPS
    consumer of the split has by construction: the downstream program
    depends on ``(left, right)`` only through gauge-invariant contractions,
    i.e. only on the spectral PROJECTOR ``P = U_k U_k†`` of ``θθ†``.
    First-order perturbation of P has denominators only ACROSS the
    kept/discarded gap — intra-block degeneracies drop out exactly:

        dU_k = U_d (K ∘ (U_d† dρ U_k)) + (I − UU†) dθ V_k S_k⁻¹,
        K_ji = 1 / (λ_i − λ_j)   (i kept, j discarded, λ = s²),
        dB   = dU_k† θ + U_k† dθ,

    broadened only at the cut, then linear-transposed. Contract: callers
    must consume the pair gauge-invariantly (any ``left → left·G``,
    ``right → G†·right`` with unitary G leaves the result unchanged) — true
    for every contraction in this module, and exactly the property that
    makes bond gauges physically meaningless in an MPS.
    """
    u, s, vh = jnp.linalg.svd(theta, full_matrices=False)
    return u[:, :k], s[:k, None].astype(vh.dtype) * vh[:k]


def _trunc_split_fwd(theta, k):
    u, s, vh = jnp.linalg.svd(theta, full_matrices=False)
    out = (u[:, :k], s[:k, None].astype(vh.dtype) * vh[:k])
    return out, (theta, u, s, vh)


def _trunc_split_bwd(k, res, cots):
    theta, u, s, vh = res
    uk, ud = u[:, :k], u[:, k:]
    sk = s[:k]
    lam = s * s
    # broadened 1/(λ_i − λ_j) over (discarded j, kept i) ONLY
    diff = lam[None, :k] - lam[k:, None]  # (r−k, k)
    kmat = (diff / (diff * diff + _SVD_EPS)).astype(theta.dtype)
    sk_inv = (sk / (sk * sk + _SVD_EPS)).astype(theta.dtype)
    vk = vh[:k].conj().T  # (n, k)
    vk_sk = vk * sk[None, :].astype(theta.dtype)  # θ† U_k = V_k S_k
    tall = theta.shape[0] > theta.shape[1]

    def jvp(dtheta):
        drho_uk = dtheta @ vk_sk + theta @ (dtheta.conj().T @ uk)
        du_k = ud @ (kmat * (ud.conj().T @ drho_uk))
        if tall:
            # null-space response (I − UU†) dθ V_k S_k⁻¹ — jax's m>n
            # projector correction, with the broadened inverse
            ndtv = dtheta @ vk
            ndtv = ndtv - u @ (u.conj().T @ ndtv)
            du_k = du_k + ndtv * sk_inv[None, :]
        db = du_k.conj().T @ theta + uk.conj().T @ dtheta
        return du_k, db

    (dtheta_bar,) = jax.linear_transpose(jvp, theta)(tuple(cots))
    return (dtheta_bar,)


trunc_split.defvjp(_trunc_split_fwd, _trunc_split_bwd)


def _split_bond(theta: jnp.ndarray, left_phys: int, chi: int):
    """Split a contracted two-site block back into (left, right) tensors.

    ``theta``: ``(l·2, 2·r)`` matrix (left site's physical index folded into
    the rows). SVD-truncate the middle bond to ``min(chi, full_rank_bound)``;
    the singular values are absorbed RIGHT (left factor stays an isometry),
    the TEBD convention that keeps left-of-cursor sites canonical during a
    left-to-right gate sweep.
    """
    keep = min(chi, theta.shape[0], theta.shape[1])
    left, right = trunc_split(theta, keep)
    return (
        left.reshape(theta.shape[0] // left_phys, left_phys, keep),
        right.reshape(keep, 2, -1),
    )


# ---------------------------------------------------------------------------
# Circuit application (single sample; sites = python list of (l, 2, r))
# ---------------------------------------------------------------------------

def _gate_cnot(reversed_control: bool = False) -> jnp.ndarray:
    """(2, 2, 2, 2) complex64 two-site gate ``[p', q', p, q]`` — CNOT with
    the control on the LEFT site (or the right, ``reversed_control``)."""
    import numpy as np

    g = np.zeros((2, 2, 2, 2), np.complex64)
    for p in range(2):
        for q in range(2):
            if reversed_control:
                g[p ^ q, q, p, q] = 1.0
            else:
                g[p, q ^ p, p, q] = 1.0
    return jnp.asarray(g)


def _gate_swap() -> jnp.ndarray:
    import numpy as np

    g = np.zeros((2, 2, 2, 2), np.complex64)
    for p in range(2):
        for q in range(2):
            g[q, p, p, q] = 1.0
    return jnp.asarray(g)


def _apply_1q(site: jnp.ndarray, gate: jnp.ndarray) -> jnp.ndarray:
    """(l, 2, r) site ← 2×2 gate on its physical index."""
    return jnp.einsum("ps,lsr->lpr", gate, site)


def _apply_two_site(a: jnp.ndarray, b: jnp.ndarray, gate: jnp.ndarray, chi: int):
    """Two-site gate on adjacent sites: contract, apply, SVD-split to χ."""
    theta = jnp.einsum("lpr,rqs->lpqs", a, b)
    theta = jnp.einsum("pqab,labs->lpqs", gate, theta)
    l, _, _, s = theta.shape
    return _split_bond(theta.reshape(l * 2, 2 * s), 2, chi)


def _apply_cnot_wrap(sites: list[jnp.ndarray], chi: int) -> list[jnp.ndarray]:
    """CNOT(n-1, 0): control on the LAST site, target on the FIRST.

    SWAP the control qubit down to position 1 (adjacent moves), apply the
    reversed-control CNOT on sites (0, 1), SWAP it back — 2(n-2) + 1 generic
    two-site splits, no MPO growth, no exactly-degenerate spectra (see the
    module docstring).
    """
    n = len(sites)
    swap = _gate_swap()
    for i in range(n - 1, 1, -1):  # control walks from site n-1 to site 1
        sites[i - 1], sites[i] = _apply_two_site(sites[i - 1], sites[i], swap, chi)
    sites[0], sites[1] = _apply_two_site(
        sites[0], sites[1], _gate_cnot(reversed_control=True), chi
    )
    for i in range(1, n - 1):  # walk it back home
        sites[i], sites[i + 1] = _apply_two_site(sites[i], sites[i + 1], swap, chi)
    return sites


def _expvals_z(sites: list[jnp.ndarray]) -> jnp.ndarray:
    """Per-wire ⟨Z_i⟩ via environment sweeps, normalized by ⟨ψ|ψ⟩."""
    n = len(sites)
    z = jnp.asarray([1.0, -1.0], sites[0].dtype)
    # left environments: L[i] is the (l_i, l_i) env left of site i
    lenvs = [jnp.ones((1, 1), sites[0].dtype)]
    for t in sites[:-1]:
        lenvs.append(jnp.einsum("ab,apr,bps->rs", lenvs[-1], t.conj(), t))
    # right environments, built right to left
    renv = jnp.ones((1, 1), sites[0].dtype)
    evs = [None] * n
    norm = None
    for i in range(n - 1, -1, -1):
        t = sites[i]
        evs[i] = jnp.einsum(
            "ab,apr,p,bps,rs->", lenvs[i], t.conj(), z, t, renv
        )
        if i == n - 1:
            norm = jnp.einsum("ab,apr,bps,rs->", lenvs[i], t.conj(), t, renv)
        renv = jnp.einsum("apr,bps,rs->ab", t.conj(), t, renv)
    norm_r = jnp.maximum(jnp.real(norm), 1e-30)
    return jnp.stack([jnp.real(e) for e in evs]) / norm_r


def _mps_forward(
    angles: jnp.ndarray, weights: jnp.ndarray, n: int, n_layers: int, chi: int
) -> jnp.ndarray:
    """Single-sample reference circuit on an MPS: angles (n,) -> ⟨Z⟩ (n,)."""
    cdtype = jnp.complex64
    half_a = 0.5 * angles.astype(jnp.float32)
    # RY product state: bond-1 chain, amplitudes (cos, sin) per site
    sites = [
        jnp.stack([jnp.cos(half_a[q]), jnp.sin(half_a[q])]).astype(cdtype).reshape(1, 2, 1)
        for q in range(n)
    ]
    # whole-circuit trig in one vectorized shot (gate-matrix-cache rule)
    half_w = 0.5 * weights.astype(jnp.float32)
    c, s = jnp.cos(half_w), jnp.sin(half_w)  # (L, n, 2)
    for layer in range(n_layers):
        for q in range(n):
            cy, sy = c[layer, q, 0].astype(cdtype), s[layer, q, 0].astype(cdtype)
            cz, sz = c[layer, q, 1], s[layer, q, 1]
            ry = jnp.stack(
                [jnp.stack([cy, -sy]), jnp.stack([sy, cy])]
            )
            ez = jnp.stack([cz - 1j * sz, cz + 1j * sz]).astype(cdtype)
            rz = jnp.diag(ez)
            sites[q] = _apply_1q(sites[q], rz @ ry)
        cnot = _gate_cnot()
        for q in range(n - 1):
            sites[q], sites[q + 1] = _apply_two_site(sites[q], sites[q + 1], cnot, chi)
        sites = _apply_cnot_wrap(sites, chi)
    return _expvals_z(sites)


def mps_circuit(
    angles: jnp.ndarray,
    weights: jnp.ndarray,
    n_qubits: int,
    n_layers: int,
    chi: int = DEFAULT_CHI,
) -> jnp.ndarray:
    """Reference circuit on a bond-χ MPS: angles (..., n) -> ⟨Z⟩ (..., n).

    Batched over samples via ``vmap`` (the weights broadcast). ``chi`` is the
    truncation bond dimension (``quantum.mps_chi``): χ ≥ 2^(n/2) is exact —
    the chain's Schmidt rank can never exceed it — smaller χ is a controlled
    approximation whose error is non-increasing in χ.
    """
    if chi < 2:
        raise ValueError(f"mps_chi must be >= 2, got {chi}")
    lead = angles.shape[:-1]
    flat = angles.reshape((-1, n_qubits)) if lead else angles[None]
    fn = partial(_mps_forward, n=n_qubits, n_layers=n_layers, chi=chi)
    out = jax.vmap(fn, in_axes=(0, None))(flat, weights)
    out = out.astype(angles.dtype if angles.dtype != jnp.bfloat16 else jnp.float32)
    return out.reshape(lead + (n_qubits,)) if lead else out[0]
