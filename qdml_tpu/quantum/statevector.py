"""Differentiable TPU-native state-vector quantum simulator primitives.

Replaces PennyLane's CPU ``default.qubit`` device + torch ``QNode`` bridge
(reference ``Estimators_QuantumNAT_onchipQNN.py:122-149``) — the defining
performance problem of the reference, whose every forward pass crosses a
torch->PennyLane->CPU boundary (SURVEY.md §3.1). Here the statevector lives
on-device as a :class:`~qdml_tpu.utils.complexops.CArr` real pair of shape
``(..., 2**n)``, gates are jit-compiled XLA ops, batching is a leading axis
(not a Python loop over samples), and gradients come from plain JAX AD — no
parameter-shift rules needed on a simulator.

Conventions: qubit 0 is the MOST significant bit of the flat basis index
(axis order of the ``(2,)*n`` tensor view), matching PennyLane wire order.

Scaling: with n qubits the statevector has ``2**n`` amplitudes; the flat last
dimension maps to TPU lanes. For ``n >= 14`` use the mesh-sharded simulator in
:mod:`qdml_tpu.quantum.sharded`.
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp
import numpy as np

from qdml_tpu.utils.complexops import CArr


def zero_state(n: int, batch_shape: tuple[int, ...] = ()) -> CArr:
    """|0...0> statevector, shape ``batch_shape + (2**n,)``."""
    dim = 2**n
    re = jnp.zeros(batch_shape + (dim,), jnp.float32).at[..., 0].set(1.0)
    return CArr(re, jnp.zeros(batch_shape + (dim,), jnp.float32))


def _split(psi: CArr, n: int, q: int):
    """View the flat statevector with qubit ``q`` exposed: returns the two
    half-slices ``psi_{q=0}``, ``psi_{q=1}`` of shape ``(..., 2**q, 2**(n-q-1))``
    plus the lead shape for reassembly."""
    lead = psi.shape[:-1]
    left, right = 2**q, 2 ** (n - q - 1)
    v = psi.reshape(lead + (left, 2, right))
    return v[..., 0, :], v[..., 1, :], lead


def _join(p0: CArr, p1: CArr, lead, n: int) -> CArr:
    re = jnp.stack([p0.re, p1.re], axis=-2)
    im = jnp.stack([p0.im, p1.im], axis=-2)
    return CArr(re, im).reshape(lead + (2**n,))


def _bcast(theta: jnp.ndarray) -> jnp.ndarray:
    """Angle with batch shape ``lead`` -> broadcastable over ``(lead, L, R)``."""
    return jnp.asarray(theta)[..., None, None]


def apply_ry(psi: CArr, n: int, q: int, theta: jnp.ndarray) -> CArr:
    """RY(theta) on qubit q. RY is real, so this is four real multiplies.

    ``theta`` may be scalar or batched with the statevector's lead shape
    (per-sample angles for AngleEmbedding, reference ``Estimators...py:127``).
    """
    return apply_ry_cs(
        psi, n, q, jnp.cos(jnp.asarray(theta) / 2), jnp.sin(jnp.asarray(theta) / 2)
    )


def apply_ry_cs(psi: CArr, n: int, q: int, c: jnp.ndarray, s: jnp.ndarray) -> CArr:
    """RY application from PRECOMPUTED half-angle (cos, sin) — the gate-matrix
    cache form: callers that walk many gates (``apply_ansatz_tensor``) derive
    the whole circuit's trig table in one vectorized ``cos``/``sin`` pair and
    feed per-gate scalars here, instead of re-deriving trig gate by gate."""
    p0, p1, lead = _split(psi, n, q)
    c, s = _bcast(c), _bcast(s)
    new0 = CArr(c * p0.re - s * p1.re, c * p0.im - s * p1.im)
    new1 = CArr(s * p0.re + c * p1.re, s * p0.im + c * p1.im)
    return _join(new0, new1, lead, n)


def apply_rz(psi: CArr, n: int, q: int, theta: jnp.ndarray) -> CArr:
    """RZ(theta) on qubit q: diag(e^{-i theta/2}, e^{+i theta/2})."""
    return apply_rz_cs(
        psi, n, q, jnp.cos(jnp.asarray(theta) / 2), jnp.sin(jnp.asarray(theta) / 2)
    )


def apply_rz_cs(psi: CArr, n: int, q: int, c: jnp.ndarray, s: jnp.ndarray) -> CArr:
    """RZ application from precomputed half-angle (cos, sin) — see
    :func:`apply_ry_cs` for the gate-matrix-cache rationale."""
    p0, p1, lead = _split(psi, n, q)
    c, s = _bcast(c), _bcast(s)
    new0 = CArr(c * p0.re + s * p0.im, c * p0.im - s * p0.re)  # * e^{-i t/2}
    new1 = CArr(c * p1.re - s * p1.im, c * p1.im + s * p1.re)  # * e^{+i t/2}
    return _join(new0, new1, lead, n)


def apply_1q(psi: CArr, n: int, q: int, u: CArr) -> CArr:
    """Apply an arbitrary single-qubit gate ``u`` (CArr, shape (..., 2, 2),
    broadcastable over the lead shape) to qubit q."""
    p0, p1, lead = _split(psi, n, q)

    def el(i, j) -> CArr:
        return CArr(_bcast(u.re[..., i, j]), _bcast(u.im[..., i, j]))

    new0 = el(0, 0) * p0 + el(0, 1) * p1
    new1 = el(1, 0) * p0 + el(1, 1) * p1
    return _join(new0, new1, lead, n)


def apply_cnot(psi: CArr, n: int, control: int, target: int) -> CArr:
    """CNOT as a basis permutation (gather on the flat statevector)."""
    perm = cnot_perm(n, control, target)
    return CArr(psi.re[..., perm], psi.im[..., perm])


def apply_perm(psi: CArr, perm: jnp.ndarray) -> CArr:
    """Apply a precomputed basis-state permutation: psi'[y] = psi[perm[y]]."""
    return CArr(psi.re[..., perm], psi.im[..., perm])


@lru_cache(maxsize=None)
def cnot_perm(n: int, control: int, target: int) -> np.ndarray:
    """Source-index permutation for CNOT(control, target): psi'[y] = psi[src[y]]."""
    y = np.arange(2**n)
    cbit = (y >> (n - 1 - control)) & 1
    src = y ^ (cbit << (n - 1 - target))
    return src


@lru_cache(maxsize=None)
def ring_cnot_perm(n: int) -> np.ndarray:
    """Composed permutation of the reference's entangling ring
    (``Estimators...py:137-139``): CNOT(i, i+1) for i < n-1, then CNOT(n-1, 0).

    Returns ``src`` with ``psi'[y] = psi[src[y]]``.
    """
    # Forward map f: basis x -> ring(x), built by applying CNOTs in order.
    x = np.arange(2**n)
    out = x.copy()
    for c in range(n - 1):
        cbit = (out >> (n - 1 - c)) & 1
        out = out ^ (cbit << (n - 1 - (c + 1)))
    cbit = (out >> (n - 1 - (n - 1))) & 1
    out = out ^ (cbit << (n - 1 - 0))
    # psi'[f(x)] = psi[x]  =>  src[y] = f^{-1}(y)
    src = np.empty_like(x)
    src[out] = x
    return src


@lru_cache(maxsize=None)
def z_signs(n: int) -> np.ndarray:
    """(2**n, n) matrix of PauliZ eigenvalues: entry [b, i] = +1 if bit i of
    basis state b (MSB-first) is 0 else -1."""
    b = np.arange(2**n)
    bits = (b[:, None] >> (n - 1 - np.arange(n))[None, :]) & 1
    return (1.0 - 2.0 * bits).astype(np.float32)


def ry_product_state(angles: jnp.ndarray, n: int) -> jnp.ndarray:
    """Closed-form AngleEmbedding state: ``RY(a_q)`` per qubit on |0...0>.

    RY rotations on |0> produce a REAL product state —
    ``amp[x] = prod_q (bit_q(x) ? sin(a_q/2) : cos(a_q/2))`` (MSB-first, the
    module's qubit convention) — so the embedded statevector costs n
    doubling multiplies instead of n gate applications on the full 2^n
    tensor, and downstream complex arithmetic can exploit a real LHS (two
    real matmuls, not four). Identical to
    ``angle_embed(zero_state(n, lead), angles, n)``; returns the real
    amplitude array of shape ``angles.shape[:-1] + (2**n,)``.
    """
    lead = angles.shape[:-1]
    half = 0.5 * angles
    c, s = jnp.cos(half), jnp.sin(half)
    amp = jnp.ones(lead + (1,), jnp.float32)
    for q in range(n):
        pair = jnp.stack([c[..., q], s[..., q]], axis=-1)  # (..., 2)
        amp = (amp[..., :, None] * pair[..., None, :]).reshape(lead + (-1,))
    return amp


def expvals_z(psi: CArr, n: int) -> jnp.ndarray:
    """Per-wire <PauliZ_i> (reference measurement, ``Estimators...py:142``):
    probabilities contracted with the sign matrix — one real MXU matmul."""
    probs = psi.abs2()  # (..., 2**n)
    return probs @ jnp.asarray(z_signs(n))


# -- common fixed gates (for tests and extensions) --------------------------


def gate_h() -> CArr:
    m = np.array([[1.0, 1.0], [1.0, -1.0]]) / np.sqrt(2.0)
    return CArr(jnp.asarray(m, jnp.float32), jnp.zeros((2, 2), jnp.float32))


def gate_rx(theta: float) -> CArr:
    c, s = np.cos(theta / 2), np.sin(theta / 2)
    return CArr(
        jnp.asarray([[c, 0.0], [0.0, c]], jnp.float32),
        jnp.asarray([[0.0, -s], [-s, 0.0]], jnp.float32),
    )
