"""Pallas TPU kernels for the quantum-circuit hot path.

The reference executes its variational circuit sample-by-sample on PennyLane's
CPU ``default.qubit`` (``Estimators_QuantumNAT_onchipQNN.py:122-149``) — the
hottest, slowest boundary in its training loop (SURVEY.md §3.1). This module
holds the Pallas TPU kernels for that hot path. The production ``pallas``
backend kernel (``fused_qsc_expvals``) computes the WHOLE circuit from raw
angles in one ``pallas_call`` per batch tile:

    expvals = square([amp(angles) | amp(angles)] @ blockdiag(Ur^T, Ui^T)) @ [z; z]

where ``amp`` is the real RY product state built IN KERNEL from lane-iota
bit masks — the embedded statevector never exists in HBM, the duplicated
layout fills all 128 lanes at the shipped 6-qubit shape (no padding waste),
and the real LHS needs two matmuls' work, not a complex product's four.

The v2 engine adds ``fused_circuit_expvals``: the ENTIRE L-layer circuit
(embedding, rotations, ring CNOTs, <Z>) in one VMEM-resident kernel with an
in-kernel ``fori_loop`` over layers — one launch instead of the per-layer
path's 2L, no HBM statevector round-trips between layers, optional bf16
amplitudes, and an adjoint-style backward that re-materializes each layer's
input by reverse rotation from the saved final state (O(1)-in-L memory).
Two further kernels are retained: ``fused_unitary_expvals`` (the round-2
psi-input formulation, kept as the benchmarking baseline) and
``apply_rotation_layer`` (the v1 per-layer fusion, kept as a tested
primitive; production dispatch goes through the whole-circuit kernels via
the autotuner — ``qdml_tpu.quantum.autotune``, docs/QUANTUM.md).

Gradients are provided by ``jax.custom_vjp``s whose backward passes are plain
XLA matmul/gate algebra (matmuls are what the MXU does best either way; the
fusion win is in the forward's elided HBM round-trips).

On non-TPU backends the kernels run in Pallas interpret mode, which is how the
CPU test suite validates them against the XLA paths (``tests/test_pallas.py``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from qdml_tpu.quantum import statevector as sv
from qdml_tpu.utils.complexops import CArr

# Batch tile: multiple of the f32 sublane tile (8); large enough to amortise
# the (D, D) unitary reload across many samples.
_TILE_B = 256
# Lane width: pad the 2^n amplitude axis (and the n-wire output axis) to this.
_LANES = 128


def _pad_to(x: jnp.ndarray, axis: int, size: int) -> jnp.ndarray:
    have = x.shape[axis]
    if have == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, size - have)
    return jnp.pad(x, pad)


def _interpret() -> bool:
    # one config-driven knob for every kernel (QDML_PALLAS_INTERPRET) — the
    # per-module backend sniffing this used to be is consolidated in
    # utils.platform so eager/jit/interpret selection stays uniform
    from qdml_tpu.utils.platform import pallas_interpret

    return pallas_interpret()


def _fused_kernel(ar_ref, ai_ref, br_ref, bi_ref, z_ref, out_ref):
    """One batch tile: Gauss-trick complex matmul + |.|^2 + Z contraction."""
    ar, ai = ar_ref[:], ai_ref[:]
    br, bi = br_ref[:], bi_ref[:]
    # (a_r + i a_i)(b_r + i b_i) with 3 real MXU matmuls (Gauss/Karatsuba).
    k1 = jnp.dot(ar + ai, br, preferred_element_type=jnp.float32)
    k2 = jnp.dot(ar, bi - br, preferred_element_type=jnp.float32)
    k3 = jnp.dot(ai, br + bi, preferred_element_type=jnp.float32)
    cr = k1 - k3
    ci = k1 + k2
    probs = cr * cr + ci * ci
    out_ref[:] = jnp.dot(probs, z_ref[:], preferred_element_type=jnp.float32)


def _fused_forward(
    ar: jnp.ndarray,
    ai: jnp.ndarray,
    bt_r: jnp.ndarray,
    bt_i: jnp.ndarray,
    z: jnp.ndarray,
) -> jnp.ndarray:
    """Padded, tiled pallas_call. a: (B, D); bt = U^T: (D, D); z: (D, n)."""
    batch, dim = ar.shape
    n_out = z.shape[-1]
    dim_p = max(_LANES, ((dim + _LANES - 1) // _LANES) * _LANES)
    n_p = max(_LANES, ((n_out + _LANES - 1) // _LANES) * _LANES)
    tile_b = min(_TILE_B, max(8, ((batch + 7) // 8) * 8))
    batch_p = ((batch + tile_b - 1) // tile_b) * tile_b

    ar = _pad_to(_pad_to(ar, 0, batch_p), 1, dim_p)
    ai = _pad_to(_pad_to(ai, 0, batch_p), 1, dim_p)
    bt_r = _pad_to(_pad_to(bt_r, 0, dim_p), 1, dim_p)
    bt_i = _pad_to(_pad_to(bt_i, 0, dim_p), 1, dim_p)
    z = _pad_to(_pad_to(z, 0, dim_p), 1, n_p)

    grid = (batch_p // tile_b,)
    batch_spec = pl.BlockSpec((tile_b, dim_p), lambda i: (i, 0), memory_space=pltpu.VMEM)
    full = pl.BlockSpec((dim_p, dim_p), lambda i: (0, 0), memory_space=pltpu.VMEM)
    z_spec = pl.BlockSpec((dim_p, n_p), lambda i: (0, 0), memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        _fused_kernel,
        grid=grid,
        in_specs=[batch_spec, batch_spec, full, full, z_spec],
        out_specs=pl.BlockSpec((tile_b, n_p), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((batch_p, n_p), jnp.float32),
        interpret=_interpret(),
    )(ar, ai, bt_r, bt_i, z)
    return out[:batch, :n_out]


@jax.custom_vjp
def _fused_expvals(ar, ai, bt_r, bt_i, z):
    return _fused_forward(ar, ai, bt_r, bt_i, z)


def _fused_fwd(ar, ai, bt_r, bt_i, z):
    return _fused_forward(ar, ai, bt_r, bt_i, z), (ar, ai, bt_r, bt_i, z)


def _fused_bwd(res, g):
    """Backward in plain XLA: the heavy ops are matmuls either way.

    With c = a @ B (complex), ev = (c_r^2 + c_i^2) @ z:
      dprobs = g @ z^T;  dc_r = 2 c_r dprobs;  dc_i = 2 c_i dprobs;
      da = dc @ conj(B)^T;  dB = conj(a)^T @ dc;  dz = probs^T @ g.
    """
    ar, ai, bt_r, bt_i, z = res
    cr = ar @ bt_r - ai @ bt_i
    ci = ar @ bt_i + ai @ bt_r
    dprobs = g @ z.T
    dcr = 2.0 * cr * dprobs
    dci = 2.0 * ci * dprobs
    dar = dcr @ bt_r.T + dci @ bt_i.T
    dai = -dcr @ bt_i.T + dci @ bt_r.T
    dbt_r = ar.T @ dcr + ai.T @ dci
    dbt_i = -ai.T @ dcr + ar.T @ dci
    dz = (cr * cr + ci * ci).T @ g
    return dar, dai, dbt_r, dbt_i, dz


_fused_expvals.defvjp(_fused_fwd, _fused_bwd)


def fused_unitary_expvals(psi: CArr, u: CArr, n_qubits: int) -> jnp.ndarray:
    """``psi (..., 2^n) -> per-wire <Z> (..., n)`` through unitary ``u``.

    Equivalent to ``expvals_z(psi @ u^T)``. Round-2 formulation, no longer
    on the production ``pallas`` backend (it lost to XLA dense on-chip at
    n=6: 128-lane padding waste + a separate embedding pass); retained as
    the general psi-input fusion and as the benchmarking baseline for
    :func:`fused_qsc_expvals`, which fuses the embedding in and fills the
    lanes via the duplicated-amp layout.
    """
    lead = psi.shape[:-1]
    dim = psi.shape[-1]
    ar = psi.re.reshape(-1, dim)
    ai = psi.im.reshape(-1, dim)
    z = jnp.asarray(sv.z_signs(n_qubits))
    ev = _fused_expvals(ar, ai, u.re.T, u.im.T, z)
    return ev.reshape(lead + (n_qubits,))


# ---------------------------------------------------------------------------
# Whole-circuit QSC kernel: angles -> <Z> in one pallas_call
# ---------------------------------------------------------------------------
# Round-2 on-chip profiling showed the psi-input kernel above LOSING to plain
# XLA dense at the shipped 6-qubit shape: it pads the 64-wide statevector to
# 128 lanes (75% of every tile wasted), issues four matmuls, and still leaves
# the angle embedding as a separate XLA pass over the (B, 64) statevector.
# This kernel removes all three costs at once by exploiting that the
# RY-embedded state is a REAL product state (statevector.ry_product_state):
#
#   - the embedding is built IN KERNEL from the (tile, n) angles via lane-
#     iota bit masks — the statevector never exists in HBM at all (input
#     traffic drops from 2 x B x 2^n floats to B x n);
#   - the amplitude row is materialised directly in DUPLICATED layout
#     (tile, 2*2^n) = [amp | amp], so at n=6 the tile is a fully-occupied
#     128 lanes wide — zero padding waste;
#   - one matmul against blockdiag(Ur^T, Ui^T) yields [c_r | c_i] in a
#     single MXU pass (real LHS: two real matmuls' work, not four), and one
#     more against the stacked sign matrix [z; z] contracts |c|^2 to <Z>.

# Batch tile for the whole-circuit kernel: (tile, 2D) buffers at n=6 are
# (512, 128) f32 = 256 KB; with angles + c + out the kernel sits ~1 MB of
# VMEM — far under the ~16 MB/core budget, large enough to amortise the
# (2D, 2D) unitary reload.
_QSC_TILE_B = 512


def _qsc_kernel(ang_ref, w_ref, z2_ref, out_ref, *, n: int):
    """One batch tile: build [amp|amp], one blockdiag matmul, one contraction."""
    dim = 1 << n
    half = 0.5 * ang_ref[:]
    c = jnp.cos(half)
    s = jnp.sin(half)
    tile_b, width = out_ref.shape[0], w_ref.shape[0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (tile_b, width), 1)
    x = lane & (dim - 1)  # duplicated basis index: both halves, any padding
    amp = jnp.ones((tile_b, width), jnp.float32)
    for q in range(n):
        bit = (x >> (n - 1 - q)) & 1
        amp = amp * jnp.where(bit == 1, s[:, q : q + 1], c[:, q : q + 1])
    cmat = jnp.dot(amp, w_ref[:], preferred_element_type=jnp.float32)
    out_ref[:] = jnp.dot(cmat * cmat, z2_ref[:], preferred_element_type=jnp.float32)


def _qsc_forward(angles: jnp.ndarray, ur_t, ui_t, z, n: int) -> jnp.ndarray:
    """angles (B, n) -> expvals (B, n) through one pallas_call.

    ``ur_t``/``ui_t``: U^T (D, D); ``z``: (D, n) sign matrix.
    """
    batch = angles.shape[0]
    dim = 1 << n
    if dim > 256:
        # Past n=8 the (2D, 2D) blockdiag operand grows quadratically toward
        # the VMEM budget (n=10 would need a 16 MB W block alone). The
        # kernel targets the reference's 4-8 qubit regime; larger circuits
        # take the mathematically identical XLA formulation (and from ~10
        # qubits the tensor/sharded paths win anyway — circuits.run_circuit).
        return _xla_qsc_expvals(angles, ur_t, ui_t, z, n)
    width = max(_LANES, 2 * dim)  # duplicated amp layout, >= one lane tile
    n_p = ((n + _LANES - 1) // _LANES) * _LANES
    tile_b = min(_QSC_TILE_B, max(8, ((batch + 7) // 8) * 8))
    batch_p = ((batch + tile_b - 1) // tile_b) * tile_b

    # blockdiag(Ur^T, Ui^T) padded to (width, width): [amp|amp] @ W = [cr|ci].
    # Padded rows are zero, so garbage amp values in lanes >= 2D are inert.
    w = jnp.zeros((width, width), jnp.float32)
    w = jax.lax.dynamic_update_slice(w, ur_t, (0, 0))
    w = jax.lax.dynamic_update_slice(w, ui_t, (dim, dim))
    z2 = jnp.zeros((width, n_p), jnp.float32)
    z2 = jax.lax.dynamic_update_slice(z2, z, (0, 0))
    z2 = jax.lax.dynamic_update_slice(z2, z, (dim, 0))

    ang = _pad_to(angles, 0, batch_p)

    out = pl.pallas_call(
        partial(_qsc_kernel, n=n),
        grid=(batch_p // tile_b,),
        in_specs=[
            pl.BlockSpec((tile_b, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((width, width), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((width, n_p), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile_b, n_p), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((batch_p, n_p), jnp.float32),
        interpret=_interpret(),
    )(ang, w, z2)
    return out[:batch, :n]


def _xla_qsc_expvals(angles, ur_t, ui_t, z, n: int) -> jnp.ndarray:
    """XLA twin with identical math (real product state, two real matmuls,
    sign contraction) — the backward differentiates through this."""
    amp = sv.ry_product_state(angles, n)
    cr = amp @ ur_t
    ci = amp @ ui_t
    return (cr * cr + ci * ci) @ z


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _qsc_expvals(angles, ur_t, ui_t, z, n):
    return _qsc_forward(angles, ur_t, ui_t, z, n)


def _qsc_fwd(angles, ur_t, ui_t, z, n):
    return _qsc_forward(angles, ur_t, ui_t, z, n), (angles, ur_t, ui_t, z)


def _qsc_bwd(n, res, g):
    angles, ur_t, ui_t, z = res
    _, vjp = jax.vjp(lambda a, br, bi, zz: _xla_qsc_expvals(a, br, bi, zz, n), *res)
    return vjp(g)


_qsc_expvals.defvjp(_qsc_fwd, _qsc_bwd)


def fused_qsc_expvals(angles: jnp.ndarray, u: CArr, n_qubits: int) -> jnp.ndarray:
    """Reference circuit measurement from raw angles: AngleEmbedding + the
    precompiled ansatz unitary ``u`` + per-wire <Z>, one kernel per batch
    tile. Equivalent to the dense path of
    :func:`qdml_tpu.quantum.circuits.run_circuit`; the embedded statevector
    never exists in HBM."""
    lead = angles.shape[:-1]
    a2 = angles.reshape(-1, n_qubits)
    z = jnp.asarray(sv.z_signs(n_qubits))
    ev = _qsc_expvals(a2, u.re.T, u.im.T, z, n_qubits)
    return ev.reshape(lead + (n_qubits,))


# ---------------------------------------------------------------------------
# Whole-circuit multi-layer kernel: the VMEM-resident L-layer chain
# ---------------------------------------------------------------------------
# The per-layer fusion below (apply_rotation_layer) still round-trips the
# (B, 2^n) statevector through HBM once per layer — 2L pallas_call launches
# per circuit plus the XLA ring-permutation gathers between them. This kernel
# runs the ENTIRE circuit in one pallas_call per batch tile: the RY product-
# state embedding is built in kernel from the (tile, n) angles, an in-kernel
# ``fori_loop`` walks all L layers (roll-based RY/RZ rotations + the ring
# CNOTs as XOR-partner selects) with the statevector tile pinned in VMEM the
# whole way, and the <Z> contraction happens before anything leaves the chip.
# HBM traffic per tile drops from ~2L statevector round-trips to one angles
# read + one (state, expvals) write; Mosaic's grid pipeline double-buffers the
# tile DMA (batch is padded ONCE, up front, to the tile multiple).
#
# Amplitudes may optionally be carried in bfloat16 (halved VMEM residency and
# vector-op width at ~2x the per-gate rounding); the final |.|^2 <Z>
# contraction always accumulates in float32 on the MXU.
#
# The backward is adjoint-style (Qandle's reversibility argument applied to
# AD): the forward saves ONLY the final statevector, and the backward
# re-materializes each layer's input by applying the INVERSE gates to it
# (RZ(-w), RY(-w), inverse ring permutation) while propagating the cotangent
# through the per-layer vjp — O(1)-in-L memory instead of the L statevector
# residuals per-layer AD would store.

# Amplitude-axis bounds for the kernel: the XOR-partner rolls need the
# amplitude axis to BE the lane axis (>= one 128-lane tile, n >= 7); past
# dim=4096 (n=12) the (dim, 128) sign matrix plus double-buffered state tiles
# crowd the ~16 MB VMEM budget — and from ~14 qubits the statevector should be
# mesh-sharded anyway (quantum/sharded.py).
_CIRCUIT_MIN_DIM = _LANES
_CIRCUIT_MAX_DIM = 4096
# VMEM budget steering the batch-tile size: re+im tiles (amp dtype) plus the
# pipeline's double buffering must fit comfortably under the per-core budget.
_CIRCUIT_VMEM_TILE_BYTES = 2 * 1024 * 1024


def _circuit_tile_b(batch: int, dim: int, amp_bytes: int) -> int:
    """Batch-tile height: sublane-aligned (16 for bf16 amplitudes, 8 for
    f32 — the dtype's min tile), VMEM-budgeted, batch-bounded."""
    sub = 16 if amp_bytes == 2 else 8
    cap = max(sub, _CIRCUIT_VMEM_TILE_BYTES // (2 * dim * amp_bytes))
    cap = min(128, (cap // sub) * sub)
    return min(cap, max(sub, ((batch + sub - 1) // sub) * sub))


def _circuit_kernel(
    ang_ref, cs_ref, z2_ref, out_ref, re_ref, im_ref, *, n: int, layers: int, bf16: bool
):
    """One batch tile, full circuit: embed -> L x (rotations + ring) -> <Z>.

    ``cs_ref`` (SMEM, (layers, n, 4)): per-gate (cos, sin) of the RY and RZ
    half-angles, precomputed on host — the kernel reads scalars, never
    recomputes weight trig per tile. The layer walk is a ``fori_loop`` so the
    program is O(1) in L; the per-qubit gate chain inside one layer is a
    static Python loop (n is a compile-time constant).
    """
    dim = 1 << n
    amp_dtype = jnp.bfloat16 if bf16 else jnp.float32
    half = 0.5 * ang_ref[:]
    c = jnp.cos(half)
    s = jnp.sin(half)
    tile_b = out_ref.shape[0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (tile_b, dim), 1)
    # RY product-state embedding from lane-iota bit masks (real, in VMEM —
    # the embedded statevector never exists in HBM)
    amp = jnp.ones((tile_b, dim), jnp.float32)
    for q in range(n):
        bit = (lane >> (n - 1 - q)) & 1
        amp = amp * jnp.where(bit == 1, s[:, q : q + 1], c[:, q : q + 1])
    ar = amp.astype(amp_dtype)
    ai = jnp.zeros((tile_b, dim), amp_dtype)

    def one_layer(l, carry):
        ar, ai = carry
        for q in range(n):
            m = 1 << (n - 1 - q)
            bit = (lane >> (n - 1 - q)) & 1
            sgn = jnp.where(bit == 1, 1.0, -1.0).astype(amp_dtype)
            # XOR-partner exchange: two lane rolls + iota-mask select (the
            # Mosaic-friendly formulation; wrap-around only ever lands on
            # positions of the opposite bit, which take the other branch)
            pr = jnp.where(bit == 0, pltpu.roll(ar, dim - m, 1), pltpu.roll(ar, m, 1))
            pi = jnp.where(bit == 0, pltpu.roll(ai, dim - m, 1), pltpu.roll(ai, m, 1))
            cy = cs_ref[l, q, 0].astype(amp_dtype)
            sy = cs_ref[l, q, 1].astype(amp_dtype)
            br = cy * ar + sgn * sy * pr
            bi = cy * ai + sgn * sy * pi
            cz = cs_ref[l, q, 2].astype(amp_dtype)
            sz = cs_ref[l, q, 3].astype(amp_dtype)
            ar = cz * br - sgn * sz * bi
            ai = cz * bi + sgn * sz * br
        # entangling ring: CNOT(i, i+1) for i < n-1, then CNOT(n-1, 0) —
        # each as a control-masked XOR-partner select on the target bit
        for ctl in range(n):
            tgt = (ctl + 1) % n
            mt = 1 << (n - 1 - tgt)
            cbit = (lane >> (n - 1 - ctl)) & 1
            tbit = (lane >> (n - 1 - tgt)) & 1
            pr = jnp.where(tbit == 0, pltpu.roll(ar, dim - mt, 1), pltpu.roll(ar, mt, 1))
            pi = jnp.where(tbit == 0, pltpu.roll(ai, dim - mt, 1), pltpu.roll(ai, mt, 1))
            ar = jnp.where(cbit == 1, pr, ar)
            ai = jnp.where(cbit == 1, pi, ai)
        return ar, ai

    ar, ai = jax.lax.fori_loop(0, layers, one_layer, (ar, ai))
    arf = ar.astype(jnp.float32)
    aif = ai.astype(jnp.float32)
    re_ref[:] = arf
    im_ref[:] = aif
    # f32 MXU accumulation regardless of the amplitude dtype
    out_ref[:] = jnp.dot(arf * arf + aif * aif, z2_ref[:], preferred_element_type=jnp.float32)


def _xla_circuit(angles: jnp.ndarray, weights: jnp.ndarray, n: int, layers: int):
    """XLA twin with identical math (embed -> gates -> ring -> <Z>), returning
    ``(expvals, final_re, final_im)`` like the kernel path. Small/huge dims
    fall back here, and the adjoint backward's per-layer vjp reuses its
    building blocks."""
    amp = sv.ry_product_state(angles, n)
    psi = CArr(amp, jnp.zeros_like(amp))
    ring = jnp.asarray(sv.ring_cnot_perm(n))
    for l in range(layers):
        for q in range(n):
            psi = sv.apply_ry(psi, n, q, weights[l, q, 0])
            psi = sv.apply_rz(psi, n, q, weights[l, q, 1])
        psi = sv.apply_perm(psi, ring)
    return sv.expvals_z(psi, n), psi.re, psi.im


def _circuit_forward(angles: jnp.ndarray, weights: jnp.ndarray, n: int, layers: int, bf16: bool):
    """angles (B, n), weights (layers, n, 2) -> (expvals (B, n), final state)."""
    dim = 1 << n
    if not (_CIRCUIT_MIN_DIM <= dim <= _CIRCUIT_MAX_DIM) or layers < 1:
        return _xla_circuit(angles, weights, n, layers)
    batch = angles.shape[0]
    amp_bytes = 2 if bf16 else 4
    tile_b = _circuit_tile_b(batch, dim, amp_bytes)
    batch_p = ((batch + tile_b - 1) // tile_b) * tile_b  # pad ONCE, up front
    ang = _pad_to(angles, 0, batch_p)

    half = weights / 2.0
    cs = jnp.stack(
        [
            jnp.cos(half[..., 0]),
            jnp.sin(half[..., 0]),
            jnp.cos(half[..., 1]),
            jnp.sin(half[..., 1]),
        ],
        axis=-1,
    )  # (layers, n, 4) f32 scalars for SMEM
    n_p = ((n + _LANES - 1) // _LANES) * _LANES
    z2 = jnp.zeros((dim, n_p), jnp.float32)
    z2 = jax.lax.dynamic_update_slice(z2, jnp.asarray(sv.z_signs(n)), (0, 0))

    state_spec = pl.BlockSpec((tile_b, dim), lambda i: (i, 0), memory_space=pltpu.VMEM)
    ev, fre, fim = pl.pallas_call(
        partial(_circuit_kernel, n=n, layers=layers, bf16=bf16),
        grid=(batch_p // tile_b,),
        in_specs=[
            pl.BlockSpec((tile_b, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((layers, n, 4), lambda i: (0, 0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((dim, n_p), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile_b, n_p), lambda i: (i, 0), memory_space=pltpu.VMEM),
            state_spec,
            state_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch_p, n_p), jnp.float32),
            jax.ShapeDtypeStruct((batch_p, dim), jnp.float32),
            jax.ShapeDtypeStruct((batch_p, dim), jnp.float32),
        ],
        interpret=_interpret(),
    )(ang, cs, z2)
    return ev[:batch, :n], fre[:batch], fim[:batch]


def _apply_layer_fwd(pre, pim, w_l, n: int, ring):
    """One layer's forward on a (B, dim) real pair — the function whose vjp
    the adjoint backward evaluates at the re-materialized layer input."""
    psi = CArr(pre, pim)
    for q in range(n):
        psi = sv.apply_ry(psi, n, q, w_l[q, 0])
        psi = sv.apply_rz(psi, n, q, w_l[q, 1])
    psi = sv.apply_perm(psi, ring)
    return psi.re, psi.im


def _undo_layer(psi: CArr, w_l: jnp.ndarray, n: int, inv_ring) -> CArr:
    """Exact inverse of :func:`_apply_layer_fwd`: inverse ring permutation,
    then RZ(-w)/RY(-w) in reverse gate order — the reverse rotation that
    re-materializes the layer's input from its output."""
    psi = sv.apply_perm(psi, inv_ring)
    for q in reversed(range(n)):
        psi = sv.apply_rz(psi, n, q, -w_l[q, 1])
        psi = sv.apply_ry(psi, n, q, -w_l[q, 0])
    return psi


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _circuit_expvals(angles, weights, n, layers, bf16):
    ev, _fre, _fim = _circuit_forward(angles, weights, n, layers, bf16)
    return ev


def _circuit_fwd(angles, weights, n, layers, bf16):
    ev, fre, fim = _circuit_forward(angles, weights, n, layers, bf16)
    # residuals: inputs + FINAL statevector only — never the per-layer chain
    return ev, (angles, weights, fre, fim)


def _circuit_bwd(n, layers, bf16, res, g):
    """Adjoint backward: walk the layers in reverse, re-materializing each
    layer's input statevector by reverse rotation from the saved final state
    and pushing the cotangent through the per-layer vjp. Memory is O(2^n)
    regardless of L (vs the L+1 statevectors plain AD would hold)."""
    angles, weights, fre, fim = res
    z = jnp.asarray(sv.z_signs(n))
    dprobs = g @ z.T
    lam = CArr(2.0 * fre * dprobs, 2.0 * fim * dprobs)
    psi = CArr(fre, fim)
    ring_np = sv.ring_cnot_perm(n)
    ring = jnp.asarray(ring_np)
    inv_ring = jnp.asarray(np.argsort(ring_np))
    dws = []
    for l in reversed(range(layers)):
        psi_in = _undo_layer(psi, weights[l], n, inv_ring)
        _, layer_vjp = jax.vjp(
            lambda pre, pim, w_l: _apply_layer_fwd(pre, pim, w_l, n, ring),
            psi_in.re,
            psi_in.im,
            weights[l],
        )
        lre, lim, dw_l = layer_vjp((lam.re, lam.im))
        lam = CArr(lre, lim)
        dws.append(dw_l)
        psi = psi_in
    dweights = jnp.stack(dws[::-1]) if dws else jnp.zeros_like(weights)
    # embedding cotangent: the embedded state is REAL and its imaginary part
    # is identically zero independent of the angles, so only lam.re flows
    _, embed_vjp = jax.vjp(lambda a: sv.ry_product_state(a, n), angles)
    (dangles,) = embed_vjp(lam.re)
    return dangles, dweights


_circuit_expvals.defvjp(_circuit_fwd, _circuit_bwd)


def fused_circuit_expvals(
    angles: jnp.ndarray,
    weights: jnp.ndarray,
    n_qubits: int,
    n_layers: int,
    bf16_amps: bool = False,
) -> jnp.ndarray:
    """Full reference circuit — AngleEmbedding + L x (RY/RZ rotations + ring
    CNOTs) + per-wire <Z> — as ONE VMEM-resident pallas_call per batch tile.

    Unlike :func:`fused_qsc_expvals` (which needs the precompiled
    ``(2^n, 2^n)`` ansatz unitary and tops out around n=8), this path never
    builds the dense unitary: it walks the gate chain in kernel, so it scales
    with the per-layer tensor path (n ~ 7-12 single-chip) while paying ONE
    launch instead of 2L. Outside the kernel's lane/VMEM window it falls back
    to the mathematically identical XLA twin. ``bf16_amps`` carries the
    statevector in bfloat16 (f32 accumulation for the <Z> contraction).
    """
    lead = angles.shape[:-1]
    a2 = angles.reshape(-1, n_qubits)
    ev = _circuit_expvals(a2, weights, n_qubits, n_layers, bool(bf16_amps))
    return ev.reshape(lead + (n_qubits,))


# ---------------------------------------------------------------------------
# Fused rotation-layer kernel (tensor path, larger n)
# ---------------------------------------------------------------------------


def _layer_kernel_body(ar_ref, ai_ref, cos_ref, sin_ref, or_ref, oi_ref, *, n: int):
    """Apply one full rotation layer — RY(w[q,0]) then RZ(w[q,1]) on every
    qubit q — to a (tile_b, 2^n) statevector block without leaving VMEM.

    The XOR-partner exchange for qubit q (stride m = 2^(n-1-q) along the flat
    amplitude axis) is built from two lane rolls plus an iota-mask select —
    the Mosaic-friendly formulation (no lane-crossing reshapes): for a
    position with qubit-bit 0 the partner sits at +m (roll by -m), for bit 1
    at -m (roll by +m); circular wrap-around only ever lands on positions of
    the opposite bit, which take the other branch.
    """
    ar, ai = ar_ref[:], ai_ref[:]
    shape = ar.shape
    lane = jax.lax.broadcasted_iota(jnp.int32, shape, dimension=1)
    for q in range(n):
        m = 1 << (n - 1 - q)
        bit = (lane >> (n - 1 - q)) & 1
        sgn = jnp.where(bit == 1, 1.0, -1.0).astype(jnp.float32)
        # partner amplitudes (index XOR m); roll shift must be non-negative,
        # so the -m roll is written as dim - m.
        dim = shape[1]
        pr = jnp.where(bit == 0, pltpu.roll(ar, dim - m, 1), pltpu.roll(ar, m, 1))
        pi = jnp.where(bit == 0, pltpu.roll(ai, dim - m, 1), pltpu.roll(ai, m, 1))
        # RY(t): [c, -s; s, c] (real): new = c*a + sgn*s*partner.
        cy, sy = cos_ref[q, 0], sin_ref[q, 0]
        br = cy * ar + sgn * sy * pr
        bi = cy * ai + sgn * sy * pi
        # RZ(p): diag(e^{-ip/2}, e^{+ip/2}) by bit: re' = c*re - sgn*s*im.
        cz, sz = cos_ref[q, 1], sin_ref[q, 1]
        ar = cz * br - sgn * sz * bi
        ai = cz * bi + sgn * sz * br
    or_ref[:] = ar
    oi_ref[:] = ai


def _xla_rotation_layer(ar: jnp.ndarray, ai: jnp.ndarray, weights_l: jnp.ndarray, n: int):
    """XLA reference semantics of one rotation layer (used for the backward)."""
    psi = CArr(ar, ai)
    for q in range(n):
        psi = sv.apply_ry(psi, n, q, weights_l[q, 0])
        psi = sv.apply_rz(psi, n, q, weights_l[q, 1])
    return psi.re, psi.im


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _rotation_layer(ar, ai, weights_l, n):
    return _rotation_layer_pallas(ar, ai, weights_l, n)


def _rotation_layer_fwd(ar, ai, weights_l, n):
    return _rotation_layer_pallas(ar, ai, weights_l, n), (ar, ai, weights_l)


def _rotation_layer_bwd(n, res, g):
    """Backward by AD through the (mathematically identical) XLA layer —
    forward stays fused in VMEM; the backward's gate chain is XLA's bread
    and butter and reuses the residual inputs (rematerialisation)."""
    ar, ai, weights_l = res
    _, vjp = jax.vjp(lambda a, b, w: _xla_rotation_layer(a, b, w, n), ar, ai, weights_l)
    return vjp(g)


_rotation_layer.defvjp(_rotation_layer_fwd, _rotation_layer_bwd)


def apply_rotation_layer(psi: CArr, weights_l: jnp.ndarray, n: int) -> CArr:
    """One ansatz rotation layer (all qubits' RY+RZ) as a single fused kernel.

    ``weights_l``: (n, 2) — per-qubit (RY, RZ) angles of one layer (the ring
    CNOT that follows is a pure permutation, applied outside via
    :func:`qdml_tpu.quantum.statevector.apply_perm`).
    """
    lead = psi.shape[:-1]
    dim = psi.shape[-1]
    assert dim == (1 << n)
    re, im = _rotation_layer(psi.re.reshape(-1, dim), psi.im.reshape(-1, dim), weights_l, n)
    return CArr(re.reshape(lead + (dim,)), im.reshape(lead + (dim,)))


def _rotation_layer_pallas(ar: jnp.ndarray, ai: jnp.ndarray, weights_l: jnp.ndarray, n: int):
    dim = 1 << n
    if dim < _LANES:
        # The kernel's XOR-partner rolls need the amplitude axis to BE the
        # lane axis; below one 128-lane tile, Mosaic would have to pad, and a
        # circular roll over padding corrupts the exchange. Use the
        # mathematically identical XLA layer instead (n >= 7 engages the
        # kernel with naturally lane-aligned 2^n >= 128).
        return _xla_rotation_layer(ar, ai, weights_l, n)
    batch = ar.shape[0]
    tile_b = min(128, max(8, ((batch + 7) // 8) * 8))
    batch_p = ((batch + tile_b - 1) // tile_b) * tile_b
    ar = _pad_to(ar, 0, batch_p)
    ai = _pad_to(ai, 0, batch_p)
    cos = jnp.cos(weights_l / 2.0)
    sin = jnp.sin(weights_l / 2.0)

    spec = pl.BlockSpec((tile_b, dim), lambda i: (i, 0), memory_space=pltpu.VMEM)
    wspec = pl.BlockSpec((n, 2), lambda i: (0, 0), memory_space=pltpu.SMEM)
    re, im = pl.pallas_call(
        partial(_layer_kernel_body, n=n),
        grid=(batch_p // tile_b,),
        in_specs=[spec, spec, wspec, wspec],
        out_specs=[spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((batch_p, dim), jnp.float32),
            jax.ShapeDtypeStruct((batch_p, dim), jnp.float32),
        ],
        interpret=_interpret(),
    )(ar, ai, cos, sin)
    return re[:batch], im[:batch]
