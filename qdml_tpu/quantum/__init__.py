from qdml_tpu.quantum.circuits import (  # noqa: F401
    angle_embed,
    ansatz_unitary,
    apply_ansatz_tensor,
    fused_ansatz_unitary,
    fused_layer_unitaries,
    rot_gate,
    run_circuit,
)
from qdml_tpu.quantum.trajectories import (  # noqa: F401
    apply_random_paulis,
    run_circuit_trajectories,
)
from qdml_tpu.quantum.statevector import (  # noqa: F401
    apply_1q,
    apply_cnot,
    apply_perm,
    apply_ry,
    apply_rz,
    cnot_perm,
    expvals_z,
    gate_h,
    gate_rx,
    ring_cnot_perm,
    z_signs,
    zero_state,
)
