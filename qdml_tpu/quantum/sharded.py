"""Mesh-sharded statevector simulation: the 2^n amplitudes across devices.

The reference's scaling axis is qubit count (published 4/6/8-qubit runs; the
BASELINE.json "16-qubit QNN, pjit model-sharded statevec" config) — the
TPU-native analog of sequence parallelism (SURVEY.md §5.7): a 16-qubit batched
statevector (batch x 65536 amplitudes) is partitioned over the mesh's model
axis and gates on sharded qubits become pairwise ``ppermute`` exchanges over
the ICI ring, exactly the ring-exchange pattern of ring attention.

Layout: with K = 2^k devices on the ``model`` axis, the k MOST significant
qubits are "global" (their bits index the device), the remaining n-k are local
(flat trailing dimension of each shard — maps to TPU lanes). Per device the
shard is ``(batch, 2^(n-k))``.

Gate rules (all differentiable; AD flows through ``ppermute``):

- 1q gate on LOCAL qubit: ordinary axis-split application, zero comms.
- RZ on GLOBAL qubit: diagonal — each device applies its bit's phase. No comms.
- RY (or any 1q) on GLOBAL qubit: one ``ppermute`` with the partner device
  (index XOR bit) then a local linear combination.
- CNOT: control global/local x target global/local — either a local
  permutation, a masked local flip, or a partner exchange with ``where``.

Everything runs inside one ``shard_map`` region so XLA schedules the
collectives; with ``k = 0`` this degrades to the unsharded tensor path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from qdml_tpu.quantum import statevector as sv
from qdml_tpu.utils.complexops import CArr


def _axis_size(axis_name: str) -> int:
    # jax.lax.axis_size is newer-jax only; psum(1, axis) is the portable
    # idiom (constant-folds to the mesh axis size, no runtime collective).
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)


def _my_bit(axis_name: str, k: int, q: int) -> jnp.ndarray:
    """Bit q (MSB-first among the k global qubits) of this device's index."""
    idx = jax.lax.axis_index(axis_name)
    return (idx >> (k - 1 - q)) & 1


def _partner_perm(k_devices: int, bit: int) -> list[tuple[int, int]]:
    """ppermute pairs: each device exchanges with index XOR (1 << bit_pos)."""
    return [(d, d ^ bit) for d in range(k_devices)]


def _exchange(local: CArr, axis_name: str, k: int, q: int) -> CArr:
    """Fetch the partner shard for global qubit q (index XOR)."""
    nd = _axis_size(axis_name)
    pairs = _partner_perm(nd, 1 << (k - 1 - q))
    return CArr(
        jax.lax.ppermute(local.re, axis_name, pairs),
        jax.lax.ppermute(local.im, axis_name, pairs),
    )


def _bc(theta) -> jnp.ndarray:
    """Angle (scalar or batched (B,)) -> broadcastable over the (B, 2^n_local) shard."""
    t = jnp.asarray(theta)
    return t[..., None] if t.ndim else t


def ry_global(local: CArr, theta, axis_name: str, k: int, q: int) -> CArr:
    """RY(theta) on a sharded qubit: one partner exchange + linear combine."""
    other = _exchange(local, axis_name, k, q)
    b = _my_bit(axis_name, k, q)
    c = jnp.cos(_bc(theta) / 2)
    s = jnp.sin(_bc(theta) / 2)
    # b == 0: amp0' = c*a0 - s*a1 (other holds a1); b == 1: amp1' = s*a0 + c*a1.
    sign = jnp.where(b == 0, -1.0, 1.0)
    return CArr(c * local.re + sign * s * other.re, c * local.im + sign * s * other.im)


def rz_global(local: CArr, theta, axis_name: str, k: int, q: int) -> CArr:
    """RZ on a sharded qubit is diagonal: apply the bit's phase locally."""
    b = _my_bit(axis_name, k, q)
    t = _bc(theta) / 2
    c = jnp.cos(t)
    s = jnp.where(b == 0, -jnp.sin(t), jnp.sin(t))  # e^{-it/2} or e^{+it/2}
    return CArr(c * local.re - s * local.im, c * local.im + s * local.re)


def _local_bits(n_local: int, q: int) -> jnp.ndarray:
    """(2^n_local,) 0/1 mask of bit q (MSB-first) of the local flat index."""
    idx = jnp.arange(2**n_local)
    return (idx >> (n_local - 1 - q)) & 1


def cnot_sharded(
    local: CArr, axis_name: str, k: int, n_local: int, control: int, target: int
) -> CArr:
    """CNOT with qubits indexed globally (0..k-1 sharded, k..n-1 local)."""
    c_global, t_global = control < k, target < k
    if not c_global and not t_global:
        perm = jnp.asarray(sv.cnot_perm(n_local, control - k, target - k))
        return sv.apply_perm(local, perm)
    if c_global and not t_global:
        # X on the local target when my control bit is 1: flip-bit permutation.
        cbit = _my_bit(axis_name, k, control)
        flip = jnp.asarray(_flip_perm(n_local, target - k))
        flipped = sv.apply_perm(local, flip)
        keep = (cbit == 0)
        return CArr(
            jnp.where(keep, local.re, flipped.re), jnp.where(keep, local.im, flipped.im)
        )
    if not c_global and t_global:
        other = _exchange(local, axis_name, k, target)
        cbit = _local_bits(n_local, control - k)  # (2^n_local,)
        take_other = (cbit == 1)
        return CArr(
            jnp.where(take_other, other.re, local.re),
            jnp.where(take_other, other.im, local.im),
        )
    # both global: exchange on target bit where my control bit is 1
    other = _exchange(local, axis_name, k, target)
    cbit = _my_bit(axis_name, k, control)
    keep = (cbit == 0)
    return CArr(
        jnp.where(keep, local.re, other.re), jnp.where(keep, local.im, other.im)
    )


def _flip_perm(n_local: int, q: int) -> np.ndarray:
    idx = np.arange(2**n_local)
    return idx ^ (1 << (n_local - 1 - q))


def apply_1q_sharded(
    local: CArr,
    axis_name: str,
    k: int,
    n_local: int,
    q: int,
    kind: str,
    theta,
) -> CArr:
    """Dispatch RY/RZ on global or local qubit q (global index)."""
    if q < k:
        return ry_global(local, theta, axis_name, k, q) if kind == "ry" else rz_global(
            local, theta, axis_name, k, q
        )
    ql = q - k
    if kind == "ry":
        return sv.apply_ry(local, n_local, ql, theta)
    return sv.apply_rz(local, n_local, ql, theta)


def expvals_z_sharded(local: CArr, axis_name: str, k: int, n_local: int, n: int) -> jnp.ndarray:
    """Per-wire <Z_i> with a single psum: (..., 2^n_local) -> (..., n)."""
    probs = local.abs2()  # (B, 2^n_local)
    local_ev = probs @ jnp.asarray(sv.z_signs(n_local))  # (B, n_local)
    total = jnp.sum(probs, axis=-1, keepdims=True)  # (B, 1)
    idx = jax.lax.axis_index(axis_name)
    gbits = (idx >> (k - 1 - jnp.arange(k))) & 1  # (k,)
    gsigns = 1.0 - 2.0 * gbits.astype(jnp.float32)
    global_ev = total * gsigns  # (B, k)
    ev = jnp.concatenate([global_ev, local_ev], axis=-1)  # (B, n)
    return jax.lax.psum(ev, axis_name)


def _circuit_local(
    angles: jnp.ndarray,
    weights: jnp.ndarray,
    n: int,
    n_layers: int,
    k: int,
    axis_name: str,
) -> jnp.ndarray:
    """The reference circuit on one shard (runs inside shard_map)."""
    n_local = n - k
    batch = angles.shape[:-1]
    # |0...0>: amplitude 1 at flat index 0 on device 0 only.
    idx = jax.lax.axis_index(axis_name)
    re = jnp.zeros(batch + (2**n_local,), jnp.float32)
    re = re.at[..., 0].set(jnp.where(idx == 0, 1.0, 0.0))
    psi = CArr(re, jnp.zeros_like(re))

    for q in range(n):
        psi = apply_1q_sharded(psi, axis_name, k, n_local, q, "ry", angles[..., q])
    for l in range(n_layers):
        for q in range(n):
            psi = apply_1q_sharded(psi, axis_name, k, n_local, q, "ry", weights[l, q, 0])
            psi = apply_1q_sharded(psi, axis_name, k, n_local, q, "rz", weights[l, q, 1])
        for c in range(n - 1):
            psi = cnot_sharded(psi, axis_name, k, n_local, c, c + 1)
        psi = cnot_sharded(psi, axis_name, k, n_local, n - 1, 0)
    return expvals_z_sharded(psi, axis_name, k, n_local, n)


def run_circuit_sharded(
    angles: jnp.ndarray,
    weights: jnp.ndarray,
    n_qubits: int,
    n_layers: int,
    mesh: Mesh | None = None,
    axis_name: str = "model",
) -> jnp.ndarray:
    """Reference circuit with the statevector sharded over ``mesh[axis_name]``.

    Falls back to the tensor path when no suitable mesh axis exists.
    """
    if mesh is None:
        mesh = _default_model_mesh(axis_name)
    k_devices = mesh.shape[axis_name]
    k = int(np.log2(k_devices))
    if 2**k != k_devices:
        raise ValueError(f"model axis size {k_devices} must be a power of two")
    if k == 0:
        from qdml_tpu.quantum.circuits import run_circuit

        return run_circuit(angles, weights, n_qubits, n_layers, "tensor")

    # jax.shard_map is top-level only on newer jax; 0.4.x keeps it in
    # jax.experimental.shard_map.
    try:
        shard_map = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map

    fn = shard_map(
        partial(
            _circuit_local,
            n=n_qubits,
            n_layers=n_layers,
            k=k,
            axis_name=axis_name,
        ),
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=P(),
    )
    return fn(angles, weights)


def _default_model_mesh(axis_name: str) -> Mesh:
    devs = np.array(jax.devices())
    k = 1 << int(np.log2(len(devs)))
    return Mesh(devs[:k], (axis_name,))
