"""Streaming drift detection over per-scenario serve statistics.

The serving stack already measures everything a detector needs
(``ServeMetrics``: per-scenario prediction counts + confidence sums, the
sparse-dispatch overflow counters, and — when ground truth is available, as
in the loadgen/dryrun harnesses — served NMSE); this module turns those
streams into *decisions*. Detection is the Page-Hinkley/CUSUM family: per
(scenario, signal) a one-sided cumulative-deviation statistic against the
stream's own running mean, with a magnitude slack ``delta`` (drift smaller
than this is noise by definition) and a trip threshold. Two hardening
layers sit on top, because a false fine-tune + swap cycle is expensive:

- **min_samples** — the running mean must be established before the
  statistic can trip (the first windows DEFINE in-distribution);
- **debounce** — ``debounce`` CONSECUTIVE tripping windows are required
  before a ``drift_event`` fires; a single noisy window resets nothing and
  triggers nothing.

A fired detector latches (``active()``) until the controller adapts and
calls :meth:`DriftMonitor.reset` — re-arming against the post-adaptation
distribution, so the detector never compares the fine-tuned world against
the stale pre-drift mean.

Signals and their trip directions (docs/CONTROL.md):

- ``confidence`` — per-scenario windowed mean of the routed class's
  probability; drift trips on a sustained DROP;
- ``nmse_parity`` — served NMSE in dB (fed externally by harnesses that
  know ground truth); trips on a sustained RISE (values are ~10x the
  fraction signals, so callers scale thresholds — ``DB_SCALE``);
- ``overflow_rate`` — sparse-dispatch overflow fraction (scenario ``-1``,
  fleet-wide); trips on a sustained RISE (a scenario-mix shift starving
  expert capacity).

Thread safety: the monitor is written by the controller tick thread and read
by status/report paths, so the detector-window map is lock-guarded
(``_windows`` -> ``_lock``, enforced by graftlint's LOCK_MAP).
"""

from __future__ import annotations

import threading

from qdml_tpu.utils import lockdep

from qdml_tpu.control.events import emit_record

# nmse_parity streams are in dB (~10x the dynamic range of the [0, 1]
# fraction signals): detector delta/threshold scale up by this factor.
DB_SCALE = 10.0

# signal -> trip direction ("down": a sustained drop is drift; "up": a rise)
SIGNALS: dict[str, str] = {
    "confidence": "down",
    "nmse_parity": "up",
    "overflow_rate": "up",
}


class PageHinkley:
    """One-sided Page-Hinkley/CUSUM mean-shift detector for a scalar stream.

    ``update(x)`` folds one observation into the running mean and the
    cumulative deviation statistic ``cum = max(0, cum + dev)`` where ``dev``
    is ``mean - x - delta`` (direction "down") or ``x - mean - delta``
    ("up"); returns True while ``cum > threshold`` and at least
    ``min_samples`` observations established the mean. ``delta`` is the
    magnitude slack (drift smaller than delta never accumulates), so on a
    stationary stream ``cum`` repeatedly decays to zero — the
    false-positive property pinned in tests/test_control.py.
    """

    def __init__(
        self,
        delta: float = 0.01,
        threshold: float = 0.15,
        direction: str = "down",
        min_samples: int = 5,
    ):
        if direction not in ("down", "up"):
            raise ValueError(f"direction must be 'down' or 'up', got {direction!r}")
        if delta < 0 or threshold <= 0:
            raise ValueError(
                f"need delta >= 0 and threshold > 0, got {delta}, {threshold}"
            )
        self.delta = float(delta)
        self.threshold = float(threshold)
        self.direction = direction
        self.min_samples = int(min_samples)
        self.reset()

    def reset(self) -> None:
        self.n = 0
        self.mean = 0.0
        self.cum = 0.0

    def update(self, x: float) -> bool:
        x = float(x)
        self.n += 1
        # running mean BEFORE folding x in would bias the very first windows;
        # the standard PH form tracks the mean of everything seen so far
        self.mean += (x - self.mean) / self.n
        dev = (self.mean - x - self.delta) if self.direction == "down" else (
            x - self.mean - self.delta
        )
        self.cum = max(0.0, self.cum + dev)
        return self.n >= self.min_samples and self.cum > self.threshold


class DriftMonitor:
    """Per-(scenario, signal) detector bank with debounce + latched events.

    ``observe(scenario, signal, value)`` feeds one windowed statistic (the
    controller differences two metric-verb snapshots to build windows) and
    returns a ``drift_event`` record dict the FIRST time that stream's
    debounced detector fires — also emitted to the telemetry sink, so every
    detection is a durable, structured artifact. The stream then stays
    ``active`` until :meth:`reset` re-arms it (post-adaptation).
    """

    def __init__(
        self,
        delta: float = 0.01,
        threshold: float = 0.15,
        debounce: int = 2,
        min_samples: int = 5,
        sink=None,
    ):
        self.delta = float(delta)
        self.threshold = float(threshold)
        self.debounce = max(1, int(debounce))
        self.min_samples = int(min_samples)
        self._sink = sink
        self._lock = lockdep.Lock("DriftMonitor._lock")
        # (scenario, signal) -> {"det": PageHinkley, "hits": int, "fired": bool}
        self._windows: dict[tuple[int, str], dict] = {}

    def observe(self, scenario: int, signal: str, value: float) -> dict | None:
        """Feed one windowed statistic; returns the ``drift_event`` record on
        the debounced first trip of that (scenario, signal) stream, else
        ``None``. Unknown signals raise — a typo'd signal name silently
        never detecting anything is the worst failure mode a detector can
        have."""
        if signal not in SIGNALS:
            raise ValueError(f"unknown drift signal {signal!r} (have {sorted(SIGNALS)})")
        with self._lock:
            key = (int(scenario), signal)
            ent = self._windows.get(key)
            if ent is None:
                scale = DB_SCALE if signal == "nmse_parity" else 1.0
                ent = self._windows[key] = {
                    "det": PageHinkley(
                        delta=self.delta * scale,
                        threshold=self.threshold * scale,
                        direction=SIGNALS[signal],
                        min_samples=self.min_samples,
                    ),
                    "hits": 0,
                    "fired": False,
                }
            if ent["fired"]:
                return None  # latched: one event per drift episode
            det: PageHinkley = ent["det"]
            tripped = det.update(value)
            ent["hits"] = ent["hits"] + 1 if tripped else 0
            if ent["hits"] < self.debounce:
                return None
            ent["fired"] = True
            event = {
                "scenario": int(scenario),
                "signal": signal,
                "value": round(float(value), 6),
                "mean": round(det.mean, 6),
                "stat": round(det.cum, 6),
                "threshold": det.threshold,
                "windows": det.n,
                "debounce": self.debounce,
            }
        return emit_record(self._sink, "drift_event", **event)

    def active(self) -> list[tuple[int, str]]:
        """(scenario, signal) streams whose drift_event has fired and not
        been reset — what the controller's adaptation queue drains."""
        with self._lock:
            return sorted(k for k, e in self._windows.items() if e["fired"])

    def reset(self, scenario: int | None = None) -> None:
        """Re-arm detectors (all of them, or one scenario's) — called after
        an adaptation deploys, so the bank learns the POST-adaptation
        distribution as its new in-distribution mean."""
        with self._lock:
            for (s, _sig), ent in self._windows.items():
                if scenario is None or s == int(scenario):
                    ent["det"].reset()
                    ent["hits"] = 0
                    ent["fired"] = False

    def state(self) -> dict:
        """Snapshot for status displays / control_event records."""
        with self._lock:
            return {
                f"{s}:{sig}": {
                    "n": e["det"].n,
                    "mean": round(e["det"].mean, 6),
                    "stat": round(e["det"].cum, 6),
                    "hits": e["hits"],
                    "fired": e["fired"],
                }
                for (s, sig), e in sorted(self._windows.items())
            }
