"""Canary-gated deployment + post-swap watch/rollback.

A fine-tuned checkpoint is a CANDIDATE, not a deploy: the gate evaluates it
against the LIVE params on held-out probe sets before it ever serves a
request, and keeps watching after the swap so a canary that lied (probe set
unlucky, drift moved again) is rolled back automatically.

Canary protocol (:meth:`Deployer.canary`):

- **drifted probes** — fresh samples from the drifted channel family (the
  distribution the candidate was fine-tuned FOR): the candidate must beat
  the live params by at least ``min_gain_db`` NMSE there, or the fine-tune
  bought nothing and does not deploy;
- **base probes, every scenario** — samples from the frozen families: the
  candidate must not regress any UN-drifted scenario by more than ``tol_db``
  (the single-trunk freeze makes big regressions structurally impossible —
  other trunks are bit-identical — but the routed pipeline is shared, so the
  gate verifies end-to-end anyway). The drifted scenario's frozen-family
  numbers are reported but never gated: that family no longer exists in
  production, and a trunk adapted to a large drift necessarily scores worse
  on it — gating there would block adaptation exactly when drift is
  largest.

Both sides run through the SAME fused serving forward
(``ServeEngine.offline_forward`` on throwaway engines), so the canary
measures exactly what production will serve. These are control-plane
compiles — never the serving process's request path.

Deploy (:meth:`Deployer.deploy`) goes through the existing hot-swap with an
EXPLICIT tag map (``swap_from_workdir(tags=...)`` / ``{"op": "swap",
"tags": ...}``): zero recompiles, in-flight batches keep the old params, and
a stale ``hdce_best`` can never shadow the promoted ``hdce_last``. The
pre-deploy tags are recorded as the rollback target.

Watch window (:meth:`Deployer.observe_served`): for ``watch_ticks`` ticks
after a deploy the controller feeds the served NMSE-parity stat; a
regression beyond ``rollback_db`` against the reference triggers an
immediate rollback swap to the recorded tags. Watch state is shared between
the controller tick thread and status readers (``_watch`` -> ``_lock``,
graftlint LOCK_MAP).
"""

from __future__ import annotations

import dataclasses
import threading

from qdml_tpu.utils import lockdep

import jax.numpy as jnp
import numpy as np

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.data.datasets import make_network_batch
from qdml_tpu.control.events import emit_record
from qdml_tpu.telemetry import span
from qdml_tpu.train.checkpoint import restore_params
from qdml_tpu.utils.metrics import nmse_db

# probe indices start well past both the training range and the loadgen
# offset (data_len * 3), so the canary never scores on samples any other
# consumer has seen
PROBE_INDEX_OFFSET = 5


def probe_batch(
    cfg: ExperimentConfig,
    scenario: int,
    n: int,
    drift_step: int = 0,
) -> dict[str, np.ndarray]:
    """``n`` held-out probe samples of one scenario (``drift_step > 0`` draws
    them from the DRIFTED family instead of the frozen one): ``{"x",
    "h_perf"}`` host arrays."""
    data = cfg.data
    if drift_step > 0:
        data = dataclasses.replace(
            data, drift_step=int(drift_step), drift_scenario=int(scenario)
        )
    geom = ChannelGeometry.from_config(data)
    i = jnp.arange(n)
    batch = make_network_batch(
        jnp.uint32(cfg.data.seed),
        jnp.full((n,), scenario),
        i % cfg.data.n_users,
        cfg.data.data_len * PROBE_INDEX_OFFSET + i,
        jnp.float32(cfg.data.snr_db),
        geom,
    )
    return {
        "x": np.asarray(batch["yp_img"], np.float32),
        "h_perf": np.asarray(batch["h_perf"], np.float32),
    }


def _probe_scorer(cfg, hdce_vars, clf_vars, quantum):
    """One engine + ONE jitted fused forward, reused across every probe set
    of a canary: ``offline_forward`` re-jits per call (fresh wrapper, fresh
    trace), which at S scenarios would mean 2·(S+1) compiles per canary —
    minutes of control-plane stall at S≫3 for a program that never changes
    between probe sets (all sets share probe_n, so one shape = one
    compile)."""
    import jax

    from qdml_tpu.serve.engine import ServeEngine

    eng = ServeEngine(cfg, hdce_vars, clf_vars, quantum=quantum)
    fwd = jax.jit(eng._forward)
    live = eng.live_vars()

    def score(probes) -> float:
        h, _pred, _conf = fwd(*live, jnp.asarray(probes["x"]))
        h = np.asarray(jax.device_get(h))
        err = float(np.sum((h - probes["h_perf"]) ** 2))
        pow_ = float(np.sum(probes["h_perf"] ** 2))
        return nmse_db(err / pow_)

    return score


def _served_nmse_db(cfg, hdce_vars, clf_vars, quantum, probes) -> float:
    """End-to-end NMSE (dB) of the fused serving forward on one probe set —
    classifier routing included, exactly what production serves. One-shot
    form of :func:`_probe_scorer` (which amortizes the compile across many
    probe sets)."""
    return _probe_scorer(cfg, hdce_vars, clf_vars, quantum)(probes)


class Deployer:
    """Canary gate + explicit-tag hot-swap + post-deploy watch/rollback.

    Transport-agnostic: ``swap_fn(tags)`` performs the actual swap — the
    in-process controller passes ``engine.swap_from_workdir``; the remote
    (``qdml-tpu control``) controller passes the ``{"op": "swap"}`` socket
    verb. The canary itself always evaluates locally from the shared
    workdir.
    """

    def __init__(
        self,
        cfg: ExperimentConfig,
        workdir: str,
        swap_fn,
        live_hdce_vars=None,
        clf_vars=None,
        quantum: bool = False,
        sink=None,
        dry_run: bool = False,
    ):
        ctl = cfg.control
        self.cfg = cfg
        self.workdir = workdir
        self._swap_fn = swap_fn
        self._live_hdce = live_hdce_vars
        self._clf = clf_vars
        self._quantum = quantum
        self._sink = sink
        self.dry_run = bool(dry_run)
        self.probe_n = int(ctl.probe_n)
        self.min_gain_db = float(ctl.min_gain_db)
        self.tol_db = float(ctl.tol_db)
        self.watch_ticks = int(ctl.watch_ticks)
        self.rollback_db = float(ctl.rollback_db)
        self._lock = lockdep.Lock("Deployer._lock")
        # active post-deploy watch: {"ticks_left", "ref_db", "rollback_tags",
        # "deployed_tags"} — None when no deploy is being watched
        self._watch: dict | None = None
        # the tag map this deployer last put live (deploy or rollback): the
        # engine-less (remote) canary resolves its LIVE baseline from these,
        # NOT from latest_tag — best > last would re-introduce the exact
        # stale-best-shadows-fresh-last bug the explicit-tag swap fixes
        self._live_tags: dict | None = None
        # the last canaried candidate: (tag, hdce_vars, clf_vars). An
        # engine-less deploy of that SAME tag binds these as the live
        # baseline (zero extra restores) — the fine-tune tag is REUSED
        # (hdce_last) every episode, so re-resolving the tracked tag at the
        # next episode's canary, after fine-tune overwrote it, would restore
        # the next candidate and compare it to itself (gain exactly 0,
        # adaptation permanently aborted)
        self._pending_cand: tuple | None = None

    def _emit(self, action: str, **payload) -> dict:
        return emit_record(
            self._sink, "control_event",
            action=action, dry_run=self.dry_run, **payload,
        )

    def _live_vars(self):
        """The params currently serving: the engine's live tuple when bound;
        else the tags THIS deployer last deployed (a prior adaptation's
        hdce_last must stay the baseline — latest_tag's best > last
        preference would resolve the stale original); else the newest
        workdir checkpoints (nothing deployed yet)."""
        if self._live_hdce is not None and self._clf is not None:
            return self._live_hdce, self._clf
        from qdml_tpu.train.checkpoint import CheckpointNotFoundError, restore_latest_params

        live_hdce_tag = (self._live_tags or {}).get("hdce")
        if live_hdce_tag is not None:
            hdce, _ = restore_params(self.workdir, live_hdce_tag)
        else:
            hdce, _, _ = restore_latest_params(self.workdir, "hdce")
        try:
            clf_tag = (self._live_tags or {}).get("qsc")
            if clf_tag is not None:
                clf, _ = restore_params(self.workdir, clf_tag)
            else:
                clf, _, _ = restore_latest_params(self.workdir, "qsc")
            quantum = True
        except CheckpointNotFoundError:
            clf_tag = (self._live_tags or {}).get("sc")
            if clf_tag is not None:
                clf, _ = restore_params(self.workdir, clf_tag)
            else:
                clf, _, _ = restore_latest_params(self.workdir, "sc")
            quantum = False
        self._quantum = quantum
        return hdce, clf

    def set_live(self, hdce_vars, clf_vars, quantum: bool | None = None) -> None:
        """Rebind the live reference after a confirmed deploy/rollback."""
        self._live_hdce = hdce_vars
        self._clf = clf_vars
        if quantum is not None:
            self._quantum = quantum

    def live_hdce_tag(self) -> str | None:
        """The hdce tag this deployer last deployed (None before any
        deploy) — the continual fine-tune's warm-start base: each episode
        must build on the tree that is actually SERVING, or a second
        episode's reassembly would silently revert the first episode's
        adapted trunk to the original checkpoint."""
        return (self._live_tags or {}).get("hdce")

    # -- canary -------------------------------------------------------------

    def canary(
        self, candidate_tag: str, scenario: int, drift_step: int
    ) -> dict:
        """Evaluate candidate vs live; returns the canary report with
        ``passed`` set. Never swaps — :meth:`deploy` does, and only when
        this passed."""
        cand_vars, _ = restore_params(self.workdir, candidate_tag)
        live_hdce, clf = self._live_vars()
        self._pending_cand = (candidate_tag, cand_vars, clf)
        with span("control_canary", scenario=scenario, tag=candidate_tag):
            # one compiled forward per SIDE for the whole canary (every
            # probe set shares probe_n, so the program never re-traces)
            score_live = _probe_scorer(self.cfg, live_hdce, clf, self._quantum)
            score_cand = _probe_scorer(self.cfg, cand_vars, clf, self._quantum)
            drifted = probe_batch(
                self.cfg, scenario, self.probe_n, drift_step=drift_step
            )
            drift_live = score_live(drifted)
            drift_cand = score_cand(drifted)
            base: dict = {}
            worst_regress = 0.0
            for s in range(self.cfg.data.n_scenarios):
                probes = probe_batch(self.cfg, s, self.probe_n, drift_step=0)
                live_db = score_live(probes)
                cand_db = score_cand(probes)
                base[str(s)] = {
                    "live_db": round(live_db, 3),
                    "cand_db": round(cand_db, 3),
                }
                if s == scenario:
                    # the DRIFTED scenario's frozen family no longer exists
                    # in production — a trunk adapted to a large drift
                    # necessarily regresses on it, and gating on that would
                    # block adaptation exactly when drift is largest. Its
                    # frozen-family numbers stay in the report (informational)
                    continue
                worst_regress = max(worst_regress, cand_db - live_db)
        gain = drift_live - drift_cand
        passed = gain >= self.min_gain_db and worst_regress <= self.tol_db
        return self._emit(
            "canary",
            passed=bool(passed),
            tag=candidate_tag,
            scenario=int(scenario),
            drift_step=int(drift_step),
            gain_db=round(gain, 3),
            min_gain_db=self.min_gain_db,
            worst_base_regress_db=round(worst_regress, 3),
            tol_db=self.tol_db,
            drifted_probes={
                "live_db": round(drift_live, 3), "cand_db": round(drift_cand, 3)
            },
            base_probes=base,
        )

    # -- deploy + watch -----------------------------------------------------

    def deploy(
        self,
        tags: dict,
        rollback_tags: dict,
        ref_db: float | None = None,
    ) -> dict:
        """Hot-swap ``tags`` live (explicit-tag path — a stale ``*_best``
        cannot shadow them) and arm the watch window with ``rollback_tags``
        as the escape hatch. ``ref_db`` is the served-NMSE reference the
        watch compares against (e.g. the canary's candidate probe figure)."""
        if self.dry_run:
            return self._emit("deploy", tags=tags, skipped="dry_run")
        rec = self._swap_fn(tags)
        self._live_tags = {**(self._live_tags or {}), **tags}
        pend = self._pending_cand
        if pend is not None and pend[0] == tags.get("hdce"):
            # bind the canary's already-restored candidate as the live
            # baseline (see _pending_cand above — zero extra restores, and
            # the next episode compares against what is actually serving).
            # UNCONDITIONAL on purpose: gating on `_live_hdce is None` would
            # fire only on the FIRST deploy and leave every later episode's
            # canary comparing against episode 1's tree; the in-process
            # controller overwrites this with the engine's live view right
            # after deploy anyway (loop.py), so both modes stay correct.
            self.set_live(pend[1], pend[2])
        with self._lock:
            self._watch = {
                "ticks_left": self.watch_ticks,
                "ref_db": ref_db,
                "rollback_tags": dict(rollback_tags),
                "deployed_tags": dict(tags),
            }
        return self._emit("deploy", tags=tags, swap=rec, ref_db=ref_db)

    def watching(self) -> bool:
        with self._lock:
            return self._watch is not None

    def observe_served(self, nmse_db_served: float | None) -> dict | None:
        """One watch tick: feed the latest served-NMSE stat (None when the
        tick had no measurement — the tick still counts down, a deploy must
        not stay on watch forever). Returns the rollback record when the
        watch tripped, the confirmation record when the window closed clean,
        else None."""
        with self._lock:
            if self._watch is None:
                return None
            w = self._watch
            regressed = (
                nmse_db_served is not None
                and w["ref_db"] is not None
                and nmse_db_served > w["ref_db"] + self.rollback_db
            )
            w["ticks_left"] -= 1
            confirmed = w["ticks_left"] <= 0 and not regressed
            if regressed or confirmed:
                self._watch = None
        if regressed:
            rec = self._swap_fn(w["rollback_tags"])
            # the rollback tags are now live: re-point the canary baseline
            # and drop any bound in-memory reference (it holds the params
            # the rollback just replaced)
            self._live_tags = {**(self._live_tags or {}), **w["rollback_tags"]}
            self._live_hdce = None
            self._clf = None
            return self._emit(
                "rollback",
                tags=w["rollback_tags"],
                from_tags=w["deployed_tags"],
                observed_db=round(float(nmse_db_served), 3),
                ref_db=w["ref_db"],
                rollback_db=self.rollback_db,
                swap=rec,
            )
        if confirmed:
            return self._emit("deploy_confirmed", tags=w["deployed_tags"])
        return None
