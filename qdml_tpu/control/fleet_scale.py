"""Fleet-tier autoscaling policy: the backend-COUNT axis (docs/FLEET.md).

:class:`~qdml_tpu.control.autoscale.Autoscaler` resizes replicas INSIDE the
existing hosts; this policy changes how many host processes exist, driving
:meth:`~qdml_tpu.fleet.lifecycle.BackendLifecycle.scale_to` (spawn-and-warm
admission / drain-then-retire) through an injected ``scale_fn``. The
discipline mirrors the replica scaler — deliberately boring hysteresis,
debounce, cooldown, hard min/max bounds, SLO-guarded scale-down — with two
fleet-tier additions:

- **burn-alert guard** — while the monitor's burn-rate alert is firing
  (telemetry/burnrate.py), scale-DOWN is refused outright: retiring
  capacity during an SLO-budget burn converts an incident into an outage.
  A burn alert alone never spawns either (it may be one stuck host the
  router is already ejecting, and the tiny-model serving tier recovers by
  failover faster than queue depth can build). The one exception is a burn
  WITH a provably short-handed fleet: when ``backends_live`` has fallen
  below the provisioned membership while an alert fires, the fleet is
  demonstrably down a host AND paging for it — that pair is the honest
  grow signal, and the spawn decision carries the alert's episode id so
  the event stream records which page drove it.
- **planner targets** — a ``plan --emit-target`` JSON
  (telemetry/capacity.py) pins the desired backend count directly: the
  policy converges to the planned count one cooldown-spaced step at a
  time (scale-down steps still SLO/burn-guarded), instead of walking the
  watermark band. The target rides with its ``assumptions_sha`` so the
  emitted events record WHICH planning run is being obeyed.

Every decision emits a structured ``fleet_scale_event``; ``dry_run``
reports decisions without calling ``scale_fn``. One spawn/retire at a time
(``cooldown_ticks`` must outlast a spawn-and-warm, which is seconds to
minutes) — the fleet never flaps on its own admission transient.
"""

from __future__ import annotations

import json
import threading

from qdml_tpu.utils import lockdep

from qdml_tpu.control.events import emit_record

#: scale-down is refused when windowed SLO attainment is below this (the
#: replica autoscaler's guard, docs/CONTROL.md — same floor, one tier up)
SLO_FLOOR = 0.99


def load_planner_target(path: str) -> dict:
    """Read a ``plan --emit-target`` JSON (telemetry/capacity.py
    :func:`emit_target` shape). Raises ValueError when the file carries no
    actionable count (``backends_needed: null`` — the planner's honest
    "unmeetable at any size" answer must not be silently coerced)."""
    with open(path) as fh:
        rec = json.load(fh)
    tgt = rec.get("fleet_target") if "fleet_target" in rec else rec
    if not isinstance(tgt, dict) or tgt.get("backends_needed") is None:
        raise ValueError(
            f"{path} carries no actionable backends_needed "
            "(planner target unmet at every candidate size?)"
        )
    return tgt


class FleetAutoscaler:
    """Hysteresis policy over the fleet-total queue depth (and/or a planner
    target), acting through ``scale_fn(n_backends) -> record``."""

    def __init__(
        self,
        scale_fn,
        min_backends: int = 1,
        max_backends: int = 4,
        queue_high: float = 32.0,
        queue_low: float = 2.0,
        debounce: int = 2,
        cooldown_ticks: int = 5,
        sink=None,
        dry_run: bool = False,
    ):
        if not 1 <= int(min_backends) <= int(max_backends):
            raise ValueError(
                f"need 1 <= min_backends <= max_backends, got "
                f"{min_backends}..{max_backends}"
            )
        if not float(queue_low) < float(queue_high):
            raise ValueError(
                f"need fleet_queue_low < fleet_queue_high, got "
                f"{queue_low} >= {queue_high}"
            )
        self.min_backends = int(min_backends)
        self.max_backends = int(max_backends)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.debounce = max(1, int(debounce))
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        self._scale_fn = scale_fn
        self._sink = sink
        self.dry_run = bool(dry_run)
        self._lock = lockdep.Lock("FleetAutoscaler._lock")
        self._target = self.min_backends
        self._high_streak = 0
        self._low_streak = 0
        self._cooldown = 0
        self._planner: dict | None = None
        self._decisions = 0

    def set_planner_target(self, target: dict | None) -> None:
        """Pin (or clear) a ``plan --emit-target`` record: the policy then
        converges to its ``backends_needed`` (clamped to the min/max
        bounds) instead of walking the watermarks."""
        with self._lock:
            self._planner = dict(target) if target else None

    def _clamp(self, n: int) -> int:
        return max(self.min_backends, min(self.max_backends, int(n)))

    def observe(
        self,
        queue_depth: float,
        backends: int,
        slo_attainment: float | None = None,
        burn_alert: bool = False,
        alert_episode: str | None = None,
        backends_live: int | None = None,
    ) -> dict | None:
        """One policy tick over the monitor's windowed signals. Returns the
        emitted ``fleet_scale_event`` payload when a decision fired, else
        None. ``backends`` is the OBSERVED provisioned membership — the
        policy re-anchors to it each tick, so an operator's manual
        fleet-scale is respected, exactly like the replica scaler.
        ``backends_live`` is the router's live (non-ejected) count when the
        caller has it: a firing burn alert combined with
        ``backends_live < backends`` counts as grow pressure (the fleet is
        provably short-handed AND paging), rides the same debounce, and the
        decision carries ``alert_episode`` — the burn alert's episode id —
        so the event stream answers "which alert drove this scale-up" by
        join, not by timestamp proximity."""
        slo_ok = slo_attainment is None or slo_attainment >= SLO_FLOOR
        short_handed = (
            burn_alert
            and backends_live is not None
            and int(backends_live) < max(1, int(backends))
        )
        with self._lock:
            self._target = max(1, int(backends))
            if self._cooldown > 0:
                self._cooldown -= 1
                self._high_streak = self._low_streak = 0
                return None
            planner = self._planner
            direction = None
            if planner is not None:
                desired = self._clamp(planner["backends_needed"])
                if desired > self._target:
                    direction = "up"
                elif desired < self._target and slo_ok and not burn_alert:
                    direction = "down"
            else:
                if queue_depth > self.queue_high or short_handed:
                    self._high_streak += 1
                    self._low_streak = 0
                elif queue_depth < self.queue_low and slo_ok and not burn_alert:
                    self._low_streak += 1
                    self._high_streak = 0
                else:
                    self._high_streak = self._low_streak = 0
                if (
                    self._high_streak >= self.debounce
                    and self._target < self.max_backends
                ):
                    direction = "up"
                elif (
                    self._low_streak >= self.debounce
                    and self._target > self.min_backends
                ):
                    direction = "down"
            if direction is None:
                return None
            new_target = self._target + (1 if direction == "up" else -1)
            self._target = new_target
            self._high_streak = self._low_streak = 0
            self._cooldown = self.cooldown_ticks
            self._decisions += 1
            decision = f"scale#{self._decisions}"
        rec = None if self.dry_run else self._scale_fn(new_target)
        return emit_record(
            self._sink, "fleet_scale_event",
            action="fleet_scale", direction=direction, backends=new_target,
            backends_before=int(backends),
            backends_live=None if backends_live is None else int(backends_live),
            queue_depth=float(queue_depth),
            slo_attainment=slo_attainment, burn_alert=bool(burn_alert),
            alert_episode=alert_episode if burn_alert else None,
            decision=decision,
            planner_sha=(planner or {}).get("assumptions_sha"),
            dry_run=self.dry_run, result=rec,
        )

    def state(self) -> dict:
        with self._lock:
            return {
                "target": self._target,
                "high_streak": self._high_streak,
                "low_streak": self._low_streak,
                "cooldown": self._cooldown,
                "planner": None if self._planner is None else {
                    "backends_needed": self._planner.get("backends_needed"),
                    "assumptions_sha": self._planner.get("assumptions_sha"),
                },
            }
