"""Queue-depth replica autoscaler with hysteresis.

Scaling signal: the live ``{"op": "metrics"}`` queue depth (requests waiting
at the shared micro-batcher) plus SLO attainment. Queue depth is the honest
load signal for this architecture — rps measures what WAS served, depth
measures what is NOT being served fast enough — and it is already in every
metrics poll, so the scaler costs nothing extra.

Policy (deliberately boring; an exciting autoscaler is an outage
generator):

- sustained depth above ``queue_high`` for ``scale_debounce`` consecutive
  ticks -> scale UP one replica (never above ``max_replicas``);
- sustained depth below ``queue_low`` (and SLO healthy) for
  ``scale_debounce`` ticks -> scale DOWN one (never below
  ``min_replicas``);
- ``cooldown_ticks`` must pass after any action before the next — the
  hysteresis band (high/low watermarks + debounce + cooldown) is what keeps
  one bursty MMPP cycle from flapping the pool.

Actions go through the drain-safe pool levers
(:meth:`~qdml_tpu.serve.server.ReplicaPool.add_replica` /
:meth:`~qdml_tpu.serve.server.ReplicaPool.remove_replica` — a removed
replica's queue share is drained by its peers via the shared
``ExitCoordinator``, pinned in tests) or, remotely, the ``{"op": "scale"}``
verb. Every decision emits a ``control_event`` record; in dry-run mode the
decision is reported and not taken.

Shared state: the debounce/cooldown counters and the current target are
written by the controller tick thread and read by status paths
(``_target`` -> ``_lock``, graftlint LOCK_MAP).
"""

from __future__ import annotations

import threading

from qdml_tpu.utils import lockdep

from qdml_tpu.control.events import emit_record


class Autoscaler:
    """Hysteresis controller: observe(queue_depth, slo, replicas) -> action.

    ``scale_fn(n)`` performs the resize (pool.scale_to in-process, the scale
    verb remotely); the scaler only decides.
    """

    def __init__(
        self,
        scale_fn,
        min_replicas: int = 1,
        max_replicas: int = 4,
        queue_high: float = 16.0,
        queue_low: float = 2.0,
        debounce: int = 2,
        cooldown_ticks: int = 3,
        sink=None,
        dry_run: bool = False,
    ):
        if not (1 <= min_replicas <= max_replicas):
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}"
            )
        if queue_low >= queue_high:
            raise ValueError(
                f"hysteresis band requires queue_low < queue_high, got "
                f"{queue_low} >= {queue_high}"
            )
        self._scale_fn = scale_fn
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.debounce = max(1, int(debounce))
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        self._sink = sink
        self.dry_run = bool(dry_run)
        self._lock = lockdep.Lock("Autoscaler._lock")
        # the scaler's shared decision state: current target replica count
        # (None until the first observation tells us the actual count),
        # debounce streaks and the cooldown countdown
        self._target: int | None = None
        self._high_streak = 0
        self._low_streak = 0
        self._cooldown = 0

    def _emit(self, **payload) -> dict:
        return emit_record(
            self._sink, "control_event",
            action="scale", dry_run=self.dry_run, **payload,
        )

    def state(self) -> dict:
        with self._lock:
            return {
                "target": self._target,
                "high_streak": self._high_streak,
                "low_streak": self._low_streak,
                "cooldown": self._cooldown,
            }

    def observe(
        self,
        queue_depth: float,
        replicas: int,
        slo_attainment: float | None = None,
    ) -> dict | None:
        """One tick: fold the latest depth reading in; returns the action
        record when a resize was decided (and, unless dry-run, performed),
        else None. ``replicas`` is the pool's CURRENT size from the same
        poll — the scaler re-anchors to it, so an operator's manual resize
        is respected rather than fought."""
        with self._lock:
            self._target = int(replicas)
            if self._cooldown > 0:
                self._cooldown -= 1
                self._high_streak = self._low_streak = 0
                return None
            if queue_depth > self.queue_high:
                self._high_streak += 1
                self._low_streak = 0
            elif queue_depth < self.queue_low and (
                slo_attainment is None or slo_attainment >= 0.99
            ):
                self._low_streak += 1
                self._high_streak = 0
            else:
                self._high_streak = self._low_streak = 0
            up = (
                self._high_streak >= self.debounce
                and self._target < self.max_replicas
            )
            down = (
                self._low_streak >= self.debounce
                and self._target > self.min_replicas
            )
            if not (up or down):
                return None
            new_target = self._target + (1 if up else -1)
            self._target = new_target
            self._high_streak = self._low_streak = 0
            self._cooldown = self.cooldown_ticks
        direction = "up" if up else "down"
        rec = None if self.dry_run else self._scale_fn(new_target)
        return self._emit(
            direction=direction,
            replicas=new_target,
            queue_depth=round(float(queue_depth), 2),
            queue_high=self.queue_high,
            queue_low=self.queue_low,
            slo_attainment=slo_attainment,
            result=rec,
        )
