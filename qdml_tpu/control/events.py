"""One home for control-plane telemetry emission.

Every control module needs the same three lines — resolve the explicit sink
or the process-global one, check it is active, emit a ``counters`` record —
and keeping four copies in sync is how a future record-shape change
silently drops telemetry from one emitter. ``drift_event`` and
``control_event`` records (schemas: docs/CONTROL.md) both route through
here.
"""

from __future__ import annotations

from qdml_tpu.telemetry.events import publish
from qdml_tpu.telemetry.spans import get_sink


def emit_record(sink, name: str, **payload) -> dict:
    """Emit one ``counters`` record named ``name`` to ``sink`` (or the
    process-global sink when ``sink`` is None); returns the payload either
    way, so callers can use the emitted record as their return value.
    Every record also lands on the process-global event spine
    (telemetry/events.py) — the sink is the durable JSONL, the bus feeds
    the live ``{"op": "events"}`` tail."""
    target = sink if sink is not None else get_sink()
    if target is not None and getattr(target, "active", False):
        target.emit("counters", name=name, **payload)
    publish(name, tier="control", **payload)
    return payload
