"""Fleet control plane: the closed serve -> detect -> adapt -> deploy loop.

PRs 7-9 built every hook this package needs — live ``{"op": "metrics"}``
serve statistics with per-scenario confidence, continual-training-ready
checkpoint machinery, zero-recompile hot-swap (``{"op": "swap"}``), elastic
replica pools — but nothing CLOSED the loop: a drifting scenario degraded
silently until a human retrained. This package is the supervisor that runs
the cycle autonomously (QuantumNAT's argument, arXiv 2110.11331, applied at
fleet scope: models must be adapted to the perturbed conditions they
actually face, not the clean ones they were born in):

- :mod:`~qdml_tpu.control.drift` — streaming Page-Hinkley/CUSUM detectors
  over per-scenario serve statistics (classifier confidence, served NMSE
  parity, routing overflow rate) with debounce, emitting structured
  ``drift_event`` records;
- :mod:`~qdml_tpu.control.finetune` — continual fine-tuning of ONLY the
  drifted scenario trunk (warm-start from the live checkpoint, shared FC
  head and every other trunk frozen — bit-identical, pinned), on fresh
  on-device batches from the drifted channel family;
- :mod:`~qdml_tpu.control.deploy` — canary-gated deployment: candidate vs
  live on held-out probes, deploy through the existing hot-swap path with
  an EXPLICIT checkpoint tag, automatic rollback when post-swap serving
  regresses inside the watch window;
- :mod:`~qdml_tpu.control.autoscale` — a queue-depth/SLO replica autoscaler
  with hysteresis over the drain-safe
  :meth:`~qdml_tpu.serve.server.ReplicaPool.add_replica` /
  :meth:`~qdml_tpu.serve.server.ReplicaPool.remove_replica` levers;
- :mod:`~qdml_tpu.control.loop` — :class:`FleetController` wiring it all
  into one supervised loop (``qdml-tpu control``), with a dry-run mode that
  reports every decision and takes none. Attached through
  :class:`~qdml_tpu.fleet.poller.FleetPoller` (or ``SocketPoller`` at the
  router's front address) the SAME loop supervises a multi-process fleet
  behind ``qdml-tpu route`` — docs/FLEET.md.

Knobs: :class:`qdml_tpu.config.ControlConfig`. Record schemas + operational
guidance: ``docs/CONTROL.md``. The committed closed-loop proof:
``results/control_dryrun/`` (scripts/control_dryrun.py).
"""

from qdml_tpu.control.autoscale import Autoscaler  # noqa: F401
from qdml_tpu.control.deploy import Deployer  # noqa: F401
from qdml_tpu.control.drift import DriftMonitor, PageHinkley  # noqa: F401
from qdml_tpu.control.finetune import finetune_trunk  # noqa: F401
from qdml_tpu.control.loop import FleetController  # noqa: F401
