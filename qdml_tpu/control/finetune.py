"""Continual fine-tuning of ONE drifted scenario trunk.

The adaptation step of the control loop (docs/CONTROL.md): when a scenario's
channel family drifts, ONLY that scenario's expert trunk needs new weights —
the shared FC head encodes cross-scenario structure serving every family,
and the other trunks' families did not move. Retraining everything would be
slower, riskier (a full retrain can regress healthy scenarios) and pointless.

Mechanics:

- **warm start** — the live checkpoint restores from the workdir (explicit
  ``base_tag`` or ``latest_tag`` discovery, exactly the restore machinery
  serving uses), so fine-tuning continues from the deployed weights;
- **single-trunk isolation** — the stacked trunk params carry a leading
  scenario axis (:class:`~qdml_tpu.models.cnn.StackedConvP128`), so slice
  ``s`` is carved into a 1-scenario :class:`~qdml_tpu.train.hdce.HDCE`
  twin (identical module names -> identical param tree modulo the leading
  axis). Every OTHER trunk never enters the fine-tune step at all — frozen
  by construction, bit-identical by construction;
- **masked head** — the shared FC head must ride along in the forward (the
  trunk adapts TO the frozen head) but must not move: an
  ``optax.multi_transform`` maps its subtree to ``set_to_zero`` while the
  trunk slice gets Adam — the masked-optimizer half of the freeze. At
  reassembly the head subtree is taken from the BASE checkpoint verbatim,
  so head bit-identity is guaranteed even against degenerate float edge
  cases (``-0.0 + 0.0``), not just expected;
- **drifted on-device data** — fresh batches synthesize inside the jitted
  step from the drifted channel family (``family_table`` at the detected
  drift step, the scenario's row perturbed), via the grid loader's scenario
  slice — no files, no host batch build;
- **normal checkpoint tags** — the reassembled full tree saves as
  ``hdce_last`` with provenance meta, so every existing restore path
  (serving, eval, export) works unchanged. The deployer must pass this tag
  EXPLICITLY to the hot-swap: ``latest_tag``'s best > last preference would
  let a stale ``hdce_best`` from the original training run shadow it (the
  fix in ``ServeEngine.swap_from_workdir``).

Compile accounting: fine-tune steps compile like any training — in a
production fleet this runs on a trainer process, not the serving process;
the in-process dryrun snapshots compile counters per traffic window so the
zero-request-path-compile pins stay meaningful (scripts/control_dryrun.py).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import optax

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.data.datasets import DMLGridLoader
from qdml_tpu.telemetry import span
from qdml_tpu.train.checkpoint import (
    latest_tag,
    restore_params,
    save_checkpoint,
)
from qdml_tpu.train.hdce import HDCE, make_hdce_eval_step, make_hdce_train_step
from qdml_tpu.train.state import TrainState
from qdml_tpu.models.cnn import activation_dtype


def _subtree_keys(params: dict) -> tuple[str, str]:
    """(trunk_key, head_key) of the HDCE param tree — resolved by module
    name rather than hardcoded index, so a flax renaming fails loudly here
    instead of silently freezing the wrong subtree."""
    trunk = next((k for k in params if "StackedConv" in k), None)
    head = next((k for k in params if "FCP128" in k), None)
    if trunk is None or head is None:
        raise ValueError(
            f"HDCE param tree missing trunk/head subtrees (have {sorted(params)})"
        )
    return trunk, head


def _slice_scenario(tree, s: int):
    """Take stacked-axis slice ``s`` keeping the leading axis (S=1)."""
    return jax.tree.map(lambda a: np.asarray(a)[s : s + 1], tree)


def _scatter_scenario(base_tree, ft_tree, s: int):
    """Write the fine-tuned slice back into a COPY of the base stack; every
    other row is a byte-for-byte copy of the base array (the bit-identity
    pin in tests/test_control.py)."""

    def _set(b, f):
        out = np.array(b)  # host copy; rows != s untouched bits
        out[s] = np.asarray(f[0], out.dtype)
        return out

    return jax.tree.map(_set, base_tree, ft_tree)


def finetune_trunk(
    cfg: ExperimentConfig,
    workdir: str,
    scenario: int,
    drift_step: int,
    steps: int | None = None,
    lr: float | None = None,
    batch_size: int | None = None,
    base_tag: str | None = None,
    seed: int = 0,
) -> dict:
    """Fine-tune scenario ``scenario``'s trunk on its drifted channel family
    and save the reassembled checkpoint as ``hdce_last``.

    Returns the promotion record: ``{"tag", "rollback_tag", "scenario",
    "drift_step", "steps", "loss_first", "loss_last", "val_nmse_db_before",
    "val_nmse_db_after", "base_tag"}``. ``rollback_tag`` names a checkpoint
    holding the PRE-fine-tune params (the warm-start source; when
    ``hdce_last`` itself was the source, a ``hdce_prev`` backup is written
    first so rolling back from disk is always possible).
    """
    if not (0 <= scenario < cfg.data.n_scenarios):
        raise ValueError(
            f"scenario must be < {cfg.data.n_scenarios}, got {scenario}"
        )
    if drift_step < 1:
        raise ValueError(f"drift_step must be >= 1 to fine-tune, got {drift_step}")
    ctl = cfg.control
    steps = int(steps if steps is not None else ctl.ft_steps)
    lr = float(lr if lr is not None else ctl.ft_lr)
    batch_size = int(batch_size if batch_size is not None else ctl.ft_batch)

    base_tag = base_tag or latest_tag(workdir, "hdce")
    if base_tag is None:
        raise FileNotFoundError(
            f"no hdce checkpoint under {workdir!r} to warm-start from"
        )
    base_vars, base_meta = restore_params(workdir, base_tag)
    trunk_key, head_key = _subtree_keys(base_vars["params"])

    # 1-scenario twin of the serving model: same module classes, same names,
    # so the sliced subtrees drop straight in
    model = HDCE(
        n_scenarios=1,
        features=cfg.model.features,
        out_dim=cfg.h_out_dim,
        dtype=activation_dtype(cfg.model.dtype),
        bn_momentum=0.9**cfg.data.n_users,
        conv_impl=cfg.model.conv_impl,
    )
    params = {
        trunk_key: _slice_scenario(base_vars["params"][trunk_key], scenario),
        head_key: jax.tree.map(np.asarray, base_vars["params"][head_key]),
    }
    batch_stats = {
        trunk_key: _slice_scenario(base_vars["batch_stats"][trunk_key], scenario)
    }
    # masked optimizer: the trunk trains, the shared head's updates are
    # ZEROED — it shapes the gradients (the trunk adapts to the head it will
    # serve behind) but never moves
    labels = {
        trunk_key: jax.tree.map(lambda _: "train", params[trunk_key]),
        head_key: jax.tree.map(lambda _: "freeze", params[head_key]),
    }
    tx = optax.multi_transform(
        {"train": optax.adam(lr), "freeze": optax.set_to_zero()}, labels
    )
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=tx, batch_stats=batch_stats
    )

    # drifted single-scenario data: the loader's scenario slice generates
    # ONLY rows of family `scenario`, whose family_table row is perturbed at
    # the detected drift step — synthesis happens inside the jitted step
    drift_data = dataclasses.replace(
        cfg.data, drift_step=int(drift_step), drift_scenario=int(scenario),
        seed=cfg.data.seed + seed,
    )
    geom = ChannelGeometry.from_config(drift_data)
    train_loader = DMLGridLoader(drift_data, batch_size, "train", geom)
    train_loader.set_process_slice(
        0, train_loader.batch_size, scen_start=scenario, scen_count=1
    )
    val_loader = DMLGridLoader(drift_data, batch_size, "val", geom)
    val_loader.set_process_slice(
        0, val_loader.batch_size, scen_start=scenario, scen_count=1
    )

    train_step = make_hdce_train_step(model, state.tx, probes=False)
    eval_step = make_hdce_eval_step(model)

    def _val_nmse_db(st) -> float:
        err = pow_ = 0.0
        for i, batch in enumerate(val_loader.epoch(0, shuffle=False)):
            out = eval_step(st, batch)
            err += float(out["err"])
            pow_ += float(out["pow"])
            if i >= 3:  # a few hundred samples bound the probe cost
                break
        return 10.0 * np.log10(max(err / max(pow_, 1e-30), 1e-30))

    with span("control_finetune", scenario=scenario, drift_step=drift_step, steps=steps):
        val_before = _val_nmse_db(state)
        loss_first = loss_last = None
        done = 0
        epoch = 0
        while done < steps:
            for batch in train_loader.epoch(epoch):
                state, m = train_step(state, batch)
                loss_last = float(m["loss"])
                if loss_first is None:
                    loss_first = loss_last
                done += 1
                if done >= steps:
                    break
            epoch += 1
        val_after = _val_nmse_db(state)
    if loss_last is None or not np.isfinite(loss_last):
        raise RuntimeError(
            f"fine-tune of scenario {scenario} produced non-finite loss "
            f"({loss_last}) — refusing to promote a checkpoint"
        )

    # reassemble: fine-tuned slice scattered into the base stack; head and
    # every other trunk are the BASE arrays verbatim (bit-identity by
    # construction, not by arithmetic)
    new_params = dict(base_vars["params"])
    new_params[trunk_key] = _scatter_scenario(
        base_vars["params"][trunk_key], state.params[trunk_key], scenario
    )
    new_stats = dict(base_vars["batch_stats"])
    new_stats[trunk_key] = _scatter_scenario(
        base_vars["batch_stats"][trunk_key], state.batch_stats[trunk_key], scenario
    )

    rollback_tag = base_tag
    if base_tag == "hdce_last":
        # the promotion below overwrites the warm-start source: keep a disk
        # copy so rollback never depends on in-memory state alone
        save_checkpoint(workdir, "hdce_prev", base_vars, base_meta or None)
        rollback_tag = "hdce_prev"
    rec = {
        "tag": "hdce_last",
        "rollback_tag": rollback_tag,
        "base_tag": base_tag,
        "scenario": int(scenario),
        "drift_step": int(drift_step),
        "steps": steps,
        "lr": lr,
        "loss_first": loss_first,
        "loss_last": loss_last,
        "val_nmse_db_before": round(val_before, 3),
        "val_nmse_db_after": round(val_after, 3),
    }
    meta = {
        "epoch": int((base_meta or {}).get("epoch", -1)),
        "name": cfg.name,
        "finetune": {k: rec[k] for k in (
            "scenario", "drift_step", "steps", "lr", "base_tag",
            "val_nmse_db_before", "val_nmse_db_after",
        )},
    }
    save_checkpoint(
        workdir, "hdce_last", {"params": new_params, "batch_stats": new_stats}, meta
    )
    return rec
