"""FleetController: the supervised serve -> detect -> adapt -> deploy loop.

One controller per fleet. Each tick it polls the live metrics view (the
same payload the ``{"op": "metrics"}`` verb serves), differences the
per-scenario counters against the previous poll into WINDOWED statistics,
feeds the drift detectors and the autoscaler, services any post-deploy
watch window, and — when a debounced drift_event has fired — runs the
adaptation pipeline:

    drift_event(scenario s)
      -> finetune_trunk(s)        # only trunk s trains; head + peers frozen
      -> Deployer.canary          # candidate vs live on held-out probes
      -> Deployer.deploy          # explicit-tag hot-swap, zero recompiles
      -> watch window             # served stats; auto-rollback on regress
      -> DriftMonitor.reset       # re-arm against the adapted distribution

Three attachment modes share all of that logic:

- **in-process** (:class:`PoolPoller`) — the controller holds the
  :class:`~qdml_tpu.serve.server.ReplicaPool` and
  :class:`~qdml_tpu.serve.engine.ServeEngine` directly (the dryrun/test
  harness, scripts/control_dryrun.py);
- **remote** (:class:`SocketPoller`, ``qdml-tpu control``) — the controller
  attaches to a running ``qdml-tpu serve`` endpoint over the
  ``metrics``/``swap``/``scale`` verbs and shares only the checkpoint
  workdir; fine-tune and canary run in the controller's process, so the
  serving process's request path never compiles;
- **fleet** (:class:`~qdml_tpu.fleet.poller.FleetPoller`, docs/FLEET.md) —
  the same verbs against a ``qdml-tpu route`` front door: drift detection
  windows the AGGREGATED per-scenario counters (raw sums difference
  exactly), tagged swaps fan out to every live backend, and scale targets
  the fleet total while the router chooses WHICH host to resize. Because
  the router speaks the serve protocol verbatim, ``SocketPoller`` pointed
  at ``fleet.host:fleet.port`` is the remote form — nothing here changes.

Drift-step hint: in this reproduction the drifted channel family is
SYNTHESIZED (``family_table`` drift trajectories) — the controller cannot
measure the environment's true drift step from serve stats alone, so
``drift_step_hint`` (default ``serve.drift_step``, the injected value in
the harnesses) tells fine-tune/canary which family to synthesize. A real
deployment replaces that data source with logged production traffic; every
other part of the loop is production-shaped.

Every decision lands in the telemetry stream as a structured
``control_event`` record (schemas: docs/CONTROL.md); ``control.dry_run``
reports decisions without acting on any of them.
"""

from __future__ import annotations

import json
import socket
import threading
import time

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.control.autoscale import Autoscaler
from qdml_tpu.control.deploy import Deployer
from qdml_tpu.control.drift import DriftMonitor
from qdml_tpu.control.events import emit_record
from qdml_tpu.telemetry.timeseries import counter_delta

# an adaptation that keeps failing its canary must not retrain forever on
# the same drift episode: after this many failed attempts per scenario the
# stream stays latched and a human reads the control_events
MAX_ADAPT_ATTEMPTS = 3


class PoolPoller:
    """In-process attachment: the controller owns references to the live
    pool + engine + workdir (dryrun/tests)."""

    def __init__(self, pool, engine, workdir: str):
        self.pool = pool
        self.engine = engine
        self.workdir = workdir

    def metrics(self) -> dict:
        return self.pool.live_metrics()

    def health(self) -> dict:
        return self.pool.health()

    def swap(self, tags: dict) -> dict:
        return self.engine.swap_from_workdir(self.workdir, tags=tags)

    def scale(self, n: int) -> dict:
        return self.pool.scale_to(n)


class SocketPoller:
    """Remote attachment over the serve socket's JSON verbs (one short-lived
    connection per call: the controller polls on second timescales, and a
    held-open connection would couple its lifetime to the server's)."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        self.host = host
        self.port = port
        self.timeout_s = float(timeout_s)

    def _verb(self, payload: dict) -> dict:
        with socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        ) as sk:
            fh = sk.makefile("rw", encoding="utf-8", newline="\n")
            fh.write(json.dumps(payload) + "\n")
            fh.flush()
            line = fh.readline()
        if not line:
            raise ConnectionError(f"serve endpoint {self.host}:{self.port} closed")
        rep = json.loads(line)
        if not rep.get("ok"):
            raise RuntimeError(f"verb {payload.get('op')!r} failed: {rep.get('reason')}")
        return rep

    def metrics(self) -> dict:
        return self._verb({"op": "metrics"})["metrics"]

    def health(self) -> dict:
        """The cheap 1 Hz liveness view (no histogram merges server-side) —
        what the continuous monitor scrapes between metrics polls."""
        return self._verb({"op": "health"})["health"]

    def events(self, cursor: dict | None = None, limit: int = 512) -> dict:
        """The event-spine tail (docs/TELEMETRY.md "event spine"): the
        monitor's third sanctioned verb. Pass the previous reply's cursor
        back to resume with no gaps and no duplicates."""
        msg: dict = {"op": "events", "limit": int(limit)}
        if cursor is not None:
            msg["cursor"] = cursor
        return self._verb(msg)["events"]

    def swap(self, tags: dict) -> dict:
        return self._verb({"op": "swap", "tags": tags})["swap"]

    def scale(self, n: int) -> dict:
        return self._verb({"op": "scale", "replicas": n})["scale"]

    def fleet(self, backends: int | None = None) -> dict:
        """Backend-count axis (router endpoints): membership status, or —
        with ``backends`` — converge the serving member count through the
        router's lifecycle manager. A plain serve host answers the status
        form with ``bad_request`` and a lifecycle-less router answers the
        scaling form with ``fleet_scale_unavailable``; both surface here as
        the typed RuntimeError ``_verb`` raises on ok=false."""
        if backends is None:
            return self._verb({"op": "fleet"})["fleet"]
        return self._verb({"op": "fleet", "backends": int(backends)})["fleet"]


class FleetController:
    """The loop. Construct with a poller, call :meth:`tick` (or :meth:`run`);
    harnesses with ground truth additionally feed :meth:`observe_parity`."""

    def __init__(
        self,
        cfg: ExperimentConfig,
        workdir: str,
        poller,
        engine=None,
        sink=None,
        drift_step_hint: int | None = None,
    ):
        ctl = cfg.control
        self.cfg = cfg
        self.workdir = workdir
        self.poller = poller
        self.engine = engine
        self._sink = sink
        self.dry_run = bool(ctl.dry_run)
        self.drift_step_hint = int(
            drift_step_hint
            if drift_step_hint is not None
            else (cfg.serve.drift_step or 1)
        )
        self.min_window = int(ctl.min_window)
        self.monitor = DriftMonitor(
            delta=ctl.ph_delta,
            threshold=ctl.ph_threshold,
            debounce=ctl.debounce,
            min_samples=5,
            sink=sink,
        )
        self.autoscaler = (
            Autoscaler(
                poller.scale,
                min_replicas=ctl.min_replicas,
                max_replicas=ctl.max_replicas,
                queue_high=ctl.queue_high,
                queue_low=ctl.queue_low,
                debounce=ctl.scale_debounce,
                cooldown_ticks=ctl.cooldown_ticks,
                sink=sink,
                dry_run=ctl.dry_run,
            )
            if ctl.autoscale
            else None
        )
        live = engine.live_vars() if engine is not None else (None, None)
        self.deployer = Deployer(
            cfg,
            workdir,
            swap_fn=poller.swap,
            live_hdce_vars=live[0],
            clf_vars=live[1],
            quantum=bool(getattr(engine, "quantum", False)),
            sink=sink,
            dry_run=ctl.dry_run,
        )
        self._prev_scenario: dict = {}
        self._prev_dispatch: dict = {}
        # latest served-NMSE measurement PER SCENARIO: the post-deploy watch
        # must compare the adapted scenario's own parity against the canary
        # reference — another scenario's intrinsically-worse NMSE fed to a
        # scenario-agnostic slot would trip spurious rollbacks
        self._latest_parity: dict[int, float] = {}
        self._watch_scenario: int | None = None
        self._attempts: dict[int, int] = {}
        self._prev_slo: dict | None = None
        # dry-run adapt decisions and suspensions are reported ONCE per drift
        # episode — a latched detector would otherwise re-report every tick
        # forever
        self._dry_reported: set[int] = set()
        self._suspended_reported: set[int] = set()
        self.ticks = 0

    # -- telemetry ----------------------------------------------------------

    def _emit(self, action: str, **payload) -> dict:
        return emit_record(
            self._sink, "control_event",
            action=action, dry_run=self.dry_run, **payload,
        )

    # -- external ground-truth feed ------------------------------------------

    def observe_parity(self, scenario: int, nmse_db_served: float) -> dict | None:
        """Feed a served-NMSE measurement (dB) for one scenario — harnesses
        that know ground truth (loadgen windows, the dryrun) wire this; it
        drives both the ``nmse_parity`` drift detector and the post-deploy
        watch reference (keyed by scenario — the watch only reads the
        adapted scenario's own stream)."""
        self._latest_parity[int(scenario)] = float(nmse_db_served)
        return self.monitor.observe(scenario, "nmse_parity", nmse_db_served)

    # -- the loop ------------------------------------------------------------

    def _window_scenarios(self, m: dict) -> list[dict]:
        """Difference this poll's per-scenario cumulative counters against
        the previous poll into windowed means; feed the detectors."""
        events = []
        per = m.get("per_scenario") or {}
        for key, cur in per.items():
            prev = self._prev_scenario.get(key, {"n": 0, "conf_sum": 0.0})
            dn, reset = counter_delta(prev.get("n"), cur.get("n"))
            dconf, _ = counter_delta(prev.get("conf_sum"), cur.get("conf_sum"))
            if reset:
                # a restarted backend's counters started over: naive
                # subtraction would feed the detector a negative "window",
                # and the clamped delta mixes pre-/post-restart history —
                # report the reset, skip this window's detector feed
                emit_record(
                    self._sink, "counter_reset", source="control_loop",
                    counter=f"per_scenario[{key}].n",
                    prev=prev.get("n", 0), cur=cur.get("n", 0),
                )
            elif dn >= self.min_window and cur.get("conf_sum") is not None:
                ev = self.monitor.observe(int(key), "confidence", dconf / dn)
                if ev:
                    events.append(ev)
        self._prev_scenario = {
            k: {"n": v.get("n", 0), "conf_sum": v.get("conf_sum", 0.0)}
            for k, v in per.items()
        }
        disp = m.get("dispatch") or {}
        prev_d = self._prev_dispatch
        d_routed, r_reset = counter_delta(
            prev_d.get("routed_rows"), disp.get("routed_rows")
        )
        d_over, o_reset = counter_delta(
            prev_d.get("overflow_rows"), disp.get("overflow_rows")
        )
        if r_reset or o_reset:
            emit_record(
                self._sink, "counter_reset", source="control_loop",
                counter="dispatch.routed_rows",
                prev=prev_d.get("routed_rows") or 0,
                cur=disp.get("routed_rows") or 0,
            )
        elif d_routed >= self.min_window:
            ev = self.monitor.observe(-1, "overflow_rate", d_over / d_routed)
            if ev:
                events.append(ev)
        self._prev_dispatch = {
            "routed_rows": disp.get("routed_rows"),
            "overflow_rows": disp.get("overflow_rows"),
        }
        return events

    def _windowed_slo(self, slo: dict | None) -> float | None:
        """Attainment over THIS poll window (cumulative counters
        differenced), like every other detector input. The pool-lifetime
        aggregate would let one early overload veto scale-down forever."""
        prev = self._prev_slo
        self._prev_slo = dict(slo) if slo else self._prev_slo
        if not slo:
            return None
        dn, reset = counter_delta((prev or {}).get("n"), slo.get("n"))
        dmet, _ = counter_delta((prev or {}).get("met"), slo.get("met"))
        if reset:
            # restart mid-window: attainment over a clamped window would
            # blend two processes' histories — report, return no reading
            emit_record(
                self._sink, "counter_reset", source="control_loop",
                counter="slo.n",
                prev=(prev or {}).get("n", 0), cur=slo.get("n", 0),
            )
            return None
        return dmet / dn if dn > 0 else None

    def _adapt(self, scenario: int) -> dict:
        """The adaptation pipeline for one drifted scenario."""
        from qdml_tpu.control.finetune import finetune_trunk

        attempts = self._attempts.get(scenario, 0)
        if attempts >= MAX_ADAPT_ATTEMPTS:
            if scenario in self._suspended_reported:
                return {}
            self._suspended_reported.add(scenario)
            return self._emit(
                "adapt_suspended", scenario=scenario, attempts=attempts
            )
        if self.dry_run:
            if scenario in self._dry_reported:
                return {}
            self._dry_reported.add(scenario)
            return self._emit(
                "adapt", scenario=scenario, skipped="dry_run",
                drift_step=self.drift_step_hint,
            )
        self._attempts[scenario] = attempts + 1
        ft = finetune_trunk(
            self.cfg, self.workdir, scenario, drift_step=self.drift_step_hint,
            # continual: warm-start from the tree that is SERVING (the
            # deployer's tracked tag once anything deployed) — latest_tag's
            # best > last preference would base a second episode on the
            # ORIGINAL checkpoint and revert the first episode's trunk
            base_tag=self.deployer.live_hdce_tag(),
        )
        self._emit("finetune", **ft)
        rep = self.deployer.canary(ft["tag"], scenario, self.drift_step_hint)
        if not rep["passed"]:
            # re-arm: if the drift persists, the detectors re-fire after
            # fresh debounced windows and we try again (bounded above)
            self.monitor.reset(scenario)
            return self._emit("adapt_aborted", scenario=scenario, canary=rep)
        dep = self.deployer.deploy(
            tags={"hdce": ft["tag"]},
            rollback_tags={"hdce": ft["rollback_tag"]},
            ref_db=rep["drifted_probes"]["cand_db"],
        )
        if self.engine is not None:
            # rebind the canary's live reference to the now-serving params
            self.deployer.set_live(*self.engine.live_vars())
        # the WHOLE bank re-arms: post-deploy serve stats are a new
        # distribution for every scenario (routing shares the classifier).
        # The per-scenario poll snapshot is deliberately KEPT — clearing it
        # would make the re-armed detectors' first window a difference
        # against zero, i.e. a pool-lifetime aggregate, not a window
        self.monitor.reset()
        # a deploy invalidates any parity measured against the OLD params:
        # the watch must wait for a fresh post-deploy measurement (ticks
        # without one still count down), not roll back on a stale reading
        self._watch_scenario = scenario
        self._latest_parity.pop(scenario, None)
        self._attempts[scenario] = 0
        return self._emit(
            "adapted", scenario=scenario, finetune=ft, canary=rep, deploy=dep
        )

    def tick(self) -> dict:
        """One observe -> decide -> act cycle; returns what happened (the
        same facts the control_event records carry)."""
        self.ticks += 1
        m = self.poller.metrics()
        out: dict = {"tick": self.ticks, "events": []}
        out["events"].extend(self._window_scenarios(m))
        if self.autoscaler is not None:
            act = self.autoscaler.observe(
                float(m.get("queue_depth_now") or 0.0),
                int(m.get("replicas") or 1),
                self._windowed_slo(m.get("slo")),
            )
            if act:
                out["events"].append(act)
        if self.deployer.watching():
            watch = self.deployer.observe_served(
                self._latest_parity.get(self._watch_scenario)
                if self._watch_scenario is not None
                else None
            )
            if watch:
                out["events"].append(watch)
        else:
            fired = [s for s, _sig in self.monitor.active() if s >= 0]
            for scenario in fired:
                ev = self._adapt(scenario)
                if ev:
                    out["events"].append(ev)
                if self._attempts.get(scenario, 0) < MAX_ADAPT_ATTEMPTS:
                    # one real adaptation per tick; a SUSPENDED scenario only
                    # (re-)reports and must not starve later-numbered drifted
                    # scenarios of their turn
                    break
        return out

    def run(
        self,
        ticks: int | None = None,
        interval_s: float | None = None,
        stop: threading.Event | None = None,
    ) -> int:
        """Tick until ``ticks`` is exhausted / ``stop`` is set /
        KeyboardInterrupt. Transient endpoint failures (server restarting)
        are reported and retried next tick; they must not kill the
        supervisor."""
        interval = float(
            interval_s if interval_s is not None else self.cfg.control.interval_s
        )
        done = 0
        try:
            while (ticks is None or done < ticks) and not (stop and stop.is_set()):
                try:
                    self.tick()
                except (ConnectionError, OSError, TimeoutError) as e:
                    self._emit("poll_failed", error=str(e))
                except (RuntimeError, ValueError, FileNotFoundError) as e:
                    # an adaptation-pipeline failure (rejected swap verb,
                    # non-finite fine-tune loss, checkpoint race) is ONE
                    # failed episode, not a reason to stop supervising the
                    # fleet — autoscaling, watch/rollback and detection must
                    # keep running; the record carries the error
                    self._emit(
                        "tick_failed", error=f"{type(e).__name__}: {e}"
                    )
                done += 1
                if stop is not None:
                    stop.wait(interval)
                else:
                    time.sleep(interval)
        except KeyboardInterrupt:
            pass
        return 0

    def run_in_thread(
        self, interval_s: float | None = None
    ) -> tuple[threading.Thread, threading.Event]:
        """Background supervision (the dryrun runs the controller alongside
        live loadgen traffic): returns (thread, stop_event)."""
        stop = threading.Event()
        t = threading.Thread(
            target=self.run,
            kwargs={"interval_s": interval_s, "stop": stop},
            daemon=True,
            name="fleet-controller",
        )
        t.start()
        return t, stop


def control_main(
    cfg: ExperimentConfig, logger=None, workdir: str | None = None, ticks: int | None = None
) -> int:
    """``qdml-tpu control``: attach to the running serve endpoint and
    supervise it until interrupted (or for ``--ticks=N`` polls)."""
    sink = None if logger is None else logger.telemetry
    poller = SocketPoller(cfg.serve.host, cfg.serve.port)
    ctrl = FleetController(cfg, workdir, poller, sink=sink)
    print(
        json.dumps(
            {
                "control": f"{cfg.serve.host}:{cfg.serve.port}",
                "workdir": workdir,
                "dry_run": ctrl.dry_run,
                "interval_s": cfg.control.interval_s,
                "autoscale": ctrl.autoscaler is not None,
                "drift_step_hint": ctrl.drift_step_hint,
            }
        ),
        flush=True,
    )
    return ctrl.run(ticks=ticks)
