#!/bin/bash
# Science phase 2: finish QSC (6q, resume), add 4q/8q runs for the Loss-Curve
# figure, then the SNR-sweep eval and both published-figure artifacts.
# Runs on whatever backend JAX_PLATFORMS selects - the science curves are
# backend-independent; only throughput evidence needs the chip.
set -e
cd /root/repo
python -m qdml_tpu.cli train-qsc --train.workdir=runs/science --train.resume=true
python -m qdml_tpu.cli train-qsc --train.workdir=runs/science_q4 --quantum.n_qubits=4 --train.resume=true
python -m qdml_tpu.cli train-qsc --train.workdir=runs/science_q8 --quantum.n_qubits=8 --train.resume=true
python -m qdml_tpu.cli eval --train.workdir=runs/science --eval.results_dir=results
python -m qdml_tpu.cli loss-curves --eval.results_dir=results --curves="CNN (classical SC):runs/science/Pn_128/default/train-sc.metrics.jsonl,QML 4 qubits:runs/science_q4/Pn_128/default/train-qsc.metrics.jsonl,QML 6 qubits:runs/science/Pn_128/default/train-qsc.metrics.jsonl,QML 8 qubits:runs/science_q8/Pn_128/default/train-qsc.metrics.jsonl"
echo "SCIENCE PHASE 2 DONE"
