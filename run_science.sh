#!/bin/bash
# VERDICT r1 #3: reproduce the reference's published curves end-to-end.
# Reference protocol: 100 epochs, bs 256, lr 1e-3 (halved/30), train SNR 10.
set -e
cd /root/repo
for cmd in train-hdce train-sc train-qsc; do
  echo "=== $cmd ==="
  python -m qdml_tpu.cli $cmd --train.workdir=runs/science
done
echo "=== eval ==="
python -m qdml_tpu.cli eval --train.workdir=runs/science --eval.results_dir=results
