"""Alias package: full-name import path for the framework.

The canonical implementation lives in :mod:`qdml_tpu` (the project's dashed
name is not a valid Python identifier); this package re-exports it under the
full underscored name.
"""

from qdml_tpu import *  # noqa: F401,F403
from qdml_tpu import (  # noqa: F401
    config,
    data,
    eval,
    models,
    ops,
    parallel,
    quantum,
    runtime,
    train,
    utils,
)
from qdml_tpu import __version__  # noqa: F401
