"""Unified event spine + live attachment (qdml_tpu/telemetry/events.py,
attach.py; docs/TELEMETRY.md "event spine", docs/CONTROL.md "hands-off
loop"): envelope construction, cursor-tail semantics (resume with no gaps
and no duplicates, explicit loss on overflow, epoch-mismatch restart),
router aggregation ordering, the scraper's events verb, and the
attachment's reconnect/give-up discipline.

All host-side — no engine, no sockets: buses are constructed directly and
router aggregation runs over faked backends, property-style over scripted
pollers. The live end-to-end path is scripts/live_fleet_dryrun.py's
committed run."""

from __future__ import annotations

import random
import threading
import time

import pytest

from qdml_tpu.control.fleet_scale import FleetAutoscaler
from qdml_tpu.telemetry.attach import MonitorAttachment
from qdml_tpu.telemetry.events import (
    EventBus,
    classify,
    ensure_bus,
    install_bus,
    normalize_tail,
)
from qdml_tpu.telemetry.timeseries import MonitorScraper


@pytest.fixture(autouse=True)
def _fresh_bus():
    """Isolate the process-global bus per test: library emitters publish to
    whatever is installed, and a shared bus would leak one test's events
    into another's cursors."""
    install_bus(EventBus(capacity=4096))
    yield
    install_bus(None)


# ---------------------------------------------------------------------------
# Envelope + severity vocabulary
# ---------------------------------------------------------------------------


def test_envelope_hoists_correlation_keys_and_keeps_payload_intact():
    bus = EventBus(clock=lambda: 123.5)
    env = bus.publish(
        "fleet_scale_event", tier="control",
        direction="up", backends=3, alert_episode="router#1",
        decision="scale#1", assumptions_sha="a" * 64,
    )
    assert env["seq"] == 1 and env["ts"] == 123.5
    assert env["tier"] == "control" and env["kind"] == "fleet_scale_event"
    # hoisted correlation keys (alias forms included)...
    assert env["episode"] == "router#1"
    assert env["decision"] == "scale#1"
    assert env["planner_sha"] == "a" * 64
    # ...while the payload survives untouched under data
    assert env["data"]["alert_episode"] == "router#1"
    assert env["data"]["backends"] == 3


def test_severity_vocabulary():
    assert classify("replica_quarantined") == "critical"
    assert classify("replica_restarted") == "warning"
    assert classify("monitor_timeseries") == "debug"
    assert classify("some_future_kind") == "info"
    # monitor_alert is state-dependent: firing pages, resolved informs
    assert classify("monitor_alert", {"state": "firing"}) == "critical"
    assert classify("monitor_alert", {"state": "resolved"}) == "info"
    # publisher override always wins
    bus = EventBus()
    assert bus.publish("replica_quarantined", severity="info")["severity"] == "info"


# ---------------------------------------------------------------------------
# Cursor-tail semantics: no gaps, no duplicates, explicit loss
# ---------------------------------------------------------------------------


def test_tail_resume_has_no_gaps_and_no_duplicates():
    bus = EventBus(capacity=64)
    cursor = None
    seen: list[int] = []
    for batch in range(5):
        for i in range(7):
            bus.publish("k", i=batch * 7 + i)
        t = bus.tail(cursor)
        cursor = {"start_seq": t["start_seq"], "seq": t["next_seq"]}
        seen.extend(e["seq"] for e in t["events"])
        assert t["lost"] == 0 and t["dropped"] == 0
    assert seen == list(range(1, 36))
    # a re-poll with the same cursor and nothing new is empty, not a replay
    t = bus.tail(cursor)
    assert t["events"] == [] and t["next_seq"] == 35


def test_tail_property_random_interleaving_of_publish_and_poll():
    """Property-style: any interleaving of publishes and cursor polls over
    a ring that never overflows yields every seq exactly once, in order."""
    rng = random.Random(7)
    bus = EventBus(capacity=10_000)
    cursor = None
    published = 0
    seen: list[int] = []
    for _ in range(200):
        if rng.random() < 0.7:
            published += 1
            bus.publish("k", n=published)
        else:
            t = bus.tail(cursor, limit=rng.randint(1, 50))
            cursor = {"start_seq": t["start_seq"], "seq": t["next_seq"]}
            seen.extend(e["seq"] for e in t["events"])
    while len(seen) < published:  # drain (limit may have capped a poll)
        t = bus.tail(cursor)
        if not t["events"]:
            break
        cursor = {"start_seq": t["start_seq"], "seq": t["next_seq"]}
        seen.extend(e["seq"] for e in t["events"])
    assert seen == list(range(1, published + 1))


def test_overflow_increments_drop_counter_and_tail_reports_loss():
    bus = EventBus(capacity=4)
    for i in range(10):
        bus.publish("k", i=i)
    t = bus.tail(None)
    # 6 evictions, and a from-the-head reader lost exactly those 6
    assert t["dropped"] == 6 and t["lost"] == 6
    assert [e["seq"] for e in t["events"]] == [7, 8, 9, 10]
    # a cursor that kept up reads loss-free from here on (the cumulative
    # drop counter still ticks for the ring eviction the publish caused)
    cursor = {"start_seq": t["start_seq"], "seq": t["next_seq"]}
    bus.publish("k", i=10)
    t2 = bus.tail(cursor)
    assert t2["lost"] == 0 and t2["dropped"] == 7
    assert [e["seq"] for e in t2["events"]] == [11]
    # ...but a cursor the ring lapped sees cursor-relative loss
    lapped = {"start_seq": t["start_seq"], "seq": 2}
    t3 = bus.tail(lapped)
    assert t3["lost"] == (11 - 4) - 2  # oldest-1 - since


def test_epoch_mismatch_restarts_from_head_with_honest_loss():
    bus = EventBus(capacity=8)
    for i in range(3):
        bus.publish("k", i=i)
    stale = {"start_seq": bus.start_seq - 999, "seq": 3}
    t = bus.tail(stale)
    # the dead process's cursor must NOT skip the new process's first seqs
    assert [e["seq"] for e in t["events"]] == [1, 2, 3]
    assert t["lost"] == 0


def test_normalize_tail_handles_both_shapes():
    single = {"start_seq": 5, "next_seq": 9, "dropped": 1, "lost": 0,
              "events": [{"seq": 9}]}
    evs, cur, dropped, lost = normalize_tail(single)
    assert evs == [{"seq": 9}] and cur == {"start_seq": 5, "seq": 9}
    assert dropped == 1 and lost == 0
    agg = {"fleet": True, "events": [], "dropped": 0, "lost": 2,
           "cursor": {"router": {"start_seq": 1, "seq": 4}}}
    evs, cur, dropped, lost = normalize_tail(agg)
    assert cur == {"router": {"start_seq": 1, "seq": 4}} and lost == 2


def test_bus_publish_is_thread_safe_and_loss_is_never_silent():
    bus = EventBus(capacity=128)

    def pump(tag):
        for i in range(500):
            bus.publish("k", tag=tag, i=i)

    threads = [threading.Thread(target=pump, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = bus.snapshot()
    # every publish got a unique seq; evictions are all counted
    assert snap["seq"] == 2000
    assert snap["size"] + snap["dropped"] == 2000


# ---------------------------------------------------------------------------
# Router aggregation over faked backends
# ---------------------------------------------------------------------------


class _FakeState:
    def __init__(self, live=True):
        self._live = live
        self.failures = 0
        self.successes = 0

    def live(self):
        return self._live

    def record_failure(self):
        self.failures += 1
        return False

    def record_success(self):
        self.successes += 1
        return False


class _FakeBackend:
    """A backend whose {"op": "events"} verb answers from its own bus."""

    def __init__(self, host_id, bus=None, dead=False):
        self.host_id = host_id
        self.addr = f"127.0.0.1:{host_id}"
        self.bus = bus or EventBus()
        self.dead = dead
        self.state = _FakeState()

    def call(self, msg):
        assert msg["op"] == "events"
        if self.dead:
            raise ConnectionError("down")
        return {"ok": True,
                "events": self.bus.tail(msg.get("cursor"),
                                        limit=msg.get("limit") or 512)}


def _router_with(backends):
    from qdml_tpu.fleet.router import FleetRouter

    r = FleetRouter([("127.0.0.1", 1)], poll_interval_s=3600.0)
    r.backends = backends  # never started: live_events only walks this list
    return r


def test_router_aggregation_preserves_per_backend_ordering():
    b0, b1 = _FakeBackend("b0"), _FakeBackend("b1")
    for i in range(4):
        b0.bus.publish("a", i=i)
        b1.bus.publish("b", i=i)
    ensure_bus().publish("router_event", x=1)
    router = _router_with([b0, b1])
    view = router.live_events(None)
    assert view["fleet"] is True and view["dropped"] == 0
    # per-source cursors for every folded source
    assert set(view["cursor"]) == {"router", "b0", "b1"}
    # within each source the seqs are strictly increasing (ordering
    # preserved); every event is stamped with its source
    for src in ("router", "b0", "b1"):
        seqs = [e["seq"] for e in view["events"] if e["source"] == src]
        assert seqs == sorted(seqs) and len(seqs) >= 1
    # resume through the aggregated cursor: only new events come back
    b0.bus.publish("a", i=99)
    view2 = router.live_events(view["cursor"])
    assert [(e["source"], e["data"]["i"]) for e in view2["events"]] == [("b0", 99)]


def test_router_aggregation_sums_loss_and_survives_dead_backend():
    b0 = _FakeBackend("b0", bus=EventBus(capacity=2))
    dead = _FakeBackend("b9", dead=True)
    for i in range(5):
        b0.bus.publish("a", i=i)
    router = _router_with([b0, dead])
    view = router.live_events(None)
    # b0's evictions surface at the front door; the dead backend is skipped
    # with a recorded failure, not an exception
    assert view["dropped"] == 3 and view["lost"] == 3
    assert dead.state.failures == 1 and "b9" not in view["cursor"]


def test_router_per_backend_cursor_survives_that_backends_restart_only():
    b0, b1 = _FakeBackend("b0"), _FakeBackend("b1")
    b0.bus.publish("a", i=0)
    b1.bus.publish("b", i=0)
    router = _router_with([b0, b1])
    view = router.live_events(None)
    # b1 restarts: new bus, new epoch
    b1.bus = EventBus()
    b1.bus.publish("b", i=1)
    b0.bus.publish("a", i=1)
    view2 = router.live_events(view["cursor"])
    got = {(e["source"], e["data"]["i"]) for e in view2["events"]}
    # b0 resumed (only the new event); b1's mismatched epoch restarted that
    # source from ITS buffer head without disturbing b0's cursor
    assert got == {("b0", 1), ("b1", 1)}


# ---------------------------------------------------------------------------
# The scraper's events verb (cursor keeping, loss ledger, echo guard)
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class _Sink:
    active = True

    def __init__(self):
        self.records = []

    def emit(self, kind, **payload):
        self.records.append({"kind": kind, **payload})


class _EventsPoller:
    """Scripted three-verb poller backed by a real bus."""

    def __init__(self, bus):
        self.bus = bus
        self.fail_events = False

    def health(self):
        return {"warm": True, "replicas": 1, "queue_depth": 0,
                "quarantined": [], "swap_epoch": 0, "uptime_s": 5.0,
                "start_seq": 1}

    def metrics(self):
        return {"completed": 0, "shed": {}, "faults": {}, "restarts": 0,
                "slo": {"n": 0, "met": 0},
                "breaker": {"state": "closed", "fast_fails": 0, "admitted": 0}}

    def events(self, cursor=None, limit=512):
        if self.fail_events:
            raise ConnectionResetError("front door restarting")
        return self.bus.tail(cursor, limit=limit)


def test_scraper_tails_spine_with_resumable_cursor_and_loss_ledger():
    clk, sink = _Clock(), _Sink()
    bus = EventBus(capacity=4)
    p = _EventsPoller(bus)
    s = MonitorScraper(p, sink=sink, interval_s=1.0, clock=clk,
                       tail_events=True)
    bus.publish("replica_restarted", replica=0)
    s.scrape_once()
    assert s.events_seen == 1 and s.event_drops == 0
    # overflow between scrapes: the ledger carries the evictions
    for i in range(9):
        bus.publish("k", i=i)
    clk.t += 1.0
    rec = s.scrape_once()
    assert s.event_drops > 0 or s.events_lost > 0
    assert rec["spine"]["events"] == 4  # ring kept only the newest 4
    # tailed envelopes land in the stream nested under ev (envelopes carry
    # their own kind/ts and must not clobber the record's)
    spine_recs = [r for r in sink.records if r["kind"] == "spine_event"]
    assert spine_recs and all("ev" in r for r in spine_recs)
    # summary folds the ledger the report's zero-loss gate reads
    out = s.summary()
    assert out["event_drops"] == s.event_drops + s.events_lost
    assert out["spine"]["events"] == s.events_seen


def test_scraper_does_not_republish_tailed_envelopes_echo_guard():
    """A monitor co-resident with its router tails the same process-global
    bus it publishes to: tailed envelopes must NOT be re-published or the
    spine would echo into itself forever."""
    clk, sink = _Clock(), _Sink()
    bus = ensure_bus()  # the scraper's own publishes land here too
    p = _EventsPoller(bus)
    s = MonitorScraper(p, sink=sink, interval_s=1.0, clock=clk,
                       tail_events=True)
    for step in range(4):
        clk.t += 1.0
        s.scrape_once()
    assert not any(
        e["kind"] == "spine_event"
        for e in bus.tail(None, limit=10_000)["events"]
    )


def test_scraper_events_failure_is_a_typed_scrape_error_and_cursor_survives():
    clk, sink = _Clock(), _Sink()
    bus = EventBus()
    p = _EventsPoller(bus)
    s = MonitorScraper(p, sink=sink, interval_s=1.0, clock=clk,
                       tail_events=True)
    bus.publish("k", i=0)
    s.scrape_once()
    cursor = dict(s.events_cursor)
    bus.publish("k", i=1)  # published DURING the outage
    p.fail_events = True
    clk.t += 1.0
    s.scrape_once()  # health/metrics fine, events verb down
    assert s.scrape_errors == 1 and s.events_cursor == cursor
    evs = [r for r in sink.records if r["kind"] == "monitor_event"
           and r.get("event") == "scrape_error"]
    assert evs and evs[0]["verb"] == "events"
    # recovery: the kept cursor resumes with no gaps and no duplicates
    p.fail_events = False
    clk.t += 1.0
    s.scrape_once()
    assert s.events_seen == 2 and s.events_lost == 0


def test_scraper_without_events_verb_downgrades_silently():
    class _TwoVerb:
        def health(self):
            return {"warm": True, "replicas": 1, "queue_depth": 0,
                    "quarantined": [], "swap_epoch": 0}

        def metrics(self):
            return {"completed": 0, "shed": {}, "faults": {}, "restarts": 0,
                    "slo": {"n": 0, "met": 0},
                    "breaker": {"state": "closed", "fast_fails": 0,
                                "admitted": 0}}

    s = MonitorScraper(_TwoVerb(), sink=_Sink(), interval_s=1.0,
                       clock=_Clock(), tail_events=True)
    rec = s.scrape_once()
    assert rec["spine"]["events"] == 0 and s.scrape_errors == 0


# ---------------------------------------------------------------------------
# The attachment: policy ticks, correlation, reconnect, typed give-up
# ---------------------------------------------------------------------------


class _FiringAlerter:
    def __init__(self):
        self.open = []

    def firing(self):
        return list(self.open)


def test_attachment_tick_stamps_alert_episode_onto_scale_decision():
    sink = _Sink()
    scaled = []
    auto = FleetAutoscaler(
        lambda k: scaled.append(k) or {"ok": True, "actions": []},
        min_backends=2, max_backends=3, queue_high=5.0, queue_low=1.0,
        debounce=2, cooldown_ticks=0, sink=sink,
    )
    p = _EventsPoller(EventBus())
    s = MonitorScraper(p, sink=sink, interval_s=1.0, clock=_Clock())
    s.alerter = _FiringAlerter()
    att = MonitorAttachment(s, auto)
    # two high-queue windows while the router alert burns -> scale up,
    # stamped with the open episode id and a decision id
    s.alerter.open = [{"signal": "router", "episode": "router#1"}]
    assert att.tick({"queue_depth": 20, "backends": 2}) is None  # debounce 1/2
    d = att.tick({"queue_depth": 20, "backends": 2})
    assert d is not None and d["direction"] == "up" and scaled == [3]
    assert d["burn_alert"] is True and d["alert_episode"] == "router#1"
    assert d["decision"] == "scale#1"
    assert att.summary()["scale_events"][0]["alert_episode"] == "router#1"
    # quiet queue but alert still burning: scale-DOWN is refused
    for _ in range(5):
        att.tick({"queue_depth": 0, "backends": 3})
    assert len(att.decisions) == 1
    # alert resolves -> the loop drains back down, uncorrelated
    s.alerter.open = []
    att.tick({"queue_depth": 0, "backends": 3})
    d = att.tick({"queue_depth": 0, "backends": 3})
    assert d["direction"] == "down" and d["alert_episode"] is None


def test_attachment_short_handed_burn_grows_without_queue_pressure():
    # ms-latency tiers fail over faster than instantaneous queue depth can
    # build: the grow signal under a stall is burn + backends_live below
    # membership, never the burn alone
    sink = _Sink()
    scaled = []
    auto = FleetAutoscaler(
        lambda k: scaled.append(k) or {"ok": True, "actions": []},
        min_backends=2, max_backends=3, queue_high=10.0, queue_low=1.0,
        debounce=2, cooldown_ticks=0, sink=sink,
    )
    p = _EventsPoller(EventBus())
    s = MonitorScraper(p, sink=sink, interval_s=1.0, clock=_Clock())
    s.alerter = _FiringAlerter()
    att = MonitorAttachment(s, auto)
    # burn firing but the fleet is at full live strength: no grow
    s.alerter.open = [{"signal": "router", "episode": "router#1"}]
    for _ in range(4):
        assert att.tick({"queue_depth": 0, "backends": 2,
                         "backends_live": 2}) is None
    # the stalled host drops out of the live set: burn + deficit -> up,
    # correlated to the open episode, live count recorded on the event
    assert att.tick({"queue_depth": 0, "backends": 2,
                     "backends_live": 1}) is None  # debounce 1/2
    d = att.tick({"queue_depth": 0, "backends": 2, "backends_live": 1})
    assert d is not None and d["direction"] == "up" and scaled == [3]
    assert d["alert_episode"] == "router#1" and d["backends_live"] == 1
    # a deficit WITHOUT a burn alert stays the router's problem: no grow
    s.alerter.open = []
    auto2 = FleetAutoscaler(
        lambda k: scaled.append(k), min_backends=2, max_backends=3,
        queue_high=10.0, queue_low=-1.0, debounce=2, cooldown_ticks=0,
        sink=sink,
    )
    att2 = MonitorAttachment(s, auto2)
    for _ in range(4):
        assert att2.tick({"queue_depth": 0, "backends": 2,
                          "backends_live": 1}) is None


def test_attachment_reconnects_with_cursor_resume_and_reattach_event():
    sink = _Sink()
    bus = EventBus()
    p = _EventsPoller(bus)
    fail_all = {"on": False}
    real_health = p.health
    p.health = lambda: (_ for _ in ()).throw(ConnectionError("down")) \
        if fail_all["on"] else real_health()
    auto = FleetAutoscaler(lambda k: {"ok": True}, min_backends=1,
                           max_backends=2, queue_high=1e9, queue_low=-1.0,
                           sink=sink)
    s = MonitorScraper(p, sink=sink, interval_s=0.01, tail_events=True)
    att = MonitorAttachment(s, auto, reconnect_backoff_s=0.01,
                            reconnect_max_s=0.02, max_reconnects=50)
    bus.publish("k", i=0)
    stop = threading.Event()
    t = threading.Thread(target=att.run, args=(3.0, stop), daemon=True)
    t.start()
    time.sleep(0.15)
    fail_all["on"] = True
    bus.publish("k", i=1)  # published during the outage
    time.sleep(0.15)
    fail_all["on"] = False
    time.sleep(0.15)
    stop.set()
    t.join(timeout=5.0)
    assert att.reattaches >= 1 and att.give_up is None
    reatt = [r for r in sink.records if r.get("event") == "monitor_reattach"]
    assert reatt and reatt[0]["after_attempts"] >= 1
    # the outage-spanning cursor resumed: both events seen exactly once
    seen = [r["ev"]["data"]["i"] for r in sink.records
            if r["kind"] == "spine_event" and r["ev"]["kind"] == "k"]
    assert seen == [0, 1]


def test_attachment_gives_up_typed_after_max_reconnects():
    sink = _Sink()

    class _AlwaysDown:
        def health(self):
            raise ConnectionRefusedError("gone")

        def metrics(self):  # pragma: no cover - never reached
            return {}

    auto = FleetAutoscaler(lambda k: {"ok": True}, min_backends=1,
                           max_backends=2, sink=sink)
    s = MonitorScraper(_AlwaysDown(), sink=sink, interval_s=0.01)
    att = MonitorAttachment(s, auto, reconnect_backoff_s=0.005,
                            reconnect_max_s=0.01, max_reconnects=3)
    ticks = att.run(5.0)  # returns LONG before the duration: typed give-up
    assert ticks == 0
    assert att.give_up is not None
    assert att.give_up["reason"] == "reconnect_exhausted"
    assert att.give_up["attempts"] == 3
    give = [r for r in sink.records
            if r.get("event") == "monitor_attach_giveup"]
    assert give, "the give-up must be an emitted event, not just state"
    assert att.summary()["give_up"]["reason"] == "reconnect_exhausted"
