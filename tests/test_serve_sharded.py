"""Sharded multi-replica serving: pjit-sharded AOT buckets over the mesh,
replica pools on the shared feed, and zero-downtime checkpoint hot-swap.

Runs on the 8-virtual-device CPU mesh from conftest.py (the
``XLA_FLAGS=--xla_force_host_platform_device_count`` pattern the mesh
dryruns use, applied by ``utils.platform.force_cpu(8)``) — the tier-1
multi-device serve smoke the ISSUE-7 acceptance criteria name: the sharded
engine proves zero request-path compiles across warmup, steady traffic AND
a live hot-swap, and the loadgen fleet summary carries the topology the
report gate consumes.
"""

import dataclasses
import json

import jax
import numpy as np
import pytest

from qdml_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    ServeConfig,
    TrainConfig,
    override,
)
from qdml_tpu.parallel.mesh import serve_mesh
from qdml_tpu.serve import ReplicaPool, ServeEngine, ServeLoop, run_loadgen
from qdml_tpu.serve.loadgen import make_request_samples
from qdml_tpu.serve.types import Prediction

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

ZERO = {"hits": 0, "misses": 0, "requests": 0}


def _cfg(**serve_kw):
    serve_kw.setdefault("batching", "bucket")  # the coalescing path's pins
    serve = ServeConfig(max_batch=8, buckets=(4, 8), max_wait_ms=1.0, max_queue=64, **serve_kw)
    return ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=1),
        # data_axis=4: both buckets (4, 8) divide, so every executable is
        # batch-sharded; fed/model stay 1 unless a test overrides
        mesh=MeshConfig(data_axis=4, model_axis=1, fed_axis=1),
        serve=serve,
    )


def _vars(cfg, seed=None):
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    if seed is not None:
        cfg = dataclasses.replace(cfg, train=dataclasses.replace(cfg.train, seed=seed))
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    return hdce_vars, {"params": sc_state.params}


@pytest.fixture(scope="module")
def sharded():
    """One warmed data-parallel engine + offline reference shared by the
    sharded serving tests (each bucket is an XLA compile; module scope keeps
    the suite fast)."""
    cfg = _cfg()
    mesh = serve_mesh(cfg)
    assert mesh is not None and mesh.shape["data"] == 4
    hdce_vars, clf_vars = _vars(cfg)
    engine = ServeEngine(cfg, hdce_vars, clf_vars, mesh=mesh)
    samples = make_request_samples(cfg, 32)
    offline_h, offline_pred, _ = engine.offline_forward(samples["x"])
    warm = engine.warmup()
    return cfg, engine, samples, offline_h, offline_pred, warm


def test_serve_mesh_resolution():
    """serve_mesh: auto builds the mesh, off pins single-device, expert
    sharding validates the fed axis before any bucket compiles."""
    assert serve_mesh(_cfg()) is not None
    assert serve_mesh(_cfg(shard="off")) is None
    with pytest.raises(ValueError, match="serve.shard"):
        serve_mesh(_cfg(shard="maybe"))
    bad = override(_cfg(expert_sharding=True), "mesh.fed_axis", 2)
    with pytest.raises(ValueError):  # fed=2 != n_scenarios=3 (training_mesh or serve_mesh)
        serve_mesh(bad)


def test_sharded_warmup_bakes_batch_sharding(sharded):
    """Every bucket the data axis divides is lowered batch-sharded — the
    sharding is baked into the executable, recorded per bucket, and the
    warmup record carries the mesh topology."""
    cfg, engine, _, _, _, warm = sharded
    assert engine.bucket_sharding == {"4": "data", "8": "data"}
    assert warm["sharding"] == {"4": "data", "8": "data"}
    assert warm["mesh"] == {
        "devices": 4,
        "axes": {"fed": 1, "data": 4, "model": 1},
        "expert_sharding": False,
    }
    # the executables' h output is actually partitioned over the data axis
    out_sh = engine._compiled[8](
        *engine.live_vars(),
        np.zeros((8, *cfg.image_hw, 2), np.float32),
    )[0].sharding
    assert "data" in str(out_sh.spec)


def test_sharded_infer_parity_and_zero_compiles(sharded):
    """Sharded buckets (and padded partial fills) reproduce the offline
    forward; the request path never compiles — the SPMD program is as
    pinned as the single-device one."""
    cfg, engine, samples, offline_h, offline_pred, _ = sharded
    for n in (1, 3, 4, 5, 8):
        h, pred, _conf, bucket = engine.infer(samples["x"][:n])
        assert h.shape == (n, cfg.h_out_dim)
        np.testing.assert_allclose(h, offline_h[:n], rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(pred, offline_pred[:n])
    assert engine.request_path_compiles() == ZERO


def test_sharded_serve_loop_end_to_end(sharded):
    """The full loop over the sharded engine: N requests coalesce, serve,
    parity-check, zero request-path compiles (the tier-1 multi-device serve
    smoke)."""
    cfg, engine, samples, offline_h, offline_pred, _ = sharded
    loop = ServeLoop(engine).start()
    try:
        futs = [loop.submit(samples["x"][i], rid=i) for i in range(20)]
        results = [f.result(timeout=30.0) for f in futs]
    finally:
        loop.stop()
    assert all(isinstance(r, Prediction) for r in results)
    served = np.stack([r.h for r in sorted(results, key=lambda r: r.rid)])
    np.testing.assert_allclose(served, offline_h[:20], rtol=1e-5, atol=1e-5)
    assert engine.request_path_compiles() == ZERO


def test_expert_sharded_trunks_parity():
    """serve.expert_sharding over a fed=3 mesh: stacked trunk leaves live
    sharded over `fed` (the federated placement rules), and the fused
    forward still matches an unsharded engine bit-for-bit-modulo-fp."""
    cfg = _cfg(expert_sharding=True)
    cfg = override(cfg, "mesh.fed_axis", 3)
    cfg = override(cfg, "mesh.data_axis", 2)
    mesh = serve_mesh(cfg)
    assert mesh is not None and mesh.shape["fed"] == 3 and mesh.shape["data"] == 2
    hdce_vars, clf_vars = _vars(cfg)
    engine = ServeEngine(cfg, hdce_vars, clf_vars, mesh=mesh)
    samples = make_request_samples(cfg, 16)
    offline_h, offline_pred, _ = engine.offline_forward(samples["x"])
    warm = engine.warmup()
    assert warm["mesh"]["expert_sharding"] is True
    # trunk params are genuinely fed-sharded on device
    leaves = jax.tree_util.tree_leaves_with_path(engine.live_vars()[0])
    stacked = [l for p, l in leaves if "StackedConvP128" in str(p)]
    assert stacked and all("fed" in str(l.sharding.spec) for l in stacked)
    for n in (3, 8):
        h, pred, _, _ = engine.infer(samples["x"][:n])
        np.testing.assert_allclose(h, offline_h[:n], rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(pred, offline_pred[:n])
    assert engine.request_path_compiles() == ZERO


def test_ragged_sharded_sparse_expert_padded_rows_never_leak():
    """The strongest padded-rows-never-leak pin: a RAGGED engine with FORCED
    sparse dispatch and fed-sharded experts on the 8-virtual-device mesh —
    NaN/Inf garbage in the pad tail of the capacity tier cannot perturb any
    valid output (the traced mask zeroes pad rows before the classifier, so
    garbage can neither route, consume sparse capacity, nor reach a trunk),
    and the mesh-sharded ragged request path still never compiles."""
    cfg = _cfg(expert_sharding=True, dispatch="sparse", batching="ragged")
    cfg = override(cfg, "mesh.fed_axis", 3)
    cfg = override(cfg, "mesh.data_axis", 2)
    mesh = serve_mesh(cfg)
    hdce_vars, clf_vars = _vars(cfg)
    engine = ServeEngine(cfg, hdce_vars, clf_vars, mesh=mesh)
    samples = make_request_samples(cfg, 8)
    offline_h, offline_pred, _ = engine.offline_forward(samples["x"])
    warm = engine.warmup()
    assert engine.batching_mode == {"4": "ragged", "8": "ragged"}
    assert engine.dispatch_mode == {"4": "sparse", "8": "sparse"}
    assert warm["mesh"]["expert_sharding"] is True

    # clean-path parity first (sparse + ragged + sharded composes)
    for n in (2, 5, 8):
        h, pred, _, info = engine.infer(samples["x"][:n])
        assert info.mode == "ragged"
        np.testing.assert_allclose(h, offline_h[:n], rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(pred, offline_pred[:n])

    # garbage pad tail straight into the compiled sparse executable
    xp = np.full((8, *cfg.image_hw, 2), np.nan, np.float32)
    xp[6] = np.inf
    xp[:3] = samples["x"][:3]
    out = engine._compiled[8](*engine.live_vars(), xp, np.int32(3))
    h = np.asarray(jax.device_get(out[0]))
    np.testing.assert_allclose(h[:3], offline_h[:3], rtol=1e-5, atol=1e-5)
    assert np.isfinite(h).all()  # the mask ran before any compute
    assert engine.request_path_compiles() == ZERO


# ---------------------------------------------------------------------------
# Zero-downtime checkpoint hot-swap
# ---------------------------------------------------------------------------


def test_hot_swap_under_traffic_zero_compiles_exact_parity():
    """The ISSUE-7 hot-swap acceptance pin: serve traffic across a live
    swap — requests before the swap match the OLD checkpoint's offline
    forward, requests after match the NEW one's, and the compile-cache
    counters prove zero compiles across warmup + steady traffic + the swap
    itself.

    Standalone engine (not the module fixture): BOTH parity references must
    compile BEFORE warmup arms the gate — the counters are process-global,
    so the gate window has to contain nothing but serving + the swap (the
    same ordering discipline loadgen documents)."""
    cfg = _cfg()
    mesh = serve_mesh(cfg)
    hdce_vars, clf_vars = _vars(cfg)
    new_hdce, new_clf = _vars(cfg, seed=123)
    engine = ServeEngine(cfg, hdce_vars, clf_vars, mesh=mesh)
    samples = make_request_samples(cfg, 16)
    offline_h, _, _ = engine.offline_forward(samples["x"])
    # the NEW checkpoint's parity reference, through the same engine family,
    # compiled before the gate arms
    ref_engine = ServeEngine(cfg, new_hdce, new_clf, mesh=mesh)
    new_h, new_pred, _ = ref_engine.offline_forward(samples["x"])
    engine.warmup()

    pool = ReplicaPool(engine, replicas=2).start()
    try:
        pre = [pool.submit(samples["x"][i], rid=i) for i in range(12)]
        pre_res = [f.result(timeout=30.0) for f in pre]
        rec = engine.swap_params(new_hdce, new_clf)
        post = [pool.submit(samples["x"][i], rid=100 + i) for i in range(12)]
        post_res = [f.result(timeout=30.0) for f in post]
    finally:
        pool.stop()

    # swap bookkeeping: epoch advanced, the swap itself compiled NOTHING
    assert rec["epoch"] == 1 and engine.swap_epoch == 1
    assert rec["compile"] == ZERO
    # pre-swap traffic resolved against the OLD checkpoint...
    for r in pre_res:
        assert isinstance(r, Prediction)
        np.testing.assert_allclose(r.h, offline_h[r.rid], rtol=1e-5, atol=1e-5)
    # ...post-swap traffic EXACTLY matches the NEW checkpoint's offline
    # forward (same executables, new params — NMSE parity is bitwise at f32)
    for r in post_res:
        assert isinstance(r, Prediction)
        np.testing.assert_allclose(r.h, new_h[r.rid - 100], rtol=1e-5, atol=1e-5)
        assert r.scenario == int(new_pred[r.rid - 100])
    # the whole window — warmup snapshot through traffic through the swap
    # through drain — saw zero request-path compiles
    assert engine.request_path_compiles() == ZERO
    # swaps are repeatable: back to the original checkpoint, still zero
    assert engine.swap_params(hdce_vars, clf_vars)["compile"] == ZERO
    h, _, _, _ = engine.infer(samples["x"][:4])
    np.testing.assert_allclose(h, offline_h[:4], rtol=1e-5, atol=1e-5)
    assert engine.request_path_compiles() == ZERO


def test_swap_rejects_mismatched_checkpoint(sharded):
    """A shape-changing checkpoint cannot hot-swap: validation raises BEFORE
    anything is placed, and the old params keep serving."""
    cfg, engine, samples, offline_h, *_ = sharded
    wrong_cfg = ExperimentConfig(
        data=dataclasses.replace(cfg.data),
        model=ModelConfig(features=16),  # different trunk width
        train=cfg.train,
        serve=cfg.serve,
        mesh=cfg.mesh,
    )
    wrong_h, wrong_c = _vars(wrong_cfg)
    with pytest.raises(ValueError, match="hot-swap"):
        engine.swap_params(wrong_h, wrong_c)
    h, _, _, _ = engine.infer(samples["x"][:4])
    np.testing.assert_allclose(h, offline_h[:4], rtol=1e-5, atol=1e-5)


def test_swap_before_warmup_raises():
    cfg = _cfg(shard="off")
    hdce_vars, clf_vars = _vars(cfg)
    engine = ServeEngine(cfg, hdce_vars, clf_vars)
    with pytest.raises(RuntimeError, match="warmup"):
        engine.swap_params(hdce_vars, clf_vars)


def test_swap_from_workdir_redeploys_newest(tmp_path):
    """The {"op": "swap"} engine half: a training run promoting a new *_best
    into the workdir hot-swaps in (tags re-resolved each call), zero
    compiles, and the served numbers flip to the new checkpoint."""
    from qdml_tpu.train.checkpoint import save_checkpoint

    cfg = _cfg(shard="off")
    h0, c0 = _vars(cfg)
    h1, c1 = _vars(cfg, seed=321)
    wd = str(tmp_path)
    save_checkpoint(wd, "hdce_last", h0)
    save_checkpoint(wd, "sc_last", c0)
    engine = ServeEngine.from_workdir(cfg, wd)
    samples = make_request_samples(cfg, 8)
    engine.warmup()
    before, _, _, _ = engine.infer(samples["x"][:4])
    # a better checkpoint lands (best beats last in tag discovery)
    save_checkpoint(wd, "hdce_best", h1)
    save_checkpoint(wd, "sc_best", c1)
    rec = engine.swap_from_workdir(wd)
    assert rec["tags"] == {"hdce": "hdce_best", "sc": "sc_best"}
    assert rec["compile"] == ZERO
    after, _, _, _ = engine.infer(samples["x"][:4])
    assert np.max(np.abs(after - before)) > 0  # the deploy actually landed
    assert engine.request_path_compiles() == ZERO


# ---------------------------------------------------------------------------
# Fleet loadgen over the sharded engine (the >=2-device dryrun in-suite)
# ---------------------------------------------------------------------------


def test_multi_replica_sharded_loadgen_fleet_summary(tmp_path):
    """loadgen over a 2-replica pool on the 4-device data-parallel engine:
    every request completes with parity, the serve_summary carries the fleet
    block (replicas, workers, mesh topology, per-bucket sharding,
    rps_per_replica), and the report gate consumes the record end to end."""
    from qdml_tpu.telemetry import run_manifest
    from qdml_tpu.telemetry.report import EXIT_OK, report_main
    from qdml_tpu.utils.metrics import MetricsLogger

    cfg = _cfg(replicas=2)
    mesh = serve_mesh(cfg)
    hdce_vars, clf_vars = _vars(cfg)
    engine = ServeEngine(cfg, hdce_vars, clf_vars, mesh=mesh)
    path = str(tmp_path / "fleet.metrics.jsonl")
    logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
    summary = run_loadgen(
        cfg, engine, rate=2000.0, n=48, deadline_ms=30000.0, logger=logger
    )
    logger.close()

    assert summary["completed"] == 48 and summary["n_shed"] == 0
    assert summary["compile_cache_after_warmup"] == ZERO
    assert summary["parity_max_abs_err"] < 1e-4
    assert summary["replicas"] == 2 and summary["workers"] == 2
    assert summary["mesh"] == {
        "devices": 4,
        "axes": {"fed": 1, "data": 4, "model": 1},
        "expert_sharding": False,
    }
    assert summary["bucket_sharding"] == {"4": "data", "8": "data"}
    assert summary["rps_per_replica"] == pytest.approx(summary["rps"] / 2, abs=0.02)
    assert summary["slo"]["attainment"] == 1.0
    assert sum(summary["server_metrics"]["replica_completed"]) == 48

    # the new gate consumes the fleet record: same artifact as its own
    # baseline gates clean (rps, p50/p95/p99, slo all "ok")
    rc = report_main([f"--current={path}", f"--baseline={path}"])
    assert rc == EXIT_OK
