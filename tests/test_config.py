"""Config system: presets, dotted overrides, geometry single-sourcing.

The reference hardcodes every hyperparameter (``Runner...py:20-38``,
``Test.py:13-21``); this suite checks the dataclass/CLI layer that replaces
them, and in particular that the CNN geometry (input image, head width) is
DERIVED from ``DataConfig`` so a non-default channel geometry can never
silently desynchronize the model (VERDICT round 1, weak #6).
"""

import jax.numpy as jnp

from qdml_tpu.config import (
    DataConfig,
    ExperimentConfig,
    TrainConfig,
    from_args,
    override,
    presets,
)


def test_default_geometry_matches_reference():
    cfg = ExperimentConfig()
    assert cfg.data.pilot_num == 128      # Runner...py:21 Pilot_num
    assert cfg.data.h_dim == 1024         # filename token (Runner...py:49-55)
    assert cfg.image_hw == (16, 8)        # reshape target (Runner...py:108)
    assert cfg.h_out_dim == 2048          # Linear(4096, 2048) (Estimators...py:275)
    assert cfg.feat_dim == 4096


def test_geometry_derives_from_data_config():
    cfg = ExperimentConfig(data=DataConfig(n_ant=16, n_sub=8, n_beam=4))
    assert cfg.image_hw == (8, 4)
    assert cfg.h_out_dim == 16 * 8 * 2
    assert cfg.feat_dim == 32 * 8 * 4
    # dotted override of the data geometry keeps everything in sync
    cfg2 = override(cfg, "data.n_ant", 32)
    assert cfg2.h_out_dim == 32 * 8 * 2


def test_small_geometry_trains_one_step():
    """A non-default geometry trains without any manual model syncing."""
    from qdml_tpu.data.datasets import DMLGridLoader
    from qdml_tpu.train.hdce import init_hdce_state, make_hdce_train_step

    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=32),
        train=TrainConfig(batch_size=4, n_epochs=1),
    )
    loader = DMLGridLoader(cfg.data, cfg.train.batch_size)
    batch = next(iter(loader.epoch(0)))
    assert batch["yp_img"].shape == (3, 3, 4, 8, 4, 2)
    assert batch["h_label"].shape == (3, 3, 4, 16 * 8 * 2)
    model, state = init_hdce_state(cfg, loader.steps_per_epoch)
    step = make_hdce_train_step(model, state.tx)
    state, m = step(state, batch)
    assert jnp.isfinite(m["loss"])


def test_presets_cover_baseline_configs():
    p = presets()
    assert set(p) == {"single_4q", "dp_8q", "sharded_16q", "federated", "nat_sweep", "robust_qsc"}
    assert p["robust_qsc"].quantum.input_norm and p["robust_qsc"].data.snr_jitter == (5.0, 15.0)
    assert p["sharded_16q"].quantum.n_qubits == 16
    assert p["sharded_16q"].quantum.backend == "sharded"
    assert p["federated"].mesh.fed_axis == 3
    assert p["nat_sweep"].quantum.use_quantumnat


def test_from_args_dotted_overrides():
    cfg = from_args(["--preset=dp_8q", "--train.lr=3e-4", "--data.n_sub=8"])
    assert cfg.quantum.n_qubits == 8
    assert cfg.train.lr == 3e-4
    assert cfg.image_hw == (8, 8)
