"""Native C++ IO runtime: npy mmap, threaded gather, prefetch pipeline,
and the npy-cache grid loader built on them.

All paths are validated against plain numpy; the numpy fallback keeps these
tests meaningful even where the toolchain is unavailable (is_native is then
asserted False, not skipped silently).
"""

import shutil

import numpy as np
import pytest

from qdml_tpu.runtime import (
    NativeNpyFile,
    PrefetchPipeline,
    gather_rows,
    native_available,
)

HAVE_GXX = shutil.which("g++") is not None


def test_native_builds_when_toolchain_present():
    if HAVE_GXX:
        assert native_available(), "g++ present but native build failed"


@pytest.mark.parametrize(
    "dtype,shape",
    [(np.float32, (37, 16)), (np.complex64, (21, 8)), (np.int64, (11,)), (np.float64, (5, 3, 4))],
)
def test_npy_open_matches_numpy(tmp_path, dtype, shape):
    rng = np.random.default_rng(0)
    arr = rng.standard_normal(shape).astype(dtype)
    path = str(tmp_path / "a.npy")
    np.save(path, arr)
    with NativeNpyFile(path) as f:
        assert f.is_native == native_available()
        np.testing.assert_array_equal(np.asarray(f.array), arr)


def test_npy_open_large_header_v2(tmp_path):
    # forces a v2 header via a long dtype-irrelevant shape tuple edge: big 1-d
    arr = np.arange(1000, dtype=np.float32).reshape(100, 10)
    path = str(tmp_path / "b.npy")
    np.save(path, arr)
    with NativeNpyFile(path) as f:
        np.testing.assert_array_equal(np.asarray(f.array), arr)


@pytest.mark.parametrize("n_threads", [1, 4])
def test_gather_rows_matches_fancy_indexing(n_threads):
    rng = np.random.default_rng(1)
    src = rng.standard_normal((500, 33)).astype(np.float32)
    idx = rng.integers(0, 500, size=301)
    out = gather_rows(src, idx, n_threads=n_threads)
    np.testing.assert_array_equal(out, src[idx])


def test_gather_rows_complex():
    rng = np.random.default_rng(2)
    src = (rng.standard_normal((64, 17)) + 1j * rng.standard_normal((64, 17))).astype(
        np.complex64
    )
    idx = rng.permutation(64)
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])


def test_prefetch_pipeline_roundtrip():
    rng = np.random.default_rng(3)
    src = rng.standard_normal((256, 24)).astype(np.float32)
    pipe = PrefetchPipeline(src, batch=32, n_slots=3, n_threads=2)
    assert pipe.is_native == native_available()
    batches = [rng.integers(0, 256, size=32) for _ in range(6)]
    # pipelined: keep two in flight
    tickets = [pipe.submit(batches[0]), pipe.submit(batches[1])]
    for i in range(2, len(batches) + 2):
        t = tickets.pop(0)
        got = pipe.get(t)
        np.testing.assert_array_equal(got.copy(), src[batches[i - 2]])
        pipe.release(t)
        if i < len(batches):
            tickets.append(pipe.submit(batches[i]))
    pipe.close()


def test_prefetch_partial_batch():
    src = np.arange(100, dtype=np.float32).reshape(50, 2)
    pipe = PrefetchPipeline(src, batch=16, n_slots=2)
    t = pipe.submit(np.array([3, 1, 4]))
    got = pipe.get(t)
    np.testing.assert_array_equal(got, src[[3, 1, 4]])
    pipe.release(t)
    pipe.close()


def test_npy_grid_loader_early_break_and_error(tmp_path):
    """Abandoning the epoch mid-way must not leave the producer thread stuck,
    and assembly errors must surface instead of hanging the consumer."""
    import threading

    from qdml_tpu.config import DataConfig
    from qdml_tpu.data.datasets import NpyGridLoader, save_npy_cache

    cfg = DataConfig(data_len=40)
    save_npy_cache(str(tmp_path), cfg, chunk=16)
    loader = NpyGridLoader(str(tmp_path), cfg, batch_size=4)
    before = threading.active_count()
    for _ in range(3):
        for _batch in loader.epoch(0):
            break  # abandon immediately
    assert threading.active_count() <= before + 1  # producers wound down

    # error propagation: poison the assembler
    def boom(idx):
        raise RuntimeError("bad row")

    loader._assemble = boom
    with pytest.raises(RuntimeError, match="bad row"):
        for _batch in loader.epoch(1):
            pass
    loader.close()


def test_step_timer_zero_warmup():
    from qdml_tpu.utils.profiling import StepTimer

    timer = StepTimer(warmup=0)
    for _ in range(3):
        timer.tick()
    assert timer.steps_per_sec() > 0


def test_native_npy_view_outlives_file_object(tmp_path):
    """The array view must keep the mapping alive (no use-after-munmap)."""
    import gc

    arr = np.arange(64, dtype=np.float32).reshape(8, 8)
    path = str(tmp_path / "c.npy")
    np.save(path, arr)
    view = NativeNpyFile(path).array  # file object immediately unreferenced
    gc.collect()
    np.testing.assert_array_equal(np.asarray(view), arr)  # must not crash
    if native_available():
        assert not view.flags.writeable


def test_npy_grid_loader_matches_synthetic(tmp_path):
    """NpyGridLoader over a materialised cache == DMLGridLoader on-device."""
    from qdml_tpu.config import DataConfig
    from qdml_tpu.data.datasets import DMLGridLoader, NpyGridLoader, save_npy_cache

    cfg = DataConfig(data_len=40)
    save_npy_cache(str(tmp_path), cfg, chunk=16)
    ref_loader = DMLGridLoader(cfg, batch_size=8)
    npy_loader = NpyGridLoader(str(tmp_path), cfg, batch_size=8)
    assert npy_loader.steps_per_epoch == ref_loader.steps_per_epoch

    ref_batches = list(ref_loader.epoch(0, shuffle=False))
    npy_batches = list(npy_loader.epoch(0, shuffle=False))
    assert len(npy_batches) == len(ref_batches)
    for rb, nb in zip(ref_batches, npy_batches):
        for key in ("yp_img", "h_label", "h_perf", "indicator"):
            np.testing.assert_allclose(
                np.asarray(nb[key]), np.asarray(rb[key]), rtol=1e-5, atol=1e-6
            )
    npy_loader.close()
