"""Autotuned quantum-impl dispatcher (qdml_tpu/quantum/autotune.py):
selection-table round-trip, corrupt/missing-table dense fallback, override
precedence, tuner gating, and the serve-warmup zero-request-path-compiles
guarantee with autotuning enabled (compile-cache counters, as in PR 2)."""

import json
import os

import numpy as np
import pytest

from qdml_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    QuantumConfig,
    ServeConfig,
    TrainConfig,
)
from qdml_tpu.quantum import autotune
from qdml_tpu.quantum.circuits import resolve_impl


@pytest.fixture(autouse=True)
def _isolated_table(tmp_path, monkeypatch):
    """Every test gets its own table file and a cold in-process cache."""
    monkeypatch.setenv(autotune.ENV_TABLE, str(tmp_path / "qsc_impl.json"))
    autotune.invalidate_cache()
    yield
    autotune.invalidate_cache()


# ---------------------------------------------------------------------------
# Table round-trip / fallback
# ---------------------------------------------------------------------------


def test_ensure_round_trips_manifest_headed_table():
    entry = autotune.ensure(3, 2, 7, budget_s=0.05)
    # bucketing: batch 7 -> bucket 8; the entry names what was measured
    assert entry["batch_bucket"] == 8 and entry["n_qubits"] == 3
    assert entry["best_train"] in entry["candidates"]
    assert entry["best_fwd"] in entry["candidates"]
    for rec in entry["candidates"].values():
        assert ("fwd_ms" in rec and "train_ms" in rec) or "error" in rec
    # persisted file is manifest-headed and reloads to the same selection
    path = autotune.table_path()
    with open(path) as fh:
        data = json.load(fh)
    assert data["kind"] == "qsc_autotune_table"
    assert data["manifest"]["kind"] == "manifest"
    autotune.invalidate_cache()
    assert autotune.lookup(3, 2, 7) == entry["best_train"]
    assert autotune.lookup(3, 2, 7, mode="infer") == entry["best_fwd"]
    # a second ensure() is a cache hit, not a re-measurement
    again = autotune.ensure(3, 2, 7, budget_s=0.05)
    assert again["ts"] == entry["ts"]


def test_missing_and_corrupt_table_fall_back_to_dense():
    """lookup never raises; resolve_impl degrades to the dense fallback."""
    # missing file
    assert autotune.lookup(6, 3, 256) is None
    assert resolve_impl("auto", "auto", 6, 3, 256) == "dense"
    # corrupt JSON
    path = autotune.table_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        fh.write("{definitely not json")
    autotune.invalidate_cache()
    assert autotune.lookup(6, 3, 256) is None
    assert resolve_impl("auto", "auto", 6, 3, 256) == "dense"
    # structurally alien payloads and garbage winners are rejected too
    # ("auto" would recurse through the resolver)
    for bad in ("not-a-backend", "auto"):
        with open(path, "w") as fh:
            json.dump({"entries": {"cpu/n6/L3/b256/float32": {"best_train": bad}}}, fh)
        autotune.invalidate_cache()
        assert autotune.lookup(6, 3, 256) is None
        assert resolve_impl("auto", "auto", 6, 3, 256) == "dense"
    # "sharded" is no longer garbage: the scaling subsystem made it a
    # first-class selection, canonicalized and re-checked against THIS
    # topology at read time — the 8-virtual-device harness dispatches it...
    with open(path, "w") as fh:
        json.dump(
            {"entries": {"cpu/n6/L3/b256/float32": {"best_train": "sharded"}}}, fh
        )
    autotune.invalidate_cache()
    assert autotune.lookup(6, 3, 256) == "sharded_statevector"
    # ...and a single-device process degrades to the heuristic instead of
    # dispatching a collective program with nobody to exchange with
    autotune.invalidate_cache()
    with open(path, "w") as fh:
        json.dump(
            {"entries": {"cpu/n6/L3/b256/float32": {"best_train": "sharded"}}}, fh
        )
    from unittest import mock

    with mock.patch.object(autotune, "model_axis_devices", return_value=1):
        sel, reason = autotune.lookup_reason(6, 3, 256)
        assert sel is None and reason == "entry-ineligible"
        assert resolve_impl("auto", "auto", 6, 3, 256) == "dense"


def test_impl_override_wins_over_table():
    """quantum.impl (and the legacy backend) beat any table entry."""
    import jax

    key = autotune.table_key(jax.default_backend(), 6, 3, 256)
    autotune.save_table({key: {"best_train": "pallas", "best_fwd": "pallas"}})
    assert resolve_impl("auto", "auto", 6, 3, 256) == "pallas"  # table engaged
    assert resolve_impl("tensor", "auto", 6, 3, 256) == "tensor"
    assert resolve_impl("dense", "pallas", 6, 3, 256) == "dense"
    assert resolve_impl("auto", "tensor", 6, 3, 256) == "tensor"


def test_eligible_impls_by_shape():
    # dense_fused races dense at every shape: the gate-matrix-cached build is
    # a first-class impl the table must PROVE wins, never assume
    assert autotune.eligible_impls(4, "cpu") == ["dense", "dense_fused", "pallas"]
    assert autotune.eligible_impls(7, "tpu") == [
        "dense", "dense_fused", "pallas", "pallas_circuit",
    ]
    assert autotune.eligible_impls(10, "tpu") == [
        "dense", "dense_fused", "pallas_circuit", "tensor",
    ]
    assert "sharded" not in autotune.eligible_impls(14, "tpu")


def test_prewarm_gating():
    """prewarm only tunes when the dispatcher is actually in play: impl and
    backend both auto AND autotune enabled for this platform ("auto" means
    off on the CPU test backend — tier-1 pays zero tuning compiles)."""
    cfg = ExperimentConfig(quantum=QuantumConfig(n_qubits=3, n_layers=1))
    assert autotune.prewarm(cfg, batch=8) is None  # autotune="auto" on cpu
    cfg = ExperimentConfig(
        quantum=QuantumConfig(n_qubits=3, n_layers=1, impl="dense", autotune="on")
    )
    assert autotune.prewarm(cfg, batch=8) is None  # impl forced
    cfg = ExperimentConfig(
        quantum=QuantumConfig(n_qubits=3, n_layers=1, backend="tensor", autotune="on")
    )
    assert autotune.prewarm(cfg, batch=8) is None  # legacy backend forced
    cfg = ExperimentConfig(
        quantum=QuantumConfig(n_qubits=3, n_layers=1, autotune="on")
    )
    entry = autotune.prewarm(cfg, batch=8)
    assert entry is not None and entry["best_train"] in entry["candidates"]
    # force=True re-measures even over the fresh entry (the bench contract:
    # candidate timings must come from THIS window)
    entry2 = autotune.prewarm(cfg, batch=8, force=True)
    assert entry2["ts"] != entry["ts"]


def test_prewarm_installs_configured_table_path(tmp_path):
    """quantum.autotune_table must become the table the TRACE-TIME lookup
    reads: the tuner writing one file while dispatch reads another would
    silently pin the dense fallback after paying the full tuning cost."""
    custom = str(tmp_path / "custom" / "table.json")
    cfg = ExperimentConfig(
        quantum=QuantumConfig(
            n_qubits=3, n_layers=1, autotune="on", autotune_table=custom
        )
    )
    entry = autotune.prewarm(cfg, batch=8)
    assert os.path.exists(custom)
    # the plain lookup (no path — exactly what circuits.resolve_impl does)
    # now resolves against the configured table
    assert autotune.lookup(3, 1, 8) == entry["best_train"]
    assert resolve_impl("auto", "auto", 3, 1, 8) == entry["best_train"]


# ---------------------------------------------------------------------------
# Report gate: QSC compares best-of-impls, not a losing fixed impl
# ---------------------------------------------------------------------------


def _bench_artifact(path, **impl_sps):
    rec = {
        "metric": "hdce_train_samples_per_sec_per_chip",
        "value": 100.0,
        "platform": "cpu_fallback",
        "details": {k: {"samples_per_sec": v} for k, v in impl_sps.items()},
    }
    path.write_text(json.dumps(rec) + "\n")
    return str(path)


def test_report_qsc_gates_on_best_of_impls(tmp_path):
    """A fixed impl losing ground (or a regressed loser) must not fail the
    gate while the best-of-impls throughput held or improved — and the
    synthesized qsc.best_of_impls row must itself gate."""
    from qdml_tpu.telemetry.report import build_report_data

    base = _bench_artifact(tmp_path / "base.json", qsc_dense=12.0, qsc_pallas=10.0)
    # pallas collapsed, but the auto-dispatched path beats the old best
    cur = _bench_artifact(
        tmp_path / "cur.json", qsc_dense=12.0, qsc_pallas=5.0, qsc_auto=13.0
    )
    data = build_report_data([cur], base, threshold_pct=10.0)
    by_metric = {g["metric"]: g for g in data["gates"]}
    assert by_metric["qsc_pallas.samples_per_sec"]["status"] == "informational"
    assert by_metric["qsc.best_of_impls"]["status"] == "ok"
    assert not data["regressions"]

    # every impl regressing DOES fail: best-of-impls is a real gate
    cur2 = _bench_artifact(tmp_path / "cur2.json", qsc_dense=6.0, qsc_pallas=5.0)
    data2 = build_report_data([cur2], base, threshold_pct=10.0)
    assert any(r["metric"] == "qsc.best_of_impls" for r in data2["regressions"])
    # the per-impl rows still never feed the regression list
    assert not any("qsc_" in r["metric"] for r in data2["regressions"])


def test_report_qsc_auto_regression_is_not_demoted(tmp_path):
    """qsc_auto IS the hot path: a mis-dispatching autotuner (auto slow while
    a fixed impl still measures fast, so best-of-impls stays green) must
    fail the gate on the qsc_auto row itself."""
    from qdml_tpu.telemetry.report import build_report_data

    base = _bench_artifact(tmp_path / "base.json", qsc_dense=12.0, qsc_auto=12.5)
    cur = _bench_artifact(tmp_path / "cur.json", qsc_dense=12.0, qsc_auto=7.0)
    data = build_report_data([cur], base, threshold_pct=10.0)
    assert any(r["metric"] == "qsc_auto.samples_per_sec" for r in data["regressions"])
    by_metric = {g["metric"]: g for g in data["gates"]}
    assert by_metric["qsc_auto.samples_per_sec"]["status"] == "regression"
    # best-of still carried by the healthy fixed impl — and that is exactly
    # why qsc_auto needs its own armed row
    assert by_metric["qsc.best_of_impls"]["status"] == "ok"


# ---------------------------------------------------------------------------
# Serve warmup: autotune at AOT-bucket compile time, zero request-path compiles
# ---------------------------------------------------------------------------


def test_serve_warmup_autotunes_with_zero_request_path_compiles():
    """With quantum.impl=auto and the tuner forced ON, warmup runs the
    micro-benchmark and AOT-compiles the winner per bucket — and the request
    path still provably never compiles (the engine's own post-warmup
    compile-cache snapshot, the PR-2 gate)."""
    from qdml_tpu.serve import ServeEngine
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        quantum=QuantumConfig(n_qubits=3, n_layers=1, autotune="on"),
        train=TrainConfig(batch_size=16, n_epochs=1),
        serve=ServeConfig(max_batch=4, buckets=(4,), max_wait_ms=1.0, max_queue=32, batching="bucket"),
    )
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, qsc_state = init_sc_state(cfg, quantum=True, steps_per_epoch=4)
    engine = ServeEngine(cfg, hdce_vars, {"params": qsc_state.params}, quantum=True)
    warm = engine.warmup()
    # the warmup artifact names the impl each bucket's executable dispatches,
    # with the tuner's candidate timings attached
    assert warm["quantum_impl"]["4"]["impl"] in (
        "dense", "dense_fused", "pallas", "tensor",
    )
    assert warm["quantum_impl"]["4"].get("autotuned") is True
    assert "dense" in warm["quantum_impl"]["4"]["candidates"]
    # the winner is the persisted table's infer-mode selection
    assert warm["quantum_impl"]["4"]["impl"] == (
        autotune.lookup(3, 1, 4, mode="infer") or "dense"
    )
    x = np.random.default_rng(0).standard_normal((3, *cfg.image_hw, 2)).astype(np.float32)
    for _ in range(3):
        h, pred, _conf, info = engine.infer(x)
        assert h.shape[0] == 3 and info.bucket == 4
    assert engine.request_path_compiles() == {"hits": 0, "misses": 0, "requests": 0}


def test_serve_mps_impl_baked_into_aot_bucket_zero_compiles():
    """A scaling impl pinned into the engine: warmup AOT-compiles the mps
    circuit (chi from quantum.mps_chi, recorded per bucket) and the request
    path still provably never compiles — the PR-5 pin survives the new
    subsystem."""
    from qdml_tpu.serve import ServeEngine
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        quantum=QuantumConfig(n_qubits=3, n_layers=1, impl="mps", mps_chi=4),
        train=TrainConfig(batch_size=16, n_epochs=1),
        serve=ServeConfig(max_batch=4, buckets=(4,), max_wait_ms=1.0, max_queue=32, batching="bucket"),
    )
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, qsc_state = init_sc_state(cfg, quantum=True, steps_per_epoch=4)
    engine = ServeEngine(cfg, hdce_vars, {"params": qsc_state.params}, quantum=True)
    warm = engine.warmup()
    assert warm["quantum_impl"]["4"]["impl"] == "mps"
    assert warm["quantum_impl"]["4"]["mps_chi"] == 4
    x = np.random.default_rng(0).standard_normal((3, *cfg.image_hw, 2)).astype(np.float32)
    for _ in range(3):
        h, pred, _conf, info = engine.infer(x)
        assert h.shape[0] == 3 and info.bucket == 4
    assert engine.request_path_compiles() == {"hits": 0, "misses": 0, "requests": 0}


# ---------------------------------------------------------------------------
# The autotune_fallback record: a table pathology is never silent
# ---------------------------------------------------------------------------


def test_fallback_record_emitted_once_per_pathology(tmp_path):
    """A corrupt table degrades to the heuristic AND leaves one structured
    autotune_fallback record in the active telemetry sink — deduplicated per
    (table, shape, reason), so tracing the same circuit twice reports once."""
    from qdml_tpu.telemetry.core import Telemetry
    from qdml_tpu.telemetry.spans import set_sink

    path = autotune.table_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        fh.write("{definitely not json")
    autotune.invalidate_cache()

    jsonl = tmp_path / "run.jsonl"
    sink = Telemetry(str(jsonl))
    set_sink(sink)
    try:
        assert resolve_impl("auto", "auto", 6, 3, 256) == "dense"
        assert resolve_impl("auto", "auto", 6, 3, 256) == "dense"  # dedup
        # a DIFFERENT pathology at the same shape is its own record
        with open(path, "w") as fh:
            json.dump(
                {"entries": {autotune.table_key("cpu", 6, 3, 256): {"best_train": "nope"}}},
                fh,
            )
        # reload the table but keep the emitted-set (same process lifetime)
        autotune._CACHE.clear()
        autotune._STATUS.clear()
        assert resolve_impl("auto", "auto", 6, 3, 256) == "dense"
    finally:
        set_sink(None)
        sink.close()

    recs = [json.loads(ln) for ln in jsonl.read_text().splitlines() if ln.strip()]
    falls = [r for r in recs if r.get("kind") == "autotune_fallback"]
    assert len(falls) == 2, falls
    assert falls[0]["reason"] == "table-corrupt"
    assert falls[1]["reason"] == "entry-alien"
    for r in falls:
        assert r["table"] == path and r["fallback"] == "dense"
        assert r["key"].endswith("/n6/L3/b256/float32")


def test_fallback_missing_table_is_not_a_pathology():
    """The normal cold start (no table yet) must NOT emit a fallback record
    — only corrupt/alien/undispatchable states are report-worthy."""
    sel, reason = autotune.lookup_reason(6, 3, 256)
    assert sel is None and reason is None
