"""Serving subsystem: micro-batcher edge cases, bucketed AOT engine parity,
zero request-path compiles, loadgen harness, checkpoint tag discovery, and
the report serving-latency section."""

import json
import threading
import time

import jax
import numpy as np
import pytest

from qdml_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    ServeConfig,
    TrainConfig,
)
from qdml_tpu.serve import (
    MicroBatcher,
    Overloaded,
    Prediction,
    Request,
    ServeEngine,
    ServeLoop,
    pick_bucket,
    power_of_two_buckets,
)
from qdml_tpu.serve.loadgen import make_request_samples, run_loadgen
from qdml_tpu.serve.types import (
    DEADLINE_AT_DEQUEUE,
    DEADLINE_AT_SUBMIT,
    QUEUE_FULL,
)


def _read_jsonl(path):
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


# ---------------------------------------------------------------------------
# Micro-batcher (deterministic fake clock — no sleeping)
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _req(rid, deadline=None):
    return Request(rid=rid, x=np.zeros((2, 2, 2), np.float32), deadline=deadline)


def _batcher(clock, max_batch=4, max_wait_s=0.005, max_queue=8):
    return MicroBatcher(
        max_batch=max_batch, max_wait_s=max_wait_s, max_queue=max_queue, clock=clock
    )


def test_empty_queue_flush_is_noop():
    mb = _batcher(FakeClock())
    batch, shed = mb.next_batch()
    assert batch == [] and shed == []
    assert mb.wait_hint() == mb.max_wait_s


def test_max_wait_timeout_flushes_single_request():
    clock = FakeClock()
    mb = _batcher(clock)
    assert mb.submit(_req(1)) is None
    # not aged yet: coalescing window still open
    batch, shed = mb.next_batch()
    assert batch == [] and shed == [] and mb.depth == 1
    assert mb.wait_hint() == pytest.approx(0.005)
    clock.t = 0.005
    batch, shed = mb.next_batch()
    assert [r.rid for r in batch] == [1] and shed == [] and mb.depth == 0


def test_full_batch_flushes_without_waiting():
    clock = FakeClock()
    mb = _batcher(clock, max_batch=4)
    for i in range(6):
        assert mb.submit(_req(i)) is None
    batch, _ = mb.next_batch()  # t=0: full batch beats the wait window
    assert [r.rid for r in batch] == [0, 1, 2, 3]
    assert mb.depth == 2


def test_deadline_already_expired_at_dequeue_is_shed():
    clock = FakeClock()
    mb = _batcher(clock)
    assert mb.submit(_req(1, deadline=0.003)) is None
    assert mb.submit(_req(2, deadline=1.0)) is None
    clock.t = 0.01  # past req 1's deadline AND past max_wait
    batch, shed = mb.next_batch()
    # shed pairs (request, result): the caller needs the REQUEST back to
    # resolve its future — a dropped future is a client hung forever
    assert [(r.rid, o.rid) for r, o in shed] == [(1, 1)]
    assert all(o.reason == DEADLINE_AT_DEQUEUE for _, o in shed)
    assert shed[0][1].latency_s == pytest.approx(0.01)
    assert [r.rid for r in batch] == [2]  # live request still served


def test_deadline_already_expired_at_submit_rejected():
    clock = FakeClock()
    clock.t = 5.0
    mb = _batcher(clock)
    out = mb.submit(_req(1, deadline=4.0))
    assert isinstance(out, Overloaded) and out.reason == DEADLINE_AT_SUBMIT
    assert mb.depth == 0


def test_bounded_queue_sheds_instead_of_collapsing():
    mb = _batcher(FakeClock(), max_batch=2, max_queue=3)
    assert all(mb.submit(_req(i)) is None for i in range(3))
    out = mb.submit(_req(99))
    assert isinstance(out, Overloaded) and out.reason == QUEUE_FULL
    assert mb.depth == 3  # rejected request never enqueued


def test_continuous_admission_dispatches_without_waiting():
    """Continuous mode (the ragged engine's batcher policy): next_batch
    returns whatever is queued the moment anything is queued — no bucket-edge
    coalescing, no max-wait stall — and wait_hint is 0 on a non-empty queue
    (an idle engine must never sleep on work)."""
    clock = FakeClock()
    mb = MicroBatcher(max_batch=4, max_wait_s=0.005, max_queue=8, clock=clock,
                      continuous=True)
    assert mb.submit(_req(1)) is None
    # t=0, far from aged, far from full: continuous flushes anyway
    assert mb.wait_hint() == 0.0
    batch, shed = mb.next_batch()
    assert [r.rid for r in batch] == [1] and shed == []
    # a backlog still caps at max_batch per dispatch
    for i in range(2, 8):
        assert mb.submit(_req(i)) is None
    batch, _ = mb.next_batch()
    assert [r.rid for r in batch] == [2, 3, 4, 5]
    assert mb.depth == 2
    # empty queue: the idle sleep bound is unchanged
    mb.next_batch()
    assert mb.wait_hint() == mb.max_wait_s


def test_continuous_admission_still_sheds_expired_deadlines():
    """Deadline shedding is admission machinery, not coalescing machinery —
    continuous mode keeps it bit-for-bit."""
    clock = FakeClock()
    mb = MicroBatcher(max_batch=4, max_wait_s=0.005, max_queue=8, clock=clock,
                      continuous=True)
    assert mb.submit(_req(1, deadline=0.003)) is None
    assert mb.submit(_req(2, deadline=1.0)) is None
    clock.t = 0.01
    batch, shed = mb.next_batch()
    assert [(r.rid, o.reason) for r, o in shed] == [(1, DEADLINE_AT_DEQUEUE)]
    assert [r.rid for r in batch] == [2]


def test_bucket_overflow_falls_back_to_largest():
    buckets = (1, 2, 4, 8)
    assert pick_bucket(3, buckets) == 4
    assert pick_bucket(8, buckets) == 8
    assert pick_bucket(50, buckets) == 8  # oversize -> largest, never a new shape
    assert power_of_two_buckets(8) == (1, 2, 4, 8)
    assert power_of_two_buckets(6) == (1, 2, 4, 6)  # max_batch always last
    with pytest.raises(ValueError):
        power_of_two_buckets(0)
    with pytest.raises(ValueError):
        MicroBatcher(max_batch=8, max_queue=4)  # queue smaller than one batch


# ---------------------------------------------------------------------------
# Engine: restore -> warmup -> serve, parity with the offline forward
# ---------------------------------------------------------------------------


def _tiny_cfg():
    return ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=1),
        # batching pinned to the bucket incumbent: these are the coalescing
        # path's pins; the ragged twins live below (and the auto race, which
        # would otherwise time+persist a table entry at warmup, is exercised
        # against a tmp table in test_batching_auto_race_*)
        serve=ServeConfig(
            max_batch=8, buckets=(4, 8), max_wait_ms=1.0, max_queue=32,
            batching="bucket",
        ),
    )


@pytest.fixture(scope="module")
def warmed():
    """One warmed engine + offline reference shared by the serving tests
    (each bucket is an XLA compile; module scope keeps the suite fast)."""
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    cfg = _tiny_cfg()
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    engine = ServeEngine(cfg, hdce_vars, {"params": sc_state.params})
    samples = make_request_samples(cfg, 32)
    offline_h, offline_pred, _ = engine.offline_forward(samples["x"])
    engine.warmup()
    return cfg, engine, samples, offline_h, offline_pred


def test_unwarmed_engine_refuses_request_path(warmed):
    cfg, engine, *_ = warmed
    fresh = ServeEngine(cfg, *engine.live_vars())
    with pytest.raises(RuntimeError, match="warmup"):
        fresh.infer(np.zeros((2, *cfg.image_hw, 2), np.float32))


def test_infer_parity_across_buckets(warmed):
    """Every bucket (and the padded partial fills) must reproduce the offline
    eval forward on the same checkpoint — padding rows cannot leak."""
    cfg, engine, samples, offline_h, offline_pred = warmed
    for n in (1, 3, 4, 5, 8):
        h, pred, _conf, info = engine.infer(samples["x"][:n])
        assert info.bucket == pick_bucket(n, engine.buckets)
        assert info.n == n and info.rows == info.bucket and info.chunks == 1
        assert info.mode == "bucket"
        assert h.shape == (n, cfg.h_out_dim)
        np.testing.assert_allclose(h, offline_h[:n], rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(pred, offline_pred[:n])


def test_oversize_batch_serves_in_largest_bucket_chunks(warmed):
    cfg, engine, samples, offline_h, offline_pred = warmed
    n = 19  # > largest bucket (8): 8 + 8 + 3-padded-to-4
    x = np.concatenate([samples["x"]] * 2)[:n]
    ref = np.concatenate([offline_h] * 2)[:n]
    h, pred, _conf, info = engine.infer(x)
    assert info.bucket == engine.buckets[-1] and h.shape[0] == n
    np.testing.assert_allclose(h, ref, rtol=1e-5, atol=1e-5)
    # regression (oversize accounting): the final near-empty chunk used to be
    # invisible — fill was reported as n/largest = 19/8 = 2.375, inflating
    # batch-fill stats past 1.0. DispatchInfo sums the STATIC rows of every
    # chunk executable (8 + 8 + pad-to-4), so fill/pad accounting is honest.
    assert info.n == 19 and info.rows == 20 and info.chunks == 3
    assert info.fill == pytest.approx(19 / 20) and info.padded == 1
    from qdml_tpu.serve.metrics import ServeMetrics
    from qdml_tpu.serve.types import Prediction as P

    m = ServeMetrics()
    m.observe_batch(
        [P(rid=i, h=h[i], scenario=0, latency_s=0.0, bucket=info.bucket,
           batch_n=n) for i in range(n)],
        info, depth=0, dur_s=0.01,
    )
    fill = m.batch_fill.summary(unit=None)
    assert fill["max"] <= 1.0  # never >1 again
    assert m.rows() == {
        "useful": 19, "valid": 19, "dispatched": 20, "padded": 1,
        "dispatches": 3,
    }
    assert m.padding_waste() == pytest.approx(0.05)


def test_serve_smoke_zero_request_path_compiles(warmed):
    """The tier-1 acceptance smoke: restore -> warmup -> N requests through
    the full loop -> parity with the offline forward and NO compile-cache
    activity on the request path (the engine's own post-warmup snapshot —
    the process-global counters are never reset by serving)."""
    cfg, engine, samples, offline_h, offline_pred = warmed
    loop = ServeLoop(engine).start()
    try:
        futs = [loop.submit(samples["x"][i], rid=i) for i in range(20)]
        results = [f.result(timeout=30.0) for f in futs]
    finally:
        loop.stop()
    assert all(isinstance(r, Prediction) for r in results)
    served = np.stack([r.h for r in sorted(results, key=lambda r: r.rid)])
    np.testing.assert_allclose(served, offline_h[:20], rtol=1e-5, atol=1e-5)
    assert [r.scenario for r in sorted(results, key=lambda r: r.rid)] == [
        int(p) for p in offline_pred[:20]
    ]
    assert engine.request_path_compiles() == {"hits": 0, "misses": 0, "requests": 0}
    assert all(r.latency_s >= 0 and r.bucket in engine.buckets for r in results)


def test_loadgen_fast_run_emits_manifest_headed_telemetry(warmed, tmp_path):
    from qdml_tpu.telemetry import run_manifest
    from qdml_tpu.utils.metrics import MetricsLogger

    cfg, engine, *_ = warmed
    path = str(tmp_path / "loadgen.metrics.jsonl")
    logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
    summary = run_loadgen(cfg, engine, rate=2000.0, n=48, logger=logger)
    logger.close()

    assert summary["completed"] == 48 and summary["n_shed"] == 0
    assert summary["compile_cache_after_warmup"] == {"hits": 0, "misses": 0, "requests": 0}
    # per-request NMSE parity with the offline forward on the same checkpoint
    assert summary["parity_max_abs_err"] < 1e-4
    assert summary["pred_agreement"] == 1.0
    assert summary["nmse_db_served"] == pytest.approx(summary["nmse_db_offline"], abs=1e-6)
    assert {"p50_ms", "p95_ms", "p99_ms"} <= set(summary["latency_ms"])
    # the end-of-run poll of the live-metrics verb, folded slim (only the
    # fields the verb ADDS; the summary already carries the histograms)
    assert summary["server_metrics"] == {
        "workers": 1, "replicas": 1, "replica_completed": [48],
        "queue_depth_now": 0, "buckets": list(engine.buckets),
        "completed": 48, "swap_epoch": 0,
        # the trace/phase decomposition rides the same poll; null here —
        # this run samples at the trace_sample=0 default (tests/test_tracing
        # pins the traced shape)
        "phases": None, "trace": None,
    }
    # fleet facts ride the summary for the report gate (single-device here)
    assert summary["replicas"] == 1 and summary["workers"] == 1
    assert summary["mesh"] is None and summary["rps_per_replica"] == summary["rps"]
    assert summary["arrival"] == {"process": "poisson", "burstiness": 4.0}
    # no deadlines offered -> no SLO figure (never a fake 100%)
    assert summary["slo"] is None
    # warmup cost accounting rides into the serve_summary record
    assert all(c["available"] for c in summary["warmup"]["cost"].values())

    lines = _read_jsonl(path)
    assert lines[0]["kind"] == "manifest"
    kinds = [l.get("kind") for l in lines]
    assert "serve_summary" in kinds
    names = {l.get("name") for l in lines if l.get("kind") in ("span", "counters")}
    assert {"serve_batch", "serve_request", "serve"} <= names
    cnt = [l for l in lines if l.get("kind") == "counters" and l.get("name") == "serve"][0]
    assert cnt["latency"]["n"] == 48 and cnt["compile_cache"]["requests"] == 0


def test_socket_server_roundtrip(warmed):
    """The `qdml-tpu serve` framing layer: newline-JSON over local TCP."""
    import asyncio
    import socket
    from concurrent.futures import Future

    from qdml_tpu.serve.server import serve_async

    cfg, engine, samples, offline_h, offline_pred = warmed
    loop_ = ServeLoop(engine).start()
    aloop = asyncio.new_event_loop()
    t = threading.Thread(target=aloop.run_forever, daemon=True)
    t.start()
    ready: Future = Future()
    task = asyncio.run_coroutine_threadsafe(
        serve_async(loop_, "127.0.0.1", 0, ready), aloop
    )
    try:
        port = ready.result(timeout=10.0)
        with socket.create_connection(("127.0.0.1", port), timeout=10.0) as sk:
            fh = sk.makefile("rw")
            for i in range(3):
                fh.write(json.dumps({"id": i, "x": samples["x"][i].tolist()}) + "\n")
                fh.flush()
                resp = json.loads(fh.readline())
                assert resp["ok"] and resp["id"] == i
                assert resp["pred"] == int(offline_pred[i])
                np.testing.assert_allclose(
                    np.asarray(resp["h"], np.float32), offline_h[i], rtol=1e-5, atol=1e-5
                )
                assert resp["latency_ms"] >= 0
            # malformed line answers with a typed error, connection survives
            fh.write("not json\n")
            fh.flush()
            assert json.loads(fh.readline()) == {"ok": False, "reason": "bad_json"}
            # valid JSON but bad payload: typed bad_request, connection and
            # worker both survive (nothing reaches the batcher)
            fh.write(json.dumps({"id": 9}) + "\n")
            fh.flush()
            resp = json.loads(fh.readline())
            assert resp["ok"] is False and resp["reason"].startswith("bad_request")
            fh.write(json.dumps({"id": 10, "x": [[1.0]]}) + "\n")
            fh.flush()
            resp = json.loads(fh.readline())
            assert resp["ok"] is False and "shape" in resp["reason"]
            # and a real request still round-trips afterwards
            fh.write(json.dumps({"id": 11, "x": samples["x"][0].tolist()}) + "\n")
            fh.flush()
            assert json.loads(fh.readline())["ok"] is True
    finally:
        task.cancel()
        aloop.call_soon_threadsafe(aloop.stop)
        t.join(timeout=5.0)
        loop_.stop()


def test_dequeue_shed_resolves_future(warmed):
    """Regression: a request whose deadline expires IN QUEUE must still
    resolve its future (typed Overloaded) — driving the loop's pump directly
    with a fake clock, no worker thread, no races."""
    cfg, engine, samples, *_ = warmed
    clock = FakeClock()
    loop = ServeLoop(
        engine,
        batcher=MicroBatcher(max_batch=4, max_wait_s=0.005, max_queue=8, clock=clock),
    )
    fut = loop.submit(samples["x"][0], rid=1, deadline_ms=3.0)
    assert not fut.done()
    clock.t = 0.01  # deadline (t=0.003) passes while queued
    loop._serve_one()
    res = fut.result(timeout=1.0)
    assert isinstance(res, Overloaded) and res.reason == DEADLINE_AT_DEQUEUE
    assert loop.metrics.shed[DEADLINE_AT_DEQUEUE] == 1


def test_submit_validates_shape_synchronously(warmed):
    """Client errors never reach the worker (one ragged request inside a
    coalesced batch would crash everyone else's batch)."""
    cfg, engine, *_ = warmed
    loop = ServeLoop(engine)
    with pytest.raises(ValueError, match="shape"):
        loop.submit(np.zeros((3, 3), np.float32))


def test_dead_worker_rejects_instead_of_stranding(warmed):
    """submit() on a loop whose worker has exited resolves immediately with
    a typed shutdown result — never an unresolvable future."""
    from qdml_tpu.serve.types import SHUTDOWN

    cfg, engine, samples, *_ = warmed
    loop = ServeLoop(engine).start()
    loop.stop()
    res = loop.submit(samples["x"][0]).result(timeout=1.0)
    assert isinstance(res, Overloaded) and res.reason == SHUTDOWN


def test_overload_shedding_under_burst(warmed):
    """A burst beyond the bounded queue resolves every future with a typed
    result — completed + shed == submitted, nothing hangs or raises."""
    cfg, engine, *_ = warmed
    batcher = MicroBatcher(max_batch=4, max_wait_s=0.05, max_queue=4)
    loop = ServeLoop(engine, batcher=batcher)
    # don't start the worker yet: the whole burst lands on a stalled queue
    x = np.zeros((2, *cfg.image_hw, 2), np.float32)[0]
    futs = [loop.submit(x, rid=i) for i in range(16)]
    loop.start()
    try:
        results = [f.result(timeout=30.0) for f in futs]
    finally:
        loop.stop()
    ok = [r for r in results if isinstance(r, Prediction)]
    shed = [r for r in results if isinstance(r, Overloaded)]
    assert len(ok) + len(shed) == 16
    assert len(shed) == 12 and all(o.reason == QUEUE_FULL for o in shed)
    assert loop.metrics.shed[QUEUE_FULL] == 12


@pytest.mark.slow
def test_loadgen_soak_open_loop_with_deadlines(warmed, tmp_path):
    """Soak: sustained open-loop Poisson traffic with deadlines over a small
    queue — load is shed (typed), everything else parity-checks, and the
    request path still never compiles."""
    import dataclasses

    from qdml_tpu.telemetry import run_manifest
    from qdml_tpu.utils.metrics import MetricsLogger

    cfg, engine, *_ = warmed
    cfg = dataclasses.replace(
        cfg, serve=dataclasses.replace(cfg.serve, max_queue=16, max_wait_ms=0.5)
    )
    logger = MetricsLogger(
        str(tmp_path / "soak.jsonl"), echo=False, manifest=run_manifest(cfg)
    )
    summary = run_loadgen(
        cfg, engine, rate=2000.0, n=1500, deadline_ms=100.0, logger=logger
    )
    logger.close()
    assert summary["completed"] + summary["n_shed"] == 1500
    assert summary["completed"] > 0
    assert summary["compile_cache_after_warmup"]["requests"] == 0
    assert summary["parity_max_abs_err"] < 1e-4
    assert set(summary["shed"]) <= {QUEUE_FULL, DEADLINE_AT_SUBMIT, DEADLINE_AT_DEQUEUE}


# ---------------------------------------------------------------------------
# Live metrics verb + per-worker metrics merge + warmup cost accounting
# ---------------------------------------------------------------------------


def test_warmup_returns_per_bucket_cost(warmed):
    """Every AOT bucket carries a COMPILED cost record (flops, bytes, peak
    temp memory, roofline) — the serving half of the cost-accounting
    acceptance criterion."""
    cfg, engine, *_ = warmed
    assert set(engine.bucket_cost) == {str(b) for b in engine.buckets}
    for rec in engine.bucket_cost.values():
        assert rec["available"] is True and rec["source"] == "compiled"
        assert rec["flops"] > 0 and rec["bytes_accessed"] > 0
        assert rec["peak_temp_bytes"] is not None
        assert rec["roofline"] in ("compute-bound", "memory-bound")


def test_live_metrics_snapshot(warmed):
    """ServeLoop.live_metrics — the `{"op": "metrics"}` payload — reports
    counters, tail percentiles, batch fill, shed counts, queue depth and the
    compile-cache snapshot of a RUNNING loop."""
    cfg, engine, samples, *_ = warmed
    loop = ServeLoop(engine).start()
    try:
        futs = [loop.submit(samples["x"][i], rid=i) for i in range(12)]
        results = [f.result(timeout=30.0) for f in futs]
        live = loop.live_metrics()
    finally:
        loop.stop()
    assert all(isinstance(r, Prediction) for r in results)
    assert "kind" not in live  # a reading, not a run artifact
    assert live["completed"] == 12 and live["workers"] == 1
    assert live["queue_depth_now"] == 0 and live["buckets"] == list(engine.buckets)
    assert live["latency_ms"]["n"] == 12
    assert {"p50_ms", "p95_ms", "p99_ms"} <= set(live["latency_ms"])
    assert live["compile_cache_after_warmup"] == {"hits": 0, "misses": 0, "requests": 0}


def test_multi_worker_loop_merges_per_worker_metrics(warmed):
    """workers=2: both workers drain the shared batcher into their own
    collectors; the merged view accounts for every request exactly once and
    parity still holds (the engine is thread-safe post-warmup)."""
    cfg, engine, samples, offline_h, _ = warmed
    loop = ServeLoop(engine, workers=2).start()
    try:
        assert len(loop._threads) == 2
        # two bounded waves (the queue holds 32): every future resolves, and
        # work lands on whichever worker dequeues first
        results = []
        for wave in range(2):
            futs = [
                loop.submit(samples["x"][i % 32], rid=wave * 32 + i)
                for i in range(32)
            ]
            results += [f.result(timeout=30.0) for f in futs]
    finally:
        loop.stop()
    preds = [r for r in results if isinstance(r, Prediction)]
    assert len(preds) == 64  # bounded waves: nothing shed, nothing stranded
    for r in preds:
        np.testing.assert_allclose(r.h, offline_h[r.rid % 32], rtol=1e-5, atol=1e-5)
    merged = loop.merged_metrics()
    assert merged.completed == 64
    assert merged.latency.summary()["n"] == 64
    assert merged.batches == sum(m.batches for m in loop._worker_metrics)
    assert engine.request_path_compiles() == {"hits": 0, "misses": 0, "requests": 0}


def test_socket_metrics_verb(warmed):
    """`{"op": "metrics"}` over the TCP framing returns the live counters
    without submitting any inference."""
    import asyncio
    import socket
    from concurrent.futures import Future

    from qdml_tpu.serve.server import serve_async

    cfg, engine, samples, *_ = warmed
    loop_ = ServeLoop(engine).start()
    aloop = asyncio.new_event_loop()
    t = threading.Thread(target=aloop.run_forever, daemon=True)
    t.start()
    ready: Future = Future()
    task = asyncio.run_coroutine_threadsafe(
        serve_async(loop_, "127.0.0.1", 0, ready), aloop
    )
    try:
        port = ready.result(timeout=10.0)
        with socket.create_connection(("127.0.0.1", port), timeout=10.0) as sk:
            fh = sk.makefile("rw")
            # one real request so the counters are non-trivial
            fh.write(json.dumps({"id": 0, "x": samples["x"][0].tolist()}) + "\n")
            fh.flush()
            assert json.loads(fh.readline())["ok"] is True
            fh.write(json.dumps({"op": "metrics", "id": "m1"}) + "\n")
            fh.flush()
            resp = json.loads(fh.readline())
            assert resp["ok"] is True and resp["id"] == "m1"
            m = resp["metrics"]
            assert m["completed"] >= 1 and m["latency_ms"]["n"] >= 1
            assert m["compile_cache_after_warmup"]["requests"] == 0
            assert m["buckets"] == list(engine.buckets)
            # the verb itself submitted no inference
            fh.write(json.dumps({"op": "metrics"}) + "\n")
            fh.flush()
            m2 = json.loads(fh.readline())
            assert m2["metrics"]["completed"] == m["completed"]
    finally:
        task.cancel()
        aloop.call_soon_threadsafe(aloop.stop)
        t.join(timeout=5.0)
        loop_.stop()


# ---------------------------------------------------------------------------
# Checkpoint tag discovery + eval-only restore (serving's restore path)
# ---------------------------------------------------------------------------


def test_latest_tag_preference_and_eval_only_restore(tmp_path):
    from qdml_tpu.train.checkpoint import latest_tag, restore_params, save_checkpoint

    wd = str(tmp_path)
    assert latest_tag(wd, "hdce") is None

    resume_payload = {
        "params": {"w": np.arange(4.0, dtype=np.float32)},
        "opt_state": {"mu": np.ones(4, np.float32)},
        "step": np.asarray(7),
        "batch_stats": {"mean": np.zeros(4, np.float32)},
    }
    save_checkpoint(wd, "hdce_resume", resume_payload, {"epoch": 3})
    assert latest_tag(wd, "hdce") == "hdce_resume"
    # eval-only restore: params + batch_stats come back, optimizer state does not
    vars_, meta = restore_params(wd, "hdce_resume")
    assert set(vars_) == {"params", "batch_stats"} and meta["epoch"] == 3
    np.testing.assert_array_equal(vars_["params"]["w"], resume_payload["params"]["w"])

    save_checkpoint(wd, "hdce_last", {"params": {"w": np.ones(4, np.float32)}})
    assert latest_tag(wd, "hdce") == "hdce_last"
    save_checkpoint(wd, "hdce_best", {"params": {"w": np.zeros(4, np.float32)}})
    assert latest_tag(wd, "hdce") == "hdce_best"  # best beats last beats resume
    # a params-only payload restores without a batch_stats key
    vars_, _ = restore_params(wd, "hdce_best")
    assert set(vars_) == {"params"}
    assert latest_tag(wd, "qsc") is None  # other families unaffected


def test_from_workdir_corrupt_qsc_fails_loud_never_downgrades(tmp_path):
    """A qsc tag that EXISTS but fails to restore (partial/corrupt write)
    must propagate as the TYPED restore error, not silently fall back to the
    classical classifier — a quantum deployment quietly serving SCP128 is
    the worst failure mode. Only the typed never-trained miss
    (CheckpointNotFoundError) downgrades. Since the resilience PR the
    failure is typed CheckpointRestoreError (a RuntimeError, NOT a
    FileNotFoundError), so no fallback keyed on the never-trained miss can
    ever confuse the two."""
    import os

    from qdml_tpu.train.checkpoint import (
        CheckpointNotFoundError,
        CheckpointRestoreError,
        save_checkpoint,
    )

    wd = str(tmp_path)
    save_checkpoint(wd, "hdce_last", {"params": {"w": np.ones(4, np.float32)}})
    save_checkpoint(wd, "sc_last", {"params": {"w": np.ones(4, np.float32)}})
    # corrupt qsc: the tag directory resolves (latest_tag finds it) but
    # orbax's restore raises — underneath it is a FileNotFoundError, the
    # exact shape a broad except would confuse with "never trained"
    os.makedirs(os.path.join(wd, "qsc_last"))
    with pytest.raises(CheckpointRestoreError) as ei:
        ServeEngine.from_workdir(_tiny_cfg(), wd)
    assert not isinstance(ei.value, CheckpointNotFoundError)  # the restore failure, not the miss


# ---------------------------------------------------------------------------
# Compile-cache counters: listener idempotency + reset
# ---------------------------------------------------------------------------


def test_install_listener_idempotent(monkeypatch):
    from jax import monitoring

    from qdml_tpu.utils import compile_cache as cc

    calls = []
    monkeypatch.setattr(cc, "_LISTENING", False)
    monkeypatch.setattr(monitoring, "register_event_listener", lambda fn: calls.append(fn))
    cc.enable_compile_cache()
    cc.enable_compile_cache()
    cc._install_listener()
    assert len(calls) == 1  # one listener, however many times enabling repeats


def test_reset_stats_zeroes_counters():
    from qdml_tpu.utils import compile_cache as cc

    cc._on_event("/jax/compilation_cache/cache_hits")
    cc._on_event("/jax/compilation_cache/cache_misses")
    cc._on_event("/jax/compilation_cache/compile_requests_use_cache")
    stats = cc.compile_cache_stats()
    assert stats["hits"] >= 1 and stats["misses"] >= 1 and stats["requests"] >= 1
    cc.reset_stats()
    assert cc.compile_cache_stats() == {"hits": 0, "misses": 0, "requests": 0}
    # snapshot is a copy, not the live dict
    snap = cc.compile_cache_stats()
    cc._on_event("/jax/compilation_cache/cache_hits")
    assert snap["hits"] == 0


# ---------------------------------------------------------------------------
# Report: serving-latency section
# ---------------------------------------------------------------------------


def _serve_summary_rec(p50, p95, p99, rps, platform=None):
    rec = {
        "kind": "serve_summary",
        "rps": rps,
        "latency_ms": {"n": 100, "p50_ms": p50, "p95_ms": p95, "p99_ms": p99},
    }
    if platform is not None:
        rec["platform"] = platform
    return rec


def _write(tmp_path, name, *objs):
    p = tmp_path / name
    with open(p, "w") as fh:
        for o in objs:
            fh.write(json.dumps(o) + "\n")
    return str(p)


def test_report_serving_latency_section_and_gate(tmp_path):
    from qdml_tpu.telemetry.report import EXIT_OK, EXIT_REGRESSION, build_report, report_main

    base = _write(tmp_path, "base.jsonl", _serve_summary_rec(5.0, 9.0, 12.0, 800.0))
    # p99 +50%, rps -40%: both must gate
    bad = _write(tmp_path, "bad.jsonl", _serve_summary_rec(5.1, 9.2, 18.0, 480.0))
    md, regressions, armed = build_report([bad], base, 10.0)
    assert "## serving latency" in md and armed
    names = {r["metric"] for r in regressions}
    assert "serving.p99_ms" in names and "serve.rps" in names
    assert "serving.p50_ms" not in names  # within threshold
    assert report_main([f"--current={bad}", f"--baseline={base}"]) == EXIT_REGRESSION

    # latency IMPROVING (going down) must not gate
    good = _write(tmp_path, "good.jsonl", _serve_summary_rec(2.0, 4.0, 6.0, 900.0))
    md, regressions, armed = build_report([good], base, 10.0)
    assert not regressions and "improved" in md
    assert report_main([f"--current={good}", f"--baseline={base}"]) == EXIT_OK


def test_arrival_processes_shapes_and_mean_rate():
    """All three arrival processes produce n strictly-increasing offsets; the
    Poisson and MMPP means track the target rate (law of large numbers at
    n=4000), and the bursty/diurnal processes are visibly non-Poisson (gap
    coefficient of variation > 1 — burstiness is the point)."""
    from qdml_tpu.serve.loadgen import arrival_times

    rng = np.random.default_rng(7)
    n, rate = 4000, 500.0
    for process in ("poisson", "bursty", "diurnal"):
        t = arrival_times(n, rate, np.random.default_rng(7), process=process)
        assert t.shape == (n,) and np.all(np.diff(t) > 0) and t[0] > 0
        mean_rate = n / t[-1]
        assert 0.6 * rate < mean_rate < 1.6 * rate, (process, mean_rate)
    # the MMPP generator is exact (gaps truncate+resample at state switches:
    # lull-rate gaps must not swallow burst dwells), so its realized mean
    # tracks the nominal rate even at high burstiness — a regression to
    # draw-then-flip lands ~40% under nominal at burstiness=16, far outside
    # this band (dwell-length variance keeps finite-n runs within ~±20%)
    for b in (4.0, 16.0):
        t = arrival_times(n, rate, np.random.default_rng(11), process="bursty", burstiness=b)
        assert 0.8 * rate < n / t[-1] < 1.2 * rate, (b, n / t[-1])
    gaps_p = np.diff(arrival_times(n, rate, np.random.default_rng(1), process="poisson"))
    for process in ("bursty", "diurnal"):
        gaps = np.diff(arrival_times(n, rate, np.random.default_rng(1), process=process))
        cv = np.std(gaps) / np.mean(gaps)
        cv_p = np.std(gaps_p) / np.mean(gaps_p)
        assert cv > cv_p * 1.1, (process, cv, cv_p)  # over-dispersed vs Poisson
    with pytest.raises(ValueError, match="arrival process"):
        arrival_times(4, 10.0, rng, process="lunar")
    with pytest.raises(ValueError, match="rate"):
        arrival_times(4, 0.0, rng)


def test_loadgen_slo_attainment_with_generous_deadline(warmed, tmp_path):
    """Every request carries a deadline it can trivially meet -> the
    serve_summary slo block reports full attainment over exactly n
    requests."""
    cfg, engine, *_ = warmed
    summary = run_loadgen(cfg, engine, rate=2000.0, n=24, deadline_ms=30000.0)
    assert summary["completed"] == 24
    assert summary["slo"] == {"n": 24, "met": 24, "attainment": 1.0}
    assert summary["deadline_ms"] == 30000.0


def test_slo_counts_sheds_as_misses(warmed):
    """A deadline-carrying request shed at dequeue is an SLO miss; the
    attainment fraction reflects it (driving the pump with a fake clock)."""
    cfg, engine, samples, *_ = warmed
    clock = FakeClock()
    loop = ServeLoop(
        engine,
        batcher=MicroBatcher(max_batch=4, max_wait_s=0.005, max_queue=8, clock=clock),
    )
    f1 = loop.submit(samples["x"][0], rid=1, deadline_ms=3.0)
    f2 = loop.submit(samples["x"][1], rid=2, deadline_ms=10000.0)
    clock.t = 0.01  # req 1's deadline passes while queued
    loop._serve_one()
    assert isinstance(f1.result(timeout=1.0), Overloaded)
    assert isinstance(f2.result(timeout=1.0), Prediction)
    assert f2.result().deadline_met is True
    slo = loop.metrics.slo()
    assert slo == {"n": 2, "met": 1, "attainment": 0.5}


def test_replica_pool_shares_feed_and_merges_metrics(warmed):
    """ReplicaPool: 2 replicas drain ONE batcher against ONE warmed engine —
    every future resolves, parity holds, the pool-merged metrics account for
    every request exactly once, and the request path never compiles."""
    from qdml_tpu.serve import ReplicaPool

    cfg, engine, samples, offline_h, _ = warmed
    pool = ReplicaPool(engine, replicas=2).start()
    try:
        assert pool.n_replicas == 2 and pool.workers == 2
        results = []
        for wave in range(2):
            futs = [
                pool.submit(samples["x"][i % 32], rid=wave * 32 + i)
                for i in range(32)
            ]
            results += [f.result(timeout=30.0) for f in futs]
        live = pool.live_metrics()
    finally:
        pool.stop()
    preds = [r for r in results if isinstance(r, Prediction)]
    assert len(preds) == 64
    for r in preds:
        np.testing.assert_allclose(r.h, offline_h[r.rid % 32], rtol=1e-5, atol=1e-5)
    merged = pool.merged_metrics()
    assert merged.completed == 64 and merged.latency.summary()["n"] == 64
    assert live["replicas"] == 2 and sum(live["replica_completed"]) == 64
    assert engine.request_path_compiles() == {"hits": 0, "misses": 0, "requests": 0}


def test_pool_stopped_replica_does_not_shed_while_peer_lives(warmed):
    """Stopping ONE replica of a pool must not drain the shared queue as
    SHUTDOWN while its peer still serves — last-worker-out POOL-WIDE is the
    drain trigger (the PR-3 hazard generalized across replicas)."""
    from qdml_tpu.serve import ReplicaPool

    cfg, engine, samples, *_ = warmed
    pool = ReplicaPool(engine, replicas=2).start()
    try:
        pool.replicas[0].stop()
        # peer replica still drains the shared feed: work completes normally
        futs = [pool.submit(samples["x"][i], rid=i) for i in range(8)]
        results = [f.result(timeout=30.0) for f in futs]
        assert all(isinstance(r, Prediction) for r in results)
    finally:
        pool.stop()
    # the WHOLE pool stopped -> submits reject with typed shutdown
    from qdml_tpu.serve.types import SHUTDOWN

    res = pool.submit(samples["x"][0]).result(timeout=1.0)
    assert isinstance(res, Overloaded) and res.reason == SHUTDOWN


def test_report_serving_slo_gate_and_fleet_line(tmp_path):
    """serve_summary slo.attainment gates like roofline (a DROP beyond the
    threshold regresses); the fleet block renders baseline-vs-current
    topology in the serving section."""
    from qdml_tpu.telemetry.report import EXIT_OK, EXIT_REGRESSION, build_report, report_main

    def rec(attain, replicas, devices, rps):
        r = _serve_summary_rec(5.0, 9.0, 12.0, rps)
        r["slo"] = {"n": 100, "met": int(attain * 100), "attainment": attain}
        r["replicas"] = replicas
        r["workers"] = replicas
        r["mesh"] = {"devices": devices, "axes": {"data": devices}}
        r["rps_per_replica"] = round(rps / replicas, 2)
        return r

    base = _write(tmp_path, "base.jsonl", rec(0.99, 2, 4, 800.0))
    bad = _write(tmp_path, "bad.jsonl", rec(0.70, 2, 4, 820.0))
    md, regressions, armed = build_report([bad], base, 10.0)
    assert armed and {r["metric"] for r in regressions} == {"serve.slo_attainment"}
    assert "serving SLO attainment" in md
    assert "fleet: baseline 2 replica(s) x 4 device(s)" in md
    assert report_main([f"--current={bad}", f"--baseline={base}"]) == EXIT_REGRESSION

    ok = _write(tmp_path, "ok.jsonl", rec(0.995, 4, 8, 1600.0))
    md, regressions, armed = build_report([ok], base, 10.0)
    assert not regressions
    assert "current 4 replica(s) x 8 device(s)" in md
    assert report_main([f"--current={ok}", f"--baseline={base}"]) == EXIT_OK


def test_report_goodput_and_padding_waste_gates(tmp_path):
    """The ragged-batching gates: goodput_rps rides the throughput gate
    (lower = regression), padding_waste gates ABSOLUTELY like the overflow
    rate (current > baseline + 0.05 fails; near-zero baselines make ratios
    meaningless), and the fleet line names the batching mode."""
    from qdml_tpu.telemetry.report import (
        EXIT_OK,
        EXIT_REGRESSION,
        build_report,
        report_main,
    )

    def rec(goodput, waste, mode):
        r = _serve_summary_rec(5.0, 9.0, 12.0, 800.0)
        r["goodput_rps"] = goodput
        r["padding_waste"] = waste
        r["batching"] = {"mode": mode, "continuous_admission": mode == "ragged"}
        r["replicas"] = 1
        r["workers"] = 1
        return r

    base = _write(tmp_path, "base.jsonl", rec(760.0, 0.08, "bucket"))
    # goodput -40%, padding waste +9 points: both must gate
    bad = _write(tmp_path, "bad.jsonl", rec(456.0, 0.17, "bucket"))
    md, regressions, armed = build_report([bad], base, 10.0)
    assert armed
    names = {r["metric"] for r in regressions}
    assert {"serve.goodput_rps", "serve.padding_waste"} <= names
    assert "serving padding waste" in md
    assert report_main([f"--current={bad}", f"--baseline={base}"]) == EXIT_REGRESSION

    # the ragged win direction: goodput up, waste down, mode named on the
    # fleet line — no regression, exit 0 (the dryrun's round-trip shape)
    good = _write(tmp_path, "good.jsonl", rec(840.0, 0.01, "ragged"))
    md, regressions, armed = build_report([good], base, 10.0)
    assert not regressions
    assert "ragged-batching" in md and "bucket-batching" in md
    assert "pad waste" in md
    assert report_main([f"--current={good}", f"--baseline={base}"]) == EXIT_OK

    # inside the slack band: ok, not improved/regressed
    near = _write(tmp_path, "near.jsonl", rec(800.0, 0.10, "bucket"))
    _, regressions, _ = build_report([near], base, 10.0)
    assert not regressions


def test_report_serving_platform_mismatch_disarms(tmp_path):
    """A CPU loadgen run diffed against a TPU baseline compares hardware,
    not code: deltas shown, serving gate disarmed (loadgen stamps its
    backend into serve_summary precisely so this check can fire)."""
    from qdml_tpu.telemetry.report import EXIT_OK, build_report, report_main

    base = _write(
        tmp_path, "tpu.jsonl", _serve_summary_rec(1.0, 2.0, 3.0, 9000.0, platform="tpu")
    )
    cur = _write(
        tmp_path, "cpu.jsonl", _serve_summary_rec(10.0, 20.0, 30.0, 400.0, platform="cpu")
    )
    md, regressions, armed = build_report([cur], base, 10.0)
    assert regressions and not armed and "platform mismatch" in md
    assert report_main([f"--current={cur}", f"--baseline={base}"]) == EXIT_OK


# ---------------------------------------------------------------------------
# Ragged continuous batching: traced valid-count tiers, parity, goodput
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ragged(warmed):
    """The warmed bucket engine's ragged twin on the SAME params: every
    capacity tier compiled with a traced valid-count (module scope — each
    tier is an XLA compile)."""
    import dataclasses

    cfg, engine, samples, offline_h, offline_pred = warmed
    rcfg = dataclasses.replace(
        cfg, serve=dataclasses.replace(cfg.serve, batching="ragged")
    )
    rengine = ServeEngine(rcfg, *engine.live_vars())
    warm = rengine.warmup()
    return rcfg, rengine, warm


def test_ragged_vs_bucket_bit_exact_at_every_fill(warmed, ragged):
    """The ragged-vs-bucket parity pin: at EVERY fill level 1..capacity the
    ragged executable (traced n_valid, masked pad tail) returns bit-identical
    fp32 outputs to the bucket executable on the same params — the mask may
    not perturb a single ulp of any valid row."""
    cfg, bengine, samples, offline_h, offline_pred = warmed
    rcfg, rengine, _ = ragged
    assert rengine.batching_mode == {"4": "ragged", "8": "ragged"}
    assert rengine.continuous_admission is True
    for n in range(1, rengine.buckets[-1] + 1):
        hb, pb, cb, ib = bengine.infer(samples["x"][:n])
        hr, pr, cr, ir = rengine.infer(samples["x"][:n])
        assert ib.bucket == ir.bucket and ir.mode == "ragged"
        np.testing.assert_array_equal(hr, hb)
        np.testing.assert_array_equal(pr, pb)
        np.testing.assert_array_equal(cr, cb)
        np.testing.assert_allclose(hr, offline_h[:n], rtol=1e-5, atol=1e-5)
    assert rengine.request_path_compiles() == {"hits": 0, "misses": 0, "requests": 0}


def test_ragged_padded_rows_never_leak(warmed, ragged):
    """Garbage (NaN/Inf) in the pad tail of a ragged tier cannot perturb
    valid outputs: the traced mask zeroes pad rows INSIDE the program, so
    the proof is by construction, not by row-independence convention."""
    cfg, bengine, samples, offline_h, _ = warmed
    rcfg, rengine, _ = ragged
    xp = np.full((8, *cfg.image_hw, 2), np.nan, np.float32)
    xp[5:7] = np.inf
    xp[:3] = samples["x"][:3]
    out = rengine._compiled[8](*rengine.live_vars(), xp, np.int32(3))
    h = np.asarray(jax.device_get(out[0]))[:3]
    np.testing.assert_allclose(h, offline_h[:3], rtol=1e-5, atol=1e-5)
    # and the pad rows came out finite (the zero-masked forward), proving the
    # mask ran before any compute could propagate the garbage
    assert np.isfinite(np.asarray(jax.device_get(out[0]))).all()


def test_ragged_zero_compiles_across_warmup_traffic_and_swap():
    """The ragged twin of the hot-swap acceptance pin: a ragged engine
    serves traffic through the full loop, hot-swaps a checkpoint, serves
    again — zero request-path compiles across the whole window (the traced
    valid-count executables cover every fill level by construction)."""
    import dataclasses

    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    cfg = _tiny_cfg()
    cfg = dataclasses.replace(
        cfg, serve=dataclasses.replace(cfg.serve, batching="ragged")
    )

    def _vars(seed):
        c = dataclasses.replace(cfg, train=dataclasses.replace(cfg.train, seed=seed))
        _, hdce_state = init_hdce_state(c, 4)
        _, sc_state = init_sc_state(c, quantum=False, steps_per_epoch=4)
        return (
            {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats},
            {"params": sc_state.params},
        )

    h0, c0 = _vars(0)
    h1, c1 = _vars(123)
    engine = ServeEngine(cfg, h0, c0)
    samples = make_request_samples(cfg, 12)
    old_h, _, _ = engine.offline_forward(samples["x"])
    ref = ServeEngine(cfg, h1, c1)
    new_h, _, _ = ref.offline_forward(samples["x"])
    engine.warmup()

    loop = ServeLoop(engine).start()
    try:
        assert loop.batcher.continuous is True  # admission synced at start()
        pre = [loop.submit(samples["x"][i], rid=i) for i in range(12)]
        pre_res = [f.result(timeout=30.0) for f in pre]
        rec = engine.swap_params(h1, c1)
        post = [loop.submit(samples["x"][i], rid=100 + i) for i in range(12)]
        post_res = [f.result(timeout=30.0) for f in post]
    finally:
        loop.stop()
    assert rec["compile"] == {"hits": 0, "misses": 0, "requests": 0}
    for r in pre_res:
        assert isinstance(r, Prediction)
        np.testing.assert_allclose(r.h, old_h[r.rid], rtol=1e-5, atol=1e-5)
    for r in post_res:
        assert isinstance(r, Prediction)
        np.testing.assert_allclose(r.h, new_h[r.rid - 100], rtol=1e-5, atol=1e-5)
    assert engine.request_path_compiles() == {"hits": 0, "misses": 0, "requests": 0}
    # goodput/padding accounting rode along (every dispatch observed)
    m = loop.merged_metrics()
    assert m.rows()["valid"] == 24 and m.rows()["dispatched"] >= 24
    assert m.padding_waste() is not None


def test_batching_auto_race_persists_and_rereads(warmed, tmp_path):
    """serve.batching=auto: warmup races bucket-vs-ragged per capacity tier
    against a tmp table, persists the measured winner, and a second warmup
    READS the table instead of re-timing (entry identity pins it)."""
    import dataclasses

    from qdml_tpu.serve import batching_autotune

    cfg, engine, *_ = warmed
    acfg = dataclasses.replace(
        cfg, serve=dataclasses.replace(cfg.serve, batching="auto", buckets=(4,),
                                       max_batch=4)
    )
    table = str(tmp_path / "serve_batching.json")
    batching_autotune.invalidate_cache()
    batching_autotune.set_table_path(table)
    try:
        e1 = ServeEngine(acfg, *engine.live_vars())
        warm = e1.warmup()
        entry = warm["batching"]["race"]["4"]
        assert entry["best_infer"] in ("bucket", "ragged")
        assert {"bucket", "ragged"} <= set(entry["candidates"])
        assert all(
            isinstance(c.get("infer_ms"), float) for c in entry["candidates"].values()
        )
        assert e1.batching_mode["4"] == entry["best_infer"]
        # persisted: a fresh load sees the same entry, and a second engine's
        # warmup resolves from the table (same ts pins "read, not re-raced")
        batching_autotune.invalidate_cache()
        batching_autotune.set_table_path(table)
        saved = batching_autotune.load_table()[entry["key"]]
        assert saved["ts"] == entry["ts"]
        e2 = ServeEngine(acfg, *engine.live_vars())
        warm2 = e2.warmup()
        assert warm2["batching"]["race"]["4"]["ts"] == entry["ts"]
        assert batching_autotune.lookup(4, "dense") == entry["best_infer"]
    finally:
        batching_autotune.invalidate_cache()


def test_batching_config_validation():
    import dataclasses

    cfg = _tiny_cfg()
    bad = dataclasses.replace(
        cfg, serve=dataclasses.replace(cfg.serve, batching="loose")
    )
    with pytest.raises(ValueError, match="serve.batching"):
        from qdml_tpu.train.hdce import init_hdce_state

        _, hdce_state = init_hdce_state(cfg, 4)
        ServeEngine(bad, {"params": hdce_state.params}, {"params": {}})


def test_loadgen_ragged_summary_carries_goodput_and_batching(ragged, tmp_path):
    """run_loadgen over a ragged engine: the summary's goodput/padding/rows
    columns are filled, the batching block names the mode per tier, and the
    zero-compile gate holds — the committed dryrun's per-run shape."""
    rcfg, rengine, _ = ragged
    summary = run_loadgen(rcfg, rengine, rate=2000.0, n=32, deadline_ms=30000.0)
    assert summary["completed"] == 32 and summary["n_shed"] == 0
    assert summary["compile_cache_after_warmup"] == {"hits": 0, "misses": 0, "requests": 0}
    assert summary["batching"] == {
        "mode": "ragged",
        "per_tier": {"4": "ragged", "8": "ragged"},
        "continuous_admission": True,
    }
    # every request completed within its (generous) deadline -> goodput == rps
    assert summary["goodput_rps"] == pytest.approx(summary["rps"], abs=0.02)
    rows = summary["rows"]
    assert rows["useful"] == rows["valid"] == 32
    assert rows["dispatched"] >= 32 and rows["padded"] == rows["dispatched"] - 32
    assert summary["padding_waste"] == pytest.approx(
        rows["padded"] / rows["dispatched"], abs=1e-4  # summary rounds to 4dp
    )
    assert summary["parity_max_abs_err"] < 1e-4
