"""Whole-program concurrency analyzer + runtime lockdep witness (ISSUE 19).

Static half (qdml_tpu/analysis/concurrency.py): the four rules over
on-disk fixtures presented at qdml_tpu-shaped fake paths (the same pattern
the per-module rule tests use), edge precision (nesting makes an edge,
sequential acquisition does not), RLock re-entry exemption, the committed
``results/lockgraph/`` artifact's freshness contract, and suppression/
dead-suppression flowing through the engine like any per-module rule.

Runtime half (qdml_tpu/utils/lockdep.py): disabled mode IS the stdlib
class (import-time inert, zero overhead — the checkify-off discipline),
enabled mode witnesses edges and raises a typed LockOrderError naming both
edges and both first-seen stacks, and one full chaos fault class re-runs
under QDML_LOCKDEP=1 pinning zero inversions across crash + restart.
"""

import ast
import json
import os
import subprocess
import sys
import textwrap
import threading
from collections import Counter

import pytest

from qdml_tpu.analysis import concurrency
from qdml_tpu.analysis import cli as lint_cli
from qdml_tpu.analysis.engine import LintEngine, ModuleContext
from qdml_tpu.utils import lockdep

REPO = lint_cli.repo_root()
FIXDIR = os.path.join("tests", "fixtures", "lint", "concurrency")


def _fixture_ctx(name: str, fake_path: str) -> ModuleContext:
    with open(os.path.join(REPO, FIXDIR, name), encoding="utf-8") as fh:
        src = fh.read()
    return ModuleContext(
        os.path.join("/fake", fake_path), fake_path, src, ast.parse(src)
    )


def _inline_ctx(src: str, fake_path: str) -> ModuleContext:
    src = textwrap.dedent(src)
    return ModuleContext(
        os.path.join("/fake", fake_path), fake_path, src, ast.parse(src)
    )


def _analyze(*ctxs, lock_map=None):
    return concurrency.analyze_modules(list(ctxs), lock_map=lock_map or {})


def _rules(grouped) -> Counter:
    return Counter(f.rule for fs in grouped.values() for f in fs)


# ---------------------------------------------------------------------------
# static half: the four rules over fixtures
# ---------------------------------------------------------------------------


def test_inversion_fixture_flags_the_cycle():
    grouped, model = _analyze(
        _fixture_ctx("inversion.py", "qdml_tpu/serve/inversion.py")
    )
    assert model.cycles() == [["Inverted._a", "Inverted._b"]]
    findings = [
        f for fs in grouped.values() for f in fs
        if f.rule == "lock-order-inversion"
    ]
    # one finding per participating edge: either line is a fix site
    assert len(findings) == 2
    for f in findings:
        assert "Inverted._a" in f.message and "Inverted._b" in f.message
        assert "deadlock" in f.message


def test_ordered_fixture_is_clean_and_sequential_makes_no_edge():
    grouped, model = _analyze(
        _fixture_ctx("inversion_clean.py", "qdml_tpu/serve/ordered.py")
    )
    assert _rules(grouped)["lock-order-inversion"] == 0
    assert ("Ordered._a", "Ordered._b") in model.edges
    # sequential() takes b then a NON-nested: no reverse edge, no fake cycle
    assert ("Ordered._b", "Ordered._a") not in model.edges
    assert model.cycles() == []


def test_blocking_fixture_direct_and_closure():
    grouped, _ = _analyze(
        _fixture_ctx("blocking.py", "qdml_tpu/serve/blocking.py")
    )
    findings = sorted(
        (f for fs in grouped.values() for f in fs
         if f.rule == "blocking-under-lock"),
        key=lambda f: f.line,
    )
    assert len(findings) == 2
    assert "sleep" in findings[0].text                 # direct site
    assert "self._settle()" in findings[1].text        # closure call site
    assert "wait()" in findings[1].message             # names the blocker


def test_blocking_clean_fixture():
    grouped, _ = _analyze(
        _fixture_ctx("blocking_clean.py", "qdml_tpu/serve/patient.py")
    )
    assert _rules(grouped)["blocking-under-lock"] == 0


def test_sync_io_in_async_fixture():
    # presented AS serve/server.py: the rule only arms on the event-loop
    # files (project.ASYNC_SCOPED_FILES)
    grouped, _ = _analyze(
        _fixture_ctx("async_io.py", "qdml_tpu/serve/server.py")
    )
    findings = sorted(
        (f for fs in grouped.values() for f in fs
         if f.rule == "sync-io-in-async"),
        key=lambda f: f.line,
    )
    assert len(findings) == 2
    assert {f.context for f in findings} == {
        "bad_handler", "bad_closure_handler"
    }
    # the same source OUTSIDE the scoped files is silent
    grouped, _ = _analyze(
        _fixture_ctx("async_io.py", "qdml_tpu/serve/other.py")
    )
    assert _rules(grouped)["sync-io-in-async"] == 0


def test_unmapped_shared_state_fixture():
    row = {"qdml_tpu/serve/shared_state.py": {"Guarded": {"_count": "_lock"}}}
    grouped, _ = _analyze(
        _fixture_ctx("shared_state.py", "qdml_tpu/serve/shared_state.py"),
        lock_map=row,
    )
    findings = [
        f for fs in grouped.values() for f in fs
        if f.rule == "unmapped-shared-state"
    ]
    # Racy: thread root + caller, no row -> flagged. Guarded: identical
    # shape, row sanctions it. Solo: caller-only writes, one entry point.
    assert len(findings) == 1
    assert "Racy._count" in findings[0].message
    assert "thread:_loop" in findings[0].message
    assert "caller" in findings[0].message


def test_dead_lock_map_fixture():
    stale_map = {
        "qdml_tpu/serve/dead_map.py": {
            "Here": {"_old": "_lock", "_live": "_zap_lock"},
            "Gone": {"_x": "_l"},
        },
        "qdml_tpu/serve/missing.py": {"Nobody": {"_y": "_l"}},
    }
    grouped, _ = _analyze(
        _fixture_ctx("dead_map.py", "qdml_tpu/serve/dead_map.py"),
        _inline_ctx("LOCK_MAP = {}\n", "qdml_tpu/analysis/project.py"),
        lock_map=stale_map,
    )
    msgs = [
        f.message for fs in grouped.values() for f in fs
        if f.rule == "dead-lock-map-entry"
    ]
    assert len(msgs) == 4
    assert any("_old" in m and "never assigned" in m for m in msgs)
    assert any("_zap_lock" in m and "not constructed" in m for m in msgs)
    assert any("class 'Gone'" in m for m in msgs)
    assert any("missing.py" in m and "not in the scanned tree" in m for m in msgs)


def test_static_rlock_reentry_no_self_cycle():
    ctx = _inline_ctx(
        """
        import threading


        class Gate:
            def __init__(self):
                self._gate = threading.RLock()

            def outer(self):
                with self._gate:
                    self.inner()

            def inner(self):
                with self._gate:
                    return 1
        """,
        "qdml_tpu/serve/gate.py",
    )
    grouped, model = _analyze(ctx)
    assert model.locks["Gate._gate"].kind == "rlock"
    assert model.cycles() == []
    assert _rules(grouped)["lock-order-inversion"] == 0


def test_engine_suppression_and_dead_suppression_for_concurrency(tmp_path):
    """Concurrency findings merge BEFORE suppression processing: an inline
    reasoned disable suppresses them, and a stale one goes dead-suppression
    — same machinery as every per-module rule."""
    (tmp_path / "mod.py").write_text(
        textwrap.dedent(
            """
            import threading
            import time


            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def f(self):
                    with self._lock:
                        time.sleep(0.1)  # lint: disable=blocking-under-lock(test: the hold is the point)

                def g(self):
                    self.n += 1  # lint: disable=unmapped-shared-state(stale: single entry point, rule never fires here)
            """
        )
    )
    result = LintEngine(str(tmp_path)).run(["mod.py"])
    sup = [f for f in result.suppressed if f.rule == "blocking-under-lock"]
    assert len(sup) == 1 and sup[0].reason.startswith("test:")
    rules = Counter(f.rule for f in result.new)
    assert rules["dead-suppression"] == 1
    assert rules["blocking-under-lock"] == 0


# ---------------------------------------------------------------------------
# the committed repo artifacts
# ---------------------------------------------------------------------------


def test_repo_lock_graph_is_cycle_free_and_fresh():
    """The acceptance pin: the real package's lock-order graph has no cycle,
    and the committed results/lockgraph/ byte-matches a regeneration (the
    documented hierarchy is generated, never asserted)."""
    _grouped, model = concurrency.analyze_files(REPO)
    assert model.cycles() == []
    assert concurrency.check_lockgraph(
        model, os.path.join(REPO, "results", "lockgraph")
    ) == []


def test_lockgraph_check_detects_staleness(tmp_path):
    _grouped, model = concurrency.analyze_files(REPO)
    out = tmp_path / "lockgraph"
    concurrency.write_lockgraph(model, str(out))
    assert concurrency.check_lockgraph(model, str(out)) == []
    graph = json.loads((out / "lockgraph.json").read_text())
    graph["nodes"] = graph["nodes"][:-1]  # a lock vanished from the record
    (out / "lockgraph.json").write_text(json.dumps(graph))
    problems = concurrency.check_lockgraph(model, str(out))
    assert problems and "stale" in problems[0]


def test_repo_lockdep_witness_artifact_certifies_zero_inversions():
    path = os.path.join(REPO, "results", "lockdep_dryrun", "CHAOS_DRYRUN.json")
    with open(path) as fh:
        d = json.load(fh)
    w = d["lockdep"]
    assert w["enabled"] is True
    assert w["inversions"] == 0 and w["inversion_edges"] == []
    assert w["locks"] > 0 and w["edges"] > 0
    assert d["all_pass"] is True
    # the witnessed classes cover crash + restart + swap
    assert set(d["classes"]) == {"replica_crash", "corrupt_swap"}
    assert d["classes"]["replica_crash"]["restarts"] >= 1


# ---------------------------------------------------------------------------
# runtime half: lockdep unit tests
# ---------------------------------------------------------------------------


def test_lockdep_disabled_is_the_stdlib_class(monkeypatch):
    monkeypatch.delenv("QDML_LOCKDEP", raising=False)
    assert type(lockdep.Lock("X")) is type(threading.Lock())
    assert type(lockdep.RLock("X")) is type(threading.RLock())
    # import-time constructions in the package picked the stdlib path too
    from qdml_tpu.runtime import native_io

    assert type(native_io._LOCK) is type(threading.Lock())
    # a real class constructed now: stdlib lock, zero wrapper overhead
    from qdml_tpu.serve.faults import FaultPlan

    assert type(FaultPlan(seed=0)._lock) is type(threading.Lock())


@pytest.fixture
def witnessed(monkeypatch):
    monkeypatch.setenv("QDML_LOCKDEP", "1")
    lockdep.reset()
    yield
    lockdep.reset()


def test_lockdep_consistent_order_is_clean(witnessed):
    a, b = lockdep.Lock("A"), lockdep.Lock("B")
    for _ in range(3):
        with a:
            with b:
                pass
    w = lockdep.witness_summary()
    assert w["enabled"] is True
    assert w["edges"] == 1 and w["inversions"] == 0
    assert w["locks"] == 2 and w["max_held"] == 2


def test_lockdep_inversion_raises_typed_error_with_both_stacks(witnessed):
    a, b = lockdep.Lock("A"), lockdep.Lock("B")
    with a:
        with b:
            pass
    with pytest.raises(lockdep.LockOrderError) as exc:
        with b:
            with a:
                pass
    e = exc.value
    assert e.first == ("A", "B") and e.second == ("B", "A")
    assert "first-seen stack for A -> B" in str(e)
    assert "acquiring stack for B -> A" in str(e)
    assert e.first_stack and e.second_stack
    # recorded before the raise: the counter survives swallowed exceptions
    assert lockdep.witness_summary()["inversions"] == 1


def test_lockdep_rlock_reentry_is_exempt(witnessed):
    g = lockdep.RLock("G")
    with g:
        with g:
            pass
    w = lockdep.witness_summary()
    assert w["edges"] == 0 and w["inversions"] == 0


def test_lockdep_env_read_at_construction(witnessed, monkeypatch):
    assert isinstance(lockdep.Lock("now"), lockdep._DepLock)
    monkeypatch.delenv("QDML_LOCKDEP")
    assert type(lockdep.Lock("later")) is type(threading.Lock())


# ---------------------------------------------------------------------------
# --changed-only
# ---------------------------------------------------------------------------


def _git(cwd, *args):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=cwd, check=True, capture_output=True,
    )


def test_changed_only_scopes_the_report(tmp_path, monkeypatch):
    _git(tmp_path, "init", "-q")
    (tmp_path / "clean_mod.py").write_text(
        "y = 2  # lint: disable=broad-except\n"  # bare-suppression finding
    )
    _git(tmp_path, "add", "clean_mod.py")
    _git(tmp_path, "commit", "-qm", "seed")
    (tmp_path / "touched.py").write_text(
        "x = 1  # lint: disable=broad-except\n"  # bare-suppression finding
    )
    assert lint_cli.changed_files(str(tmp_path)) == ["touched.py"]
    # the full run sees both findings; restrict_to reports the touched file
    engine = LintEngine(str(tmp_path))
    full = engine.run(["clean_mod.py", "touched.py"])
    assert len(full.new) == 2
    scoped = engine.run(
        ["clean_mod.py", "touched.py"], restrict_to=["touched.py"]
    )
    assert [f.path for f in scoped.new] == ["touched.py"]
    # the CLI flag end-to-end: findings in the touched file fail the gate...
    monkeypatch.setattr(lint_cli, "repo_root", lambda: str(tmp_path))
    assert lint_cli.lint_main(
        ["--paths=clean_mod.py,touched.py", "--changed-only"]
    ) == 1
    # ...and a clean tree short-circuits to OK even with committed findings
    _git(tmp_path, "add", "touched.py")
    _git(tmp_path, "commit", "-qm", "touch")
    assert lint_cli.lint_main(
        ["--paths=clean_mod.py,touched.py", "--changed-only"]
    ) == 0


# ---------------------------------------------------------------------------
# the chaos witness, live (tier-1, slow-allowlisted)
# ---------------------------------------------------------------------------


def test_chaos_fault_class_under_lockdep(tmp_path):
    """One full chaos fault class (replica_crash: injected crash, supervised
    restart, recovery windows) re-run with QDML_LOCKDEP=1 — the whole
    serving stack's locks witnessed live, zero inversions. The committed
    results/lockdep_dryrun/ artifact extends this to corrupt_swap."""
    out = tmp_path / "lockdep_chaos"
    env = dict(os.environ, QDML_LOCKDEP="1", JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "scripts/chaos_dryrun.py",
         "--classes=replica_crash", "--n=160", f"--out-dir={out}"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=540,
    )
    assert r.returncode == 0, (r.stdout[-3000:], r.stderr[-2000:])
    with open(out / "CHAOS_DRYRUN.json") as fh:
        d = json.load(fh)
    w = d["lockdep"]
    assert w["enabled"] is True and w["inversions"] == 0
    assert w["locks"] > 0 and w["edges"] > 0
    assert d["all_pass"] is True
    assert d["classes"]["replica_crash"]["restarts"] >= 1
