"""Data layer: generator determinism, physics sanity, baselines, loaders."""

import jax.numpy as jnp
import numpy as np
import pytest

from qdml_tpu.config import DataConfig
from qdml_tpu.data import (
    ChannelGeometry,
    DMLGridLoader,
    beam_delay_profile,
    generate_datapair,
    generate_samples,
    label_noise_var,
    ls_estimate,
    make_network_batch,
    mmse_estimate,
)
from qdml_tpu.utils import nmse_complex

CFG = DataConfig(data_len=256)
GEOM = ChannelGeometry.from_config(CFG)


def _batch(n=128, snr=10.0, seed=CFG.seed, start=0):
    i = jnp.arange(start, start + n)
    return make_network_batch(
        jnp.uint32(seed), i % 3, (i // 3) % 3, i, jnp.float32(snr), GEOM
    )


def test_shapes_and_dtypes():
    out = _batch(32)
    assert out["yp"].shape == (32, 128)
    assert out["h_perf"].shape == (32, 2048)
    assert out["h_label"].shape == (32, 2048)
    assert out["yp_img"].shape == (32, 16, 8, 2)
    assert out["indicator"].shape == (32,)
    assert out["yp_img"].dtype == jnp.float32
    assert out["indicator"].dtype == jnp.int32


def test_determinism_and_offset_disjointness():
    a = _batch(16)
    b = _batch(16)
    np.testing.assert_array_equal(np.asarray(a["yp"].re), np.asarray(b["yp"].re))
    c = _batch(16, start=10_000)
    assert not np.allclose(np.asarray(a["yp"].re), np.asarray(c["yp"].re))


def test_channel_energy_normalised():
    out = _batch(256, snr=100.0)
    epow = float(jnp.mean(out["h_perf_c"].abs2()))
    assert 0.8 < epow < 1.2  # E|H_ij|^2 ~ 1


def test_rbg_generator_same_distribution():
    """DataConfig.rng_impl="rbg" swaps the bit generator, not the physics:
    same shapes, same determinism contract, and the same channel statistics
    (energy normalisation, LS-label noise model) as the threefry default —
    only the sample stream differs."""
    geom_rbg = ChannelGeometry.from_config(DataConfig(data_len=256, rng_impl="rbg"))
    i = jnp.arange(256)
    args = (jnp.uint32(CFG.seed), i % 3, (i // 3) % 3, i)
    a = make_network_batch(*args, jnp.float32(10.0), geom_rbg)
    b = make_network_batch(*args, jnp.float32(10.0), geom_rbg)
    # Deterministic per (seed, scenario, user, index) on a fixed platform.
    np.testing.assert_array_equal(np.asarray(a["yp"].re), np.asarray(b["yp"].re))
    assert a["yp"].shape == (256, 128) and a["h_label"].shape == (256, 2048)
    # Physics invariants hold under the alternate stream.
    epow = float(jnp.mean(a["h_perf_c"].abs2()))
    assert 0.8 < epow < 1.2
    err = nmse_complex(a["h_ls"], a["h_perf_c"])
    expect = float(label_noise_var(geom_rbg, 10.0))
    assert 0.7 * expect < float(err) < 1.4 * expect
    # And it is a genuinely different stream from threefry.
    t = _batch(256)
    assert not np.allclose(np.asarray(a["yp"].re), np.asarray(t["yp"].re))


def test_split_trig_matches_direct_generator():
    """trig_impl="split" produces the SAME samples as "direct" to f32 phase
    rounding — identical keys, identical draws, only the steering/delay ramp
    evaluation changes (complexops.cexp_i_ramp)."""
    geom_split = ChannelGeometry.from_config(DataConfig(data_len=256, trig_impl="split"))
    i = jnp.arange(64)
    args = (jnp.uint32(CFG.seed), i % 3, (i // 3) % 3, i, jnp.float32(10.0))
    a = make_network_batch(*args, GEOM)
    b = make_network_batch(*args, geom_split)
    # Per-entry phase error <= ~1e-5 rad on unit-power entries -> tight atol.
    np.testing.assert_allclose(
        np.asarray(a["h_perf"]), np.asarray(b["h_perf"]), atol=1e-4
    )
    np.testing.assert_allclose(np.asarray(a["yp"].re), np.asarray(b["yp"].re), atol=1e-4)
    np.testing.assert_allclose(np.asarray(a["yp"].im), np.asarray(b["yp"].im), atol=1e-4)


def test_rng_impl_rejects_unknown():
    from qdml_tpu.data.channels import make_sample_key

    with pytest.raises(ValueError, match="rng_impl"):
        make_sample_key(0, 0, 0, 0, impl="philox")


def test_ls_error_tracks_label_noise_model():
    """The full-pilot LS observation has NMSE = label_noise_var / E|H|^2 ~=
    -SNR + 2.8 dB — the reference's published LS curve (BASELINE.md)."""
    for snr in (5.0, 15.0):
        out = _batch(512, snr=snr)
        ls = float(nmse_complex(out["h_ls"], out["h_perf_c"]))
        want = float(label_noise_var(GEOM, snr))
        assert abs(ls - want) / want < 0.15, f"LS NMSE {ls:.3f} vs model {want:.3f}"
    # and it is NOT a function of yp: at extreme pilot SNR the label keeps
    # its own independent noise
    out = _batch(256, snr=100.0)
    assert float(nmse_complex(out["h_ls"], out["h_perf_c"])) < 1e-8 + float(
        label_noise_var(GEOM, 100.0)
    ) * 2


def test_backprojection_is_sounded_sector_projection():
    """ls_estimate (minimum-norm back-projection of the compressed Yp) keeps
    exactly the sounded-beam content: re-sounding it reproduces Yp."""
    from qdml_tpu.utils.complexops import ceinsum

    out = _batch(32, snr=200.0)
    bp = ls_estimate(out["yp"], GEOM).reshape((32, GEOM.n_ant, GEOM.n_sub))
    resound = ceinsum("ba,nak->nbk", GEOM.beam_matrix, bp).reshape((32, GEOM.pilot_num))
    np.testing.assert_allclose(
        np.asarray(resound.re), np.asarray(out["yp"].re), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(resound.im), np.asarray(out["yp"].im), rtol=1e-4, atol=1e-5
    )


def test_ls_improves_with_snr_and_mmse_beats_ls():
    prof = beam_delay_profile(GEOM, n_samples=180)
    vals = {}
    for snr in (5.0, 15.0):
        out = _batch(512, snr=snr)
        ls = float(nmse_complex(out["h_ls"], out["h_perf_c"]))
        mm = float(
            nmse_complex(
                mmse_estimate(out["h_ls"], label_noise_var(GEOM, snr), prof, GEOM),
                out["h_perf_c"],
            )
        )
        vals[snr] = (ls, mm)
        assert mm < ls  # LMMSE must beat LS
    assert vals[15.0][0] < vals[5.0][0]  # LS improves with SNR


def test_scenarios_are_distinguishable():
    """Beam-energy spread differs across scenarios (the classifier's signal)."""
    spreads = []
    for s in range(3):
        out = make_network_batch(
            jnp.uint32(0),
            jnp.full((256,), s),
            jnp.arange(256) % 3,
            jnp.arange(256),
            jnp.float32(100.0),
            GEOM,
        )
        p = out["yp"].abs2().reshape(256, GEOM.n_beam, GEOM.n_sub).sum(-1)
        p = p / p.sum(-1, keepdims=True)
        idx = jnp.arange(GEOM.n_beam)
        mean = (p * idx).sum(-1)
        var = (p * (idx - mean[:, None]) ** 2).sum(-1)
        spreads.append(float(var.mean()))
    assert spreads[0] < spreads[1] < spreads[2]


def test_generate_datapair_contract():
    out = generate_datapair(90, 128, -1, 10.0, 60000, CFG, GEOM)
    ind = np.asarray(out["indicator"])
    assert set(ind.tolist()) == {0, 1, 2}
    single = generate_datapair(30, 128, 1, 10.0, 60000, CFG, GEOM)
    assert set(np.asarray(single["indicator"]).tolist()) == {1}
    with pytest.raises(ValueError):
        generate_datapair(8, 64, -1, 10.0, 0, CFG, GEOM)


def test_grid_loader():
    ldr = DMLGridLoader(CFG, batch_size=32)
    assert ldr.steps_per_epoch == int(256 * 0.9) // 32
    batches = list(ldr.epoch(0))
    assert len(batches) == ldr.steps_per_epoch
    b = batches[0]
    assert b["yp_img"].shape == (3, 3, 32, 16, 8, 2)
    ind = np.asarray(b["indicator"])
    for s in range(3):
        assert (ind[s] == s).all()
    # deterministic epochs
    b2 = next(iter(ldr.epoch(0)))
    np.testing.assert_array_equal(np.asarray(b["h_label"]), np.asarray(b2["h_label"]))
    # val split uses disjoint indices
    val = DMLGridLoader(CFG, batch_size=16, split="val")
    assert val.index_base == int(256 * 0.9)


def test_snr_jitter_is_deterministic_and_train_only():
    cfg = DataConfig(data_len=128, snr_jitter=(5.0, 15.0))
    ldr = DMLGridLoader(cfg, batch_size=32)
    snrs = [ldr._step_snr(0, s) for s in range(ldr.steps_per_epoch)]
    assert all(5.0 <= s <= 15.0 for s in snrs)
    assert len(set(snrs)) > 1  # actually varies
    assert snrs == [ldr._step_snr(0, s) for s in range(ldr.steps_per_epoch)]
    # validation epochs (shuffle=False) stay at the fixed training SNR
    val = DMLGridLoader(cfg, batch_size=16, split="val")
    a = next(iter(val.epoch(0, shuffle=False)))
    cfg_fixed = DataConfig(data_len=128)
    b = next(iter(DMLGridLoader(cfg_fixed, batch_size=16, split="val").epoch(0, shuffle=False)))
    np.testing.assert_array_equal(np.asarray(a["yp"].re), np.asarray(b["yp"].re))


def test_npy_cache_roundtrip(tmp_path):
    from qdml_tpu.data import load_npy_cache, save_npy_cache

    small = DataConfig(data_len=8)
    save_npy_cache(str(tmp_path), small, chunk=4)
    cell = load_npy_cache(str(tmp_path), small, 1, 2)
    assert cell["Yp"].shape == (8, 128) and cell["Yp"].dtype == np.complex64
    assert cell["Hlabel"].shape == (8, 1024)
    assert cell["Hperf"].shape == (8, 1024)
    # content matches on-the-fly generation
    out = make_network_batch(
        jnp.uint32(small.seed),
        jnp.full((8,), 1),
        jnp.full((8,), 2),
        jnp.arange(8),
        jnp.float32(small.snr_db),
        GEOM,
    )
    np.testing.assert_allclose(cell["Hperf"], out["h_perf_c"].to_numpy(), rtol=1e-5, atol=1e-6)


def test_grid_loader_process_slice():
    """A process-sliced loader yields exactly its slice of the global batch
    window — the multi-host each-host-generates-its-part contract."""
    import numpy as np

    from qdml_tpu.config import DataConfig
    from qdml_tpu.data.datasets import DMLGridLoader

    cfg = DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64)
    full = DMLGridLoader(cfg, 8)
    part = DMLGridLoader(cfg, 8)
    part.set_process_slice(4, 4)
    import jax

    for bf, bp in zip(full.epoch(0), part.epoch(0)):
        lf = jax.tree.leaves({k: v[:, :, 4:8] for k, v in bf.items()})
        lp = jax.tree.leaves(dict(bp))
        for a, b in zip(lf, lp):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        break
