"""Checkpoint/resume: split training must reproduce uninterrupted training.

The reference's checkpoints are write-only (no resume path at all,
SURVEY.md §5.4); here the full TrainState (params + optimizer moments + step)
round-trips through orbax, so 2+2 resumed epochs equal 4 straight epochs
bit-for-bit (data shuffling is deterministic per (seed, epoch)).
"""

import dataclasses

import jax
import numpy as np

from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig
from qdml_tpu.train.dce import train_dce
from qdml_tpu.train.hdce import train_hdce
from qdml_tpu.train.qsc import train_classifier


def _cfg(n_epochs: int, resume: bool = False) -> ExperimentConfig:
    return ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=96),
        model=ModelConfig(features=16),
        train=TrainConfig(batch_size=16, n_epochs=n_epochs, resume=resume),
    )


def _assert_trees_close(a, b, rtol=1e-5, atol=1e-6):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=rtol, atol=atol)


def test_hdce_resume_matches_straight_run(tmp_path):
    straight, _ = train_hdce(_cfg(4), workdir=str(tmp_path / "straight"))

    wd = str(tmp_path / "split")
    train_hdce(_cfg(2), workdir=wd)
    resumed, hist = train_hdce(_cfg(4, resume=True), workdir=wd)
    assert len(hist["train_loss"]) == 2  # epochs 2..3 only
    assert int(resumed.step) == int(straight.step)
    _assert_trees_close(resumed.params, straight.params)
    _assert_trees_close(resumed.batch_stats, straight.batch_stats)


def test_sc_resume_matches_straight_run(tmp_path):
    straight, _ = train_classifier(_cfg(4), quantum=False, workdir=str(tmp_path / "s"))
    wd = str(tmp_path / "r")
    train_classifier(_cfg(2), quantum=False, workdir=wd)
    resumed, hist = train_classifier(_cfg(4, resume=True), quantum=False, workdir=wd)
    assert len(hist["train_loss"]) == 2
    _assert_trees_close(resumed.params, straight.params)


def test_dce_resume_continues(tmp_path):
    wd = str(tmp_path)
    _, h1 = train_dce(_cfg(2), workdir=wd)
    resumed, h2 = train_dce(_cfg(3, resume=True), workdir=wd)
    assert len(h2["train_loss"]) == 1  # only epoch 2 runs
    steps_per_epoch = int(96 * 0.9) // 16
    assert int(resumed.step) == 3 * steps_per_epoch


def test_resume_does_not_clobber_better_best(tmp_path):
    """The running best metric persists in the resume meta; a resumed run with
    worse validation must NOT overwrite the *_best checkpoint."""
    import json

    wd = str(tmp_path)
    train_dce(_cfg(2), workdir=wd)
    with open(wd + "/dce_resume.meta.json") as fh:
        meta = json.load(fh)
    assert "best" in meta

    # Pretend an earlier run achieved an unbeatable best.
    meta["best"] = 1e-9
    with open(wd + "/dce_resume.meta.json", "w") as fh:
        json.dump(meta, fh)
    with open(wd + "/dce_best.meta.json") as fh:
        best_meta_before = json.load(fh)

    train_dce(_cfg(3, resume=True), workdir=wd)
    with open(wd + "/dce_best.meta.json") as fh:
        best_meta_after = json.load(fh)
    assert best_meta_after == best_meta_before  # untouched


def test_resume_without_checkpoint_starts_fresh(tmp_path):
    cfg = _cfg(1, resume=True)
    _, hist = train_dce(cfg, workdir=str(tmp_path / "empty"))
    assert len(hist["train_loss"]) == 1
