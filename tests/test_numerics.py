"""Numerics flight recorder + XLA cost accounting: probe semantics under
jit/scan/shard_map, zero step-path recompiles, the divergence watchdog's
forced-NaN dump-and-raise contract, cost degradation, histogram merging, and
the report cost section / --json gate output."""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qdml_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    QuantumConfig,
    TrainConfig,
    override,
)
from qdml_tpu.telemetry import (
    DivergenceError,
    FlightRecorder,
    Histogram,
    Telemetry,
    Watchdog,
    cost,
    probe_tree,
    run_manifest,
    set_sink,
)
from qdml_tpu.utils.compile_cache import compile_cache_stats


def _read_jsonl(path):
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


def tiny_cfg(**overrides) -> ExperimentConfig:
    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=80),
        model=ModelConfig(features=16),
        train=TrainConfig(batch_size=16, n_epochs=1, print_freq=1000),
    )
    for k, v in overrides.items():
        cfg = override(cfg, k, v)
    return cfg


_TREE = {
    "trunk": {"w": jnp.arange(6.0).reshape(2, 3) / 10, "b": jnp.ones(3)},
    "head": {"w": -jnp.ones((3, 2))},
}


# ---------------------------------------------------------------------------
# probe_tree semantics
# ---------------------------------------------------------------------------


def test_probe_tree_values_and_branches():
    params = jax.tree.map(lambda x: x * 2.0, _TREE)
    updates = jax.tree.map(lambda x: x * -0.01, _TREE)
    p = probe_tree(_TREE, params, updates)
    leaves = np.concatenate([np.ravel(l) for l in jax.tree.leaves(_TREE)])
    assert float(p["grad_norm"]) == pytest.approx(np.linalg.norm(leaves), rel=1e-6)
    # per-branch norms are the top-level children
    assert set(p["branch_grad_norm"]) == {"trunk", "head"}
    assert float(p["branch_grad_norm"]["head"]) == pytest.approx(np.sqrt(6.0), rel=1e-6)
    assert float(p["param_norm"]) == pytest.approx(2 * np.linalg.norm(leaves), rel=1e-6)
    # update ratio: |0.01 g| / |2 g| = 0.005
    assert float(p["update_ratio"]) == pytest.approx(0.005, rel=1e-5)
    assert int(p["nonfinite"]) == 0


def test_probe_tree_counts_nonfinite_fused():
    bad = {"a": jnp.asarray([1.0, np.nan]), "b": jnp.asarray([np.inf])}
    upd = {"a": jnp.asarray([np.nan, np.nan]), "b": jnp.asarray([0.0])}
    p = probe_tree(bad, params=None, updates=upd)
    # 2 in grads + 2 in updates, one fused counter
    assert int(p["nonfinite"]) == 4


def test_probe_tree_matches_under_jit_and_zero_recompiles():
    """jit(probe) == eager probe, and repeated calls with fresh data never
    recompile (the compile-cache request counter is the witness)."""
    jitted = jax.jit(lambda g: probe_tree(g, g, g))
    eager = probe_tree(_TREE, _TREE, _TREE)
    first = jitted(_TREE)
    for k in ("grad_norm", "param_norm", "update_ratio"):
        assert float(first[k]) == pytest.approx(float(eager[k]), rel=1e-6)
    # fresh inputs prepared BEFORE the counter snapshot (eager tree ops are
    # themselves jit-cached programs and would tick the request counter)
    inputs = [jax.tree.map(lambda x: x + i, _TREE) for i in range(3)]
    jax.block_until_ready(inputs)
    base = compile_cache_stats()["requests"]
    for tree in inputs:
        out = jitted(tree)
        jax.block_until_ready(out["grad_norm"])
    assert compile_cache_stats()["requests"] == base  # zero recompiles


def test_probe_matches_under_shard_map():
    """probe_tree inside shard_map over the 8-device CPU mesh (replicated
    inputs) returns the same scalars as eager — the probes are safe to embed
    in SPMD train steps."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    fn = shard_map(
        lambda g: probe_tree(g, g, g),
        mesh=mesh,
        in_specs=P(),
        out_specs=P(),
    )
    out = jax.jit(fn)(_TREE)
    eager = probe_tree(_TREE, _TREE, _TREE)
    assert float(out["grad_norm"]) == pytest.approx(float(eager["grad_norm"]), rel=1e-6)
    assert float(out["update_ratio"]) == pytest.approx(
        float(eager["update_ratio"]), rel=1e-6
    )
    assert int(out["nonfinite"]) == 0


def test_probe_under_scan_matches_per_step_dispatch():
    """The scan-fused DCE path stacks per-step probes (K,) that match the
    per-step dispatch loop's probes value-for-value — and running K steps
    through either path adds ZERO compile-cache requests after the first."""
    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.data.datasets import DMLGridLoader
    from qdml_tpu.train.dce import init_dce_state, make_dce_scan_steps, make_dce_train_step

    cfg = tiny_cfg()
    geom = ChannelGeometry.from_config(cfg.data)
    loader = DMLGridLoader(cfg.data, cfg.train.batch_size, "train", geom)
    model, state_a = init_dce_state(cfg, loader.steps_per_epoch)
    _, state_b = init_dce_state(cfg, loader.steps_per_epoch)

    step = make_dce_train_step(model)
    per_step = []
    steps_done = 0
    base = None
    for batch in loader.epoch(0):
        state_a, m = step(state_a, batch)
        per_step.append(
            (float(m["probe"]["grad_norm"]), float(m["probe"]["update_ratio"]))
        )
        steps_done += 1
        if steps_done == 1:
            base = compile_cache_stats()["requests"]
    assert compile_cache_stats()["requests"] == base  # step path never recompiled

    run = make_dce_scan_steps(model, geom)
    scen, user = loader.grid_coords
    scanned = []
    for idx, snrs in loader.epoch_chunks(0, k=2):
        state_b, ms = run(state_b, jnp.uint32(cfg.data.seed), scen, user, idx, snrs)
        gn = np.asarray(ms["probe"]["grad_norm"])
        ur = np.asarray(ms["probe"]["update_ratio"])
        assert gn.shape == (idx.shape[0],)  # stacked (K,) per-step probes
        scanned.extend(zip(gn.tolist(), ur.tolist()))
    for (a_gn, a_ur), (b_gn, b_ur) in zip(per_step, scanned):
        assert a_gn == pytest.approx(b_gn, rel=1e-4)
        assert a_ur == pytest.approx(b_ur, rel=1e-4)


def test_probes_compile_out_when_disabled():
    """probes=False (what the loops pass for train.probe_every=0) removes the
    probe from the step program entirely — not just from the host fetch."""
    from qdml_tpu.data.datasets import DMLGridLoader
    from qdml_tpu.train.dce import init_dce_state, make_dce_train_step

    cfg = tiny_cfg()
    loader = DMLGridLoader(cfg.data, cfg.train.batch_size)
    batch = next(iter(loader.epoch(0)))
    model, state = init_dce_state(cfg, loader.steps_per_epoch)
    _, m = make_dce_train_step(model, probes=False)(state, batch)
    assert "probe" not in m and "loss" in m


# ---------------------------------------------------------------------------
# Watchdog + FlightRecorder
# ---------------------------------------------------------------------------


def test_watchdog_trip_conditions():
    wd = Watchdog(grad_norm_max=100.0)
    assert wd.check(loss=0.5, probe={"nonfinite": 0, "grad_norm": 1.0}) is None
    assert "loss" in wd.check(loss=float("nan"))
    assert "loss" in wd.check(loss=np.asarray([0.1, np.inf]))  # scan chunk
    assert "nonfinite" in wd.check(loss=0.1, probe={"nonfinite": 3, "grad_norm": 1.0})
    assert "ceiling" in wd.check(loss=0.1, probe={"nonfinite": 0, "grad_norm": 101.0})
    # per-member vectors: ANY bad member trips
    assert "ceiling" in wd.check(probe={"nonfinite": 0, "grad_norm": np.asarray([1.0, 400.0])})
    assert Watchdog(grad_norm_max=0.0).check(probe={"nonfinite": 0, "grad_norm": 1e9}) is None


def test_flight_recorder_emits_numerics_records(tmp_path):
    cfg = tiny_cfg(**{"train.probe_every": 2, "eval.results_dir": str(tmp_path)})
    tele = Telemetry(str(tmp_path / "n.jsonl"))
    rec = FlightRecorder("unit", cfg, sink=tele)
    m = {"loss": jnp.float32(0.25), "probe": probe_tree(_TREE, _TREE, _TREE)}
    for epoch_step in range(4):
        rec.on_step(0, m, loss=0.25)
    tele.close()
    lines = [l for l in _read_jsonl(tmp_path / "n.jsonl") if l.get("kind") == "numerics"]
    # steps 1 (always), 2 and 4 (cadence) log; step 3 does not
    assert [l["step"] for l in lines] == [1, 2, 4]
    assert lines[0]["name"] == "unit" and lines[0]["grad_norm"] > 0
    assert lines[0]["branch_grad_norm"]["trunk"] > 0


def test_last_good_refreshes_without_probes(tmp_path):
    """probe_every=0 + watchdog on: the last-good snapshot must still refresh
    on the fallback cadence — a long run's dump must not 'restore' to the
    step-0 init params."""
    from qdml_tpu.telemetry.numerics import LAST_GOOD_FALLBACK_EVERY

    cfg = tiny_cfg(**{"train.probe_every": 0, "eval.results_dir": str(tmp_path)})
    rec = FlightRecorder("unit", cfg)
    rec.note_good({"w": jnp.zeros(3)})
    for i in range(1, LAST_GOOD_FALLBACK_EVERY + 1):
        rec.on_step(0, {}, loss=0.5, params={"w": jnp.full(3, float(i))})
    with pytest.raises(DivergenceError) as ei:
        rec.on_step(0, {}, loss=float("nan"))
    bundle = json.load(open(os.path.join(ei.value.dump_dir, "bundle.json")))
    assert bundle["last_good"]["step"] == LAST_GOOD_FALLBACK_EVERY
    from qdml_tpu.train.checkpoint import restore_checkpoint

    restored, _ = restore_checkpoint(ei.value.dump_dir, "last_good")
    np.testing.assert_array_equal(
        restored["params"]["w"], np.full(3, float(LAST_GOOD_FALLBACK_EVERY))
    )


def test_forced_nan_qsc_run_trips_watchdog_with_restorable_dump(tmp_path):
    """The acceptance scenario: a QSC run whose QuantumNAT noise std is
    spiked past overflow (sigma * N(0,1) -> inf -> sin(inf) = NaN in the
    circuit; merely-huge finite sigmas can survive f32 range reduction) must
    raise a typed DivergenceError naming a flight-recorder dump whose bundle
    restores to the last-good params."""
    from qdml_tpu.train.checkpoint import restore_checkpoint
    from qdml_tpu.train.qsc import train_classifier

    cfg = tiny_cfg(
        **{
            "train.probe_every": 1,
            "train.n_epochs": 2,
            "eval.results_dir": str(tmp_path / "results"),
        }
    )
    cfg = dataclasses.replace(
        cfg,
        quantum=QuantumConfig(
            n_qubits=4, use_quantumnat=True, noise_level=float("inf")
        ),
    )
    with pytest.raises(DivergenceError) as ei:
        train_classifier(cfg, quantum=True, workdir=str(tmp_path / "wd"))
    err = ei.value
    assert err.dump_dir is not None and err.dump_dir in str(err)
    assert "flightrec" in err.dump_dir
    bundle = json.load(open(os.path.join(err.dump_dir, "bundle.json")))
    assert bundle["reason"] == err.reason and bundle["name"] == "qsc_train"
    assert bundle["probe_history"]  # the tail that led up to the trip
    assert bundle["rng_key"] is not None  # the offending noise draw is replayable
    assert bundle["batch_info"] is not None
    # the bundle's last_good checkpoint restores to finite params
    assert bundle["last_good"] is not None
    restored, meta = restore_checkpoint(err.dump_dir, bundle["last_good"]["checkpoint"])
    assert meta["loop"] == "qsc_train"
    for leaf in jax.tree.leaves(restored["params"]):
        assert np.isfinite(np.asarray(leaf)).all()


def test_epoch_aggregate_watchdog_trips_with_probes_compiled_out(tmp_path):
    """probe_every=0 pins zero steady-state transfers in the fused loops —
    but divergence must STILL raise: the watchdog checks the epoch-aggregate
    loss (one existing epoch-end fetch, NaN propagates through the on-device
    sum). The trip lands at epoch granularity with the epoch-aggregate
    reason."""
    from qdml_tpu.train.qsc import train_classifier

    cfg = tiny_cfg(
        **{
            "train.probe_every": 0,
            "train.n_epochs": 2,
            "eval.results_dir": str(tmp_path / "results"),
        }
    )
    cfg = dataclasses.replace(
        cfg,
        quantum=QuantumConfig(
            n_qubits=4, use_quantumnat=True, noise_level=float("inf")
        ),
    )
    with pytest.raises(DivergenceError) as ei:
        train_classifier(cfg, quantum=True, workdir=str(tmp_path / "wd"))
    assert ei.value.reason.startswith("epoch-aggregate")
    assert ei.value.dump_dir is not None
    bundle = json.load(open(os.path.join(ei.value.dump_dir, "bundle.json")))
    assert bundle["reason"].startswith("epoch-aggregate")


def test_watchdog_disabled_lets_nan_run_continue(tmp_path):
    """train.watchdog=false restores the old silently-NaN behavior (the knob
    must actually disconnect the trip, not just the dump)."""
    from qdml_tpu.train.qsc import train_classifier

    cfg = tiny_cfg(
        **{
            "train.watchdog": False,
            "train.probe_every": 0,
            "eval.results_dir": str(tmp_path / "results"),
        }
    )
    cfg = dataclasses.replace(
        cfg,
        quantum=QuantumConfig(
            n_qubits=4, use_quantumnat=True, noise_level=float("inf")
        ),
    )
    _, hist = train_classifier(cfg, quantum=True)
    assert not np.isfinite(hist["train_loss"]).all()  # it really did NaN


def test_hdce_loop_emits_numerics_and_cost_records(tmp_path):
    """Full-loop integration: a sink-attached HDCE run writes manifest-headed
    numerics AND cost records (the acceptance shape for train loops)."""
    from qdml_tpu.train.hdce import train_hdce

    cfg = tiny_cfg(**{"eval.results_dir": str(tmp_path / "results")})
    tele = Telemetry(str(tmp_path / "train.jsonl"), manifest=run_manifest(cfg))
    set_sink(tele)
    try:
        train_hdce(cfg)
    finally:
        set_sink(None)
        tele.close()
    lines = _read_jsonl(tmp_path / "train.jsonl")
    assert lines[0]["kind"] == "manifest"
    numerics = [l for l in lines if l.get("kind") == "numerics"]
    assert numerics and numerics[0]["name"] == "hdce_train"
    # the default loop is the K=1 scan-fused dispatch: probe leaves carry a
    # leading (K,) axis, so the record's scalars arrive as length-K lists
    assert np.all(np.asarray(numerics[0]["grad_norm"]) > 0)
    assert np.all(np.asarray(numerics[0]["nonfinite"]) == 0)
    costs = [l for l in lines if l.get("kind") == "cost"]
    assert costs and costs[0]["name"] == "hdce_train_scan"
    assert costs[0]["scan_steps"] == 1
    assert costs[0]["available"] is True
    assert costs[0]["flops"] > 0 and costs[0]["bytes_accessed"] > 0
    assert costs[0]["roofline"] in ("compute-bound", "memory-bound")


# ---------------------------------------------------------------------------
# cost.analyze: real lowered/compiled programs + structural degradation
# ---------------------------------------------------------------------------


def test_cost_analyze_lowered_and_compiled():
    def f(x):
        return (x @ x).sum()

    lowered = jax.jit(f).lower(jnp.ones((32, 32)))
    rec = cost.analyze(lowered)
    assert rec["available"] and rec["source"] == "lowered"
    assert rec["flops"] > 0 and rec["bytes_accessed"] > 0
    assert rec["peak_temp_bytes"] is None  # lowered-only: no memory stats
    assert rec["roofline"] in ("compute-bound", "memory-bound")

    compiled = lowered.compile()
    rec2 = cost.analyze(compiled)
    assert rec2["available"] and rec2["source"] == "compiled"
    assert rec2["peak_temp_bytes"] is not None
    assert rec2["argument_bytes"] > 0


def test_cost_analyze_degrades_when_backend_unavailable():
    """The satellite bugfix: cost_analysis() raising (or returning nothing)
    must yield {"available": false, "reason": ...}, never an exception."""

    class Broken:
        def cost_analysis(self):
            raise NotImplementedError("no cost analysis on this backend")

    rec = cost.analyze(Broken())
    assert rec["available"] is False and "NotImplementedError" in rec["reason"]

    class Empty:
        def cost_analysis(self):
            return None

        def memory_analysis(self):
            return None

    rec = cost.analyze(Empty())
    assert rec["available"] is False and "reason" in rec

    class MemOnly:
        def cost_analysis(self):
            return []

        def memory_analysis(self):
            class M:
                temp_size_in_bytes = 123
                argument_size_in_bytes = 7

            return M()

    rec = cost.analyze(MemOnly())
    assert rec["available"] and rec["peak_temp_bytes"] == 123
    assert rec["roofline"] == "unknown"  # no flops/bytes to classify


def test_cost_analyze_jit_never_raises_on_bad_args():
    rec = cost.analyze_jit(jax.jit(lambda x: x), object())
    assert rec["available"] is False and "lowering failed" in rec["reason"]


def test_achieved_roofline_fraction_math_and_degradation():
    """achieved_roofline: ceiling = min(peak, bw * intensity); fraction =
    flops * rate / ceiling; degrades to None (never raises) on unavailable
    or flops-free cost blocks — the bench record ships without it."""
    peak, bw = cost._PLATFORM_PEAKS["cpu"]
    # memory-bound program: intensity below the ridge
    c = {"available": True, "platform": "cpu", "flops": 1e9, "bytes_accessed": 1e9}
    rec = cost.achieved_roofline(c, programs_per_sec=2.0)
    assert rec["bound"] == "memory" and rec["arithmetic_intensity"] == 1.0
    assert rec["ceiling_tflops_per_s"] == pytest.approx(bw * 1.0 / 1e12)
    # the record rounds to 6 decimals — compare at that precision
    assert rec["fraction"] == pytest.approx(2e9 / (bw * 1.0), rel=1e-4)
    # compute-bound program: intensity far past the ridge
    c2 = {"available": True, "platform": "cpu", "flops": 1e12, "bytes_accessed": 1e7}
    rec2 = cost.achieved_roofline(c2, programs_per_sec=0.01)
    assert rec2["bound"] == "compute"
    assert rec2["ceiling_tflops_per_s"] == pytest.approx(peak / 1e12)
    # degradation: unavailable / missing numbers / zero rate -> None
    assert cost.achieved_roofline({"available": False}, 1.0) is None
    assert cost.achieved_roofline({"available": True, "flops": 1e9}, 1.0) is None
    assert cost.achieved_roofline(c, 0.0) is None
    assert cost.achieved_roofline(None, 1.0) is None


def test_maybe_emit_cost_inert_without_sink(tmp_path):
    assert cost.maybe_emit_cost("x", jax.jit(lambda x: x), jnp.ones(2)) is None
    tele = Telemetry(str(tmp_path / "c.jsonl"))
    rec = cost.maybe_emit_cost("x", jax.jit(lambda x: x * 2), jnp.ones(2), sink=tele)
    tele.close()
    assert rec is not None
    lines = _read_jsonl(tmp_path / "c.jsonl")
    assert lines[0]["kind"] == "cost" and lines[0]["name"] == "x"


def test_roofline_classification_table():
    assert cost.ridge_intensity("tpu-v5e") == pytest.approx(197e12 / 8.19e11)
    # far above any ridge -> compute-bound; far below -> memory-bound
    hi = {"flops": 1e15, "bytes accessed": 1e9}
    lo = {"flops": 1e9, "bytes accessed": 1e9}

    class Stub:
        def __init__(self, ca):
            self._ca = ca

        def cost_analysis(self):
            return self._ca

    assert cost.analyze(Stub(hi), platform="tpu-v5e")["roofline"] == "compute-bound"
    assert cost.analyze(Stub(lo), platform="tpu-v5e")["roofline"] == "memory-bound"


# ---------------------------------------------------------------------------
# Histogram.merge (satellite): merged quantiles == concatenated quantiles
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_histogram_merge_property(seed):
    """Property test: for random sample sets split into random parts, the
    merged histogram's summary equals the summary of one histogram fed the
    concatenation (exact — the collector keeps raw samples)."""
    rng = np.random.default_rng(seed)
    n_parts = int(rng.integers(1, 5))
    parts = [rng.exponential(0.01, rng.integers(1, 200)) for _ in range(n_parts)]
    merged = Histogram()
    for part in parts:
        h = Histogram()
        for v in part:
            h.add(float(v))
        merged.merge(h)
    ref = Histogram()
    for v in np.concatenate(parts):
        ref.add(float(v))
    assert merged.summary() == ref.summary()


# ---------------------------------------------------------------------------
# report: cost section, program-change flag, --json gate output
# ---------------------------------------------------------------------------


def _bench_record(value, flops, bytes_=4e6, platform="cpu_fallback"):
    return {
        "metric": "hdce_train_samples_per_sec_per_chip",
        "value": value,
        "platform": platform,
        "details": {
            "hdce_f32": {
                "samples_per_sec": value,
                "cost": {
                    "available": True,
                    "flops": flops,
                    "bytes_accessed": bytes_,
                    "roofline": "memory-bound",
                },
            }
        },
    }


def _write(tmp_path, name, *objs):
    p = tmp_path / name
    with open(p, "w") as fh:
        for o in objs:
            fh.write(json.dumps(o) + "\n")
    return str(p)


def test_report_flags_regression_with_program_change(tmp_path):
    from qdml_tpu.telemetry.report import build_report_data

    base = _write(tmp_path, "b.jsonl", _bench_record(1000.0, flops=1e9))
    cur = _write(tmp_path, "c.jsonl", _bench_record(700.0, flops=2e9))
    data = build_report_data([cur], base, 10.0)
    assert data["gate_armed"]
    reg = [r for r in data["regressions"] if r["metric"] == "hdce_f32.samples_per_sec"]
    assert reg and reg[0]["program_change"]["flops"]["delta_pct"] == pytest.approx(100.0)
    assert "program changed" in data["markdown"]
    assert "## cost" in data["markdown"]
    row = [r for r in data["cost"] if r["program"] == "hdce_f32"][0]
    assert row["program_changed"] is True
    # same regression with UNCHANGED cost carries no program-change flag
    cur2 = _write(tmp_path, "c2.jsonl", _bench_record(700.0, flops=1e9))
    data2 = build_report_data([cur2], base, 10.0)
    reg2 = [r for r in data2["regressions"] if r["metric"] == "hdce_f32.samples_per_sec"]
    assert reg2 and "program_change" not in reg2[0]
    assert [r for r in data2["cost"] if r["program"] == "hdce_f32"][0][
        "program_changed"
    ] is False


def test_report_reads_stream_cost_records(tmp_path):
    """kind="cost" records from train/serve streams join the cost section
    keyed by name (and bucket)."""
    from qdml_tpu.telemetry.report import build_report_data

    def stream(v, flops):
        return [
            {"kind": "manifest"},
            {"kind": "cost", "name": "hdce_train_step", "available": True,
             "flops": flops, "bytes_accessed": 1e6, "roofline": "memory-bound"},
            {"kind": "cost", "name": "serve_bucket", "bucket": 8, "available": True,
             "flops": 5e8, "bytes_accessed": 2e6, "roofline": "memory-bound"},
            {"metric": "m", "value": v, "platform": "cpu"},
        ]

    base = _write(tmp_path, "b.jsonl", *stream(100.0, 1e9))
    cur = _write(tmp_path, "c.jsonl", *stream(95.0, 1e9))
    data = build_report_data([cur], base, 10.0)
    assert {r["program"] for r in data["cost"]} == {"hdce_train_step", "serve_bucket[8]"}


def test_lint_markers_parses_durations_and_detects_markers(tmp_path):
    """scripts/lint_markers.py: duration parsing, slow-marker source
    detection (the real `slow`-marked soak test in test_serve.py), and
    allowlist behavior."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lint_markers",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "lint_markers.py"),
    )
    lm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lm)

    durations = lm.parse_durations(
        "  12.34s call     tests/test_a.py::test_x\n"
        "   0.50s setup    tests/test_a.py::test_x\n"  # setup phase ignored
        "   7.00s call     tests/test_a.py::test_y[p0]\n"
        "   9.00s call     tests/test_a.py::test_y[p1]\n"
    )
    assert durations == {
        "tests/test_a.py::test_x": 12.34,
        "tests/test_a.py::test_y": 9.0,  # max over parametrizations
    }
    serve_py = os.path.join(os.path.dirname(__file__), "test_serve.py")
    assert lm.has_slow_marker(serve_py, "test_loadgen_soak_open_loop_with_deadlines")
    assert not lm.has_slow_marker(serve_py, "test_empty_queue_flush_is_noop")

    dur = tmp_path / "d.log"
    dur.write_text("  30.00s call     tests/test_serve.py::test_empty_queue_flush_is_noop\n")
    assert lm.main([f"--durations={dur}", "--allow=/nonexistent"]) == 1  # offender
    allow = tmp_path / "allow.txt"
    allow.write_text("tests/test_serve.py::test_empty_queue_flush_is_noop  # reason\n")
    assert lm.main([f"--durations={dur}", f"--allow={allow}"]) == 0
    # the committed allowlist keeps the real tier-1 suite lint-clean
    assert os.path.exists(
        os.path.join(os.path.dirname(__file__), "..", "scripts", "tier1_slow_allowlist.txt")
    )


def test_report_json_gate_output(tmp_path, capsys):
    from qdml_tpu.telemetry.report import EXIT_REGRESSION, report_main

    base = _write(tmp_path, "b.jsonl", _bench_record(1000.0, flops=1e9))
    cur = _write(tmp_path, "c.jsonl", _bench_record(700.0, flops=2e9))
    json_path = tmp_path / "gate.json"
    rc = report_main(
        [f"--current={cur}", f"--baseline={base}", f"--json={json_path}"]
    )
    capsys.readouterr()
    assert rc == EXIT_REGRESSION
    gate = json.load(open(json_path))
    assert gate["exit_code"] == EXIT_REGRESSION
    assert gate["gate_armed"] is True and gate["disarm_reason"] is None
    assert "markdown" not in gate  # machine-readable only
    by_metric = {g["metric"]: g for g in gate["gates"]}
    assert by_metric["hdce_f32.samples_per_sec"]["status"] == "regression+program-change"
    assert by_metric["hdce_f32.samples_per_sec"]["delta_pct"] == pytest.approx(-30.0)
    assert gate["cost"][0]["program_changed"] is True

    # disarm reason surfaces in the json too
    base2 = _write(tmp_path, "b2.jsonl", _bench_record(1000.0, flops=1e9, platform="tpu-v5e"))
    json2 = tmp_path / "gate2.json"
    rc2 = report_main([f"--current={cur}", f"--baseline={base2}", f"--json={json2}"])
    capsys.readouterr()
    gate2 = json.load(open(json2))
    assert rc2 == 0 and gate2["gate_armed"] is False
    assert "platform mismatch" in gate2["disarm_reason"]
