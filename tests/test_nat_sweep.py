"""Vmapped QuantumNAT noise-level ensemble (BASELINE.json config 5)."""

import jax
import jax.numpy as jnp
import numpy as np

from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, QuantumConfig, TrainConfig
from qdml_tpu.train.nat_sweep import (
    init_sweep,
    make_sweep_train_step,
    train_nat_sweep,
)


def _cfg(n_epochs=1):
    return ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=16),
        quantum=QuantumConfig(n_qubits=4, n_layers=2),
        train=TrainConfig(batch_size=16, n_epochs=n_epochs),
    )


def test_zero_noise_member_matches_plain_qsc_step():
    """Ensemble member with sigma=0 must evolve exactly like an unperturbed
    single-model step (same seed, same data)."""
    cfg = _cfg()
    from qdml_tpu.data.datasets import DMLGridLoader

    loader = DMLGridLoader(cfg.data, cfg.train.batch_size)
    batch = next(iter(loader.epoch(0)))

    model, tx, params, opt_state, sigmas = init_sweep(cfg, [0.0, 0.1], loader.steps_per_epoch)
    step = make_sweep_train_step(model, tx)
    rngs = jax.random.split(jax.random.PRNGKey(7), 2)
    new_params, _, ms = step(params, opt_state, rngs, sigmas, batch)
    losses = ms["loss"]

    # independent plain step on member 0's params
    import optax

    from qdml_tpu.models.losses import nll_loss

    p0 = jax.tree.map(lambda x: x[0], params)
    x = batch["yp_img"].reshape(-1, *batch["yp_img"].shape[3:])
    labels = batch["indicator"].reshape(-1)

    def loss_fn(p):
        return nll_loss(model.apply({"params": p}, x, train=False), labels)

    loss0, grads = jax.value_and_grad(loss_fn)(p0)
    updates, _ = tx.update(grads, tx.init(p0), p0)
    want = optax.apply_updates(p0, updates)
    np.testing.assert_allclose(float(losses[0]), float(loss0), rtol=1e-5)
    # Adam's first-step update is lr * g/(sqrt(g^2)+eps): for near-zero
    # gradient elements this is numerically ill-conditioned, so vmapped vs
    # plain execution can differ by up to the update scale (lr=1e-3) on
    # isolated elements — compare at that granularity.
    for la, lb in zip(
        jax.tree.leaves(jax.tree.map(lambda x: x[0], new_params)), jax.tree.leaves(want)
    ):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-3, atol=2e-3)


def test_noise_perturbs_only_qweights():
    """Nonzero sigma changes the loss only through the circuit weights; the
    two members start from different seeds so just check both train finitely
    and the sigma=0.5 member sees a different loss than sigma=0 with SAME
    params."""
    cfg = _cfg()
    from qdml_tpu.data.datasets import DMLGridLoader

    loader = DMLGridLoader(cfg.data, cfg.train.batch_size)
    batch = next(iter(loader.epoch(0)))
    model, tx, params, opt_state, _ = init_sweep(cfg, [0.0, 0.5], loader.steps_per_epoch)
    # share member 0's params across both members
    shared = jax.tree.map(lambda x: jnp.stack([x[0], x[0]]), params)
    shared_opt = jax.tree.map(
        lambda x: jnp.stack([x[0], x[0]]) if hasattr(x, "ndim") and x.ndim > 0 else x,
        opt_state,
    )
    step = make_sweep_train_step(model, tx)
    rng = jax.random.split(jax.random.PRNGKey(3), 2)
    rng = jnp.stack([rng[0], rng[0]])  # same noise draw for both
    _, _, ms = step(shared, shared_opt, rng, jnp.asarray([0.0, 0.5]), batch)
    losses = ms["loss"]
    assert abs(float(losses[0]) - float(losses[1])) > 1e-6


def test_train_nat_sweep_end_to_end(tmp_path):
    cfg = _cfg(n_epochs=2)
    params, history = train_nat_sweep(
        cfg, noise_levels=(0.0, 0.05), workdir=str(tmp_path)
    )
    assert len(history["train_loss"]) == 2
    assert history["train_loss"][0].shape == (2,)
    assert np.isfinite(history["train_loss"][-1]).all()
    assert np.isfinite(history["val_acc"][-1]).all()
    # stacked params carry the ensemble axis
    leaf = jax.tree.leaves(params)[0]
    assert leaf.shape[0] == 2
    assert (tmp_path / "nat_sweep_last").is_dir()
    # best-member checkpoint is a SINGLE model's params (no ensemble axis)
    # loadable into one QSCP128, with the winning sigma in its metadata
    import json

    from qdml_tpu.train.checkpoint import restore_checkpoint

    best, meta = restore_checkpoint(str(tmp_path), "nat_sweep_best")
    assert jax.tree.leaves(best["params"])[0].shape == jax.tree.leaves(params)[0].shape[1:]
    assert meta["sigma"] in (0.0, 0.05)
    assert 0.0 <= meta["val_acc"] <= 1.0
    with open(tmp_path / "nat_sweep_best.meta.json") as fh:
        assert json.load(fh)["member"] in (0, 1)


def test_train_nat_sweep_resume(tmp_path):
    """A 1-epoch run + resumed 2nd epoch ends at exactly the same params as an
    uninterrupted 2-epoch run (same seeds, same data; fresh noise per epoch)."""
    import dataclasses

    full_params, full_hist = train_nat_sweep(
        _cfg(n_epochs=2), noise_levels=(0.0, 0.05), workdir=str(tmp_path / "full")
    )

    part_dir = str(tmp_path / "part")
    train_nat_sweep(_cfg(n_epochs=1), noise_levels=(0.0, 0.05), workdir=part_dir)
    cfg2 = _cfg(n_epochs=2)
    cfg2 = dataclasses.replace(cfg2, train=dataclasses.replace(cfg2.train, resume=True))
    res_params, res_hist = train_nat_sweep(
        cfg2, noise_levels=(0.0, 0.05), workdir=part_dir
    )
    assert len(res_hist["train_loss"]) == 1  # only the resumed epoch ran
    np.testing.assert_allclose(
        np.asarray(res_hist["train_loss"][0]),
        np.asarray(full_hist["train_loss"][1]),
        rtol=1e-6,
    )
    for la, lb in zip(jax.tree.leaves(res_params), jax.tree.leaves(full_params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-5, atol=1e-6)

    # ADVICE r4 — legacy-workdir window: resume once more with the member-best
    # tracker deleted (a workdir trained before tracking existed). The tracker
    # restarts and its meta must record that the selection window starts at
    # the resume epoch, not 0 — post-resume maxima are not all-run bests.
    import shutil

    from qdml_tpu.train.checkpoint import restore_checkpoint

    shutil.rmtree(tmp_path / "part" / "nat_sweep_member_best")
    mb_meta = tmp_path / "part" / "nat_sweep_member_best.meta.json"
    if mb_meta.exists():
        mb_meta.unlink()
    cfg3 = _cfg(n_epochs=3)
    cfg3 = dataclasses.replace(cfg3, train=dataclasses.replace(cfg3.train, resume=True))
    train_nat_sweep(cfg3, noise_levels=(0.0, 0.05), workdir=part_dir)
    _, meta = restore_checkpoint(part_dir, "nat_sweep_member_best")
    assert meta["member_best_from_epoch"] == 2  # epochs 0-1 were never scored
    assert list(meta["member_best_epoch"]) == [2, 2]


def test_nat_sweep_scan_steps_match_history():
    """train_nat_sweep with scan_steps>1 reproduces the per-step history
    (losses per member per epoch), including the per-(step, member) noise
    keys."""
    import dataclasses

    import numpy as np

    cfg = _cfg(n_epochs=2)
    h1 = train_nat_sweep(cfg, noise_levels=(0.0, 0.05))[1]
    cfg2 = dataclasses.replace(cfg, train=dataclasses.replace(cfg.train, scan_steps=3))
    h2 = train_nat_sweep(cfg2, noise_levels=(0.0, 0.05))[1]
    np.testing.assert_allclose(
        np.asarray(h1["train_loss"]), np.asarray(h2["train_loss"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(h1["val_acc"]), np.asarray(h2["val_acc"]), rtol=1e-5
    )


def test_member_best_checkpoint_tracks_per_member_max(tmp_path):
    """ADVICE r3: the ensemble trainer keeps EVERY member's best-validation
    params (nat_sweep_member_best), so ensemble studies can use the same
    best-val selection rule as the single-model seed studies. The recorded
    per-member best accs must equal the elementwise max of the per-epoch
    val-acc history."""
    from qdml_tpu.train.checkpoint import restore_checkpoint
    from qdml_tpu.train.nat_sweep import train_nat_sweep

    cfg = _cfg(n_epochs=3)
    params, hist = train_nat_sweep(
        cfg, noise_levels=(0.0, 0.3), workdir=str(tmp_path / "wd")
    )
    restored, meta = restore_checkpoint(str(tmp_path / "wd"), "nat_sweep_member_best")
    va = np.stack(hist["val_acc"])  # (epochs, members)
    np.testing.assert_allclose(meta["member_best_acc"], va.max(0), rtol=1e-6)
    for m, ep in enumerate(meta["member_best_epoch"]):
        assert va[ep, m] == va[:, m].max()
    # stacked structure matches the training params
    assert jax.tree_util.tree_structure(restored["params"]) == jax.tree_util.tree_structure(params)
    # an uninterrupted run's selection window covers every epoch
    assert meta["member_best_from_epoch"] == 0


