"""Scalable-simulation subsystem (breaking the 12-qubit wall): equivalence
pins for the bond-chi MPS and mesh-sharded statevector impls vs dense at
n <= 12 (values AND grads, f32/bf16, jit/vmap, QuantumNAT stream
impl-invariant), the 8-virtual-device sharded pins, chi-truncation
monotonicity, the n/topology eligibility windows with their typed
ineligibility errors, checkpoint-meta reconcile of the new impl names, the
qubit-scaling helpers, and the report's qsc_scaling section round-trip.

The conftest harness forces 8 virtual CPU devices, so the sharded impl's
shard_map program (k=3 global qubits, ppermute partner exchanges, one psum)
runs exactly as it would on an 8-chip mesh slice.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qdml_tpu.quantum import autotune
from qdml_tpu.quantum.circuits import canonical_impl, run_circuit


def _rand_inputs(n, layers, batch, seed=0):
    rng = np.random.default_rng(seed)
    angles = jnp.asarray(rng.uniform(-2, 2, (batch, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(0, 2 * np.pi, (layers, n, 2)).astype(np.float32))
    return angles, w


def _full_chi(n):
    # the chain's Schmidt rank can never exceed 2^(n//2): exact simulation
    return 1 << (n // 2)


# ---------------------------------------------------------------------------
# MPS equivalence vs dense (n <= 12 window)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n,layers", [(4, 2), (6, 3), (8, 2)])
def test_mps_values_match_dense_at_full_chi(n, layers):
    angles, w = _rand_inputs(n, layers, batch=4, seed=1)
    dense = run_circuit(angles, w, n, layers, "dense")
    mps = run_circuit(angles, w, n, layers, "mps", mps_chi=_full_chi(n))
    np.testing.assert_allclose(np.asarray(mps), np.asarray(dense), atol=1e-5)


def test_mps_grads_match_dense():
    """AD through the truncated-SVD splits (the custom projector-gauge
    backward) must reproduce the dense path's weight gradients at full chi."""
    n, layers = 6, 2
    angles, w = _rand_inputs(n, layers, batch=3, seed=2)

    def loss(weights, backend, chi=None):
        out = run_circuit(angles, weights, n, layers, backend, mps_chi=chi)
        return jnp.sum(out**2)

    g_dense = jax.grad(loss)(w, "dense")
    g_mps = jax.grad(loss)(w, "mps", _full_chi(n))
    np.testing.assert_allclose(np.asarray(g_mps), np.asarray(g_dense), atol=2e-4)


def test_mps_bf16_inputs_track_dense():
    """bf16 angle/weight inputs: the mps path computes complex64 internally
    and returns f32; it must sit within bf16 resolution of the f32 dense
    reference."""
    n, layers = 6, 2
    angles, w = _rand_inputs(n, layers, batch=4, seed=3)
    dense = run_circuit(angles, w, n, layers, "dense")
    mps = run_circuit(
        angles.astype(jnp.bfloat16),
        w.astype(jnp.bfloat16),
        n,
        layers,
        "mps",
        mps_chi=_full_chi(n),
    )
    assert mps.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(mps), np.asarray(dense), atol=3e-2)


def test_mps_jit_vmap_and_lead_shapes():
    n, layers = 5, 2
    angles, w = _rand_inputs(n, layers, batch=6, seed=4)
    lead = angles.reshape(2, 3, n)
    fn = jax.jit(
        lambda a, w: run_circuit(a, w, n, layers, "mps", mps_chi=_full_chi(n))
    )
    out = fn(lead, w)
    assert out.shape == (2, 3, n)
    flat = run_circuit(angles, w, n, layers, "dense")
    np.testing.assert_allclose(
        np.asarray(out).reshape(6, n), np.asarray(flat), atol=1e-5
    )
    # single-sample (no lead) shape round-trips too
    one = run_circuit(angles[0], w, n, layers, "mps", mps_chi=_full_chi(n))
    assert one.shape == (n,)
    np.testing.assert_allclose(np.asarray(one), np.asarray(flat[0]), atol=1e-5)


def test_mps_chi_truncation_error_non_increasing():
    """chi is a controlled approximation knob: error vs dense must be
    non-increasing in chi, and exact (<= 1e-5) at chi >= 2^(n/2)."""
    n, layers = 8, 3
    angles, w = _rand_inputs(n, layers, batch=3, seed=5)
    dense = np.asarray(run_circuit(angles, w, n, layers, "dense"))
    errs = []
    for chi in (2, 4, 8, 16):
        out = np.asarray(run_circuit(angles, w, n, layers, "mps", mps_chi=chi))
        errs.append(float(np.max(np.abs(out - dense))))
    for lo, hi in zip(errs[1:], errs[:-1]):
        assert lo <= hi + 1e-7, errs
    assert errs[-1] <= 1e-5, errs  # chi = 16 = 2^(8/2): nothing to truncate
    assert errs[0] > errs[-1], errs  # chi=2 genuinely truncates this circuit


def test_mps_rejects_degenerate_chi():
    angles, w = _rand_inputs(4, 1, batch=2)
    with pytest.raises(ValueError, match="mps_chi"):
        run_circuit(angles, w, 4, 1, "mps", mps_chi=1)


# ---------------------------------------------------------------------------
# Sharded statevector pins (8-virtual-device harness)
# ---------------------------------------------------------------------------


def test_sharded_values_and_grads_match_dense():
    """k = log2(8) = 3 global qubits on the forced-CPU harness: the ppermute
    exchange program must reproduce dense <Z> exactly (f32), and AD must flow
    through the collectives to the same weight grads. One jitted
    value_and_grad program pins both — compiling AD through an 8-way
    shard_map on CPU costs tens of seconds, so the value-only and grad-only
    variants would double the bill for no extra coverage."""
    n, layers = 5, 2
    angles, w = _rand_inputs(n, layers, batch=3, seed=6)

    def loss_and_out(weights, backend):
        out = run_circuit(angles, weights, n, layers, backend)
        return jnp.sum(out**2), out

    (l_d, out_d), g_d = jax.value_and_grad(
        lambda w: loss_and_out(w, "dense"), has_aux=True
    )(w)
    (l_s, out_s), g_s = jax.jit(
        jax.value_and_grad(
            lambda w: loss_and_out(w, "sharded_statevector"), has_aux=True
        )
    )(w)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_d), atol=1e-5)
    np.testing.assert_allclose(np.asarray(g_s), np.asarray(g_d), atol=2e-5)
    np.testing.assert_allclose(float(l_s), float(l_d), rtol=1e-6)


@pytest.mark.slow
def test_sharded_bf16_inputs_track_dense():
    n, layers = 5, 2
    angles, w = _rand_inputs(n, layers, batch=4, seed=8)
    dense = run_circuit(angles, w, n, layers, "dense")
    shard = run_circuit(
        angles.astype(jnp.bfloat16),
        w.astype(jnp.bfloat16),
        n,
        layers,
        "sharded_statevector",
    )
    np.testing.assert_allclose(np.asarray(shard), np.asarray(dense), atol=3e-2)


def _quantumnat_logprobs(impl, x, key):
    from qdml_tpu.models.qsc import QSCP128

    m = QSCP128(
        n_qubits=4,
        n_layers=2,
        use_quantumnat=True,
        noise_level=0.3,
        impl=impl,
        mps_chi=_full_chi(4),
    )
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    return np.asarray(m.apply(variables, x, train=True, rngs={"quantumnat": key}))


def test_quantumnat_noise_stream_invariant_mps():
    """Switching to a scaling impl may not perturb which noisy point the
    QuantumNAT stream evaluates: same key => same log-probs as dense."""
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.standard_normal((3, 16, 8, 2)).astype(np.float32))
    key = jax.random.PRNGKey(11)
    np.testing.assert_allclose(
        _quantumnat_logprobs("dense", x, key),
        _quantumnat_logprobs("mps", x, key),
        rtol=1e-4,
        atol=1e-5,
    )


@pytest.mark.slow
def test_quantumnat_noise_stream_invariant_sharded():
    """The sharded leg of the invariance pin (compiling the model apply over
    the 8-way shard_map dominates tier-1 budget, so it rides the slow lane)."""
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.standard_normal((3, 16, 8, 2)).astype(np.float32))
    key = jax.random.PRNGKey(11)
    np.testing.assert_allclose(
        _quantumnat_logprobs("dense", x, key),
        _quantumnat_logprobs("sharded_statevector", x, key),
        rtol=1e-4,
        atol=1e-5,
    )


# ---------------------------------------------------------------------------
# Eligibility windows / typed ineligibility / canonical names
# ---------------------------------------------------------------------------


def test_canonical_impl_aliases_and_unknown():
    assert canonical_impl("sharded") == "sharded_statevector"
    assert canonical_impl("pallas_tensor") == "pallas_circuit"
    assert canonical_impl("mps") == "mps"
    with pytest.raises(ValueError, match="unknown circuit impl"):
        canonical_impl("qiskit")


def test_eligible_impls_scaling_windows():
    # crossover window: tensor races mps at 13..14
    assert autotune.eligible_impls(13, "cpu") == ["tensor", "mps"]
    assert autotune.eligible_impls(14, "cpu") == ["tensor", "mps"]
    # past every full-statevector window, mps is the only 1-device candidate
    assert autotune.eligible_impls(16, "cpu") == ["mps"]
    assert autotune.eligible_impls(24, "cpu", devices_on_model=1) == ["mps"]
    # a >= 2-device model axis adds the partitioned statevector from n = 10
    assert autotune.eligible_impls(16, "cpu", devices_on_model=8) == [
        "mps",
        "sharded_statevector",
    ]
    assert "sharded_statevector" in autotune.eligible_impls(
        10, "cpu", devices_on_model=2
    )
    assert "sharded_statevector" not in autotune.eligible_impls(
        9, "cpu", devices_on_model=8
    )
    # topology-blind callers (devices_on_model=None) never see sharded
    assert "sharded_statevector" not in autotune.eligible_impls(16, "tpu")
    # dense never appears past its wall
    for n in (13, 16, 24):
        assert "dense" not in autotune.eligible_impls(n, "cpu", 8)


def test_impl_eligible_reasons():
    ok, why = autotune.impl_eligible("dense", 16)
    assert not ok and "n <= 12" in why
    ok, why = autotune.impl_eligible("tensor", 16)
    assert not ok and "mps or sharded_statevector" in why
    ok, why = autotune.impl_eligible("sharded_statevector", 10, devices_on_model=1)
    assert not ok and ">= 2 devices" in why
    # the alias funnels through the same check
    ok, _ = autotune.impl_eligible("sharded", 10, devices_on_model=8)
    assert ok
    ok, _ = autotune.impl_eligible("mps", 24)
    assert ok
    with pytest.raises(ValueError):
        autotune.impl_eligible("qiskit", 8)


# ---------------------------------------------------------------------------
# Checkpoint meta reconcile: new impl names + typed topology errors
# ---------------------------------------------------------------------------


def test_reconcile_accepts_scaling_impl_provenance():
    """A checkpoint trained with mps/sharded reconciles cleanly when the eval
    config lets the dispatcher re-resolve (impl provenance is popped, chi is
    an execution knob the eval config owns)."""
    from qdml_tpu.config import ExperimentConfig
    from qdml_tpu.train.checkpoint import reconcile_quantum_cfg

    cfg = ExperimentConfig()
    out = reconcile_quantum_cfg(
        cfg, {"quantum": {"n_qubits": 6, "impl": "mps", "mps_chi": 64}}
    )
    assert out.quantum.n_qubits == 6
    assert out.quantum.impl == cfg.quantum.impl  # provenance, not folded in
    assert out.quantum.mps_chi == cfg.quantum.mps_chi
    # the deprecated alias is accepted as provenance too
    out = reconcile_quantum_cfg(
        cfg, {"quantum": {"n_qubits": 6, "impl": "sharded"}}
    )
    assert out.quantum.n_qubits == 6


def test_reconcile_rejects_ineligible_pin_typed():
    """An EXPLICIT eval-config pin that cannot run at the checkpoint's qubit
    count / this topology raises the typed error, not a KeyError (or a
    partnerless collective) deep in the first forward."""
    from unittest import mock

    from qdml_tpu.config import ExperimentConfig, QuantumConfig
    from qdml_tpu.quantum.autotune import ImplIneligibleError
    from qdml_tpu.train.checkpoint import reconcile_quantum_cfg

    # dense pinned, checkpoint says n=16: past the dense wall
    cfg = ExperimentConfig(quantum=QuantumConfig(impl="dense"))
    with pytest.raises(ImplIneligibleError, match="n <= 12"):
        reconcile_quantum_cfg(cfg, {"quantum": {"n_qubits": 16}})
    # sharded_statevector pinned (via the legacy backend knob, alias form),
    # restored on a single-device process
    cfg = ExperimentConfig(quantum=QuantumConfig(backend="sharded"))
    with mock.patch.object(autotune, "model_axis_devices", return_value=1):
        with pytest.raises(ImplIneligibleError, match=">= 2 devices"):
            reconcile_quantum_cfg(cfg, {"quantum": {"n_qubits": 10}})
    # same pin on the 8-device harness topology: fine
    out = reconcile_quantum_cfg(cfg, {"quantum": {"n_qubits": 10}})
    assert out.quantum.n_qubits == 10


def test_reconcile_unknown_impl_name_is_diagnosable():
    from qdml_tpu.config import ExperimentConfig
    from qdml_tpu.train.checkpoint import reconcile_quantum_cfg

    with pytest.raises(ValueError, match="unknown circuit impl"):
        reconcile_quantum_cfg(
            ExperimentConfig(), {"quantum": {"n_qubits": 4, "impl": "qiskit"}}
        )


def test_reconcile_ineligible_provenance_only_notes(capsys):
    """A provenance-only impl (no eval pin) that can't run here must NOT
    raise — the dispatcher re-resolves — but it says so."""
    from unittest import mock

    from qdml_tpu.config import ExperimentConfig
    from qdml_tpu.train.checkpoint import reconcile_quantum_cfg

    with mock.patch.object(autotune, "model_axis_devices", return_value=1):
        out = reconcile_quantum_cfg(
            ExperimentConfig(),
            {"quantum": {"n_qubits": 10, "impl": "sharded_statevector"}},
        )
    assert out.quantum.n_qubits == 10
    assert "ineligible on this topology" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Qubit-scaling helpers + report section round-trip
# ---------------------------------------------------------------------------


def test_scaling_grid_helpers():
    from qdml_tpu.eval.sweep import (
        QUBIT_SCALING_GRID,
        scaling_batch,
        scaling_chi,
    )

    assert QUBIT_SCALING_GRID[0] == 4 and QUBIT_SCALING_GRID[-1] == 24
    assert all(b == 64 for b in map(scaling_batch, (4, 12, 16)))
    assert scaling_batch(20) == 8 and scaling_batch(24) == 2
    # chi caps at the exactness bound: more buys nothing
    assert scaling_chi(6, 16) == 8  # 2^(6//2)
    assert scaling_chi(13, 16) == 16
    assert scaling_chi(4, 1) == 2  # floor


def test_impl_agreement_uses_independent_reference():
    from qdml_tpu.eval.sweep import impl_agreement

    agr = impl_agreement(6, "mps", n_layers=2, batch=3, mps_chi=8)
    assert agr["reference"] == "dense"
    assert agr["max_abs_delta"] is not None and agr["max_abs_delta"] <= 1e-5


def test_report_extracts_and_gates_qsc_scaling(tmp_path):
    """Each scaling point becomes its own throughput gate key (n=16
    regressing cannot hide behind n=6 improving) and the crossover section
    renders impl/chi/margin/agreement."""
    from qdml_tpu.telemetry.report import extract, report_main

    rec = {
        "metric": "qsc_scaling_points",
        "value": 2,
        "platform": "cpu",
        "details": {
            "qsc_scaling": {
                "points": [
                    {
                        "n_qubits": 4,
                        "quantum_impl": "dense_fused",
                        "samples_per_sec": 1000.0,
                        "batch": 64,
                        "candidates": {
                            "dense": {"train_ms": 2.0},
                            "dense_fused": {"train_ms": 1.0},
                        },
                        "agreement": {"reference": "dense", "max_abs_delta": 1e-7},
                    },
                    {
                        "n_qubits": 16,
                        "quantum_impl": "mps",
                        "mps_chi": 16,
                        "samples_per_sec": 5.0,
                        "batch": 8,
                        "candidates": {"mps": {"train_ms": 100.0}},
                        "agreement": {"reference": None, "max_abs_delta": None},
                    },
                ],
                "devices_on_model": 8,
                "platform": "cpu",
            }
        },
    }
    p = tmp_path / "scaling.jsonl"
    p.write_text(json.dumps(rec) + "\n")
    src = extract(str(p))
    assert src["throughput"]["qsc_scaling.n04.best_of_impls"] == 1000.0
    assert src["throughput"]["qsc_scaling.n16.best_of_impls"] == 5.0
    out = tmp_path / "report.md"
    rc = report_main(
        [f"--current={p}", f"--baseline={p}", f"--out={out}"]
    )
    assert rc == 0
    md = out.read_text()
    assert "qubit scaling (best-of-impls per n)" in md
    assert "qsc_scaling.n16.best_of_impls" in md
    assert "2.00x vs dense" in md  # the crossover margin, straight off the race
    assert "| 16 | mps | 16 |" in md
