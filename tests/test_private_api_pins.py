"""Pin the private jax APIs our runtime probes rely on.

:mod:`qdml_tpu.utils.platform` and :mod:`qdml_tpu.parallel.multihost` probe
``jax._src.xla_bridge._backends`` and ``jax._src.distributed.global_state``
to decide whether a backend/coordination client is live. Both probes carry
graceful fallbacks, but the fallbacks *change semantics* (``force_cpu``
degrades to a late failure at the caller's device-count check;
``ensure_initialized`` degrades to message-matching on RuntimeError text).
A jax upgrade that moves these attributes should fail HERE, loudly, instead
of silently shifting init behavior (ADVICE r2).
"""

import jax


def test_xla_bridge_backends_attr_exists():
    from jax._src import xla_bridge

    assert hasattr(xla_bridge, "_backends")
    assert isinstance(xla_bridge._backends, dict)


def test_distributed_global_state_attr_exists():
    from jax._src import distributed as _dist

    state = _dist.global_state
    # `client` is None until initialize(); the attribute itself must exist.
    assert hasattr(state, "client")


def test_probes_agree_with_reality():
    from qdml_tpu.parallel.multihost import _runtime_initialized
    from qdml_tpu.utils.platform import backend_initialized

    # The conftest pinned the CPU backend but no test initializes
    # jax.distributed; touching a device commits the backend.
    jax.devices()
    assert backend_initialized() is True
    assert _runtime_initialized() is False
