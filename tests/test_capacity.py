"""Trace-replay capacity planner (qdml_tpu/telemetry/capacity.py): the
queue theory is pinned against closed forms (M/D/1 Crommelin, M/M/1
sojourn), the window models against synthetic committed artifacts, and
the sweep/CLI against their contracts. Host-side — no engine, no jax."""

from __future__ import annotations

import json
import math
import random

import pytest

from qdml_tpu.telemetry.capacity import (
    P99_BAND,
    RPS_BAND_FRAC,
    WIRE_P99_BAND,
    QuantileDist,
    load_summary,
    md1_wait_cdf,
    md1_wait_quantile,
    mm1_sojourn_quantile,
    plan_backends,
    plan_main,
    replay_arrivals,
    simulate_queue,
    validate_window,
    validate_windows,
    window_model,
)


# ---------------------------------------------------------------------------
# queue theory vs the simulator
# ---------------------------------------------------------------------------


def _sim_wait_quantiles(lam, services, qs, n=60000, seed=3):
    arr = replay_arrivals(n, lam, "poisson", seed=seed)
    waits = sorted(simulate_queue(arr, services))
    return [waits[min(n - 1, int(q * n))] for q in qs]


def test_simulator_matches_md1_closed_form():
    """The DES against Crommelin's exact M/D/1 waiting-time CDF — the
    planner's queue core is real queueing theory, not vibes."""
    lam, d = 0.7, 1.0
    sim = _sim_wait_quantiles(lam, [d] * 60000, [0.5, 0.9, 0.99])
    for q, w_sim in zip([0.5, 0.9, 0.99], sim):
        w_exact = md1_wait_quantile(q, lam, d)
        assert w_exact == pytest.approx(w_sim, rel=0.10, abs=0.05), (
            f"q={q}: sim {w_sim} vs M/D/1 {w_exact}"
        )


def test_simulator_matches_mm1_closed_form():
    lam, mu = 0.6, 1.0
    rng = random.Random(11)
    n = 60000
    arr = replay_arrivals(n, lam, "poisson", seed=5)
    svc = [rng.expovariate(mu) for _ in range(n)]
    waits = simulate_queue(arr, svc)
    soj = sorted(w + s for w, s in zip(waits, svc))
    q90_sim = soj[int(0.9 * n)]
    q90_exact = mm1_sojourn_quantile(0.9, lam, mu)
    assert q90_exact == pytest.approx(q90_sim, rel=0.08)


def test_md1_cdf_shape_and_quantile_inversion():
    lam, d = 0.5, 1.0
    assert md1_wait_cdf(0.0, lam, d) == pytest.approx(1 - lam * d)  # P(W=0)=1-rho
    assert md1_wait_cdf(-1.0, lam, d) == 0.0
    prev = 0.0
    for t in [0.0, 0.5, 1.0, 2.0, 4.0, 8.0]:
        cur = md1_wait_cdf(t, lam, d)
        assert 0.0 <= cur <= 1.0 and cur >= prev  # monotone CDF
        prev = cur
    for q in (0.5, 0.9, 0.99):
        t = md1_wait_quantile(q, lam, d)
        assert md1_wait_cdf(t, lam, d) == pytest.approx(q, abs=1e-3)
    # unstable queue: no finite wait distribution
    assert md1_wait_cdf(10.0, lam=1.5, d=1.0) == 0.0


def test_simulate_queue_multiserver_and_empty():
    assert simulate_queue([], []) == []
    # two servers, simultaneous arrivals with unit service: no one waits
    waits = simulate_queue([0.0, 0.0], [1.0, 1.0], servers=2)
    assert waits == [0.0, 0.0]
    # one server: the second waits a full service time
    waits = simulate_queue([0.0, 0.0], [1.0, 1.0], servers=1)
    assert waits == [0.0, 1.0]


def test_replay_arrivals_processes():
    uni = replay_arrivals(100, 50.0, "uniform")
    gaps = [b - a for a, b in zip(uni, uni[1:])]
    assert all(g == pytest.approx(0.02) for g in gaps)
    poi = replay_arrivals(5000, 50.0, "poisson", seed=1)
    assert len(poi) == 5000 and poi == sorted(poi)
    assert 5000 / poi[-1] == pytest.approx(50.0, rel=0.1)
    # mmpp alternates hot/cold phases; same deterministic seed, same answer
    mm = replay_arrivals(1000, 50.0, "mmpp", burstiness=3.0, seed=2)
    assert mm == replay_arrivals(1000, 50.0, "mmpp", burstiness=3.0, seed=2)


# ---------------------------------------------------------------------------
# quantile-dist reconstruction
# ---------------------------------------------------------------------------


def test_quantile_dist_interpolation_and_mean():
    d = QuantileDist.from_summary(
        {"n": 100, "mean_ms": 11.0, "p50_ms": 10.0, "p95_ms": 20.0,
         "p99_ms": 30.0, "max_ms": 40.0}
    )
    assert d.quantile(0.5) == pytest.approx(10.0)
    assert d.quantile(0.99) == pytest.approx(30.0)
    assert d.quantile(1.0) == pytest.approx(40.0)
    # piecewise-linear between anchors
    mid = d.quantile(0.725)
    assert 10.0 < mid < 20.0
    # sampling stays inside the support
    rng = random.Random(0)
    xs = [d.sample(rng) for _ in range(2000)]
    assert min(xs) >= 0.0 and max(xs) <= 40.0
    med = sorted(xs)[1000]
    assert med == pytest.approx(10.0, rel=0.15)
    assert 0.0 < d.mean() < 40.0


def test_quantile_dist_missing_is_none():
    assert QuantileDist.from_summary(None) is None
    assert QuantileDist.from_summary({"p50_ms": None}) is None


# ---------------------------------------------------------------------------
# window models + validation bands (synthetic committed artifacts)
# ---------------------------------------------------------------------------


def _phase(p50, p95=None, p99=None, mx=None):
    return {"n": 500, "mean_ms": p50, "p50_ms": p50,
            "p95_ms": p95 or p50 * 1.2, "p99_ms": p99 or p50 * 1.4,
            "max_ms": mx or p50 * 1.6}


def _traced_summary(p99_ms=32.0, mean_ms=21.0, rps=100.0):
    return {
        "kind": "serve_summary",
        "n_requests": 2000,
        "rps": rps,
        "offered_rps": rps * 1.01,
        "arrival": {"process": "poisson", "burstiness": 1.0},
        "latency_ms": {"mean_ms": mean_ms, "p50_ms": mean_ms,
                       "p95_ms": p99_ms * 0.9, "p99_ms": p99_ms,
                       "max_ms": p99_ms * 1.3},
        "phases": {
            "batch_wait": _phase(4.0),
            "queue_wait": _phase(1.0),
            "compute": _phase(10.0),
            "fetch": _phase(2.0),
            "wire": _phase(3.0),
            "pick": _phase(0.5),
        },
        "trace": {"reconciliation": {"mean_unattributed_ms": 0.5}},
    }


def _wire_summary(p99_ms=30.0):
    return {
        "kind": "serve_summary",
        "completed": 1500,
        "rps": 90.0,
        "latency_ms": {"mean_ms": 21.0, "p50_ms": 20.0, "p95_ms": 27.0,
                       "p99_ms": p99_ms, "max_ms": 45.0},
        "router": {"wire_latency_ms": _phase(20.0, 26.0, 29.0, 44.0)},
    }


def _write_window(tmp_path, name, summary):
    p = tmp_path / name
    with open(p, "w") as fh:
        fh.write(json.dumps({"kind": "manifest", "argv": ["test"]}) + "\n")
        fh.write(json.dumps(summary) + "\n")
    return str(p)


def test_window_model_picks_phases_then_wire_then_none(tmp_path):
    assert window_model(_traced_summary())["mode"] == "phases"
    assert window_model(_wire_summary())["mode"] == "wire"
    bare = {"kind": "serve_summary", "latency_ms": {"p99_ms": 5.0}}
    assert window_model(bare)["mode"] is None
    with pytest.raises(ValueError):
        load_summary(_write_window(tmp_path, "empty.jsonl",
                                   {"kind": "counters", "completed": 1}))


def test_validate_window_phases_mode_self_consistent(tmp_path):
    """A window whose client quantiles match its phase composition must
    validate well inside the band."""
    path = _write_window(tmp_path, "traced.jsonl", _traced_summary())
    row = validate_window(path, n_samples=8000, seed=1)
    assert row["mode"] == "phases" and row["ok"] is True
    assert row["p99_ratio"] == pytest.approx(1.0, abs=math.log(P99_BAND))
    assert row["rps_err"] <= RPS_BAND_FRAC
    assert row["band"]["p99_factor"] == P99_BAND


def test_validate_window_flags_inconsistent_phases(tmp_path):
    """Client p99 wildly above what the phases can compose: the self-replay
    must FAIL the band, not rubber-stamp the artifact."""
    bad = _traced_summary(p99_ms=300.0, mean_ms=150.0)
    path = _write_window(tmp_path, "bad.jsonl", bad)
    row = validate_window(path, n_samples=4000, seed=1)
    assert row["ok"] is False and row["p99_ratio"] < 1.0 / P99_BAND


def test_validate_window_wire_mode_gets_wider_band(tmp_path):
    """Wire-mode windows cannot see client-side connection queueing, so
    they get the documented wider band: a 3x gap fails phases mode but
    passes wire mode."""
    assert WIRE_P99_BAND > P99_BAND
    wire = _wire_summary(p99_ms=90.0)  # wire dist p99 29ms -> ~3x gap
    path = _write_window(tmp_path, "wire.jsonl", wire)
    row = validate_window(path, n_samples=4000, seed=1)
    assert row["mode"] == "wire"
    assert row["p99_ratio"] < 1.0 / P99_BAND  # would fail the phases band
    assert row["ok"] is True                  # inside the wire band


def test_validate_windows_aggregates_and_skips_unjudgeable(tmp_path):
    good = _write_window(tmp_path, "a.jsonl", _traced_summary())
    bare = _write_window(
        tmp_path, "b.jsonl",
        {"kind": "serve_summary", "latency_ms": {}, "n_requests": 0},
    )
    rep = validate_windows([good, bare], n_samples=4000, seed=1)
    assert rep["n_windows"] == 1 and rep["ok"] is True
    assert rep["rows"][1]["ok"] is None and "note" in rep["rows"][1]
    assert rep["max_p99_ratio"] >= 1.0  # folded |log ratio|, always >= 1


# ---------------------------------------------------------------------------
# planning sweep
# ---------------------------------------------------------------------------


def test_plan_backends_sweep_monotone_and_answers(tmp_path):
    path = _write_window(tmp_path, "traced.jsonl", _traced_summary())
    rep = plan_backends(path, target_rps=300.0, p99_ms=60.0,
                        max_backends=8, n_samples=3000, seed=2)
    sweep = rep["sweep"]
    assert [r["backends"] for r in sweep] == list(range(1, 9))
    # per-backend load and predicted p99 fall as the fleet grows
    p99s = [r["predicted_p99_ms"] for r in sweep]
    assert p99s[0] > p99s[-1]
    assert all(b["per_backend_rps"] < a["per_backend_rps"]
               for a, b in zip(sweep, sweep[1:]))
    k = rep["backends_needed"]
    assert k is not None
    # minimality: everything below the answer misses the target
    for r in sweep:
        if r["backends"] < k:
            assert not r["meets_target"]
    assert sweep[k - 1]["meets_target"] and sweep[k - 1]["stable"]
    # compute+fetch mean ~12ms -> 1 backend at 300rps is rho ~3.6: unstable
    assert sweep[0]["stable"] is False


def test_plan_backends_exogenous_floor_returns_none(tmp_path):
    """Adding backends only shrinks queue wait; batch_wait/wire/pick and
    the residual are an exogenous floor a sweep cannot beat. A target
    below the floor must answer None, not a fantasy fleet size."""
    path = _write_window(tmp_path, "traced.jsonl", _traced_summary())
    rep = plan_backends(path, target_rps=100.0, p99_ms=5.0,
                        max_backends=4, n_samples=2000, seed=2)
    assert rep["backends_needed"] is None
    assert all(not r["meets_target"] for r in rep["sweep"])


def test_plan_backends_requires_phases(tmp_path):
    path = _write_window(tmp_path, "wire.jsonl", _wire_summary())
    with pytest.raises(ValueError, match="no phase spans"):
        plan_backends(path, target_rps=50.0, p99_ms=100.0)


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


def test_plan_main_exit_codes(tmp_path, capsys):
    good = _write_window(tmp_path, "good.jsonl", _traced_summary())
    bad = _write_window(tmp_path, "bad.jsonl",
                        _traced_summary(p99_ms=300.0, mean_ms=150.0))
    assert plan_main([]) == 2                      # no trace
    assert plan_main([f"--trace={good}"]) == 2     # no question asked
    capsys.readouterr()
    # validation: all-pass 0, any-fail 3
    assert plan_main([f"--trace={good}", "--validate", "--seed=1"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["plan_validation"]["ok"] is True
    assert plan_main([f"--trace={good},{bad}", "--validate", "--seed=1"]) == 3
    # planning: answered 0, unmeetable 3; --json round-trips
    outp = tmp_path / "plan.json"
    rc = plan_main([f"--trace={good}", "--target-rps=300", "--p99-ms=60",
                    "--seed=2", f"--json={outp}"])
    capsys.readouterr()
    assert rc == 0
    assert json.loads(outp.read_text())["backends_needed"] is not None
    assert plan_main([f"--trace={good}", "--target-rps=100",
                      "--p99-ms=5", "--max-backends=2", "--seed=2"]) == 3
    capsys.readouterr()
