"""Model zoo: shapes, scenario isolation, loss semantics."""

import jax
import jax.numpy as jnp
import numpy as np

from qdml_tpu.models import (
    DCEP128,
    FCP128,
    QSCP128,
    SCP128,
    ConvP128,
    StackedConvP128,
    accuracy,
    nll_loss,
    nmse_loss,
)

KEY = jax.random.PRNGKey(0)
X = jax.random.normal(KEY, (4, 16, 8, 2))


def test_conv_p128_shape():
    m = ConvP128()
    v = m.init(KEY, X, train=False)
    out = m.apply(v, X, train=False)
    assert out.shape == (4, 32 * 16 * 8)  # 4096, reference Estimators...py:266


def test_fc_and_dce_shapes():
    feats = jnp.ones((4, 4096))
    m = FCP128()
    out = m.apply(m.init(KEY, feats), feats)
    assert out.shape == (4, 2048)  # 64*16*2, reference Estimators...py:275
    d = DCEP128()
    v = d.init(KEY, X, train=False)
    assert d.apply(v, X, train=False).shape == (4, 2048)


def test_sc_p128_log_probs():
    m = SCP128()
    out = m.apply(m.init(KEY, X), X)
    assert out.shape == (4, 3)
    np.testing.assert_allclose(np.exp(np.asarray(out)).sum(-1), 1.0, rtol=1e-5)


def test_qsc_p128_log_probs():
    m = QSCP128(n_qubits=4, n_layers=2)
    v = m.init(KEY, X, train=False)
    out = m.apply(v, X, train=False)
    assert out.shape == (4, 3)
    np.testing.assert_allclose(np.exp(np.asarray(out)).sum(-1), 1.0, rtol=1e-5)


def test_stacked_conv_scenario_isolation():
    """Gradients from scenario s must touch only trunk slice s (the fused
    equivalent of the reference's per-scenario optimizers, Runner...py:160-163)."""
    m = StackedConvP128(n_scenarios=3)
    xs = jax.random.normal(KEY, (3, 4, 16, 8, 2))
    v = m.init(KEY, xs, train=False)

    def loss(params):
        out = m.apply({"params": params, "batch_stats": v["batch_stats"]}, xs, train=False)
        return jnp.sum(out[0] ** 2)  # scenario 0 only

    g = jax.grad(loss)(v["params"])
    leaves = jax.tree.leaves(g)
    assert all(l.shape[0] == 3 for l in leaves)
    for l in leaves:
        assert float(jnp.abs(l[0]).sum()) > 0  # slice 0 gets gradient
        assert float(jnp.abs(l[1]).sum()) == 0  # slices 1,2 untouched
        assert float(jnp.abs(l[2]).sum()) == 0


def test_quantumnat_noise_changes_forward_only_in_train():
    m = QSCP128(n_qubits=4, n_layers=2, use_quantumnat=True, noise_level=0.5)
    v = m.init(KEY, X, train=False)
    clean = m.apply(v, X, train=False)
    k = jax.random.PRNGKey(7)
    noisy = m.apply(v, X, train=True, rngs={"quantumnat": k})
    noisy2 = m.apply(v, X, train=True, rngs={"quantumnat": k})
    assert not np.allclose(np.asarray(clean), np.asarray(noisy))
    np.testing.assert_array_equal(np.asarray(noisy), np.asarray(noisy2))  # deterministic in key


def test_losses():
    x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    xh = x + 1.0
    np.testing.assert_allclose(float(nmse_loss(xh, x)), 4.0 / 30.0, rtol=1e-6)
    lp = jnp.log(jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1]]))
    labels = jnp.asarray([0, 1])
    np.testing.assert_allclose(
        float(nll_loss(lp, labels)), -(np.log(0.7) + np.log(0.8)) / 2, rtol=1e-6
    )
    assert float(accuracy(lp, labels)) == 1.0


def test_qsc_input_norm_scale_invariant():
    """With input_norm the log-probs are invariant to input power — the
    low-SNR robustness property the raw-pilot encoding lacks."""
    m = QSCP128(n_qubits=4, n_layers=2, input_norm=True)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(3, 16, 8, 2)), jnp.float32)
    v = m.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        np.asarray(m.apply(v, x)), np.asarray(m.apply(v, 7.5 * x)), rtol=1e-4
    )


def test_qsc_depolarizing_eval_mode():
    """A trained/initialised QSC evaluates under state-level noise by
    swapping the module config only — same param tree, trajectory-averaged
    circuit, valid log-probabilities, key-deterministic."""
    import jax

    from qdml_tpu.models.qsc import QSCP128

    x = jnp.ones((4, 16, 8, 2), jnp.float32)
    clean_model = QSCP128(n_qubits=4, n_layers=2, backend="tensor")
    vars_ = clean_model.init(jax.random.PRNGKey(0), x, train=False)
    clean = clean_model.apply(vars_, x, train=False)

    noisy_model = QSCP128(
        n_qubits=4, n_layers=2, depolarizing_p=0.2, n_trajectories=8
    )
    rngs = {"trajectories": jax.random.PRNGKey(1)}
    noisy = noisy_model.apply(vars_, x, train=False, rngs=rngs)
    assert noisy.shape == clean.shape == (4, 3)
    np.testing.assert_allclose(
        np.exp(np.asarray(noisy)).sum(-1), 1.0, rtol=1e-5
    )
    # same key -> same trajectories; heavy noise -> different logits
    again = noisy_model.apply(vars_, x, train=False, rngs=rngs)
    np.testing.assert_array_equal(np.asarray(noisy), np.asarray(again))
    assert not np.allclose(np.asarray(noisy), np.asarray(clean), atol=1e-4)


def test_qsc_depolarizing_rejects_non_tensor_backend():
    """ADVICE r3: with depolarizing_p > 0 the trajectory simulator (tensor
    formulation) runs regardless of the configured backend; an explicit
    dense/pallas/sharded choice must error instead of being silently
    ignored."""
    import pytest

    from qdml_tpu.models.qsc import QSCP128

    x = jnp.ones((2, 16, 8, 2), jnp.float32)
    model = QSCP128(n_qubits=4, n_layers=1, backend="dense", depolarizing_p=0.1)
    with pytest.raises(ValueError, match="cannot be honored"):
        model.init(jax.random.PRNGKey(0), x, train=False)
    # an explicit impl='dense' is likewise unhonorable
    model = QSCP128(n_qubits=4, n_layers=1, impl="dense", depolarizing_p=0.1)
    with pytest.raises(ValueError, match="cannot be honored"):
        model.init(jax.random.PRNGKey(0), x, train=False)
    # but impl='tensor' WINS over a non-tensor legacy backend (resolve_impl
    # precedence) — the trajectory simulator honors it, no error
    model = QSCP128(n_qubits=4, n_layers=1, impl="tensor", backend="pallas", depolarizing_p=0.1)
    model.init(jax.random.PRNGKey(0), x, train=False)


def test_conv_impls_agree():
    """The shift_matmul lowering is the same convolution as lax conv — same
    param tree (checkpoint-interchangeable), same outputs and gradients to
    float tolerance — so `auto`'s platform choice can never change results,
    only speed (the XLA:CPU batched-conv gradient cliff,
    results/perf_r4/cpu_fallback_profile.json)."""
    from qdml_tpu.models.cnn import SpatialConv

    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 16, 8, 2)), jnp.float32)
    conv = SpatialConv(8, (3, 3), impl="conv")
    shift = SpatialConv(8, (3, 3), impl="shift_matmul")
    v = conv.init(jax.random.PRNGKey(1), x)
    assert jax.tree_util.tree_structure(v) == jax.tree_util.tree_structure(
        shift.init(jax.random.PRNGKey(1), x)
    )
    np.testing.assert_allclose(
        np.asarray(conv.apply(v, x)), np.asarray(shift.apply(v, x)), atol=2e-5
    )
    gc = jax.grad(lambda p: jnp.sum(conv.apply(p, x) ** 2))(v)
    gs = jax.grad(lambda p: jnp.sum(shift.apply(p, x) ** 2))(v)
    np.testing.assert_allclose(
        np.asarray(gc["params"]["kernel"]), np.asarray(gs["params"]["kernel"]), atol=2e-3
    )


def test_conv_impls_agree_bf16():
    """The agreement holds in bfloat16 as well (ADVICE r4): the shift
    lowering accumulates its kh*kw partials in f32 — same as lax.conv's
    internal accumulator — so the bf16 disagreement is one output rounding
    step, not a kh*kw-term error sum. Tolerance is bf16 ulp-scale."""
    from qdml_tpu.models.cnn import SpatialConv

    x = jnp.asarray(np.random.default_rng(2).normal(size=(4, 16, 8, 8)), jnp.float32)
    conv = SpatialConv(8, (3, 3), dtype=jnp.bfloat16, impl="conv")
    shift = SpatialConv(8, (3, 3), dtype=jnp.bfloat16, impl="shift_matmul")
    v = conv.init(jax.random.PRNGKey(1), x)
    oc, os_ = conv.apply(v, x), shift.apply(v, x)
    assert oc.dtype == os_.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(oc, np.float32), np.asarray(os_, np.float32), atol=0.06, rtol=0.03
    )


def test_stacked_trunk_conv_impl_override():
    """conv_impl threads through the vmapped trunk; both lowerings produce
    the same stacked features from the same params."""
    from qdml_tpu.models.cnn import StackedConvP128

    x = jnp.asarray(np.random.default_rng(1).normal(size=(3, 2, 16, 8, 2)), jnp.float32)
    a = StackedConvP128(conv_impl="conv")
    b = StackedConvP128(conv_impl="shift_matmul")
    v = a.init(jax.random.PRNGKey(0), x, train=False)
    np.testing.assert_allclose(
        np.asarray(a.apply(v, x, train=False)),
        np.asarray(b.apply(v, x, train=False)),
        atol=1e-4,
    )
