"""Torch checkpoint interop: Flax <-> reference-named state dicts.

The strong check here is FORWARD EQUIVALENCE: random Flax weights exported to
a reference-named torch state dict, loaded into torch modules built with the
reference architecture (Conv/BN/ReLU trunk, C-major flatten, shared linear
head — ``Estimators_QuantumNAT_onchipQNN.py:40-101, 237-279``), must produce
the same outputs on the same inputs (NHWC vs NCHW transposed). That proves
both the weight mapping and that our modules ARE the reference architecture.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from torch import nn  # noqa: E402

from qdml_tpu.models.cnn import SCP128, QSCPreprocess  # noqa: E402
from qdml_tpu.train.hdce import HDCE  # noqa: E402
from qdml_tpu.train.torch_interop import (  # noqa: E402
    export_hdce,
    export_qsc,
    export_sc,
    import_hdce,
    import_qsc,
    import_sc,
    normalize_state_dict,
)


def _nchw(x_nhwc: np.ndarray) -> torch.Tensor:
    return torch.from_numpy(np.transpose(x_nhwc, (0, 3, 1, 2)).copy())


class _TorchTrunk(nn.Module):
    """Reference Conv_P128 architecture (fresh implementation for the test)."""

    def __init__(self):
        super().__init__()
        blocks = []
        ch = 2
        for _ in range(3):
            blocks += [
                nn.Conv2d(ch, 32, 3, padding=1, bias=False),
                nn.BatchNorm2d(32),
                nn.ReLU(),
            ]
            ch = 32
        self.cnn = nn.Sequential(*blocks)

    def forward(self, x):
        return self.cnn(x).flatten(1)  # C-major flatten


class _TorchHead(nn.Module):
    def __init__(self):
        super().__init__()
        self.FC = nn.Linear(32 * 16 * 8, 2048)

    def forward(self, x):
        return self.FC(x)


class _TorchSC(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(2, 32, 3, padding=1, bias=False)
        self.conv2 = nn.Conv2d(32, 32, 3, padding=1, bias=False)
        self.FC = nn.Linear(32 * 4 * 2, 3)

    def forward(self, x):
        x = torch.relu(self.conv1(x))
        x = torch.max_pool2d(x, 2, 2)
        x = torch.relu(self.conv2(x))
        x = torch.max_pool2d(x, 2, 2)
        return torch.log_softmax(self.FC(x.flatten(1)), dim=1)


class _TorchQSCPreprocess(nn.Module):
    def __init__(self, n_qubits=6):
        super().__init__()
        self.preprocess = nn.Sequential(
            nn.Conv2d(2, 16, 3, padding=1),
            nn.ReLU(),
            nn.MaxPool2d(2),
            nn.Conv2d(16, 32, 3, padding=1),
            nn.ReLU(),
            nn.MaxPool2d(2),
            nn.Flatten(),
            nn.Linear(32 * 4 * 2, n_qubits),
            nn.Tanh(),
        )

    def forward(self, x):
        return self.preprocess(x)


def _rand_x(batch=5, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((batch, 16, 8, 2)).astype(np.float32)


def test_hdce_export_forward_equivalence():
    model = HDCE()
    x = _rand_x()
    xs = jnp.broadcast_to(jnp.asarray(x)[None], (3,) + x.shape)
    variables = model.init(jax.random.PRNGKey(0), xs, train=False)
    # make batch_stats non-trivial so BN mapping is actually exercised
    variables = jax.tree.map(lambda v: v, variables)
    want = np.asarray(model.apply(variables, xs, train=False))  # (3, B, 2048)

    conv_sds, fc_sd = export_hdce(
        {"params": variables["params"], "batch_stats": variables["batch_stats"]}
    )
    head = _TorchHead()
    head.load_state_dict({k: torch.from_numpy(v) for k, v in fc_sd.items()})
    head.eval()
    for s in range(3):
        trunk = _TorchTrunk()
        trunk.load_state_dict(
            {k: torch.from_numpy(np.asarray(v)) for k, v in conv_sds[s].items()}
        )
        trunk.eval()
        with torch.no_grad():
            got = head(trunk(_nchw(x))).numpy()
        np.testing.assert_allclose(got, want[s], rtol=1e-4, atol=1e-4)


def test_hdce_import_roundtrip():
    model = HDCE()
    xs = jnp.zeros((3, 2, 16, 8, 2))
    variables = model.init(jax.random.PRNGKey(1), xs, train=False)
    conv_sds, fc_sd = export_hdce(
        {"params": variables["params"], "batch_stats": variables["batch_stats"]}
    )
    back = import_hdce(conv_sds, fc_sd)
    for la, lb in zip(jax.tree.leaves(back), jax.tree.leaves(dict(variables))):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-6)


def test_sc_export_forward_equivalence():
    model = SCP128()
    x = _rand_x(batch=7, seed=2)
    params = model.init(jax.random.PRNGKey(2), jnp.asarray(x), train=False)["params"]
    want = np.asarray(model.apply({"params": params}, jnp.asarray(x), train=False))

    tm = _TorchSC()
    tm.load_state_dict({k: torch.from_numpy(v) for k, v in export_sc(params).items()})
    tm.eval()
    with torch.no_grad():
        got = tm(_nchw(x)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_sc_import_handles_reference_formats():
    model = SCP128()
    params = model.init(jax.random.PRNGKey(3), jnp.zeros((1, 16, 8, 2)), train=False)[
        "params"
    ]
    sd = export_sc(params)
    # DataParallel 'module.' prefix + {'state_dict': ...} wrapper (Test.py:23-62)
    wrapped = {"state_dict": {f"module.{k}": v for k, v in sd.items()}}
    back = import_sc(normalize_state_dict(wrapped))
    for la, lb in zip(jax.tree.leaves(back), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-6)


def test_qsc_preprocess_forward_equivalence_and_roundtrip():
    from qdml_tpu.models.qsc import QSCP128

    model = QSCP128(n_qubits=4, n_layers=2)
    x = _rand_x(batch=3, seed=4)
    params = model.init(jax.random.PRNGKey(4), jnp.asarray(x), train=False)["params"]
    sd = export_qsc(params)

    # preprocess (angles) must agree with the torch reference preprocess
    pre = QSCPreprocess(n_qubits=4)
    angles_flax = np.asarray(
        pre.apply({"params": params["QSCPreprocess_0"]}, jnp.asarray(x))
    )
    tp = _TorchQSCPreprocess(n_qubits=4)
    tp.load_state_dict(
        {k: torch.from_numpy(v) for k, v in sd.items() if k.startswith("preprocess")}
    )
    tp.eval()
    with torch.no_grad():
        angles_torch = tp(_nchw(x)).numpy()
    np.testing.assert_allclose(angles_torch, angles_flax, rtol=1e-4, atol=1e-5)

    # full round trip
    back = import_qsc(sd)
    for la, lb in zip(jax.tree.leaves(back), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-6)


def test_import_reference_dir_genuine_wrapping(tmp_path):
    """Reference checkpoints are wrapped {'conv': sd}/{'linear': sd}
    (Runner...py:237-264) and SC is named {bs}_{snr}dB_epoch99_DML_SC.pth with
    key 'cnn' (Test.py:71-73); the directory importer must accept exactly
    those artifacts (ADVICE round 1, medium)."""
    from qdml_tpu.train.torch_interop import import_reference_dir

    model = HDCE()
    xs = jnp.zeros((3, 2, 16, 8, 2))
    variables = model.init(jax.random.PRNGKey(5), xs, train=False)
    conv_sds, fc_sd = export_hdce(
        {"params": variables["params"], "batch_stats": variables["batch_stats"]}
    )
    for i, sd in enumerate(conv_sds):
        torch.save(
            {"conv": {k: torch.from_numpy(np.asarray(v)) for k, v in sd.items()}},
            tmp_path / f"Conv{i}_256_10dB_best_DML.pth",
        )
    torch.save(
        {"linear": {k: torch.from_numpy(np.asarray(v)) for k, v in fc_sd.items()}},
        tmp_path / "Linear_256_10dB_best_DML.pth",
    )

    sc = SCP128()
    sc_params = sc.init(jax.random.PRNGKey(6), jnp.zeros((1, 16, 8, 2)), train=False)[
        "params"
    ]
    torch.save(
        {"cnn": {k: torch.from_numpy(v) for k, v in export_sc(sc_params).items()}},
        tmp_path / "256_10dB_epoch99_DML_SC.pth",  # reference SC scheme (Test.py:72)
    )

    out = import_reference_dir(str(tmp_path))
    assert set(out) == {"hdce", "sc"}
    for la, lb in zip(jax.tree.leaves(out["hdce"]), jax.tree.leaves(dict(variables))):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-6)
    for la, lb in zip(jax.tree.leaves(out["sc"]["params"]), jax.tree.leaves(sc_params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-6)


def test_import_reference_dir_stale_qsc_name(tmp_path):
    """Test.py:79-84 probes QSC_optimized_best.pth wrapped as
    {'model_state_dict': sd}; the importer accepts that stale format too."""
    from qdml_tpu.models.qsc import QSCP128
    from qdml_tpu.train.torch_interop import import_reference_dir

    model = QSCP128(n_qubits=4, n_layers=2)
    params = model.init(jax.random.PRNGKey(7), jnp.zeros((1, 16, 8, 2)), train=False)[
        "params"
    ]
    sd = {k: torch.from_numpy(np.asarray(v)) for k, v in export_qsc(params).items()}
    torch.save({"model_state_dict": sd}, tmp_path / "QSC_optimized_best.pth")
    out = import_reference_dir(str(tmp_path))
    assert set(out) == {"qsc"}
    for la, lb in zip(jax.tree.leaves(out["qsc"]["params"]), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-6)


def test_export_import_reference_dir_roundtrip(tmp_path):
    """export_reference_dir writes artifacts the reference's loaders accept;
    import_reference_dir (which enforces those genuine formats) reads them
    back bit-for-bit."""
    from qdml_tpu.models.qsc import QSCP128
    from qdml_tpu.train.torch_interop import export_reference_dir, import_reference_dir

    model = HDCE()
    xs = jnp.zeros((3, 2, 16, 8, 2))
    variables = model.init(jax.random.PRNGKey(8), xs, train=False)
    hdce_vars = {"params": variables["params"], "batch_stats": variables["batch_stats"]}
    sc_params = SCP128().init(
        jax.random.PRNGKey(9), jnp.zeros((1, 16, 8, 2)), train=False
    )["params"]
    qsc_params = QSCP128(n_qubits=4, n_layers=2).init(
        jax.random.PRNGKey(10), jnp.zeros((1, 16, 8, 2)), train=False
    )["params"]

    written = export_reference_dir(
        str(tmp_path), hdce_vars=hdce_vars, sc_params=sc_params, qsc_params=qsc_params
    )
    names = sorted(p.split("/")[-1] for p in written)
    assert "256_10dB_best_DML_SC.pth" in names          # Test.py:72 scheme
    assert "QSC_optimized_best.pth" in names            # Test.py:80 probe
    # wrapper keys are what the reference reads (Test.py:100-106)
    obj = torch.load(tmp_path / "Conv0_256_10dB_best_DML.pth", weights_only=False)
    assert set(obj) == {"conv"}
    obj = torch.load(tmp_path / "Linear_256_10dB_best_DML.pth", weights_only=False)
    assert set(obj) == {"linear"}

    out = import_reference_dir(str(tmp_path))
    assert set(out) == {"hdce", "sc", "qsc"}
    for la, lb in zip(jax.tree.leaves(out["hdce"]), jax.tree.leaves(hdce_vars)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-6)
    for la, lb in zip(jax.tree.leaves(out["sc"]["params"]), jax.tree.leaves(sc_params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-6)
    for la, lb in zip(jax.tree.leaves(out["qsc"]["params"]), jax.tree.leaves(qsc_params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-6)
