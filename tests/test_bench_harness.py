"""bench.py parent-process control flow (no jax import, no devices).

The harness's value is its behavior under a flapping tunnelled backend
(VERDICT r1 weak #1, r2 missing #1): these tests drive main() with stubbed
probe/children and pin the record-assembly contract — platform labeling,
guaranteed late probe, budget-capped-but-floored child timeout, fixed
headline key, and the committed-record pointer on fallback artifacts.
"""

import contextlib
import importlib.util
import io
import json
import os
import sys

import pytest


@pytest.fixture()
def benchmod(monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "measure_torch_cpu_reference", lambda: 50.0)
    return mod


def _run_main(mod) -> dict:
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = mod.main()
    rec = json.loads(buf.getvalue().strip().splitlines()[-1])
    return rc, rec


def test_cpu_fallback_record_with_guaranteed_late_probe(benchmod, monkeypatch):
    """Tunnel down throughout: structured cpu_fallback record, at least one
    late probe even with the wall budget already exhausted, and the pointer
    to the newest committed on-chip record."""
    probes = []

    def fake_probe(attempts=None, timeout_s=None):
        probes.append(attempts)
        return "down"

    def fake_child(env, platform, timeout_s):
        assert platform == "cpu"
        return {
            "backend": "cpu",
            "devices": 1,
            "hdce_f32": {"samples_per_sec": 100.0, "model_tflops": 1.0},
            "hdce_bf16": {"samples_per_sec": 120.0, "model_tflops": 1.2},
        }

    monkeypatch.setattr(benchmod, "probe_tpu", fake_probe)
    monkeypatch.setattr(benchmod, "_run_bench_child", fake_child)
    monkeypatch.setenv("QDML_BENCH_WALL_BUDGET_S", "1")
    rc, rec = _run_main(benchmod)
    assert rc == 0
    assert rec["platform"] == "cpu_fallback"
    assert rec["dtype"] == "float32"  # reference-dtype headline off-TPU
    assert rec["mfu"] is None
    # up-front probe + the guaranteed late probe, both with the default
    # (env-tunable) attempt count rather than a hardcoded single attempt
    assert probes == [None, None]
    # fallback artifacts always point at committed on-chip evidence
    assert rec["latest_committed_tpu_record"]["platform"].startswith("tpu")


def test_late_recovery_upgrades_to_tpu_with_floored_child_timeout(
    benchmod, monkeypatch
):
    """Tunnel returns during the late window: the record upgrades to tpu-*,
    the headline is the FIXED default-stream scan key (not a max over noisy
    variants), and the late child keeps at least the old 1500s timeout."""
    state = {"probes": 0, "children": []}

    def fake_probe(attempts=None, timeout_s=None):
        state["probes"] += 1
        return None if state["probes"] >= 2 else "down"

    def fake_child(env, platform, timeout_s):
        state["children"].append((platform, timeout_s))
        if platform == "cpu":
            return {
                "backend": "cpu",
                "hdce_f32": {"samples_per_sec": 1.0, "model_tflops": 0.1},
            }
        return {
            "backend": "tpu",
            "devices": 1,
            "hdce_bf16_scan": {
                "samples_per_sec": 9e5,
                "model_tflops": 60.0,
                "scan_steps": 16,
            },
            "hdce_bf16_scan_rbg": {
                "samples_per_sec": 9.9e5,
                "model_tflops": 64.0,
                "scan_steps": 16,
                "rng_impl": "rbg",
            },
            "hdce_bf16": {"samples_per_sec": 8e5, "model_tflops": 50.0},
        }

    monkeypatch.setattr(benchmod, "probe_tpu", fake_probe)
    monkeypatch.setattr(benchmod, "_run_bench_child", fake_child)
    monkeypatch.setenv("QDML_BENCH_WALL_BUDGET_S", "1")
    rc, rec = _run_main(benchmod)
    assert rc == 0
    assert rec["platform"].startswith("tpu")
    # fixed headline: the default threefry scan, though the rbg single
    # measurement is numerically larger
    assert rec["value"] == 9e5
    assert "hardware-RBG" not in rec["unit"]
    assert rec["details"]["hdce_bf16_scan_rbg"]["mfu"] is not None
    tpu_children = [c for c in state["children"] if c[0] == "tpu"]
    assert tpu_children and tpu_children[0][1] >= 1500


def test_bench_writes_telemetry_jsonl_with_manifest_header(
    benchmod, monkeypatch, tmp_path
):
    """--out writes the bench artifact as a telemetry JSONL: run-manifest
    header line first (host-only fallback here — the stubbed child returns no
    manifest), then the record — the shape `qdml-tpu report` consumes."""
    out = tmp_path / "bench.jsonl"

    def fake_child(env, platform, timeout_s):
        return {
            "backend": "cpu",
            "devices": 1,
            "hdce_f32": {"samples_per_sec": 100.0, "model_tflops": 1.0},
        }

    monkeypatch.setattr(benchmod, "probe_tpu", lambda **kw: "down")
    monkeypatch.setattr(benchmod, "_run_bench_child", fake_child)
    monkeypatch.setenv("QDML_BENCH_WALL_BUDGET_S", "1")
    monkeypatch.setattr(sys, "argv", ["bench.py", "--out", str(out)])
    rc, rec = _run_main(benchmod)
    assert rc == 0
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert lines[0]["kind"] == "manifest"
    assert lines[1]["kind"] == "bench_record"
    assert lines[1]["value"] == rec["value"]
    # the JSONL round-trips through the report extractor
    from qdml_tpu.telemetry.report import extract

    src = extract(str(out))
    assert src["manifest"] is not None
    assert src["throughput"]["hdce_train_samples_per_sec_per_chip"] == rec["value"]


def test_child_manifest_is_lifted_out_of_details(benchmod, monkeypatch, tmp_path):
    """A child-provided manifest becomes the telemetry header and is removed
    from the record's details."""
    out = tmp_path / "bench.jsonl"

    def fake_probe(attempts=None, timeout_s=None):
        return None

    def fake_child(env, platform, timeout_s):
        return {
            "backend": "tpu",
            "devices": 1,
            "manifest": {"kind": "manifest", "host": "tpu-vm"},
            "hdce_bf16_scan": {
                "samples_per_sec": 9e5,
                "model_tflops": 60.0,
                "scan_steps": 16,
            },
        }

    monkeypatch.setattr(benchmod, "probe_tpu", fake_probe)
    monkeypatch.setattr(benchmod, "_run_bench_child", fake_child)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--out", str(out)])
    rc, rec = _run_main(benchmod)
    assert rc == 0
    assert "manifest" not in rec["details"]
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert lines[0] == {"kind": "manifest", "host": "tpu-vm"}


def test_all_children_fail_yields_structured_error(benchmod, monkeypatch):
    monkeypatch.setattr(benchmod, "probe_tpu", lambda **kw: "down")
    monkeypatch.setattr(benchmod, "_run_bench_child", lambda *a, **kw: None)
    monkeypatch.setenv("QDML_BENCH_WALL_BUDGET_S", "1")
    rc, rec = _run_main(benchmod)
    assert rc == 1
    assert rec["platform"] == "none"
    assert rec["value"] is None
    assert "error" in rec
