"""bench.py parent-process control flow (no jax import, no devices).

The harness's value is its behavior under a flapping tunnelled backend
(VERDICT r1 weak #1, r2 missing #1): these tests drive main() with stubbed
probe/children and pin the record-assembly contract — platform labeling,
guaranteed late probe, budget-capped-but-floored child timeout, fixed
headline key, and the committed-record pointer on fallback artifacts.
"""

import contextlib
import importlib.util
import io
import json
import os
import sys

import pytest


@pytest.fixture()
def benchmod(monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "measure_torch_cpu_reference", lambda: 50.0)
    return mod


def _run_main(mod) -> dict:
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = mod.main()
    rec = json.loads(buf.getvalue().strip().splitlines()[-1])
    return rc, rec


def test_cpu_fallback_record_with_guaranteed_late_probe(benchmod, monkeypatch):
    """Tunnel down throughout: structured cpu_fallback record, at least one
    late probe even with the wall budget already exhausted, and the pointer
    to the newest committed on-chip record."""
    probes = []

    def fake_probe(attempts=None, timeout_s=None):
        probes.append(attempts)
        return "down"

    def fake_child(env, platform, timeout_s):
        assert platform == "cpu"
        return {
            "backend": "cpu",
            "devices": 1,
            "hdce_f32": {"samples_per_sec": 100.0, "model_tflops": 1.0},
            "hdce_bf16": {"samples_per_sec": 120.0, "model_tflops": 1.2},
        }

    monkeypatch.setattr(benchmod, "probe_tpu", fake_probe)
    monkeypatch.setattr(benchmod, "_run_bench_child", fake_child)
    monkeypatch.setenv("QDML_BENCH_WALL_BUDGET_S", "1")
    rc, rec = _run_main(benchmod)
    assert rc == 0
    assert rec["platform"] == "cpu_fallback"
    assert rec["dtype"] == "float32"  # reference-dtype headline off-TPU
    assert rec["mfu"] is None
    # up-front probe + the guaranteed late probe, both with the default
    # (env-tunable) attempt count rather than a hardcoded single attempt
    assert probes == [None, None]
    # fallback artifacts always point at committed on-chip evidence
    assert rec["latest_committed_tpu_record"]["platform"].startswith("tpu")


def test_late_recovery_upgrades_to_tpu_with_floored_child_timeout(
    benchmod, monkeypatch
):
    """Tunnel returns during the late window: the record upgrades to tpu-*,
    the headline is the FIXED default-stream scan key (not a max over noisy
    variants), and the late child keeps at least the old 1500s timeout."""
    state = {"probes": 0, "children": []}

    def fake_probe(attempts=None, timeout_s=None):
        state["probes"] += 1
        return None if state["probes"] >= 2 else "down"

    def fake_child(env, platform, timeout_s):
        state["children"].append((platform, timeout_s))
        if platform == "cpu":
            return {
                "backend": "cpu",
                "hdce_f32": {"samples_per_sec": 1.0, "model_tflops": 0.1},
            }
        return {
            "backend": "tpu",
            "devices": 1,
            "hdce_bf16_scan": {
                "samples_per_sec": 9e5,
                "model_tflops": 60.0,
                "scan_steps": 16,
            },
            "hdce_bf16_scan_rbg": {
                "samples_per_sec": 9.9e5,
                "model_tflops": 64.0,
                "scan_steps": 16,
                "rng_impl": "rbg",
            },
            "hdce_bf16": {"samples_per_sec": 8e5, "model_tflops": 50.0},
        }

    monkeypatch.setattr(benchmod, "probe_tpu", fake_probe)
    monkeypatch.setattr(benchmod, "_run_bench_child", fake_child)
    monkeypatch.setenv("QDML_BENCH_WALL_BUDGET_S", "1")
    rc, rec = _run_main(benchmod)
    assert rc == 0
    assert rec["platform"].startswith("tpu")
    # fixed headline: the default threefry scan, though the rbg single
    # measurement is numerically larger
    assert rec["value"] == 9e5
    assert "hardware-RBG" not in rec["unit"]
    assert rec["details"]["hdce_bf16_scan_rbg"]["mfu"] is not None
    tpu_children = [c for c in state["children"] if c[0] == "tpu"]
    assert tpu_children and tpu_children[0][1] >= 1500


def test_bench_writes_telemetry_jsonl_with_manifest_header(
    benchmod, monkeypatch, tmp_path
):
    """--out writes the bench artifact as a telemetry JSONL: run-manifest
    header line first (host-only fallback here — the stubbed child returns no
    manifest), then the record — the shape `qdml-tpu report` consumes."""
    out = tmp_path / "bench.jsonl"

    def fake_child(env, platform, timeout_s):
        return {
            "backend": "cpu",
            "devices": 1,
            "hdce_f32": {"samples_per_sec": 100.0, "model_tflops": 1.0},
        }

    monkeypatch.setattr(benchmod, "probe_tpu", lambda **kw: "down")
    monkeypatch.setattr(benchmod, "_run_bench_child", fake_child)
    monkeypatch.setenv("QDML_BENCH_WALL_BUDGET_S", "1")
    monkeypatch.setattr(sys, "argv", ["bench.py", "--out", str(out)])
    rc, rec = _run_main(benchmod)
    assert rc == 0
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert lines[0]["kind"] == "manifest"
    assert lines[1]["kind"] == "bench_record"
    assert lines[1]["value"] == rec["value"]
    # the JSONL round-trips through the report extractor
    from qdml_tpu.telemetry.report import extract

    src = extract(str(out))
    assert src["manifest"] is not None
    assert src["throughput"]["hdce_train_samples_per_sec_per_chip"] == rec["value"]


def test_child_manifest_is_lifted_out_of_details(benchmod, monkeypatch, tmp_path):
    """A child-provided manifest becomes the telemetry header and is removed
    from the record's details."""
    out = tmp_path / "bench.jsonl"

    def fake_probe(attempts=None, timeout_s=None):
        return None

    def fake_child(env, platform, timeout_s):
        return {
            "backend": "tpu",
            "devices": 1,
            "manifest": {"kind": "manifest", "host": "tpu-vm"},
            "hdce_bf16_scan": {
                "samples_per_sec": 9e5,
                "model_tflops": 60.0,
                "scan_steps": 16,
            },
        }

    monkeypatch.setattr(benchmod, "probe_tpu", fake_probe)
    monkeypatch.setattr(benchmod, "_run_bench_child", fake_child)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--out", str(out)])
    rc, rec = _run_main(benchmod)
    assert rc == 0
    assert "manifest" not in rec["details"]
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert lines[0] == {"kind": "manifest", "host": "tpu-vm"}


def test_all_children_fail_yields_structured_error(benchmod, monkeypatch):
    monkeypatch.setattr(benchmod, "probe_tpu", lambda **kw: "down")
    monkeypatch.setattr(benchmod, "_run_bench_child", lambda *a, **kw: None)
    monkeypatch.setenv("QDML_BENCH_WALL_BUDGET_S", "1")
    rc, rec = _run_main(benchmod)
    assert rc == 1
    assert rec["platform"] == "none"
    assert rec["value"] is None
    assert "error" in rec


def test_transfer_guard_trip_records_counted_transfer(benchmod):
    """A sub-bench killed by the timed loop's strict transfer guard records
    host_transfers=1 (so the report gate fails on a counted transfer), while
    ordinary failures stay plain error entries."""
    trip = benchmod._bench_error_entry(
        RuntimeError("Disallowed host-to-device transfer ... transfer guard")
    )
    assert trip["host_transfers"] == 1 and "error" in trip
    trip2 = benchmod._bench_error_entry(
        RuntimeError("jax_transfer_guard_device_to_host: device-to-host transfer")
    )
    assert trip2["host_transfers"] == 1
    plain = benchmod._bench_error_entry(ValueError("backend init hang"))
    assert "host_transfers" not in plain and "error" in plain


def test_probe_storm_collapses_to_structured_summary(benchmod, monkeypatch):
    """The BENCH_r05 retry-storm artifact shape is gone: N identical timeout
    tails collapse into ONE probe_attempts summary (outcome counts, window)
    plus a single structured probe_unavailable record on artifacts that never
    reached the TPU."""
    # seed a storm-shaped probe log (what 10 timed-out attempts produce)
    benchmod.PROBE_LOG.extend(
        {"t": 60.0 * i, "timeout_s": 45, "result": "probe timed out after 45s (backend init hang)"}
        for i in range(9)
    )
    benchmod.PROBE_LOG.append({"t": 580.0, "timeout_s": 150, "result": "rc!=0"})
    summary = benchmod.summarize_probe_log()
    assert summary["attempts"] == 10
    assert summary["outcomes"] == {
        "probe timed out after 45s (backend init hang)": 9,
        "rc!=0": 1,
    }
    assert summary["window_s"] == 580.0
    assert summary["first"]["t"] == 0.0 and summary["last"]["result"] == "rc!=0"
    # no successful probe anywhere -> the single structured outcome
    down = benchmod.probe_unavailable_outcome(600.0, 450.0)
    assert down is not None and down["probe_budget_s"] == 600.0
    # one success anywhere in the campaign suppresses it
    benchmod.PROBE_LOG.append({"t": 700.0, "timeout_s": 45, "result": "ok"})
    assert benchmod.probe_unavailable_outcome(600.0, 450.0) is None

    # end to end: a fallback record carries the summary, not the raw tails
    benchmod.PROBE_LOG.clear()
    monkeypatch.setattr(benchmod, "probe_tpu", lambda **kw: "down")

    def fake_child(env, platform, timeout_s):
        return {
            "backend": "cpu",
            "hdce_f32": {"samples_per_sec": 10.0, "model_tflops": 0.1},
        }

    monkeypatch.setattr(benchmod, "_run_bench_child", fake_child)
    monkeypatch.setenv("QDML_BENCH_WALL_BUDGET_S", "1")
    rc, rec = _run_main(benchmod)
    assert rc == 0
    assert isinstance(rec["probe_attempts"], dict)  # summary, not a list
    assert "probe_unavailable" in rec
    assert rec["probe_unavailable"]["probe_budget_s"] > 0
