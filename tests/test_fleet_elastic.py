"""Elastic fleet lifecycle (qdml_tpu/fleet/lifecycle.py + router membership
+ control/fleet_scale.py, docs/FLEET.md "elastic fleet").

All host-side — no engine, no warmup: ring-resize properties run on the
router's pure hash machinery, the lifecycle state machine runs on injected
spawn/verify fakes, admission verification runs against a minimal protocol
stub, and the autoscaler runs on scripted signals. The real
separate-process topology (spawn -> banner -> verify -> admit under MMPP
traffic) is the committed dryrun's job (scripts/fleet_elastic_dryrun.py ->
results/fleet_elastic/).
"""

from __future__ import annotations

import asyncio
import json
import socket as socket_mod
import threading
from concurrent.futures import Future

import pytest

from qdml_tpu.control.fleet_scale import (
    FleetAutoscaler,
    load_planner_target,
)
from qdml_tpu.fleet import route_async
from qdml_tpu.fleet.lifecycle import (
    AdmissionFailed,
    BackendLifecycle,
    verify_warm,
)
from qdml_tpu.fleet.poller import FleetPoller
from qdml_tpu.fleet.router import Backend, FleetRouter
from qdml_tpu.serve.client import ServeClient
from qdml_tpu.telemetry.capacity import emit_target


def _router(n: int, base_port: int = 45800, **kw) -> FleetRouter:
    """Router over n unconnected local addresses (never .start()ed: the
    ring/membership machinery under test is pure; polls against these ports
    fail fast with connection-refused when a test path reaches one)."""
    opts = dict(timeout_s=0.2, retries=0, poll_interval_s=30.0,
                dedup_ttl_s=30.0)
    opts.update(kw)
    return FleetRouter(
        [("127.0.0.1", base_port + i) for i in range(n)], **opts
    )


def _primaries(router: FleetRouter, keys) -> dict:
    return {k: router._candidates(k)[0].addr for k in keys}


# ---------------------------------------------------------------------------
# consistent-hash ring resize: bounded key movement
# ---------------------------------------------------------------------------


def test_ring_add_moves_only_new_hosts_share():
    """Adding one host moves ONLY keys that now land on it (~1/(N+1) of
    the id space, vnode variance bounded) — every surviving assignment is
    untouched, the property that keeps server-side dedup windows valid
    across a scale-up."""
    r = _router(4)
    keys = [f"req-{i}" for i in range(3000)]
    before = _primaries(r, keys)
    b = r.add_backend("127.0.0.1", 45990)
    after = _primaries(r, keys)
    moved = [k for k in keys if after[k] != before[k]]
    assert moved, "a new host must take ownership of some arcs"
    # every moved key moved TO the new host; nothing shuffled between
    # surviving hosts
    assert all(after[k] == b.addr for k in moved)
    frac = len(moved) / len(keys)
    assert 0.05 < frac < 0.45, f"moved share {frac} outside the vnode bound"


def test_ring_remove_restores_prior_assignment_exactly():
    """Retiring the added host hands its keys back bit-exactly: surviving
    hosts' vnode points are keyed on their stable addresses, so the rebuilt
    ring is identical to the pre-add ring."""
    r = _router(3)
    keys = [f"k-{i}" for i in range(2000)]
    before = _primaries(r, keys)
    b = r.add_backend("127.0.0.1", 45991)
    r.begin_retire(b)
    # draining: off the ring immediately, still a member until removal
    assert _primaries(r, keys) == before
    assert r.health()["backends_draining"] == 1
    rec = r.finish_retire(b)
    assert rec["addr"] == b.addr
    assert _primaries(r, keys) == before
    assert len(r.backends) == 3


def test_ring_retire_original_member_moves_only_its_keys():
    r = _router(4)
    keys = [f"id-{i}" for i in range(3000)]
    before = _primaries(r, keys)
    victim = r.backends[1]
    r.begin_retire(victim.addr)
    after = _primaries(r, keys)
    owned = [k for k in keys if before[k] == victim.addr]
    assert owned, "victim owned some arcs"
    # only the victim's keys moved; everyone else's stayed put
    for k in keys:
        if before[k] == victim.addr:
            assert after[k] != victim.addr
        else:
            assert after[k] == before[k]


def test_draining_state_is_typed_and_guarded():
    r = _router(2)
    victim = r.backends[0]
    b = r.begin_retire(victim.addr)
    assert b is victim and victim.draining
    assert r.begin_retire(victim.addr) is victim  # idempotent
    assert victim.poll_row()["state"] == "draining"
    assert FleetRouter.state_row(victim) == {"state": "draining"}
    assert victim not in r.live_backends()
    # the last non-draining member is not retirable
    with pytest.raises(ValueError):
        r.begin_retire(r.backends[1].addr)
    with pytest.raises(KeyError):
        r.begin_retire("nobody:1")


def _ok_call(calls):
    def fake_call(self, msg, timeout_s=None, idempotent=True):
        calls.append((self.addr, msg.get("op") or "infer", msg.get("id")))
        return {"id": msg.get("id"), "ok": True, "pred": "s0", "h": [0.0]}
    return fake_call


def test_retry_before_resize_dedup_hits_after(monkeypatch):
    """A retry issued AFTER its original backend retired re-attaches at the
    router's dedup table — identical reply, zero new forwards: membership
    changes do not break the idempotent-retry contract."""
    calls: list = []
    monkeypatch.setattr(Backend, "call", _ok_call(calls))
    r = _router(2)
    rep1 = r.request({"id": "rid-keep", "x": [1.0]})
    assert rep1["ok"]
    forwards = [c for c in calls if c[1] == "infer"]
    assert len(forwards) == 1
    served_by = forwards[0][0]
    rec = r.retire_backend(served_by, wait_s=1.0)
    assert rec["drained"] and rec["inflight_at_removal"] == 0
    assert len(r.backends) == 1
    rep2 = r.request({"id": "rid-keep", "x": [1.0]})
    assert rep2 == rep1
    assert len([c for c in calls if c[1] == "infer"]) == 1
    assert r.dedup.hits == 1


# ---------------------------------------------------------------------------
# lifecycle state machine: spawn -> warming -> admitted / quarantined,
# drain -> retired (injected spawn/verify fakes)
# ---------------------------------------------------------------------------


class _FakeProc:
    def __init__(self, host, port, host_id):
        self.host, self.port, self.host_id = host, port, host_id
        self.killed = False
        self.terminated = False
        self._alive = True

    def alive(self):
        return self._alive

    def kill(self):
        self.killed = True
        self._alive = False

    def terminate(self, timeout_s: float = 10.0):
        self.terminated = True
        self._alive = False


def _fake_spawner(procs, base_port=46100):
    state = {"n": 0}

    def spawn(overrides, port=0, host="127.0.0.1", log_path=None,
              timeout_s=600.0, env=None, python=None):
        state["n"] += 1
        p = _FakeProc(host, base_port + state["n"], f"spawned-{state['n']}")
        procs.append(p)
        return p

    return spawn


def _lifecycle(router, procs, verify=None, **kw):
    return BackendLifecycle(
        router,
        spawn_fn=_fake_spawner(procs),
        verify_fn=verify or (lambda h, p, timeout_s=10.0: {"warm": True}),
        drain_wait_s=1.0,
        **kw,
    )


def test_scale_up_admits_only_after_verification(monkeypatch):
    monkeypatch.setattr(Backend, "call", _ok_call([]))
    r = _router(1)
    procs: list = []
    verified: list = []

    def verify(host, port, timeout_s=10.0):
        # admission order pin: at verification time the router must NOT yet
        # know the standby — verify-then-admit, never admit-then-verify
        assert all(b.port != port for b in r.backends)
        verified.append(port)
        return {"warm": True, "compile_cache_after_warmup": {}}

    lc = _lifecycle(r, procs, verify=verify)
    rec = lc.scale_up()
    assert rec["ok"] and rec["stage"] == "admitted"
    assert verified == [procs[0].port]
    assert len(r.backends) == 2 and lc.fleet_size() == 2
    st = lc.status()
    assert st["lifecycle"][rec["addr"]]["state"] == "admitted"
    assert rec["addr"] in st["owned"]


def test_cold_backend_is_quarantined_never_admitted(monkeypatch):
    monkeypatch.setattr(Backend, "call", _ok_call([]))
    r = _router(1)
    procs: list = []

    def verify(host, port, timeout_s=10.0):
        raise AdmissionFailed(f"{host}:{port} reports warm=False")

    lc = _lifecycle(r, procs, verify=verify)
    rec = lc.scale_up()
    assert not rec["ok"] and rec["stage"] == "quarantined"
    assert "warm=False" in rec["reason"]
    assert len(r.backends) == 1  # the serving fleet never saw it
    assert procs[0].killed
    assert lc.status()["lifecycle"][rec["addr"]]["state"] == "quarantined"
    assert rec["addr"] not in lc.status()["owned"]


def test_kill_during_admission_quarantines_standby(monkeypatch):
    """A standby dying mid-verification (transport error) is the same
    quarantine path: killed, recorded, fleet untouched."""
    monkeypatch.setattr(Backend, "call", _ok_call([]))
    r = _router(2)
    procs: list = []

    def verify(host, port, timeout_s=10.0):
        procs[-1]._alive = False  # the process died under us
        raise ConnectionResetError("peer vanished mid-verify")

    lc = _lifecycle(r, procs, verify=verify)
    rec = lc.scale_up()
    assert not rec["ok"] and rec["stage"] == "quarantined"
    assert len(r.backends) == 2
    assert not procs[0].killed  # already dead: no second kill


def test_scale_down_drains_and_terminates_only_owned(monkeypatch):
    monkeypatch.setattr(Backend, "call", _ok_call([]))
    r = _router(1)
    procs: list = []
    lc = _lifecycle(r, procs)
    lc.scale_up()
    assert lc.fleet_size() == 2
    rec = lc.scale_down()
    # LIFO victim: the lifecycle-owned admission goes first, terminated
    assert rec["ok"] and rec["stage"] == "retired"
    assert rec["addr"] == f"{procs[0].host}:{procs[0].port}"
    assert rec["terminated"] and procs[0].terminated
    assert rec["drained"]
    assert lc.fleet_size() == 1
    # shrinking again would touch the boot-time backend: it is drained out
    # of the ring but NOT terminated (its supervisor owns the process) —
    # and here it is the last member, so the router refuses outright
    with pytest.raises(ValueError):
        lc.scale_down()


def test_scale_to_converges_and_aborts_on_failed_admission(monkeypatch):
    monkeypatch.setattr(Backend, "call", _ok_call([]))
    r = _router(1)
    procs: list = []
    gate = {"fail": False}

    def verify(host, port, timeout_s=10.0):
        if gate["fail"]:
            raise AdmissionFailed("cold standby")
        return {"warm": True}

    lc = _lifecycle(r, procs, verify=verify)
    rec = lc.scale_to(3)
    assert rec["ok"] and rec["backends"] == 3 and rec["backends_before"] == 1
    assert [a["stage"] for a in rec["actions"]] == ["admitted", "admitted"]
    # a failed admission aborts the grow loop (no blind tight-loop retry)
    gate["fail"] = True
    rec = lc.scale_to(5)
    assert not rec["ok"] and rec["backends"] == 3
    assert rec["actions"][-1]["stage"] == "quarantined"
    assert len(rec["actions"]) == 1
    gate["fail"] = False
    rec = lc.scale_to(1)
    assert rec["ok"] and rec["backends"] == 1
    assert all(p.terminated for p in procs[:2])
    with pytest.raises(ValueError):
        lc.scale_to(0)


# ---------------------------------------------------------------------------
# admission verification over the live verbs (protocol stub)
# ---------------------------------------------------------------------------


def _stub_server(replies: dict) -> int:
    """Minimal serve-protocol stub: one connection, answers health/metrics
    from the given payload dicts."""
    srv = socket_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def run():
        conn, _ = srv.accept()
        fh = conn.makefile("rw", encoding="utf-8", newline="\n")
        for line in fh:
            msg = json.loads(line)
            rep = {"id": msg.get("id"), "ok": True, **replies[msg["op"]]}
            fh.write(json.dumps(rep) + "\n")
            fh.flush()
        conn.close()
        srv.close()

    threading.Thread(target=run, daemon=True).start()
    return port


def test_verify_warm_accepts_warm_zero_compile_backend():
    port = _stub_server({
        "health": {"health": {"warm": True, "host_id": "b-ok", "replicas": 1}},
        "metrics": {"metrics": {
            "compile_cache_after_warmup": {"bucket_4": 0, "bucket_8": 0},
        }},
    })
    facts = verify_warm("127.0.0.1", port, timeout_s=5.0)
    assert facts["warm"] and facts["host_id"] == "b-ok"


def test_verify_warm_rejects_cold_and_compiling_backends():
    port = _stub_server({
        "health": {"health": {"warm": False}},
        "metrics": {"metrics": {}},
    })
    with pytest.raises(AdmissionFailed, match="warm=False"):
        verify_warm("127.0.0.1", port, timeout_s=5.0)
    port = _stub_server({
        "health": {"health": {"warm": True}},
        "metrics": {"metrics": {
            "compile_cache_after_warmup": {"bucket_4": 2},
        }},
    })
    with pytest.raises(AdmissionFailed, match="request-path compiles"):
        verify_warm("127.0.0.1", port, timeout_s=5.0)
    port = _stub_server({
        "health": {"health": {"warm": True}},
        "metrics": {"metrics": {}},  # no compile ledger at all
    })
    with pytest.raises(AdmissionFailed, match="no compile_cache"):
        verify_warm("127.0.0.1", port, timeout_s=5.0)


# ---------------------------------------------------------------------------
# fleet-tier autoscaler: hysteresis, guards, planner targets
# ---------------------------------------------------------------------------


def _scaler(calls, **kw):
    opts = dict(min_backends=1, max_backends=3, queue_high=10.0,
                queue_low=1.0, debounce=2, cooldown_ticks=2)
    opts.update(kw)
    return FleetAutoscaler(
        lambda n: calls.append(n) or {"ok": True, "backends": n}, **opts
    )


def test_fleet_autoscaler_debounce_cooldown_and_bounds():
    calls: list = []
    a = _scaler(calls)
    assert a.observe(50.0, 1) is None  # streak 1 of 2
    ev = a.observe(50.0, 1)
    assert ev["direction"] == "up" and ev["backends"] == 2 and calls == [2]
    # cooldown eats the next two ticks even under sustained pressure
    assert a.observe(50.0, 2) is None
    assert a.observe(50.0, 2) is None
    assert a.observe(50.0, 2) is None  # streak restarts post-cooldown
    ev = a.observe(50.0, 2)
    assert ev["direction"] == "up" and calls == [2, 3]
    # at max_backends: no further up, streaks at the bound fire nothing
    for _ in range(8):
        assert a.observe(50.0, 3) is None
    assert calls == [2, 3]


def test_fleet_autoscaler_slo_and_burn_guard_scale_down():
    calls: list = []
    a = _scaler(calls, cooldown_ticks=0)
    # low queue but SLO burning: the low streak never accumulates
    for _ in range(6):
        assert a.observe(0.0, 3, slo_attainment=0.9) is None
    # low queue, healthy SLO, but burn alert firing: still refused
    for _ in range(6):
        assert a.observe(0.0, 3, slo_attainment=1.0, burn_alert=True) is None
    assert calls == []
    assert a.observe(0.0, 3, slo_attainment=1.0) is None
    ev = a.observe(0.0, 3, slo_attainment=1.0)
    assert ev["direction"] == "down" and calls == [2]
    # re-anchoring: an operator's manual fleet change is respected
    ev = None
    for _ in range(3):
        ev = a.observe(50.0, 1) or ev
    assert ev["backends"] == 2 and calls[-1] == 2


def test_fleet_autoscaler_planner_target_converges_stepwise():
    calls: list = []
    a = _scaler(calls, cooldown_ticks=1)
    a.set_planner_target({"backends_needed": 3, "assumptions_sha": "sha-abc"})
    ev = a.observe(0.0, 1)  # planner mode: no watermark debounce
    assert ev["direction"] == "up" and ev["planner_sha"] == "sha-abc"
    assert calls == [2]
    assert a.observe(0.0, 2) is None  # cooldown spaces the steps
    ev = a.observe(0.0, 2)
    assert ev["backends"] == 3 and calls == [2, 3]
    assert a.observe(0.0, 3) is None  # converged: nothing to do
    assert a.observe(0.0, 3) is None
    # planner scale-down still rides the guards
    a.set_planner_target({"backends_needed": 1, "assumptions_sha": "sha-abc"})
    assert a.observe(0.0, 3, burn_alert=True) is None
    assert a.observe(0.0, 3, slo_attainment=0.5) is None
    ev = a.observe(0.0, 3, slo_attainment=1.0)
    assert ev["direction"] == "down" and calls[-1] == 2
    # a planner target beyond max_backends clamps to the bound
    a.set_planner_target({"backends_needed": 99, "assumptions_sha": "s2"})
    a.observe(0.0, 3)  # burn the cooldown tick
    for _ in range(4):
        ev = a.observe(0.0, 3) or ev
    assert a.state()["target"] <= 3
    a.set_planner_target(None)
    assert a.state()["planner"] is None


def test_fleet_autoscaler_dry_run_and_validation():
    calls: list = []
    a = _scaler(calls, dry_run=True, cooldown_ticks=0)
    a.observe(50.0, 1)
    ev = a.observe(50.0, 1)
    assert ev["dry_run"] and ev["result"] is None and calls == []
    with pytest.raises(ValueError):
        FleetAutoscaler(lambda n: None, min_backends=3, max_backends=2)
    with pytest.raises(ValueError):
        FleetAutoscaler(lambda n: None, queue_high=1.0, queue_low=5.0)


# ---------------------------------------------------------------------------
# planner-target handoff: emit_target <-> load_planner_target round-trip
# ---------------------------------------------------------------------------


_PLAN_REC = {
    "trace": "w.jsonl",
    "target_rps": 100.0,
    "p99_target_ms": 50.0,
    "workers_per_backend": 1,
    "sweep": [{"backends": 1, "predicted_p99_ms": 80.0, "meets_target": False},
              {"backends": 2, "predicted_p99_ms": 30.0, "meets_target": True}],
    "backends_needed": 2,
}


def test_emit_target_roundtrip_and_sha_seals_assumptions(tmp_path):
    tgt = emit_target(_PLAN_REC)
    assert tgt["backends_needed"] == 2 and len(tgt["assumptions_sha"]) == 64
    p = tmp_path / "target.json"
    p.write_text(json.dumps({"fleet_target": tgt}))
    loaded = load_planner_target(str(p))
    assert loaded == tgt
    # the sha is deterministic and moves with ANY planning input
    assert emit_target(dict(_PLAN_REC))["assumptions_sha"] == tgt["assumptions_sha"]
    retargeted = emit_target({**_PLAN_REC, "target_rps": 200.0})
    assert retargeted["assumptions_sha"] != tgt["assumptions_sha"]
    # a null answer (plan unmeetable) refuses LOUDLY at consumption
    p.write_text(json.dumps(
        {"fleet_target": emit_target({**_PLAN_REC, "backends_needed": None})}
    ))
    with pytest.raises(ValueError, match="no actionable backends_needed"):
        load_planner_target(str(p))


def _phase(p50):
    return {"n": 500, "mean_ms": p50, "p50_ms": p50, "p95_ms": p50 * 1.2,
            "p99_ms": p50 * 1.4, "max_ms": p50 * 1.6}


def test_plan_main_emit_target_cli_roundtrip(tmp_path, capsys):
    """``plan --emit-target`` writes the exact record the autoscaler's
    loader consumes — the full CLI round-trip the closed loop rides."""
    from qdml_tpu.telemetry.capacity import plan_main

    summary = {
        "kind": "serve_summary", "n_requests": 2000, "rps": 100.0,
        "offered_rps": 101.0,
        "arrival": {"process": "poisson", "burstiness": 1.0},
        "latency_ms": {"mean_ms": 21.0, "p50_ms": 21.0, "p95_ms": 29.0,
                       "p99_ms": 32.0, "max_ms": 42.0},
        "phases": {"batch_wait": _phase(4.0), "queue_wait": _phase(1.0),
                   "compute": _phase(10.0), "fetch": _phase(2.0),
                   "wire": _phase(3.0), "pick": _phase(0.5)},
        "trace": {"reconciliation": {"mean_unattributed_ms": 0.5}},
    }
    w = tmp_path / "traced.jsonl"
    w.write_text(json.dumps(summary) + "\n")
    out = tmp_path / "target.json"
    rc = plan_main([
        f"--trace={w}", "--target-rps=40", "--p99-ms=200",
        "--max-backends=4", f"--emit-target={out}",
    ])
    capsys.readouterr()
    assert rc == 0
    tgt = load_planner_target(str(out))
    assert isinstance(tgt["backends_needed"], int)
    assert tgt["trace"] == str(w) and tgt["target_rps"] == 40.0
    assert len(tgt["assumptions_sha"]) == 64


# ---------------------------------------------------------------------------
# the {"op": "fleet"} wire verb + poller attachments
# ---------------------------------------------------------------------------


class _FakeLifecycle:
    """scale_to semantics without processes: converges up to max_ok."""

    def __init__(self, router, max_ok=3):
        self.router = router
        self.max_ok = max_ok

    def status(self):
        return {"backends": len(self.router.backends), "lifecycle": {}}

    def scale_to(self, n):
        got = min(int(n), self.max_ok)
        return {"backends_before": len(self.router.backends), "backends": got,
                "target": int(n), "ok": got == int(n), "actions": []}


@pytest.fixture()
def front(monkeypatch):
    """Two route_async front doors over fake-call routers: one lifecycle-
    less, one with a fake lifecycle manager."""
    monkeypatch.setattr(Backend, "call", _ok_call([]))
    aloop = asyncio.new_event_loop()
    t = threading.Thread(target=aloop.run_forever, daemon=True)
    t.start()
    routers, ports, tasks = [], [], []
    for lc_factory in (lambda r: None, lambda r: _FakeLifecycle(r)):
        r = _router(2)
        ready: Future = Future()
        task = asyncio.run_coroutine_threadsafe(
            route_async(r, "127.0.0.1", 0, ready, lifecycle=lc_factory(r)),
            aloop,
        )
        ports.append(ready.result(timeout=10.0))
        routers.append(r)
        tasks.append(task)
    yield routers, ports
    for task in tasks:
        task.cancel()
    aloop.call_soon_threadsafe(aloop.stop)
    t.join(timeout=5.0)


def test_fleet_verb_status_form_always_answers(front):
    routers, (plain_port, elastic_port) = front
    with ServeClient("127.0.0.1", plain_port, timeout_s=5.0, retries=0) as c:
        rep = c.fleet()
        assert rep["ok"] and rep["fleet"]["elastic"] is False
        assert rep["fleet"]["backends"] == 2
    with ServeClient("127.0.0.1", elastic_port, timeout_s=5.0, retries=0) as c:
        rep = c.fleet()
        assert rep["ok"] and rep["fleet"]["elastic"] is True


def test_fleet_verb_scaling_form_typed_replies(front):
    routers, (plain_port, elastic_port) = front
    with ServeClient("127.0.0.1", plain_port, timeout_s=5.0, retries=0) as c:
        rep = c.fleet(backends=3)
        assert not rep["ok"]
        assert rep["reason"].startswith("fleet_scale_unavailable")
    with ServeClient("127.0.0.1", elastic_port, timeout_s=5.0, retries=0) as c:
        rep = c.fleet(backends=3)
        assert rep["ok"] and rep["fleet"]["backends"] == 3
        rep = c.fleet(backends=9)  # beyond the fake's convergence ceiling
        assert not rep["ok"]
        assert rep["reason"].startswith("fleet_scale_failed")
        rep = c.fleet(backends=0)  # still a replica-axis-free verb: typed
        assert rep["ok"] is False or rep["fleet"]["target"] == 0


def test_socket_poller_speaks_fleet_verb(front):
    from qdml_tpu.control.loop import SocketPoller

    routers, (plain_port, elastic_port) = front
    p = SocketPoller("127.0.0.1", elastic_port, timeout_s=5.0)
    assert p.fleet()["elastic"] is True
    assert p.fleet(3)["backends"] == 3
    with pytest.raises(RuntimeError, match="fleet_scale_failed"):
        p.fleet(9)
    p_plain = SocketPoller("127.0.0.1", plain_port, timeout_s=5.0)
    with pytest.raises(RuntimeError, match="fleet_scale_unavailable"):
        p_plain.fleet(3)


def test_fleet_poller_fleet_axis(monkeypatch):
    monkeypatch.setattr(Backend, "call", _ok_call([]))
    r = _router(2)
    bare = FleetPoller(r)
    assert bare.fleet()["backends"] == 2
    with pytest.raises(RuntimeError, match="fleet_scale_unavailable"):
        bare.fleet(3)
    armed = FleetPoller(r, lifecycle=_FakeLifecycle(r))
    assert armed.fleet(3)["ok"] is True


# ---------------------------------------------------------------------------
# monitor: membership-derived events
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


class _MemberPoller:
    def __init__(self, bids):
        self.bids = list(bids)

    def health(self):
        return {
            "warm": True, "quarantined": [], "swap_epoch": 0,
            "per_backend": {
                bid: {"start_seq": 1, "uptime_s": 5.0, "poll_ok": True,
                      "state": "closed"}
                for bid in self.bids
            },
        }

    def metrics(self):
        return {"completed": 0, "shed": {}, "faults": {}, "restarts": 0}


def test_monitor_derives_membership_events():
    from qdml_tpu.telemetry.timeseries import MonitorScraper

    clk = _Clock()
    p = _MemberPoller(["b0", "b1"])
    s = MonitorScraper(p, interval_s=1.0, clock=clk)
    s.scrape_once()  # first scrape seeds silently: boot set != admissions
    assert not any(e["event"] == "backend_admitted" for e in s.events)
    clk.t += 1.0
    p.bids.append("b2")
    s.scrape_once()
    admitted = [e for e in s.events if e["event"] == "backend_admitted"]
    assert [e["backend"] for e in admitted] == ["b2"]
    clk.t += 1.0
    p.bids.remove("b0")
    s.scrape_once()
    retired = [e for e in s.events if e["event"] == "backend_retired"]
    assert [e["backend"] for e in retired] == ["b0"]
    assert "b0" not in s._prev_backends  # diff state dropped on retirement
    clk.t += 1.0
    p.bids.append("b0")  # same id re-admitted later: diffs fresh
    s.scrape_once()
    admitted = [e for e in s.events if e["event"] == "backend_admitted"]
    assert [e["backend"] for e in admitted] == ["b2", "b0"]
    restarts = [e for e in s.events if e["event"] == "backend_restart"]
    assert restarts == []  # the re-admission is not a restart


# ---------------------------------------------------------------------------
# graftlint: the new lifecycle/ring mutable state is lock-disciplined
# ---------------------------------------------------------------------------


def test_lock_map_covers_lifecycle_and_ring_state():
    import ast

    from qdml_tpu.analysis.engine import ModuleContext
    from qdml_tpu.analysis.rules import rule_serve_lock_discipline

    lifecycle_src = (
        "import threading\n"
        "class BackendLifecycle:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._members = {}\n"
        "        self._procs = {}\n"
        "    def locked(self):\n"
        "        with self._lock:\n"
        "            return dict(self._members), list(self._procs)\n"
        "    def racy_members(self):\n"
        "        return self._members.get('x')\n"
        "    def racy_procs(self):\n"
        "        return list(self._procs)\n"
    )
    path = "qdml_tpu/fleet/lifecycle.py"
    ctx = ModuleContext(path, path, lifecycle_src, ast.parse(lifecycle_src))
    assert {f.line for f in rule_serve_lock_discipline(ctx)} == {11, 13}

    ring_src = (
        "import threading\n"
        "class FleetRouter:\n"
        "    def __init__(self):\n"
        "        self._ring_lock = threading.Lock()\n"
        "        self._ring = []\n"
        "        self._ring_idx = []\n"
        "    def snapshot(self):\n"
        "        with self._ring_lock:\n"
        "            return self._ring, self._ring_idx\n"
        "    def racy(self):\n"
        "        return len(self._ring)\n"
    )
    path = "qdml_tpu/fleet/router.py"
    ctx = ModuleContext(path, path, ring_src, ast.parse(ring_src))
    assert {f.line for f in rule_serve_lock_discipline(ctx)} == {11}
    # the real modules are clean (also pinned by the repo-wide lint gate)
    other = ModuleContext("other/f.py", "other/f.py", ring_src,
                          ast.parse(ring_src))
    assert rule_serve_lock_discipline(other) == []
