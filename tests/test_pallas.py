"""Pallas kernel equivalence: fused paths vs the XLA dense/tensor paths.

Runs in Pallas interpret mode on the CPU test backend (the kernels detect the
backend and interpret themselves); on real TPU the same tests exercise the
compiled Mosaic kernels.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qdml_tpu.quantum import statevector as sv
from qdml_tpu.quantum.circuits import angle_embed, ansatz_unitary, run_circuit
from qdml_tpu.quantum.pallas_kernels import (
    apply_rotation_layer,
    fused_unitary_expvals,
)


def _rand_inputs(n, layers, batch, seed=0):
    rng = np.random.default_rng(seed)
    angles = jnp.asarray(rng.uniform(-1, 1, (batch, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(-3, 3, (layers, n, 2)).astype(np.float32))
    return angles, w


@pytest.mark.parametrize("n,batch", [(4, 5), (6, 300)])
def test_fused_expvals_matches_dense(n, batch):
    layers = 2
    angles, w = _rand_inputs(n, layers, batch)
    want = run_circuit(angles, w, n, layers, "dense")
    got = run_circuit(angles, w, n, layers, "pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)


def test_fused_expvals_gradients_match():
    n, layers, batch = 5, 2, 7
    angles, w = _rand_inputs(n, layers, batch, seed=3)

    def loss(backend):
        return lambda w_, a_: jnp.sum(run_circuit(a_, w_, n, layers, backend) ** 2)

    gw_ref, ga_ref = jax.grad(loss("dense"), argnums=(0, 1))(w, angles)
    gw, ga = jax.grad(loss("pallas"), argnums=(0, 1))(w, angles)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref), rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(ga_ref), rtol=1e-3, atol=1e-5)


def test_fused_expvals_direct_call():
    """fused_unitary_expvals == expvals_z(psi @ U^T) on a non-embedded state."""
    n, batch = 4, 9
    rng = np.random.default_rng(1)
    angles = jnp.asarray(rng.uniform(-2, 2, (batch, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(0, 6, (1, n, 2)).astype(np.float32))
    psi = angle_embed(sv.zero_state(n, (batch,)), angles, n)
    u = ansatz_unitary(w, n, 1)
    got = fused_unitary_expvals(psi, u, n)
    from qdml_tpu.utils.complexops import ceinsum

    want = sv.expvals_z(ceinsum("...i,ji->...j", psi, u), n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("n", [3, 6, 7])
def test_rotation_layer_kernel_matches_tensor(n):
    # n=3/6 (dim < 128 lanes) take the XLA fallback inside
    # _rotation_layer_pallas; n=7 (dim == 128) is the smallest case that
    # engages the actual Mosaic roll/mask kernel body — without it the
    # kernel branch had NO coverage (found in round 4).
    batch = 11
    rng = np.random.default_rng(n)
    angles = jnp.asarray(rng.uniform(-1, 1, (batch, n)).astype(np.float32))
    w_l = jnp.asarray(rng.uniform(-3, 3, (n, 2)).astype(np.float32))
    psi = angle_embed(sv.zero_state(n, (batch,)), angles, n)

    got = apply_rotation_layer(psi, w_l, n)
    want = psi
    for q in range(n):
        want = sv.apply_ry(want, n, q, w_l[q, 0])
        want = sv.apply_rz(want, n, q, w_l[q, 1])
    np.testing.assert_allclose(np.asarray(got.re), np.asarray(want.re), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got.im), np.asarray(want.im), rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_pallas_tensor_backend_end_to_end():
    # slow-marked (VERDICT r3 ask #8): the unit case above covers both the
    # kernel and fallback branches; this composition test (full circuit +
    # grads through the custom_vjp) costs ~25s of XLA:CPU grad compiles.
    # n=7 so the explicit run exercises the kernel branch in composition.
    n, layers, batch = 7, 3, 17
    angles, w = _rand_inputs(n, layers, batch, seed=9)
    want = run_circuit(angles, w, n, layers, "tensor")
    got = run_circuit(angles, w, n, layers, "pallas_tensor")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)

    g_ref = jax.grad(lambda w_: jnp.sum(run_circuit(angles, w_, n, layers, "tensor")))(w)
    g = jax.grad(lambda w_: jnp.sum(run_circuit(angles, w_, n, layers, "pallas_tensor")))(w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-3, atol=1e-5)


def test_pallas_under_jit_and_vmap():
    n, layers = 4, 2
    angles, w = _rand_inputs(n, layers, 6, seed=4)

    f = jax.jit(lambda a, w_: run_circuit(a, w_, n, layers, "pallas"))
    np.testing.assert_allclose(
        np.asarray(f(angles, w)),
        np.asarray(run_circuit(angles, w, n, layers, "dense")),
        rtol=1e-4,
        atol=1e-5,
    )


@pytest.mark.parametrize("n", [3, 6])
def test_ry_product_state_matches_angle_embed(n):
    """Closed-form embedding == gate-wise RY chain on |0...0> (and is real)."""
    rng = np.random.default_rng(7)
    angles = jnp.asarray(rng.uniform(-3, 3, (4, n)).astype(np.float32))
    want = angle_embed(sv.zero_state(n, (4,)), angles, n)
    amp = sv.ry_product_state(angles, n)
    np.testing.assert_allclose(np.asarray(amp), np.asarray(want.re), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(want.im), 0.0, atol=1e-7)


# ---------------------------------------------------------------------------
# Whole-circuit multi-layer VMEM-resident kernel (v2)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "n,layers,batch",
    [
        (4, 2, 6),    # dim < 128: the XLA-twin fallback branch
        (7, 3, 11),   # dim == 128: smallest shape engaging the Mosaic kernel,
                      # batch 11 forces sublane padding (pad-once tiling)
        (7, 1, 16),   # single layer: fori_loop boundary
    ],
)
def test_fused_circuit_matches_tensor(n, layers, batch):
    """Values: one-pallas_call L-layer kernel == gate-wise statevector
    reference (interpret mode on the CPU suite; compiled Mosaic on TPU)."""
    angles, w = _rand_inputs(n, layers, batch, seed=n + layers)
    want = run_circuit(angles, w, n, layers, "tensor")
    got = run_circuit(angles, w, n, layers, "pallas_circuit")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)


def test_fused_circuit_gradients_match():
    """Gradients: the adjoint backward (reverse-rotation re-materialization
    from the saved FINAL state only) == AD through the gate chain, for both
    weights and embedding angles, on the kernel-engaging shape."""
    n, layers, batch = 7, 2, 9
    angles, w = _rand_inputs(n, layers, batch, seed=5)

    def loss(backend):
        return lambda w_, a_: jnp.sum(run_circuit(a_, w_, n, layers, backend) ** 2)

    gw_ref, ga_ref = jax.grad(loss("tensor"), argnums=(0, 1))(w, angles)
    gw, ga = jax.grad(loss("pallas_circuit"), argnums=(0, 1))(w, angles)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref), rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(ga_ref), rtol=1e-3, atol=1e-5)


def test_fused_circuit_bf16_amplitudes():
    """bf16 statevector residency: values track the f32 reference to bf16
    tolerance, gradients stay finite and directionally consistent (the <Z>
    contraction accumulates in f32 regardless)."""
    from qdml_tpu.quantum.pallas_kernels import fused_circuit_expvals

    n, layers, batch = 7, 2, 12
    angles, w = _rand_inputs(n, layers, batch, seed=8)
    want = np.asarray(run_circuit(angles, w, n, layers, "tensor"))
    got = np.asarray(fused_circuit_expvals(angles, w, n, layers, bf16_amps=True))
    assert got.dtype == np.float32
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.03)

    gw = jax.grad(
        lambda w_: jnp.sum(fused_circuit_expvals(angles, w_, n, layers, bf16_amps=True) ** 2)
    )(w)
    gw_ref = jax.grad(
        lambda w_: jnp.sum(run_circuit(angles, w_, n, layers, "tensor") ** 2)
    )(w)
    assert np.all(np.isfinite(np.asarray(gw)))
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref), rtol=0.2, atol=0.05)


def test_fused_circuit_lead_shape_and_jit():
    """Extra lead dims survive the reshape/pad path, under jit."""
    n, layers = 7, 2
    rng = np.random.default_rng(13)
    angles = jnp.asarray(rng.uniform(-2, 2, (2, 5, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(0, 6, (layers, n, 2)).astype(np.float32))
    f = jax.jit(lambda a, w_: run_circuit(a, w_, n, layers, "pallas_circuit"))
    got = f(angles, w)
    assert got.shape == (2, 5, n)
    want = run_circuit(angles, w, n, layers, "tensor")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)


def test_pallas_tensor_alias_routes_to_circuit_kernel():
    """The deprecated pre-v2 backend name keeps working and produces the
    whole-circuit kernel's numbers (no more per-layer host-loop launches)."""
    n, layers, batch = 7, 2, 5
    angles, w = _rand_inputs(n, layers, batch, seed=2)
    a = run_circuit(angles, w, n, layers, "pallas_tensor")
    b = run_circuit(angles, w, n, layers, "pallas_circuit")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0.0)


def test_quantumnat_noise_stream_identical_across_impls():
    """The QuantumNAT noise draw must be a function of the rng stream ONLY —
    switching circuit implementation may not perturb which noisy point the
    gradient is taken at. Same key, different impls => same log-probs."""
    from qdml_tpu.models.qsc import QSCP128

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((4, 16, 8, 2)).astype(np.float32))
    key = jax.random.PRNGKey(7)
    outs = {}
    for impl in ("dense", "dense_fused", "pallas", "tensor"):
        m = QSCP128(n_qubits=4, n_layers=2, use_quantumnat=True, noise_level=0.3, impl=impl)
        variables = m.init(jax.random.PRNGKey(0), x, train=False)
        outs[impl] = np.asarray(
            m.apply(variables, x, train=True, rngs={"quantumnat": key})
        )
    np.testing.assert_allclose(outs["dense"], outs["tensor"], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(outs["dense"], outs["pallas"], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(outs["dense"], outs["dense_fused"], rtol=1e-4, atol=1e-5)


def test_fused_qsc_odd_batch_and_lead_shape():
    """Non-tile-aligned batch + extra lead dims survive the padding/reshape."""
    from qdml_tpu.quantum.pallas_kernels import fused_qsc_expvals

    n, layers = 4, 1
    rng = np.random.default_rng(11)
    angles = jnp.asarray(rng.uniform(-2, 2, (3, 11, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(0, 6, (layers, n, 2)).astype(np.float32))
    u = ansatz_unitary(w, n, layers)
    got = fused_qsc_expvals(angles, u, n)
    want = run_circuit(angles, w, n, layers, "dense")
    assert got.shape == (3, 11, n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)
