"""Fleet control plane (docs/CONTROL.md): drift detectors (stationary
false-positive property + forced trip), channel-family drift trajectories
(drift-0 bit-identity pin), single-trunk continual fine-tuning (frozen
head/peers bit-identity pin), drain-safe elastic replica scaling, the canary
gate + rollback watch, queue-depth autoscaler hysteresis, the controller
loop, traffic-side drift injection, and the controller LOCK_MAP lint rows."""

import dataclasses
import json
import textwrap
import threading
import time

import numpy as np
import pytest

from qdml_tpu.config import (
    ControlConfig,
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    ServeConfig,
    TrainConfig,
)
from qdml_tpu.control.autoscale import Autoscaler
from qdml_tpu.control.deploy import Deployer
from qdml_tpu.control.drift import DB_SCALE, DriftMonitor, PageHinkley
from qdml_tpu.control.finetune import _subtree_keys, finetune_trunk
from qdml_tpu.control.loop import FleetController, PoolPoller
from qdml_tpu.data.channels import family_table
from qdml_tpu.serve import Prediction, ReplicaPool, ServeEngine
from qdml_tpu.serve.loadgen import make_request_samples, run_loadgen
from qdml_tpu.serve.metrics import ServeMetrics

ZERO = {"hits": 0, "misses": 0, "requests": 0}


def _tiny_cfg(**control_overrides) -> ExperimentConfig:
    return ExperimentConfig(
        name="control_test",
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=96),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=1),
        serve=ServeConfig(max_batch=8, buckets=(4, 8), max_wait_ms=1.0, max_queue=64, batching="bucket"),
        control=ControlConfig(
            **{
                "ft_steps": 4, "ft_batch": 16, "probe_n": 12, "min_window": 4,
                "interval_s": 0.01, "watch_ticks": 2, **control_overrides,
            }
        ),
    )


@pytest.fixture(scope="module")
def ctl_env(tmp_path_factory):
    """One tiny trained-shape workdir + warmed engine + offline reference
    shared by the control tests (each bucket is an XLA compile)."""
    from qdml_tpu.train.checkpoint import save_checkpoint
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    cfg = _tiny_cfg()
    wd = str(tmp_path_factory.mktemp("control_wd"))
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    clf_vars = {"params": sc_state.params}
    save_checkpoint(wd, "hdce_best", hdce_vars, {"epoch": 0, "name": cfg.name})
    save_checkpoint(wd, "sc_best", clf_vars, {"epoch": 0, "name": cfg.name})
    engine = ServeEngine(cfg, hdce_vars, clf_vars)
    samples = make_request_samples(cfg, 32)
    offline_h, offline_pred, offline_conf = engine.offline_forward(samples["x"])
    engine.warmup()
    return cfg, wd, engine, samples, offline_h, offline_pred, offline_conf


# ---------------------------------------------------------------------------
# Drift detectors (pure host code)
# ---------------------------------------------------------------------------


def test_page_hinkley_stationary_stream_never_trips():
    """The false-positive property at default thresholds: N windows of
    in-distribution traffic (mean-stationary noise at observed serve-stat
    scales) must never trip, across seeds and both directions — a false trip
    costs a fine-tune + canary + swap cycle."""
    for seed in range(5):
        rng = np.random.default_rng(seed)
        for direction, mean, sig in (
            ("down", 0.9, 0.01),   # confidence-like stream
            ("up", 0.02, 0.005),   # overflow-rate-like stream
        ):
            det = PageHinkley(direction=direction)  # DEFAULT thresholds
            trips = sum(
                det.update(mean + sig * rng.standard_normal()) for _ in range(400)
            )
            assert trips == 0, (seed, direction)


def test_page_hinkley_trips_on_forced_drift():
    rng = np.random.default_rng(7)
    det = PageHinkley(direction="down")
    for _ in range(50):
        assert not det.update(0.9 + 0.01 * rng.standard_normal())
    tripped_at = None
    for i in range(50):
        if det.update(0.7 + 0.01 * rng.standard_normal()):
            tripped_at = i
            break
    assert tripped_at is not None and tripped_at < 10  # detects within a few windows


def test_drift_monitor_stationary_false_positive_property():
    """The monitor-level version of the FP property: every (scenario,
    signal) stream fed stationary windows at default knobs fires nothing."""
    rng = np.random.default_rng(3)
    mon = DriftMonitor()  # default knobs — the satellite's stated property
    for _ in range(200):
        for s in range(3):
            assert mon.observe(s, "confidence", 0.85 + 0.01 * rng.standard_normal()) is None
        assert mon.observe(-1, "overflow_rate", abs(0.01 * rng.standard_normal())) is None
        assert mon.observe(0, "nmse_parity", -12.0 + 0.2 * rng.standard_normal()) is None
    assert mon.active() == []


def test_drift_monitor_debounce_latch_reset():
    """One tripping window is NOT an event (debounce); the event fires once
    (latch), names the stream, and reset() re-arms."""
    mon = DriftMonitor(delta=0.005, threshold=0.05, debounce=2, min_samples=3)
    for _ in range(10):
        assert mon.observe(1, "confidence", 0.9) is None
    events = []
    for _ in range(10):
        ev = mon.observe(1, "confidence", 0.4)
        if ev:
            events.append(ev)
    assert len(events) == 1  # debounced AND latched: exactly one event
    assert events[0]["scenario"] == 1 and events[0]["signal"] == "confidence"
    assert events[0]["windows"] >= mon.min_samples
    assert mon.active() == [(1, "confidence")]
    mon.reset(1)
    assert mon.active() == []
    # nmse_parity runs on the dB scale (10x thresholds)
    st = DriftMonitor(delta=0.005, threshold=0.05)
    st.observe(0, "nmse_parity", -10.0)
    assert st.state()["0:nmse_parity"] is not None
    with pytest.raises(ValueError, match="unknown drift signal"):
        mon.observe(0, "typo_signal", 1.0)
    assert DB_SCALE == 10.0


# ---------------------------------------------------------------------------
# Channel-family drift trajectories
# ---------------------------------------------------------------------------


def test_family_table_drift_zero_is_bit_identical():
    """The frozen-preset pin: drift step 0 reproduces family_table down to
    the bit (the early return applies NO float op), at S=3 and S>3."""
    for s in (3, 8):
        base = family_table(s)
        drift0 = family_table(s, drift_step=0, drift_scenario=1)
        for k in ("n_paths", "angle_spread", "delay_spread", "k_factor", "mobility"):
            assert np.array_equal(base[k], drift0[k]), (s, k)
            assert base[k].dtype == drift0[k].dtype
        assert base["preset"] == drift0["preset"]


def test_family_table_drift_perturbs_only_target_row():
    base = family_table(6)
    d = family_table(6, drift_step=3, drift_scenario=1)
    for k in ("angle_spread", "delay_spread", "k_factor", "mobility"):
        assert not np.array_equal(base[k][1], d[k][1]), k
        mask = np.arange(6) != 1
        assert np.array_equal(base[k][mask], d[k][mask]), k
    assert d["preset"][1].endswith("~d3") and d["preset"][0] == base["preset"][0]
    # drift is monotone in the step (more steps = more perturbation)
    d2 = family_table(6, drift_step=6, drift_scenario=1)
    assert d2["delay_spread"][1] > d["delay_spread"][1] > base["delay_spread"][1]
    assert d2["k_factor"][1] < d["k_factor"][1] < base["k_factor"][1]
    # drift_scenario=-1 drifts every family
    all_d = family_table(6, drift_step=2)
    assert not np.array_equal(base["mobility"], all_d["mobility"])
    with pytest.raises(ValueError, match="drift_step"):
        family_table(3, drift_step=-1)


def test_geometry_threads_drift_and_validates():
    from qdml_tpu.data.channels import ChannelGeometry

    data = DataConfig(n_scenarios=3, drift_step=2, drift_scenario=1)
    geom = ChannelGeometry.from_config(data)
    assert geom.drift_step == 2 and geom.drift_scenario == 1
    with pytest.raises(ValueError, match="drift_scenario"):
        ChannelGeometry(n_scenarios=3, drift_scenario=5)


# ---------------------------------------------------------------------------
# Single-trunk continual fine-tuning
# ---------------------------------------------------------------------------


def test_finetune_freezes_head_and_peer_trunks_bit_identically(ctl_env):
    """The acceptance pin: fine-tuning the drifted trunk leaves every other
    trunk AND the shared FC head (params and batch stats) bit-identical —
    and actually changes the target trunk."""
    import jax

    from qdml_tpu.train.checkpoint import restore_params

    cfg, wd, *_ = ctl_env
    base, _ = restore_params(wd, "hdce_best")
    rec = finetune_trunk(cfg, wd, scenario=1, drift_step=3)
    assert rec["tag"] == "hdce_last" and rec["rollback_tag"] == "hdce_best"
    assert np.isfinite(rec["loss_last"])
    new, meta = restore_params(wd, "hdce_last")
    trunk_key, head_key = _subtree_keys(base["params"])

    def rows(tree, s):
        return [np.asarray(leaf)[s] for leaf in jax.tree.leaves(tree)]

    # shared head: bit-identical (params; FCP128 has no batch stats)
    for a, b in zip(
        jax.tree.leaves(base["params"][head_key]), jax.tree.leaves(new["params"][head_key])
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # peer trunks: bit-identical params AND batch stats
    for s in (0, 2):
        for a, b in zip(rows(base["params"][trunk_key], s), rows(new["params"][trunk_key], s)):
            assert np.array_equal(a, b)
        for a, b in zip(
            rows(base["batch_stats"][trunk_key], s), rows(new["batch_stats"][trunk_key], s)
        ):
            assert np.array_equal(a, b)
    # the drifted trunk moved
    assert any(
        not np.array_equal(a, b)
        for a, b in zip(rows(base["params"][trunk_key], 1), rows(new["params"][trunk_key], 1))
    )
    # provenance rides normal checkpoint meta
    assert meta["finetune"]["scenario"] == 1 and meta["finetune"]["drift_step"] == 3
    assert meta["finetune"]["base_tag"] == "hdce_best"


def test_finetune_validates_inputs(ctl_env):
    cfg, wd, *_ = ctl_env
    with pytest.raises(ValueError, match="scenario"):
        finetune_trunk(cfg, wd, scenario=7, drift_step=1)
    with pytest.raises(ValueError, match="drift_step"):
        finetune_trunk(cfg, wd, scenario=0, drift_step=0)
    with pytest.raises(FileNotFoundError):
        finetune_trunk(cfg, "/nonexistent/workdir", scenario=0, drift_step=1)


# ---------------------------------------------------------------------------
# Elastic replica pool: drain-safe scale-down under in-flight traffic
# ---------------------------------------------------------------------------


def test_remove_replica_drains_nothing_under_in_flight_traffic(ctl_env):
    """The drain-safety pin: scale down WHILE submitted requests are still
    queued/in flight — every future must resolve with a real Prediction
    (the shared ExitCoordinator keeps the last-worker-out drain from firing
    while peers live), and the request path never compiles."""
    from qdml_tpu.utils.compile_cache import compile_cache_stats

    cfg, _wd, engine, samples, offline_h, *_ = ctl_env
    pool = ReplicaPool(engine, replicas=3).start()
    pre = compile_cache_stats()
    try:
        assert pool.n_replicas == 3
        futs = [pool.submit(samples["x"][i % 32], rid=i) for i in range(48)]
        removed = pool.remove_replica()  # mid-burst scale-down
        assert removed is not None
        results = [f.result(timeout=30.0) for f in futs]
        assert all(isinstance(r, Prediction) for r in results)
        served = np.stack([r.h for r in sorted(results, key=lambda r: r.rid)])
        np.testing.assert_allclose(
            served, np.concatenate([offline_h[:32], offline_h[:16]]), rtol=1e-5, atol=1e-5
        )
        assert pool.n_replicas == 2
        # scale back up under the same warmed engine: zero new compiles
        pool.add_replica()
        assert pool.n_replicas == 3
        more = [pool.submit(samples["x"][i], rid=100 + i) for i in range(8)]
        assert all(isinstance(f.result(timeout=30.0), Prediction) for f in more)
    finally:
        pool.stop()
    # zero compiles across the whole scale-down/up traffic window (the
    # counters are process-global, so the gate is the window delta)
    assert compile_cache_stats() == pre
    # the retired replica's served history stays in the pool aggregate
    assert pool.merged_metrics().completed == 56
    rec = pool.scale_to(1)
    assert rec["replicas"] == 1 and pool.n_replicas == 1
    # never below one replica; replica 0 (the submit front) survives
    assert pool.remove_replica() is None


def test_pool_metrics_confidence_and_per_scenario(ctl_env):
    """ServeMetrics satellite: per-scenario prediction counts + the
    classifier-confidence histogram flow through observe/merge/snapshot
    exactly (conf_sum differencing is the detectors' window input)."""
    cfg, _wd, engine, samples, _h, offline_pred, offline_conf = ctl_env
    pool = ReplicaPool(engine, replicas=2).start()
    try:
        futs = [pool.submit(samples["x"][i], rid=i) for i in range(24)]
        results = [f.result(timeout=30.0) for f in futs]
    finally:
        pool.stop()
    assert all(isinstance(r, Prediction) for r in results)
    # per-request confidence matches the offline forward's routed-class prob
    for r in results:
        assert r.confidence == pytest.approx(float(offline_conf[r.rid]), abs=1e-5)
    m = pool.live_metrics()
    per = m["per_scenario"]
    counts = {k: v["n"] for k, v in per.items()}
    expect: dict = {}
    for p in offline_pred[:24]:
        expect[str(int(p))] = expect.get(str(int(p)), 0) + 1
    assert counts == expect
    total_conf = sum(v.get("conf_sum", 0.0) for v in per.values())
    assert total_conf == pytest.approx(float(np.sum(offline_conf[:24])), abs=1e-2)
    assert m["confidence"]["n"] == 24
    assert m["dispatch"]["mode"] == "dense"
    # merge exactness: two collectors fed halves == one fed all
    a, b, whole = ServeMetrics(), ServeMetrics(), ServeMetrics()
    for i, r in enumerate(results):
        (a if i % 2 == 0 else b).observe_prediction(r)
        whole.observe_prediction(r)
    a.merge(b)
    assert a.scenario_counts == whole.scenario_counts
    assert a.confidence.summary() == whole.confidence.summary()
    assert a.scenario_conf_sum == pytest.approx(whole.scenario_conf_sum)


# ---------------------------------------------------------------------------
# Explicit-tag hot-swap (the stale-best shadow fix)
# ---------------------------------------------------------------------------


def test_swap_explicit_tag_beats_stale_best_shadow(ctl_env, tmp_path):
    """After continual fine-tuning writes hdce_last, the default newest-tag
    resolution still prefers the STALE hdce_best — the deployer must pin the
    promoted tag explicitly, and the explicit path must reject unknown
    tags."""
    import jax

    from qdml_tpu.train.checkpoint import restore_params

    from qdml_tpu.train.checkpoint import has_checkpoint

    cfg, wd, engine, samples, *_ = ctl_env
    # the module fixture's finetune test already promoted hdce_last; only
    # re-run the (compile-heavy) fine-tune if test ordering ever changes
    if not has_checkpoint(wd, "hdce_last"):
        finetune_trunk(cfg, wd, scenario=1, drift_step=3)
    last, _ = restore_params(wd, "hdce_last")
    best, _ = restore_params(wd, "hdce_best")
    trunk_key, _hk = _subtree_keys(best["params"])
    assert any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(last["params"]), jax.tree.leaves(best["params"]))
    )
    # default resolution: the stale best shadows the fine-tuned last
    rec = engine.swap_from_workdir(wd)
    assert rec["tags"]["hdce"] == "hdce_best"
    # the deployer's path: explicit tag pins the promoted checkpoint
    rec = engine.swap_from_workdir(wd, tags={"hdce": "hdce_last"})
    assert rec["tags"] == {"hdce": "hdce_last", "sc": "sc_best"}
    assert rec["compile"] == ZERO
    live_trunk = jax.tree.leaves(engine.live_vars()[0]["params"][trunk_key])
    want_trunk = jax.tree.leaves(last["params"][trunk_key])
    for a, b in zip(live_trunk, want_trunk):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(FileNotFoundError, match="pinned tag"):
        engine.swap_from_workdir(wd, tags={"hdce": "hdce_nope"})
    # the restart twin: a FRESH engine pinned to the promoted tag comes up
    # serving hdce_last (construction only — no warmup compiles here)
    restarted = ServeEngine.from_workdir(cfg, wd, tags={"hdce": "hdce_last"})
    for a, b in zip(
        jax.tree.leaves(restarted.live_vars()[0]["params"][trunk_key]), want_trunk
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(FileNotFoundError, match="pinned tag"):
        ServeEngine.from_workdir(cfg, wd, tags={"hdce": "hdce_nope"})
    # restore the original params for the other fixture tests (the swap
    # record's own windowed compile delta is the zero-compile instrument —
    # the fine-tune above legitimately compiled its train step in-process)
    assert engine.swap_from_workdir(wd, tags={"hdce": "hdce_best"})["compile"] == ZERO


# ---------------------------------------------------------------------------
# Autoscaler hysteresis
# ---------------------------------------------------------------------------


def test_autoscaler_hysteresis_debounce_cooldown_bounds():
    calls = []
    sc = Autoscaler(
        lambda n: calls.append(n) or {"replicas": n},
        min_replicas=1, max_replicas=3,
        queue_high=10.0, queue_low=2.0, debounce=2, cooldown_ticks=2,
    )
    # one spike is NOT a scale-up (debounce)
    assert sc.observe(50.0, 1) is None
    assert sc.observe(0.0, 1) is None  # streak reset
    assert sc.observe(50.0, 1) is None
    act = sc.observe(50.0, 1)
    assert act and act["direction"] == "up" and calls == [2]
    # cooldown: sustained pressure right after an action does nothing
    assert sc.observe(50.0, 2) is None and sc.observe(50.0, 2) is None
    # after cooldown, the next sustained burst scales again, capped at max
    assert sc.observe(50.0, 2) is None
    act = sc.observe(50.0, 2)
    assert act and calls == [2, 3]
    sc2 = Autoscaler(
        lambda n: {"replicas": n}, min_replicas=1, max_replicas=3,
        queue_high=10.0, queue_low=2.0, debounce=1, cooldown_ticks=0,
    )
    # at max: no further up
    assert sc2.observe(50.0, 3) is None
    # idle: scales down, respecting SLO health and min bound
    act = sc2.observe(0.0, 3)
    assert act and act["direction"] == "down" and act["replicas"] == 2
    assert sc2.observe(0.0, 2, slo_attainment=0.5) is None  # SLO unhealthy: hold
    act = sc2.observe(0.0, 2, slo_attainment=1.0)
    assert act and act["replicas"] == 1
    assert sc2.observe(0.0, 1) is None  # at min
    with pytest.raises(ValueError, match="hysteresis"):
        Autoscaler(lambda n: None, queue_high=1.0, queue_low=2.0)


def test_autoscaler_dry_run_reports_without_acting():
    calls = []
    sc = Autoscaler(
        lambda n: calls.append(n), debounce=1, cooldown_ticks=0,
        queue_high=10.0, queue_low=2.0, max_replicas=4, dry_run=True,
    )
    act = sc.observe(50.0, 1)
    assert act["dry_run"] is True and act["direction"] == "up"
    assert calls == []  # decided, reported, NOT taken


def test_pool_autoscaler_scales_live_pool(ctl_env):
    """The in-process wiring: sustained queue depth observed from the live
    pool grows it via the drain-safe lever; the request path stays
    compile-free."""
    from qdml_tpu.utils.compile_cache import compile_cache_stats

    cfg, _wd, engine, samples, *_ = ctl_env
    pool = ReplicaPool(engine, replicas=1).start()
    pre = compile_cache_stats()
    try:
        sc = Autoscaler(
            pool.scale_to, max_replicas=2, queue_high=4.0, queue_low=0.5,
            debounce=1, cooldown_ticks=0,
        )
        act = sc.observe(20.0, pool.n_replicas)
        assert act and pool.n_replicas == 2
        futs = [pool.submit(samples["x"][i], rid=i) for i in range(8)]
        assert all(isinstance(f.result(timeout=30.0), Prediction) for f in futs)
    finally:
        pool.stop()
    assert compile_cache_stats() == pre


# ---------------------------------------------------------------------------
# Canary gate + watch/rollback (the deployer)
# ---------------------------------------------------------------------------


def _fake_swap_recorder(calls):
    def swap(tags):
        calls.append(dict(tags))
        return {"epoch": len(calls), "compile": ZERO, "tags": dict(tags)}

    return swap


def test_deployer_watch_rollback_and_confirm():
    """Pure watch-window mechanics against a recording swap_fn: regression
    beyond rollback_db rolls the previous tags back; a clean window
    confirms; no watch -> observe is a no-op."""
    cfg = _tiny_cfg(watch_ticks=2, rollback_db=1.0)
    calls: list = []
    dep = Deployer(cfg, "unused_wd", swap_fn=_fake_swap_recorder(calls))
    assert dep.observe_served(-10.0) is None  # no active watch
    dep.deploy({"hdce": "hdce_last"}, {"hdce": "hdce_best"}, ref_db=-12.0)
    assert calls == [{"hdce": "hdce_last"}] and dep.watching()
    # served parity regressed >1 dB against the canary reference: roll back
    rec = dep.observe_served(-10.5)
    assert rec["action"] == "rollback" and calls[-1] == {"hdce": "hdce_best"}
    assert not dep.watching()
    # clean window: confirmation after watch_ticks
    dep.deploy({"hdce": "hdce_last"}, {"hdce": "hdce_best"}, ref_db=-12.0)
    assert dep.observe_served(-12.1) is None
    rec = dep.observe_served(None)  # tick without a measurement still counts
    assert rec["action"] == "deploy_confirmed" and not dep.watching()
    # dry-run deployer never swaps
    calls.clear()
    dry = Deployer(cfg, "unused_wd", swap_fn=_fake_swap_recorder(calls), dry_run=True)
    rec = dry.deploy({"hdce": "x"}, {"hdce": "y"})
    assert rec["skipped"] == "dry_run" and calls == [] and not dry.watching()


@pytest.mark.slow
def test_canary_gates_on_probe_sets(ctl_env):
    """The canary evaluates candidate vs live through the real fused serving
    forward on held-out probes: a relaxed gate passes the fine-tuned
    candidate; an impossible min-gain fails it (and nothing swaps either
    way). Slow lane: each canary compiles several offline forwards."""
    cfg, wd, engine, *_ = ctl_env
    ft = finetune_trunk(cfg, wd, scenario=1, drift_step=3)
    calls: list = []
    relaxed = dataclasses.replace(
        cfg, control=dataclasses.replace(cfg.control, min_gain_db=-50.0, tol_db=50.0)
    )
    dep = Deployer(
        relaxed, wd, swap_fn=_fake_swap_recorder(calls),
        live_hdce_vars=engine.live_vars()[0], clf_vars=engine.live_vars()[1],
    )
    rep = dep.canary(ft["tag"], scenario=1, drift_step=3)
    assert rep["passed"] is True and calls == []
    assert set(rep["base_probes"]) == {"0", "1", "2"}
    assert rep["drifted_probes"]["live_db"] is not None
    strict = dataclasses.replace(
        cfg, control=dataclasses.replace(cfg.control, min_gain_db=1e9)
    )
    dep2 = Deployer(
        strict, wd, swap_fn=_fake_swap_recorder(calls),
        live_hdce_vars=engine.live_vars()[0], clf_vars=engine.live_vars()[1],
    )
    rep2 = dep2.canary(ft["tag"], scenario=1, drift_step=3)
    assert rep2["passed"] is False and calls == []


# ---------------------------------------------------------------------------
# Controller loop
# ---------------------------------------------------------------------------


class _FakePoller:
    """Scripted metrics feed + recording levers for deterministic controller
    tests (no serving, no jax)."""

    def __init__(self, snapshots):
        self.snapshots = list(snapshots)
        self.i = 0
        self.swaps: list = []
        self.scales: list = []
        self.replicas = 1

    def metrics(self):
        m = dict(self.snapshots[min(self.i, len(self.snapshots) - 1)])
        m["replicas"] = self.replicas  # a real pool reports its post-scale size
        self.i += 1
        return m

    def swap(self, tags):
        self.swaps.append(dict(tags))
        return {"epoch": len(self.swaps), "compile": ZERO, "tags": dict(tags)}

    def scale(self, n):
        self.scales.append(n)
        self.replicas = n
        return {"replicas": n}


def _snap(conf_by_scen, n_per=20, tick=0, depth=0.0, replicas=1):
    """One cumulative metrics snapshot: per-scenario counts/conf_sums grow
    by n_per each tick at the given window means."""
    per = {
        s: {
            "n": n_per * (tick + 1),
            "conf_sum": round(sum(conf_by_scen[s][: tick + 1]) * n_per, 4),
        }
        for s in conf_by_scen
    }
    return {
        "per_scenario": per,
        "queue_depth_now": depth,
        "replicas": replicas,
        "slo": None,
        "dispatch": {"routed_rows": 0, "overflow_rows": 0},
    }


def test_controller_dry_run_detects_and_reports_without_acting(tmp_path):
    """Windowed confidence means from successive metric polls drive the
    detectors; in dry-run the drift_event fires and the adapt decision is
    reported with skipped="dry_run" — no fine-tune, no swap, no scale."""
    cfg = _tiny_cfg(dry_run=True, debounce=2)
    ticks = 30
    conf = {
        "0": [0.9] * ticks,
        "1": [0.9] * 8 + [0.55] * (ticks - 8),  # scenario 1 drifts at tick 8
        "2": [0.88] * ticks,
    }
    poller = _FakePoller([_snap(conf, tick=t) for t in range(ticks)])
    ctrl = FleetController(cfg, str(tmp_path), poller, drift_step_hint=3)
    events = []
    for _ in range(ticks):
        events.extend(ctrl.tick()["events"])
    drift = [e for e in events if e.get("signal") == "confidence"]
    assert len(drift) == 1 and drift[0]["scenario"] == 1
    adapt = [e for e in events if e.get("action") == "adapt"]
    assert adapt and adapt[0]["skipped"] == "dry_run" and adapt[0]["scenario"] == 1
    assert poller.swaps == [] and poller.scales == []
    # stationary scenarios never fired
    assert all(e["scenario"] == 1 for e in drift)


def test_controller_autoscales_on_queue_depth(tmp_path):
    cfg = _tiny_cfg(
        autoscale=True, max_replicas=2, queue_high=8.0, queue_low=0.5,
        scale_debounce=2, cooldown_ticks=1,
    )
    conf = {"0": [0.9] * 10, "1": [0.9] * 10, "2": [0.9] * 10}
    snaps = [_snap(conf, tick=t, depth=(30.0 if t >= 2 else 0.0)) for t in range(10)]
    poller = _FakePoller(snaps)
    ctrl = FleetController(cfg, str(tmp_path), poller)
    for _ in range(10):
        ctrl.tick()
    assert poller.scales == [2]  # scaled up once, then capped at max


@pytest.mark.slow
def test_controller_full_adapt_pipeline_in_process(ctl_env):
    """The closed loop end to end on the live tiny engine: a fired detector
    drives finetune -> canary (relaxed gate) -> explicit-tag hot-swap on the
    REAL engine -> watch window -> confirm; the serving path sees zero
    compiles across the swap. Slow lane: fine-tune + canary compile."""
    cfg, wd, engine, samples, *_ = ctl_env
    relaxed = dataclasses.replace(
        cfg, control=dataclasses.replace(
            cfg.control, min_gain_db=-50.0, tol_db=50.0, watch_ticks=1,
        ),
    )
    pool = ReplicaPool(engine, replicas=1).start()
    epoch_before = engine.swap_epoch
    try:
        ctrl = FleetController(
            relaxed, wd, PoolPoller(pool, engine, wd), engine=engine, drift_step_hint=3
        )
        # drive the detector directly (deterministic; traffic-driven
        # detection is covered by the dry-run test and the dryrun artifact)
        for _ in range(10):
            ctrl.monitor.observe(1, "confidence", 0.9)
        for _ in range(10):
            ctrl.monitor.observe(1, "confidence", 0.4)
        assert ctrl.monitor.active() == [(1, "confidence")]
        out = ctrl.tick()
        adapted = [e for e in out["events"] if e.get("action") == "adapted"]
        assert adapted, out["events"]
        rec = adapted[0]
        assert rec["finetune"]["tag"] == "hdce_last"
        assert rec["canary"]["passed"] is True
        assert rec["deploy"]["swap"]["tags"]["hdce"] == "hdce_last"
        assert rec["deploy"]["swap"]["compile"] == ZERO
        assert engine.swap_epoch == epoch_before + 1
        # detectors re-armed post-deploy
        assert ctrl.monitor.active() == []
        # traffic still serves, compile-free, on the adapted checkpoint
        from qdml_tpu.utils.compile_cache import compile_cache_stats

        pre = compile_cache_stats()
        futs = [pool.submit(samples["x"][i], rid=i) for i in range(8)]
        assert all(isinstance(f.result(timeout=30.0), Prediction) for f in futs)
        assert compile_cache_stats() == pre
        # watch window: one clean tick confirms the deploy
        assert ctrl.deployer.watching()
        ctrl.observe_parity(1, rec["canary"]["drifted_probes"]["cand_db"])
        out2 = ctrl.tick()
        confirm = [e for e in out2["events"] if e.get("action") == "deploy_confirmed"]
        assert confirm
    finally:
        pool.stop()
        # restore the fixture engine's original checkpoint for later tests
        engine.swap_from_workdir(wd, tags={"hdce": "hdce_best"})


# ---------------------------------------------------------------------------
# Traffic-side drift injection (loadgen --drift-at)
# ---------------------------------------------------------------------------


def test_make_request_samples_drift_partition():
    cfg = _tiny_cfg()
    base = make_request_samples(cfg, 12)
    mixed = make_request_samples(cfg, 12, drift_at=6, drift_step=4, drift_scenario=1)
    # pre-drift prefix is bit-identical to the stationary stream
    np.testing.assert_array_equal(base["x"][:6], mixed["x"][:6])
    np.testing.assert_array_equal(base["indicator"][:6], mixed["indicator"][:6])
    # post-drift: the mix shifts toward the drifting family...
    post = mixed["indicator"][6:]
    assert (post == 1).sum() >= 3
    # ...and the drifting family's channels actually changed
    drift_rows = [i for i in range(6, 12) if mixed["indicator"][i] == 1]
    base_all = make_request_samples(cfg, 12, drift_at=6, drift_step=0)
    np.testing.assert_array_equal(base_all["x"], base["x"])  # step 0 = stationary
    changed = [
        i for i in drift_rows
        if base["indicator"][i] == 1 and not np.array_equal(base["x"][i], mixed["x"][i])
    ]
    same_scen_rows = [i for i in drift_rows if base["indicator"][i] == 1]
    assert changed == same_scen_rows and same_scen_rows  # drifted bits differ
    with pytest.raises(ValueError, match="drift_scenario"):
        make_request_samples(cfg, 8, drift_at=0, drift_step=1, drift_scenario=9)


@pytest.mark.slow
def test_loadgen_drift_windows_and_external_pool(ctl_env, tmp_path):
    """--drift-at mid-run: the summary grows the drift block and pre/post
    windows; attaching to an external pool keeps the caller's pool running
    and gates compiles over the traffic window only. Slow lane: one full
    loadgen run + one offline-reference compile."""
    from qdml_tpu.config import override
    from qdml_tpu.telemetry import run_manifest
    from qdml_tpu.utils.metrics import MetricsLogger

    cfg, _wd, engine, *_ = ctl_env
    cfg = override(override(cfg, "serve.drift_step", 4), "serve.drift_scenario", 1)
    pool = ReplicaPool(engine, replicas=1).start()
    path = str(tmp_path / "drift_loadgen.jsonl")
    logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
    try:
        summary = run_loadgen(
            cfg, engine, rate=2000.0, n=48, logger=logger, pool=pool, drift_at=24
        )
    finally:
        logger.close()
    # the external pool is still ours and still serving
    try:
        fut = pool.submit(np.zeros((*cfg.image_hw, 2), np.float32), rid="after")
        assert isinstance(fut.result(timeout=30.0), Prediction)
    finally:
        pool.stop()
    assert summary["drift"] == {"at": 24, "step": 4, "scenario": 1}
    w = summary["windows"]
    assert w["pre_drift"]["n"] + w["post_drift"]["n"] == summary["completed"] == 48
    assert w["pre_drift"]["nmse_db_drift_scenario"] is not None
    # zero compiles across the traffic window (the external-pool gate form)
    assert summary["compile_cache_after_warmup"] == ZERO
    assert summary["warmup"] is None  # attached mode never re-warms
    lines = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert any(l.get("kind") == "serve_summary" for l in lines)


# ---------------------------------------------------------------------------
# Socket verbs: scale + explicit-tag swap
# ---------------------------------------------------------------------------


def test_socket_scale_and_swap_tag_verbs(ctl_env):
    """The remote controller's levers over the wire: {"op": "scale"}
    resizes the pool (drain-safe), metrics reflects it, and a swap with an
    unknown pinned tag answers a typed failure without killing the server."""
    import asyncio
    import socket
    from concurrent.futures import Future

    from qdml_tpu.serve.server import serve_async

    cfg, wd, engine, samples, *_ = ctl_env
    pool = ReplicaPool(engine, replicas=1).start()
    aloop = asyncio.new_event_loop()
    t = threading.Thread(target=aloop.run_forever, daemon=True)
    t.start()
    ready: Future = Future()
    swap_fn = lambda tags=None: engine.swap_from_workdir(wd, tags=tags)  # noqa: E731
    task = asyncio.run_coroutine_threadsafe(
        serve_async(pool, "127.0.0.1", 0, ready, swap_fn=swap_fn), aloop
    )
    try:
        port = ready.result(timeout=10.0)
        with socket.create_connection(("127.0.0.1", port), timeout=10.0) as sk:
            fh = sk.makefile("rw")

            def verb(payload):
                fh.write(json.dumps(payload) + "\n")
                fh.flush()
                return json.loads(fh.readline())

            rep = verb({"op": "scale", "replicas": 2})
            assert rep["ok"] and rep["scale"]["replicas"] == 2
            rep = verb({"op": "metrics"})
            assert rep["metrics"]["replicas"] == 2
            assert "per_scenario" in rep["metrics"]
            rep = verb({"op": "scale", "replicas": 1})
            assert rep["ok"] and rep["scale"]["replicas"] == 1
            rep = verb({"op": "scale"})  # missing replicas: typed error
            assert rep["ok"] is False and rep["reason"].startswith("bad_request")
            rep = verb({"op": "swap", "tags": {"hdce": "hdce_nope"}})
            assert rep["ok"] is False and "pinned tag" in rep["reason"]
            rep = verb({"op": "swap", "tags": "notamap"})
            assert rep["ok"] is False and "str->str" in rep["reason"]
            # server survives: a real request round-trips
            rep = verb({"id": 1, "x": samples["x"][0].tolist()})
            assert rep["ok"] is True
    finally:
        task.cancel()
        aloop.call_soon_threadsafe(aloop.stop)
        t.join(timeout=5.0)
        pool.stop()


# ---------------------------------------------------------------------------
# graftlint LOCK_MAP rows for the controller's shared state
# ---------------------------------------------------------------------------


def _lint_ctx(source: str, relpath: str):
    import ast

    from qdml_tpu.analysis import ModuleContext

    return ModuleContext(relpath, relpath, source, ast.parse(source))


@pytest.mark.parametrize(
    "relpath,cls,attr,lock",
    [
        ("qdml_tpu/control/drift.py", "DriftMonitor", "_windows", "_lock"),
        ("qdml_tpu/control/autoscale.py", "Autoscaler", "_target", "_lock"),
        ("qdml_tpu/control/deploy.py", "Deployer", "_watch", "_lock"),
        ("qdml_tpu/serve/server.py", "ReplicaPool", "_replicas", "_pool_lock"),
    ],
)
def test_lock_map_covers_controller_state(relpath, cls, attr, lock):
    """Inline fixture positives/negatives per guarded field: an unlocked
    touch of the controller's shared state is a finding under the mapped
    path; the locked twin is clean; an unmapped path is out of scope."""
    from qdml_tpu.analysis.rules import rule_serve_lock_discipline

    src = textwrap.dedent(
        f"""
        import threading

        class {cls}:
            def __init__(self):
                self.{attr} = {{}}          # __init__ exempt
                self.{lock} = threading.Lock()

            def locked(self):
                with self.{lock}:
                    return len(self.{attr})

            def unlocked(self):
                return self.{attr}
        """
    )
    findings = rule_serve_lock_discipline(_lint_ctx(src, relpath))
    assert len(findings) == 1
    assert findings[0].context == f"{cls}.unlocked"
    assert attr in findings[0].message and lock in findings[0].message
    assert rule_serve_lock_discipline(_lint_ctx(src, "qdml_tpu/other.py")) == []


def test_repo_gate_stays_clean_on_control_package():
    """The controller modules themselves pass the extended lock rule (the
    real enforcement is the repo lint gate; this pins the three files the
    LOCK_MAP newly names)."""
    from qdml_tpu.analysis.rules import rule_serve_lock_discipline

    for relpath in (
        "qdml_tpu/control/drift.py",
        "qdml_tpu/control/autoscale.py",
        "qdml_tpu/control/deploy.py",
        "qdml_tpu/serve/server.py",
    ):
        src = open(relpath).read()
        assert rule_serve_lock_discipline(_lint_ctx(src, relpath)) == [], relpath
