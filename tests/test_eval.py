"""Eval harness: routing ops, SNR sweep structure and baseline sanity."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from qdml_tpu.config import DataConfig, EvalConfig, ExperimentConfig, ModelConfig, TrainConfig
from qdml_tpu.eval import run_snr_sweep, save_results_json
from qdml_tpu.ops import one_hot_dispatch, select_expert
from qdml_tpu.train.hdce import init_hdce_state
from qdml_tpu.train.qsc import init_sc_state


def test_select_expert_and_one_hot_agree():
    rng = np.random.default_rng(0)
    stacked = jnp.asarray(rng.standard_normal((3, 8, 5)).astype(np.float32))
    logp = jnp.asarray(rng.standard_normal((8, 3)).astype(np.float32))
    pred = jnp.argmax(logp, -1)
    a = select_expert(stacked, pred)
    b = one_hot_dispatch(stacked, logp)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    for i in range(8):
        np.testing.assert_allclose(np.asarray(a[i]), np.asarray(stacked[int(pred[i]), i]))


def test_routing_variants_agree_property():
    """Property test over random shapes/dtypes: the gather dispatch and the
    one-hot einsum dispatch are the same function for ANY in-range ids —
    including bf16 (0/1 masks and a single-nonzero sum are exact in bf16)
    and S > 3 (the serving engine is not tied to the reference's 3 experts)."""
    rng = np.random.default_rng(42)
    for s, b, d in ((2, 4, 3), (3, 8, 5), (5, 16, 7), (7, 3, 2)):
        for dtype in (jnp.float32, jnp.bfloat16):
            stacked = jnp.asarray(rng.standard_normal((s, b, d)), dtype=dtype)
            logp = jnp.asarray(rng.standard_normal((b, s)), dtype=dtype)
            pred = jnp.argmax(logp, -1)
            a = select_expert(stacked, pred)
            o = one_hot_dispatch(stacked, logp)
            assert a.dtype == stacked.dtype and o.dtype == stacked.dtype
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), np.asarray(o, np.float32)
            )
            # and both really picked row pred[i] of expert slice
            for i in range(b):
                np.testing.assert_array_equal(
                    np.asarray(a[i], np.float32),
                    np.asarray(stacked[int(pred[i]), i], np.float32),
                )


def test_select_expert_clips_out_of_range_ids():
    """Corrupted ids clip to the nearest valid expert — identically under
    eager numpy semantics (where negatives would WRAP) and under jit (where
    XLA clamps), so the two paths can never diverge."""
    stacked = jnp.arange(3 * 2 * 4, dtype=jnp.float32).reshape(3, 2, 4)
    pred = jnp.asarray([5, -4])  # above range, below range
    eager = select_expert(stacked, pred)
    jitted = jax.jit(select_expert)(stacked, pred)
    np.testing.assert_array_equal(np.asarray(eager[0]), np.asarray(stacked[2, 0]))
    np.testing.assert_array_equal(np.asarray(eager[1]), np.asarray(stacked[0, 1]))
    np.testing.assert_array_equal(np.asarray(eager), np.asarray(jitted))


def _sweep_cfg():
    return ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=16),
        train=TrainConfig(batch_size=16, n_epochs=1),
        eval=EvalConfig(snr_grid=(5.0, 15.0), test_len=60, batch_size=30),
    )


def test_snr_sweep_structure(tmp_path):
    cfg = _sweep_cfg()
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    sc_vars = {"params": sc_state.params}
    qcfg = dataclasses.replace(cfg, quantum=dataclasses.replace(cfg.quantum, n_qubits=4, n_layers=2))
    _, qsc_state = init_sc_state(qcfg, quantum=True, steps_per_epoch=4)
    qsc_vars = {"params": qsc_state.params}

    from qdml_tpu.utils.metrics import MetricsLogger

    logger = MetricsLogger(str(tmp_path / "eval.metrics.jsonl"), echo=False)
    results = run_snr_sweep(qcfg, hdce_vars, sc_vars, qsc_vars, logger=logger)
    logger.close()
    assert results["snr"] == [5.0, 15.0]

    # line-level provenance: one JSONL row per SNR with every curve and acc
    import json

    with open(tmp_path / "eval.metrics.jsonl") as fh:
        rows = [json.loads(ln) for ln in fh]
    assert [r["snr_db"] for r in rows] == [5.0, 15.0]
    for r, i in zip(rows, range(2)):
        assert r["n_samples"] == 60.0
        for curve in ("ls", "mmse", "mmse_oracle", "hdce_classical", "hdce_quantum"):
            assert r[f"nmse_db_{curve}"] == results["nmse_db"][curve][i]
        assert r["acc_classical"] == results["acc"]["classical"][i]
    for curve in ("ls", "mmse", "mmse_oracle", "hdce_classical", "hdce_quantum"):
        assert len(results["nmse_db"][curve]) == 2
        assert np.isfinite(results["nmse_db"][curve]).all()
    # MMSE beats LS at both SNRs; the oracle-prior MMSE beats the generic one;
    # LS improves with SNR
    assert results["nmse_db"]["mmse"][0] < results["nmse_db"]["ls"][0]
    assert results["nmse_db"]["mmse_oracle"][0] < results["nmse_db"]["mmse"][0]
    assert results["nmse_db"]["ls"][1] < results["nmse_db"]["ls"][0]
    for key in ("classical", "quantum"):
        assert len(results["acc"][key]) == 2
        assert all(0.0 <= a <= 1.0 for a in results["acc"][key])

    path = save_results_json(results, str(tmp_path))
    assert (tmp_path / "quantum_classical_comparison.json").exists()


def test_sweep_without_quantum_checkpoint():
    """Graceful fallback when no quantum classifier exists (Test.py:81-86)."""
    cfg = _sweep_cfg()
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    results = run_snr_sweep(cfg, hdce_vars, {"params": sc_state.params}, None)
    assert "hdce_quantum" not in results["nmse_db"]
    assert "quantum" not in results["acc"]
    assert "dce" not in results["nmse_db"]  # no DCE checkpoint -> no curve


def test_sweep_step_expert_parallel_matches_unsharded():
    """Fed-sharded eval: the all-hypotheses trunk pass with trunk weights
    sharded over a (fed=3, data=2) mesh produces the same sums as the
    unsharded step — expert parallelism as a sharding annotation, the eval
    twin of test_parallel.py::test_federated_step_matches_single_device."""
    from qdml_tpu.data.baselines import beam_delay_profile
    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.eval.sweep import make_sweep_step
    from qdml_tpu.parallel.federated import shard_hdce_vars
    from qdml_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg = _sweep_cfg()
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    sc_vars = {"params": sc_state.params}
    geom = ChannelGeometry.from_config(cfg.data)
    profile = beam_delay_profile(geom)

    step = make_sweep_step(cfg, geom, hdce_vars, sc_vars, None, profile)
    args = (jnp.asarray(0), jnp.asarray(0), jnp.float32(10.0))
    ref = jax.device_get(step(*args))

    mesh = make_mesh(MeshConfig(fed_axis=3, data_axis=2, model_axis=1))
    vars_fed = shard_hdce_vars(hdce_vars, mesh, n_scenarios=cfg.data.n_scenarios)
    # trunk weights really live fed-sharded
    stacked = [
        l
        for p, l in jax.tree_util.tree_leaves_with_path(vars_fed["params"])
        if "StackedConvP128" in str(p)
    ][0]
    assert "fed" in str(stacked.sharding.spec)
    step_ep = make_sweep_step(
        cfg, geom, vars_fed, sc_vars, None, profile, mesh=mesh
    )
    out = jax.device_get(step_ep(*args))
    assert set(out) == set(ref)
    for k in ref:
        np.testing.assert_allclose(out[k], ref[k], rtol=2e-5, atol=1e-6)


def test_sweep_with_dce_baseline():
    """The monolithic-DCE control curve appears when dce_vars are passed and
    is a plain un-routed estimate (same key scheme as the other curves)."""
    from qdml_tpu.train.dce import init_dce_state

    cfg = _sweep_cfg()
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    _, dce_state = init_dce_state(cfg, 4)
    dce_vars = {"params": dce_state.params, "batch_stats": dce_state.batch_stats}
    results = run_snr_sweep(
        cfg, hdce_vars, {"params": sc_state.params}, None, dce_vars=dce_vars
    )
    assert len(results["nmse_db"]["dce"]) == len(results["snr"])
    # untrained nets are far above the classical baselines; the curve just
    # has to be finite and per-SNR
    import math

    assert all(math.isfinite(v) for v in results["nmse_db"]["dce"])


def test_loss_curves_roundtrip(tmp_path):
    """Loss-curve post-processing: JSONL epoch records -> figure + JSON twin."""
    import json

    from qdml_tpu.eval.loss_curves import (
        create_loss_curve_plot,
        parse_curve_spec,
        read_loss_history,
    )

    p = tmp_path / "m.jsonl"
    with open(p, "w") as fh:
        for e, loss in enumerate([1.0, 0.5, 0.25]):
            fh.write(json.dumps({"epoch": e, "train_loss": loss}) + "\n")
            fh.write(json.dumps({"step": e * 10, "loss": loss}) + "\n")  # batch rec
    assert read_loss_history(str(p)) == [1.0, 0.5, 0.25]
    spec = parse_curve_spec(f"CNN:{p},QML 4q:{p}")
    assert [s[0] for s in spec] == ["CNN", "QML 4q"]
    out = create_loss_curve_plot(
        [(label, read_loss_history(path)) for label, path in spec], str(tmp_path)
    )
    assert out is None or (tmp_path / "Loss_Curve.png").exists()
    with open(tmp_path / "loss_curves.json") as fh:
        assert json.load(fh)["CNN"] == [1.0, 0.5, 0.25]


def test_results_markdown_table():
    from qdml_tpu.eval.report import results_markdown_table

    results = {
        "snr": [5.0, 15.0],
        "nmse_db": {
            "ls": [-2.3, -12.3],
            "mmse": [-6.8, -13.5],
            "dce": [-7.5, -14.0],
            "hdce_classical": [-10.0, -16.0],
        },
        "acc": {"classical": [0.8, 0.95]},
    }
    table = results_markdown_table(results)
    assert "| LS | -2.3 | -12.3 | -2.2 / -12 |" in table
    # beyond-reference curve: labeled, with no published value to compare to
    assert "| DCE (monolithic) | -7.5 | -14.0 | — |" in table
    assert "accuracy (classical SC)" in table
    assert table.count("\n") >= 5


def test_reconcile_quantum_cfg():
    from qdml_tpu.config import ExperimentConfig
    from qdml_tpu.train.checkpoint import reconcile_quantum_cfg

    cfg = ExperimentConfig()
    assert reconcile_quantum_cfg(cfg, {}) is cfg  # no meta: unchanged
    out = reconcile_quantum_cfg(
        cfg, {"quantum": {"n_qubits": 4, "input_norm": True}}
    )
    assert out.quantum.n_qubits == 4 and out.quantum.input_norm is True
    assert out.quantum.n_layers == cfg.quantum.n_layers  # untouched field

    # backend is an execution-strategy knob, not architecture: the eval
    # config wins even when the checkpoint recorded a different one (a
    # 'sharded'-trained checkpoint must be evaluable single-host; ADVICE r2)
    out = reconcile_quantum_cfg(
        cfg, {"quantum": {"n_qubits": 4, "backend": "sharded"}}
    )
    assert out.quantum.backend == cfg.quantum.backend
    assert out.quantum.n_qubits == 4


def test_snr_scan_matches_per_batch_loop():
    """The scanned per-SNR sweep accumulates exactly what the per-batch
    dispatch loop would (same generation indices, same accumulation order)."""
    from qdml_tpu.data.baselines import beam_delay_profile
    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.eval.sweep import make_snr_scan, make_sweep_step

    cfg = _sweep_cfg()
    geom = ChannelGeometry.from_config(cfg.data)
    model, state = init_hdce_state(cfg, steps_per_epoch=1)
    hdce_vars = {"params": state.params, "batch_stats": state.batch_stats}
    sc_model, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=1)
    sc_vars = {"params": sc_state.params}
    step = make_sweep_step(cfg, geom, hdce_vars, sc_vars, None, beam_delay_profile(geom))

    n_batches = cfg.eval.test_len // cfg.eval.batch_size
    start = jnp.asarray(cfg.data.data_len * 3)
    snr = jnp.float32(5.0)
    sums: dict = {}
    for b in range(n_batches):
        out = step(start, jnp.asarray(b * cfg.eval.batch_size), snr)
        for k, v in out.items():
            sums[k] = sums.get(k, 0.0) + float(v)

    scanned = make_snr_scan(cfg, step, n_batches)(start, snr)
    for k, v in sums.items():
        np.testing.assert_allclose(scanned[k], v, rtol=1e-6)
