"""Quantum simulator: analytic gate goldens, independent numpy reference,
tensor/dense path equivalence, differentiability."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qdml_tpu.quantum import (
    ansatz_unitary,
    apply_1q,
    apply_cnot,
    apply_ry,
    apply_rz,
    expvals_z,
    gate_h,
    ring_cnot_perm,
    run_circuit,
    zero_state,
)
from qdml_tpu.utils.complexops import CArr

# ---------------------------------------------------------------------------
# Independent numpy reference simulator (dense complex matrices, MSB-first)
# ---------------------------------------------------------------------------


def np_ry(t):
    c, s = np.cos(t / 2), np.sin(t / 2)
    return np.array([[c, -s], [s, c]], dtype=np.complex128)


def np_rz(t):
    return np.diag([np.exp(-1j * t / 2), np.exp(1j * t / 2)])


def np_on_wire(u, q, n):
    m = np.eye(1, dtype=np.complex128)
    for i in range(n):
        m = np.kron(m, u if i == q else np.eye(2))
    return m


def np_cnot(c, t, n):
    dim = 2**n
    m = np.zeros((dim, dim), dtype=np.complex128)
    for x in range(dim):
        cbit = (x >> (n - 1 - c)) & 1
        y = x ^ (cbit << (n - 1 - t))
        m[y, x] = 1.0
    return m


def np_reference_circuit(angles, weights, n, n_layers):
    psi = np.zeros(2**n, dtype=np.complex128)
    psi[0] = 1.0
    for q in range(n):
        psi = np_on_wire(np_ry(angles[q]), q, n) @ psi
    for l in range(n_layers):
        for q in range(n):
            psi = np_on_wire(np_ry(weights[l, q, 0]), q, n) @ psi
            psi = np_on_wire(np_rz(weights[l, q, 1]), q, n) @ psi
        for c in range(n - 1):
            psi = np_cnot(c, c + 1, n) @ psi
        psi = np_cnot(n - 1, 0, n) @ psi
    probs = np.abs(psi) ** 2
    bits = (np.arange(2**n)[:, None] >> (n - 1 - np.arange(n))[None, :]) & 1
    return probs @ (1.0 - 2.0 * bits)


# ---------------------------------------------------------------------------
# Analytic gate goldens
# ---------------------------------------------------------------------------


def test_ry_on_zero():
    """RY(t)|0> = cos(t/2)|0> + sin(t/2)|1>, <Z> = cos t."""
    t = 0.7
    psi = apply_ry(zero_state(1), 1, 0, jnp.float32(t))
    np.testing.assert_allclose(psi.to_numpy(), [np.cos(t / 2), np.sin(t / 2)], rtol=1e-6)
    np.testing.assert_allclose(expvals_z(psi, 1), [np.cos(t)], rtol=1e-5)


def test_rz_phase():
    """RZ on |+> rotates the relative phase."""
    t = 1.1
    psi = apply_1q(zero_state(1), 1, 0, gate_h())
    psi = apply_rz(psi, 1, 0, jnp.float32(t))
    expected = np.array([np.exp(-1j * t / 2), np.exp(1j * t / 2)]) / np.sqrt(2)
    np.testing.assert_allclose(psi.to_numpy(), expected, rtol=1e-6, atol=1e-7)


def test_cnot_truth_table():
    for c, t, x, y in [(0, 1, 0b10, 0b11), (0, 1, 0b11, 0b10), (1, 0, 0b01, 0b11)]:
        re = jnp.zeros(4).at[x].set(1.0)
        psi = apply_cnot(CArr(re, jnp.zeros(4)), 2, c, t)
        assert float(psi.re[y]) == 1.0


def test_bell_state():
    """H(0); CNOT(0,1) -> (|00> + |11>)/sqrt(2)."""
    psi = apply_1q(zero_state(2), 2, 0, gate_h())
    psi = apply_cnot(psi, 2, 0, 1)
    np.testing.assert_allclose(
        psi.to_numpy(), np.array([1, 0, 0, 1]) / np.sqrt(2), rtol=1e-6, atol=1e-7
    )


def test_ring_perm_matches_sequential_cnots():
    n = 4
    rng = np.random.default_rng(3)
    v = rng.standard_normal(2**n) + 1j * rng.standard_normal(2**n)
    v /= np.linalg.norm(v)
    psi = CArr.from_numpy(v)
    seq = psi
    for c in range(n - 1):
        seq = apply_cnot(seq, n, c, c + 1)
    seq = apply_cnot(seq, n, n - 1, 0)
    ringed = CArr(psi.re[jnp.asarray(ring_cnot_perm(n))], psi.im[jnp.asarray(ring_cnot_perm(n))])
    np.testing.assert_allclose(ringed.to_numpy(), seq.to_numpy(), rtol=1e-6)


# ---------------------------------------------------------------------------
# Full-circuit equivalence vs numpy reference; path equivalence
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n,layers", [(4, 3), (6, 3), (8, 2)])
def test_circuit_matches_numpy_reference(n, layers):
    rng = np.random.default_rng(n)
    angles = rng.uniform(-1, 1, (5, n)).astype(np.float32)
    weights = rng.uniform(-np.pi, np.pi, (layers, n, 2)).astype(np.float32)
    want = np.stack([np_reference_circuit(a, weights, n, layers) for a in angles])
    for backend in ("tensor", "dense", "dense_fused"):
        got = run_circuit(jnp.asarray(angles), jnp.asarray(weights), n, layers, backend)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_unitarity():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.uniform(-3, 3, (3, 5, 2)).astype(np.float32))
    u = ansatz_unitary(w, 5, 3).to_numpy()
    np.testing.assert_allclose(u @ u.conj().T, np.eye(32), atol=1e-5)


def test_norm_preserved_batched():
    rng = np.random.default_rng(1)
    angles = jnp.asarray(rng.uniform(-1, 1, (7, 6)).astype(np.float32))
    w = jnp.asarray(rng.uniform(-3, 3, (3, 6, 2)).astype(np.float32))
    ev = run_circuit(angles, w, 6, 3, "tensor")
    assert ev.shape == (7, 6)
    assert np.all(np.abs(np.asarray(ev)) <= 1.0 + 1e-5)


def test_gradients_match_finite_difference():
    n, layers = 4, 2
    rng = np.random.default_rng(2)
    angles = jnp.asarray(rng.uniform(-1, 1, (3, n)).astype(np.float32))
    w0 = rng.uniform(-1, 1, (layers, n, 2)).astype(np.float32)

    def loss(w, backend):
        return jnp.sum(run_circuit(angles, w, n, layers, backend) ** 2)

    for backend in ("tensor", "dense"):
        g = jax.grad(lambda w: loss(w, backend))(jnp.asarray(w0))
        eps = 1e-3
        idx = (1, 2, 0)
        wp, wm = w0.copy(), w0.copy()
        wp[idx] += eps
        wm[idx] -= eps
        fd = (float(loss(jnp.asarray(wp), backend)) - float(loss(jnp.asarray(wm), backend))) / (
            2 * eps
        )
        np.testing.assert_allclose(float(g[idx]), fd, rtol=5e-2, atol=1e-3)


def test_jit_and_vmap_compose():
    n, layers = 6, 3
    rng = np.random.default_rng(4)
    angles = jnp.asarray(rng.uniform(-1, 1, (4, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(-1, 1, (layers, n, 2)).astype(np.float32))
    f = jax.jit(lambda a, w: run_circuit(a, w, n, layers, "dense"))
    np.testing.assert_allclose(
        np.asarray(f(angles, w)),
        np.asarray(run_circuit(angles, w, n, layers, "tensor")),
        rtol=1e-4,
        atol=1e-5,
    )


def test_auto_backend_matches_explicit():
    """backend="auto" picks a working path at both small and mid n and agrees
    with the tensor reference."""
    rng = np.random.default_rng(7)
    # At n=4 auto resolves to "dense", at n=11 to "tensor" — comparing each
    # against the OTHER explicit path keeps both assertions cross-path.
    for n, other in ((4, "tensor"), (11, "dense")):
        angles = jnp.asarray(rng.uniform(-1, 1, (2, n)).astype(np.float32))
        w = jnp.asarray(rng.uniform(0, 2 * np.pi, (1, n, 2)).astype(np.float32))
        a = run_circuit(angles, w, n, 1, "auto")
        b = run_circuit(angles, w, n, 1, other)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_resolve_backend_decisions(monkeypatch):
    """The STATIC fallback resolves by qubit count only — kernel promotion is
    the autotuner's job now (quantum/autotune.py): the old static TPU-pallas
    promotion put the bench-measured LOSING impl on the hot path (BENCH_r05
    qsc_pallas 9.76k vs qsc_dense 10.4k sps), which is exactly what the
    measured dispatch table exists to prevent."""
    import jax

    from qdml_tpu.quantum.circuits import resolve_backend

    # explicit backends pass through untouched
    assert resolve_backend("tensor", 6) == "tensor"
    assert resolve_backend("sharded", 16) == "sharded"
    # the static heuristic is platform-free: dense in the small-n regime,
    # tensor past the 2^n x 2^n unitary build's win window — and never an
    # unmeasured kernel, on ANY platform
    assert resolve_backend("auto", 6) == "dense"
    assert resolve_backend("auto", 11) == "tensor"
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert resolve_backend("auto", 6) == "dense"
    assert resolve_backend("auto", 8) == "dense"
    assert resolve_backend("auto", 10) == "dense"
    assert resolve_backend("auto", 12) == "tensor"


def test_resolve_impl_precedence(monkeypatch, tmp_path):
    """impl override > legacy backend > autotune table > static fallback."""
    from qdml_tpu.quantum import autotune
    from qdml_tpu.quantum.circuits import resolve_impl

    table = str(tmp_path / "impl.json")
    monkeypatch.setenv(autotune.ENV_TABLE, table)
    autotune.invalidate_cache()
    try:
        # no table: static fallback (dense at small n)
        assert resolve_impl("auto", "auto", 6, 3, 64) == "dense"
        # a table entry wins over the fallback
        import jax

        key = autotune.table_key(jax.default_backend(), 6, 3, 64)
        autotune.save_table(
            {key: {"best_train": "pallas", "best_fwd": "tensor"}}, table
        )
        assert resolve_impl("auto", "auto", 6, 3, 64) == "pallas"
        assert resolve_impl("auto", "auto", 6, 3, 64, mode="infer") == "tensor"
        # legacy backend wins over the table; impl wins over both
        assert resolve_impl("auto", "dense", 6, 3, 64) == "dense"
        assert resolve_impl("tensor", "dense", 6, 3, 64) == "tensor"
        # deprecated alias normalizes
        assert resolve_impl("pallas_tensor", "auto", 7, 3, 64) == "pallas_circuit"
    finally:
        autotune.invalidate_cache()


# ---------------------------------------------------------------------------
# dense_fused: gate-matrix-cached / layer-fused unitary build (PR-5 pins
# extended to the fused impl — values AND grads, f32 and bf16, whole window)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n,layers", [(2, 1), (4, 2), (6, 3), (8, 2), (10, 1)])
def test_fused_ansatz_unitary_matches_unfused(n, layers):
    """fused_ansatz_unitary (one vectorized trig shot + layer-batched real
    kron + cached z_signs phase einsum) == the per-gate kron chain, across
    the whole dense win window."""
    from qdml_tpu.quantum import fused_ansatz_unitary, fused_layer_unitaries

    rng = np.random.default_rng(n * 10 + layers)
    w = jnp.asarray(rng.uniform(-np.pi, np.pi, (layers, n, 2)).astype(np.float32))
    want = ansatz_unitary(w, n, layers).to_numpy()
    got = fused_ansatz_unitary(w, n, layers).to_numpy()
    np.testing.assert_allclose(got, want, atol=2e-6)
    # per-layer: each fused layer unitary is itself unitary
    layers_u = fused_layer_unitaries(w, n, layers)
    for l in range(layers):
        u = layers_u.to_numpy()[l]
        np.testing.assert_allclose(u @ u.conj().T, np.eye(1 << n), atol=1e-5)


@pytest.mark.parametrize("n,layers", [(2, 1), (4, 3), (6, 3), (8, 2), (10, 1)])
def test_dense_fused_values_and_grads_match_dense(n, layers):
    """Values AND weight-gradients of the dense_fused impl match the unfused
    dense path over the supported window (the dispatcher may swap one for
    the other at any shape, so divergence anywhere is a silent training
    change)."""
    rng = np.random.default_rng(n)
    angles = jnp.asarray(rng.uniform(-1, 1, (5, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(0, 2 * np.pi, (layers, n, 2)).astype(np.float32))
    a = run_circuit(angles, w, n, layers, "dense")
    b = run_circuit(angles, w, n, layers, "dense_fused")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)

    def loss(w, backend):
        return jnp.sum(run_circuit(angles, w, n, layers, backend) ** 2)

    ga = jax.grad(lambda w: loss(w, "dense"))(w)
    gb = jax.grad(lambda w: loss(w, "dense_fused"))(w)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb), rtol=1e-3, atol=1e-5)


def test_dense_fused_bf16_inputs_match_dense():
    """bf16 activations (the MXU fast path feeds bf16 angles into the
    circuit): fused and unfused agree at bf16 precision, values and grads."""
    n, layers = 6, 3
    rng = np.random.default_rng(9)
    angles = jnp.asarray(rng.uniform(-1, 1, (7, n)).astype(np.float32)).astype(
        jnp.bfloat16
    )
    w = jnp.asarray(rng.uniform(0, 2 * np.pi, (layers, n, 2)).astype(np.float32))
    a = run_circuit(angles, w, n, layers, "dense")
    b = run_circuit(angles, w, n, layers, "dense_fused")
    np.testing.assert_allclose(
        np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=2e-2, atol=2e-2
    )

    def loss(w, backend):
        return jnp.sum(run_circuit(angles, w, n, layers, backend) ** 2)

    ga = jax.grad(lambda w: loss(w, "dense"))(w)
    gb = jax.grad(lambda w: loss(w, "dense_fused"))(w)
    np.testing.assert_allclose(
        np.asarray(ga, np.float32), np.asarray(gb, np.float32), rtol=5e-2, atol=5e-2
    )


def test_dense_fused_jit_vmap_and_lead_shapes():
    """dense_fused composes with jit/vmap and preserves lead shapes like
    every other impl (the dispatcher's substitutability contract)."""
    n, layers = 4, 2
    rng = np.random.default_rng(4)
    angles = jnp.asarray(rng.uniform(-1, 1, (3, 5, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(0, 2 * np.pi, (layers, n, 2)).astype(np.float32))
    f = jax.jit(lambda a, w: run_circuit(a, w, n, layers, "dense_fused"))
    out = f(angles, w)
    assert out.shape == (3, 5, n)
    want = run_circuit(angles.reshape(-1, n), w, n, layers, "dense")
    np.testing.assert_allclose(
        np.asarray(out).reshape(-1, n), np.asarray(want), rtol=1e-4, atol=1e-5
    )


def test_trajectories_p0_matches_clean_circuit():
    """p=0 twirls draw the identity every time: the trajectory path must
    reproduce the tensor backend bitwise-close, including batching."""
    from qdml_tpu.quantum.trajectories import run_circuit_trajectories

    n, layers = 4, 2
    rng = np.random.default_rng(0)
    angles = jnp.asarray(rng.uniform(-1, 1, (5, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(-3, 3, (layers, n, 2)).astype(np.float32))
    clean = run_circuit(angles, w, n, layers, "tensor")
    noisy = run_circuit_trajectories(
        angles, w, n, layers, 0.0, jax.random.PRNGKey(0), n_traj=3
    )
    np.testing.assert_allclose(np.asarray(noisy), np.asarray(clean), atol=1e-5)
    assert noisy.shape == (5, n)


def test_single_twirl_matches_depolarizing_analytics():
    """One twirl on RY(theta)|0>: E[<Z>] = (1 - 4p/3) cos(theta) — the
    depolarizing contraction (XZX = YZY = -Z, ZZZ = Z)."""
    from qdml_tpu.quantum import statevector as sv
    from qdml_tpu.quantum.trajectories import apply_random_paulis

    theta, p, n_traj = 0.7, 0.3, 4000
    psi = sv.apply_ry(sv.zero_state(1), 1, 0, jnp.float32(theta))

    def one(k):
        return sv.expvals_z(apply_random_paulis(psi, k, p, 1), 1)[0]

    keys = jax.random.split(jax.random.PRNGKey(1), n_traj)
    got = float(jnp.mean(jax.vmap(one)(keys)))
    want = (1.0 - 4.0 * p / 3.0) * np.cos(theta)
    # MC std-err ~ 1/sqrt(4000) ~ 0.016 on a bounded observable
    assert abs(got - want) < 0.05, (got, want)


def test_trajectory_noise_is_deterministic_in_key():
    from qdml_tpu.quantum.trajectories import run_circuit_trajectories

    n, layers = 3, 1
    angles = jnp.zeros((2, n), jnp.float32)
    w = jnp.ones((layers, n, 2), jnp.float32)
    a = run_circuit_trajectories(angles, w, n, layers, 0.1, jax.random.PRNGKey(7), 8)
    b = run_circuit_trajectories(angles, w, n, layers, 0.1, jax.random.PRNGKey(7), 8)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trajectory_noise_decorrelated_across_batch():
    """Identical samples in one batch must draw DIFFERENT noise: shared
    realizations would freeze the Monte-Carlo error across the batch and
    batch-aggregated estimates would not tighten with batch size."""
    from qdml_tpu.quantum.trajectories import run_circuit_trajectories

    n, layers = 3, 1
    angles = jnp.zeros((8, n), jnp.float32)  # 8 identical samples
    w = jnp.ones((layers, n, 2), jnp.float32)
    out = run_circuit_trajectories(
        angles, w, n, layers, 0.3, jax.random.PRNGKey(2), n_traj=1
    )
    assert np.unique(np.asarray(out), axis=0).shape[0] > 1


def test_trajectory_p_out_of_range_rejected():
    """ADVICE r3: p outside [0, 1] makes the Pauli-choice distribution
    invalid and jax.random.choice samples garbage silently under jit —
    the entry points must reject it eagerly."""
    import pytest

    from qdml_tpu.quantum.trajectories import run_circuit_trajectories

    n, layers = 3, 1
    angles = jnp.zeros((2, n), jnp.float32)
    w = jnp.ones((layers, n, 2), jnp.float32)
    for bad in (-0.1, 1.5, float("nan")):
        with pytest.raises(ValueError, match="must be in"):
            run_circuit_trajectories(angles, w, n, layers, bad, jax.random.PRNGKey(0), 2)
    # boundary values stay accepted
    run_circuit_trajectories(angles, w, n, layers, 0.0, jax.random.PRNGKey(0), 2)
    run_circuit_trajectories(angles, w, n, layers, 1.0, jax.random.PRNGKey(0), 2)
