# lint fixture: POSITIVE cases for the serve-path-scoped resilience rules.
# Lives under a `serve/` directory on purpose — unbounded-readline only
# applies to serve paths. Parsed only, never imported/executed.
import asyncio


async def handle_unbounded(reader, writer):
    # unbounded-readline: no timeout — one dead peer pins this connection
    # slot (and its handler task) forever
    line = await reader.readline()
    writer.write(line)


async def handle_unbounded_exactly(reader):
    # unbounded-readline: readexactly is the same hazard
    return await reader.readexactly(4)
