# lint fixture: NEGATIVE cases for the serve-path-scoped resilience rules —
# the analyzer must report NOTHING for this file. Parsed only, never
# imported/executed.
import asyncio


async def handle_bounded(reader, writer, timeout_s):
    # the sanctioned form: the await's direct operand is wait_for, which
    # bounds the read (serve/server._read_line)
    line = await asyncio.wait_for(reader.readline(), timeout_s)
    writer.write(line)


async def handle_bounded_exactly(reader, timeout_s):
    return await asyncio.wait_for(reader.readexactly(4), timeout_s)


async def non_read_await(queue):
    # awaiting anything that is not a stream read is out of scope
    return await queue.get()
