"""Fixture: unmapped-shared-state — ``_count`` is written from a spawned
thread's loop AND from the caller's thread, with no LOCK_MAP row. The
``Guarded`` twin has the identical shape but its row (passed by the test)
sanctions it; ``Solo`` is written from the caller only."""
import threading


class Racy:
    def __init__(self):
        self._count = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        self._count += 1

    def bump(self):
        self._count += 1


class Guarded:
    def __init__(self):
        self._count = 0
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        with self._lock:
            self._count += 1

    def bump(self):
        with self._lock:
            self._count += 1


class Solo:
    def __init__(self):
        self._count = 0

    def bump(self):
        self._count += 1

    def bump_again(self):
        self._count += 1
