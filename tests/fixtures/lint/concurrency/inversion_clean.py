"""Fixture twin: consistent a-before-b ordering plus SEQUENTIAL use of the
same locks — sequential acquisition (release before the next acquire) adds
no graph edge, only nesting does."""
import threading


class Ordered:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0

    def forward(self):
        with self._a:
            with self._b:
                self.n += 1

    def also_forward(self):
        with self._a:
            with self._b:
                self.n -= 1

    def sequential(self):
        # b released before a is taken: argument-evaluation order, not
        # nesting — must NOT create a b->a edge (which would fake a cycle)
        with self._b:
            x = self.n
        with self._a:
            self.n = x
