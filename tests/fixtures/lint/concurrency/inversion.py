"""Fixture: lock-order-inversion — the same two locks nested in both
orders. Two threads walking the cycle from different ends deadlock."""
import threading


class Inverted:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0

    def forward(self):
        with self._a:
            with self._b:
                self.n += 1

    def backward(self):
        with self._b:
            with self._a:
                self.n -= 1
