"""Fixture: dead-lock-map-entry — ``Here`` exists with ``_live`` guarded by
``_lock``; the test's lock map also claims a renamed attribute, a renamed
lock, and a class that no longer exists."""
import threading


class Here:
    def __init__(self):
        self._lock = threading.Lock()
        self._live = 0

    def bump(self):
        with self._lock:
            self._live += 1
