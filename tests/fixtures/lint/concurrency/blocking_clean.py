"""Fixture twin: the same blocking work, moved OUTSIDE the held region —
take the lock for the state flip only."""
import threading
import time


class Patient:
    def __init__(self):
        self._lock = threading.Lock()
        self.done = threading.Event()
        self.n = 0

    def direct(self):
        time.sleep(0.1)
        with self._lock:
            self.n += 1

    def through_helper(self):
        self._settle()
        with self._lock:
            self.n += 1

    def _settle(self):
        self.done.wait(1.0)
