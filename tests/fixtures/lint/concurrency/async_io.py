"""Fixture: sync-io-in-async — a synchronous sleep directly in an async
handler, one reached through a sync same-module helper, and the two
sanctioned shapes (awaited asyncio.sleep; run_in_executor hop). The test
presents this file under an ASYNC_SCOPED_FILES path."""
import asyncio
import time


def _sync_helper():
    time.sleep(0.01)


async def bad_handler(reader, writer):
    time.sleep(0.01)


async def bad_closure_handler(reader, writer):
    _sync_helper()


async def good_handler(reader, writer):
    await asyncio.sleep(0.01)
    loop = asyncio.get_running_loop()
    await loop.run_in_executor(None, lambda: time.sleep(0.01))
