"""Fixture: blocking-under-lock — a sleep directly inside a held region and
one reached through the same-class call closure."""
import threading
import time


class Blocky:
    def __init__(self):
        self._lock = threading.Lock()
        self.done = threading.Event()

    def direct(self):
        with self._lock:
            time.sleep(0.1)

    def through_helper(self):
        with self._lock:
            self._settle()

    def _settle(self):
        self.done.wait(1.0)
