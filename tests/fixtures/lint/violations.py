# lint fixture: POSITIVE cases — one (or two) known violations per rule.
# Parsed by tests/test_analysis.py, NEVER imported/executed (several names
# are deliberately undefined; only the AST shape matters). Excluded from the
# repo gate: qdml-tpu lint scans qdml_tpu/, scripts/, bench.py — not tests/.
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_CACHE = {}  # module-level mutable state


@jax.jit
def reads_mutable_global(x):
    # jit-mutable-global: traced read of a module dict freezes its contents
    return x + len(_CACHE)


def make_bad_train_step(model):
    # train-step-jit-audit: maker jits with no donate/static declaration
    @jax.jit
    def step(state, batch):
        return model(state, batch)

    return step


def make_bad_scan_step(fn):
    # train-step-jit-audit: the call form, also unaudited
    return jax.jit(fn)


@jax.jit
def branches_on_tracer(x):
    # tracer-branch: Python `if` on a jnp-derived local
    loss = jnp.mean(x)
    if loss > 0:
        return loss
    return -loss


@jax.jit
def loops_on_tracer(x):
    # tracer-branch: `while` directly on a jnp call
    while jnp.sum(x) > 1.0:
        x = x * 0.5
    return x


@jax.jit
def host_sync_in_step(x):
    # host-sync-hot-path: float() materializes the tracer (TypeError at best)
    return float(jnp.sum(x))


@jax.jit
def wall_clock_in_step(x):
    # wall-clock-in-jit: compiles to the trace-time timestamp
    return x * time.time()


def primary_guarded_save(params):
    # primary-only-collective: the orbax save is collective; non-primary
    # processes never join and the primary deadlocks at the barrier
    if is_primary():  # noqa: F821 — AST fixture
        save_checkpoint("w", "tag", params, {})  # noqa: F821


def early_return_then_save(params):
    # primary-only-collective: the early-return form of the same deadlock
    if not is_primary():  # noqa: F821
        return None
    save_checkpoint("w", "tag", params, {})  # noqa: F821
    return params


class BadLoop:
    def pump(self):
        # stranded-future: dequeue + future resolution with no try/finally —
        # an engine exception between the pop and set_result hangs clients
        batch, shed = self.batcher.next_batch()
        results = self.engine.infer(batch)
        for r, res in zip(batch, results):
            r.future.set_result(res)
        return True


def swallow_everything():
    # broad-except: DivergenceError (and the run's real failure) vanish here
    try:
        run_training()  # noqa: F821
    except Exception:
        return None


def swallow_interrupts():
    # broad-except: BaseException additionally eats KeyboardInterrupt
    try:
        run_training()  # noqa: F821
    except BaseException:
        return None


def pallas_loop_over_layers(x, kernel, n_layers):
    # pallas-host-loop: one kernel launch per layer, HBM round-trip between —
    # the v1 per-layer circuit shape the VMEM-resident kernel replaced
    for _ in range(n_layers):
        x = pl.pallas_call(kernel, out_shape=x)(x)
    return x


def pallas_interpret_left_on(x, kernel):
    # pallas-interpret-literal: hardcoded interpreter, TPU included
    return pl.pallas_call(kernel, out_shape=x, interpret=True)(x)


IMPORT_TIME_ARRAY = jnp.zeros((4,))  # import-time-jnp: device alloc on import


def make_k1_scan_train_step(run):
    # train-step-jit-audit: the K=1 scan-fused runner shape — the carry is
    # the whole train state, so an unaudited jit doubles its HBM footprint
    # exactly like a per-step maker's
    @jax.jit
    def step(state, seed, scen, user, idx, snrs):
        return jax.lax.scan(run, state, (idx, snrs))

    return step


def ansatz_unitary_per_gate(weights, n, n_layers):
    # gate-matrix-in-loop: one 2x2 gate matrix rebuilt per (layer, qubit) —
    # the unfused shape gate-matrix caching (fused_layer_unitaries) removes
    total = None
    for l in range(n_layers):
        u = rot_gate(weights[l, 0, 0], weights[l, 0, 1])  # noqa: F821
        total = u if total is None else total @ u
    return total


def pads_request_batch_to_bucket(x, buckets):
    # pad-to-bucket-in-serve: picks a static bucket and pads the batch into
    # it outside the sanctioned batcher path — unaccounted padding FLOPs the
    # DispatchInfo goodput/padding-waste ledger never sees
    b = pick_bucket(len(x), buckets)  # noqa: F821 — AST fixture
    xp = np.zeros((b, 4), np.float32)
    xp[: len(x)] = x
    return xp


def hammering_retry_loop(sock, payload):
    # retry-without-backoff: transient connection errors swallowed and the
    # send re-attempted immediately — no sleep anywhere in the loop, so a
    # struggling peer gets hammered at CPU speed
    for _ in range(5):
        try:
            sock.sendall(payload)
            return True
        except ConnectionResetError:
            sock = reconnect()  # noqa: F821 — AST fixture
    return False


@jax.jit
def nonzero_in_jit(x):
    # data-dependent-shape-in-jit: output length depends on runtime values
    (idx,) = jnp.nonzero(x > 0)
    return idx


@jax.jit
def unique_in_jit(ids):
    # data-dependent-shape-in-jit: jnp.unique cannot have a static shape
    return jnp.unique(ids)


@jax.jit
def where_nonzero_form_in_jit(x):
    # data-dependent-shape-in-jit: one-arg jnp.where IS nonzero
    return jnp.where(x > 0)


@jax.jit
def bool_mask_index_in_jit(x, y):
    # data-dependent-shape-in-jit: boolean-mask indexing, direct and via a
    # mask local — both lower to nonzero+gather
    direct = x[y > 0]
    mask = y > 1
    return direct, x[mask]
