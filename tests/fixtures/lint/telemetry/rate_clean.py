"""Fixture: rate shapes unwindowed-cumulative-rate must NOT flag —
windowed deltas, count-over-count ratios, and divisions by non-time
values. Expected: no findings."""

import time


class Windowed:
    def __init__(self):
        self.completed = 0
        self.slo_met = 0
        self.slo_total = 0
        self._prev = 0

    def good_windowed_delta(self, dt_s):
        # a DELTA over the window width is the sanctioned shape
        d_completed = self.completed - self._prev
        self._prev = self.completed
        return d_completed / max(dt_s, 1e-9)

    def good_count_ratio(self):
        # count over count: attainment, not a rate
        return self.slo_met / max(1, self.slo_total)

    def good_non_time_divisor(self, n_backends):
        # counter divided by a count is a share, not a rate
        return self.completed / max(1, n_backends)

    def good_time_numerator(self, t0):
        # span over count: mean latency, fine
        elapsed = time.monotonic() - t0
        return elapsed / max(1, self.slo_total)
