"""Fixture: unwindowed-cumulative-rate findings — cumulative lifetime
counters divided by wall-clock spans (the restart-garbage / long-run-inert
rate shape). Expected: exactly 3 unwindowed-cumulative-rate findings."""

import time


class Metrics:
    def __init__(self):
        self.completed = 0
        self.rows_useful = 0
        self._t0 = time.monotonic()

    def bad_direct_clock(self):
        # finding 1: counter divided by a direct span-clock expression
        return self.completed / (time.monotonic() - self._t0)

    def bad_local_span(self):
        # finding 2: counter divided by a local bound to a clock span
        elapsed = time.monotonic() - self._t0
        return self.rows_useful / elapsed

    def bad_chained_span(self, t0):
        # finding 3: one-step dataflow chain (now -> elapsed)
        now = time.perf_counter()
        elapsed = now - t0
        return self.completed / max(elapsed, 1e-9)
