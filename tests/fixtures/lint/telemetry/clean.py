# lint fixture: NEGATIVE cases for trace-in-jit-path — the analyzer must
# report NOTHING for this file. Parsed only, never imported/executed.
import jax

from qdml_tpu.telemetry.tracing import TraceContext, trace_sampled


def host_side_serve_one(batch, clock):
    # the sanctioned surface: stamping AROUND the dispatch on the host side
    # (serve/server._serve_one's shape) — not jit-reachable, not a kernel
    tr = TraceContext(batch[0].rid)
    tr.add_phase("queue_wait", clock() - batch[0].enqueue_ts)
    return tr


def host_side_sampling(rid, rate):
    # host-side sampling decision before any dispatch: fine
    return trace_sampled(rid, rate)


@jax.jit
def jitted_without_tracing(x):
    # compiled code that never touches the tracing API: fine
    return x * 2
