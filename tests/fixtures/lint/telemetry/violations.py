# lint fixture: POSITIVE cases for trace-in-jit-path — request-tracing
# construction/stamping reachable from compiled (jit or pallas) code.
# Parsed only, never imported/executed.
import jax

from qdml_tpu.telemetry.tracing import TraceContext, trace_sampled


@jax.jit
def traced_step_with_trace(x, rid):
    # trace-in-jit-path: TraceContext built inside a jitted function —
    # the stamp would freeze at trace time
    tr = TraceContext(rid)
    # trace-in-jit-path: phase stamping inside the compiled program
    tr.add_phase("compute", 0.0)
    return x


def kernel_body(x_ref, o_ref):
    # trace-in-jit-path (pallas): sampling decision inside a kernel body
    trace_sampled(3, 1.0)
    o_ref[...] = x_ref[...]


def launch(pl, x):
    return pl.pallas_call(kernel_body, out_shape=x)(x)
