"""Lint fixture: collective-outside-shardmap NEGATIVES (no findings).

Every named-axis call is reachable from a function handed to ``shard_map``
(directly or through ``functools.partial``) — including transitively through
same-module helpers, the shape ``quantum/sharded.py`` actually uses.
"""

from functools import partial

import jax


def _exchange(x):
    return jax.lax.ppermute(x, "model", [(0, 1)])


def _local(x):
    y = _exchange(x)  # transitive: still inside the region's closure
    return jax.lax.psum(y + jax.lax.axis_index("model"), "model")


def run(x, mesh):
    from jax.experimental.shard_map import shard_map

    fn = shard_map(partial(_local), mesh=mesh, in_specs=None, out_specs=None)
    return fn(x)
