"""Lint fixture: collective-outside-shardmap POSITIVES.

Lives under a ``quantum/`` path segment on purpose — the rule only scans the
mesh-sharded quantum subsystem. Each stray named-axis call below is the
multihost-deadlock shape the rule exists to catch: an axis name used where
no ``shard_map`` region binds it.
"""

from functools import partial

import jax


def _inside(x):
    # fine: reached from the shard_map region seeded in run()
    return jax.lax.psum(x, "model")


def run(x, mesh):
    from jax.experimental.shard_map import shard_map

    fn = shard_map(partial(_inside), mesh=mesh, in_specs=None, out_specs=None)
    return fn(x)


def stray_exchange(x):
    # collective-outside-shardmap: ppermute with no region binding "model"
    return jax.lax.ppermute(x, "model", [(0, 1)])


def stray_axis_query():
    # collective-outside-shardmap: axis_index outside every region
    return jax.lax.axis_index("model")
