# lint fixture: NEGATIVE cases — the legitimate twin of each violation in
# violations.py. The analyzer must report NOTHING for this file (the
# precision half of every rule's contract). Parsed only, never imported.
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_FROZEN = (1, 2, 3)  # immutable module constant: fine to close over
HOST_TABLE = np.zeros((4,))  # numpy at import time is host-only: fine


@jax.jit
def reads_immutable_global(x):
    return x + _FROZEN[0]


def make_good_train_step(model):
    # audited jit: donation declared
    @partial(jax.jit, donate_argnums=(0,))
    def step(state, batch):
        return model(state, batch)

    return step


def make_good_eval_step(model):
    # eval makers are exempt from the audit (nothing to donate)
    @jax.jit
    def step(state, batch):
        return model(state, batch)

    return step


@jax.jit
def static_flag_branch(x, probes: bool = True):
    # `if` on a static Python flag bound before jit: trace-time dispatch,
    # the documented probes=False pattern — NOT a tracer branch
    y = jnp.mean(x)
    if probes:
        return y
    return y * 2.0


@jax.jit
def device_branchless(x):
    # the jnp.where form the tracer-branch rule asks for
    loss = jnp.mean(x)
    return jnp.where(loss > 0, loss, -loss)


def host_loop_timer(step, state, batch):
    # wall-clock + float() OUTSIDE any traced function: plain host timing
    t0 = time.time()
    state, m = step(state, batch)
    return state, float(m["loss"]), time.time() - t0


def save_on_all_processes(params, primary):
    # collective on every process, host-side write guarded: the CORRECT
    # multihost shape (the inverse of primary-only-collective)
    save_checkpoint("w", "tag", params, {})  # noqa: F821 — AST fixture
    if primary:
        write_bundle_json(params)  # noqa: F821


class GoodLoop:
    def pump(self):
        # dequeue + guaranteed resolution: failures forward into every future
        batch, shed = self.batcher.next_batch()
        try:
            results = self.engine.infer(batch)
        except BaseException as e:
            for r in batch:
                r.future.set_exception(e)
            raise
        for r, res in zip(batch, results):
            r.future.set_result(res)
        return True


def pallas_single_launch(x, kernel):
    # ONE pallas_call, interpret routed through the shared config knob, the
    # layer loop INSIDE the kernel (fori_loop): the pallas-host-loop /
    # pallas-interpret-literal rules' legitimate twin
    from qdml_tpu.utils.platform import pallas_interpret

    def body(ref, out_ref):
        out_ref[:] = jax.lax.fori_loop(0, 4, lambda i, a: a * 2.0, ref[:])

    return pl.pallas_call(body, out_shape=x, interpret=pallas_interpret())(x)


def inspect_and_reraise():
    # broad catch that unconditionally re-raises: inspect-and-forward, fine
    try:
        run_training()  # noqa: F821
    except Exception as e:
        log_failure(e)  # noqa: F821
        raise


def make_k1_scan_train_step_good(run):
    # the K=1 scan runner with its carry donated: the audited twin
    @partial(jax.jit, donate_argnums=(0,))
    def step(state, seed, scen, user, idx, snrs):
        return jax.lax.scan(run, state, (idx, snrs))

    return step


def fused_layer_build(weights, n_layers, layer_unitaries):
    # gate-matrix-in-loop's legitimate twins: ALL gate trig derived in one
    # vectorized shot OUTSIDE any loop, and the loop only APPLIES the
    # precomputed per-layer unitaries (composition, no construction)
    cos_t, sin_t = jnp.cos(0.5 * weights), jnp.sin(0.5 * weights)
    total = layer_unitaries[0]
    for l in range(1, n_layers):
        total = layer_unitaries[l] @ total
    return total, cos_t, sin_t


@jax.jit
def static_shape_routing(x, y, idx):
    # data-dependent-shape-in-jit's legitimate twins: 3-arg jnp.where masks
    # VALUES at a static shape, integer gathers are shape-static, and mask
    # reductions consume the comparison without indexing by it
    mask = y > 0
    selected = jnp.where(mask, x, 0.0)
    gathered = x[idx]  # integer-array gather: static shape
    return selected, gathered, jnp.sum(mask)


def host_side_unique(ids):
    # the same ops OUTSIDE any traced function are host-side aggregation —
    # np.unique over fetched results is how eval scripts summarize
    import numpy as np

    return np.unique(np.asarray(ids))


@jax.jit
def static_size_nonzero(x, ids):
    # jax's static-size escape hatch: size= makes the output shape a literal,
    # exactly what the data-dependent-shape rule asks callers to provide
    (idx,) = jnp.nonzero(x > 0, size=4, fill_value=0)
    return idx, jnp.unique(ids, size=4, fill_value=0)


def backing_off_retry_loop(sock, payload):
    # retry-without-backoff's legitimate twin: jittered sleep between
    # attempts (the ServeClient.call shape) — the loop may retry freely
    for attempt in range(5):
        try:
            sock.sendall(payload)
            return True
        except ConnectionResetError:
            time.sleep(0.05 * (2 ** attempt))
            sock = reconnect()  # noqa: F821 — AST fixture
    return False


def giving_up_retry_loop(sock, payload):
    # ...and a handler that EXITS the loop (raise/return/break) is a
    # give-up, not a retry: nothing to back off from
    for _ in range(5):
        try:
            sock.sendall(payload)
            return True
        except ConnectionResetError:
            raise


def reads_bucket_table(n, buckets):
    # pad-to-bucket-in-serve's legitimate twins: picking a bucket WITHOUT
    # padding into it (shape-table readers, metrics labels) is fine...
    return pick_bucket(n, buckets)  # noqa: F821 — AST fixture


def fixed_scratch_fill(x):
    # ...and zeros + slice assignment WITHOUT a bucket pick is an ordinary
    # fixed-shape scratch buffer, not a request-batch pad
    scratch = np.zeros((16, 4), np.float32)
    scratch[: len(x)] = x
    return scratch
