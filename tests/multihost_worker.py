"""Subprocess worker for ``test_multihost_2proc.py`` — NOT a test module.

Runs one real HDCE training epoch through the production multi-host path
(``training_mesh`` -> ``shard_hdce_state`` -> ``make_grid_placer``) in one of
two cluster shapes, or as the matching single-process reference:

- ``dp``:  2 processes x 2 CPU devices — pure data parallelism (data=4);
  rank -1 = one process with 4 devices, same 4-wide data axis.
- ``fed``: 3 processes x 1 CPU device — federated scenario sharding ACROSS
  processes (fed=3, data=1): each rank generates and trains ONLY its own
  base station's scenario row, the shared head aggregating over Gloo; rank
  -1 = one process with 3 devices, same fed=3 mesh.

Writes the loss history as JSON so the parent test can assert the cluster
reproduces the single-process run.

Usage: python tests/multihost_worker.py MODE RANK PORT OUT_JSON
"""

import json
import os
import sys

mode = sys.argv[1]
rank = int(sys.argv[2])
port = sys.argv[3]
out_path = sys.argv[4]

NPROC = {"dp": 2, "fed": 3}[mode]
n_local = {"dp": 2, "fed": 1}[mode] if rank >= 0 else {"dp": 4, "fed": 3}[mode]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_local}"

from qdml_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402
from qdml_tpu.utils.platform import honor_platform_env  # noqa: E402

honor_platform_env()
enable_compile_cache()

import jax  # noqa: E402

if rank >= 0:
    # the production init path (selects Gloo CPU collectives on jax versions
    # that default the option to "none")
    from qdml_tpu.parallel.multihost import ensure_initialized

    ensure_initialized(
        coordinator_address=f"localhost:{port}",
        num_processes=NPROC,
        process_id=rank,
        local_device_ids=list(range(n_local)),
    )

from qdml_tpu.config import (  # noqa: E402
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from qdml_tpu.telemetry import run_manifest, set_sink  # noqa: E402
from qdml_tpu.train.hdce import train_hdce  # noqa: E402
from qdml_tpu.utils.metrics import MetricsLogger  # noqa: E402

cfg = ExperimentConfig(
    data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=40, train_split=0.8),
    model=ModelConfig(features=8),
    train=TrainConfig(batch_size=8, n_epochs=1, print_freq=1000),
    mesh=MeshConfig(fed_axis=3) if mode == "fed" else MeshConfig(),
)
# Telemetry through the production multi-host path: every rank constructs
# the manifest-headed logger and routes spans/counters into it, but only the
# primary (process 0) may create/write the file — the parent test asserts
# exactly that.
logger = MetricsLogger(
    out_path + ".metrics.jsonl",
    echo=False,
    manifest=run_manifest(cfg, argv=["multihost_worker", mode, str(rank)]),
)
set_sink(logger.telemetry)
_, history = train_hdce(cfg, logger=logger)
logger.close()
with open(out_path, "w") as fh:
    json.dump(
        {
            "mode": mode,
            "rank": rank,
            "nproc": jax.process_count(),
            "n_global_devices": len(jax.devices()),
            "train_loss": history["train_loss"],
            "val_nmse": history["val_nmse"],
        },
        fh,
    )
