"""Subprocess worker for ``test_multihost_2proc.py`` — NOT a test module.

Runs one real HDCE training epoch through the production multi-host path
(``training_mesh`` -> ``shard_hdce_state`` -> ``make_grid_placer``) either as
one rank of a genuine 2-process ``jax.distributed`` cluster (rank 0/1, two
local CPU devices each, Gloo collectives) or as the single-process reference
(rank -1, four local CPU devices — the same 4-wide data axis in one process).
Writes the loss history as JSON so the parent test can assert the two
execution modes are numerically equivalent.

Usage: python tests/multihost_worker.py RANK PORT OUT_JSON
"""

import json
import os
import sys

rank = int(sys.argv[1])
port = sys.argv[2]
out_path = sys.argv[3]

n_local = 2 if rank >= 0 else 4
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_local}"

from qdml_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402
from qdml_tpu.utils.platform import honor_platform_env  # noqa: E402

honor_platform_env()
enable_compile_cache()

import jax  # noqa: E402

if rank >= 0:
    jax.distributed.initialize(
        f"localhost:{port}", num_processes=2, process_id=rank, local_device_ids=[0, 1]
    )

from qdml_tpu.config import (  # noqa: E402
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    TrainConfig,
)
from qdml_tpu.train.hdce import train_hdce  # noqa: E402

cfg = ExperimentConfig(
    data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=40, train_split=0.8),
    model=ModelConfig(features=8),
    train=TrainConfig(batch_size=8, n_epochs=1, print_freq=1000),
)
_, history = train_hdce(cfg)
with open(out_path, "w") as fh:
    json.dump(
        {
            "rank": rank,
            "nproc": jax.process_count(),
            "n_global_devices": len(jax.devices()),
            "train_loss": history["train_loss"],
            "val_nmse": history["val_nmse"],
        },
        fh,
    )
