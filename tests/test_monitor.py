"""Flight-deck monitoring: reset-safe counter differencing, multi-window
burn-rate alerting, the scrape loop's discipline + derived events, and the
timeline renderer (qdml_tpu/telemetry/timeseries.py + burnrate.py).

All host-side — no engine, no sockets: the scraper runs against fake
pollers with a fake clock, so the windowing/alerting semantics pin exactly
(the live end-to-end path is scripts/monitor_dryrun.py's committed run).
"""

from __future__ import annotations

import random

from qdml_tpu.telemetry.burnrate import (
    BurnAlerter,
    BurnRateRule,
    burn_rate,
    render_timeline,
)
from qdml_tpu.telemetry.timeseries import (
    MonitorScraper,
    Ring,
    SnapshotDiff,
    counter_delta,
)


# ---------------------------------------------------------------------------
# counter_delta / SnapshotDiff — reset-safe differencing
# ---------------------------------------------------------------------------


def test_counter_delta_basic_and_none():
    assert counter_delta(10, 15) == (5.0, False)
    assert counter_delta(None, 7) == (7.0, False)   # first report
    assert counter_delta(None, None) == (0.0, False)
    assert counter_delta(3, 3) == (0.0, False)


def test_counter_delta_reset_clamps_and_flags():
    # restart: counter went backwards — window clamps to everything the
    # reborn counter saw, and the reset is FLAGGED, never a negative rate
    d, reset = counter_delta(100, 12)
    assert d == 12.0 and reset is True


def test_counter_delta_never_negative_across_random_restarts():
    """Property: over any monotonic-with-restarts counter trajectory, every
    window is >= 0 and resets are flagged exactly when the value drops."""
    rng = random.Random(7)
    for _trial in range(50):
        value, prev = 0.0, None
        for _step in range(200):
            if rng.random() < 0.07:
                value = float(rng.randrange(0, 5))  # restart
            else:
                value += rng.randrange(0, 20)
            d, reset = counter_delta(prev, value)
            assert d >= 0.0
            assert reset == (prev is not None and value < prev)
            prev = value


def test_snapshot_diff_resets_are_per_name():
    diff = SnapshotDiff()
    assert diff.window("a", 10) == (10.0, False)
    assert diff.window("b", 5) == (5.0, False)
    # "a" restarts; "b" keeps differencing cleanly
    assert diff.window("a", 2) == (2.0, True)
    assert diff.window("b", 9) == (4.0, False)
    assert diff.window("a", 6) == (4.0, False)  # post-reset windows are clean


def test_ring_is_bounded():
    r = Ring(cap=4)
    for i in range(10):
        r.add({"i": i})
    assert len(r) == 4
    assert [x["i"] for x in r] == [6, 7, 8, 9]
    assert r.last() == {"i": 9}


# ---------------------------------------------------------------------------
# burn-rate rules — multi-window, debounce, latch, zero-traffic
# ---------------------------------------------------------------------------


def test_burn_rate_zero_traffic_is_none_not_nan():
    assert burn_rate(0, 0, 0.01) is None
    assert burn_rate(5, 0, 0.01) is None          # no eligible traffic
    assert burn_rate(0, 100, 0.01) == 0.0
    assert burn_rate(1, 100, 0.01) == 1.0          # spending exactly budget
    assert burn_rate(2, 100, 0.01) == 2.0


def _rule(**kw):
    kw.setdefault("signal", "slo")
    kw.setdefault("budget", 0.01)
    kw.setdefault("fast_s", 2.0)
    kw.setdefault("slow_s", 6.0)
    kw.setdefault("threshold", 10.0)
    kw.setdefault("debounce", 2)
    return BurnRateRule(**kw)


def test_rule_fires_only_when_both_windows_exceed():
    """A short error spike saturates the fast window but not the slow one:
    no alert. Sustained errors push BOTH over: alert."""
    r = _rule()
    t = 0.0
    # 6s of healthy traffic fills the slow window with good evidence
    for _ in range(6):
        t += 1.0
        r.feed(t, 0, 100)
        assert r.evaluate(t) is None
    # one bad window: the fast window saturates but the slow one is still
    # diluted by the healthy history
    t += 1.0
    r.feed(t, 50, 100)
    burns = r.burns(t)
    assert burns["fast"] >= 10.0 and burns["slow"] < 10.0
    assert r.evaluate(t) is None and r.firing is False
    # sustained: errors keep coming until the slow window crosses too,
    # then debounce=2 needs two consecutive over-threshold evaluations
    fired = None
    for _ in range(10):
        t += 1.0
        r.feed(t, 50, 100)
        a = r.evaluate(t)
        if a is not None:
            fired = a
            break
    assert fired is not None and fired["state"] == "firing"
    assert fired["fast_burn"] >= 10.0 and fired["slow_burn"] >= 10.0
    assert r.fired_count == 1


def test_rule_debounce_requires_consecutive_evidence():
    r = _rule(fast_s=1.0, slow_s=1.0, debounce=3)
    t = 0.0
    # two over-threshold evaluations, then a healthy one: counter resets
    for _ in range(2):
        t += 1.0
        r.feed(t, 50, 100)
        assert r.evaluate(t) is None
    t += 1.0
    r.feed(t, 0, 100)
    assert r.evaluate(t) is None and r._pending == 0
    # three consecutive: fires on the third
    results = []
    for _ in range(3):
        t += 1.0
        r.feed(t, 50, 100)
        results.append(r.evaluate(t))
    assert results[:2] == [None, None]
    assert results[2] is not None and results[2]["state"] == "firing"


def test_rule_latches_until_both_windows_recover():
    r = _rule(fast_s=1.0, slow_s=4.0, debounce=1)
    t = 0.0
    for _ in range(4):
        t += 1.0
        r.feed(t, 50, 100)
        if r.evaluate(t) is not None:
            break
    assert r.firing
    # fast window recovers immediately; slow still holds the bad samples —
    # the alert must stay latched (no resolved transition)
    t += 1.0
    r.feed(t, 0, 100)
    assert r.evaluate(t) is None and r.firing is True
    # keep feeding healthy windows until the slow window flushes
    resolved = None
    for _ in range(8):
        t += 1.0
        r.feed(t, 0, 100)
        a = r.evaluate(t)
        if a is not None:
            resolved = a
            break
    assert resolved is not None and resolved["state"] == "resolved"
    assert r.firing is False and r.resolved_count == 1


def test_rule_zero_traffic_windows_freeze_state():
    """An idle window (no eligible traffic) is no evidence either way: it
    must not advance the debounce, fire, or resolve."""
    r = _rule(fast_s=1.0, slow_s=1.0, debounce=1)
    t = 1.0
    r.feed(t, 0, 0)
    assert r.evaluate(t) is None and r.firing is False
    # while firing, zero traffic must not resolve
    t += 1.0
    r.feed(t, 50, 100)
    assert r.evaluate(t)["state"] == "firing"
    t += 2.0  # past the windows: they now hold nothing
    assert r.evaluate(t) is None and r.firing is True


def test_alerter_for_run_scales_windows_and_slo_budget():
    a = BurnAlerter.for_run(duration_s=30.0, interval_s=0.5, slo_target=0.95)
    slo = a.rules["slo"]
    assert abs(slo.budget - 0.05) < 1e-12
    assert slo.fast_s >= 1.0 and slo.slow_s >= 3 * slo.fast_s
    assert slo.slow_s <= 3600.0
    assert set(a.rules) >= {"slo", "shed", "breaker", "quarantine", "router",
                            "stranded"}
    # stamped mark rides every transition
    r = a.rules["stranded"]
    t = 0.0
    fired = []
    for _ in range(10):
        t += 1.0
        a.feed(t, "stranded", 5, 100)
        fired += a.evaluate(t, mark="fault_seg")
    assert fired and all(x["mark"] == "fault_seg" for x in fired)


# ---------------------------------------------------------------------------
# the scraper — fake pollers, fake clock
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


class _Sink:
    active = True

    def __init__(self):
        self.records = []

    def emit(self, kind, **payload):
        self.records.append({"kind": kind, **payload})


class _ServePoller:
    """Single-host serve shapes; scripted counter evolution."""

    def __init__(self):
        self.calls = []
        self.completed = 0
        self.slo_n = 0
        self.slo_met = 0
        self.start_seq = 111
        self.uptime = 5.0

    def health(self):
        self.calls.append("health")
        return {
            "warm": True, "replicas": 2, "queue_depth": 1,
            "quarantined": [], "swap_epoch": 0,
            "uptime_s": self.uptime, "start_seq": self.start_seq,
        }

    def metrics(self):
        self.calls.append("metrics")
        return {
            "completed": self.completed,
            "shed": {}, "faults": {}, "restarts": 0,
            "slo": {"n": self.slo_n, "met": self.slo_met},
            "breaker": {"state": "closed", "fast_fails": 0,
                        "admitted": self.completed},
        }


def test_scraper_uses_only_observability_verbs_and_windows_rates():
    clk, sink, p = _Clock(), _Sink(), _ServePoller()
    s = MonitorScraper(p, sink=sink, interval_s=1.0, clock=clk)
    p.completed, p.slo_n, p.slo_met = 100, 50, 50
    assert s.scrape_once()["dt_s"] is None  # first window has no width
    clk.t += 2.0
    p.completed, p.slo_n, p.slo_met = 160, 80, 78
    p.uptime += 2.0
    rec = s.scrape_once()
    # scrape discipline: health + metrics only, never an inference verb
    assert set(p.calls) == {"health", "metrics"}
    # windowed, not lifetime: 60 completions over 2s
    assert rec["completed"] == 60.0 and rec["rps"] == 30.0
    assert rec["slo"] == {"n": 30.0, "met": 28.0, "attainment": 0.9333}
    kinds = {r["kind"] for r in sink.records}
    assert "monitor_timeseries" in kinds and "counter_reset" not in kinds


def test_scraper_restart_emits_reset_and_event_never_negative():
    clk, sink, p = _Clock(), _Sink(), _ServePoller()
    s = MonitorScraper(p, sink=sink, interval_s=1.0, clock=clk)
    p.completed, p.slo_n, p.slo_met = 500, 400, 400
    s.scrape_once()
    # process restart: counters start over, construction epoch changes
    clk.t += 1.0
    p.completed, p.slo_n, p.slo_met = 30, 20, 20
    p.start_seq, p.uptime = 222, 0.4
    rec = s.scrape_once()
    assert rec["completed"] == 30.0 and rec["rps"] >= 0.0
    assert "completed" in rec["resets"]
    resets = [r for r in sink.records if r["kind"] == "counter_reset"]
    assert {r["counter"] for r in resets} >= {"completed", "slo_n", "slo_met"}
    events = [r for r in sink.records if r["kind"] == "monitor_event"]
    assert any(e.get("event") == "backend_restart" for e in events)
    assert s.resets_total == len(resets)


def test_scraper_survives_poller_failure_as_scrape_error():
    class _Dead:
        def health(self):
            raise ConnectionRefusedError("down")

        def metrics(self):  # pragma: no cover - never reached
            return {}

    clk, sink = _Clock(), _Sink()
    s = MonitorScraper(_Dead(), sink=sink, interval_s=1.0, clock=clk)
    assert s.scrape_once() is None
    assert s.scrape_errors == 1
    evs = [r for r in sink.records if r["kind"] == "monitor_event"]
    assert any(e.get("event") == "scrape_error" for e in evs)


class _RouterPoller:
    """Fleet shapes: per-backend rows + router aggregation."""

    def __init__(self):
        self.forwarded = 0
        self.failed = 0
        self.failovers = 0
        self.ejections = 0
        self.seqs = {"b0": 1, "b1": 2}

    def health(self):
        return {
            "fleet": True, "backends": 2,
            "backends_live": 2 - (1 if self.ejections else 0),
            "queue_depth": 0, "replicas": 2, "swap_epoch": 0,
            "router": {
                "forwarded": self.forwarded,
                "failed_forwards": self.failed,
                "failovers": self.failovers,
                "ejections": self.ejections, "readmissions": 0,
            },
            "per_backend": {
                b: {"poll_ok": True, "start_seq": seq, "uptime_s": 9.0}
                for b, seq in self.seqs.items()
            },
        }

    def metrics(self):
        return {
            "completed": self.forwarded, "shed": {}, "faults": {},
            "restarts": 0, "slo": {"n": self.forwarded,
                                   "met": self.forwarded - self.failed},
            "per_backend": {},
        }


def test_scraper_router_signal_alerts_during_fault_segment_only():
    """The dryrun's paging path in miniature: healthy windows under
    'baseline' never alert; a sustained failover storm under 'fault' fires
    the router burn alert, tagged with the segment mark."""
    clk, sink, p = _Clock(), _Sink(), _RouterPoller()
    alerter = BurnAlerter(
        {"router": BurnRateRule("router", 0.02, fast_s=2.0, slow_s=6.0,
                                threshold=8.0, debounce=2)}
    )
    s = MonitorScraper(p, sink=sink, interval_s=1.0, alerter=alerter,
                       clock=clk)
    s.mark("baseline")
    for _ in range(8):
        clk.t += 1.0
        p.forwarded += 50
        s.scrape_once()
    assert len(s.alerts) == 0
    s.mark("fault")
    fired = []
    for _ in range(10):
        clk.t += 1.0
        p.forwarded += 50
        p.failed += 20
        p.failovers += 5
        rec = s.scrape_once()
        if rec["alerts"]:
            fired.append(rec)
    assert fired, "router burn alert must fire during the fault segment"
    alerts = [r for r in sink.records if r["kind"] == "monitor_alert"]
    assert alerts[0]["signal"] == "router" and alerts[0]["mark"] == "fault"
    summ = s.summary()
    assert summ["alerts"]["by_mark"].get("fault", 0) >= 1
    assert summ["alerts"]["by_mark"].get("baseline", 0) == 0
    assert summ["peak_burn"]["router"]["fast"] >= 8.0


def test_scraper_derives_ejection_event_from_router_counters():
    clk, sink, p = _Clock(), _Sink(), _RouterPoller()
    s = MonitorScraper(p, sink=sink, interval_s=1.0, clock=clk)
    s.scrape_once()
    clk.t += 1.0
    p.ejections = 1
    s.scrape_once()
    evs = [r for r in sink.records if r["kind"] == "monitor_event"]
    assert any(e.get("event") == "backend_ejected" for e in evs)


def test_scraper_detects_per_backend_restart_by_start_seq():
    clk, sink, p = _Clock(), _Sink(), _RouterPoller()
    s = MonitorScraper(p, sink=sink, interval_s=1.0, clock=clk)
    s.scrape_once()
    clk.t += 1.0
    p.seqs["b1"] = 99  # backend b1 restarted; b0 did not
    s.scrape_once()
    restarts = [
        r for r in sink.records
        if r["kind"] == "monitor_event" and r.get("event") == "backend_restart"
    ]
    assert [r["backend"] for r in restarts] == ["b1"]


# ---------------------------------------------------------------------------
# control-loop windowing (satellite: reset-safe differencing in the
# FleetController's detector feeds)
# ---------------------------------------------------------------------------


class _ObsMonitor:
    def __init__(self):
        self.observed = []

    def observe(self, scenario, metric, value):
        self.observed.append((scenario, metric, value))
        return None


def _bare_controller():
    from qdml_tpu.control.loop import FleetController

    ctl = FleetController.__new__(FleetController)
    ctl.monitor = _ObsMonitor()
    ctl.min_window = 1
    ctl._prev_scenario = {}
    ctl._prev_dispatch = {}
    ctl._prev_slo = None
    ctl._sink = _Sink()
    return ctl


def test_control_windowed_slo_reset_returns_none_and_reports():
    ctl = _bare_controller()
    assert ctl._windowed_slo({"n": 100, "met": 99}) == 0.99
    assert ctl._windowed_slo({"n": 150, "met": 148}) == 0.98
    # server restarted: cumulative counters went backwards — a naive
    # difference would be a NEGATIVE attainment; the reset-safe path
    # reports a counter_reset and yields no reading for this window
    got = ctl._windowed_slo({"n": 40, "met": 39})
    assert got is None
    resets = [r for r in ctl._sink.records if r.get("name") == "counter_reset"]
    assert resets and resets[0]["counter"] == "slo.n"
    # next window differences cleanly from the post-restart snapshot
    assert ctl._windowed_slo({"n": 80, "met": 79}) == 1.0


def test_control_window_scenarios_skips_detector_feed_on_reset():
    ctl = _bare_controller()
    ctl._window_scenarios(
        {"per_scenario": {"0": {"n": 100, "conf_sum": 90.0}}}
    )
    ctl._window_scenarios(
        {"per_scenario": {"0": {"n": 200, "conf_sum": 185.0}}}
    )
    assert ctl.monitor.observed[-1] == (0, "confidence", 0.95)
    n_obs = len(ctl.monitor.observed)
    # restart: n drops — the detector must NOT be fed a fabricated mean
    ctl._window_scenarios(
        {"per_scenario": {"0": {"n": 10, "conf_sum": 9.0}}}
    )
    assert len(ctl.monitor.observed) == n_obs
    resets = [r for r in ctl._sink.records if r.get("name") == "counter_reset"]
    assert resets and "per_scenario[0].n" in resets[0]["counter"]
    # windows never negative in the observe stream
    assert all(v >= 0 for _, _, v in ctl.monitor.observed)


# ---------------------------------------------------------------------------
# timeline rendering
# ---------------------------------------------------------------------------


def test_render_timeline_correlates_alerts_with_stack_events():
    records = [
        {"kind": "manifest", "argv": ["monitor"], "ts": 1000.0},
        {"kind": "monitor_timeseries", "ts": 1001.0, "t_s": 1.0, "seq": 1,
         "mark": "baseline", "rps": 50.0, "slo": {"n": 50, "met": 50},
         "queue_depth": 0, "replicas": 2, "backends_live": 2,
         "burn": {"slo": {"fast": 0.0, "slow": 0.0}}},
        {"kind": "monitor_event", "event": "backend_restart",
         "backend": "b1", "t_s": 1.6, "mark": "fault"},
        {"kind": "monitor_timeseries", "ts": 1002.0, "t_s": 2.0, "seq": 2,
         "mark": "fault", "rps": 20.0, "slo": {"n": 40, "met": 20},
         "queue_depth": 7, "replicas": 2, "backends_live": 1,
         "burn": {"slo": {"fast": 50.0, "slow": 12.0},
                  "router": {"fast": 30.0, "slow": 9.0}}},
        {"kind": "monitor_alert", "signal": "router", "state": "firing",
         "t_s": 2.0, "mark": "fault", "fast_burn": 30.0, "slow_burn": 9.0,
         "threshold": 8.0, "budget": 0.02, "fast_s": 2.0, "slow_s": 6.0},
        {"kind": "monitor_summary", "windows": 2, "duration_s": 2.0,
         "interval_s": 1.0, "scrape_errors": 0, "counter_resets": 1,
         "alerts": {"fired": 1, "resolved": 0,
                    "by_mark": {"fault": 1}, "by_signal": {"router": 1}},
         "peak_burn": {"router": {"fast": 30.0, "slow": 9.0}},
         "planner": {"ok": True, "n_windows": 3, "max_p99_ratio": 1.4,
                     "max_rps_err": 0.05}},
    ]
    # a sibling stack stream's event (kind=counters) merges by wall clock:
    # ts 1001.7 -> t_s 0.7 after the manifest offset... offset comes from
    # the first window (ts 1001 at t_s 1.0), so 1001.7 maps to t_s 1.7
    stack = [
        {"kind": "counters", "name": "replica_restarted", "ts": 1001.7,
         "replica": "serve-replica-0"},
        {"kind": "counters", "name": "loss", "ts": 1001.8},  # not an event
    ]
    md = render_timeline(records, extra_events=stack)
    assert "**ALERT router**" in md
    assert "backend_restart(b1)" in md
    assert "replica_restarted(serve-replica-0)" in md
    assert "loss" not in md
    # the firing alert lists the events inside its fast window
    assert "correlated events" in md
    assert "router FIRING" in md
    assert "capacity-planner validation: PASS" in md
    # segment marks label their windows
    assert "| baseline |" in md and "| fault |" in md
