"""Fleet router tier (qdml_tpu/fleet, docs/FLEET.md): balancing, ejection/
re-admission, fleet-wide dedup across failover, verb fan-out/aggregation,
the FleetPoller + controller attachment, and the backend identity block.

The backend "hosts" here are two ServeLoops over ONE warmed engine behind
two real serve_async socket front-ends — two endpoints from the router's
point of view, one warmup/compile budget from the test suite's (same tiny
shapes as tests/test_faults.py, so the persistent compile cache shares the
bucket executables). The REAL separate-process topology is the committed
dryrun's job (scripts/fleet_router_dryrun.py -> results/fleet_router/).
"""

from __future__ import annotations

import asyncio
import json
import socket
import threading
import time
from concurrent.futures import Future

import pytest

from qdml_tpu.config import (
    DataConfig,
    ExperimentConfig,
    FleetConfig,
    ModelConfig,
    ServeConfig,
    TrainConfig,
)
from qdml_tpu.fleet import (
    BackendState,
    FleetPoller,
    FleetRouter,
    parse_backends,
    route_async,
)
from qdml_tpu.serve import ServeClient, ServeEngine, ServeLoop, serve_async


def _tiny_cfg(**serve_kw):
    # identical shapes to tests/test_faults.py so the persistent compile
    # cache shares the bucket executables across files
    serve = dict(
        max_batch=8, buckets=(4, 8), max_wait_ms=1.0, max_queue=32,
        batching="bucket",
    )
    serve.update(serve_kw)
    return ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=1),
        serve=ServeConfig(**serve),
    )


@pytest.fixture(scope="module")
def warmed():
    from qdml_tpu.serve import make_request_samples
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    cfg = _tiny_cfg()
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    clf_vars = {"params": sc_state.params}
    engine = ServeEngine(cfg, hdce_vars, clf_vars)
    samples = make_request_samples(cfg, 32)
    engine.warmup()
    return cfg, engine, samples


class _SwapCounter:
    """Per-backend fake swap_fn: counts calls, optionally fails typed (the
    corrupt-checkpoint shape) — fan-out SEMANTICS under test; real checkpoint
    swaps through the router are the committed dryrun's job."""

    def __init__(self, name: str, fail: bool = False):
        self.name = name
        self.fail = fail
        self.calls = 0

    def __call__(self, tags=None):
        self.calls += 1
        if self.fail:
            raise ValueError(f"checkpoint on {self.name} failed to restore")
        return {"epoch": self.calls, "tags": tags,
                "compile": {"hits": 0, "misses": 0, "requests": 0}}


@pytest.fixture()
def fleet(warmed):
    """Two socket backends (own ServeLoop each, shared warmed engine) + a
    started FleetRouter over both."""
    cfg, engine, samples = warmed
    aloop = asyncio.new_event_loop()
    t = threading.Thread(target=aloop.run_forever, daemon=True)
    t.start()
    loops, ports, swaps, tasks = [], [], [], []
    for i in range(2):
        loop_ = ServeLoop(engine, name=f"backend-{i}-loop").start()
        swap = _SwapCounter(f"backend-{i}")
        ready: Future = Future()
        task = asyncio.run_coroutine_threadsafe(
            serve_async(
                loop_, "127.0.0.1", 0, ready, swap_fn=swap,
                conn_timeout_s=30.0, dedup_ttl_s=5.0, host_id=f"backend-{i}",
            ),
            aloop,
        )
        ports.append(ready.result(timeout=30.0))
        loops.append(loop_)
        swaps.append(swap)
        tasks.append(task)
    router = FleetRouter(
        [("127.0.0.1", p) for p in ports],
        timeout_s=5.0, retries=0, eject_failures=2, eject_s=0.2,
        readmit_probes=1, poll_interval_s=30.0,  # poll driven manually
        failover=2, dedup_ttl_s=5.0,
    ).start()
    yield cfg, engine, samples, router, loops, ports, swaps, aloop
    router.stop()
    for task in tasks:
        task.cancel()
    aloop.call_soon_threadsafe(aloop.stop)
    t.join(timeout=5.0)
    for loop_ in loops:
        loop_.stop()


def _fleet_completed(loops) -> int:
    return sum(lp.merged_metrics().completed for lp in loops)


# ---------------------------------------------------------------------------
# Pure units: endpoint parsing, ejection state machine, ring affinity
# ---------------------------------------------------------------------------


def test_parse_backends():
    assert parse_backends("127.0.0.1:1, h2:8377") == [("127.0.0.1", 1), ("h2", 8377)]
    assert parse_backends("", default=("local", 9)) == [("local", 9)]
    with pytest.raises(ValueError):
        parse_backends("missing-port")
    with pytest.raises(ValueError):
        parse_backends("", default=None)


def test_backend_state_machine_breaker_semantics():
    """closed -> open on consecutive failures, open -> half-open after
    eject_s, half-open closes after readmit_probes successes and re-opens on
    one failure — the serve/breaker.py shape keyed on transport failures."""
    t = {"now": 0.0}
    s = BackendState(eject_failures=2, eject_s=1.0, readmit_probes=2,
                     clock=lambda: t["now"])
    assert s.allow() and s.state == "closed" and s.live()
    assert not s.record_failure()        # 1 of 2
    assert s.record_success() is False   # success RESETS the streak
    assert not s.record_failure()
    assert s.record_failure()            # 2 consecutive -> ejected
    assert s.state == "open" and not s.live() and not s.allow()
    t["now"] = 1.5
    assert s.allow() and s.state == "half_open"  # eject_s elapsed: probing
    assert not s.record_success()        # 1 of 2 probes
    assert s.record_failure()            # half-open failure re-opens
    assert s.state == "open"
    t["now"] = 3.0
    assert s.allow()
    assert not s.record_success() and s.record_success()  # 2 probes -> closed
    assert s.state == "closed"
    assert s.summary()["ejections"] == 2 and s.summary()["readmissions"] == 1


def test_hash_affinity_stable_and_spreading(fleet):
    """One id always resolves to the same backend order (retries land where
    the server dedup window holds); many ids spread over both backends."""
    *_, router, _loops, _ports, _swaps, _ = fleet
    first = [router._candidates(f"rid-{i}")[0].addr for i in range(64)]
    assert first == [router._candidates(f"rid-{i}")[0].addr for i in range(64)]
    assert len(set(first)) == 2  # both backends own part of the id space


def test_least_queue_prefers_shallow_backend(fleet):
    *_, router, _loops, _ports, _swaps, _ = fleet
    router.balance = "least_queue"
    try:
        router.backends[0].queue_depth = 7
        router.backends[1].queue_depth = 1
        assert router._candidates("any")[0] is router.backends[1]
        router.backends[1].queue_depth = 9
        assert router._candidates("any")[0] is router.backends[0]
    finally:
        router.balance = "hash"
        for b in router.backends:
            b.queue_depth = 0


# ---------------------------------------------------------------------------
# Request path + aggregation over two live socket backends
# ---------------------------------------------------------------------------


def test_router_serves_and_aggregates(fleet):
    cfg, engine, samples, router, loops, ports, _swaps, _ = fleet
    before = _fleet_completed(loops)
    x0 = samples["x"][0].tolist()
    reps = [router.request({"id": f"agg-{i}", "x": x0}) for i in range(12)]
    assert all(r["ok"] for r in reps)
    assert _fleet_completed(loops) == before + 12
    # the health poll learned each backend's stamped identity
    router.poll_once()
    assert {b.host_id for b in router.backends} == {"backend-0", "backend-1"}
    m = router.live_metrics()
    assert m["fleet"] is True and m["backends_polled"] == 2
    assert m["completed"] == _fleet_completed(loops)
    # per-backend AND merged rows: the blended blob is exactly what the
    # aggregation must never collapse to
    assert set(m["per_backend"]) == {"backend-0", "backend-1"}
    per_total = sum(v["completed"] for v in m["per_backend"].values())
    assert per_total == m["completed"]
    # per-scenario counts sum exactly (raw sums -> windowable by the
    # controller exactly like one host's)
    scen_total = sum(v["n"] for v in (m["per_scenario"] or {}).values())
    assert scen_total == m["completed"]
    # compile gate: per-key sum across hosts, all-zero (one warmup, zero
    # request-path compiles through the router)
    assert m["compile_cache_after_warmup"]["requests"] == 0
    rt = m["router"]
    assert rt["backends"] == 2 and rt["backends_live"] == 2
    assert rt["forwarded"] >= 12 and rt["wire_latency_ms"]["n"] >= 12


def test_router_health_is_cheap_and_identified(fleet):
    *_, router, _loops, _ports, _swaps, _ = fleet
    router.poll_once()
    h = router.health()
    assert h["fleet"] is True and h["backends"] == 2
    assert set(h["per_backend"]) == {"backend-0", "backend-1"}
    row = h["per_backend"]["backend-0"]
    assert row["state"] == "closed" and row["listen"] is not None


def test_backend_identity_block_on_the_wire(fleet):
    """Satellite: {"op":"health"} and {"op":"metrics"} replies carry the
    stable host_id + listen address (anonymous replies cannot be attributed
    after a failover)."""
    *_, ports, _swaps, _ = fleet
    with socket.create_connection(("127.0.0.1", ports[0]), timeout=10.0) as sk:
        fh = sk.makefile("rw")
        fh.write(json.dumps({"op": "health"}) + "\n")
        fh.flush()
        h = json.loads(fh.readline())["health"]
        assert h["host_id"] == "backend-0"
        assert h["listen"] == f"127.0.0.1:{ports[0]}"
        fh.write(json.dumps({"op": "metrics"}) + "\n")
        fh.flush()
        m = json.loads(fh.readline())["metrics"]
        assert m["host_id"] == "backend-0" and m["listen"].endswith(str(ports[0]))


# ---------------------------------------------------------------------------
# Ejection, failover, fleet-wide dedup (the satellite pin)
# ---------------------------------------------------------------------------


def _eject(backend) -> None:
    while backend.state.live():
        backend.state.record_failure()


def test_dedup_holds_across_ejection_and_failover(fleet):
    """Satellite pin: a ServeClient same-id retry against a backend that is
    healthy-then-ejected-then-readmitted lands EXACTLY ONE dispatch
    fleet-wide — the router's dedup re-attaches the retry even though the
    original backend is out of rotation, where per-backend server dedup
    alone would re-dispatch on the failover host."""
    cfg, engine, samples, router, loops, ports, _swaps, aloop = fleet
    ready: Future = Future()
    task = asyncio.run_coroutine_threadsafe(
        route_async(router, "127.0.0.1", 0, ready), aloop
    )
    front_port = ready.result(timeout=30.0)
    try:
        with ServeClient("127.0.0.1", front_port, timeout_s=10.0,
                         retries=1, backoff_s=0.01, seed=0) as client:
            rid = "fleet-dup-1"
            before = _fleet_completed(loops)
            rep1 = client.request(samples["x"][0], rid=rid)
            assert rep1["ok"] is True
            served_by = router._candidates(rid)[0]
            # the serving backend leaves rotation (healthy -> ejected)
            _eject(served_by)
            assert not served_by.state.live()
            # the same-id retry (reconnect shape: fresh connection, same id)
            rep2 = client.request(samples["x"][0], rid=rid)
            assert rep2["ok"] is True and rep2["h"] == rep1["h"]
            assert rep2["pred"] == rep1["pred"]
            assert _fleet_completed(loops) == before + 1  # ONE dispatch fleet-wide
            assert router.dedup.hits >= 1
            # a FRESH id routes around the ejected host (failover order)
            rep3 = client.request(samples["x"][1], rid="fleet-dup-2")
            assert rep3["ok"] is True
            # re-admission: the backend is actually healthy, so the next
            # poll probes it back in (eject_s=0.2)
            time.sleep(0.25)
            router.poll_once()
            assert served_by.state.live()
            assert router.router_summary()["readmissions"] >= 1
    finally:
        task.cancel()


def test_ejected_fleet_gives_up_typed(fleet):
    *_, router, loops, _ports, _swaps, _ = fleet
    for b in router.backends:
        _eject(b)
    try:
        rep = router.request({"id": "nobody-home", "x": [[0.0]]})
        assert rep["ok"] is False and rep["reason"].startswith("no_backend")
    finally:
        for b in router.backends:
            b.state._lock.acquire()
            b.state._state = "closed"
            b.state._fails = 0
            b.state._lock.release()


def test_front_socket_hardening(fleet):
    """Router-side socket garbage (the chaos class): bad JSON gets a typed
    reply with the connection surviving; the next line still serves."""
    cfg, engine, samples, router, loops, ports, _swaps, aloop = fleet
    ready: Future = Future()
    task = asyncio.run_coroutine_threadsafe(
        route_async(router, "127.0.0.1", 0, ready, conn_timeout_s=30.0), aloop
    )
    front_port = ready.result(timeout=30.0)
    try:
        with socket.create_connection(("127.0.0.1", front_port), timeout=10.0) as sk:
            fh = sk.makefile("rw")
            sk.sendall(b"NOT JSON {{{\n")
            assert json.loads(fh.readline()) == {"ok": False, "reason": "bad_json"}
            fh.write(json.dumps(
                {"id": "after-garbage", "x": samples["x"][0].tolist()}
            ) + "\n")
            fh.flush()
            assert json.loads(fh.readline())["ok"] is True
            # a non-object line is a typed bad_request, not a dropped conn
            fh.write(json.dumps([1, 2, 3]) + "\n")
            fh.flush()
            rep = json.loads(fh.readline())
            assert rep["ok"] is False and rep["reason"].startswith("bad_request")
    finally:
        task.cancel()


# ---------------------------------------------------------------------------
# Verb fan-out: swap all-or-report-partial, fleet metrics through FleetPoller
# ---------------------------------------------------------------------------


def test_swap_fanout_all_and_partial(fleet):
    cfg, engine, samples, router, loops, ports, swaps, _ = fleet
    router.poll_once()
    rec = router.swap_fanout({"hdce": "hdce_last"})
    assert rec["ok"] is True and rec["partial"] is False
    assert rec["ok_count"] == 2 and rec["fanned_to"] == 2 and rec["skipped"] == []
    assert swaps[0].calls == 1 and swaps[1].calls == 1
    assert set(rec["backends"]) == {"backend-0", "backend-1"}
    assert all(r["ok"] for r in rec["backends"].values())
    # one backend's swap now fails typed (corrupt-checkpoint shape):
    # all-or-report-partial — ok flips false, the per-host report names it
    swaps[1].fail = True
    rec = router.swap_fanout(None)
    assert rec["ok"] is False and rec["partial"] is True and rec["ok_count"] == 1
    assert "swap_failed" in rec["backends"]["backend-1"]["reason"]
    swaps[1].fail = False
    # an EJECTED backend is skipped, not failed: the survivors' swap still
    # counts as a fleet success (ejection never suspends adaptation)
    _eject(router.backends[1])
    try:
        rec = router.swap_fanout(None)
        assert rec["ok"] is True and rec["partial"] is True
        assert rec["skipped"] == ["backend-1"] and rec["fanned_to"] == 1
    finally:
        time.sleep(0.25)
        router.poll_once()  # readmit (eject_s=0.2, healthy backend)
        assert router.backends[1].state.live()


def test_fleet_poller_swap_raises_on_live_failure(fleet):
    *_, router, _loops, _ports, swaps, _ = fleet
    poller = FleetPoller(router)
    swaps[0].fail = True
    try:
        with pytest.raises(RuntimeError, match="fleet swap partial"):
            poller.swap({"hdce": "hdce_last"})
    finally:
        swaps[0].fail = False
    rec = poller.swap({"hdce": "hdce_last"})
    assert rec["ok"] is True


def test_controller_ticks_over_aggregated_fleet(fleet, tmp_path):
    """The FleetController consumes the router's AGGREGATED metrics exactly
    like one host's: per-scenario windows difference the summed counters,
    drift fires on the harness parity feed, and (dry_run) the adapt decision
    is reported — detection spans hosts without any controller change."""
    from qdml_tpu.config import override
    from qdml_tpu.control.loop import FleetController

    cfg, engine, samples, router, loops, ports, _swaps, _ = fleet
    ctl_cfg = override(cfg, "control.dry_run", True)
    ctl_cfg = override(ctl_cfg, "control.min_window", 4)
    ctrl = FleetController(
        ctl_cfg, str(tmp_path), FleetPoller(router), drift_step_hint=1
    )
    x0 = samples["x"][0].tolist()
    for i in range(10):
        assert router.request({"id": f"tick-a-{i}", "x": x0})["ok"]
    out = ctrl.tick()  # first poll: baseline window
    assert out["tick"] == 1
    for i in range(10):
        assert router.request({"id": f"tick-b-{i}", "x": x0})["ok"]
    out = ctrl.tick()
    assert out["tick"] == 2  # windowed the summed per-scenario counters
    # drift on the ground-truth parity feed -> a dry-run adapt decision
    for v in [-12.0] * 6 + [-6.0] * 8:
        ctrl.observe_parity(0, v)
    out = ctrl.tick()
    assert any(e.get("action") == "adapt" for e in out["events"])


def test_scale_fleet_targets_deepest_queue_host(fleet, monkeypatch):
    """scale_fleet differences the fleet total and grows the deepest-queue
    host (the autoscaler's WHICH-host decision). ServeLoop backends have no
    scale verb, so the backend exchange is faked at Backend.call — the
    decision logic, not the serve verb, is under test here (the real verb
    is pinned in test_control/test_serve; the dryrun drives it end to end)."""
    *_, router, _loops, _ports, _swaps, _ = fleet
    monkeypatch.setattr(router, "poll_once", lambda: None)
    b0, b1 = router.backends
    b0.replicas, b0.queue_depth = 1, 9
    b1.replicas, b1.queue_depth = 1, 0
    calls = []

    def fake_call(self, msg, **kw):
        calls.append((self.host_id, msg["replicas"]))
        return {"ok": True, "scale": {"replicas": msg["replicas"]}}

    monkeypatch.setattr(type(b0), "call", fake_call)
    rec = router.scale_fleet(4)
    assert rec["replicas_before"] == 2 and rec["replicas"] == 4
    # both grows land on the deep-queue host, absolute targets in order
    assert calls == [(b0.host_id, 2), (b0.host_id, 3)]
    assert rec["actions"][-1] == {"backend": b0.host_id, "replicas": 3}
    # the poll thread stays the SINGLE writer of Backend.replicas: the
    # scale arithmetic runs on a local snapshot and never mutates it (a
    # stale health reply landing mid-loop must not desync the targets)
    assert b0.replicas == 1 and b1.replicas == 1
    # scale-down (counts as the next poll would report them): only hosts
    # above 1 replica shrink — never below 1 per host
    calls.clear()
    b0.replicas = 3
    rec = router.scale_fleet(2)
    assert rec["replicas"] == 2
    assert calls == [(b0.host_id, 2), (b0.host_id, 1)]
    b0.replicas = 1
    b0.queue_depth = b1.queue_depth = 0


# ---------------------------------------------------------------------------
# Lint: the router's lock discipline rows are armed
# ---------------------------------------------------------------------------


def test_lock_map_covers_router_state():
    """Unlocked touches of the ejection state machine / dedup table in a
    file at the router's path are findings; the locked twins are clean (the
    LOCK_MAP fixture idiom of tests/test_analysis.py)."""
    import ast

    from qdml_tpu.analysis.engine import ModuleContext
    from qdml_tpu.analysis.rules import rule_serve_lock_discipline

    src = (
        "import threading\n"
        "class BackendState:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._state = 'closed'\n"
        "    def locked(self):\n"
        "        with self._lock:\n"
        "            return self._state\n"
        "    def unlocked(self):\n"
        "        return self._state\n"
        "class RouterDedup:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._entries = {}\n"
        "    def racy(self, rid):\n"
        "        return self._entries.get(rid)\n"
    )
    path = "qdml_tpu/fleet/router.py"
    ctx = ModuleContext(path, path, src, ast.parse(src))
    findings = rule_serve_lock_discipline(ctx)
    assert {f.line for f in findings} == {10, 16}
    # the real module is clean (also covered by the repo-wide lint gate)
    ctx_other = ModuleContext("other/file.py", "other/file.py", src, ast.parse(src))
    assert rule_serve_lock_discipline(ctx_other) == []
