"""DCE monolithic-baseline training path (reference ``DCE_P128``,
``Estimators_QuantumNAT_onchipQNN.py:40-75``)."""

import numpy as np

from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig
from qdml_tpu.train.dce import train_dce


def test_dce_trains_and_loss_decreases(tmp_path):
    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=128),
        model=ModelConfig(features=16),
        train=TrainConfig(batch_size=16, n_epochs=3),
    )
    state, history = train_dce(cfg, workdir=str(tmp_path))
    assert len(history["train_loss"]) == 3
    assert np.isfinite(history["train_loss"]).all()
    assert history["train_loss"][-1] < history["train_loss"][0]
    assert (tmp_path / "dce_best").is_dir()
    assert (tmp_path / "dce_last").is_dir()


def test_step_timer():
    from qdml_tpu.utils.profiling import StepTimer

    import jax.numpy as jnp

    timer = StepTimer(warmup=2)
    for i in range(6):
        timer.tick(jnp.ones((2,)) * i)
    assert timer.steps_per_sec() > 0
    assert timer.samples_per_sec(32) == timer.steps_per_sec() * 32


def test_dce_scan_steps_match_history():
    """train_dce with scan_steps>1 reproduces the per-step history."""
    import dataclasses

    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=128),
        model=ModelConfig(features=16),
        train=TrainConfig(batch_size=16, n_epochs=2),
    )
    h1 = train_dce(cfg)[1]
    cfg_scan = dataclasses.replace(cfg, train=dataclasses.replace(cfg.train, scan_steps=3))
    h2 = train_dce(cfg_scan)[1]
    np.testing.assert_allclose(h1["train_loss"], h2["train_loss"], rtol=1e-5)
    np.testing.assert_allclose(h1["val_nmse"], h2["val_nmse"], rtol=1e-5)
