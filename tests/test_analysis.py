"""graftlint + checkify sanitizer: rule positives/negatives over the
committed fixture files, suppression/baseline mechanics, the repo's own gate,
and the checkify-on/off equivalence + compile-identity pins (docs/ANALYSIS.md)."""

import json
import os
import textwrap

import numpy as np
import pytest

from qdml_tpu.analysis import LintEngine, ModuleContext, parse_suppressions
from qdml_tpu.analysis.cli import lint_main, repo_root
from qdml_tpu.analysis.engine import load_baseline, save_baseline

REPO = repo_root()
FIXDIR = "tests/fixtures/lint"


def _rules_found(findings):
    out = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return out


def _ctx(source: str, relpath: str = "fixture.py") -> ModuleContext:
    import ast

    return ModuleContext(relpath, relpath, source, ast.parse(source))


# ---------------------------------------------------------------------------
# Rule positives / negatives over the committed fixtures
# ---------------------------------------------------------------------------


def test_violation_fixture_trips_every_rule():
    engine = LintEngine(REPO)
    findings, err = engine.lint_file(f"{FIXDIR}/violations.py")
    assert err is None
    rules = _rules_found(findings)
    assert rules["jit-mutable-global"] == 1
    assert rules["train-step-jit-audit"] == 3      # decorator + call + K=1 scan maker
    assert rules["tracer-branch"] == 2             # if + while
    assert rules["host-sync-hot-path"] == 1
    assert rules["wall-clock-in-jit"] == 1
    assert rules["primary-only-collective"] == 2   # guarded + early-return
    assert rules["stranded-future"] == 1
    assert rules["broad-except"] == 2              # Exception + BaseException
    assert rules["import-time-jnp"] == 1
    assert rules["pallas-host-loop"] == 1          # per-layer launch loop
    assert rules["pallas-interpret-literal"] == 1  # hardcoded interpret=True
    assert rules["gate-matrix-in-loop"] == 1       # per-gate build in layer loop
    # nonzero + unique + 1-arg where + direct mask + mask-local (2 on 1 line
    # dedup to their own lines: direct and via-local sit on separate lines)
    assert rules["data-dependent-shape-in-jit"] == 5
    assert rules["pad-to-bucket-in-serve"] == 1    # bucket pick + zeros pad
    assert rules["retry-without-backoff"] == 1     # sleepless IO retry loop
    # every finding carries a usable anchor
    for f in findings:
        assert f.path.endswith("violations.py") and f.line > 0 and f.message


def test_clean_fixture_is_silent():
    engine = LintEngine(REPO)
    findings, err = engine.lint_file(f"{FIXDIR}/clean.py")
    assert err is None
    assert findings == [], _rules_found(findings)


def test_collective_outside_shardmap_fixtures():
    """The scaling subsystem's deadlock-shape rule: stray named-axis calls in
    quantum/ are findings; everything reachable from a shard_map region
    (directly or transitively through same-module helpers) is clean; paths
    outside quantum/ are out of scope; and the real sharded subsystem passes
    its own rule."""
    from qdml_tpu.analysis.rules import rule_collective_outside_shardmap

    engine = LintEngine(REPO)
    findings, err = engine.lint_file(f"{FIXDIR}/quantum/violations.py")
    assert err is None
    assert _rules_found(findings) == {"collective-outside-shardmap": 2}
    assert {f.line for f in findings} == {28, 33}
    findings, err = engine.lint_file(f"{FIXDIR}/quantum/clean.py")
    assert err is None
    assert findings == [], _rules_found(findings)
    # scope: the identical source under a non-quantum path never fires
    with open(f"{FIXDIR}/quantum/violations.py") as fh:
        src = fh.read()
    assert rule_collective_outside_shardmap(_ctx(src, "qdml_tpu/serve/x.py")) == []
    # the subsystem the rule protects is itself clean
    findings, err = engine.lint_file("qdml_tpu/quantum/sharded.py")
    assert err is None
    assert not [f for f in findings if f.rule == "collective-outside-shardmap"]


def test_unbounded_readline_fixtures():
    """The serve-path resilience rule: bare awaited stream reads in serve/
    paths are findings; the wait_for-wrapped form is clean; the identical
    source outside a serve/ path is out of scope; and the real socket server
    passes its own rule."""
    from qdml_tpu.analysis.rules import rule_unbounded_readline

    engine = LintEngine(REPO)
    findings, err = engine.lint_file(f"{FIXDIR}/serve/violations.py")
    assert err is None
    assert _rules_found(findings) == {"unbounded-readline": 2}
    findings, err = engine.lint_file(f"{FIXDIR}/serve/clean.py")
    assert err is None
    assert findings == [], _rules_found(findings)
    # scope: the identical source under a non-serve path never fires
    with open(f"{FIXDIR}/serve/violations.py") as fh:
        src = fh.read()
    assert rule_unbounded_readline(_ctx(src, "qdml_tpu/control/x.py")) == []
    # the subsystem the rule protects is itself clean
    findings, err = engine.lint_file("qdml_tpu/serve/server.py")
    assert err is None
    assert not [f for f in findings if f.rule == "unbounded-readline"]


def test_trace_in_jit_path_fixtures():
    """The tracing host-side-only contract rule: TraceContext construction /
    phase stamping inside a jitted function or a pallas kernel body is a
    finding; the sanctioned host-side serve-loop shape is clean; and the
    real stamping surfaces (serve loop, router, loadgen) pass their own
    rule."""
    engine = LintEngine(REPO)
    findings, err = engine.lint_file(f"{FIXDIR}/telemetry/violations.py")
    assert err is None
    # jitted TraceContext + jitted add_phase + pallas-kernel trace_sampled
    assert _rules_found(findings) == {"trace-in-jit-path": 3}
    kinds = {f.line: f.message for f in findings}
    assert any("pallas-kernel" in m for m in kinds.values())
    assert any("jit-reachable" in m for m in kinds.values())
    findings, err = engine.lint_file(f"{FIXDIR}/telemetry/clean.py")
    assert err is None
    assert findings == [], _rules_found(findings)
    # the sanctioned stamping surfaces are clean under the rule
    for path in (
        "qdml_tpu/serve/server.py",
        "qdml_tpu/serve/loadgen.py",
        "qdml_tpu/fleet/router.py",
        "qdml_tpu/telemetry/tracing.py",
    ):
        findings, err = engine.lint_file(path)
        assert err is None
        assert not [f for f in findings if f.rule == "trace-in-jit-path"], path


def test_unwindowed_cumulative_rate_fixtures():
    """The windowed-rate discipline rule: a cumulative lifetime counter
    divided by a wall-clock span (directly, via a span-bound local, or
    through a one-step name chain) is a finding; windowed deltas,
    count-over-count ratios and non-time divisors are clean; the sanctioned
    differencing module is exempt by path; and the real counter surfaces
    pass their own rule (run-level summary rates carry inline suppressions
    with reasons)."""
    from qdml_tpu.analysis.rules import rule_unwindowed_cumulative_rate

    engine = LintEngine(REPO)
    findings, err = engine.lint_file(f"{FIXDIR}/telemetry/rate_violations.py")
    assert err is None
    assert _rules_found(findings) == {"unwindowed-cumulative-rate": 3}
    findings, err = engine.lint_file(f"{FIXDIR}/telemetry/rate_clean.py")
    assert err is None
    assert findings == [], _rules_found(findings)
    # the sanctioned differencing module is exempt by relpath, even for a
    # shape the rule would otherwise flag
    with open(f"{FIXDIR}/telemetry/rate_violations.py") as fh:
        src = fh.read()
    assert rule_unwindowed_cumulative_rate(
        _ctx(src, "qdml_tpu/telemetry/timeseries.py")
    ) == []
    # and the same source under any other qdml_tpu path fires
    assert len(rule_unwindowed_cumulative_rate(
        _ctx(src, "qdml_tpu/serve/other.py")
    )) == 3
    # the real cumulative-counter surfaces pass their own rule (the
    # run-level summary rates in serve/metrics.py via reasoned suppression)
    for path in (
        "qdml_tpu/serve/metrics.py",
        "qdml_tpu/fleet/router.py",
        "qdml_tpu/control/loop.py",
        "qdml_tpu/telemetry/burnrate.py",
    ):
        findings, err = engine.lint_file(path)
        assert err is None
        assert not [
            f for f in findings
            if f.rule == "unwindowed-cumulative-rate" and not f.suppressed
        ], path


def test_retry_without_backoff_own_client_is_clean():
    """The sanctioned retry shape — ServeClient.call's jittered exponential
    backoff — passes the rule that exists because of it."""
    engine = LintEngine(REPO)
    findings, err = engine.lint_file("qdml_tpu/serve/client.py")
    assert err is None
    assert not [f for f in findings if f.rule == "retry-without-backoff"]


def test_lock_discipline_rule_uses_project_map():
    """The lock map keys on real repo paths, so the rule is exercised with an
    inline module presented under the mapped path."""
    from qdml_tpu.analysis.rules import rule_serve_lock_discipline

    src = textwrap.dedent(
        """
        import threading

        class MicroBatcher:
            def __init__(self):
                self._q = []              # __init__ is exempt
                self._lock = threading.Lock()

            def good(self):
                with self._lock:
                    return len(self._q)

            def bad(self):
                return self._q.pop()      # outside the lock
        """
    )
    ctx = _ctx(src, "qdml_tpu/serve/batcher.py")
    findings = rule_serve_lock_discipline(ctx)
    assert len(findings) == 1
    assert findings[0].context == "MicroBatcher.bad"
    # the same source under an unmapped path is out of scope
    assert rule_serve_lock_discipline(_ctx(src, "other/file.py")) == []


def test_lock_discipline_covers_pool_exit_coordinator():
    """The replica-pool worker-exit counter: reads/writes of
    ExitCoordinator._live outside `with self._lock:` are findings (the
    crashed-worker-sheds-live-queue race), the locked twins are clean."""
    from qdml_tpu.analysis.rules import rule_serve_lock_discipline

    src = textwrap.dedent(
        """
        import threading

        class ExitCoordinator:
            def __init__(self):
                self._lock = threading.Lock()
                self._live = 0            # __init__ is exempt

            def leave_locked(self):
                with self._lock:
                    self._live -= 1
                    return self._live <= 0

            def leave_racy(self):
                self._live -= 1           # unlocked decrement
                return self._live <= 0    # unlocked read
        """
    )
    findings = rule_serve_lock_discipline(_ctx(src, "qdml_tpu/serve/server.py"))
    assert all(f.rule == "serve-lock-discipline" for f in findings)
    assert {f.context for f in findings} == {"ExitCoordinator.leave_racy"}
    assert len(findings) >= 1


def test_lock_discipline_covers_engine_swap_state():
    """The hot-swap structures: the live (hdce, clf) param tuple and the
    swap epoch flip atomically under _swap_lock — a bare read can see a
    torn checkpoint mid-swap; the locked twins are clean."""
    from qdml_tpu.analysis.rules import rule_serve_lock_discipline

    src = textwrap.dedent(
        """
        import threading

        class ServeEngine:
            def __init__(self):
                self._swap_lock = threading.Lock()
                self._live = (1, 2)       # __init__ is exempt
                self._swap_epoch = 0

            def infer_locked(self):
                with self._swap_lock:
                    h, c = self._live
                return h, c

            def swap_locked(self, new):
                with self._swap_lock:
                    self._swap_epoch += 1
                    self._live = new

            def infer_torn(self):
                return self._live         # unlocked: can tear mid-swap

            def epoch_racy(self):
                return self._swap_epoch   # unlocked epoch read
        """
    )
    findings = rule_serve_lock_discipline(_ctx(src, "qdml_tpu/serve/engine.py"))
    assert {f.context for f in findings} == {
        "ServeEngine.infer_torn",
        "ServeEngine.epoch_racy",
    }


def test_lock_discipline_covers_event_bus_ring_state():
    """The event spine's ring/cursor state: seq allocation, the deque and
    the drop counter move together under _lock — an unlocked publish could
    tear seq/dropped accounting and make loss silent; the locked twins are
    clean, and the real module passes its own rule."""
    from qdml_tpu.analysis.rules import rule_serve_lock_discipline

    src = textwrap.dedent(
        """
        import threading

        class EventBus:
            def __init__(self):
                self._lock = threading.Lock()
                self._ring = []           # __init__ is exempt
                self._seq = 0
                self._dropped = 0

            def publish_locked(self, env):
                with self._lock:
                    self._seq += 1
                    self._ring.append(env)
                    if len(self._ring) > 4:
                        self._ring.pop(0)
                        self._dropped += 1
                    return self._seq

            def publish_racy(self, env):
                self._seq += 1            # unlocked seq allocation
                self._ring.append(env)    # unlocked append
                return self._dropped      # unlocked drop-counter read
        """
    )
    findings = rule_serve_lock_discipline(
        _ctx(src, "qdml_tpu/telemetry/events.py")
    )
    assert {f.context for f in findings} == {"EventBus.publish_racy"}
    engine = LintEngine(REPO)
    real, err = engine.lint_file("qdml_tpu/telemetry/events.py")
    assert err is None
    assert not [
        f for f in real
        if f.rule == "serve-lock-discipline" and not f.suppressed
    ]


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


def test_suppression_parsing_reasons_and_top_level_commas():
    sup = parse_suppressions(
        "x = 1  # lint: disable=rule-a(reason one (nested, commas)),rule-b\n"
        "y = 2  # lint: disable=rule-c(simple)\n"
    )
    assert sup[1]["rule-a"] == "reason one (nested, commas)"
    assert sup[1]["rule-b"] is None  # reason-less: recorded but not honored
    assert sup[2]["rule-c"] == "simple"


def test_suppression_requires_reason(tmp_path):
    (tmp_path / "mod.py").write_text(
        textwrap.dedent(
            """
            def f():
                try:
                    g()
                except Exception:  # lint: disable=broad-except(probe may raise anything; result is advisory)
                    pass

            def h():
                try:
                    g()
                except Exception:  # lint: disable=broad-except
                    pass

            x = 1  # lint: disable=tracer-branch
            """
        )
    )
    engine = LintEngine(str(tmp_path))
    result = engine.run(["mod.py"])
    # the reasoned suppression holds; the reason-less one does NOT suppress —
    # the finding stays, annotated with the policy pointer — and a reason-less
    # comment matching nothing is reported as dead weight
    assert len(result.suppressed) == 1
    assert result.suppressed[0].reason.startswith("probe may raise")
    rules = _rules_found(result.new)
    assert rules == {"broad-except": 1, "bare-suppression": 1}
    unsuppressed = next(f for f in result.new if f.rule == "broad-except")
    assert "reasons are mandatory" in unsuppressed.message


def test_dead_suppression_and_nested_sync_dedup(tmp_path):
    """A reasoned suppression matching nothing is stale documentation and is
    flagged; nested sync calls on one line yield ONE finding (duplicate
    fingerprints would double-count the gate while one baseline entry
    silently absorbed both)."""
    (tmp_path / "mod.py").write_text(
        textwrap.dedent(
            """
            import jax
            import jax.numpy as jnp
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(jax.device_get(x))  # two syncs, one line

            y = 1  # lint: disable=broad-except(nothing here ever raised)
            """
        )
    )
    result = LintEngine(str(tmp_path)).run(["mod.py"])
    rules = _rules_found(result.new)
    assert rules["host-sync-hot-path"] == 1  # deduped by (rule, line)
    assert rules["dead-suppression"] == 1


def test_missing_path_fails_the_gate(tmp_path, capsys):
    """A typo'd --paths (or renamed DEFAULT_PATHS entry) must fail, not scan
    nothing and report green."""
    result = LintEngine(str(tmp_path)).run(["no/such/dir"])
    assert not result.ok and "no such file" in result.errors[0]
    rc = lint_main(["--paths=qdml_tpu/serv"])
    capsys.readouterr()
    assert rc == 1


# ---------------------------------------------------------------------------
# Baseline round-trip
# ---------------------------------------------------------------------------


def test_baseline_round_trip_and_rearm(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text("def f():\n    try:\n        g()\n    except Exception:\n        pass\n")
    engine = LintEngine(str(tmp_path))
    raw = engine.run(["mod.py"])
    assert _rules_found(raw.new) == {"broad-except": 1}

    bl_path = tmp_path / "baseline.json"
    save_baseline(str(bl_path), raw.new)
    baseline = load_baseline(str(bl_path))
    assert len(baseline) == 1
    gated = engine.run(["mod.py"], baseline=baseline)
    assert gated.new == [] and len(gated.baselined) == 1
    assert gated.baselined[0].reason  # grandfather reason is written

    # fingerprints are line-number free: shifting the offender down leaves it
    # baselined; EDITING the offending line re-arms the gate
    mod.write_text(
        "import os\n\n\ndef f():\n    try:\n        g()\n    except Exception:\n        pass\n"
    )
    assert engine.run(["mod.py"], baseline=baseline).new == []
    mod.write_text("def f():\n    try:\n        g()\n    except BaseException:\n        pass\n")
    rearmed = engine.run(["mod.py"], baseline=baseline)
    assert _rules_found(rearmed.new) == {"broad-except": 1}

    # regenerating preserves a hand-written reason for surviving entries
    entry = next(iter(baseline.values()))
    entry["reason"] = "custom triage note"
    save_baseline(str(bl_path), raw.new, previous=baseline)
    assert next(iter(load_baseline(str(bl_path)).values()))["reason"] == "custom triage note"


def test_write_baseline_excludes_meta_findings(tmp_path, capsys):
    """--write-baseline must not freeze policy violations (bare-suppression)
    or data-driven slow-marker findings into the AST baseline."""
    root = tmp_path / "repo"
    root.mkdir()
    (root / "mod.py").write_text(
        textwrap.dedent(
            """
            def f():
                try:
                    g()
                except Exception:
                    pass

            x = 1  # lint: disable=broad-except
            """
        )
    )
    import qdml_tpu.analysis.cli as lint_cli

    bl = root / "bl.json"
    orig = lint_cli.repo_root
    lint_cli.repo_root = lambda: str(root)
    try:
        rc = lint_cli.lint_main(
            ["--paths=mod.py", f"--baseline={bl}", "--write-baseline"]
        )
    finally:
        lint_cli.repo_root = orig
    out = capsys.readouterr().out
    assert rc == 0 and "NOT baselined" in out
    entries = json.loads(bl.read_text())["entries"]
    assert [e["rule"] for e in entries] == ["broad-except"]  # no bare-suppression


# ---------------------------------------------------------------------------
# CLI: exit codes, --json artifact, the repo's own gate, slow-marker fold-in
# ---------------------------------------------------------------------------


def test_repo_gate_is_clean(capsys):
    """THE acceptance gate: qdml-tpu lint --baseline exits 0 on this repo —
    every finding fixed, suppressed with a written reason, or baselined."""
    assert lint_main(["--baseline"]) == 0
    assert "0 new findings" in capsys.readouterr().out


def test_lint_cli_fixture_findings_and_json(tmp_path, capsys):
    json_path = tmp_path / "lint.json"
    rc = lint_main([f"--paths={FIXDIR}/violations.py", f"--json={json_path}"])
    capsys.readouterr()
    assert rc == 1
    gate = json.loads(json_path.read_text())
    assert gate["kind"] == "lint_gate" and gate["ok"] is False
    assert gate["new_findings"] == sum(gate["per_rule"].values()) == len(gate["findings"])
    assert gate["exit_code"] == 1
    assert gate["per_rule"]["tracer-branch"] == 2


def test_lint_cli_slow_marker_rule(tmp_path, capsys):
    dur = tmp_path / "d.log"
    dur.write_text("  30.00s call     tests/test_serve.py::test_empty_queue_flush_is_noop\n")
    json_path = tmp_path / "lint.json"
    rc = lint_main(
        [
            f"--paths={FIXDIR}/clean.py",
            f"--durations={dur}",
            "--allow=/nonexistent",
            f"--json={json_path}",
        ]
    )
    capsys.readouterr()
    assert rc == 1
    gate = json.loads(json_path.read_text())
    assert gate["per_rule"] == {"slow-marker": 1}
    # the slow-marked soak test and the committed allowlist both satisfy it
    dur.write_text(
        "  30.00s call     tests/test_serve.py::test_loadgen_soak_open_loop_with_deadlines\n"
    )
    rc = lint_main([f"--paths={FIXDIR}/clean.py", f"--durations={dur}"])
    capsys.readouterr()
    assert rc == 0


def test_report_folds_lint_gate(tmp_path, capsys):
    """report --lint: a failing lint artifact forces the regression exit even
    when the perf side is clean."""
    from qdml_tpu.telemetry.report import EXIT_REGRESSION, report_main

    bench = {"metric": "sps", "value": 100.0, "platform": "cpu"}
    base = tmp_path / "b.jsonl"
    base.write_text(json.dumps(bench) + "\n")
    cur = tmp_path / "c.jsonl"
    cur.write_text(json.dumps(bench) + "\n")
    lint_ok = tmp_path / "ok.json"
    lint_ok.write_text(json.dumps({"ok": True, "new_findings": 0, "suppressed": 3, "baselined": 1}))
    lint_bad = tmp_path / "bad.json"
    lint_bad.write_text(
        json.dumps({"ok": False, "new_findings": 2, "per_rule": {"tracer-branch": 2}})
    )
    assert report_main([f"--current={cur}", f"--baseline={base}", f"--lint={lint_ok}"]) == 0
    capsys.readouterr()
    json_out = tmp_path / "gate.json"
    rc = report_main(
        [f"--current={cur}", f"--baseline={base}", f"--lint={lint_bad}", f"--json={json_out}"]
    )
    capsys.readouterr()
    assert rc == EXIT_REGRESSION
    gate = json.loads(json_out.read_text())
    assert gate["lint_failed"] is True
    row = next(g for g in gate["gates"] if g["kind"] == "lint")
    assert row["status"] == "regression" and row["current"] == 2


# ---------------------------------------------------------------------------
# Checkify sanitizer: off == today's program, on == same numerics + typed trip
# ---------------------------------------------------------------------------


def _tiny_cfg(**train_overrides):
    from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig

    return ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=80),
        model=ModelConfig(features=16),
        train=TrainConfig(batch_size=16, n_epochs=1, **train_overrides),
    )


@pytest.fixture(scope="module")
def dce_setup():
    from qdml_tpu.data.datasets import DMLGridLoader
    from qdml_tpu.train.dce import init_dce_state

    cfg = _tiny_cfg()
    loader = DMLGridLoader(cfg.data, cfg.train.batch_size)
    batch = next(iter(loader.epoch(0)))
    model, state = init_dce_state(cfg, loader.steps_per_epoch)
    return cfg, loader, batch, model, state


def test_checkify_off_is_compile_identical(dce_setup):
    """checkify_errors=False must build TODAY's program: the maker's lowered
    HLO is byte-identical to a directly-jitted step, and re-dispatching adds
    zero compile-cache requests (the probes=False pinning pattern)."""
    import jax
    from functools import partial

    from qdml_tpu.train.dce import _dce_step, init_dce_state, make_dce_train_step
    from qdml_tpu.utils.compile_cache import compile_cache_stats, enable_compile_cache
    from qdml_tpu.utils.platform import donation_argnums

    cfg, loader, batch, model, state = dce_setup
    enable_compile_cache()

    maker_step = make_dce_train_step(model, probes=True, checkify_errors=False)

    # the pre-PR-4 maker body, verbatim (same inner name so HLO module names
    # cannot differ for naming reasons alone)
    @partial(jax.jit, donate_argnums=donation_argnums(0))
    def step(state, batch):
        return _dce_step(model, state, batch, probes=True)

    assert (
        maker_step.lower(state, batch).as_text()
        == step.lower(state, batch).as_text()
    )

    # and the off path never recompiles across dispatches
    _, st2 = init_dce_state(cfg, loader.steps_per_epoch)
    st2, m = maker_step(st2, batch)
    base = compile_cache_stats()["requests"]
    st2, m = maker_step(st2, batch)
    assert compile_cache_stats()["requests"] == base
    assert "checkify_err" not in m


def test_checkify_on_matches_off_numerics(dce_setup):
    """Same params, same metrics: checkify adds error TRACKING, never math."""
    import jax

    from qdml_tpu.train.dce import init_dce_state, make_dce_train_step

    cfg, loader, batch, model, _ = dce_setup
    _, s_off = init_dce_state(cfg, loader.steps_per_epoch)
    _, s_on = init_dce_state(cfg, loader.steps_per_epoch)
    step_off = make_dce_train_step(model, probes=True, checkify_errors=False)
    step_on = make_dce_train_step(model, probes=True, checkify_errors=True)
    for _ in range(2):
        s_off, m_off = step_off(s_off, batch)
        s_on, m_on = step_on(s_on, batch)
    assert "checkify_err" in m_on and m_on["checkify_err"].get() is None
    np.testing.assert_array_equal(np.asarray(m_off["loss"]), np.asarray(m_on["loss"]))
    np.testing.assert_array_equal(
        np.asarray(m_off["probe"]["grad_norm"]), np.asarray(m_on["probe"]["grad_norm"])
    )
    for a, b in zip(jax.tree.leaves(s_off.params), jax.tree.leaves(s_on.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkify_trip_raises_through_flight_recorder(dce_setup, tmp_path):
    """A tripped check surfaces exactly like a watchdog divergence: dump
    bundle + typed DivergenceError naming the offending primitive."""
    import dataclasses

    from qdml_tpu.telemetry import DivergenceError, FlightRecorder
    from qdml_tpu.train.dce import init_dce_state, make_dce_train_step

    cfg, loader, batch, model, _ = dce_setup
    cfg = dataclasses.replace(
        cfg,
        train=dataclasses.replace(cfg.train, checkify=True),
        eval=dataclasses.replace(cfg.eval, results_dir=str(tmp_path)),
    )
    _, state = init_dce_state(cfg, loader.steps_per_epoch)
    step = make_dce_train_step(model, probes=True, checkify_errors=True)
    bad = dict(batch)
    yp = np.asarray(bad["yp_img"]).copy()
    yp[...] = np.inf
    bad["yp_img"] = yp
    state, m = step(state, bad)
    assert m["checkify_err"].get() is not None
    rec = FlightRecorder("unit", cfg, workdir=None)
    rec.note_good(state.params)
    with pytest.raises(DivergenceError, match="checkify") as ei:
        rec.on_step(0, m, loss=float(np.asarray(m["loss"])), params=state.params)
    assert ei.value.reason.startswith("checkify:")
    assert ei.value.dump_dir and os.path.exists(
        os.path.join(ei.value.dump_dir, "bundle.json")
    )
    bundle = json.load(open(os.path.join(ei.value.dump_dir, "bundle.json")))
    assert bundle["reason"].startswith("checkify:")


def test_checkify_classifier_step_batched_scatter_compat(dce_setup):
    """The classifier NLL loss picks log-probs via take_along_axis, which
    this jax lowers to a BATCHED gather whose gradient is a batched
    scatter-add — the shape that crashed checkify's stock scatter-OOB rule
    at trace time (IndexError, caught driving train-sc --train.checkify on
    the real backend). Pins the sanitizer's compat backfill: the checkified
    classifier step must trace, run, and match the unchecked step exactly."""
    import jax

    from qdml_tpu.train.qsc import init_sc_state, make_sc_train_step

    cfg, loader, batch, _model, _state = dce_setup
    model, s_on = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    _, s_off = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    rng = jax.random.PRNGKey(0)
    step_on = make_sc_train_step(model, needs_rng=False, probes=True, checkify_errors=True)
    step_off = make_sc_train_step(model, needs_rng=False, probes=True, checkify_errors=False)
    s_on, m_on = step_on(s_on, batch, rng)
    s_off, m_off = step_off(s_off, batch, rng)
    assert m_on["checkify_err"].get() is None
    np.testing.assert_array_equal(np.asarray(m_on["loss"]), np.asarray(m_off["loss"]))
    for a, b in zip(jax.tree.leaves(s_on.params), jax.tree.leaves(s_off.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_serve_checkify_parity_and_trip():
    """serve.checkify: warmed checkified buckets reproduce the offline
    forward, keep the zero-request-path-compiles gate, and convert a
    poisoned batch into a typed DivergenceError (no hang, no garbage)."""
    from qdml_tpu.config import (
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        ServeConfig,
        TrainConfig,
    )
    from qdml_tpu.serve import ServeEngine
    from qdml_tpu.serve.loadgen import make_request_samples
    from qdml_tpu.telemetry import DivergenceError
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=1),
        serve=ServeConfig(max_batch=4, buckets=(4,), checkify=True, batching="bucket"),
    )
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    engine = ServeEngine(cfg, hdce_vars, {"params": sc_state.params})
    samples = make_request_samples(cfg, 8)
    offline_h, offline_pred, _ = engine.offline_forward(samples["x"])
    engine.warmup()
    h, pred, _conf, bucket = engine.infer(samples["x"][:3])
    np.testing.assert_allclose(h, offline_h[:3], rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(pred, offline_pred[:3])
    assert all(v == 0 for v in engine.request_path_compiles().values())
    bad = samples["x"][:2].copy()
    bad[...] = np.inf
    with pytest.raises(DivergenceError, match="serve checkify"):
        engine.infer(bad)
    # the engine survives the trip: the next clean batch still serves
    h2, _, _, _ = engine.infer(samples["x"][:2])
    np.testing.assert_allclose(h2, offline_h[:2], rtol=1e-5, atol=1e-5)
