"""CArr (complex-as-real-pair) algebra vs numpy complex ground truth."""

import jax.numpy as jnp
import numpy as np
import pytest

from qdml_tpu.utils import CArr, ceinsum, cexp_i, cmatmul, pack_h, unpack_h, yp_to_image


def _rand_c(rng, *shape):
    return (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(np.complex64)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def test_roundtrip(rng):
    x = _rand_c(rng, 3, 4)
    np.testing.assert_allclose(CArr.from_numpy(x).to_numpy(), x, rtol=1e-6)


def test_elementwise(rng):
    a, b = _rand_c(rng, 5, 7), _rand_c(rng, 5, 7)
    ca, cb = CArr.from_numpy(a), CArr.from_numpy(b)
    np.testing.assert_allclose((ca + cb).to_numpy(), a + b, rtol=1e-5)
    np.testing.assert_allclose((ca - cb).to_numpy(), a - b, rtol=1e-5)
    np.testing.assert_allclose((ca * cb).to_numpy(), a * b, rtol=1e-5)
    np.testing.assert_allclose(ca.conj().to_numpy(), a.conj(), rtol=1e-5)
    np.testing.assert_allclose(ca.abs2(), np.abs(a) ** 2, rtol=1e-5)


def test_real_scaling(rng):
    a = _rand_c(rng, 4, 4)
    s = rng.standard_normal((4, 4)).astype(np.float32)
    got = (CArr.from_numpy(a) * jnp.asarray(s)).to_numpy()
    np.testing.assert_allclose(got, a * s, rtol=1e-5)


def test_cmatmul_gauss_trick(rng):
    a, b = _rand_c(rng, 6, 8), _rand_c(rng, 8, 5)
    got = cmatmul(CArr.from_numpy(a), CArr.from_numpy(b)).to_numpy()
    np.testing.assert_allclose(got, a @ b, rtol=1e-4, atol=1e-5)


def test_ceinsum(rng):
    a, b = _rand_c(rng, 3, 6, 8), _rand_c(rng, 8, 5)
    got = ceinsum("bij,jk->bik", CArr.from_numpy(a), CArr.from_numpy(b)).to_numpy()
    np.testing.assert_allclose(got, np.einsum("bij,jk->bik", a, b), rtol=1e-4, atol=1e-5)


def test_cexp_i():
    theta = np.linspace(-3, 3, 17).astype(np.float32)
    np.testing.assert_allclose(
        cexp_i(jnp.asarray(theta)).to_numpy(), np.exp(1j * theta), rtol=1e-5, atol=1e-6
    )


@pytest.mark.parametrize("n", [64, 16, 13, 1])
def test_cexp_i_ramp_matches_direct(rng, n):
    """Angle-split ramp == direct per-element sin/cos to f32 rounding, at the
    generator's steering (n=64), delay (n=16), a non-divisible n, and n=1."""
    from qdml_tpu.utils import cexp_i_ramp

    theta = rng.uniform(-4.0, 4.0, (5, 7)).astype(np.float32)
    got = cexp_i_ramp(jnp.asarray(theta), n).to_numpy()
    assert got.shape == (5, 7, n)
    want = np.exp(1j * theta[..., None] * np.arange(n, dtype=np.float32))
    # Tolerance: the split path rounds theta*a and theta*split*b separately;
    # at |theta| <= 4, k <= 63 the f32 ulp of the ~250-radian angle is ~3e-5.
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_pack_unpack(rng):
    h = _rand_c(rng, 4, 10)
    packed = pack_h(CArr.from_numpy(h))
    assert packed.shape == (4, 20)
    np.testing.assert_allclose(unpack_h(packed).to_numpy(), h, rtol=1e-6)


def test_yp_to_image_layout(rng):
    """Pixel (sub k, beam b, re) must equal Re Yp[b*n_sub + k] (beam-major flat)."""
    yp = _rand_c(rng, 2, 128)
    img = yp_to_image(CArr.from_numpy(yp), n_sub=16, n_beam=8)
    assert img.shape == (2, 16, 8, 2)
    b, k = 5, 11
    np.testing.assert_allclose(img[1, k, b, 0], yp[1, b * 16 + k].real, rtol=1e-6)
    np.testing.assert_allclose(img[1, k, b, 1], yp[1, b * 16 + k].imag, rtol=1e-6)
