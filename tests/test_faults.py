"""Fault-tolerant serving: chaos-injection plan, replica supervision
(restart/backoff/quarantine), circuit-breaker brownout, protocol hardening
(timeouts, line bounds, dedup), retrying client, corrupt-checkpoint swap
rejection, and the provably-free-when-disabled pins (docs/RESILIENCE.md).

One fast fault per class runs here (the tier-1 chaos smoke); the full
fault-class matrix with committed artifacts is scripts/chaos_dryrun.py ->
results/chaos_dryrun/.
"""

import asyncio
import json
import socket
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from qdml_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    ServeConfig,
    TrainConfig,
)
from qdml_tpu.serve import (
    CircuitBreaker,
    FaultInjected,
    FaultPlan,
    FaultSpec,
    Overloaded,
    Prediction,
    ReplicaPool,
    ServeClient,
    ServeClientError,
    ServeEngine,
    ServeLoop,
    serve_async,
)
from qdml_tpu.serve.faults import RestartPolicy
from qdml_tpu.serve.types import BREAKER_OPEN, SHUTDOWN


def _tiny_cfg(**serve_kw):
    # identical shapes to tests/test_serve.py's engine so the persistent
    # compile cache (conftest) shares the bucket executables across files
    serve = dict(
        max_batch=8, buckets=(4, 8), max_wait_ms=1.0, max_queue=32,
        batching="bucket",
    )
    serve.update(serve_kw)
    return ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=1),
        serve=ServeConfig(**serve),
    )


@pytest.fixture(scope="module")
def warmed():
    """One warmed engine + offline reference shared by the fault tests."""
    from qdml_tpu.serve import make_request_samples
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    cfg = _tiny_cfg()
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    clf_vars = {"params": sc_state.params}
    engine = ServeEngine(cfg, hdce_vars, clf_vars)
    samples = make_request_samples(cfg, 32)
    offline_h, offline_pred, _ = engine.offline_forward(samples["x"])
    engine.warmup()
    return cfg, engine, samples, offline_h, offline_pred, (hdce_vars, clf_vars)


def _fast_supervision(pool, budget=3, base_s=0.002):
    """Tighten the pool's supervision knobs for test speed (interval/backoff
    in the ms range; the knobs are config fields in production)."""
    pool._sup_interval_s = 0.01
    pool._policy = RestartPolicy(base_s=base_s, budget=budget, max_s=0.05)
    return pool


# ---------------------------------------------------------------------------
# FaultPlan: deterministic schedule, typed injection, audit trail
# ---------------------------------------------------------------------------


def test_fault_plan_schedule_and_validation():
    plan = FaultPlan(
        [FaultSpec("worker_exception", at=1), FaultSpec("socket_drop", at=3, times=2)],
        seed=7,
    )
    assert plan.describe() == {
        "seed": 7,
        "faults": [
            {"kind": "worker_exception", "at": 1, "times": 1},
            {"kind": "socket_drop", "at": 3, "times": 2},
        ],
    }
    # worker_batch occasions: 0 passes, 1 raises typed, 2 passes
    plan.check_worker_batch("r0")
    with pytest.raises(FaultInjected) as ei:
        plan.check_worker_batch("r0")
    assert ei.value.kind == "worker_exception" and ei.value.seq == 1
    plan.check_worker_batch("r0")
    assert plan.fired == [
        {"kind": "worker_exception", "site": "worker_batch", "seq": 1, "replica": "r0"}
    ]
    # client-side classes read the same schedule
    assert not plan.client_fault_at("socket_drop", 2)
    assert plan.client_fault_at("socket_drop", 3)
    assert plan.client_fault_at("socket_drop", 4)
    assert not plan.client_fault_at("socket_drop", 5)
    with pytest.raises(ValueError):
        FaultSpec("not_a_fault")
    with pytest.raises(ValueError):
        FaultSpec("socket_drop", at=-1)


def test_fault_plan_replica_targeting_is_per_replica():
    """A targeted spec fires only on its replica; occasion counters are per
    (site, replica) so one replica's traffic never advances another's
    schedule."""
    plan = FaultPlan([FaultSpec("replica_crash", at=0, replica="serve-replica-1")])
    plan.check_worker_loop("serve-replica-0")  # untargeted replica: clean
    with pytest.raises(FaultInjected):
        plan.check_worker_loop("serve-replica-1")
    plan.check_worker_loop("serve-replica-0")


def test_restart_policy_backoff_is_jittered_exponential():
    import random

    pol = RestartPolicy(base_s=0.1, budget=3, jitter=0.5, max_s=10.0)
    rng = random.Random(0)
    d0, d1, d2 = pol.delay(0, rng), pol.delay(1, rng), pol.delay(2, rng)
    assert 0.1 <= d0 <= 0.15 and 0.2 <= d1 <= 0.3 and 0.4 <= d2 <= 0.6
    assert not pol.exhausted(2) and pol.exhausted(3)


# ---------------------------------------------------------------------------
# Circuit breaker: watermark trip, brownout, half-open recovery
# ---------------------------------------------------------------------------


def test_breaker_state_machine_deterministic_clock():
    t = {"now": 0.0}
    br = CircuitBreaker(
        max_queue=10, high_frac=0.8, low_frac=0.3, open_s=1.0, probes=2,
        clock=lambda: t["now"],
    )
    assert br.allow(depth=3) and br.state == "closed"
    # depth hits the high watermark (8): OPEN, this submit fast-fails
    assert not br.allow(depth=8)
    assert br.state == "open"
    # while open, everything fast-fails — even at depth 0 (time, not depth,
    # closes the open window; that is what makes brownout cheap)
    assert not br.allow(depth=0)
    # after open_s: half-open; low depth closes immediately
    t["now"] = 1.5
    assert br.allow(depth=1) and br.state == "closed"
    # trip again, recover through probes at MID depth (between watermarks):
    # probes are finite — still-high backlog re-opens when they run out
    assert not br.allow(depth=9)
    t["now"] = 3.0
    assert br.allow(depth=5) and br.state == "half_open"  # probe 1
    assert br.allow(depth=5)                              # probe 2
    assert not br.allow(depth=5)                          # probes spent -> re-open
    assert br.state == "open"
    s = br.summary()
    assert s["opens"] == 3 and s["fast_fails"] == 4 and s["admitted"] == 4
    assert s["open_fraction"] == pytest.approx(0.5)
    assert s["high_watermark"] == 8 and s["low_watermark"] == 3


def test_breaker_fronts_submit_with_typed_shed(warmed):
    """serve.breaker=True: once queued depth crosses the watermark, submit
    fast-fails with typed Overloaded(breaker_open) BEFORE enqueueing — the
    queue never grows past the brownout point, and the shed is counted."""
    cfg, engine, samples, *_ = warmed
    import dataclasses

    bcfg = dataclasses.replace(
        cfg, serve=dataclasses.replace(
            cfg.serve, breaker=True, breaker_high_frac=0.25, breaker_low_frac=0.1,
            max_queue=16,
        )
    )
    # same engine, breaker-enabled loop: NOT started — the queue only fills
    eng2 = ServeEngine(bcfg, *engine.live_vars())
    eng2._compiled = engine._compiled  # share executables: no new compiles
    eng2._warm, eng2._stats0 = engine._warm, engine._stats0
    eng2.batching_mode, eng2.dispatch_mode = engine.batching_mode, engine.dispatch_mode
    loop = ServeLoop(eng2)
    assert loop._breaker is not None
    futs = [loop.submit(samples["x"][i % 32], rid=i) for i in range(6)]
    # high watermark = 0.25 * 16 = 4: submits 0..3 enqueue, 4 trips, 5 fails
    res4, res5 = futs[4].result(0.1), futs[5].result(0.1)
    assert isinstance(res4, Overloaded) and res4.reason == BREAKER_OPEN
    assert isinstance(res5, Overloaded) and res5.reason == BREAKER_OPEN
    assert loop.batcher.depth == 4
    assert loop.metrics.shed == {BREAKER_OPEN: 2}
    s = loop._breaker.summary()
    assert s["state"] == "open" and s["fast_fails"] == 2
    assert loop.health()["breaker"]["state"] == "open"
    # drain so the module engine's shared executables see no stale queue
    loop.start()
    assert all(
        isinstance(f.result(timeout=30.0), (Prediction, Overloaded)) for f in futs
    )
    loop.stop()


# ---------------------------------------------------------------------------
# Supervision: worker_exception / replica_crash recovery, quarantine
# ---------------------------------------------------------------------------


def test_worker_exception_resolves_batch_and_supervisor_restarts(warmed):
    """The worker_exception fault class end-to-end on a 1-replica pool: the
    poisoned batch's futures resolve WITH the failure (typed closure, no
    hang), the supervisor restarts the replica, later traffic serves, and
    the request path never compiled."""
    cfg, engine, samples, offline_h, *_ = warmed
    plan = FaultPlan([FaultSpec("worker_exception", at=0)])
    pool = _fast_supervision(ReplicaPool(engine, replicas=1, faults=plan))
    pool.start()
    try:
        f0 = pool.submit(samples["x"][0], rid=0)
        with pytest.raises(FaultInjected):
            f0.result(timeout=10.0)
        # supervision: the crashed replica comes back and serves
        deadline = time.monotonic() + 10.0
        while pool._restart_total == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pool._restart_total == 1
        futs = [pool.submit(samples["x"][i], rid=i) for i in range(8)]
        results = [f.result(timeout=30.0) for f in futs]
        assert all(isinstance(r, Prediction) for r in results)
        np.testing.assert_allclose(
            np.stack([r.h for r in sorted(results, key=lambda r: r.rid)]),
            offline_h[:8], rtol=1e-5, atol=1e-5,
        )
        merged = pool.merged_metrics()
        assert merged.faults.get("worker_exception") == 1
        assert merged.restarts == 1
        h = pool.health()
        assert h["replicas_live"] == 1 and h["restarts"] == 1
        assert h["quarantined"] == [] and h["warm"] is True
    finally:
        pool.stop()
    assert engine.request_path_compiles() == {"hits": 0, "misses": 0, "requests": 0}


def test_replica_crash_quarantines_after_budget_peers_keep_serving(warmed):
    """A crash-looping replica (replica_crash with times past the budget)
    is restarted budget times, then QUARANTINED — the peer replica keeps
    serving the shared queue throughout, and nothing strands."""
    cfg, engine, samples, *_ = warmed
    plan = FaultPlan(
        [FaultSpec("replica_crash", at=0, times=50, replica="serve-replica-1")]
    )
    pool = _fast_supervision(
        ReplicaPool(engine, replicas=2, faults=plan), budget=1
    )
    pool.start()
    try:
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            futs = [pool.submit(samples["x"][i % 32], rid=i) for i in range(8)]
            results = [f.result(timeout=30.0) for f in futs]
            # every future resolves — served by the peer, or shed typed in
            # the crash window — the zero-stranded invariant under chaos
            assert all(isinstance(r, (Prediction, Overloaded)) for r in results)
            if pool.health()["quarantined"]:
                break
            time.sleep(0.02)
        h = pool.health()
        assert h["quarantined"] == ["serve-replica-1"]
        assert h["replicas"] == 1 and h["replicas_live"] == 1
        assert pool._restart_total == 1  # budget=1: one restart, then quarantine
        # the surviving peer serves normally
        futs = [pool.submit(samples["x"][i], rid=100 + i) for i in range(8)]
        assert all(
            isinstance(f.result(timeout=30.0), Prediction) for f in futs
        )
    finally:
        pool.stop()
    assert engine.request_path_compiles() == {"hits": 0, "misses": 0, "requests": 0}


def test_restart_budget_decays_after_sustained_health(warmed):
    """The budget measures crash LOOPS, not lifetime totals: a slot whose
    last restart is older than RestartPolicy.reset_after_s forgets its
    history — a transient fault long after an earlier one restarts instead
    of quarantining; back-to-back faults still exhaust the budget."""
    cfg, engine, samples, *_ = warmed
    pol = RestartPolicy(base_s=0.001, budget=1, reset_after_s=0.05, max_s=0.01)
    assert pol.stale(0.06) and not pol.stale(0.01)

    pool = _fast_supervision(ReplicaPool(engine, replicas=1), budget=1)
    pool._policy = pol
    pool._supervise = False  # drive the restart path directly, no sweeps
    pool.start()
    try:
        # slot crashed ONCE, long ago (stale): budget must reset -> restart
        pool._restart_counts["serve-replica-0"] = 1
        pool._restart_ts["serve-replica-0"] = time.monotonic() - 1.0
        pool._restart_replica(pool.replicas[0], "worker_death")
        assert pool.health()["quarantined"] == []
        assert pool._restart_total == 1
        assert pool._restart_counts["serve-replica-0"] == 1  # 0 + this one
        # crash again IMMEDIATELY (fresh ts): budget=1 exhausts -> quarantine
        pool._restart_replica(pool.replicas[0], "worker_death")
        assert pool.health()["quarantined"] == ["serve-replica-0"]
    finally:
        pool.stop()


def test_quarantine_event_is_emitted(warmed, tmp_path):
    """replica_quarantined / replica_restarted are structured telemetry
    records (the fleet controller's and operator's signal)."""
    from qdml_tpu.telemetry import run_manifest, set_sink
    from qdml_tpu.utils.metrics import MetricsLogger

    cfg, engine, samples, *_ = warmed
    path = str(tmp_path / "quarantine.metrics.jsonl")
    logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
    set_sink(logger.telemetry)
    try:
        plan = FaultPlan([FaultSpec("replica_crash", at=0, times=50)])
        pool = _fast_supervision(
            ReplicaPool(engine, replicas=1, faults=plan), budget=1
        )
        pool.start()
        try:
            # keep offering work: the crash site fires on observed-work
            # occasions, so the restarted replica must SEE requests to
            # crash-loop its way to quarantine (every future resolves typed)
            deadline = time.monotonic() + 15.0
            i = 0
            while not pool.health()["quarantined"] and time.monotonic() < deadline:
                res = pool.submit(samples["x"][i % 32], rid=i).result(timeout=10.0)
                assert isinstance(res, (Prediction, Overloaded))
                i += 1
                time.sleep(0.005)
            assert pool.health()["quarantined"] == ["serve-replica-0"]
            # quarantined 1-replica pool: submits shed typed, nothing hangs
            res = pool.submit(samples["x"][1], rid=1).result(timeout=5.0)
            assert isinstance(res, Overloaded) and res.reason == SHUTDOWN
        finally:
            pool.stop()
    finally:
        set_sink(None)
        logger.close()
    with open(path) as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    names = [r.get("name") for r in recs if r.get("kind") == "counters"]
    assert "replica_restarted" in names and "replica_quarantined" in names
    q = next(r for r in recs if r.get("name") == "replica_quarantined")
    assert q["replica"] == "serve-replica-0" and q["reason"] == "worker_death"
    assert q["restarts"] == 1


# ---------------------------------------------------------------------------
# Inert-plan freedom: HLO identity + zero compiles (the "provably free" pin)
# ---------------------------------------------------------------------------


def test_fault_hooks_disabled_are_provably_free(warmed):
    """No-fault serving is byte-identical to the pre-resilience build: the
    fused forward's lowered HLO does not mention any fault machinery (the
    hooks are host-side only), and serving traffic with an INERT plan
    installed performs zero request-path compiles and bit-identical
    results."""
    import jax

    cfg, engine, samples, offline_h, *_ = warmed
    spec = jax.ShapeDtypeStruct((4, *cfg.image_hw, 2), np.float32)
    hdce_live, clf_live = engine.live_vars()
    var_specs = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), (hdce_live, clf_live)
    )
    text_before = jax.jit(engine._forward).lower(*var_specs, spec).as_text()
    inert = FaultPlan([])  # installed but schedules nothing
    pool = ReplicaPool(engine, replicas=1, faults=inert).start()
    try:
        futs = [pool.submit(samples["x"][i], rid=i) for i in range(8)]
        results = [f.result(timeout=30.0) for f in futs]
    finally:
        pool.stop()
    assert all(isinstance(r, Prediction) for r in results)
    np.testing.assert_allclose(
        np.stack([r.h for r in sorted(results, key=lambda r: r.rid)]),
        offline_h[:8], rtol=1e-5, atol=1e-5,
    )
    assert inert.fired == []
    assert engine.request_path_compiles() == {"hits": 0, "misses": 0, "requests": 0}
    text_after = jax.jit(engine._forward).lower(*var_specs, spec).as_text()
    assert text_before == text_after  # the traced program never saw the plan


# ---------------------------------------------------------------------------
# Socket hardening: health verb, timeouts, line bounds, garbage, dedup
# ---------------------------------------------------------------------------


@pytest.fixture()
def sock_server(warmed):
    """A ServeLoop behind the asyncio socket front-end with tight hardening
    knobs (idle timeout 0.5 s, 64 KiB lines, dedup on) and a swap_fn that
    rejects like a corrupt checkpoint would."""
    from qdml_tpu.train.checkpoint import CheckpointRestoreError

    cfg, engine, samples, *_ = warmed
    loop_ = ServeLoop(engine).start()

    def bad_swap(tags=None):
        raise CheckpointRestoreError("checkpoint 'hdce_bad' exists but failed to restore")

    aloop = asyncio.new_event_loop()
    t = threading.Thread(target=aloop.run_forever, daemon=True)
    t.start()
    ready: Future = Future()
    task = asyncio.run_coroutine_threadsafe(
        serve_async(
            loop_, "127.0.0.1", 0, ready, swap_fn=bad_swap,
            conn_timeout_s=0.5, max_line_bytes=65536, dedup_ttl_s=5.0,
        ),
        aloop,
    )
    port = ready.result(timeout=10.0)
    yield cfg, loop_, samples, port
    task.cancel()
    aloop.call_soon_threadsafe(aloop.stop)
    t.join(timeout=5.0)
    loop_.stop()


def test_health_verb_and_swap_failed_reply(sock_server):
    cfg, loop_, samples, port = sock_server
    with socket.create_connection(("127.0.0.1", port), timeout=10.0) as sk:
        fh = sk.makefile("rw")
        fh.write(json.dumps({"op": "health", "id": "h1"}) + "\n")
        fh.flush()
        rep = json.loads(fh.readline())
        assert rep["ok"] and rep["id"] == "h1"
        h = rep["health"]
        assert h["warm"] is True and h["started"] is True
        assert h["workers_alive"] == 1 and h["queue_depth"] == 0
        assert h["swap_epoch"] == 0 and "dedup_hits" in h
        # a swap against a corrupt checkpoint replies typed and the server
        # keeps serving (the old params stayed live)
        fh.write(json.dumps({"op": "swap", "id": "s1"}) + "\n")
        fh.flush()
        rep = json.loads(fh.readline())
        assert rep["ok"] is False and rep["reason"].startswith("swap_failed")
        assert "failed to restore" in rep["reason"]
        fh.write(json.dumps({"id": 1, "x": samples["x"][0].tolist()}) + "\n")
        fh.flush()
        assert json.loads(fh.readline())["ok"] is True


def test_idle_connection_reaped_with_typed_reply(sock_server):
    *_, port = sock_server
    with socket.create_connection(("127.0.0.1", port), timeout=10.0) as sk:
        # stalled_client fault class: connect, send NOTHING — the server
        # must reap the slot at conn_timeout_s with a typed reply + close
        sk.settimeout(5.0)
        fh = sk.makefile("rb")
        line = fh.readline()
        assert json.loads(line) == {"ok": False, "reason": "idle_timeout"}
        assert fh.readline() == b""  # closed


def test_oversized_line_rejected_typed(sock_server):
    cfg, _, samples, port = sock_server
    with socket.create_connection(("127.0.0.1", port), timeout=10.0) as sk:
        sk.settimeout(5.0)
        sk.sendall(b'{"id": 1, "x": "' + b"a" * 70000 + b'"}\n')
        fh = sk.makefile("rb")
        rep = json.loads(fh.readline())
        assert rep["ok"] is False and "max_line_bytes" in rep["reason"]
        assert fh.readline() == b""  # framing lost -> connection closed


def test_partial_line_and_drop_leave_server_healthy(sock_server):
    cfg, loop_, samples, port = sock_server
    # partial_line fault class: a fragment with no newline, then vanish
    sk = socket.create_connection(("127.0.0.1", port), timeout=10.0)
    sk.sendall(b'{"id": 1, "x": [[')
    sk.close()
    # socket_drop fault class: a full request, then vanish before the reply
    sk = socket.create_connection(("127.0.0.1", port), timeout=10.0)
    sk.sendall((json.dumps({"id": "drop", "x": samples["x"][0].tolist()}) + "\n").encode())
    sk.close()
    time.sleep(0.2)
    # the server is healthy and still serves new connections
    with socket.create_connection(("127.0.0.1", port), timeout=10.0) as sk2:
        fh = sk2.makefile("rw")
        fh.write(json.dumps({"id": 2, "x": samples["x"][1].tolist()}) + "\n")
        fh.flush()
        assert json.loads(fh.readline())["ok"] is True


def test_dedup_retried_id_never_double_dispatches(sock_server):
    """The retry contract's server half: re-sending an id within the dedup
    TTL returns the SAME result without re-dispatching (completed count
    advances once)."""
    cfg, loop_, samples, port = sock_server
    before = loop_.merged_metrics().completed
    with socket.create_connection(("127.0.0.1", port), timeout=10.0) as sk:
        fh = sk.makefile("rw")
        fh.write(json.dumps({"id": "dup-1", "x": samples["x"][0].tolist()}) + "\n")
        fh.flush()
        rep1 = json.loads(fh.readline())
        # the retry (same id, fresh line — as after a reconnect)
        fh.write(json.dumps({"id": "dup-1", "x": samples["x"][0].tolist()}) + "\n")
        fh.flush()
        rep2 = json.loads(fh.readline())
    assert rep1["ok"] and rep2["ok"] and rep1["h"] == rep2["h"]
    assert loop_.merged_metrics().completed == before + 1  # ONE dispatch
    # the hit is visible in the health verb
    with socket.create_connection(("127.0.0.1", port), timeout=10.0) as sk:
        fh = sk.makefile("rw")
        fh.write(json.dumps({"op": "health"}) + "\n")
        fh.flush()
        assert json.loads(fh.readline())["health"]["dedup_hits"] >= 1


def test_client_retries_reconnect_and_give_up_typed(sock_server):
    cfg, loop_, samples, port = sock_server
    with ServeClient("127.0.0.1", port, timeout_s=10.0, retries=2,
                     backoff_s=0.01, seed=0) as client:
        rep = client.request(samples["x"][0], rid="c-1")
        assert rep["ok"] is True
        # server closes the connection under the client (idle reap at 0.5s):
        # the next request reconnects with backoff and still succeeds
        time.sleep(0.9)
        rep = client.request(samples["x"][1], rid="c-2")
        assert rep["ok"] is True
        counters = client.counters()
        assert counters["reconnects"] >= 1 and counters["give_ups"] == 0
        assert client.health()["ok"] is True
        assert client.metrics()["ok"] is True
    # a dead endpoint exhausts retries into the typed client error
    dead = ServeClient("127.0.0.1", 1, timeout_s=0.2, retries=1, backoff_s=0.01)
    with pytest.raises(ServeClientError):
        dead.request(samples["x"][0], rid="c-3")
    assert dead.counters()["give_ups"] == 1


# ---------------------------------------------------------------------------
# Corrupt checkpoints: typed restore error + swap leaves old params serving
# ---------------------------------------------------------------------------


def test_restore_latest_params_corrupt_tag_raises_typed(tmp_path):
    from qdml_tpu.train.checkpoint import (
        CheckpointNotFoundError,
        CheckpointRestoreError,
        restore_latest_params,
        save_checkpoint,
    )

    wd = str(tmp_path)
    # never trained: the typed miss
    with pytest.raises(CheckpointNotFoundError):
        restore_latest_params(wd, "hdce")
    # a valid save, then TRUNCATE its array data: the tag resolves but the
    # restore must raise the typed restore error, never the miss
    save_checkpoint(wd, "hdce_last", {"params": {"w": np.ones(8, np.float32)}})
    import os
    import shutil

    # truncate the checkpoint down to one garbage file: the tag directory
    # still RESOLVES (latest_tag finds it), but every byte of tree/array
    # data is gone — the shape a crash mid-save or a bad copy leaves behind
    tag_dir = os.path.join(wd, "hdce_last")
    shutil.rmtree(tag_dir)
    os.makedirs(tag_dir)
    with open(os.path.join(tag_dir, "_METADATA"), "w") as fh:
        fh.write("garbage, not orbax metadata")
    with pytest.raises(CheckpointRestoreError) as ei:
        restore_latest_params(wd, "hdce")
    assert not isinstance(ei.value, CheckpointNotFoundError)
    assert "hdce_last" in str(ei.value)


def test_corrupt_swap_rejected_old_params_keep_serving(warmed, tmp_path):
    """The corrupt_swap chaos class at the engine level: a swap pinned to a
    tag that exists but cannot restore raises typed, swap_epoch stays 0, and
    the live engine serves bit-identical results after the rejection."""
    import os

    from qdml_tpu.train.checkpoint import CheckpointRestoreError, save_checkpoint

    cfg, engine, samples, offline_h, _, (hdce_vars, clf_vars) = warmed
    wd = str(tmp_path)
    save_checkpoint(wd, "hdce_last", hdce_vars)
    save_checkpoint(wd, "sc_last", clf_vars)
    os.makedirs(os.path.join(wd, "hdce_bad"))  # exists, not a checkpoint
    h_before, *_ = engine.infer(samples["x"][:4])
    with pytest.raises(CheckpointRestoreError):
        engine.swap_from_workdir(wd, tags={"hdce": "hdce_bad"})
    assert engine.swap_epoch == 0
    h_after, *_ = engine.infer(samples["x"][:4])
    np.testing.assert_array_equal(h_before, h_after)
    # and a GOOD swap to the same workdir's healthy tags still works
    rec = engine.swap_from_workdir(wd, tags={"hdce": "hdce_last"})
    assert rec["epoch"] == 1 and all(v == 0 for v in rec["compile"].values())


# ---------------------------------------------------------------------------
# Ragged + hot-swap pins under an injected crash (PR-7/PR-12 invariants)
# ---------------------------------------------------------------------------


def test_ragged_hotswap_pins_hold_under_injected_crash(warmed):
    """The PR-12 ragged program and the PR-7 zero-recompile hot-swap survive
    chaos: traffic on a ragged-mode 1-replica pool, a worker_exception crash
    mid-run, supervised restart, a live hot-swap to rescaled params — every
    future resolves, post-swap results match the rescaled reference, and the
    request path never compiles."""
    import dataclasses

    import jax

    cfg, engine, samples, _, _, (hdce_vars, clf_vars) = warmed
    rcfg = dataclasses.replace(
        cfg, serve=dataclasses.replace(cfg.serve, batching="ragged")
    )
    ragged = ServeEngine(rcfg, hdce_vars, clf_vars)
    # rescaled checkpoint for the swap (same tree/shapes/dtypes, different
    # numbers) + BOTH references compiled BEFORE warmup so the request-path
    # compile gate measures serving alone
    hdce2 = jax.tree.map(lambda a: np.asarray(a) * 1.5, hdce_vars)
    ref_old, _, _ = ragged.offline_forward(samples["x"])
    ref_new, _, _ = ServeEngine(rcfg, hdce2, clf_vars).offline_forward(samples["x"])
    assert np.abs(ref_old - ref_new).max() > 0  # the swap is observable
    ragged.warmup()
    assert ragged.continuous_admission  # forced-ragged engine admits continuously
    plan = FaultPlan([FaultSpec("worker_exception", at=1)])
    pool = _fast_supervision(ReplicaPool(ragged, replicas=1, faults=plan))
    pool.start()
    try:
        futs = [pool.submit(samples["x"][i], rid=i) for i in range(12)]
        results = []
        for f in futs:
            try:
                results.append(f.result(timeout=30.0))
            except FaultInjected:
                results.append(None)  # the poisoned batch: typed closure
        assert any(r is None for r in results)  # the crash actually fired
        ok = [r for r in results if isinstance(r, Prediction)]
        for r in ok:
            np.testing.assert_allclose(r.h, ref_old[r.rid], rtol=1e-5, atol=1e-5)
        # wait out the restart, then hot-swap under the recovered pool
        deadline = time.monotonic() + 10.0
        while pool._restart_total == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pool._restart_total == 1
        rec = ragged.swap_params(hdce2, clf_vars)
        assert rec["epoch"] == 1 and all(v == 0 for v in rec["compile"].values())
        futs = [pool.submit(samples["x"][i], rid=100 + i) for i in range(12)]
        post = [f.result(timeout=30.0) for f in futs]
        assert all(isinstance(r, Prediction) for r in post)
        for r in post:
            np.testing.assert_allclose(
                r.h, ref_new[r.rid - 100], rtol=1e-5, atol=1e-5
            )
    finally:
        pool.stop()
    assert ragged.request_path_compiles() == {"hits": 0, "misses": 0, "requests": 0}


# ---------------------------------------------------------------------------
# Report gates: stranded-futures (always-armed) + breaker open fraction
# ---------------------------------------------------------------------------


def _summary_jsonl(tmp_path, name, **over):
    rec = {
        "kind": "serve_summary", "platform": "cpu", "rps": 100.0,
        "completed": 100, "batches": 10, "shed": {},
        "latency_ms": {"n": 100, "p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0,
                       "mean_ms": 1.0, "max_ms": 3.0},
        "stranded_futures": 0,
        "breaker": {"state": "closed", "opens": 0, "fast_fails": 0,
                    "admitted": 100, "open_fraction": 0.0},
    }
    rec.update(over)
    p = tmp_path / name
    p.write_text(json.dumps(rec) + "\n")
    return str(p)


def test_report_stranded_futures_gate_always_armed(tmp_path):
    from qdml_tpu.telemetry.report import EXIT_REGRESSION, build_report_data, report_main

    base = _summary_jsonl(tmp_path, "base.jsonl")
    good = _summary_jsonl(tmp_path, "good.jsonl")
    data = build_report_data([good], base)
    row = next(g for g in data["gates"] if g["metric"] == "serve.stranded_futures")
    assert row["status"] == "ok" and data["stranded_failed"] is False
    # one stranded future fails — even under a platform-mismatch disarm
    bad = _summary_jsonl(tmp_path, "bad.jsonl", stranded_futures=2, platform="tpu")
    data = build_report_data([bad], base)
    assert data["gate_armed"] is False  # platform mismatch disarms perf...
    assert data["stranded_failed"] is True  # ...but never this invariant
    row = next(g for g in data["gates"] if g["metric"] == "serve.stranded_futures")
    assert row["status"] == "regression" and row["baseline"] == 0
    assert report_main([f"--current={bad}", f"--baseline={base}"]) == EXIT_REGRESSION
    assert report_main([f"--current={good}", f"--baseline={base}"]) == 0


def test_report_breaker_open_fraction_absolute_gate(tmp_path):
    from qdml_tpu.telemetry.report import build_report_data

    base = _summary_jsonl(tmp_path, "base.jsonl")
    # within slack (0.05): ok; beyond: regression — ABSOLUTE comparison
    ok = _summary_jsonl(
        tmp_path, "ok.jsonl",
        breaker={"open_fraction": 0.03, "state": "closed", "opens": 1,
                 "fast_fails": 3, "admitted": 97},
    )
    data = build_report_data([ok], base)
    row = next(g for g in data["gates"] if g["metric"] == "serve.breaker_open_fraction")
    assert row["status"] == "ok"
    bad = _summary_jsonl(
        tmp_path, "brk.jsonl",
        breaker={"open_fraction": 0.2, "state": "open", "opens": 4,
                 "fast_fails": 20, "admitted": 80},
    )
    data = build_report_data([bad], base)
    row = next(g for g in data["gates"] if g["metric"] == "serve.breaker_open_fraction")
    assert row["status"] == "regression"
    assert any(r["metric"] == "serve.breaker_open_fraction" for r in data["regressions"])
