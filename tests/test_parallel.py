"""SPMD: sharded statevector vs tensor path; DP/federated step equivalence.

Runs on the 8-virtual-device CPU mesh from conftest.py (the standard JAX way to
test pjit/psum logic without a pod, SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from qdml_tpu.config import DataConfig, ExperimentConfig, MeshConfig, ModelConfig, TrainConfig
from qdml_tpu.data.datasets import DMLGridLoader
from qdml_tpu.parallel import (
    make_mesh,
    replicate,
    shard_grid_batch,
    shard_hdce_state,
)
from qdml_tpu.quantum.circuits import run_circuit
from qdml_tpu.quantum.sharded import run_circuit_sharded
from qdml_tpu.train.hdce import init_hdce_state, make_hdce_train_step

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _model_mesh(k: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:k]), ("model",))


@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_sharded_circuit_matches_tensor(n_devices):
    # jit both paths: eager per-op dispatch through shard_map on the 1-CPU
    # 8-virtual-device host costs minutes; compiled it is seconds
    n, layers = 6, 2
    rng = np.random.default_rng(n_devices)
    angles = jnp.asarray(rng.uniform(-1, 1, (5, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(-3, 3, (layers, n, 2)).astype(np.float32))
    mesh = _model_mesh(n_devices)
    want = jax.jit(lambda a, w: run_circuit(a, w, n, layers, "tensor"))(angles, w)
    got = jax.jit(lambda a, w: run_circuit_sharded(a, w, n, layers, mesh))(angles, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)


def test_sharded_circuit_gradients_match():
    n, layers = 5, 2
    rng = np.random.default_rng(0)
    angles = jnp.asarray(rng.uniform(-1, 1, (3, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(-1, 1, (layers, n, 2)).astype(np.float32))

    g_ref = jax.jit(
        jax.grad(lambda w: jnp.sum(run_circuit(angles, w, n, layers, "tensor") ** 2))
    )(w)
    mesh = _model_mesh(4)
    g_sh = jax.jit(
        jax.grad(lambda w: jnp.sum(run_circuit_sharded(angles, w, n, layers, mesh) ** 2))
    )(w)
    np.testing.assert_allclose(np.asarray(g_sh), np.asarray(g_ref), rtol=1e-3, atol=1e-5)


def test_sharded_circuit_14q_matches_tensor():
    """Sharded-vs-tensor value+grad at config-3 LAYOUT scale, in the default
    suite (VERDICT r2 #8). n=14 over k=8 devices has the same local-shard
    structure as the full 16-qubit case (3 global qubits, 2^11 local
    amplitudes — every gate class crosses the ppermute ring) at a fraction
    of the compile+run cost; the full n=16 variant below stays slow-marked.
    """
    n, layers = 14, 1
    rng = np.random.default_rng(14)
    angles = jnp.asarray(rng.uniform(-1, 1, (2, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(0, 2 * np.pi, (layers, n, 2)).astype(np.float32))
    mesh = _model_mesh(8)

    # One jitted value_and_grad program per path (not four separate jits):
    # the XLA CPU compile dominates this test's cold cost.
    def vg(circuit_fn):
        def loss(w):
            out = circuit_fn(w)
            return jnp.sum(out**2), out

        return jax.jit(jax.value_and_grad(loss, has_aux=True))

    (_, want), g_ref = vg(lambda w: run_circuit(angles, w, n, layers, "tensor"))(w)
    (_, got), g_sh = vg(lambda w: run_circuit_sharded(angles, w, n, layers, mesh))(w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(g_sh), np.asarray(g_ref), rtol=1e-3, atol=1e-4)


@pytest.mark.slow
def test_sharded_circuit_16q_matches_tensor():
    """The ``sharded_16q`` scale (BASELINE config 3): 16 qubits over 8 devices.

    slow-marked: value+grad at 2^16 amplitudes costs minutes on a cold
    compile cache (run with ``-m slow``); the default suite still exercises
    the 16-qubit sharded path end-to-end via
    ``test_sharded_16q_preset_one_train_step`` below.

    At n=16 the local-shard layout differs materially from the small-n cases
    above (2^13 local amplitudes per device, 3 global qubits), so value AND
    grad are checked against the unsharded tensor path.
    """
    n, layers = 16, 1
    rng = np.random.default_rng(16)
    angles = jnp.asarray(rng.uniform(-1, 1, (2, n)).astype(np.float32))
    w = jnp.asarray(rng.uniform(0, 2 * np.pi, (layers, n, 2)).astype(np.float32))
    mesh = _model_mesh(8)
    # jit both paths: at 2^16 amplitudes, eager per-op dispatch through
    # shard_map on 8 virtual devices is minutes; compiled it is seconds.
    want = jax.jit(lambda a, w: run_circuit(a, w, n, layers, "tensor"))(angles, w)
    got = jax.jit(lambda a, w: run_circuit_sharded(a, w, n, layers, mesh))(angles, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-3, atol=1e-4)

    g_ref = jax.jit(
        jax.grad(lambda w: jnp.sum(run_circuit(angles, w, n, layers, "tensor") ** 2))
    )(w)
    g_sh = jax.jit(
        jax.grad(lambda w: jnp.sum(run_circuit_sharded(angles, w, n, layers, mesh) ** 2))
    )(w)
    np.testing.assert_allclose(np.asarray(g_sh), np.asarray(g_ref), rtol=1e-3, atol=1e-4)


def test_sharded_16q_preset_one_train_step():
    """BASELINE config 3 end-to-end: one QSC train step at n_qubits=16 with the
    statevector sharded over the mesh (VERDICT r1 #4)."""
    from qdml_tpu.config import override, presets
    from qdml_tpu.train.qsc import init_sc_state, make_sc_train_step

    cfg = presets()["sharded_16q"]
    cfg = override(cfg, "data.data_len", 48)
    cfg = override(cfg, "train.batch_size", 4)
    cfg = override(cfg, "quantum.n_layers", 1)  # keep the CPU compile small
    loader = DMLGridLoader(cfg.data, cfg.train.batch_size)
    batch = next(iter(loader.epoch(0)))
    model, state = init_sc_state(cfg, quantum=True, steps_per_epoch=1)
    step = make_sc_train_step(model, needs_rng=cfg.quantum.use_quantumnat)
    state, m = step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(m["loss"]))


def _tiny_setup(batch_size=16):
    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=16),
        train=TrainConfig(batch_size=batch_size, n_epochs=1),
    )
    loader = DMLGridLoader(cfg.data, batch_size)
    batch = next(iter(loader.epoch(0)))
    model, state = init_hdce_state(cfg, loader.steps_per_epoch)
    step = make_hdce_train_step(model, state.tx)
    return cfg, state, step, batch


def _first_leaf(tree):
    return np.asarray(jax.tree.leaves(tree)[0])


def test_dp_step_matches_single_device():
    cfg, state, step, batch = _tiny_setup()
    _, m_single = step(state, batch)
    new_single, _ = step(state, batch)

    mesh = make_mesh(MeshConfig(data_axis=-1, model_axis=1, fed_axis=1))
    assert mesh.shape["data"] == 8
    state_dp = replicate(state, mesh)
    batch_dp = shard_grid_batch(batch, mesh)
    new_dp, m_dp = step(state_dp, batch_dp)
    np.testing.assert_allclose(float(m_dp["loss"]), float(m_single["loss"]), rtol=1e-5)
    np.testing.assert_allclose(
        _first_leaf(new_dp.params), _first_leaf(new_single.params), rtol=1e-4, atol=1e-6
    )


def test_federated_step_matches_single_device():
    cfg, state, step, batch = _tiny_setup()
    new_single, m_single = step(state, batch)

    mesh = make_mesh(MeshConfig(fed_axis=3, data_axis=-1, model_axis=1))
    assert mesh.shape["fed"] == 3 and mesh.shape["data"] == 2
    state_fed = shard_hdce_state(state, mesh)
    batch_fed = shard_grid_batch(batch, mesh, fed=True)
    new_fed, m_fed = step(state_fed, batch_fed)
    np.testing.assert_allclose(float(m_fed["loss"]), float(m_single["loss"]), rtol=1e-5)
    np.testing.assert_allclose(
        _first_leaf(new_fed.params), _first_leaf(new_single.params), rtol=1e-4, atol=1e-6
    )
    # trunk params actually sharded over fed
    conv_leaf = jax.tree_util.tree_leaves_with_path(new_fed.params)
    stacked = [l for p, l in conv_leaf if "StackedConvP128" in str(p)][0]
    assert "fed" in str(stacked.sharding.spec)


def test_tensor_parallel_head():
    cfg, state, step, batch = _tiny_setup()
    _, m_single = step(state, batch)
    mesh = make_mesh(MeshConfig(fed_axis=1, data_axis=2, model_axis=4))
    state_tp = shard_hdce_state(state, mesh, tensor_parallel=True)
    batch_tp = shard_grid_batch(batch, mesh)
    _, m_tp = step(state_tp, batch_tp)
    np.testing.assert_allclose(float(m_tp["loss"]), float(m_single["loss"]), rtol=1e-5)


def test_multihost_local_batch_assembly_degenerates_single_process():
    """local_grid_batch_to_global on one process must equal shard_grid_batch
    (same data, same shardings) and run the SAME train step unchanged."""
    from qdml_tpu.parallel import local_grid_batch_to_global, process_batch_slice

    cfg, state, step, batch = _tiny_setup()
    mesh = make_mesh(MeshConfig(data_axis=-1, model_axis=1, fed_axis=1))
    start, local = process_batch_slice(cfg.train.batch_size, mesh)
    assert (start, local) == (0, cfg.train.batch_size)  # single process

    host_np = jax.tree.map(lambda x: np.asarray(x), batch)
    global_batch = local_grid_batch_to_global(host_np, mesh)
    ref = shard_grid_batch(batch, mesh)
    for a, b in zip(jax.tree.leaves(global_batch), jax.tree.leaves(ref)):
        assert a.sharding == b.sharding
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    state_dp = replicate(state, mesh)
    _, m = step(state_dp, global_batch)
    assert np.isfinite(float(m["loss"]))


def test_platform_helpers():
    """conftest pinned CPU via force_cpu(8); once a backend is live the pin
    reports inapplicable instead of silently half-applying."""
    import jax

    from qdml_tpu.utils.platform import backend_initialized, force_cpu

    assert jax.default_backend() == "cpu" and len(jax.devices()) == 8
    assert backend_initialized()
    assert force_cpu(4) is False  # too late to repin — and says so
    assert len(jax.devices()) == 8


def test_ensure_initialized_idempotent_and_strict(monkeypatch):
    """Benign repeat-init messages are swallowed; genuine coordinator
    failures propagate (a pod run must not silently degrade to independent
    single-process trainings)."""
    from qdml_tpu.parallel import multihost

    monkeypatch.setattr(multihost, "_runtime_initialized", lambda: False)

    calls = []

    def fake_init(**kw):
        calls.append(kw)
        raise RuntimeError("jax.distributed.initialize should only be called once.")

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    multihost.ensure_initialized(coordinator_address="h:1")  # no raise
    assert calls

    def fail_init(**kw):
        raise RuntimeError("barrier timed out waiting for coordinator")

    monkeypatch.setattr(jax.distributed, "initialize", fail_init)
    with pytest.raises(RuntimeError, match="barrier"):
        multihost.ensure_initialized(coordinator_address="h:1")


def test_process_batch_slice_rejects_interleaved_mesh(monkeypatch):
    """The process-contiguity contract is validated, not assumed: a mesh that
    interleaves processes along the data axis (as a hybrid DCN layout can)
    would silently permute the global batch, so it must be rejected."""
    from types import SimpleNamespace

    from qdml_tpu.parallel.multihost import process_batch_slice

    def fake_mesh(proc_of_coord):
        devs = np.array(
            [[SimpleNamespace(process_index=p)] for p in proc_of_coord], dtype=object
        )
        return SimpleNamespace(devices=devs, axis_names=("data", "model"))

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)

    start, local = process_batch_slice(8, fake_mesh([0, 0, 1, 1]))
    assert (start, local) == (4, 4)
    with pytest.raises(ValueError, match="not process-contiguous"):
        process_batch_slice(8, fake_mesh([0, 1, 0, 1]))
    with pytest.raises(ValueError, match="uneven|coordinates"):
        process_batch_slice(8, fake_mesh([0, 0, 1]))


def test_training_mesh_validation():
    """training_mesh builds a mesh on multi-device hosts and rejects layouts
    that would fail mid-epoch with opaque errors."""
    import dataclasses

    from qdml_tpu.parallel.mesh import training_mesh

    cfg = ExperimentConfig(train=TrainConfig(batch_size=16))
    mesh = training_mesh(cfg)
    assert mesh is not None and mesh.shape["data"] == 8

    bad_fed = dataclasses.replace(cfg, mesh=MeshConfig(fed_axis=2))
    with pytest.raises(ValueError, match="n_scenarios"):
        training_mesh(bad_fed)

    bad_names = dataclasses.replace(cfg, mesh=MeshConfig(data_axis_name="dp"))
    with pytest.raises(ValueError, match="axis names"):
        training_mesh(bad_names)

    # Batch divisibility is judged per-loader by the placer (it sees the
    # split-clamped size): indivisible batches degrade to replicated on one
    # process instead of crashing at startup.
    from qdml_tpu.config import DataConfig
    from qdml_tpu.data.datasets import DMLGridLoader
    from qdml_tpu.parallel.multihost import make_grid_placer

    loader = DMLGridLoader(DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64), 12)
    place = make_grid_placer(loader, mesh)
    batch = next(iter(loader.epoch(0)))
    assert place(batch)["indicator"].shape == batch["indicator"].shape


def test_make_grid_placer_multiprocess_decisions(monkeypatch):
    """Under multiple processes the placer slices the loader (divisible) or
    refuses outright (split-clamped indivisible batch)."""
    from qdml_tpu.config import DataConfig
    from qdml_tpu.data.datasets import DMLGridLoader
    from qdml_tpu.parallel import multihost

    from types import SimpleNamespace

    # A stub 2-process mesh: 8 data coordinates, first half owned by process
    # 0, second by process 1 (the real single-process mesh cannot express
    # multi-process ownership).
    devs = np.array(
        [[SimpleNamespace(process_index=i // 4)] for i in range(8)], dtype=object
    )
    mesh = SimpleNamespace(
        shape={"data": 8, "model": 1}, devices=devs, axis_names=("data", "model")
    )
    dcfg = DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64)

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)

    loader = DMLGridLoader(dcfg, 16)
    multihost.make_grid_placer(loader, mesh)
    assert loader._pslice == (8, 8)  # second host generates the upper half

    bad = DMLGridLoader(dcfg, 12)
    with pytest.raises(ValueError, match="multi-process"):
        multihost.make_grid_placer(bad, mesh)


def _stub_grid_mesh(pidx_grid):
    """Stub (fed, data, model) mesh from an array of process indices."""
    from types import SimpleNamespace

    arr = np.asarray(pidx_grid)
    devs = np.empty(arr.shape, dtype=object)
    for i, p in np.ndenumerate(arr):
        devs[i] = SimpleNamespace(process_index=int(p))
    return SimpleNamespace(
        shape={"fed": arr.shape[0], "data": arr.shape[1], "model": arr.shape[2]},
        devices=devs,
        axis_names=("fed", "data", "model"),
    )


def test_process_grid_slice_fed_rectangles(monkeypatch):
    """Federated cross-host ownership: each process generates exactly the
    (scenario, batch) rectangle its mesh coordinates cover (r2 weak #7)."""
    from qdml_tpu.parallel.multihost import process_grid_slice

    monkeypatch.setattr(jax, "process_count", lambda: 3)

    # one fed row per process, full data axis: scenario-partitioned only
    rows = [[[p], [p]] for p in range(3)]  # (fed=3, data=2, model=1)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    assert process_grid_slice(8, 3, _stub_grid_mesh(rows), fed=True) == (1, 1, 0, 8)

    # 6 single-cell processes: scenario AND batch partitioned
    monkeypatch.setattr(jax, "process_count", lambda: 6)
    cells = [[[2 * f + d] for d in range(2)] for f in range(3)]
    monkeypatch.setattr(jax, "process_index", lambda: 5)  # (fed=2, data=1)
    assert process_grid_slice(8, 3, _stub_grid_mesh(cells), fed=True) == (2, 1, 4, 4)

    # fed=False delegates to the batch-only contract (full scenario range)
    monkeypatch.setattr(jax, "process_count", lambda: 3)
    monkeypatch.setattr(jax, "process_index", lambda: 2)
    flat = [[[d // 2] for d in range(6)]]  # (fed=1, data=6, model=1): 2 cols/proc
    assert process_grid_slice(12, 3, _stub_grid_mesh(flat), fed=False) == (0, 3, 8, 4)


def test_process_grid_slice_rejects_bad_layouts(monkeypatch):
    from qdml_tpu.parallel.multihost import process_grid_slice

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)

    # a (fed, data) cell whose model group spans two processes
    split_cell = [[[0, 1]], [[1, 1]]]  # (fed=2, data=1, model=2)
    with pytest.raises(ValueError, match="model axis"):
        process_grid_slice(8, 2, _stub_grid_mesh(split_cell), fed=True)

    # diagonal ownership: cells (0,0) and (1,1) are not a rectangle
    diag = [[[0], [1]], [[1], [0]]]
    with pytest.raises(ValueError, match="rectangle"):
        process_grid_slice(8, 2, _stub_grid_mesh(diag), fed=True)

    # scenario count not divisible by the fed axis
    rows2 = [[[0]], [[1]]]
    with pytest.raises(ValueError, match="scenarios"):
        process_grid_slice(8, 3, _stub_grid_mesh(rows2), fed=True)


def test_scan_fused_steps_on_mesh_match_single_device():
    """Scan-fused dispatch composes with a single-process DP mesh: the
    sharding constraint on the in-scan generated batch makes the whole
    K-step program run SPMD with the same losses/params as the unsharded
    scan (generation partitions over the mesh — the intra-process twin of
    the multi-host per-slice data path)."""
    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.train.hdce import make_hdce_scan_steps

    cfg, state, _, _ = _tiny_setup()
    geom = ChannelGeometry.from_config(cfg.data)
    loader = DMLGridLoader(cfg.data, cfg.train.batch_size)
    scen, user = loader.grid_coords
    idx, snrs = next(loader.epoch_chunks(0, k=3))
    seed = jnp.uint32(cfg.data.seed)

    from qdml_tpu.train.hdce import init_hdce_state as _init

    model, state_a = _init(cfg, loader.steps_per_epoch)
    _, state_b = _init(cfg, loader.steps_per_epoch)
    run_single = make_hdce_scan_steps(model, geom)
    state_a, ms_a = run_single(state_a, seed, scen, user, idx, snrs)

    mesh = make_mesh(MeshConfig(data_axis=-1, model_axis=1, fed_axis=1))
    state_b = replicate(state_b, mesh)
    run_mesh = make_hdce_scan_steps(model, geom, mesh=mesh)
    state_b, ms_b = run_mesh(state_b, seed, scen, user, idx, snrs)

    np.testing.assert_allclose(
        np.asarray(ms_b["loss"]), np.asarray(ms_a["loss"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        _first_leaf(state_b.params), _first_leaf(state_a.params), rtol=1e-4, atol=1e-6
    )
