"""Real multi-process clusters through the production multi-host training path.

Round-2 verdict (missing #2): every multi-host contract was verified only by
stubbing ``device.process_index`` in one process. These tests launch actual
OS processes that form ``jax.distributed`` clusters on localhost (CPU
backend, Gloo collectives) and train a full HDCE epoch through
``training_mesh`` / ``shard_hdce_state`` / ``make_grid_placer``:

- ``dp``: 2 processes x 2 devices — per-process batch-slice generation,
  global array assembly, cross-process gradient psum;
- ``fed``: 3 processes x 1 device — federated scenario sharding ACROSS
  processes (round-2 weak #7: config 4's "federated across pod slices"):
  each rank generates and trains only its own scenario row, with the shared
  head aggregated over the wire.

Each cluster's loss history must match the single-process run of the
identical mesh. Slow-marked (cold jax starts + XLA CPU compiles per
process); run with ``pytest -m slow tests/test_multihost_2proc.py``.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch(mode: str, rank: int, port: int, out: str, log_path: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # The worker pins its own platform/device-count; scrub ambient overrides.
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    # Log to a FILE, not a pipe: live cluster ranks must never block on an
    # unread pipe buffer mid-collective while the parent waits on another
    # rank (classic sequential-communicate deadlock).
    log = open(log_path, "w")
    return subprocess.Popen(
        [sys.executable, _WORKER, mode, str(rank), str(port), out],
        env=env,
        cwd=_REPO,
        stdout=log,
        stderr=subprocess.STDOUT,
        text=True,
    )


def _run_cluster(mode: str, nproc: int, tmp_path):
    port = _free_port()
    outs = [str(tmp_path / f"{mode}_rank{r}.json") for r in range(nproc)]
    logs = [str(tmp_path / f"{mode}_rank{r}.log") for r in range(nproc)]
    procs = [_launch(mode, r, port, outs[r], logs[r]) for r in range(nproc)]
    try:
        for p in procs:
            p.wait(timeout=900)
    except subprocess.TimeoutExpired:
        # A hung collective is the exact failure mode under test: kill every
        # rank and surface all logs instead of leaking live processes.
        for p in procs:
            p.kill()
        tails = "\n".join(
            f"--- {mode} rank {r} ---\n{open(lg).read()[-2000:]}"
            for r, lg in enumerate(logs)
        )
        pytest.fail(f"{mode} cluster deadlocked (15 min):\n{tails}")
    for r, p in enumerate(procs):
        log = open(logs[r]).read()
        assert p.returncode == 0, f"{mode} rank {r} failed:\n{log[-3000:]}"

    ref_out = str(tmp_path / f"{mode}_single.json")
    ref_log = str(tmp_path / f"{mode}_single.log")
    single = _launch(mode, -1, port, ref_out, ref_log)
    try:
        single.wait(timeout=900)
    except subprocess.TimeoutExpired:
        single.kill()
        pytest.fail(
            f"{mode} single-process reference hung:\n{open(ref_log).read()[-2000:]}"
        )
    log = open(ref_log).read()
    assert single.returncode == 0, f"{mode} single-process reference failed:\n{log[-3000:]}"
    _assert_primary_writer_telemetry(outs)
    return [json.load(open(o)) for o in outs], json.load(open(ref_out))


def _assert_primary_writer_telemetry(outs):
    """Only rank 0 writes the telemetry stream, and its first line is a
    manifest recording the real cluster topology."""
    metrics = [o + ".metrics.jsonl" for o in outs]
    assert os.path.exists(metrics[0]), "primary rank wrote no telemetry file"
    with open(metrics[0]) as fh:
        first = json.loads(fh.readline())
        rest = [json.loads(ln) for ln in fh if ln.strip()]
    assert first["kind"] == "manifest"
    assert first["jax"]["process_count"] == len(outs)
    assert first["jax"]["process_index"] == 0
    kinds = {r.get("kind") for r in rest}
    assert "span" in kinds and "counters" in kinds
    for path in metrics[1:]:
        assert not os.path.exists(path), f"non-primary rank wrote {path}"


@pytest.mark.slow
def test_two_process_hdce_matches_single_process(tmp_path):
    recs, ref = _run_cluster("dp", 2, tmp_path)
    assert [r["nproc"] for r in recs] == [2, 2]
    assert [r["n_global_devices"] for r in recs] == [4, 4]
    assert ref["nproc"] == 1 and ref["n_global_devices"] == 4

    # Both ranks observe identical (replicated, psum-aggregated) metrics...
    np.testing.assert_allclose(recs[0]["train_loss"], recs[1]["train_loss"], rtol=1e-6)
    np.testing.assert_allclose(recs[0]["val_nmse"], recs[1]["val_nmse"], rtol=1e-6)
    # ...and the 2-process cluster reproduces the single-process run: the
    # per-process slice generation + global assembly is data-identical and
    # the cross-process psum is the same reduction over the same 4-wide mesh.
    np.testing.assert_allclose(recs[0]["train_loss"], ref["train_loss"], rtol=1e-5)
    np.testing.assert_allclose(recs[0]["val_nmse"], ref["val_nmse"], rtol=1e-5)


@pytest.mark.slow
def test_three_process_federated_matches_single_process(tmp_path):
    """Fed-over-the-wire: one base station (scenario trunk) per process."""
    recs, ref = _run_cluster("fed", 3, tmp_path)
    assert [r["nproc"] for r in recs] == [3, 3, 3]
    assert ref["nproc"] == 1 and ref["n_global_devices"] == 3

    for r in (1, 2):
        np.testing.assert_allclose(recs[0]["train_loss"], recs[r]["train_loss"], rtol=1e-6)
    np.testing.assert_allclose(recs[0]["train_loss"], ref["train_loss"], rtol=1e-5)
    np.testing.assert_allclose(recs[0]["val_nmse"], ref["val_nmse"], rtol=1e-5)
