"""Two REAL processes through the production multi-host training path.

Round-2 verdict (missing #2): every multi-host contract was verified only by
stubbing ``device.process_index`` in one process. This test launches two
actual OS processes that form a ``jax.distributed`` cluster on localhost
(CPU backend, 2 virtual devices each, Gloo collectives), trains one full
HDCE epoch through ``training_mesh`` / ``shard_hdce_state`` /
``make_grid_placer`` — per-process slice generation, global array assembly,
cross-process gradient psum — and asserts the loss history matches the
single-process run of the identical 4-wide data-parallel config.

Slow-marked (two cold jax starts + an XLA CPU compile per process); run with
``pytest -m slow tests/test_multihost_2proc.py``.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch(rank: int, port: int, out: str, log_path: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # The worker pins its own platform/device-count; scrub ambient overrides.
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    # Log to a FILE, not a pipe: two live cluster ranks must never block on
    # an unread pipe buffer mid-collective while the parent waits on the
    # other rank (classic sequential-communicate deadlock).
    log = open(log_path, "w")
    return subprocess.Popen(
        [sys.executable, _WORKER, str(rank), str(port), out],
        env=env,
        cwd=_REPO,
        stdout=log,
        stderr=subprocess.STDOUT,
        text=True,
    )


@pytest.mark.slow
def test_two_process_hdce_matches_single_process(tmp_path):
    port = _free_port()
    outs = [str(tmp_path / f"rank{r}.json") for r in (0, 1)]
    log_paths = [str(tmp_path / f"rank{r}.log") for r in (0, 1)]
    procs = [_launch(r, port, outs[r], log_paths[r]) for r in (0, 1)]
    for r, p in enumerate(procs):
        p.wait(timeout=900)
    for r, p in enumerate(procs):
        log = open(log_paths[r]).read()
        assert p.returncode == 0, f"rank {r} failed:\n{log[-3000:]}"

    ref_out = str(tmp_path / "single.json")
    ref_log = str(tmp_path / "single.log")
    single = _launch(-1, port, ref_out, ref_log)
    single.wait(timeout=900)
    log = open(ref_log).read()
    assert single.returncode == 0, f"single-process reference failed:\n{log[-3000:]}"

    recs = [json.load(open(o)) for o in outs]
    ref = json.load(open(ref_out))
    assert [r["nproc"] for r in recs] == [2, 2]
    assert [r["n_global_devices"] for r in recs] == [4, 4]
    assert ref["nproc"] == 1 and ref["n_global_devices"] == 4

    # Both ranks observe identical (replicated, psum-aggregated) metrics...
    np.testing.assert_allclose(recs[0]["train_loss"], recs[1]["train_loss"], rtol=1e-6)
    np.testing.assert_allclose(recs[0]["val_nmse"], recs[1]["val_nmse"], rtol=1e-6)
    # ...and the 2-process cluster reproduces the single-process run: the
    # per-process slice generation + global assembly is data-identical and
    # the cross-process psum is the same reduction over the same 4-wide mesh.
    np.testing.assert_allclose(recs[0]["train_loss"], ref["train_loss"], rtol=1e-5)
    np.testing.assert_allclose(recs[0]["val_nmse"], ref["val_nmse"], rtol=1e-5)
