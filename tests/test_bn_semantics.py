"""Quantify the BatchNorm deviation of the fused HDCE step (VERDICT r1 #6).

The reference runs NINE separate per-cell backwards per step, so each
BatchNorm normalizes over ONE (scenario, user) cell's batch
(``Runner_P128_QuantumNAT_onchipQNN.py:181-199``). The fused TPU step
reshapes the grid to (S, U*B), pooling BN batch statistics across the U user
cells of a scenario (``qdml_tpu/train/hdce.py``). Gradient accumulation is
linear, so the ONLY deviation channel is BN train-mode statistics (mean/var
over 256 vs 768 samples) — everything else is mathematically identical.

A second channel found by this measurement: the per-cell loop applies
``n_users`` sequential BN *running-stat* updates per step where the fused
step applies one, so fused running stats warmed up 3x slower and early-eval
NMSE lagged ~11% relative at 50 steps. The HDCE model now compensates with
``bn_momentum = 0.9 ** n_users`` (one update, same timescale as the
reference's three updates at torch's per-update decay 0.9), which closes
that gap to <2%.

Measured numbers (50 steps, default geometry, bs=32/cell, this host):

- max per-step train-loss gap 2.7e-2 relative (batch stats over 96 vs 32
  samples; shrinks with the real cell batch of 256),
- parameter drift after 50 steps 3.1e-2 relative L2 (Adam's sign-like early
  dynamics amplify tiny BN-stat differences),
- validation NMSE 0.4279 (fused) vs 0.4319 (per-cell) — 0.9% relative, the
  fused variant marginally ahead.

i.e. the deviation is real but bounded and does not change training behavior;
the fused step's docstring carries these bounds.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qdml_tpu.config import DataConfig, ExperimentConfig, TrainConfig
from qdml_tpu.data.datasets import DMLGridLoader
from qdml_tpu.train.hdce import cell_nmse, init_hdce_state, make_hdce_train_step

N_STEPS = 50


def make_percell_train_step(model, tx):
    """Reference BN semantics: one forward per USER cell (BN normalizes each
    (scenario, user) cell's batch alone), losses summed — the gradient
    accumulation pattern of Runner...py:181-199 with per-cell BN statistics.
    Running BN stats chain through the U sequential forwards like the
    reference's 9 sequential backwards do."""

    @jax.jit
    def step(state, batch):
        s, u, b = batch["yp_img"].shape[:3]

        def loss_fn(params):
            stats = state.batch_stats
            total = 0.0
            total_perf = 0.0
            for ui in range(u):
                x_u = batch["yp_img"][:, ui]  # (S, B, H, W, 2)
                out, upd = model.apply(
                    {"params": params, "batch_stats": stats},
                    x_u,
                    train=True,
                    mutable=["batch_stats"],
                )
                stats = upd["batch_stats"]
                pred = out.reshape(s, 1, b, -1)
                total = total + jnp.sum(cell_nmse(pred, batch["h_label"][:, ui : ui + 1]))
                total_perf = total_perf + jnp.sum(
                    cell_nmse(pred, batch["h_perf"][:, ui : ui + 1])
                )
            loss = total / (s * u)
            return loss, (stats, total_perf / (s * u))

        (loss, (new_stats, loss_perf)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        state = state.apply_gradients(grads=grads)
        state = state.replace(batch_stats=new_stats)
        return state, {"loss": loss, "loss_perf": loss_perf}

    return step


def _rel_l2(a, b) -> float:
    num = sum(float(jnp.sum((x - y) ** 2)) for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))
    den = sum(float(jnp.sum(y**2)) for y in jax.tree.leaves(b))
    return float(np.sqrt(num / max(den, 1e-30)))


@pytest.mark.slow
def test_fused_vs_percell_bn_drift():
    cfg = ExperimentConfig(
        data=DataConfig(data_len=256),
        train=TrainConfig(batch_size=32, n_epochs=1),
    )
    loader = DMLGridLoader(cfg.data, cfg.train.batch_size)
    batches = list(loader.epoch(0))
    model, state_f = init_hdce_state(cfg, loader.steps_per_epoch)
    # Identical init, but materially distinct buffers: the train step donates
    # its state on accelerator backends, so an alias would be consumed by the
    # first step and poison the second.
    state_p = jax.tree.map(lambda x: jnp.array(x), state_f)

    fused = make_hdce_train_step(model, state_f.tx)
    # The per-cell reference applies n_users sequential BN updates per step at
    # torch's per-update decay 0.9 (BatchNorm2d momentum=0.1); the fused model
    # compensates with 0.9**n_users in ONE update (init_hdce_state). Same
    # warm-up timescale, same params.
    percell = make_percell_train_step(model.clone(bn_momentum=0.9), state_p.tx)

    gaps = []
    for i in range(N_STEPS):
        batch = batches[i % len(batches)]
        state_f, mf = fused(state_f, batch)
        state_p, mp = percell(state_p, batch)
        lf, lp = float(mf["loss"]), float(mp["loss"])
        gaps.append(abs(lf - lp) / max(lp, 1e-12))

    # 1) per-step loss gap bounded
    assert max(gaps) < 0.05, f"loss gap {max(gaps):.4f} exceeds 5%"

    # 2) parameter drift bounded (Adam amplifies tiny BN-stat differences
    #    elementwise; the drift must stay far below the parameter scale)
    drift = _rel_l2(state_f.params, state_p.params)
    assert drift < 5e-2, f"param drift {drift:.4f} exceeds 5e-2 after {N_STEPS} steps"

    # 3) the two models are equivalent estimators on held-out data
    val = DMLGridLoader(cfg.data, cfg.train.batch_size, "val")
    vbatch = next(iter(val.epoch(0, shuffle=False)))
    s, u, b = vbatch["yp_img"].shape[:3]
    x = vbatch["yp_img"].reshape(s, u * b, *vbatch["yp_img"].shape[3:])

    def val_nmse(state):
        out = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats}, x, train=False
        )
        pred = out.reshape(s, u, b, -1)
        return float(jnp.mean(cell_nmse(pred, vbatch["h_label"])))

    nf, npc = val_nmse(state_f), val_nmse(state_p)
    assert abs(nf - npc) / npc < 0.02, f"val NMSE gap {nf:.5f} vs {npc:.5f}"
    print(
        f"\nBN semantics: max step loss gap {max(gaps):.2e}, "
        f"param drift {drift:.2e}, val NMSE fused {nf:.5f} vs per-cell {npc:.5f}"
    )


@pytest.mark.slow
def test_percell_grads_match_fused_with_frozen_bn():
    """With BN in inference mode (frozen stats) the per-cell and fused losses
    and gradients are EXACTLY the linear-accumulation identity — isolating BN
    batch statistics as the only deviation channel."""
    cfg = ExperimentConfig(
        data=DataConfig(data_len=64),
        train=TrainConfig(batch_size=8, n_epochs=1),
    )
    loader = DMLGridLoader(cfg.data, cfg.train.batch_size)
    batch = next(iter(loader.epoch(0)))
    model, state = init_hdce_state(cfg, loader.steps_per_epoch)
    s, u, b = batch["yp_img"].shape[:3]

    def fused_loss(params):
        x = batch["yp_img"].reshape(s, u * b, *batch["yp_img"].shape[3:])
        out = model.apply({"params": params, "batch_stats": state.batch_stats}, x, train=False)
        return jnp.mean(cell_nmse(out.reshape(s, u, b, -1), batch["h_label"]))

    def percell_loss(params):
        total = 0.0
        for ui in range(u):
            out = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                batch["yp_img"][:, ui],
                train=False,
            )
            total = total + jnp.sum(
                cell_nmse(out.reshape(s, 1, b, -1), batch["h_label"][:, ui : ui + 1])
            )
        return total / (s * u)

    lf, gf = jax.value_and_grad(fused_loss)(state.params)
    lp, gp = jax.value_and_grad(percell_loss)(state.params)
    np.testing.assert_allclose(float(lf), float(lp), rtol=1e-6)
    for a, c in zip(jax.tree.leaves(gf), jax.tree.leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), rtol=1e-4, atol=1e-6)
