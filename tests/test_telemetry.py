"""Telemetry layer: manifest round-trip, span nesting, primary-writer gating,
step-clock counters, train-loop integration, and the report regression gate."""

import json
import time

import pytest

from qdml_tpu.config import DataConfig, ExperimentConfig, ModelConfig, TrainConfig, override
from qdml_tpu.telemetry import (
    Histogram,
    StepClock,
    Telemetry,
    config_hash,
    device_memory_snapshot,
    run_manifest,
    set_sink,
    span,
)
from qdml_tpu.telemetry.report import (
    EXIT_OK,
    EXIT_REGRESSION,
    EXIT_USAGE,
    build_report,
    report_main,
)
from qdml_tpu.utils.metrics import MetricsLogger


def _read_jsonl(path):
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


# ---------------------------------------------------------------------------
# Manifest
# ---------------------------------------------------------------------------


def test_run_manifest_roundtrip():
    """The manifest JSON-round-trips and carries every provenance field the
    acceptance contract names: config hash, devices, knobs, seeds."""
    cfg = ExperimentConfig()
    man = json.loads(json.dumps(run_manifest(cfg, argv=["train-hdce"])))
    assert man["kind"] == "manifest"
    assert man["config_hash"] == config_hash(cfg)
    assert man["knobs"]["rng_impl"] == "threefry"
    assert man["knobs"]["trig_impl"] == "direct"
    assert man["knobs"]["moments_dtype"] == "float32"
    assert man["seeds"] == {"data": cfg.data.seed, "train": cfg.train.seed}
    assert man["jax"]["device_count"] >= 1
    assert man["jax"]["process_count"] == 1
    assert man["config"]["train"]["batch_size"] == cfg.train.batch_size
    # a knob change must change the content hash
    assert config_hash(override(cfg, "data.rng_impl", "rbg")) != man["config_hash"]


def test_run_manifest_without_jax_info():
    """include_jax=False keeps the manifest usable for the no-jax bench parent."""
    man = run_manifest(include_jax=False, argv=["bench.py"])
    assert man["jax"] is None and man["kind"] == "manifest"


# ---------------------------------------------------------------------------
# Sink + spans + counters
# ---------------------------------------------------------------------------


def test_metrics_logger_writes_manifest_header_and_legacy_records(tmp_path):
    cfg = ExperimentConfig()
    path = str(tmp_path / "m.jsonl")
    lg = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
    lg.log(step=1, loss=0.5)
    lg.close()
    lines = _read_jsonl(path)
    assert lines[0]["kind"] == "manifest"
    # metric records keep the legacy bare shape — no kind field
    assert "kind" not in lines[1] and lines[1]["step"] == 1 and lines[1]["loss"] == 0.5
    # legacy readers skip the header (no train_loss/epoch keys at top level)
    from qdml_tpu.eval.loss_curves import read_loss_history

    assert read_loss_history(path) == []


def test_non_primary_process_writes_nothing(tmp_path, monkeypatch):
    """Multihost primary-writer gate: a non-zero process index makes the sink
    inert — no file is even created."""
    import jax

    monkeypatch.setattr(jax, "process_index", lambda: 1)
    lg = MetricsLogger(str(tmp_path / "x.jsonl"), echo=False, manifest={"kind": "manifest"})
    lg.log(step=0, loss=1.0)
    with lg.span("s"):
        pass
    lg.close()
    assert not (tmp_path / "x.jsonl").exists()


def test_span_nesting(tmp_path):
    tele = Telemetry(str(tmp_path / "t.jsonl"))
    with span("outer", sink=tele):
        with span("inner", sink=tele, tag="x"):
            time.sleep(0.001)
    tele.close()
    inner, outer = _read_jsonl(tmp_path / "t.jsonl")  # children close first
    assert inner["path"] == "outer/inner" and inner["depth"] == 1 and inner["tag"] == "x"
    assert outer["path"] == "outer" and outer["depth"] == 0
    assert outer["dur_s"] >= inner["dur_s"] > 0
    assert inner["process"] == 0


def test_span_without_sink_is_inert():
    with span("nowhere"):
        pass  # must not raise or write anywhere


def test_histogram_percentiles():
    h = Histogram()
    for v in [0.001 * i for i in range(1, 101)]:
        h.add(v)
    s = h.summary()
    assert s["n"] == 100 and s["max_ms"] == 100.0
    assert s["p50_ms"] == pytest.approx(50.0, abs=2.0)
    assert s["p95_ms"] == pytest.approx(95.0, abs=2.0)
    assert Histogram().summary() is None


def test_step_clock_compile_steady_transfer(tmp_path):
    tele = Telemetry(str(tmp_path / "c.jsonl"))
    clock = StepClock("train", sink=tele)
    for _ in range(4):
        with clock.step() as st:
            time.sleep(0.002)
            st.transfer()
            time.sleep(0.001)
    clock.epoch_end(epoch=0)
    tele.close()
    lines = _read_jsonl(tmp_path / "c.jsonl")
    compile_span = [l for l in lines if l.get("name") == "compile_first_step"]
    assert compile_span and compile_span[0]["dur_s"] > 0
    cnt = [l for l in lines if l.get("kind") == "counters"][0]
    # first step is compile, the remaining 3 are steady state
    assert cnt["compile_s"] > 0 and cnt["step"]["n"] == 3
    assert {"p50_ms", "p95_ms", "max_ms"} <= set(cnt["step"])
    assert cnt["host_transfer"]["n"] == 3
    assert cnt["epoch"] == 0
    assert "compile_cache" in cnt and "memory" in cnt


def test_device_memory_snapshot_shape():
    snap = device_memory_snapshot()
    assert snap is not None and len(snap["devices"]) >= 1
    assert "kind" in snap["devices"][0]


# ---------------------------------------------------------------------------
# Train-loop integration (spans/counters reach the global sink)
# ---------------------------------------------------------------------------


def test_train_loop_emits_spans_and_counters(tmp_path):
    from qdml_tpu.train.hdce import train_hdce

    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=48),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=1, print_freq=1000),
    )
    tele = Telemetry(str(tmp_path / "train.jsonl"), manifest=run_manifest(cfg))
    set_sink(tele)
    try:
        train_hdce(cfg)
    finally:
        set_sink(None)
        tele.close()
    lines = _read_jsonl(tmp_path / "train.jsonl")
    assert lines[0]["kind"] == "manifest"
    names = [l.get("name") for l in lines if l.get("kind") == "span"]
    assert "train_epoch" in names and "val_epoch" in names
    assert "compile_first_step" in names
    counters = [l for l in lines if l.get("kind") == "counters"]
    assert counters and counters[0]["name"] == "hdce_train"
    assert counters[0]["step"] is not None and counters[0]["step"]["n"] >= 1


# ---------------------------------------------------------------------------
# report: delta table + regression gate
# ---------------------------------------------------------------------------


def _bench_record(value, platform="cpu_fallback", detail=1000.0):
    return {
        "metric": "hdce_train_samples_per_sec_per_chip",
        "value": value,
        "unit": "samples/sec",
        "platform": platform,
        "details": {"hdce_f32": {"samples_per_sec": detail, "model_tflops": 1.0}},
    }


def _write(tmp_path, name, *objs):
    p = tmp_path / name
    with open(p, "w") as fh:
        for o in objs:
            fh.write(json.dumps(o) + "\n")
    return str(p)


def test_report_regression_gate_exit_codes(tmp_path, capsys):
    """A synthetic 20% throughput regression vs the baseline exits nonzero
    with a markdown delta table; a within-threshold run exits 0."""
    base = _write(tmp_path, "baseline.json", _bench_record(1000.0))
    man = run_manifest(ExperimentConfig(), include_jax=False)
    bad = _write(tmp_path, "bad.jsonl", man, _bench_record(800.0, detail=790.0))
    ok = _write(tmp_path, "ok.jsonl", man, _bench_record(950.0, detail=990.0))

    rc = report_main([f"--current={bad}", f"--baseline={base}", "--threshold=10"])
    md = capsys.readouterr().out
    assert rc == EXIT_REGRESSION
    assert "| metric | baseline | current | delta |" in md
    assert "**REGRESSION**" in md and "-20.0%" in md

    rc = report_main([f"--current={ok}", f"--baseline={base}", "--threshold=10"])
    assert rc == EXIT_OK


def test_report_platform_mismatch_disarms_gate(tmp_path):
    base = _write(tmp_path, "b.json", _bench_record(1000.0, platform="tpu-v5e"))
    cur = _write(tmp_path, "c.json", _bench_record(10.0, platform="cpu_fallback"))
    md, regressions, armed = build_report([cur], base, 10.0)
    assert regressions and not armed
    assert "platform mismatch" in md
    rc = report_main([f"--current={cur}", f"--baseline={base}"])
    assert rc == EXIT_OK  # reported but not gated


def test_report_handles_driver_wrapper_and_empty_baseline(tmp_path):
    """BENCH_rNN.json driver wrappers (record in `tail`) and the targets-only
    BASELINE.json both load without crashing."""
    wrapper = {
        "n": 5,
        "rc": 0,
        "tail": "noise\n" + json.dumps(_bench_record(500.0)) + "\n",
        "parsed": None,
    }
    cur = _write(tmp_path, "wrapped.json", wrapper)
    baseline_targets = _write(
        tmp_path, "BASELINE.json", {"metric": "targets", "published": {}}
    )
    md, regressions, _ = build_report([cur], baseline_targets, 10.0)
    assert "no throughput metrics" in md and not regressions
    # and the wrapper's record is really extracted when used as baseline
    md2, regressions2, armed2 = build_report(
        [_write(tmp_path, "now.json", _bench_record(100.0))], cur, 10.0
    )
    assert regressions2 and armed2


def test_report_fails_closed_when_current_measured_nothing(tmp_path):
    """A baseline with numbers vs a current run whose record carries no
    throughput (the all-errored bench path) must gate CI, not pass it."""
    base = _write(tmp_path, "b.json", _bench_record(1000.0))
    dead = _write(
        tmp_path,
        "dead.jsonl",
        {"kind": "manifest"},
        {"metric": "hdce_train_samples_per_sec_per_chip", "value": None,
         "platform": "none", "error": "all bench children failed"},
    )
    md, regressions, armed = build_report([dead], base, 10.0)
    assert regressions and armed and "gate fails" in md
    assert report_main([f"--current={dead}", f"--baseline={base}"]) == EXIT_REGRESSION


def test_report_heterogeneous_current_platforms_disarm_gate(tmp_path):
    """Merged current files from different platforms cannot be attributed to
    one platform — deltas shown, gate disarmed."""
    base = _write(tmp_path, "b.json", _bench_record(1000.0, platform="tpu-v5e"))
    c1 = _write(tmp_path, "c1.json", _bench_record(990.0, platform="tpu-v5e"))
    c2 = _write(tmp_path, "c2.json", _bench_record(10.0, platform="cpu_fallback"))
    md, regressions, armed = build_report([c1, c2], base, 10.0)
    assert not armed and "span platforms" in md


def test_report_roofline_fraction_gate(tmp_path):
    """The roofline-fraction rows gate with the inverted sign: the fraction
    DROPPING beyond the threshold is the regression; holding or rising is
    not (docs/ROOFLINE.md)."""
    from qdml_tpu.telemetry.report import build_report_data

    def art(name, frac):
        rec = _bench_record(1000.0)
        rec["details"]["hdce_f32"]["roofline"] = {"fraction": frac, "bound": "memory"}
        return _write(tmp_path, name, rec)

    base = art("b.json", 0.50)
    ok = build_report_data([art("ok.json", 0.47)], base, 10.0)
    assert not ok["regressions"]
    assert any(
        g["kind"] == "roofline" and g["status"] == "ok" for g in ok["gates"]
    )
    bad = build_report_data([art("bad.json", 0.30)], base, 10.0)
    assert any(
        r["metric"] == "hdce_f32.roofline_fraction" for r in bad["regressions"]
    )
    assert "roofline fraction" in bad["markdown"]


def test_report_host_transfer_gate_forces_exit_even_disarmed(tmp_path):
    """A reappearing steady-state host transfer is a program property: it
    forces the regression exit even when the perf gate is disarmed by a
    platform mismatch (the lint-gate rule applied to transfers)."""
    from qdml_tpu.telemetry.report import build_report_data

    def art(name, ht, platform):
        rec = _bench_record(1000.0, platform=platform)
        rec["details"]["hdce_f32"]["host_transfers"] = ht
        return _write(tmp_path, name, rec)

    base = art("b.json", 0, "tpu-v5e")
    cur = art("c.json", 3, "cpu_fallback")  # platform mismatch disarms perf
    data = build_report_data([cur], base, 10.0)
    assert not data["gate_armed"] and data["transfer_failed"]
    assert any(g["kind"] == "host-transfers" and g["status"] == "regression"
               for g in data["gates"])
    assert report_main([f"--current={cur}", f"--baseline={base}"]) == EXIT_REGRESSION
    # equal (zero) transfers: ok row, no forced exit
    cur2 = art("c2.json", 0, "tpu-v5e")
    data2 = build_report_data([cur2], base, 10.0)
    assert not data2["transfer_failed"]


def test_report_main_usage_errors(tmp_path, capsys):
    assert report_main([]) == EXIT_USAGE
    assert report_main(["--current=/no/such", "--baseline=/no/such"]) == EXIT_USAGE
    assert report_main(["--current=a", "--baseline=b", "--threshold=10%"]) == EXIT_USAGE
    capsys.readouterr()


def test_cli_unknown_command_writes_no_metrics_file(tmp_path, monkeypatch, capsys):
    """A typo'd command must not create a manifest-headed metrics stream."""
    from qdml_tpu import cli
    from qdml_tpu.parallel import multihost

    # in-process: the backend is already up, so the pod-autodetect init this
    # container's env hints at would (correctly) refuse — not under test here
    monkeypatch.setattr(multihost, "pod_env_hint", lambda: False)
    monkeypatch.chdir(tmp_path)
    assert cli.main(["train-hcde"]) == 2
    assert not (tmp_path / "workspace").exists()
    capsys.readouterr()


def test_cli_report_subcommand(tmp_path, capsys):
    from qdml_tpu import cli

    base = _write(tmp_path, "base.json", _bench_record(1000.0))
    cur = _write(tmp_path, "cur.json", _bench_record(700.0))
    out = tmp_path / "report.md"
    rc = cli.main(
        ["report", f"--current={cur}", f"--baseline={base}", f"--out={out}"]
    )
    assert rc == EXIT_REGRESSION
    assert out.exists() and "**REGRESSION**" in out.read_text()
    capsys.readouterr()


# ---------------------------------------------------------------------------
# Satellite validations (knob rejection + non-adam warning)
# ---------------------------------------------------------------------------


def test_moments_dtype_rejects_unknown():
    from qdml_tpu.train.optim import get_optimizer

    with pytest.raises(ValueError, match="moments_dtype"):
        get_optimizer(TrainConfig(moments_dtype="bf16"), steps_per_epoch=10)


def test_moments_dtype_warns_on_non_adam():
    from qdml_tpu.train.optim import get_optimizer

    with pytest.warns(UserWarning, match="moments_dtype"):
        get_optimizer(
            TrainConfig(optimizer="adamw", moments_dtype="bfloat16"),
            steps_per_epoch=10,
        )


def test_trig_impl_rejects_unknown():
    from qdml_tpu.data.channels import ChannelGeometry

    with pytest.raises(ValueError, match="trig_impl"):
        ChannelGeometry.from_config(DataConfig(trig_impl="fast"))
    with pytest.raises(ValueError, match="rng_impl"):
        ChannelGeometry(rng_impl="philox")
