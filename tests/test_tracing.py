"""End-to-end request tracing (telemetry/tracing.py, docs/TELEMETRY.md):
sampling, wire round-trip, phase decomposition through the serve loop, the
overhead-free trace_sample=0 pins (HLO identity, zero compiles, zero
allocations), router span propagation with failover wire spans and dedup
re-attachment, exact phase aggregation, and the report's phase-gate section.
"""

from __future__ import annotations

import asyncio
import json
import threading
from concurrent.futures import Future

import numpy as np
import pytest

from qdml_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    ServeConfig,
    TrainConfig,
)
from qdml_tpu.serve import (
    Prediction,
    ReplicaPool,
    ServeClient,
    ServeEngine,
    ServeLoop,
    ServeMetrics,
    serve_async,
)
from qdml_tpu.serve.loadgen import make_request_samples, run_loadgen
from qdml_tpu.telemetry import Histogram
from qdml_tpu.telemetry.tracing import PHASES, TraceContext, trace_sampled


def _tiny_cfg(**serve_kw):
    # identical shapes to tests/test_serve.py / test_faults.py so the
    # persistent compile cache shares the bucket executables across files
    serve = dict(
        max_batch=8, buckets=(4, 8), max_wait_ms=1.0, max_queue=32,
        batching="bucket",
    )
    serve.update(serve_kw)
    return ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=1),
        serve=ServeConfig(**serve),
    )


@pytest.fixture(scope="module")
def warmed():
    """One warmed engine with serve.trace_sample=1.0 in its config — the
    engine itself never reads the knob (tracing is host-side only), so loops
    that want the untraced path pass trace_sample=0.0 and share the same
    executables: one compile budget for the whole module."""
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    cfg = _tiny_cfg(trace_sample=1.0)
    _, hdce_state = init_hdce_state(cfg, 4)
    hdce_vars = {"params": hdce_state.params, "batch_stats": hdce_state.batch_stats}
    _, sc_state = init_sc_state(cfg, quantum=False, steps_per_epoch=4)
    engine = ServeEngine(cfg, hdce_vars, {"params": sc_state.params})
    samples = make_request_samples(cfg, 32)
    engine.warmup()
    return cfg, engine, samples


# ---------------------------------------------------------------------------
# Unit: sampling + wire format + unitless histograms
# ---------------------------------------------------------------------------


def test_trace_sampled_deterministic_and_rate_shaped():
    ids = [f"req-{i}" for i in range(2000)]
    assert not any(trace_sampled(r, 0.0) for r in ids)
    assert all(trace_sampled(r, 1.0) for r in ids)
    # deterministic: the same id decides the same way every call (the
    # client/router/backend agreement property)
    assert [trace_sampled(r, 0.25) for r in ids] == [
        trace_sampled(r, 0.25) for r in ids
    ]
    frac = sum(trace_sampled(r, 0.25) for r in ids) / len(ids)
    assert 0.15 < frac < 0.35  # loose: md5 bucketing, not an RNG contract
    # monotone in rate: an id sampled at a low rate stays sampled at higher
    sampled_low = {r for r in ids if trace_sampled(r, 0.1)}
    sampled_high = {r for r in ids if trace_sampled(r, 0.5)}
    assert sampled_low <= sampled_high


def test_trace_context_wire_round_trip():
    tr = TraceContext("abc")
    tr.add_phase("batch_wait", 0.001)
    tr.add_phase("wire", 0.0021)
    tr.add_phase("wire", 0.004)  # repeated phases survive (failover spans)
    tr.total_s = 0.0085
    wire = tr.to_wire()
    assert wire["phases"] == [["batch_wait", 1.0], ["wire", 2.1], ["wire", 4.0]]
    back = TraceContext.from_wire(json.loads(json.dumps(wire)))
    assert back.rid == "abc"
    assert [n for n, _ in back.phases] == ["batch_wait", "wire", "wire"]
    assert [d for _, d in back.phases] == pytest.approx([0.001, 0.0021, 0.004])
    assert back.total_s == pytest.approx(0.0085)
    assert back.phase_sum_s() == pytest.approx(0.0071)
    # negative durations clamp (fake/coarse clocks must not poison histograms)
    tr2 = TraceContext(1)
    tr2.add_phase("queue_wait", -0.5)
    assert tr2.phases == [("queue_wait", 0.0)]
    # malformed wire blocks degrade to None, never raise on the reply path
    assert TraceContext.from_wire(None) is None
    assert TraceContext.from_wire({"phases": [["x"]]}) is None
    assert TraceContext.from_wire({"phases": "garbage"}) is None
    assert TraceContext.from_wire(42) is None


def test_histogram_unitless_summary_and_sum():
    h = Histogram()
    for v in (1.0, 2.0, 3.0, 10.0):
        h.add(v)
    raw = h.summary(unit=None)
    # honest unitless keys: no *1e3 scaling, no _ms suffix (queue depth is a
    # count, batch fill a fraction — the old "stored as seconds" shim is gone)
    assert raw == {
        "n": 4, "mean": 4.0, "p50": 3.0, "p95": 10.0, "p99": 10.0, "max": 10.0,
    }
    ms = h.summary()
    assert ms["mean_ms"] == 4000.0 and ms["p50_ms"] == 3000.0
    assert h.sum() == pytest.approx(16.0)
    assert Histogram().summary(unit=None) is None


def test_phase_histogram_merge_exact_across_workers():
    """The replica/worker merge pin, phase edition: merged per-phase
    quantiles equal quantiles of the concatenated samples (Histogram keeps
    raw samples — mirrors the tests/test_numerics.py Histogram.merge pin)."""
    rng = np.random.default_rng(7)
    workers = []
    all_samples: dict[str, list[float]] = {p: [] for p in PHASES}
    for w in range(3):
        m = ServeMetrics()
        for i in range(40):
            tr = TraceContext(f"w{w}-{i}")
            for p in PHASES:
                d = float(rng.exponential(0.002))
                tr.add_phase(p, d)
                all_samples[p].append(d)
            pred = Prediction(
                rid=f"w{w}-{i}", h=np.zeros(4, np.float32), scenario=0,
                latency_s=tr.phase_sum_s(), bucket=8, batch_n=1, trace=tr,
            )
            m.observe_prediction(pred)
        workers.append(m)
    agg = ServeMetrics()
    for m in workers:
        agg.merge(m)
    assert agg.traced == 120
    for p in PHASES:
        ref = Histogram()
        for d in all_samples[p]:
            ref.add(d)
        assert agg.phase[p].summary() == ref.summary()
        assert agg.phase[p].sum() == pytest.approx(ref.sum())
    # the (n, sum_ms) pair the router sums exactly across processes
    blk = agg.phases()
    for p in PHASES:
        assert blk[p]["n"] == 120
        assert blk[p]["sum_ms"] == pytest.approx(
            round(sum(all_samples[p]) * 1e3, 3), abs=1e-2
        )


# ---------------------------------------------------------------------------
# Serve loop: decomposition + reconciliation + coverage
# ---------------------------------------------------------------------------


def test_serve_loop_phases_decompose_latency(warmed):
    cfg, engine, samples = warmed
    loop = ServeLoop(engine).start()  # cfg trace_sample=1.0: all traced
    try:
        futs = [loop.submit(samples["x"][i], rid=i) for i in range(16)]
        results = [f.result(timeout=30.0) for f in futs]
    finally:
        loop.stop()
    assert all(isinstance(r, Prediction) and r.trace is not None for r in results)
    for r in results:
        names = [n for n, _ in r.trace.phases]
        assert names == ["batch_wait", "queue_wait", "compute", "fetch"]
        # the future-resolution boundary closes the trace at the SAME number
        # the latency histogram sees
        assert r.trace.total_s == pytest.approx(r.latency_s)
        # phases partition the latency: sum never exceeds it, and the
        # unattributed residual (stack + metrics) stays small in-process
        assert r.trace.phase_sum_s() <= r.latency_s + 1e-6
        assert r.trace.phase_sum_s() >= 0.5 * r.latency_s
    m = loop.merged_metrics()
    blk = m.phases()
    assert set(blk) == {"batch_wait", "queue_wait", "compute", "fetch"}
    assert all(blk[p]["n"] == 16 for p in blk)
    cov = m.trace_coverage()
    assert cov == {"sampled": 16, "completed": 16, "fraction": 1.0}
    s = m.summary()
    assert s["phases"] == blk and s["trace"] == cov
    # unitless satellite: queue depth / batch fill keep their back-compat
    # keys, now with honest p99 alongside
    assert set(s["queue_depth"]) == {"n", "mean", "p50", "p95", "p99", "max"}


def test_trace_sample_zero_is_overhead_free(warmed, monkeypatch):
    """The non-negotiable pin: trace_sample=0 builds no TraceContext, stamps
    no dequeue clock, compiles nothing new, transfers nothing extra — and
    the executables are the SAME objects either way (tracing never enters
    the compiled program)."""
    import qdml_tpu.serve.server as server_mod
    from qdml_tpu.utils.compile_cache import compile_cache_stats

    cfg, engine, samples = warmed
    built = []

    class _CountingCtx(TraceContext):
        def __init__(self, *a, **kw):
            built.append(a)
            super().__init__(*a, **kw)

    monkeypatch.setattr(server_mod, "TraceContext", _CountingCtx)
    pre = compile_cache_stats()
    loop = ServeLoop(engine, trace_sample=0.0).start()
    try:
        futs = [loop.submit(samples["x"][i], rid=i) for i in range(12)]
        results = [f.result(timeout=30.0) for f in futs]
    finally:
        loop.stop()
    assert built == []  # zero allocations on the untraced path
    assert all(r.trace is None for r in results)
    assert compile_cache_stats() == pre  # zero extra compiles
    assert engine.request_path_compiles() == {"hits": 0, "misses": 0, "requests": 0}
    m = loop.merged_metrics()
    assert m.phases() is None and m.trace_coverage() is None
    assert m.summary()["phases"] is None
    # untraced infer stamps nothing (DispatchInfo timing stays None)
    *_out, info = engine.infer(samples["x"][:4])
    assert info.compute_s is None and info.fetch_s is None


def test_trace_knob_leaves_hlo_identical(warmed):
    """trace_sample is invisible to XLA: the serving forward lowers to
    byte-identical HLO whatever the knob says (the serve.checkify-OFF
    compile-identity pattern applied to tracing)."""
    import dataclasses

    import jax

    cfg, engine, _ = warmed
    hdce_live, clf_live = engine.live_vars()
    texts = []
    for rate in (0.0, 1.0):
        c = dataclasses.replace(
            cfg, serve=dataclasses.replace(cfg.serve, trace_sample=rate)
        )
        e = ServeEngine(c, hdce_live, clf_live)
        lowered = jax.jit(e._forward).lower(
            hdce_live, clf_live, np.zeros((4, *c.image_hw, 2), np.float32)
        )
        texts.append(lowered.as_text())
    assert texts[0] == texts[1]


def test_traced_infer_matches_untraced_numerics(warmed):
    cfg, engine, samples = warmed
    x = samples["x"][:5]
    h0, p0, c0, i0 = engine.infer(x)
    h1, p1, c1, i1 = engine.infer(x, traced=True)
    np.testing.assert_array_equal(h0, h1)
    np.testing.assert_array_equal(p0, p1)
    assert i1.compute_s is not None and i1.compute_s >= 0
    assert i1.fetch_s is not None and i1.fetch_s >= 0
    # chunked oversize dispatch sums phase durations across chunks
    big = np.concatenate([samples["x"]] * 2)[:19]
    *_rest, info = engine.infer(big, traced=True)
    assert info.chunks == 3 and info.compute_s > 0 and info.fetch_s > 0


# ---------------------------------------------------------------------------
# Socket + router propagation
# ---------------------------------------------------------------------------


@pytest.fixture()
def backend(warmed):
    """One socket backend (untraced by default — trace_sample=0 override) on
    an ephemeral port, with its own event loop thread."""
    cfg, engine, samples = warmed
    aloop = asyncio.new_event_loop()
    t = threading.Thread(target=aloop.run_forever, daemon=True)
    t.start()
    loop_ = ServeLoop(engine, trace_sample=0.0, name="trace-backend").start()
    ready: Future = Future()
    task = asyncio.run_coroutine_threadsafe(
        serve_async(loop_, "127.0.0.1", 0, ready, host_id="trace-b0",
                    dedup_ttl_s=5.0),
        aloop,
    )
    port = ready.result(timeout=30.0)
    yield cfg, samples, port, loop_
    task.cancel()
    aloop.call_soon_threadsafe(aloop.stop)
    t.join(timeout=10.0)
    loop_.stop()


def test_socket_trace_force_flag_and_reply_schema(backend):
    cfg, samples, port, _loop = backend
    with ServeClient("127.0.0.1", port, timeout_s=10.0) as c:
        plain = c.request(samples["x"][0], rid="plain-1")
        traced = c.request(samples["x"][1], rid="traced-1", trace=True)
    # server samples at 0: only the client-forced request carries a trace
    assert plain.get("ok") and "trace" not in plain
    tr = traced.get("trace")
    assert tr is not None and tr["id"] == "traced-1"
    names = [p[0] for p in tr["phases"]]
    assert names == ["batch_wait", "queue_wait", "compute", "fetch"]
    assert all(isinstance(p[1], float) and p[1] >= 0 for p in tr["phases"])
    assert tr["total_ms"] == pytest.approx(traced["latency_ms"], abs=0.01)


def test_router_prepends_spans_and_failover_wire_spans(backend):
    """The trace-propagation parity satellite: router spans + backend spans
    reconcile with the client-observed total; a dead-backend failover shows
    up as SEPARATE wire spans; a dedup re-attached retry carries the
    dedup_wait span and the re-attachment flag."""
    import time as _time

    from qdml_tpu.fleet import FleetRouter

    cfg, samples, port, _loop = backend
    # backend 0 is a dead port: requests whose ring primary lands there must
    # fail over to the live host, leaving a failed wire span behind
    router = FleetRouter(
        [("127.0.0.1", 1), ("127.0.0.1", port)],
        timeout_s=2.0, retries=0, eject_failures=1000,  # never eject: every
        # traced request may pay the dead attempt (the failover span source)
        eject_s=0.01, readmit_probes=1, poll_interval_s=30.0, failover=2,
        trace_sample=1.0,
    )
    try:
        failover_tr = None
        for i in range(32):
            rid = f"ft-{i}"
            t0 = _time.perf_counter()
            rep = router.request({"id": rid, "x": samples["x"][0].tolist()})
            wall = _time.perf_counter() - t0
            assert rep.get("ok") is True
            tr = TraceContext.from_wire(rep.get("trace"))
            assert tr is not None
            names = [n for n, _ in tr.phases]
            assert names[0] == "pick" and "wire" in names
            # parity: router spans + backend spans never exceed the
            # client-observed wall (durations partition, no double count)
            assert tr.phase_sum_s() <= wall + 5e-3
            # backend-side phases came through the wire intact
            assert {"batch_wait", "queue_wait", "compute", "fetch"} <= set(names)
            attempts = tr.detail["router"]["attempts"]
            assert attempts[-1]["ok"] is True
            if len(attempts) >= 2:
                failover_tr = (tr, attempts)
        assert failover_tr is not None, "no request's primary was the dead host"
        tr, attempts = failover_tr
        assert attempts[0]["ok"] is False
        assert [n for n, _ in tr.phases].count("wire") == len(attempts) >= 2
        assert tr.detail["router"]["failover_retries"] >= 1
        # net wire on the successful attempt: exchange minus the backend's
        # reported serve total (duration subtraction, clock-skew-free)
        ok_att = attempts[-1]
        assert ok_att["wire_ms"] == pytest.approx(
            max(0.0, ok_att["exchange_ms"] - ok_att["server_ms"]), abs=0.01
        )
        # dedup re-attachment: same id again -> identical reply, dedup_wait
        rep1 = router.request({"id": "pin-1", "x": samples["x"][2].tolist()})
        rep2 = router.request({"id": "pin-1", "x": samples["x"][2].tolist()})
        assert rep2["h"] == rep1["h"]
        tr2 = rep2["trace"]
        assert tr2["phases"][0][0] == "dedup_wait"
        assert tr2["detail"]["dedup_reattached"] is True
        assert router.dedup.hits == 1
    finally:
        router.stop()


def test_router_metrics_aggregation_sums_phases_exactly(backend):
    from qdml_tpu.fleet import FleetRouter

    cfg, samples, port, _loop = backend
    router = FleetRouter(
        [("127.0.0.1", port)], timeout_s=5.0, retries=0,
        poll_interval_s=30.0, trace_sample=1.0,
    )
    try:
        for i in range(10):
            rep = router.request({"id": f"agg-{i}", "x": samples["x"][i].tolist()})
            assert rep.get("ok") is True
        m = router.live_metrics()
        per_backend = m["per_backend"]
        assert len(per_backend) == 1
        b_phases = next(iter(per_backend.values()))["phases"]
        agg_phases = m["phases"]
        # EXACT summation across the aggregation: per-phase n and sum_ms of
        # the fleet view equal the per-backend blocks' sums (one backend
        # here makes the equality literal; the summing code path is the same
        # for N)
        for name in ("batch_wait", "queue_wait", "compute", "fetch"):
            assert agg_phases[name]["n"] == b_phases[name]["n"] == 10
            assert agg_phases[name]["sum_ms"] == pytest.approx(
                b_phases[name]["sum_ms"], abs=1e-6
            )
        # the router's own wire row: raw samples live router-side, so it has
        # exact quantiles AND the (n, sum_ms) pair
        assert agg_phases["wire"]["n"] == 10
        assert {"p50_ms", "p99_ms", "sum_ms"} <= set(agg_phases["wire"])
        assert m["trace"]["sampled"] == 10
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# Loadgen + report
# ---------------------------------------------------------------------------


def test_loadgen_summary_carries_phases_and_reconciliation(warmed, tmp_path):
    from qdml_tpu.telemetry import run_manifest
    from qdml_tpu.utils.metrics import MetricsLogger

    cfg, engine, _ = warmed  # cfg.serve.trace_sample == 1.0
    path = str(tmp_path / "traced_loadgen.jsonl")
    logger = MetricsLogger(path, echo=False, manifest=run_manifest(cfg))
    try:
        summary = run_loadgen(cfg, engine, rate=2000.0, n=48, logger=logger)
    finally:
        logger.close()
    assert summary["trace"]["sampled"] == summary["completed"] == 48
    rec = summary["trace"]["reconciliation"]
    assert rec["n"] == 48
    assert rec["mean_phase_sum_ms"] <= rec["mean_latency_ms"] + 1e-3
    assert rec["attributed_fraction"] > 0.5
    assert set(summary["phases"]) == {"batch_wait", "queue_wait", "compute", "fetch"}
    # satellite: the end-of-run metrics poll carries the decomposition too —
    # no second verb round-trip per committed window
    assert summary["server_metrics"]["phases"] is not None
    assert summary["server_metrics"]["trace"]["sampled"] == 48


def test_replica_pool_trace_sample_override(warmed):
    cfg, engine, samples = warmed
    pool = ReplicaPool(engine, replicas=2, trace_sample=0.0).start()
    try:
        futs = [pool.submit(samples["x"][i], rid=i) for i in range(8)]
        results = [f.result(timeout=30.0) for f in futs]
    finally:
        pool.stop()
    assert all(r.trace is None for r in results)
    assert pool.merged_metrics().trace_coverage() is None


def _summary_with_phases(platform, p99s: dict, latency_p99: float,
                         trace: dict | None = None) -> dict:
    return {
        "kind": "serve_summary",
        "platform": platform,
        "rps": 100.0,
        "completed": 100,
        "latency_ms": {"p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": latency_p99},
        "phases": {
            name: {"n": 50, "mean_ms": v / 2, "p50_ms": v / 3, "p95_ms": v * 0.9,
                   "p99_ms": v, "max_ms": v * 1.1, "sum_ms": 50 * v / 2}
            for name, v in p99s.items()
        },
        "trace": trace or {"sampled": 50, "completed": 100, "fraction": 0.5},
        "stranded_futures": 0,
    }


def test_report_phase_section_gates_and_attribution(tmp_path):
    from qdml_tpu.telemetry.report import build_report_data

    base = tmp_path / "base.jsonl"
    cur = tmp_path / "cur.jsonl"
    base.write_text(json.dumps(_summary_with_phases(
        "cpu", {"batch_wait": 0.5, "queue_wait": 1.0, "compute": 2.0,
                "fetch": 0.4, "wire": 1.0}, latency_p99=5.0)) + "\n")
    # compute p99 triples, everything else flat: the end-to-end p99 move
    # must be ATTRIBUTED to compute
    cur.write_text(json.dumps(_summary_with_phases(
        "cpu", {"batch_wait": 0.5, "queue_wait": 1.0, "compute": 6.0,
                "fetch": 0.4, "wire": 1.0}, latency_p99=9.0)) + "\n")
    data = build_report_data([str(cur)], str(base), threshold_pct=10.0)
    by_metric = {g["metric"]: g for g in data["gates"]}
    assert by_metric["serve.phase.compute.p99_ms"]["status"] == "regression"
    assert by_metric["serve.phase.compute.p99_ms"]["kind"] == "phase"
    for name in ("batch_wait", "queue_wait", "fetch", "wire"):
        assert by_metric[f"serve.phase.{name}.p99_ms"]["status"] == "ok"
    md = data["markdown"]
    assert "serving phase decomposition" in md
    assert "trace coverage: sampled 50 of 100" in md
    assert "clock-skew rule" in md and "never differenced" in md
    assert "p99 attribution" in md and "compute (+200.0%)" in md
    assert any(r["metric"] == "serve.phase.compute.p99_ms"
               for r in data["regressions"])
    # flat phases -> ok round trip, section still renders with coverage
    data2 = build_report_data([str(base)], str(base), threshold_pct=10.0)
    assert not any(
        g["kind"] == "phase" and g["status"] == "regression"
        for g in data2["gates"]
    )
    assert "p99 attribution" not in data2["markdown"]


def test_report_phase_platform_mismatch_disarms(tmp_path):
    from qdml_tpu.telemetry.report import build_report_data

    base = tmp_path / "base.jsonl"
    cur = tmp_path / "cur.jsonl"
    base.write_text(json.dumps(_summary_with_phases(
        "tpu", {"compute": 2.0}, latency_p99=5.0)) + "\n")
    cur.write_text(json.dumps(_summary_with_phases(
        "cpu", {"compute": 20.0}, latency_p99=50.0)) + "\n")
    data = build_report_data([str(cur)], str(base), threshold_pct=10.0)
    # phase rows are latency-shaped: reported, but the platform mismatch
    # disarms the gate exactly like the serving-latency section
    assert data["gate_armed"] is False
    assert any(
        g["metric"] == "serve.phase.compute.p99_ms"
        and g["status"] == "regression"
        for g in data["gates"]
    )
