"""Capacity-bucketed sparse expert dispatch: value-equivalence property
tests (eager/jit, fp32/bf16, out-of-range ids, overflow fallback,
padded-batch invariance), the dense-vs-sparse dispatcher race table, the
sparse sweep path, and the scenario-scaling report gates (ISSUE 9)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qdml_tpu.ops import dispatch_autotune
from qdml_tpu.ops.routing import (
    bucket_ranks,
    expert_capacity,
    select_expert,
    sparse_dispatch,
)


def _toy(s, din, d, seed=0, dtype=jnp.float32):
    """Per-expert linear maps: the routing-level reference. Both formulations
    reduce over the SAME per-row contraction (einsum over din), so any
    disagreement is a packing/unsort bug, not float reassociation — fp32
    equality is exact by construction."""
    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.standard_normal((s, din, d)), dtype=dtype)

    def run_experts(buckets):  # (S, C, Din) -> (S, C, D)
        return jnp.einsum("scd,sde->sce", buckets, w)

    def dense_fb(x, pred):
        return select_expert(jnp.einsum("bd,sde->sbe", x, w), pred)

    return run_experts, dense_fb


def test_expert_capacity_bounds():
    assert expert_capacity(64, 8, 1.25) == 10
    assert expert_capacity(64, 64, 1.25) == 2
    assert expert_capacity(64, 3, 0.0) == 1      # floor
    assert expert_capacity(8, 1, 100.0) == 8     # ceil at batch
    assert expert_capacity(1, 5, 1.0) == 1


def test_bucket_ranks_are_within_expert_arrival_order():
    pred = jnp.asarray([2, 0, 2, 1, 2, 0], jnp.int32)
    ids, rank = bucket_ranks(pred, 3)
    np.testing.assert_array_equal(np.asarray(ids), [2, 0, 2, 1, 2, 0])
    np.testing.assert_array_equal(np.asarray(rank), [0, 0, 1, 0, 2, 1])
    # invalid rows consume no rank
    valid = jnp.asarray([True, True, False, True, True, True])
    _, rank_v = bucket_ranks(pred, 3, valid=valid)
    np.testing.assert_array_equal(np.asarray(rank_v)[[0, 4]], [0, 1])


def test_sparse_matches_dense_eager_and_jit_fp32_exact():
    """The tentpole equivalence pin: sparse == select_expert bit-for-bit in
    fp32, eager and jitted, across S/B/D shapes and random routing."""
    rng = np.random.default_rng(1)
    for s, b, din, d in ((2, 8, 4, 3), (8, 64, 12, 7), (16, 32, 5, 5), (7, 13, 3, 2)):
        run_experts, dense_fb = _toy(s, din, d, seed=s)
        x = jnp.asarray(rng.standard_normal((b, din)).astype(np.float32))
        pred = jnp.asarray(rng.integers(0, s, b).astype(np.int32))
        ref = dense_fb(x, pred)
        out, ovf = sparse_dispatch(run_experts, dense_fb, x, pred, s, 1.25)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
        out_j, ovf_j = jax.jit(
            lambda xx, pp, re=run_experts, df=dense_fb, ss=s: sparse_dispatch(
                re, df, xx, pp, ss, 1.25
            )
        )(x, pred)
        np.testing.assert_array_equal(np.asarray(out_j), np.asarray(ref))
        assert int(ovf) == int(ovf_j)


def test_sparse_bf16_tracks_dense():
    rng = np.random.default_rng(2)
    s, b, din, d = 8, 32, 6, 4
    run_experts, dense_fb = _toy(s, din, d, dtype=jnp.bfloat16)
    x = jnp.asarray(rng.standard_normal((b, din)), jnp.bfloat16)
    pred = jnp.asarray(rng.integers(0, s, b).astype(np.int32))
    out, _ = sparse_dispatch(run_experts, dense_fb, x, pred, s, 1.25)
    ref = dense_fb(x, pred)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=0.05, rtol=0.05,
    )


def test_sparse_clips_out_of_range_ids_like_select_expert():
    """Corrupted classifier ids degrade to the nearest valid expert on the
    sparse path exactly as select_expert does — eager and jit identically."""
    s, b, din, d = 4, 8, 3, 2
    run_experts, dense_fb = _toy(s, din, d)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((b, din)).astype(np.float32))
    pred = jnp.asarray([9, -4, 0, 3, 99, -1, 2, 1], jnp.int32)
    ref = dense_fb(x, pred)  # select_expert clips internally
    out, _ = sparse_dispatch(run_experts, dense_fb, x, pred, s, 1.25)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    out_j, _ = jax.jit(
        lambda xx, pp: sparse_dispatch(run_experts, dense_fb, xx, pp, s, 1.25)
    )(x, pred)
    np.testing.assert_array_equal(np.asarray(out_j), np.asarray(ref))


def test_overflow_at_low_capacity_falls_back_losslessly():
    """Every row one expert at capacity 1: all but one row overflows; the
    fallback rows take the dense path's values BIT-EXACTLY (the fallback IS
    the dense path), and the overflow count is honest."""
    s, b, din, d = 8, 16, 5, 3
    run_experts, dense_fb = _toy(s, din, d)
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((b, din)).astype(np.float32))
    pred = jnp.full((b,), 3, jnp.int32)
    out, ovf = sparse_dispatch(
        run_experts, dense_fb, x, pred, s, 1.25, capacity=1
    )
    assert int(ovf) == b - 1
    np.testing.assert_array_equal(np.asarray(out), np.asarray(dense_fb(x, pred)))
    # balanced load at sane capacity never enters the fallback
    pred_b = jnp.arange(b, dtype=jnp.int32) % s
    _, ovf_b = sparse_dispatch(run_experts, dense_fb, x, pred_b, s, 1.25)
    assert int(ovf_b) == 0


def test_padded_batch_invariance():
    """Zero-padding the batch (the serve engine's bucket fill) must not
    perturb real rows: with the valid mask, padding consumes no capacity and
    real rows pack into the SAME slots — outputs bit-identical at a fixed
    capacity."""
    s, b, pad, din, d = 8, 24, 9, 5, 3
    run_experts, dense_fb = _toy(s, din, d)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((b, din)).astype(np.float32))
    pred = jnp.asarray(rng.integers(0, s, b).astype(np.int32))
    cap = expert_capacity(b, s, 1.25)
    out, ovf = sparse_dispatch(
        run_experts, dense_fb, x, pred, s, capacity=cap
    )
    xp = jnp.concatenate([x, jnp.zeros((pad, din), jnp.float32)])
    pp = jnp.concatenate([pred, jnp.zeros((pad,), jnp.int32)])
    valid = jnp.arange(b + pad) < b
    out_p, ovf_p = sparse_dispatch(
        run_experts, dense_fb, xp, pp, s, valid=valid, capacity=cap
    )
    assert int(ovf) == int(ovf_p)  # padding rows never count as overflow
    np.testing.assert_array_equal(np.asarray(out_p)[:b], np.asarray(out))


def test_sparse_matches_dense_through_real_hdce_trunks():
    """Through the real conv trunks + shared head the two formulations agree
    to float tolerance (XLA may tile the (S*C)-row and (S*B)-row batches
    differently — ulp-level reassociation, nothing structural)."""
    from qdml_tpu.train.hdce import HDCE

    s, b = 8, 32
    rng = np.random.default_rng(6)
    model = HDCE(n_scenarios=s, features=8, out_dim=64)
    x = jnp.asarray(rng.standard_normal((b, 16, 8, 2)).astype(np.float32))
    pred = jnp.asarray(rng.integers(0, s, b).astype(np.int32))
    v = model.init(
        jax.random.PRNGKey(0), jnp.broadcast_to(x[None], (s,) + x.shape), train=False
    )

    def dense_fb(xb, pb):
        xs = jnp.broadcast_to(xb[None], (s,) + xb.shape)
        return select_expert(model.apply(v, xs, train=False), pb)

    out, _ = jax.jit(
        lambda xx, pp: sparse_dispatch(
            lambda bk: model.apply(v, bk, train=False), dense_fb, xx, pp, s, 1.25
        )
    )(x, pred)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_fb(x, pred)), atol=1e-5
    )


# ---------------------------------------------------------------------------
# Dispatcher race table
# ---------------------------------------------------------------------------


def test_eligible_modes_window():
    assert dispatch_autotune.eligible_modes(3) == ["dense"]
    assert dispatch_autotune.eligible_modes(5) == ["dense"]
    assert dispatch_autotune.eligible_modes(6) == ["dense", "sparse"]
    assert dispatch_autotune.eligible_modes(64) == ["dense", "sparse"]


def test_ensure_route_below_window_skips_race_and_writes_nothing(tmp_path):
    """S=3: only dense is eligible — nothing is timed (zero extra compiles
    for the reference grid), the exclusion reason is recorded, and NO table
    is written (a window-only decision carries no timings worth caching:
    every reference-grid warmup would otherwise write files)."""
    table = str(tmp_path / "routing.json")
    dispatch_autotune.invalidate_cache()
    calls = []

    def apply_trunks(xs):  # must never run below the window
        calls.append(1)
        return jnp.zeros(xs.shape[:2] + (4,))

    x = jnp.zeros((16, 8, 4, 2), jnp.float32)
    entry = dispatch_autotune.ensure_route(apply_trunks, x, 3, path=table)
    assert entry["best_infer"] == "dense"
    assert entry["candidates"]["dense"] == {"only_candidate": True}
    assert "sparse" in entry["excluded"][0]["mode"]
    assert calls == []
    assert not os.path.exists(table)
    dispatch_autotune.invalidate_cache()


def test_ensure_route_races_and_lookup_survives_pathologies(tmp_path):
    """S=8: both modes race for real; the winner persists; corrupt/alien
    tables and an out-of-window sparse entry all degrade to None/dense."""
    table = str(tmp_path / "routing.json")
    dispatch_autotune.invalidate_cache()
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((8, 4 * 2 * 2, 16)).astype(np.float32))

    def apply_trunks(xs):  # (S, B', 4, 2, 2) -> (S, B', 16)
        flat = xs.reshape(xs.shape[0], xs.shape[1], -1)
        return jnp.einsum("sbd,sde->sbe", flat, w)

    x = jnp.asarray(rng.standard_normal((32, 4, 2, 2)).astype(np.float32))
    entry = dispatch_autotune.ensure_route(apply_trunks, x, 8, path=table)
    assert entry["best_infer"] in ("dense", "sparse")
    assert {"dense", "sparse"} <= set(entry["candidates"])
    assert all(
        isinstance(c.get("infer_ms"), float) for c in entry["candidates"].values()
    )
    assert dispatch_autotune.lookup(8, 32, path=table) == entry["best_infer"]
    # cached ensure returns without re-measuring
    again = dispatch_autotune.ensure_route(apply_trunks, x, 8, path=table)
    assert again["ts"] == entry["ts"]

    # corrupt file -> lookup None, never raises
    dispatch_autotune.invalidate_cache()
    with open(table, "w") as fh:
        fh.write("{not json")
    assert dispatch_autotune.lookup(8, 32, path=table) is None
    assert dispatch_autotune.table_status(table) == "corrupt"

    # a hand-edited sparse selection below the window cannot force sparse
    dispatch_autotune.invalidate_cache()
    import jax as _jax

    key = dispatch_autotune.table_key(_jax.default_backend(), 3, 32)
    with open(table, "w") as fh:
        json.dump({"entries": {key: {"best_infer": "sparse"}}}, fh)
    assert dispatch_autotune.lookup(3, 32, path=table) is None
    dispatch_autotune.invalidate_cache()


# ---------------------------------------------------------------------------
# Serve engine: sparse AOT buckets
# ---------------------------------------------------------------------------


def _mini_cfg(n_scenarios=8, dispatch="sparse", buckets=(8, 16)):
    from qdml_tpu.config import (
        DataConfig,
        ExperimentConfig,
        ModelConfig,
        ServeConfig,
        TrainConfig,
    )

    return ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, n_scenarios=n_scenarios),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=8, n_epochs=1),
        serve=ServeConfig(max_batch=max(buckets), buckets=buckets, dispatch=dispatch, batching="bucket"),
    )


def _mini_engine(cfg):
    from qdml_tpu.serve import ServeEngine
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    _, hs = init_hdce_state(cfg, steps_per_epoch=10)
    _, ss = init_sc_state(cfg, quantum=False, steps_per_epoch=10)
    return ServeEngine(
        cfg, {"params": hs.params, "batch_stats": hs.batch_stats}, {"params": ss.params}
    )


def test_serve_sparse_buckets_zero_compiles_and_parity():
    """The acceptance pin: sparse baked into every AOT bucket, offline-parity
    to float tolerance, ZERO request-path compiles across warmup + traffic,
    and honest overflow accounting in dispatch_summary."""
    cfg = _mini_cfg()
    eng = _mini_engine(cfg)
    x = np.random.default_rng(0).standard_normal((11, 8, 4, 2)).astype(np.float32)
    off_h, off_p, _ = eng.offline_forward(x)
    warm = eng.warmup()
    assert set(warm["dispatch"]["mode"].values()) == {"sparse"}
    for _ in range(3):
        h, p, _c, b = eng.infer(x)
    np.testing.assert_array_equal(p, off_p)
    np.testing.assert_allclose(h, off_h, atol=1e-5)
    assert all(v == 0 for v in eng.request_path_compiles().values())
    summ = eng.dispatch_summary()
    assert summ["mode"] == "sparse"
    assert summ["routed_rows"] == 3 * 11
    assert summ["overflow_rate"] is not None
    assert summ["capacity_factor"] == cfg.serve.capacity_factor


def test_serve_auto_dispatch_below_window_stays_dense_no_race():
    """S=3 + dispatch=auto: the race is skipped (window), dense serves, and
    the dispatch block says so — the reference grid's warmup is unchanged."""
    cfg = _mini_cfg(n_scenarios=3, dispatch="auto", buckets=(8,))
    eng = _mini_engine(cfg)
    warm = eng.warmup()
    assert warm["dispatch"]["mode"] == {"8": "dense"}
    race = warm["dispatch"]["race"]["8"]
    assert race["candidates"]["dense"] == {"only_candidate": True}
    x = np.random.default_rng(1).standard_normal((5, 8, 4, 2)).astype(np.float32)
    h, p, _c, b = eng.infer(x)
    assert h.shape == (5, cfg.h_out_dim)
    assert eng.dispatch_summary()["mode"] == "dense"
    assert eng.dispatch_summary()["overflow_rate"] is None  # nothing sparse ran


def test_serve_auto_dispatch_races_above_window(tmp_path):
    """S=8 + dispatch=auto: a real measured race picks the bucket's mode and
    the entry (with both candidates timed) lands in the warmup record."""
    dispatch_autotune.invalidate_cache()
    dispatch_autotune.set_table_path(str(tmp_path / "routing.json"))
    try:
        cfg = _mini_cfg(n_scenarios=8, dispatch="auto", buckets=(16,))
        eng = _mini_engine(cfg)
        warm = eng.warmup()
        entry = warm["dispatch"]["race"]["16"]
        assert {"dense", "sparse"} <= set(entry["candidates"])
        assert warm["dispatch"]["mode"]["16"] == entry["best_infer"]
    finally:
        dispatch_autotune.invalidate_cache()


def test_sweep_sparse_dispatch_matches_dense():
    """The eval sweep's HDCE curves are dispatch-invariant: the sparse sweep
    step produces the same error sums as the dense one to float tolerance."""
    from qdml_tpu.config import (
        DataConfig,
        EvalConfig,
        ExperimentConfig,
        ModelConfig,
        TrainConfig,
    )
    from qdml_tpu.data.baselines import beam_delay_profile
    from qdml_tpu.data.channels import ChannelGeometry
    from qdml_tpu.eval.sweep import make_sweep_step
    from qdml_tpu.train.hdce import init_hdce_state
    from qdml_tpu.train.qsc import init_sc_state

    cfg = ExperimentConfig(
        data=DataConfig(n_ant=16, n_sub=8, n_beam=4, data_len=64, n_scenarios=8),
        model=ModelConfig(features=8),
        train=TrainConfig(batch_size=16, n_epochs=1),
        eval=EvalConfig(snr_grid=(10.0,), test_len=32, batch_size=32),
    )
    geom = ChannelGeometry.from_config(cfg.data)
    profile = beam_delay_profile(geom)
    _, hs = init_hdce_state(cfg, steps_per_epoch=10)
    hdce_vars = {"params": hs.params, "batch_stats": hs.batch_stats}
    _, ss = init_sc_state(cfg, quantum=False, steps_per_epoch=10)
    sc_vars = {"params": ss.params}
    outs = {}
    for dispatch in ("dense", "sparse"):
        step = make_sweep_step(
            cfg, geom, hdce_vars, sc_vars, None, profile, dispatch=dispatch
        )
        outs[dispatch] = step(jnp.asarray(0), jnp.asarray(0), jnp.float32(10.0))
    for key in outs["dense"]:
        np.testing.assert_allclose(
            float(outs["dense"][key]), float(outs["sparse"][key]), rtol=1e-5,
            err_msg=key,
        )


def test_sweep_rejects_unknown_dispatch():
    from qdml_tpu.eval.sweep import make_sweep_step

    with pytest.raises(ValueError, match="dispatch"):
        make_sweep_step(None, None, None, None, None, None, dispatch="magic")


# ---------------------------------------------------------------------------
# Report: scenario-scaling gates + serving dispatch fields
# ---------------------------------------------------------------------------


def _scenario_record(sps_by_s, dispatch_by_s=None):
    dispatch_by_s = dispatch_by_s or {}
    return {
        "kind": "bench_record",
        "metric": "scenario_scaling_points",
        "value": len(sps_by_s),
        "platform": "cpu",
        "details": {
            "scenario_scaling": {
                "platform": "cpu",
                "capacity_factor": 1.25,
                "points": [
                    {
                        "n_scenarios": s,
                        "batch": 64,
                        "capacity": 10,
                        "dispatch": dispatch_by_s.get(s, "sparse"),
                        "samples_per_sec": v,
                        "infer_ms": 1.0,
                        "candidates": {
                            "dense": {"infer_ms": 2.0},
                            "sparse": {"infer_ms": 1.0},
                        },
                        "agreement": {"max_abs_delta": 0.0},
                    }
                    for s, v in sps_by_s.items()
                ],
            }
        },
    }


def test_report_extracts_and_gates_scenario_scaling(tmp_path):
    """Every S-bucket is its own best_of_dispatch gate: S=32 regressing fails
    CI even while S=3 improves, and the crossover section renders."""
    from qdml_tpu.telemetry.report import build_report_data, report_main

    base = tmp_path / "base.jsonl"
    cur = tmp_path / "cur.jsonl"
    base.write_text(json.dumps(_scenario_record({3: 100.0, 32: 1000.0})) + "\n")
    cur.write_text(json.dumps(_scenario_record({3: 200.0, 32: 500.0})) + "\n")
    data = build_report_data([str(cur)], str(base))
    assert data["gate_armed"]
    regressed = {r["metric"] for r in data["regressions"]}
    assert "scenario_scaling.s32.best_of_dispatch" in regressed
    assert "scenario_scaling.s03.best_of_dispatch" not in regressed
    assert "## scenario scaling" in data["markdown"]
    assert "2.00x vs dense" in data["markdown"]
    rc = report_main([f"--current={cur}", f"--baseline={base}"])
    assert rc == 3
    # self-vs-self is clean
    assert report_main([f"--current={cur}", f"--baseline={cur}"]) == 0


def test_report_serving_dispatch_fields_and_overflow_gate(tmp_path):
    """serve_summary's n_scenarios/dispatch/overflow fields reach the fleet
    line, and an overflow-rate jump beyond the absolute slack fails the
    gate while an equal-rate run passes."""
    from qdml_tpu.telemetry.report import build_report_data

    def summ(rate):
        return {
            "kind": "serve_summary",
            "platform": "cpu",
            "rps": 100.0,
            "latency_ms": {"p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0},
            "replicas": 2,
            "n_scenarios": 16,
            "dispatch": {
                "mode": "sparse",
                "capacity_factor": 1.25,
                "overflow_rate": rate,
            },
        }

    base = tmp_path / "base.jsonl"
    ok = tmp_path / "ok.jsonl"
    bad = tmp_path / "bad.jsonl"
    base.write_text(json.dumps(summ(0.01)) + "\n")
    ok.write_text(json.dumps(summ(0.02)) + "\n")
    bad.write_text(json.dumps(summ(0.25)) + "\n")
    good = build_report_data([str(ok)], str(base))
    assert not any(r["metric"] == "serve.overflow_rate" for r in good["regressions"])
    assert "S=16" in good["markdown"] and "sparse-dispatch" in good["markdown"]
    failed = build_report_data([str(bad)], str(base))
    assert any(r["metric"] == "serve.overflow_rate" for r in failed["regressions"])


# ---------------------------------------------------------------------------
# Channel families (the S >> 3 data axis)
# ---------------------------------------------------------------------------


def test_family_table_prefix_property_and_base_presets():
    """Rows 0..2 are the frozen reference presets, and growing S never
    re-parameterizes existing families (the committed-stream contract)."""
    from qdml_tpu.data import channels

    t3 = channels.family_table(3)
    np.testing.assert_array_equal(t3["n_paths"], channels.SCENARIO_N_PATHS)
    np.testing.assert_array_equal(t3["k_factor"], channels.SCENARIO_K_FACTOR)
    np.testing.assert_array_equal(t3["mobility"], [0.0, 0.0, 0.0])
    t16 = channels.family_table(16)
    t64 = channels.family_table(64)
    for key in ("n_paths", "angle_spread", "delay_spread", "k_factor", "mobility"):
        np.testing.assert_array_equal(t16[key], t64[key][:16], err_msg=key)
        np.testing.assert_array_equal(t3[key], t64[key][:3], err_msg=key)
    assert all(1 <= p <= channels.MAX_PATHS for p in t64["n_paths"])
    assert all(m > 0 for m in t64["mobility"][3:])  # derived tiers move
    assert t64["preset"][0] == "inh_los" and "+t" in t64["preset"][5]
    with pytest.raises(ValueError):
        channels.family_table(0)


def test_family_samples_base_scenarios_bit_identical_across_s():
    """Sampling scenario s < 3 from an S=16 geometry is bit-identical to the
    S=3 geometry: the family axis EXTENDS the dataset, never forks it."""
    from qdml_tpu.data.channels import ChannelGeometry, generate_samples

    i = jnp.arange(12)
    kw = dict(n_ant=16, n_sub=8, n_beam=4)
    out3 = generate_samples(
        jnp.uint32(7), i % 3, i % 3, i, jnp.float32(10.0), ChannelGeometry(**kw)
    )
    out16 = generate_samples(
        jnp.uint32(7), i % 3, i % 3, i, jnp.float32(10.0),
        ChannelGeometry(n_scenarios=16, **kw),
    )
    for key in ("yp", "h_perf", "h_ls"):
        np.testing.assert_array_equal(
            np.asarray(out3[key].re), np.asarray(out16[key].re), err_msg=key
        )
        np.testing.assert_array_equal(
            np.asarray(out3[key].im), np.asarray(out16[key].im), err_msg=key
        )


def test_family_samples_distinct_and_normalized_at_high_s():
    """Derived families produce distinct, unit-energy channels on device —
    the S >> 3 grid is real data, not re-seeded copies of the base three."""
    from qdml_tpu.data.channels import ChannelGeometry, generate_samples

    geom = ChannelGeometry(n_ant=16, n_sub=8, n_beam=4, n_scenarios=12)
    n = 48
    i = jnp.arange(n)
    scen = i % 12
    out = generate_samples(jnp.uint32(3), scen, i % 3, i // 12, jnp.float32(10.0), geom)
    h = out["h_perf"]
    energy = np.asarray(jnp.sum(h.abs2(), axis=-1))
    np.testing.assert_allclose(energy.mean(), geom.h_dim, rtol=0.35)
    # same index, different family -> different realisations
    a = np.asarray(out["h_perf"].re)
    assert not np.allclose(a[3], a[4])


def test_scenario_scaling_grid_helpers():
    from qdml_tpu.eval.sweep import (
        SCENARIO_SCALING_GRID,
        dispatch_agreement,
        scenario_batch,
    )

    assert SCENARIO_SCALING_GRID[0] == 3 and SCENARIO_SCALING_GRID[-1] == 64
    assert scenario_batch(64) == scenario_batch(3) == 64
    agr = dispatch_agreement(6, batch=12, features=4)
    assert agr["max_abs_delta"] < 1e-5
    assert agr["overflow_balanced"] == 0
    assert agr["overflow_skewed"] > 0


# ---------------------------------------------------------------------------
# Committed artifact smoke (the wiring proof stays re-readable)
# ---------------------------------------------------------------------------

ARTIFACT = os.path.join("results", "scenario_scaling", "scenario_scaling.jsonl")
TABLE = os.path.join("results", "scenario_scaling", "routing_table.json")


def test_committed_scenario_scaling_artifact_round_trips_report_gate(tmp_path):
    """The committed sweep artifact re-reads through the report gate at exit
    0 (self-vs-self), extracts one best_of_dispatch gate per S, and shows the
    crossover the acceptance criteria name: dense still winning S=3, sparse
    proven (raced and won) at S >= 16."""
    from qdml_tpu.telemetry.report import build_report_data, extract, report_main

    assert os.path.exists(ARTIFACT), "commit scripts/scenario_scaling_sweep.py output"
    src = extract(ARTIFACT)
    keys = {k for k in src["throughput"] if k.startswith("scenario_scaling.s")}
    assert {
        "scenario_scaling.s03.best_of_dispatch",
        "scenario_scaling.s16.best_of_dispatch",
        "scenario_scaling.s64.best_of_dispatch",
    } <= keys
    by_s = {
        p["n_scenarios"]: p for p in src["scenario_scaling"]["points"]
    }
    assert by_s[3]["dispatch"] == "dense"
    for s in (16, 32, 64):
        assert by_s[s]["dispatch"] == "sparse", s
        # proven = raced and measured faster, not picked by heuristic
        cands = by_s[s]["candidates"]
        assert cands["sparse"]["infer_ms"] < cands["dense"]["infer_ms"]
        # and value-equivalent to the dense formulation at that S
        assert by_s[s]["agreement"]["max_abs_delta"] < 1e-5
    # dense at S=3 is the recorded window exclusion, not an accident
    assert by_s[3]["excluded"][0]["mode"] == "sparse"
    rc = report_main(
        [f"--current={ARTIFACT}", f"--baseline={ARTIFACT}",
         f"--out={tmp_path / 'r.md'}"]
    )
    assert rc == 0
    data = build_report_data([ARTIFACT], ARTIFACT)
    assert "## scenario scaling" in data["markdown"]


def test_committed_routing_table_dispatches_sparse_at_scale():
    """The committed selection table round-trips through lookup(): the
    dispatcher on this (cpu) harness serves sparse at the scale-out shapes
    and None/dense below the window — the table IS the proof the serve
    warmup reads."""
    dispatch_autotune.invalidate_cache()
    try:
        assert dispatch_autotune.lookup(16, 64, path=TABLE) == "sparse"
        assert dispatch_autotune.lookup(64, 64, path=TABLE) == "sparse"
        assert dispatch_autotune.lookup(3, 64, path=TABLE) in (None, "dense")
    finally:
        dispatch_autotune.invalidate_cache()
