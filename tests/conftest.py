"""Test harness: force the CPU backend with 8 virtual devices.

This is the standard JAX way to test pjit/psum/mesh logic without a real pod
(SURVEY.md §4): multi-chip sharding tests see an 8-device mesh backed by host
CPU. Must run before any ``import jax`` in test modules.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is dominated by XLA CPU compiles of
# the same jitted steps across test files; caching them on disk makes repeat
# runs fast without changing any test semantics.
from qdml_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache()
